"""Benchmark: dense-LM training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
The reference publishes no absolute numbers (BASELINE.md), so vs_baseline
is measured against this repo's own recorded north-star target once MoE
lands; until then it reports 1.0 (self-established baseline).
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

# v5e (v5 lite) peak bf16 TFLOPs per chip; v5p would be 459.
PEAK_FLOPS = {"v5 lite": 197e12, "v5e": 197e12, "v5p": 459e12, "v4": 275e12}


def main():
    from d9d_tpu.core import MeshParameters
    from d9d_tpu.loop import (
        AdamWProvider,
        CausalLMTask,
        DatasetProvider,
        ModelProvider,
        Trainer,
        TrainerConfig,
    )
    from d9d_tpu.models.qwen3 import Qwen3DenseCausalLM, Qwen3DenseConfig
    from d9d_tpu.nn.sdpa import build_sdpa_backend
    from d9d_tpu.parallel import replicate_plan

    cfg = Qwen3DenseConfig(
        vocab_ranges=(("default", 32_768),),
        hidden_size=1024,
        num_layers=12,
        num_heads=16,
        num_kv_heads=8,
        head_dim=64,
        intermediate_size=4096,
        remat=True,
    )
    seq_len, batch = 2048, 8
    steps_measure = 10

    class Provider(ModelProvider):
        def build_module(self, stage):
            return Qwen3DenseCausalLM(
                config=cfg, sdpa=build_sdpa_backend(), stage=stage,
                dtype=jnp.bfloat16,
            )

        def build_plan(self, c):
            return replicate_plan(c)

        def sample_inputs(self, batch_size, seq_len):
            z = jnp.zeros((batch_size, seq_len), jnp.int32)
            return (z, z, z)

    class Data(DatasetProvider):
        def build(self):
            rng = np.random.RandomState(0)
            while True:
                yield {
                    "input_ids": rng.randint(
                        0, cfg.vocab_size, size=(batch, seq_len + 1)
                    )
                }

    ctx = MeshParameters().build(jax.devices()[:1])
    trainer = Trainer(
        ctx=ctx,
        config=TrainerConfig(
            global_batch_size=batch,
            microbatch_size=batch,
            seq_len=seq_len,
            total_steps=3 + steps_measure,
            log_every=10_000,
        ),
        model_provider=Provider(),
        dataset_provider=Data(),
        task=CausalLMTask(),
        optimizer_provider=AdamWProvider(weight_decay=0.0),
    )

    data_iter = iter(trainer.dataset.build())

    def one_step():
        raw = next(data_iter)
        b = trainer._stage_batch(raw)
        rng = jax.random.fold_in(trainer.step_rng, trainer.stepper.step)
        trainer.params, trainer.opt_state, m = trainer.step_fn(
            trainer.params, trainer.opt_state, b, rng
        )
        trainer.stepper.advance()
        return m

    # warmup (compile)
    for _ in range(3):
        m = one_step()
    jax.block_until_ready(m["loss"])

    t0 = time.perf_counter()
    for _ in range(steps_measure):
        m = one_step()
    jax.block_until_ready(m["loss"])
    dt = time.perf_counter() - t0

    tokens = steps_measure * batch * seq_len
    tok_per_s = tokens / dt

    n_params = sum(
        int(np.prod(x.shape)) for x in jax.tree.leaves(trainer.params)
    )
    # fwd+bwd ≈ 6*N per token (+remat fwd ≈ 8*N) + attention 12*L*D*T/2 causal
    flops_per_token = 8 * n_params + 6 * cfg.num_layers * cfg.hidden_size * seq_len
    kind = jax.devices()[0].device_kind.lower()
    peak = next((v for k, v in PEAK_FLOPS.items() if k in kind), 197e12)
    mfu = tok_per_s * flops_per_token / peak

    print(
        json.dumps(
            {
                "metric": "dense_lm_tokens_per_sec_per_chip",
                "value": round(tok_per_s, 1),
                "unit": "tokens/s",
                "vs_baseline": 1.0,
                "detail": {
                    "mfu": round(mfu, 4),
                    "params": n_params,
                    "seq_len": seq_len,
                    "batch": batch,
                    "device": jax.devices()[0].device_kind,
                },
            }
        )
    )


if __name__ == "__main__":
    main()
