"""Benchmark: training throughput on one TPU chip (dense LM + Qwen3-MoE).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} for the
dense headline row, with the Qwen3-MoE north-star row (BASELINE.json:
tokens/sec/chip + MFU on Qwen3-MoE pretrain) under ``detail.moe``.
The reference publishes no absolute numbers (BASELINE.md), so the baseline
is this repo's own best recorded measurement (RECORDED below, mirrored in
BASELINE.md's measured-rows table); vs_baseline = value / recorded.

MFU convention (VERDICT r2 Weak #3): ``mfu`` is MODEL-flop utilisation —
6N FLOPs per token per active param plus exact attention FLOPs, regardless
of remat — and ``hfu`` (detail) counts the remat forward as useful work
(8N). For MoE, "active params" counts dense/shared weights once and expert
weights scaled by top_k/num_experts.

Uses only the public Trainer API (``Trainer.run_step``); covered by
tests/test_bench.py so it cannot silently rot against loop refactors.
"""

import json
import time

# Peak-FLOPs table and FLOPs-per-token inventory shared with the
# trainer's live-MFU gauge (d9d_tpu/telemetry/flops.py) — one convention,
# so live and bench-reported MFU cannot drift apart
from d9d_tpu.telemetry.flops import (  # noqa: E402
    DEFAULT_PEAK,
    PEAK_FLOPS,
    model_flops_per_token,
)

# Best previously recorded results (BASELINE.md measured rows).
RECORDED_DENSE = {"v5 lite": 48163.0, "v5e": 48163.0}
RECORDED_MOE = {"v5 lite": 25280.0, "v5e": 25280.0}
RECORDED_HYBRID: dict[str, float] = {}  # no chip row yet (BASELINE cfg 5)


def _flops_accounting(cfg, *, seq_len, active_param_count):
    """(model_flops_per_token, hardware_flops_per_token).

    The model term is telemetry/flops.py's shared inventory: 6N_active +
    quadratic attention on the non-linear layers (causal QK^T + PV
    fwd+bwd = 12·L·H·D·T/2 per token) + the GDN chunked delta rule on
    ``linear_attention_layers``. HFU additionally counts the remat
    forward recompute as useful work (8N)."""
    model = model_flops_per_token(
        active_param_count, seq_len=seq_len, config=cfg
    )
    hardware = model + (2.0 * active_param_count if cfg.remat else 0.0)
    return model, hardware


def _measure(trainer, data_iter, *, warmup, steps, batch, seq_len,
             profile_tag=None):
    # Sync via a host fetch, NOT block_until_ready: through the axon TPU
    # tunnel block_until_ready returns before remote execution finishes
    # (see tools/benchtime.py). run_step is one jitted executable, so
    # fetching the loss drains the whole step. The ~70 ms fetch round-trip
    # is measured on the already-materialized value and subtracted.
    import os

    from tools.benchtime import host_fetch_sync, measure_rtt

    # telemetry JSONL alongside the bench row (per-step dispatch spans +
    # tokens/s gauge) when D9D_TELEMETRY_DIR is set; a span costs ~µs
    # against multi-ms steps, so the recorded numbers stay honest
    from d9d_tpu.telemetry import attached_jsonl_sink

    tele_dir = os.environ.get("D9D_TELEMETRY_DIR")
    with attached_jsonl_sink(
        tele_dir, run_name=f"bench_{profile_tag or 'train'}"
    ) as (tele, tele_sink):
        if tele_sink is not None:
            # each leg (dense/moe/hybrid) gets its own file; clear the
            # shared hub's instruments so this leg's flush doesn't report
            # the previous legs' cumulative counters/histograms
            tele.reset_instruments()
        try:
            for _ in range(warmup):
                m = trainer.run_step(next(data_iter))
            host_fetch_sync(m["loss"])
            rtt = measure_rtt(m["loss"])
            t0 = time.perf_counter()
            for k in range(steps):
                # host dispatch only: run_step returns before the device
                # finishes (async dispatch), so this span is NOT step wall
                # time — named bench/dispatch to keep it distinct from the
                # trainer's synchronous train/step timeline
                with tele.span("bench/dispatch", step=k):
                    m = trainer.run_step(next(data_iter))
            host_fetch_sync(m["loss"])
            dt = time.perf_counter() - t0 - rtt
            tok_s = steps * batch * seq_len / dt
            tele.counter("train/tokens").add(steps * batch * seq_len)
            tele.gauge("train/tokens_per_s").set(tok_s)
        finally:
            # a raising step must not leave this leg's events unflushed
            if tele_sink is not None:
                tele.flush(step=steps)

    # optional SEPARATE traced pass (after timing, so trace collection
    # can't inflate the recorded numbers): set D9D_BENCH_PROFILE_DIR and
    # feed the capture to tools/trace_summary.py
    profile_root = os.environ.get("D9D_BENCH_PROFILE_DIR")
    if profile_root and profile_tag:
        import jax

        with jax.profiler.trace(os.path.join(profile_root, profile_tag)):
            for _ in range(2):
                m = trainer.run_step(next(data_iter))
            host_fetch_sync(m["loss"])
    return tok_s


def _peak():
    import jax

    kind = jax.devices()[0].device_kind.lower()
    return (
        next((v for k, v in PEAK_FLOPS.items() if k in kind), DEFAULT_PEAK),
        kind,
    )


def run_bench(*, tiny: bool = False) -> dict:
    """Dense-LM row (the recorded headline)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from d9d_tpu.core import MeshParameters
    from d9d_tpu.loop import (
        AdamWProvider,
        CausalLMTask,
        DatasetProvider,
        ModelProvider,
        Trainer,
        TrainerConfig,
    )
    from d9d_tpu.models.qwen3 import Qwen3DenseCausalLM, Qwen3DenseConfig
    from d9d_tpu.nn.sdpa import build_sdpa_backend
    from d9d_tpu.parallel import replicate_plan

    if tiny:
        cfg = Qwen3DenseConfig(
            vocab_ranges=(("default", 256),),
            hidden_size=64,
            num_layers=2,
            num_heads=4,
            num_kv_heads=2,
            head_dim=16,
            intermediate_size=128,
            remat=False,
        )
        seq_len, batch = 64, 4
        steps_warmup, steps_measure = 1, 2
        dtype = jnp.float32
    else:
        import os

        cfg = Qwen3DenseConfig(
            vocab_ranges=(("default", 32_768),),
            hidden_size=1024,
            num_layers=12,
            num_heads=16,
            num_kv_heads=8,
            head_dim=64,
            intermediate_size=4096,
            remat=True,
            # tuning knob for on-chip sweeps (BASELINE.md methodology)
            remat_policy=os.environ.get("D9D_BENCH_REMAT_POLICY", "full"),
            # r4 MFU lever: q/k/v as one matmul (single chip: no TP axis
            # to reshard). A/B with D9D_BENCH_FUSED_QKV=0.
            fused_qkv=os.environ.get("D9D_BENCH_FUSED_QKV", "1") == "1",
        )
        # batch knob for on-chip sweeps: more rows per step amortize
        # per-kernel overheads if HBM allows (full remat leaves plenty)
        seq_len, batch = 2048, int(os.environ.get("D9D_BENCH_BATCH", "8"))
        steps_warmup, steps_measure = 3, 10
        dtype = jnp.bfloat16

    class Provider(ModelProvider):
        def build_module(self, stage):
            return Qwen3DenseCausalLM(
                config=cfg, sdpa=build_sdpa_backend(), stage=stage,
                dtype=dtype,
            )

        def build_plan(self, c):
            return replicate_plan(c)

        def sample_inputs(self, batch_size, seq_len):
            z = jnp.zeros((batch_size, seq_len), jnp.int32)
            return (z, z, z)

    class Data(DatasetProvider):
        def build(self):
            rng = np.random.RandomState(0)
            while True:
                yield {
                    "input_ids": rng.randint(
                        0, cfg.vocab_size, size=(batch, seq_len + 1)
                    )
                }

    ctx = MeshParameters().build(jax.devices()[:1])
    trainer = Trainer(
        ctx=ctx,
        config=TrainerConfig(
            global_batch_size=batch,
            microbatch_size=batch,
            seq_len=seq_len,
            total_steps=steps_warmup + steps_measure,
            log_every=10_000,
        ),
        model_provider=Provider(),
        dataset_provider=Data(),
        task=CausalLMTask(),
        optimizer_provider=AdamWProvider(weight_decay=0.0),
    )

    tok_per_s = _measure(
        trainer, iter(Data().build()), warmup=steps_warmup,
        steps=steps_measure, batch=batch, seq_len=seq_len,
        profile_tag=None if tiny else "dense",
    )
    n_params = sum(
        int(np.prod(x.shape)) for x in jax.tree.leaves(trainer.params)
    )
    model_fpt, hw_fpt = _flops_accounting(
        cfg, seq_len=seq_len, active_param_count=n_params
    )
    peak, kind = _peak()
    recorded = next(
        (v for k, v in RECORDED_DENSE.items() if k in kind), None
    )
    vs_baseline = round(tok_per_s / recorded, 4) if (
        recorded is not None and not tiny
    ) else 1.0

    return {
        "metric": "dense_lm_tokens_per_sec_per_chip",
        "value": round(tok_per_s, 1),
        "unit": "tokens/s",
        "vs_baseline": vs_baseline,
        "detail": {
            "mfu": round(tok_per_s * model_fpt / peak, 4),
            "hfu": round(tok_per_s * hw_fpt / peak, 4),
            "params": n_params,
            "seq_len": seq_len,
            "batch": batch,
            "steps": steps_measure,
            "device": jax.devices()[0].device_kind,
        },
    }


def run_bench_moe(*, tiny: bool = False, hybrid: bool = False) -> dict:
    """Qwen3-MoE pretrain row — the BASELINE.json north-star metric.

    Single chip: local MoE path (no EP axes), auto SDPA (pallas flash on
    TPU), fused CCE, remat — target-config shape per the reference example
    (example/qwen3_moe/pretrain.json:57-80: 16 layers, 128 experts, top-8,
    hidden 768), sized to fit one chip's HBM.

    ``hybrid=True`` benches the Qwen3-Next-style family instead (BASELINE
    config 5): the same MoE stack with GatedDeltaNet on 3 of every 4
    layers (3:1 GDN:attention), sigmoid attention output gates, partial
    RoPE and zero-centered norms — the linear-attention hot path running
    through ops/gated_delta.py's chunked WY form.
    """
    import os

    import jax
    import jax.numpy as jnp
    import numpy as np

    from d9d_tpu.core import MeshParameters
    from d9d_tpu.loop import (
        AdamWProvider,
        CausalLMTask,
        DatasetProvider,
        ModelProvider,
        Trainer,
        TrainerConfig,
    )
    from d9d_tpu.loop.control.providers import OptimizerProvider
    from d9d_tpu.models.qwen3 import Qwen3MoeCausalLM, Qwen3MoeConfig
    from d9d_tpu.nn.sdpa import build_sdpa_backend
    from d9d_tpu.optim import StochasticAdamW
    from d9d_tpu.parallel import replicate_plan

    class StochasticAdamWProvider(OptimizerProvider):
        def build(self, learning_rate):
            return StochasticAdamW(
                learning_rate,
                weight_decay=0.0,
                moment_dtype=jnp.bfloat16,
            )

    def hybrid_overrides(n_layers):
        """Qwen3-Next-style geometry: GDN everywhere except every 4th
        layer (3:1 ratio), gated attention, partial RoPE, zero-centered
        norms — ONE definition so the tiny CI config and the benched chip
        config can't drift apart."""
        if not hybrid:
            return {}
        return {
            "linear_attention_layers": tuple(
                i for i in range(n_layers) if i % 4 != 3
            ),
            "use_output_gate": True,
            "rope_fraction": 0.25,
            "zero_centered_norms": True,
        }

    if tiny:
        cfg = Qwen3MoeConfig(
            vocab_ranges=(("default", 256),),
            hidden_size=64,
            num_layers=2 if not hybrid else 4,
            num_heads=4,
            num_kv_heads=2,
            head_dim=16,
            moe_intermediate_size=64,
            num_experts=8,
            num_experts_per_tok=2,
            remat=False,
            **hybrid_overrides(4),
        )
        seq_len, batch = 64, 4
        steps_warmup, steps_measure = 1, 2
        dtype = jnp.float32
    else:
        # reference example shape (pretrain.json: 16L, 128 experts, top-8,
        # h768) scaled to one chip's HBM: 64 experts x i256 keeps total
        # params + fp32 AdamW moments ~8 GB (fits a 16 GB v5e; 128E x i384
        # would need ~22 GB)
        cfg = Qwen3MoeConfig(
            vocab_ranges=(("default", 32_768),),
            hidden_size=768,
            num_layers=16,
            num_heads=12,
            num_kv_heads=4,
            head_dim=64,
            moe_intermediate_size=256,
            num_experts=64,
            num_experts_per_tok=8,
            remat=True,
            # tuning knob for on-chip sweeps, like the dense row's
            remat_policy=os.environ.get("D9D_BENCH_REMAT_POLICY", "full"),
            # r4 MFU lever, as in the dense row
            fused_qkv=os.environ.get("D9D_BENCH_FUSED_QKV", "1") == "1",
            **hybrid_overrides(16),
        )
        seq_len, batch = 2048, 8
        steps_warmup, steps_measure = 3, 10
        dtype = jnp.bfloat16

    # dropless MoE expands each token top_k x before the grouped matmuls:
    # at microbatch 8 the [B*T*top_k, D] ragged-dot temps alone are
    # ~20 x 192 MB and blow a 16 GB chip's HBM; with fp32 AdamW moments
    # even microbatch 2 needs 16.56G (params+moments 7.6G, temps 8.95G
    # incl. the fp32 grad accumulator — measured r3). StochasticAdamW with
    # bf16 moments (the reference's own optimizer family) cuts optimizer
    # state to 2.7G, which fits microbatch 2 — set D9D_BENCH_MOE_UB=2 to
    # run that variant; the recorded row is the validated microbatch-1 one.
    microbatch = batch if tiny else int(os.environ.get("D9D_BENCH_MOE_UB", "1"))

    # D9D_BENCH_MOE_ZERO=1: ZeRO-style optimizer-state sharding over
    # dp_replicate (parallel/zero.py) — the mesh spans every visible
    # chip as dp_r and each chip streams 1/N of the fp32 masters/Adam
    # moments per step (docs/design/zero_sharding.md). Single-chip
    # tunnels degrade to dp_r=1 (the code path still runs; the 1/N
    # claim needs a multi-chip window). The per-chip global batch is
    # held constant: tokens/s/chip stays the recorded metric.
    zero = os.environ.get("D9D_BENCH_MOE_ZERO", "0") == "1"
    n_dev = len(jax.devices())
    dp_replicate = (min(n_dev, 4) if tiny else n_dev) if zero else 1
    if zero and not tiny:
        # constant per-chip load: global batch AND the (DP-global)
        # microbatch scale by the replica count, so per-chip µBS and
        # num_microbatches match the single-chip leg exactly
        batch = batch * dp_replicate
        microbatch = microbatch * dp_replicate
    # per-chip µBS drives the fp32-vs-bf16-master recipe choice below
    ub_chip = microbatch // dp_replicate

    class Provider(ModelProvider):
        def build_module(self, stage):
            return Qwen3MoeCausalLM(
                config=cfg, sdpa=build_sdpa_backend(), stage=stage,
                dtype=dtype,
                # the microbatch>=2 variant runs the reference's flagship
                # recipe — bf16 master weights + stochastic-rounding AdamW
                # — which also removes the per-traversal fp32->bf16 cast
                # of every weight (2.7G of fp32 reads per pass)
                param_dtype=jnp.float32 if ub_chip <= 1 or tiny
                else jnp.bfloat16,
                # "auto" (the r4 default) encodes the r3 sweep: one
                # chunk at n<=2048 (the µBS=1 win: 25.3k vs 24.5k tok/s),
                # 512 beyond — no per-config pin needed anymore
                ce_chunk_size="auto",
            )

        def build_plan(self, c):
            return replicate_plan(c)

        def sample_inputs(self, batch_size, seq_len):
            z = jnp.zeros((batch_size, seq_len), jnp.int32)
            return (z, z, z)

    class Data(DatasetProvider):
        def build(self):
            rng = np.random.RandomState(0)
            while True:
                yield {
                    "input_ids": rng.randint(
                        0, cfg.vocab_size, size=(batch, seq_len + 1)
                    )
                }

    ctx = MeshParameters(dp_replicate=dp_replicate).build(
        jax.devices()[:dp_replicate]
    )
    trainer = Trainer(
        ctx=ctx,
        config=TrainerConfig(
            global_batch_size=batch,
            microbatch_size=microbatch,
            seq_len=seq_len,
            total_steps=steps_warmup + steps_measure,
            log_every=10_000,
            zero_sharding=zero,
        ),
        model_provider=Provider(),
        dataset_provider=Data(),
        task=CausalLMTask(),
        # microbatch 1 (the recorded row) fits fp32-moment AdamW; larger
        # microbatches only fit with bf16 moments (see note above)
        optimizer_provider=AdamWProvider(weight_decay=0.0)
        if ub_chip <= 1 or tiny
        else StochasticAdamWProvider(),
    )
    opt_state_bytes_per_chip = trainer.opt_state_bytes_per_chip()

    tok_per_s = _measure(
        trainer, iter(Data().build()), warmup=steps_warmup,
        steps=steps_measure, batch=batch, seq_len=seq_len,
        profile_tag=None if tiny else ("hybrid" if hybrid else "moe"),
    )
    # the recorded metric is tokens/sec/CHIP: the multi-replica ZeRO leg
    # measures whole-mesh throughput over dp_replicate chips
    tok_per_s /= dp_replicate

    # active params: experts scaled by top_k/num_experts, everything else
    # 1x — the same shared accounting the trainer's live-MFU gauge uses
    from d9d_tpu.telemetry.flops import active_param_count

    total_params = sum(
        int(np.prod(leaf.shape)) for leaf in jax.tree.leaves(trainer.params)
    )
    active = active_param_count([trainer.params], cfg)
    # hybrid: quadratic-attention FLOPs only on the attention layers; the
    # GDN layers' chunked delta rule counted from its matmul inventory
    model_fpt, hw_fpt = _flops_accounting(
        cfg, seq_len=seq_len, active_param_count=active,
    )
    peak, kind = _peak()
    recorded_tbl = RECORDED_HYBRID if hybrid else RECORDED_MOE
    recorded = next((v for k, v in recorded_tbl.items() if k in kind), None)
    if recorded is not None and not tiny:
        vs_baseline = round(tok_per_s / recorded, 4)
    else:
        # no recorded row yet (or tiny CI config): report null rather
        # than fabricating parity; the dense headline keeps the driver's
        # numeric contract
        vs_baseline = None if hybrid else 1.0
    return {
        "metric": (
            "qwen3_next_hybrid_tokens_per_sec_per_chip"
            if hybrid else "qwen3_moe_tokens_per_sec_per_chip"
        ),
        "value": round(tok_per_s, 1),
        "unit": "tokens/s",
        "vs_baseline": vs_baseline,
        "detail": {
            "mfu": round(tok_per_s * model_fpt / peak, 4),
            "hfu": round(tok_per_s * hw_fpt / peak, 4),
            "total_params": total_params,
            "active_params": int(active),
            "seq_len": seq_len,
            "batch": batch,
            "steps": steps_measure,
            "device": jax.devices()[0].device_kind,
            # ZeRO observability (docs/design/zero_sharding.md): the 1/N
            # optimizer-state claim as an executable number — mirrors
            # the opt/state_bytes_per_chip telemetry gauge
            "zero_sharding": zero,
            "dp_replicate": dp_replicate,
            "opt_state_bytes_per_chip": opt_state_bytes_per_chip,
        },
    }


def run_bench_input_pipeline(*, tiny: bool = False) -> dict:
    """Input-pipeline overlap check (VERDICT r3 item 4 done-criterion).

    Three step-time measurements on the same dense model:

    - ``synthetic``: one pre-staged device batch reused every step — the
      floor with zero input work;
    - ``sync``: a REAL tokenized dataset (host-side doc packing per batch)
      fetched + staged on the step path (``Trainer.run_step``);
    - ``prefetch``: the same dataset through ``BatchPrefetcher`` (the
      ``train()`` loop's default) — fetch/prepare/stage on a producer
      thread, ``depth=2``.

    Overlap is proven when ``prefetch`` ≈ ``synthetic`` while ``sync``
    carries the data cost. Matches the reference's worker-backed loader
    (d9d/loop/component/data_loader_factory.py:102).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from d9d_tpu.core import MeshParameters
    from d9d_tpu.loop import (
        AdamWProvider,
        CausalLMTask,
        DatasetProvider,
        ModelProvider,
        Trainer,
        TrainerConfig,
    )
    from d9d_tpu.loop.components.prefetch import BatchPrefetcher
    from d9d_tpu.models.qwen3 import Qwen3DenseCausalLM, Qwen3DenseConfig
    from d9d_tpu.nn.sdpa import build_sdpa_backend
    from d9d_tpu.parallel import replicate_plan
    from tools.benchtime import timeit

    if tiny:
        cfg = Qwen3DenseConfig(
            vocab_ranges=(("default", 256),), hidden_size=64, num_layers=2,
            num_heads=4, num_kv_heads=2, head_dim=16, intermediate_size=128,
            remat=False,
        )
        seq_len, batch = 64, 4
        warmup, steps = 1, 2
        dtype = jnp.float32
    else:
        cfg = Qwen3DenseConfig(
            vocab_ranges=(("default", 32_768),), hidden_size=1024,
            num_layers=12, num_heads=16, num_kv_heads=8, head_dim=64,
            intermediate_size=4096, remat=True,
        )
        seq_len, batch = 2048, 8
        warmup, steps = 3, 10
        dtype = jnp.bfloat16

    class Provider(ModelProvider):
        def build_module(self, stage):
            return Qwen3DenseCausalLM(
                config=cfg, sdpa=build_sdpa_backend(), stage=stage,
                dtype=dtype,
            )

        def build_plan(self, c):
            return replicate_plan(c)

        def sample_inputs(self, batch_size, seq_len):
            z = jnp.zeros((batch_size, seq_len), jnp.int32)
            return (z, z, z)

    def tokenized_stream():
        """Real input-pipeline work per batch: variable-length 'documents'
        packed into fixed [batch, seq+1] rows (the tokenize-and-pack host
        cost a production loader pays)."""
        rng = np.random.RandomState(0)
        need = batch * (seq_len + 1)
        while True:
            docs = []
            have = 0
            while have < need:
                doc = rng.randint(
                    0, cfg.vocab_size, size=rng.randint(64, 512)
                ).astype(np.int32)
                docs.append(doc)
                have += len(doc)
            stream = np.concatenate(docs)[:need]
            yield {"input_ids": stream.reshape(batch, seq_len + 1)}

    class Data(DatasetProvider):
        def build(self):
            return tokenized_stream()

    ctx = MeshParameters().build(jax.devices()[:1])
    trainer = Trainer(
        ctx=ctx,
        config=TrainerConfig(
            global_batch_size=batch, microbatch_size=batch, seq_len=seq_len,
            total_steps=10_000, log_every=10_000,
        ),
        model_provider=Provider(),
        dataset_provider=Data(),
        task=CausalLMTask(),
        optimizer_provider=AdamWProvider(weight_decay=0.0),
    )

    # shared fetch-sync/RTT-corrected methodology (tools/benchtime.timeit);
    # None = RTT jitter swamped the signal → reported as unmeasurable
    # synthetic floor: one staged batch reused, no input work at all
    staged = trainer._stage_batch(next(tokenized_stream()))
    synthetic_ms = timeit(
        lambda: trainer._optimizer_step(staged), reps=steps, warmup=warmup
    )

    # sync: real dataset fetched + staged on the step path
    sync_iter = tokenized_stream()
    sync_ms = timeit(
        lambda: trainer.run_step(next(sync_iter)), reps=steps, warmup=warmup
    )

    # prefetch: same dataset through the producer thread (train() default)
    pf = BatchPrefetcher(tokenized_stream(), trainer._stage_batch, depth=2)
    try:
        prefetch_ms = timeit(
            lambda: trainer._optimizer_step(next(pf)), reps=steps,
            warmup=warmup,
        )
    finally:
        pf.close()

    measurable = None not in (synthetic_ms, sync_ms, prefetch_ms)
    return {
        "metric": "input_pipeline_step_ms",
        "synthetic_ms": round(synthetic_ms, 2) if synthetic_ms else None,
        "sync_ms": round(sync_ms, 2) if sync_ms else None,
        "prefetch_ms": round(prefetch_ms, 2) if prefetch_ms else None,
        "overlap_recovered": round(
            (sync_ms - prefetch_ms) / max(sync_ms - synthetic_ms, 1e-9), 3
        ) if measurable else "unmeasurable: fetch-RTT jitter",
        "steps": steps,
    }


def run_bench_generate(*, tiny: bool = False) -> dict:
    """Autoregressive decode throughput (loop/generate.py) on the dense
    headline geometry: batch rows decode greedily from a KV cache; the
    metric is generated tokens/sec/chip (decode is HBM-bound — each token
    re-reads the weights — so this row tracks effective weight-stream
    bandwidth, not MXU)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from d9d_tpu.loop.generate import generate
    from d9d_tpu.models.qwen3 import Qwen3DenseCausalLM, Qwen3DenseConfig
    from d9d_tpu.nn.sdpa import build_sdpa_backend
    from d9d_tpu.ops.attention.pallas_decode import decode_attention_backend
    from tools.benchtime import host_fetch_sync, measure_rtt

    if tiny:
        cfg = Qwen3DenseConfig.tiny()
        batch, prompt, gen = 2, 8, 8
        dtype = jnp.float32
    else:
        cfg = Qwen3DenseConfig(
            vocab_ranges=(("default", 32_768),),
            hidden_size=1024,
            num_layers=12,
            num_heads=16,
            num_kv_heads=8,
            head_dim=64,
            intermediate_size=4096,
            remat=False,
        )
        batch, prompt, gen = 8, 128, 256
        dtype = jnp.bfloat16
    model = Qwen3DenseCausalLM(
        config=cfg, sdpa=build_sdpa_backend(), dtype=dtype,
        decode_max_length=prompt + gen,
    )
    z = jnp.zeros((batch, prompt), jnp.int32)
    pos = jnp.broadcast_to(
        jnp.arange(prompt, dtype=jnp.int32), (batch, prompt)
    )
    params = model.init(jax.random.PRNGKey(0), z, pos, z)["params"]
    # inference-weight width A/B: tools/roofline.py attributes most of the
    # decode step (~92%) to streaming fp32 master weights; D9D_BENCH_DECODE_BF16
    # casts the params once up front (what a deployment would serve)
    import os as _os

    infer_bf16 = _os.environ.get("D9D_BENCH_DECODE_BF16", "0") == "1"
    if infer_bf16:
        params = jax.tree.map(
            lambda p: p.astype(jnp.bfloat16)
            if jnp.issubdtype(p.dtype, jnp.floating) else p,
            params,
        )
    rng = np.random.RandomState(0)
    prompt_ids = jnp.asarray(
        rng.randint(0, cfg.vocab_size, (batch, prompt)), jnp.int32
    )

    run = jax.jit(
        lambda prm, p_ids: generate(model, prm, p_ids, max_new_tokens=gen)
    )
    out = run(params, prompt_ids)  # compile + warmup
    host_fetch_sync(out)
    rtt = measure_rtt(out)
    reps = 1 if tiny else 3
    t0 = time.perf_counter()
    for _ in range(reps):
        out = run(params, prompt_ids)
    host_fetch_sync(out)
    dt = time.perf_counter() - t0 - rtt
    if dt <= 0:  # RTT jitter swamped the signal (benchtime.timeit rule)
        return {
            "metric": "dense_lm_decode_tokens_per_sec_per_chip",
            "value": -1.0,
            "unit": "tokens/s",
            "vs_baseline": 0.0,
            "detail": {"error": "unmeasurable: fetch-RTT jitter"},
        }
    tok_s = reps * batch * gen / dt
    return {
        "metric": "dense_lm_decode_tokens_per_sec_per_chip",
        "value": round(tok_s, 1),
        "unit": "tokens/s",
        "vs_baseline": 1.0,  # first recorded decode row
        "detail": {
            "batch": batch,
            "prompt": prompt,
            "new_tokens": gen,
            "weights": "bf16" if infer_bf16 else "fp32_masters",
            "decode_attn": decode_attention_backend(),
            "device": jax.devices()[0].device_kind,
        },
    }


def run_bench_serving(*, tiny: bool = False) -> dict:
    """Steady-state serving throughput through ``ContinuousBatcher``
    (VERDICT r5 Weak #5: serving had no throughput story).

    Drives a Poisson-ish arrival queue through the fused K-step decode
    loop on the dense decode geometry and reports generated tokens/sec,
    slot-utilization %, and host dispatches per 1k tokens, with the
    per-token stepping mode as the pinned before/after comparison
    (tools/bench_serve.py is the CPU-runnable sweep this leg mirrors).
    Decode is HBM-bound per token like run_bench_generate; what this row
    adds is the HOST side — whether dispatch latency can starve the chip
    between chunks at serving batch sizes.
    """
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from tools.bench_serve import build_model, make_workload, run_mode

    model, params, cfg = build_model(tiny)
    batch = 2 if tiny else 8
    n_req = 4 if tiny else 24
    gen_hi = 12 if tiny else 128
    workload = make_workload(
        vocab=cfg.vocab_size, requests=n_req, seed=0,
        prompt_lo=2, prompt_hi=6 if tiny else 32,
        gen_lo=4, gen_hi=gen_hi, mean_interarrival=gen_hi / batch,
    )
    k = int(os.environ.get("D9D_BENCH_SERVE_K", "8"))
    from d9d_tpu.telemetry import attached_jsonl_sink

    # one sink across both modes; run_mode's post-warmup
    # reset_instruments() isolates each mode's flush snapshot
    with attached_jsonl_sink(
        os.environ.get("D9D_TELEMETRY_DIR"), run_name="bench_serving"
    ) as (hub, sink):

        def _timed_mode(mode_index, **kw):
            try:
                return run_mode(
                    model, params, workload, batch_size=batch, **kw
                )
            finally:
                if sink is not None:
                    hub.flush(step=mode_index)

        fused, fused_out = _timed_mode(0, chunk_size=k, overlap=True)
        per_tok, per_tok_out = _timed_mode(1, chunk_size=None, overlap=False)
    return {
        "metric": "serving_tokens_per_sec_per_chip",
        "value": round(fused["tok_per_s"], 1),
        "unit": "tokens/s",
        "vs_baseline": 1.0,  # first recorded serving row
        "detail": {
            "chunk_k": k,
            "slot_utilization": round(fused["slot_utilization"], 4),
            "dispatches_per_1k_tokens": round(
                fused["dispatches_per_1k_tokens"], 2
            ),
            "per_token_tok_per_s": round(per_tok["tok_per_s"], 1),
            "per_token_dispatches_per_1k_tokens": round(
                per_tok["dispatches_per_1k_tokens"], 2
            ),
            # introspection columns (telemetry/introspect.py): a warmed
            # steady-state serving loop must not compile at all
            "steady_state_compiles": fused["steady_state_compiles"],
            "recompiles": fused["recompiles"],
            "speedup_vs_per_token": round(
                fused["tok_per_s"] / max(per_tok["tok_per_s"], 1e-9), 3
            ),
            "exact_vs_per_token": fused_out == per_tok_out,
            "requests": n_req,
            "batch": batch,
            "device": __import__("jax").devices()[0].device_kind,
        },
    }


def run_bench_pp_fused() -> dict:
    """Fused-PP dispatch tax row (ISSUE 16): the tiny 1F1B schedule
    through the legacy per-action interpreter vs the compiled-run
    executor, counting real executable dispatches at the one point both
    runtimes share — ``TrackedJit.__call__``.

    Both counts are structural (what the host enqueues per step), not
    wall-clock, so the row is exactly reproducible on any backend; the
    same leg is pinned by tools/bench_compare.py's ``pp_micro.*`` gate
    on CPU. What running it HERE adds is the chip-side proof that the
    fused programs compile and execute on the real backend.
    """
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from tools.bench_compare import PP_MICRO, run_pp_micro

    m = run_pp_micro()
    return {
        "metric": "pp/dispatches_per_step",
        "value": m["pp_micro.dispatches_per_step"],
        "unit": "dispatches",
        "vs_baseline": 1.0,  # first recorded fused-PP row
        "detail": {
            "pp/fused_programs": m["pp_micro.fused_programs"],
            "legacy_dispatches_per_step":
                m["pp_micro.legacy_dispatches_per_step"],
            "dispatch_reduction_x": m["pp_micro.dispatch_reduction_x"],
            "exact_vs_legacy": m["pp_micro.exact_vs_legacy"],
            "multirank_dispatches_per_step":
                m["pp_micro.multirank_dispatches_per_step"],
            "multirank_fused_programs":
                m["pp_micro.multirank_fused_programs"],
            "multirank_dispatch_reduction_x":
                m["pp_micro.multirank_dispatch_reduction_x"],
            "multirank_exact_vs_legacy":
                m["pp_micro.multirank_exact_vs_legacy"],
            "num_microbatches": PP_MICRO["num_microbatches"],
            "stages_per_rank": PP_MICRO["stages_per_rank"],
            "multirank_pp": PP_MICRO["multirank_pp"],
            "device": __import__("jax").devices()[0].device_kind,
        },
    }


# rows finished before a watchdog fire; the watchdog folds them into its
# error line so a wedge mid-MoE still delivers the dense number
_partial_results: dict = {}


def _arm_watchdog(seconds: float):
    """Hard wall-clock limit on the whole bench run.

    require_backend only covers ``jax.devices()`` hanging; round 4 hit the
    other wedge — the backend comes up, the first compiled step is
    dispatched, and the tunnel never delivers the result (the host fetch
    polls forever; 48 min observed with zero tunnel traffic). A bench that
    hangs is worse for the driver than a bench that reports the outage, so
    a daemon thread prints an honest JSON error line (carrying any rows
    that DID finish) and exits 4 when the budget runs out. Disable with
    D9D_BENCH_WATCHDOG_S=0.
    """
    import os
    import threading

    def fire():
        out = {
            "error": f"bench watchdog: no result within {seconds:.0f}s "
                     "(tunnel wedged mid-step?)",
        }
        if _partial_results:
            out["partial"] = _partial_results
        print(json.dumps(out), flush=True)
        os._exit(4)

    t = threading.Timer(seconds, fire)
    t.daemon = True
    t.start()
    return t


def main():
    import os
    import sys

    # tools/ sits next to this file; anchor the import so bench.py works
    # when invoked from any cwd
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from tools.benchtime import require_backend

    tiny = "--tiny" in sys.argv[1:]
    watchdog_s = float(os.environ.get("D9D_BENCH_WATCHDOG_S", "2700"))
    if watchdog_s > 0:
        _arm_watchdog(watchdog_s)
    require_backend("bench")
    if tiny:
        # liveness ladder rung: a 2-layer model, 3 steps — proves
        # compile+execute round-trips through the tunnel before the full
        # legs commit their multi-minute compiles to it
        out = run_bench(tiny=True)
        print(json.dumps(out))
        return
    dense = run_bench()
    out = dict(dense)
    out["detail"] = dict(dense["detail"])
    _partial_results["dense"] = dense
    # The dense headline must survive an MoE failure (an OOM here ate the
    # whole round-3 capture once) — record the error instead of dying.
    try:
        moe = run_bench_moe()
    except Exception as e:  # noqa: BLE001 — any chip-side failure
        out["detail"]["moe_error"] = f"{type(e).__name__}: {str(e)[:300]}"
    else:
        out["detail"]["moe"] = {
            "metric": moe["metric"],
            "value": moe["value"],
            "unit": moe["unit"],
            "vs_baseline": moe["vs_baseline"],
            **moe["detail"],
        }
        _partial_results["moe"] = out["detail"]["moe"]
    # BASELINE config 5: the hybrid (Qwen3-Next/GDN) family's first row
    try:
        hyb = run_bench_moe(hybrid=True)
    except Exception as e:  # noqa: BLE001 — any chip-side failure
        out["detail"]["hybrid_error"] = f"{type(e).__name__}: {str(e)[:300]}"
    else:
        out["detail"]["hybrid"] = {
            "metric": hyb["metric"],
            "value": hyb["value"],
            "unit": hyb["unit"],
            "vs_baseline": hyb["vs_baseline"],
            **hyb["detail"],
        }
        _partial_results["hybrid"] = out["detail"]["hybrid"]
    # steady-state serving row (fused K-step ContinuousBatcher decode
    # loop vs per-token stepping — VERDICT r5 Weak #5)
    try:
        srv = run_bench_serving()
    except Exception as e:  # noqa: BLE001 — any chip-side failure
        out["detail"]["serving_error"] = f"{type(e).__name__}: {str(e)[:300]}"
    else:
        out["detail"]["serving"] = {
            "metric": srv["metric"],
            "value": srv["value"],
            "unit": srv["unit"],
            "vs_baseline": srv["vs_baseline"],
            **srv["detail"],
        }
        _partial_results["serving"] = out["detail"]["serving"]
    # fused-PP dispatch row (ISSUE 16: the single-controller dispatch
    # tax) — structural counts, cheap even on the tunnel
    try:
        pp = run_bench_pp_fused()
    except Exception as e:  # noqa: BLE001 — any chip-side failure
        out["detail"]["pp_error"] = f"{type(e).__name__}: {str(e)[:300]}"
    else:
        out["detail"]["pp"] = {
            "metric": pp["metric"],
            "value": pp["value"],
            "unit": pp["unit"],
            "vs_baseline": pp["vs_baseline"],
            **pp["detail"],
        }
        _partial_results["pp"] = out["detail"]["pp"]
    print(json.dumps(out))


if __name__ == "__main__":
    main()
