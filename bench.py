"""Benchmark: dense-LM training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
The reference publishes no absolute numbers (BASELINE.md), so the baseline
is this repo's own best recorded measurement (RECORDED below, mirrored in
BASELINE.md's measured-rows table); vs_baseline = value / recorded.

Uses only the public Trainer API (``Trainer.run_step``); covered by
tests/test_bench.py so it cannot silently rot against loop refactors.
"""

import json
import time

# Peak bf16 TFLOPs per chip by device kind substring.
PEAK_FLOPS = {"v5 lite": 197e12, "v5e": 197e12, "v5p": 459e12, "v4": 275e12,
              "v6": 918e12}

# Best previously recorded result for this benchmark config (BASELINE.md).
# Keyed by device kind substring; falls back to 1.0 ratio on new hardware.
RECORDED = {"v5 lite": 48163.0, "v5e": 48163.0}


def run_bench(*, tiny: bool = False) -> dict:
    """Build a dense-LM trainer and measure optimizer-step throughput.

    ``tiny=True`` shrinks the model/steps so the benchmark harness itself
    can run in tests on the 8-device CPU mesh.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from d9d_tpu.core import MeshParameters
    from d9d_tpu.loop import (
        AdamWProvider,
        CausalLMTask,
        DatasetProvider,
        ModelProvider,
        Trainer,
        TrainerConfig,
    )
    from d9d_tpu.models.qwen3 import Qwen3DenseCausalLM, Qwen3DenseConfig
    from d9d_tpu.nn.sdpa import build_sdpa_backend
    from d9d_tpu.parallel import replicate_plan

    if tiny:
        cfg = Qwen3DenseConfig(
            vocab_ranges=(("default", 256),),
            hidden_size=64,
            num_layers=2,
            num_heads=4,
            num_kv_heads=2,
            head_dim=16,
            intermediate_size=128,
            remat=False,
        )
        seq_len, batch = 64, 4
        steps_warmup, steps_measure = 1, 2
        dtype = jnp.float32
    else:
        cfg = Qwen3DenseConfig(
            vocab_ranges=(("default", 32_768),),
            hidden_size=1024,
            num_layers=12,
            num_heads=16,
            num_kv_heads=8,
            head_dim=64,
            intermediate_size=4096,
            remat=True,
        )
        seq_len, batch = 2048, 8
        steps_warmup, steps_measure = 3, 10
        dtype = jnp.bfloat16

    class Provider(ModelProvider):
        def build_module(self, stage):
            return Qwen3DenseCausalLM(
                config=cfg, sdpa=build_sdpa_backend(), stage=stage,
                dtype=dtype,
            )

        def build_plan(self, c):
            return replicate_plan(c)

        def sample_inputs(self, batch_size, seq_len):
            z = jnp.zeros((batch_size, seq_len), jnp.int32)
            return (z, z, z)

    class Data(DatasetProvider):
        def build(self):
            rng = np.random.RandomState(0)
            while True:
                yield {
                    "input_ids": rng.randint(
                        0, cfg.vocab_size, size=(batch, seq_len + 1)
                    )
                }

    ctx = MeshParameters().build(jax.devices()[:1])
    trainer = Trainer(
        ctx=ctx,
        config=TrainerConfig(
            global_batch_size=batch,
            microbatch_size=batch,
            seq_len=seq_len,
            total_steps=steps_warmup + steps_measure,
            log_every=10_000,
        ),
        model_provider=Provider(),
        dataset_provider=Data(),
        task=CausalLMTask(),
        optimizer_provider=AdamWProvider(weight_decay=0.0),
    )

    data_iter = iter(Data().build())

    # warmup (compile)
    for _ in range(steps_warmup):
        m = trainer.run_step(next(data_iter))
    jax.block_until_ready(m["loss"])

    t0 = time.perf_counter()
    for _ in range(steps_measure):
        m = trainer.run_step(next(data_iter))
    jax.block_until_ready(m["loss"])
    dt = time.perf_counter() - t0

    tokens = steps_measure * batch * seq_len
    tok_per_s = tokens / dt

    n_params = sum(
        int(np.prod(x.shape)) for x in jax.tree.leaves(trainer.params)
    )
    # fwd+bwd ≈ 6*N per token (+remat fwd ≈ 8*N) + causal attention flops:
    # 12 * L * heads * head_dim * T / 2 per token (QK^T + PV, fwd+bwd)
    param_factor = 8 if cfg.remat else 6
    attn_flops = 6 * cfg.num_layers * cfg.num_heads * cfg.head_dim * seq_len
    flops_per_token = param_factor * n_params + attn_flops
    kind = jax.devices()[0].device_kind.lower()
    peak = next((v for k, v in PEAK_FLOPS.items() if k in kind), 197e12)
    mfu = tok_per_s * flops_per_token / peak
    recorded = next((v for k, v in RECORDED.items() if k in kind), None)
    vs_baseline = round(tok_per_s / recorded, 4) if (
        recorded is not None and not tiny
    ) else 1.0

    return {
        "metric": "dense_lm_tokens_per_sec_per_chip",
        "value": round(tok_per_s, 1),
        "unit": "tokens/s",
        "vs_baseline": vs_baseline,
        "detail": {
            "mfu": round(mfu, 4),
            "params": n_params,
            "seq_len": seq_len,
            "batch": batch,
            "steps": steps_measure,
            "device": jax.devices()[0].device_kind,
        },
    }


def main():
    print(json.dumps(run_bench()))


if __name__ == "__main__":
    main()
