"""Expert-parallel token dispatch/combine over ``jax.lax.ragged_all_to_all``.

TPU-native replacement for DeepEP's NVSHMEM all-to-all buffer (reference
d9d/module/block/moe/communications/deepep.py:55-150): tokens travel to the
shard that owns their expert, compute runs only on owned assignments, and
results ride a mirrored ragged all-to-all home. Per-shard grouped-GEMM row
count is the static receive buffer size: ``capacity_factor × N_global·k/ep``
with a capacity factor set (the compute scaling the all-gather flow lacked),
or the dropless worst case ``N_global·k`` with ``capacity_factor=None``
(exact results; only the communication is reduced to the ragged rows).

Flow inside one ``shard_map`` shard over the ep axes (W shards, each
owning ``e_loc = E/W`` experts):

1. sort this shard's ``m = n·k`` assignment rows by global expert id —
   rows become contiguous per destination shard;
2. all-gather the tiny per-expert count vector → the full [W, E] count
   matrix ``S``, from which *every* shard derives identical send/recv
   sizes, offsets, and (under capacity) identical deterministic clamping;
3. ragged all-to-all the hidden rows (only real rows move);
4. re-sort received rows by local expert (they arrive grouped by source),
   grouped-GEMM through this shard's experts;
5. inverse-permute and ragged all-to-all the results back;
6. owner side: weight by router probs and scatter-add per token.

Differentiable end to end: ``ragged_all_to_all`` carries JVP/transpose
rules, so the backward re-crosses the network exactly like DeepEP's
dispatch/combine backward pair (deepep.py:91-150). Capacity overflow drops
the tail rows of a (source, destination) slice deterministically; dropped
assignments contribute exactly zero (their return slot is never written),
matching capacity-style MoE semantics. ``capacity_factor=None`` is
dropless with a ``m·W``-row buffer.
"""

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from d9d_tpu.core.types import Array
from d9d_tpu.ops.moe import combine_pairs, stable_expert_order

__all__ = ["ep_buffer_rows", "ep_dispatch_compute_combine"]


def _ragged_a2a(
    operand, output, in_off, send_sz, out_off, recv_sz, *, ep_axes, ep_world
):
    """``lax.ragged_all_to_all`` on TPU; exact-semantics emulation elsewhere.

    XLA:CPU has no ragged-all-to-all lowering, but the CPU mesh is the test
    rig — so emulate with an all-gather plus index reconstruction: for each
    output row, find the (sender, source-row) pair whose declared slice
    covers it. Slices are disjoint in this module's usage. Differentiable
    (gather-based), so backward tests exercise the same routing math.
    """
    if jax.default_backend() == "tpu":
        return lax.ragged_all_to_all(
            operand, output, in_off, send_sz, out_off, recv_sz,
            axis_name=ep_axes,
        )
    me = lax.axis_index(ep_axes)
    ops = lax.all_gather(operand, ep_axes, axis=0)  # [W, rows, D]
    in_offs = lax.all_gather(in_off, ep_axes, axis=0)  # [W, W]
    send_szs = lax.all_gather(send_sz, ep_axes, axis=0)
    out_offs = lax.all_gather(out_off, ep_axes, axis=0)

    p = jnp.arange(output.shape[0])
    starts = out_offs[:, me]  # where sender s's slice lands here
    sizes = send_szs[:, me]
    srcs_at = in_offs[:, me]
    hit = (p[:, None] >= starts[None, :]) & (
        p[:, None] < (starts + sizes)[None, :]
    )  # [rows_out, W]
    any_hit = hit.any(axis=1)
    s_of = jnp.argmax(hit, axis=1)
    row_of = jnp.take(srcs_at, s_of) + p - jnp.take(starts, s_of)
    row_of = jnp.clip(row_of, 0, operand.shape[0] - 1)
    picked = ops[s_of, row_of]
    return jnp.where(any_hit[:, None], picked, output)


def ep_buffer_rows(
    rows_per_shard: int, ep_world: int, capacity_factor: Optional[float]
) -> int:
    """Static receive-buffer row count (the per-shard grouped-GEMM size)."""
    if capacity_factor is None:
        return rows_per_shard * ep_world  # dropless worst case
    # round up to a sublane multiple for friendly tiling
    return ((math.ceil(rows_per_shard * capacity_factor) + 7) // 8) * 8


def _excl_cumsum(x: Array, axis: int = 0) -> Array:
    return jnp.cumsum(x, axis=axis) - x


def ep_dispatch_compute_combine(
    x_loc: Array,
    ids_loc: Array,
    probs_loc: Array,
    expert_fn,
    *,
    ep_axes: tuple[str, ...],
    e_loc: int,
    ep_world: int,
    capacity_factor: Optional[float],
) -> Array:
    """Inside-shard_map body: route rows to expert owners, compute, return.

    ``expert_fn(rows [M, D], group_sizes [e_loc]) -> [M, D]`` runs this
    shard's experts over expert-sorted rows (probabilities are applied on
    the owner side, after the results come home).
    """
    n, k = ids_loc.shape
    m = n * k
    d_model = x_loc.shape[-1]
    me = lax.axis_index(ep_axes)

    # 1. group assignment rows by global expert id (sort-free stable
    # permutation — see ops/moe.py stable_expert_order; TPU sorts are
    # bitonic and this runs per MoE layer per microbatch)
    ids_flat = ids_loc.reshape(-1)
    order, pair_dest, counts = stable_expert_order(ids_flat, e_loc * ep_world)
    token_of = order // k
    x_rows = jnp.take(x_loc, token_of, axis=0)  # [m, D]

    # 2. tiny count exchange: S[s, e] = rows shard s routes to expert e
    S = lax.all_gather(counts, ep_axes, axis=0)  # [W, E]
    # rows shard s sends to shard d
    R = S.reshape(ep_world, ep_world, e_loc).sum(axis=-1)  # [W(src), W(dst)]

    buf_rows = ep_buffer_rows(m, ep_world, capacity_factor)
    if capacity_factor is None:
        A = R
    else:
        # deterministic clamp, identical on every shard: earlier sources
        # keep their rows, the tail of a receiver's intake is cut
        room = jnp.maximum(buf_rows - _excl_cumsum(R, axis=0), 0)
        A = jnp.minimum(R, room)

    send_sizes = A[me]  # [W] rows I send to each dst
    input_offsets = _excl_cumsum(R[me])  # my sorted rows: blocks sized R[me]
    recv_sizes = A[:, me]  # [W] rows I receive from each src
    recv_offsets = _excl_cumsum(recv_sizes)
    output_offsets = _excl_cumsum(A, axis=0)[me]  # where my slice lands at dst

    # 3. dispatch hidden rows
    recv_buf = jnp.zeros((buf_rows, d_model), x_rows.dtype)
    with jax.named_scope("ep/dispatch_a2a"):
        recv = _ragged_a2a(
            x_rows,
            recv_buf,
            input_offsets.astype(jnp.int32),
            send_sizes.astype(jnp.int32),
            output_offsets.astype(jnp.int32),
            recv_sizes.astype(jnp.int32),
            ep_axes=ep_axes,
            ep_world=ep_world,
        )

    # 4. label received rows with their local expert. A source's slice is
    # expert-sorted; capacity cuts its tail. kcnt[s, e] = kept rows of
    # (src s, my local expert e).
    my_counts = lax.dynamic_slice_in_dim(
        S, me * e_loc, e_loc, axis=1
    )  # [W, e_loc]
    kcnt = jnp.clip(
        recv_sizes[:, None] - _excl_cumsum(my_counts, axis=1),
        0,
        my_counts,
    )
    row_pos = jnp.arange(buf_rows)
    src_of = jnp.searchsorted(
        jnp.cumsum(recv_sizes), row_pos, side="right"
    ).clip(0, ep_world - 1)
    q = row_pos - jnp.take(recv_offsets, src_of)
    incl = jnp.cumsum(kcnt, axis=1)  # [W, e_loc]
    labels = (q[:, None] >= jnp.take(incl, src_of, axis=0)).sum(axis=1)
    labels = jnp.clip(labels, 0, e_loc - 1)  # padding rows → last group

    by_expert, dest, group_sizes = stable_expert_order(labels, e_loc)
    rows_sorted = jnp.take(recv, by_expert, axis=0)

    with jax.named_scope("ep/expert_compute"):
        y_sorted = expert_fn(rows_sorted, group_sizes)
    # un-sort via the inverse permutation as a gather (dest[by_expert[r]]
    # == r) — cheaper than a zeros+scatter on TPU, same as ops/moe.py's
    # unpermute_combine
    y_buf = jnp.take(y_sorted, dest, axis=0)

    # 5. mirrored return trip (swap send/recv roles). My slice for source s
    # must land where s's sorted rows for me begin: s's own block layout.
    return_offsets = _excl_cumsum(R, axis=1)[:, me]
    with jax.named_scope("ep/combine_a2a"):
        home = _ragged_a2a(
            y_buf,
            jnp.zeros((m, d_model), y_buf.dtype),
            recv_offsets.astype(jnp.int32),
            recv_sizes.astype(jnp.int32),
            return_offsets.astype(jnp.int32),
            send_sizes.astype(jnp.int32),
            ep_axes=ep_axes,
            ep_world=ep_world,
        )

    # 6. weight by router probs, fold the k assignments per token
    # (collision-free gather form — see ops/moe.py combine_pairs)
    probs_rows = jnp.take(probs_loc.reshape(-1), order)
    weighted = home * probs_rows[:, None].astype(home.dtype)
    return combine_pairs(weighted, pair_dest, n)
