"""Fused linear + cross-entropy that never holds the full logit matrix.

TPU equivalent of the reference's vendored Cut Cross-Entropy
(d9d/kernel/cce/main.py:119): the LM head projection and the CE loss are
fused so the ``[tokens, vocab]`` logit tensor is only ever materialized one
token-chunk at a time. On TPU this is a ``lax.scan`` over token chunks with
rematerialization (``jax.checkpoint``) — the backward pass recomputes each
chunk's logits instead of storing them, trading MXU FLOPs (cheap) for HBM
(the bottleneck), which is exactly the trade the Triton kernel makes on GPU.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax

from d9d_tpu.core.types import Array

LM_IGNORE_INDEX = -100


def _chunk_loss(
    hidden: Array,
    labels: Array,
    weight_t: Array,
    logit_softcap: float | None,
    matmul_dtype: str = "fp32",
) -> Array:
    """Per-token loss for one chunk. hidden [C,D], labels [C], weight_t [D,V].

    ``matmul_dtype="bf16"`` runs the [C,D]x[D,V] einsum — the largest
    matmul in an LM step — with bf16 inputs and fp32 accumulation
    (``preferred_element_type``), the full-throughput MXU path; "fp32"
    keeps fp32 inputs (half-rate MXU) for exact math. Measured on chip by
    tools/bench_kernels.py (VERDICT r2 Weak #6); the softmax/LSE math is
    fp32 either way.
    """
    if matmul_dtype == "bf16":
        logits = jnp.einsum(
            "cd,dv->cv",
            hidden.astype(jnp.bfloat16),
            weight_t.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )
    else:
        logits = jnp.einsum(
            "cd,dv->cv",
            hidden.astype(jnp.float32),
            weight_t.astype(jnp.float32),
            precision=lax.Precision.DEFAULT,
        )
    if logit_softcap is not None:
        logits = logit_softcap * jnp.tanh(logits / logit_softcap)
    lse = jax.nn.logsumexp(logits, axis=-1)
    safe_labels = jnp.clip(labels, 0, logits.shape[-1] - 1)
    correct = jnp.take_along_axis(logits, safe_labels[:, None], axis=-1)[:, 0]
    loss = lse - correct
    return jnp.where(labels == LM_IGNORE_INDEX, 0.0, loss)


# auto chunking: a single chunk wins up to this many tokens (r3 sweep) —
# but only while the live logit slab stays within the swept budget
# (2048 tokens × 32768 vocab ≈ 268 MB fp32); larger n·V keeps chunking,
# which is the whole point of CCE (never hold [N, V])
_AUTO_SINGLE_CHUNK_MAX = 2048
_AUTO_SINGLE_CHUNK_MAX_LOGITS = 2048 * 32_768


def linear_cross_entropy(
    hidden: Array,
    weight: Array,
    labels: Array,
    *,
    chunk_size: "int | str" = "auto",
    logit_softcap: float | None = None,
    matmul_dtype: str | None = None,
) -> Array:
    """Per-token CE of ``hidden [N,D] @ weight[V,D].T`` against ``labels [N]``.

    Tokens labelled ``LM_IGNORE_INDEX`` (-100) contribute zero loss
    (reference: module/block/head/language_modelling.py:14). Returns fp32
    ``[N]`` — reduction/weighting is the caller's policy.

    ``matmul_dtype`` (see :func:`_chunk_loss`) defaults to the policy
    implied by ``hidden.dtype``: bf16 activations take the full-rate MXU
    path, anything else stays exact fp32 — so fp32 callers never lose
    precision silently.

    ``chunk_size`` follows the r3 on-chip sweeps (tools/bench_kernels.py,
    BASELINE.md): at n=16384 d=1024 v=32768 chunk 512 beat 2048/8192 by
    ~20% fwd while holding the smallest live logit slab, but at n=2048 a
    SINGLE chunk beat 512 (25.3k vs 24.5k tok/s end-to-end, the µBS=1 MoE
    row). ``"auto"`` (default) encodes that sweep: one chunk up to n=2048
    AND a logit slab no bigger than the swept 2048×32768, 512 beyond —
    pass an int to pin it.
    """
    if matmul_dtype is None:
        matmul_dtype = "bf16" if hidden.dtype == jnp.bfloat16 else "fp32"
    n, d = hidden.shape
    if chunk_size == "auto":
        v = weight.shape[0]
        single = (
            n <= _AUTO_SINGLE_CHUNK_MAX
            and n * v <= _AUTO_SINGLE_CHUNK_MAX_LOGITS
        )
        chunk_size = n if single else 512
    chunk_size = int(chunk_size)
    weight_t = weight.T  # [D, V]

    if n <= chunk_size:
        return _chunk_loss(
            hidden, labels, weight_t, logit_softcap, matmul_dtype
        )

    pad = (-n) % chunk_size
    if pad:
        hidden = jnp.pad(hidden, ((0, pad), (0, 0)))
        labels = jnp.pad(labels, (0, pad), constant_values=LM_IGNORE_INDEX)
    num_chunks = hidden.shape[0] // chunk_size
    hidden = hidden.reshape(num_chunks, chunk_size, d)
    labels = labels.reshape(num_chunks, chunk_size)

    body = jax.checkpoint(
        functools.partial(
            _chunk_loss,
            logit_softcap=logit_softcap,
            matmul_dtype=matmul_dtype,
        )
    )

    def scan_fn(carry, xs):
        h, l = xs
        return carry, body(h, l, weight_t)

    _, losses = lax.scan(scan_fn, None, (hidden, labels))
    losses = losses.reshape(-1)
    return losses[:n] if pad else losses
