"""RMS normalization op.

TPU equivalent of the reference Triton RMSNorm kernel
(d9d/kernel/normalization/rms/function.py:29, op.py:26,153), including the
zero-centered-weight variant (DeepSeek style). On TPU the forward/backward
are left to XLA, which fuses the reduction + scale into neighbouring ops —
a hand-written Pallas kernel only pays off when fused into larger blocks,
which is handled at the block level.

The reduction runs in float32 regardless of input dtype (matching the
reference kernel's internal fp32 accumulation) and casts back at the end.
"""

import jax.numpy as jnp
from jax import lax

from d9d_tpu.core.types import Array


def rms_norm(
    x: Array,
    weight: Array,
    *,
    eps: float = 1e-6,
    zero_centered: bool = False,
) -> Array:
    """Normalize ``x`` over its last dim and scale by ``weight``.

    With ``zero_centered=True`` the effective scale is ``1 + weight`` (the
    parameter is stored as an offset from 1, reference rms/function.py:29).
    """
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    normed = xf * lax.rsqrt(var + eps)
    scale = weight.astype(jnp.float32)
    if zero_centered:
        scale = scale + 1.0
    return (normed * scale).astype(dtype)
