"""Fused expert-FFN Pallas kernel over a group-aligned tile layout.

Replaces the local MoE compute chain
``grouped_matmul(gate+up) -> silu_mul -> grouped_matmul(down) -> *probs``
(ops/moe.py + nn/moe.py grouped_swiglu_apply; reference analogue:
nv-grouped-gemm + Triton permute/silu kernels, d9d/kernel/gmm/function.py,
d9d/kernel/moe/) with ONE Pallas kernel per layer call.

Why (tools/roofline.py attribution of the 0.136-MFU north star): the XLA
chain round-trips ``[M, 2*inter]`` gate+up activations and ``[M, inter]``
hidden through HBM between the grouped matmuls, and the fused gate+up
single-ragged_dot trick additionally materializes a runtime
``[E, in, 2*inter]`` weight concat every call (ADVICE r3). At the bench
geometry that is ~150 MB of avoidable HBM traffic per layer pass. This
kernel keeps those intermediates in VMEM: each grid step loads one
``[block_m, h]`` activation tile plus its expert's three weight blocks,
runs gate/up/down matmuls + silu + prob-scale on-chip, and writes only
the ``[block_m, h]`` output tile.

The enabling layout trick is GROUP ALIGNMENT: expert groups are padded to
``block_m`` multiples so every tile belongs to exactly one expert — no
boundary tiles spanning two experts, so the kernel needs no multi-pass
accumulation (the hard part of megablocks-style GMMs). The pad rows are
zeros and cost only their matmul FLOPs, which the roofline shows are not
the binding resource at MoE shapes (the step is HBM-bound). Consecutive
tiles of the same expert reuse the already-fetched weight blocks (Pallas
skips re-DMA when the mapped block index repeats, and tiles are
expert-sorted by construction).

Backward: ``fused_moe_ffn`` is a custom_vjp whose bwd re-runs the
reference XLA path under ``jax.vjp`` — exact gradients, same cost as
today's remat backward, zero extra residual memory (saved tensors are the
function's own inputs). The fused kernel accelerates the forward AND the
remat recompute (jax.checkpoint replays the custom fwd).

Enable via ``D9D_TPU_MOE_FFN=pallas`` (default ``xla``); falls back to
the XLA path when shapes don't meet the TPU tiling constraints or the
VMEM budget. ``D9D_TPU_MOE_FFN=pallas_gather`` additionally fuses the
permute gather into the kernel: the whole token matrix ``x [N, h]``
(and flat probs) sits resident in VMEM and each M-tile gathers its rows
in-kernel via the scalar-prefetched ``pair_src`` map, so the aligned
activation buffer never exists in HBM — tools/roofline.py's top
residual HBM term after the µBS/bf16 levers. The gather variant
auto-falls back to plain ``pallas`` when the residency or SMEM index
maps don't fit (:func:`_gather_fits`).

Under the gather backend the COMBINE side fuses too (default on,
``D9D_TPU_MOE_COMBINE=unfused`` for the A/B): the kernel holds the
token-major combined output ``[N, h]`` resident in VMEM (constant
output index map — flushed to HBM once) and scatter-accumulates each
tile's prob-weighted down-projection rows into their owning tokens, so
the expert-sorted ``y`` and its pair-gathered copy — the combine half
of the roofline's 79 ms/step permute+combine gather traffic — never
touch HBM. One ragged gather → grouped matmul → K-sum, all in-kernel
(:func:`_ffn_gather_combine_kernel`; fit gate :func:`_combine_fits`).

Scope: the LOCAL MoE path only. The EP flow's per-shard ``expert_fn``
receives rows the dispatch all-to-all already delivered in expert-sorted
(but unaligned) order; re-aligning them for this kernel would cost a
``[rows, h]`` scatter + gather pair (~2·M·h·2 B) that cancels what the
fusion saves (~M·(2·inter+inter)·2·2 B — equal at h = 3·inter, the
Qwen3-MoE ratio). The local path wins only because the aligned gather
REPLACES the permute gather it already had to do.
"""

import functools
import os
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from d9d_tpu.core.types import Array
from d9d_tpu.ops.moe import TokenSort, grouped_matmul
from d9d_tpu.ops.swiglu import silu_mul

LANES = 128


class AlignedMeta(NamedTuple):
    """Group-aligned layout descriptors (all int32, traced).

    dest_aligned: [M] aligned row for each (token, k) pair i (the combine
        gather indices; ``dest_aligned[i] = aligned_pos[sort.dest[i]]``).
    pair_src: [m_pad] owning pair of each aligned row (-1 for pad rows) —
        the gather map that fills the aligned activation buffer.
    gid: [T] owning expert of each block_m tile (pad tiles clamp to E-1).
    m_pad: static aligned buffer length (upper bound, block_m multiple).
    """

    dest_aligned: Array
    pair_src: Array
    gid: Array
    m_pad: int


def aligned_metadata(
    sort: TokenSort, num_experts: int, block_m: int
) -> AlignedMeta:
    """Static-shape aligned layout from a TokenSort (all jnp, O(M + E))."""
    m = sort.sort_idx.shape[0]
    # every group pads by < block_m, so this static bound always fits
    m_pad = (-(-m // block_m) + num_experts) * block_m
    gs = sort.group_sizes
    padded = ((gs + block_m - 1) // block_m) * block_m
    ends = jnp.cumsum(gs)
    aligned_starts = jnp.concatenate(
        [jnp.zeros((1,), gs.dtype), jnp.cumsum(padded)[:-1]]
    )
    rows = jnp.arange(m, dtype=jnp.int32)
    expert_of_row = jnp.searchsorted(ends, rows, side="right").astype(
        jnp.int32
    )
    starts = ends - gs
    rank = rows - starts[expert_of_row].astype(jnp.int32)
    aligned_pos = aligned_starts[expert_of_row].astype(jnp.int32) + rank
    dest_aligned = jnp.take(aligned_pos, sort.dest, axis=0)
    pair_src = (
        jnp.full((m_pad,), -1, jnp.int32)
        .at[dest_aligned]
        .set(jnp.arange(m, dtype=jnp.int32), unique_indices=True,
             mode="drop")
    )
    n_tiles = m_pad // block_m
    tile_starts = jnp.arange(n_tiles, dtype=jnp.int32) * block_m
    gid = jnp.minimum(
        jnp.searchsorted(jnp.cumsum(padded), tile_starts, side="right"),
        num_experts - 1,
    ).astype(jnp.int32)
    return AlignedMeta(
        dest_aligned=dest_aligned,
        pair_src=pair_src,
        gid=gid,
        m_pad=m_pad,
    )


def _ffn_kernel(gid_ref, a_ref, probs_ref, wg_ref, wu_ref, wd_ref, out_ref):
    """One aligned tile: out = (silu(A Wg) * (A Wu)) Wd * probs."""
    a = a_ref[...]
    g = jnp.dot(a, wg_ref[0], preferred_element_type=jnp.float32)
    u = jnp.dot(a, wu_ref[0], preferred_element_type=jnp.float32)
    hidden = (jax.nn.silu(g) * u).astype(a.dtype)
    y = jnp.dot(hidden, wd_ref[0], preferred_element_type=jnp.float32)
    out_ref[...] = (y * probs_ref[...]).astype(out_ref.dtype)


def _ffn_gather_kernel(
    gid_ref, ps_ref, x_ref, probs_ref, wg_ref, wu_ref, wd_ref, out_ref,
    a_scr, p_scr, *, block_m: int, top_k: int,
):
    """Gather-fused tile: rows stream VMEM→VMEM inside the kernel.

    The whole token matrix ``x [N, h]`` (and flat probs ``[M, 1]``) sits
    resident in VMEM (eligibility gates on the fit); each grid step
    gathers its tile's rows by the scalar-prefetched ``pair_src`` map —
    so the aligned activation buffer of the two-step path never exists
    in HBM (that buffer cost a full [m_pad, h] write + read per layer
    pass, the top residual HBM term in tools/roofline.py's post-µBS4
    attribution). Pad rows (pair_src < 0) load row 0 and are zeroed.
    """
    t = pl.program_id(0)

    def body(i, _):
        src = ps_ref[t * block_m + i]
        valid = src >= 0
        src0 = jnp.maximum(src, 0)
        row = x_ref[pl.ds(src0 // top_k, 1), :]
        a_scr[pl.ds(i, 1), :] = jnp.where(valid, row, 0)
        pr = probs_ref[pl.ds(src0, 1), :]
        p_scr[pl.ds(i, 1), :] = jnp.where(valid, pr, 0)
        return 0

    jax.lax.fori_loop(0, block_m, body, 0, unroll=8)
    a = a_scr[...]
    g = jnp.dot(a, wg_ref[0], preferred_element_type=jnp.float32)
    u = jnp.dot(a, wu_ref[0], preferred_element_type=jnp.float32)
    hidden = (jax.nn.silu(g) * u).astype(a.dtype)
    y = jnp.dot(hidden, wd_ref[0], preferred_element_type=jnp.float32)
    out_ref[...] = (y * p_scr[...]).astype(out_ref.dtype)


def _ffn_gather_combine_kernel(
    gid_ref, ps_ref, x_ref, probs_ref, wg_ref, wu_ref, wd_ref, out_ref,
    a_scr, p_scr, y_scr, *, block_m: int, top_k: int,
):
    """Gather-fused FFN **with the combine folded in**: the kernel's
    output is the token-major combined [N, h] — one ragged gather →
    grouped matmul → K-sum, no expert-sorted y in HBM at all.

    Same VMEM-resident x/probs and in-kernel row gather as
    :func:`_ffn_gather_kernel`; the difference is on the way out. The
    output block is the whole [N, h] array with a constant index map, so
    it stays resident in VMEM across the (sequential) grid and is
    flushed to HBM once: each tile scatters its rows into
    ``out[pair_src[row] // top_k]`` with an in-VMEM read-modify-write —
    the K expert contributions of each token accumulate here instead of
    in an XLA reshape+sum over a pair-gathered copy. Pad rows
    (pair_src < 0) are skipped. The K-sum therefore runs in
    expert-sorted order rather than the XLA path's slot order — same
    numbers up to fp summation order (parity-tested at ulp tolerance).
    """
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        out_ref[...] = jnp.zeros(out_ref.shape, out_ref.dtype)

    def gather(i, _):
        src = ps_ref[t * block_m + i]
        valid = src >= 0
        src0 = jnp.maximum(src, 0)
        row = x_ref[pl.ds(src0 // top_k, 1), :]
        a_scr[pl.ds(i, 1), :] = jnp.where(valid, row, 0)
        pr = probs_ref[pl.ds(src0, 1), :]
        p_scr[pl.ds(i, 1), :] = jnp.where(valid, pr, 0)
        return 0

    jax.lax.fori_loop(0, block_m, gather, 0, unroll=8)
    a = a_scr[...]
    g = jnp.dot(a, wg_ref[0], preferred_element_type=jnp.float32)
    u = jnp.dot(a, wu_ref[0], preferred_element_type=jnp.float32)
    hidden = (jax.nn.silu(g) * u).astype(a.dtype)
    y = jnp.dot(hidden, wd_ref[0], preferred_element_type=jnp.float32)
    y_scr[...] = (y * p_scr[...]).astype(out_ref.dtype)

    def combine(i, _):
        src = ps_ref[t * block_m + i]
        tok = jnp.maximum(src, 0) // top_k
        row = y_scr[pl.ds(i, 1), :]
        cur = out_ref[pl.ds(tok, 1), :]
        # pad rows write token 0's row back unchanged (+0): branchless
        out_ref[pl.ds(tok, 1), :] = cur + jnp.where(src >= 0, row, 0)
        return 0

    # NOT unrolled: consecutive rows may target the same token, so each
    # read-modify-write must retire before the next row's read
    jax.lax.fori_loop(0, block_m, combine, 0)


def _vmem_bytes_estimate(
    h: int, inter: int, block_m: int, itemsize: int
) -> int:
    """Per-grid-step VMEM bytes the fused kernel needs (ADVICE r4).

    Pallas double-buffers every streamed input block: three expert weight
    blocks (``2*h*inter`` gate+up plus ``inter*h`` down) dominate; the
    ``[block_m, h]`` activation/output tiles and ``[block_m, 1]`` probs
    ride along. The kernel body additionally holds fp32 gate/up products
    and the hidden tile (``3 * block_m * inter`` fp32, single-buffered).
    """
    weights = 3 * h * inter * itemsize * 2  # double-buffered DMA
    tiles = (2 * block_m * h + block_m) * itemsize * 2
    scratch = 3 * block_m * inter * 4
    return weights + tiles + scratch


def _vmem_budget() -> int:
    """Shared VMEM budget for both eligibility gates. Default: v5e/v4
    VMEM is 128 MiB/core; leave headroom for Mosaic's own staging. Read
    at call time like the file's other env knobs."""
    return int(
        os.environ.get("D9D_TPU_MOE_FFN_VMEM_BUDGET", 96 * 1024 * 1024)
    )


# scalar-prefetch budget for the gather variant's SMEM riders (gid +
# pair_src, int32). TPU scalar memory is far smaller than VMEM and its
# exact capacity is generation/toolchain-dependent — this conservative
# cap routes oversized maps to the two-step path instead of risking a
# Mosaic compile failure (same contract as the VMEM gate).
_SMEM_PREFETCH_BUDGET = 256 * 1024


def _gather_footprint(
    n: int, m: int, h: int, inter: int, block_m: int, itemsize: int
) -> int:
    """VMEM bytes of the gather variant: base kernel footprint + the
    resident x [n, h] / probs [m, 1] blocks (counted double-buffered
    like every other pipelined input — their index map is constant, but
    Pallas still allocates pipeline buffers) + the a/p gather scratch.
    Single source of truth for BOTH eligibility gates."""
    resident = (n * h * itemsize + m * 4) * 2  # double-buffered
    scratch = block_m * h * itemsize + block_m * 4
    return (
        _vmem_bytes_estimate(h, inter, block_m, itemsize)
        + resident + scratch
    )


def _gather_fits(
    n: int, m: int, h: int, inter: int, block_m: int, itemsize: int,
    num_experts: int,
) -> bool:
    """Can the gather variant hold x [n, h] + probs [m, 1] resident in
    VMEM on top of the base kernel footprint (plus its gather scratch),
    and its index maps in scalar memory? Also requires n and m
    sublane-aligned (full-array blocks)."""
    if n % 8 != 0 or m % 8 != 0:
        return False
    # SMEM riders: pair_src [m_pad] + gid [m_pad / block_m], int32
    # (m_pad bound per aligned_metadata: every group pads by < block_m)
    m_pad = (-(-m // block_m) + num_experts) * block_m
    if 4 * (m_pad + m_pad // block_m) > _SMEM_PREFETCH_BUDGET:
        return False
    return _gather_footprint(n, m, h, inter, block_m, itemsize) <= _vmem_budget()


def _combine_fits(
    n: int, m: int, h: int, inter: int, block_m: int, itemsize: int,
    num_experts: int,
) -> bool:
    """Gather-variant residency plus the combine's extra VMEM: the
    whole token-major output [n, h] resident across the grid (counted
    double-buffered like the other full-array blocks) and the
    [block_m, h] y scratch the scatter loop reads back from."""
    if not _gather_fits(n, m, h, inter, block_m, itemsize, num_experts):
        return False
    out_resident = n * h * itemsize * 2
    y_scratch = block_m * h * itemsize
    return (
        _gather_footprint(n, m, h, inter, block_m, itemsize)
        + out_resident + y_scratch
    ) <= _vmem_budget()


def _tpu_shapes_ok(
    h: int, inter: int, block_m: int, itemsize: int = 2
) -> bool:
    """Lane alignment AND VMEM fit — large h/inter geometries would fail
    at Mosaic compile instead of falling back (ADVICE r4), so estimate
    the footprint and route oversized shapes to the XLA chain
    (budget: :func:`_vmem_budget`)."""
    if not (h % LANES == 0 and inter % LANES == 0 and block_m % 8 == 0):
        return False
    return _vmem_bytes_estimate(h, inter, block_m, itemsize) <= _vmem_budget()


# d9d-lint: disable=D9D001 — standalone-use decorator; MoE layers trace this inside the tracked step programs
@functools.partial(
    jax.jit, static_argnames=("block_m", "interpret")
)
def _fused_ffn_call(
    aligned_x: Array,
    aligned_probs: Array,
    gid: Array,
    gate_w: Array,
    up_w: Array,
    down_w: Array,
    *,
    block_m: int,
    interpret: bool,
) -> Array:
    m_pad, h = aligned_x.shape
    inter = gate_w.shape[-1]
    n_tiles = m_pad // block_m
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,  # gid rides SMEM, available to index maps
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((block_m, h), lambda t, gid_ref: (t, 0)),
            pl.BlockSpec((block_m, 1), lambda t, gid_ref: (t, 0)),
            pl.BlockSpec((1, h, inter), lambda t, gid_ref: (gid_ref[t], 0, 0)),
            pl.BlockSpec((1, h, inter), lambda t, gid_ref: (gid_ref[t], 0, 0)),
            pl.BlockSpec((1, inter, h), lambda t, gid_ref: (gid_ref[t], 0, 0)),
        ],
        out_specs=pl.BlockSpec((block_m, h), lambda t, gid_ref: (t, 0)),
    )
    return pl.pallas_call(
        _ffn_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((m_pad, h), aligned_x.dtype),
        interpret=interpret,
    )(gid, aligned_x, aligned_probs, gate_w, up_w, down_w)


def _gather_grid_spec(
    x: Array, probs_flat: Array, pair_src: Array, gate_w: Array,
    block_m: int, out_spec: "pl.BlockSpec", extra_scratch: tuple = (),
) -> "pltpu.PrefetchScalarGridSpec":
    """Shared grid/in_specs/scratch of the two gather-variant kernels
    (resident x + probs, per-tile expert weight blocks via the gid SMEM
    rider); only the output spec and any extra scratch differ."""
    n, h = x.shape
    m = probs_flat.shape[0]
    inter = gate_w.shape[-1]
    m_pad = pair_src.shape[0]
    return pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # gid + pair_src ride SMEM
        grid=(m_pad // block_m,),
        in_specs=[
            pl.BlockSpec((n, h), lambda t, gid_ref, ps_ref: (0, 0)),
            pl.BlockSpec((m, 1), lambda t, gid_ref, ps_ref: (0, 0)),
            pl.BlockSpec((1, h, inter),
                         lambda t, gid_ref, ps_ref: (gid_ref[t], 0, 0)),
            pl.BlockSpec((1, h, inter),
                         lambda t, gid_ref, ps_ref: (gid_ref[t], 0, 0)),
            pl.BlockSpec((1, inter, h),
                         lambda t, gid_ref, ps_ref: (gid_ref[t], 0, 0)),
        ],
        out_specs=out_spec,
        scratch_shapes=[
            pltpu.VMEM((block_m, h), x.dtype),
            pltpu.VMEM((block_m, 1), jnp.float32),
            *extra_scratch,
        ],
    )


# d9d-lint: disable=D9D001 — standalone-use decorator; MoE layers trace this inside the tracked step programs
@functools.partial(
    jax.jit, static_argnames=("block_m", "top_k", "interpret")
)
def _fused_gather_call(
    x: Array,
    probs_flat: Array,
    gid: Array,
    pair_src: Array,
    gate_w: Array,
    up_w: Array,
    down_w: Array,
    *,
    block_m: int,
    top_k: int,
    interpret: bool,
) -> Array:
    """``x [N, h]`` resident + in-kernel row gather → aligned ``[m_pad, h]``
    outputs (same aligned layout as :func:`_fused_ffn_call`)."""
    h = x.shape[1]
    m_pad = pair_src.shape[0]
    grid_spec = _gather_grid_spec(
        x, probs_flat, pair_src, gate_w, block_m,
        out_spec=pl.BlockSpec((block_m, h),
                              lambda t, gid_ref, ps_ref: (t, 0)),
    )
    return pl.pallas_call(
        functools.partial(
            _ffn_gather_kernel, block_m=block_m, top_k=top_k
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((m_pad, h), x.dtype),
        interpret=interpret,
    )(gid, pair_src, x, probs_flat, gate_w, up_w, down_w)


# d9d-lint: disable=D9D001 — standalone-use decorator; MoE layers trace this inside the tracked step programs
@functools.partial(
    jax.jit, static_argnames=("block_m", "top_k", "interpret")
)
def _fused_gather_combine_call(
    x: Array,
    probs_flat: Array,
    gid: Array,
    pair_src: Array,
    gate_w: Array,
    up_w: Array,
    down_w: Array,
    *,
    block_m: int,
    top_k: int,
    interpret: bool,
) -> Array:
    """Gather + FFN + in-kernel combine → token-major ``[N, h]``
    directly (no aligned y buffer, no XLA pair gather / K-sum)."""
    n, h = x.shape
    grid_spec = _gather_grid_spec(
        x, probs_flat, pair_src, gate_w, block_m,
        # constant index map: the [N, h] accumulator stays resident in
        # VMEM across the sequential grid and flushes to HBM once
        out_spec=pl.BlockSpec((n, h), lambda t, gid_ref, ps_ref: (0, 0)),
        extra_scratch=(pltpu.VMEM((block_m, h), x.dtype),),
    )
    return pl.pallas_call(
        functools.partial(
            _ffn_gather_combine_kernel, block_m=block_m, top_k=top_k
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n, h), x.dtype),
        interpret=interpret,
    )(gid, pair_src, x, probs_flat, gate_w, up_w, down_w)


def _reference_apply(x, probs, sort, gate_w, up_w, down_w, dtype):
    """The existing XLA path (permute -> grouped matmuls -> combine);
    single source of truth for the custom_vjp backward AND the fallback.
    Uses the shared env-switched gate+up helper so the
    ``D9D_TPU_MOE_FUSED_GATE_UP`` A/B also covers the fallback and the
    custom_vjp backward under this backend (ADVICE r4)."""
    from d9d_tpu.ops.moe import (
        gate_up_grouped_matmul, permute_tokens, unpermute_combine,
    )

    permuted_x, permuted_probs = permute_tokens(x, probs, sort)
    xx = permuted_x.astype(dtype)
    g, u = gate_up_grouped_matmul(
        xx, gate_w.astype(dtype), up_w.astype(dtype), sort.group_sizes
    )
    hidden = silu_mul(g, u)
    y = grouped_matmul(hidden, down_w.astype(dtype), sort.group_sizes)
    y = y * permuted_probs[:, None].astype(dtype)
    return unpermute_combine(y, sort, x.shape[0]).astype(x.dtype)


def _zero_cotangent(x):
    import numpy as np

    if jnp.issubdtype(x.dtype, jnp.floating):
        return jnp.zeros_like(x)
    return np.zeros(x.shape, dtype=jax.dtypes.float0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(9, 10, 11, 12, 13))
def fused_moe_ffn(
    x: Array,
    probs: Array,
    gate_w: Array,
    up_w: Array,
    down_w: Array,
    sort_idx: Array,
    dest: Array,
    token_idx: Array,
    group_sizes: Array,
    num_experts: int,
    block_m: int,
    interpret: bool,
    gather: bool,
    combine: bool,
) -> Array:
    """[N, D] tokens + routing -> combined [N, D] expert outputs.

    The TokenSort is passed as four flat arrays (custom_vjp cannot take a
    NamedTuple across the nondiff boundary); int arrays get float0
    cotangents like pallas_flash's segment ids. ``gather`` selects the
    in-kernel row-gather variant (x resident in VMEM; no HBM aligned
    activation buffer); ``combine`` additionally folds the down-side
    combine into the kernel (token-major [N, D] accumulated in VMEM —
    no expert-sorted y in HBM and no XLA pair gather / K-sum).
    """
    out, _ = _fused_fwd(
        x, probs, gate_w, up_w, down_w, sort_idx, dest, token_idx,
        group_sizes, num_experts, block_m, interpret, gather, combine,
    )
    return out


def _fused_fwd(
    x, probs, gate_w, up_w, down_w, sort_idx, dest, token_idx,
    group_sizes, num_experts, block_m, interpret, gather, combine,
):
    sort = TokenSort(sort_idx, dest, token_idx, group_sizes)
    meta = aligned_metadata(sort, num_experts, block_m)
    n, h = x.shape
    k = dest.shape[0] // n
    dtype = gate_w.dtype  # caller pre-casts weights to the compute dtype
    residuals = (x, probs, gate_w, up_w, down_w, sort_idx, dest,
                 token_idx, group_sizes)
    if gather and combine:
        # one kernel end to end: in-kernel row gather AND in-kernel
        # combine — the only HBM traffic for the whole expert FFN is
        # x/probs in (resident loads) and the combined [N, h] out
        out = _fused_gather_combine_call(
            x.astype(dtype),
            probs.reshape(-1, 1).astype(jnp.float32),
            meta.gid, meta.pair_src,
            gate_w, up_w, down_w,
            block_m=block_m, top_k=k, interpret=interpret,
        )
        return out.astype(x.dtype), residuals
    if gather:
        # the kernel gathers rows itself from a VMEM-resident x — no
        # [m_pad, h] aligned buffer in HBM at all (the buffer costs a
        # full write + read per layer pass on the two-step path)
        y_aligned = _fused_gather_call(
            x.astype(dtype),
            probs.reshape(-1, 1).astype(jnp.float32),
            meta.gid, meta.pair_src,
            gate_w, up_w, down_w,
            block_m=block_m, top_k=k, interpret=interpret,
        )
    else:
        # ONE gather fills the aligned activation buffer (pair i owns
        # token i // k); pad rows read token 0 and are zeroed by the
        # mask. Traffic = today's sorted-layout gather PLUS the pad rows
        # (m_pad - m zero rows written and re-read) — the static worst
        # case pads every group by block_m, so keep E*block_m small
        # against M (the block_m eligibility/sweep choices encode this).
        valid = (meta.pair_src >= 0)[:, None]
        token_src = jnp.maximum(meta.pair_src, 0) // k
        aligned_x = jnp.where(
            valid, jnp.take(x, token_src, axis=0), 0
        ).astype(dtype)
        aligned_probs = jnp.where(
            valid,
            jnp.take(
                probs.reshape(-1), jnp.maximum(meta.pair_src, 0)
            )[:, None],
            0,
        ).astype(jnp.float32)
        y_aligned = _fused_ffn_call(
            aligned_x, aligned_probs, meta.gid,
            gate_w, up_w, down_w,
            block_m=block_m, interpret=interpret,
        )
    # combine: collision-free gather by pair then K-sum (ops/moe.py
    # combine_pairs formulation, over the aligned layout)
    pair_y = jnp.take(y_aligned, meta.dest_aligned, axis=0)
    out = pair_y.reshape(n, k, h).sum(axis=1).astype(x.dtype)
    return out, residuals


def _fused_bwd(num_experts, block_m, interpret, gather, combine, residuals,
               d_out):
    (x, probs, gate_w, up_w, down_w, sort_idx, dest, token_idx,
     group_sizes) = residuals
    sort = TokenSort(sort_idx, dest, token_idx, group_sizes)
    dtype = gate_w.dtype

    def ref(x_, probs_, g_, u_, d_):
        return _reference_apply(x_, probs_, sort, g_, u_, d_, dtype)

    _, vjp = jax.vjp(ref, x, probs, gate_w, up_w, down_w)
    dx, dprobs, dg, du, dd = vjp(d_out)
    return (
        dx, dprobs, dg, du, dd,
        _zero_cotangent(sort_idx), _zero_cotangent(dest),
        _zero_cotangent(token_idx), _zero_cotangent(group_sizes),
    )


fused_moe_ffn.defvjp(_fused_fwd, _fused_bwd)


def moe_ffn_backend() -> str:
    """'pallas', 'pallas_gather' or 'xla' — env-selected like the SDPA
    backend family. ``pallas_gather`` additionally fuses the permute
    gather into the kernel (x resident in VMEM; falls back to plain
    ``pallas`` when the residency doesn't fit)."""
    return os.environ.get("D9D_TPU_MOE_FFN", "xla")


def fused_moe_ffn_apply(
    x: Array,
    probs: Array,
    sort: TokenSort,
    gate_w: Array,
    up_w: Array,
    down_w: Array,
    dtype,
    *,
    num_experts: int,
    block_m: int | None = None,
    interpret: bool | None = None,
    gather: bool | None = None,
    combine: bool | None = None,
) -> Array:
    """Entry point for nn/moe.py: fused kernel when eligible, else the
    reference XLA chain (identical math either way). ``gather`` forces
    the in-kernel row-gather variant on/off (None = env-selected via
    ``D9D_TPU_MOE_FFN=pallas_gather``); ``combine`` forces the
    in-kernel combine on/off (None = ``D9D_TPU_MOE_COMBINE``, default
    fused, gather variant only). Either way the VMEM-fit gates can
    veto per shape."""
    from d9d_tpu.ops.moe import fused_combine_enabled

    h = x.shape[-1]
    inter = gate_w.shape[-1]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if block_m is None:
        block_m = int(os.environ.get("D9D_TPU_MOE_FFN_BLOCK_M", "128"))
    itemsize = jnp.dtype(dtype).itemsize
    if not interpret and not _tpu_shapes_ok(h, inter, block_m, itemsize):
        return _reference_apply(x, probs, sort, gate_w, up_w, down_w, dtype)
    if gather is None:
        gather = moe_ffn_backend() == "pallas_gather"
    gather = gather and _gather_fits(
        x.shape[0], probs.size, h, inter, block_m, itemsize,
        num_experts=num_experts,
    )
    if combine is None:
        combine = fused_combine_enabled()
    combine = gather and combine and _combine_fits(
        x.shape[0], probs.size, h, inter, block_m, itemsize,
        num_experts=num_experts,
    )
    from jax.ad_checkpoint import checkpoint_name

    out = fused_moe_ffn(
        x, probs,
        gate_w.astype(dtype), up_w.astype(dtype), down_w.astype(dtype),
        sort.sort_idx, sort.dest, sort.token_idx, sort.group_sizes,
        num_experts, block_m, interpret, gather, combine,
    )
    # same checkpoint name the XLA chain's grouped dots carry, so the
    # save_expensive remat policy keeps its meaning under this backend
    # (saves the [N, h] layer output — smaller than the XLA chain's
    # [M, 2*inter] — and skips the fused-forward recompute in backward)
    return checkpoint_name(out, "moe_grouped_dot")
