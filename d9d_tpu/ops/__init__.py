from d9d_tpu.ops.attention.eager import eager_sdpa
from d9d_tpu.ops.linear_ce import LM_IGNORE_INDEX, linear_cross_entropy
from d9d_tpu.ops.rms_norm import rms_norm
from d9d_tpu.ops.rope import (
    RopeScaling,
    RopeScalingLinear,
    RopeScalingLlama3,
    RopeScalingNone,
    RopeScalingNtk,
    RopeScalingYarn,
    RopeStyle,
    apply_rope,
    compute_rope_frequencies,
    make_rope_cos_sin,
)
from d9d_tpu.ops.swiglu import silu_mul, swiglu

__all__ = [
    "eager_sdpa",
    "LM_IGNORE_INDEX",
    "linear_cross_entropy",
    "rms_norm",
    "RopeScaling",
    "RopeScalingLinear",
    "RopeScalingLlama3",
    "RopeScalingNone",
    "RopeScalingNtk",
    "RopeScalingYarn",
    "RopeStyle",
    "apply_rope",
    "compute_rope_frequencies",
    "make_rope_cos_sin",
    "silu_mul",
    "swiglu",
]
