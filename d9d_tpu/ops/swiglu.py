"""Fused SwiGLU gate op: silu(gate) * up.

TPU equivalent of the reference Triton silu_mul kernel
(d9d/kernel/swiglu/function.py:23, op.py:26,97). XLA fuses this elementwise
chain into the surrounding matmuls on TPU, so the default implementation is
plain jnp; the op exists as a seam so a Pallas fusion (e.g. into the down
projection) can be swapped in without touching block code.
"""

import jax
import jax.numpy as jnp

from d9d_tpu.core.types import Array


def silu_mul(gate: Array, up: Array) -> Array:
    return jax.nn.silu(gate) * up


def swiglu(x: Array, w_gate: Array, w_up: Array, w_down: Array) -> Array:
    """Full SwiGLU FFN: down( silu(x @ gate) * (x @ up) )."""
    g = x @ w_gate
    u = x @ w_up
    return silu_mul(g, u) @ w_down
