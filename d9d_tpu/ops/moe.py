"""MoE token routing/permutation ops + grouped matmul.

Replaces the reference kernel layer for MoE (SURVEY §2.2):
- nv-grouped-gemm wheel (d9d/kernel/gmm/function.py:10,51) →
  ``jax.lax.ragged_dot`` — XLA's native grouped GEMM, MXU-tiled on TPU,
  differentiable (dI and dW both flow; the reference's GradDirection split
  is owned by the pipelining layer's two-phase VJP instead).
- Triton permute/unpermute kernels (d9d/kernel/moe/permute_with_probs.py:711,
  indices_to_multihot.py:263) → a stable argsort over expert ids + gather;
  XLA fuses the gather into the surrounding computation, and every shape is
  static (N·K rows) as TPU compilation demands.

All functions operate on a flat token dim; callers reshape [B,T,D]→[N,D].
"""

import os
from typing import NamedTuple

import jax.numpy as jnp
from jax import lax

from d9d_tpu.core.types import Array


class TokenSort(NamedTuple):
    """Result of sorting (token, choice) pairs by expert.

    sort_idx: [N*K] position in the flattened (token-major) pair array for
        each sorted row; row r of the permuted layout is pair sort_idx[r].
    dest: [N*K] inverse permutation — where pair i lands in the sorted
        layout (``dest[sort_idx[r]] == r``).
    token_idx: [N*K] owning token of each sorted row (= sort_idx // K).
    group_sizes: [E] rows per expert, in sorted order.
    """

    sort_idx: Array
    dest: Array
    token_idx: Array
    group_sizes: Array


# one-hot grouping wins below this M·E (int32 [M, E] ≈ 64 MB here); above,
# its HBM traffic inverts the r3 sweep's verdict and argsort takes over
_ONE_HOT_GROUPING_LIMIT = 16 * 1024 * 1024


def stable_expert_order(
    flat_ids: Array, num_experts: int
) -> tuple[Array, Array, Array]:
    """Stable grouping permutation over expert ids WITHOUT a sort.

    Returns ``(sort_idx [M], dest [M], group_sizes [E])`` where
    ``flat_ids[sort_idx]`` is grouped by expert with original order
    preserved within each group — exactly ``argsort(flat_ids, stable=True)``
    — and ``dest`` is the inverse permutation (where row i lands). Computed
    as one-hot → cumsum → scatter. TPU sorts lower to bitonic networks
    (log² passes); a log-depth cumsum over the [M, E] one-hot plus one
    scatter is much cheaper at MoE shapes, and the MoE layer runs this per
    layer per microbatch.

    The one-hot costs O(M·E) HBM traffic (recomputed again under remat):
    a win at swept shapes (M≤128k, E≤64: ≤33 MB) but inverting for very
    large M·E (ADVICE r3: E=256, M=131k → 134 MB ×2 per MoE layer per
    microbatch pressures HBM), so past a threshold this falls back to the
    stable argsort instead.
    """
    m = flat_ids.shape[0]
    if m * num_experts > _ONE_HOT_GROUPING_LIMIT:
        sort_idx = jnp.argsort(flat_ids, stable=True).astype(jnp.int32)
        dest = (
            jnp.zeros((m,), jnp.int32)
            .at[sort_idx]
            .set(jnp.arange(m, dtype=jnp.int32), unique_indices=True)
        )
        group_sizes = jnp.bincount(flat_ids, length=num_experts)
        return sort_idx, dest, group_sizes.astype(jnp.int32)
    one_hot = (
        flat_ids[:, None] == jnp.arange(num_experts, dtype=flat_ids.dtype)
    ).astype(jnp.int32)
    prefix = jnp.cumsum(one_hot, axis=0)  # inclusive per-expert counts
    group_sizes = prefix[-1]
    # rank of pair i among same-expert pairs, in original order
    rank = jnp.take_along_axis(prefix, flat_ids[:, None], axis=1)[:, 0] - 1
    offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(group_sizes)[:-1]]
    )
    dest = offsets[flat_ids] + rank  # where pair i lands in sorted layout
    sort_idx = jnp.zeros((m,), jnp.int32).at[dest].set(
        jnp.arange(m, dtype=jnp.int32), mode="drop", unique_indices=True
    )
    return sort_idx, dest, group_sizes.astype(jnp.int32)


def sort_tokens_by_expert(topk_ids: Array, num_experts: int) -> TokenSort:
    """Stable-sort (token, k) pairs by their routed expert id.

    topk_ids: [N, K] int32 expert assignments.
    """
    n, k = topk_ids.shape
    flat_ids = topk_ids.reshape(n * k)
    sort_idx, dest, group_sizes = stable_expert_order(flat_ids, num_experts)
    return TokenSort(
        sort_idx=sort_idx,
        dest=dest,
        token_idx=sort_idx // k,
        group_sizes=group_sizes,
    )


def permute_tokens(
    x: Array, probs: Array, sort: TokenSort
) -> tuple[Array, Array]:
    """Gather tokens (and their routing probs) into expert-sorted layout.

    x: [N, D]; probs: [N, K] → ([N*K, D], [N*K]).
    """
    from jax.ad_checkpoint import checkpoint_name

    # named for the "save_expensive" remat policy: the grouped-matmul
    # backward needs these rows (dW), and recomputing them means redoing
    # the gather under remat
    permuted_x = checkpoint_name(
        jnp.take(x, sort.token_idx, axis=0), "moe_permuted_rows"
    )
    permuted_probs = jnp.take(probs.reshape(-1), sort.sort_idx, axis=0)
    return permuted_x, permuted_probs


def fused_combine_enabled() -> bool:
    """``D9D_TPU_MOE_COMBINE`` A/B switch (default ON) for the
    gather-fused combine: under the ``pallas_gather`` FFN backend the
    down-projection's combine (ragged gather → grouped matmul → K-sum)
    runs INSIDE the fused kernel, accumulating token-major [N, D]
    outputs in VMEM — the expert-sorted y rows and the pair-gathered
    copy never exist in HBM (tools/roofline.py's 79 ms/step
    permute+combine residual is half combine-side). Read at call time
    like the file's other env knobs; ops/moe_pallas.py consults it and
    its VMEM-fit gate can still veto per shape."""
    return os.environ.get("D9D_TPU_MOE_COMBINE", "fused") != "unfused"


def combine_pairs(y: Array, dest: Array, num_tokens: int) -> Array:
    """Fold expert-sorted pair rows back to their owning tokens.

    y: [N*K, D] expert-sorted rows (already prob-weighted); ``dest`` the
    inverse permutation from :func:`stable_expert_order` → [N, D].
    Formulated as a duplicate-free gather by ``dest`` followed by a K-row
    sum instead of ``zeros.at[token_idx].add(y)``: the scatter-add
    collides K ways on every token (each token owns K expert rows) while
    ``dest`` is a permutation, so both this gather and its VJP (a scatter
    at unique indices) are collision-free on TPU. Shared by the local MoE
    path and the EP shard_map combine.
    """
    k = dest.shape[0] // num_tokens
    pair_y = jnp.take(y, dest, axis=0)  # token-major pair rows
    return pair_y.reshape(num_tokens, k, y.shape[-1]).sum(axis=1)


def unpermute_combine(y: Array, sort: TokenSort, num_tokens: int) -> Array:
    """Combine expert outputs back to their owning tokens (local path).

    y: [N*K, D] (already prob-weighted) → [N, D]. The reverse of
    ``permute_tokens``; see :func:`combine_pairs` for the formulation.
    """
    return combine_pairs(y, sort.dest, num_tokens)


def gate_up_grouped_matmul(
    x: Array, gate_w: Array, up_w: Array, group_sizes: Array
) -> tuple[Array, Array]:
    """Gate and up projections as grouped matmuls → ``(g, u)``.

    Single owner of the ``D9D_TPU_MOE_FUSED_GATE_UP`` A/B (default on:
    ONE grouped matmul over a runtime ``[E, in, 2*inter]`` concat so the
    expert-sorted rows stream from HBM once; off: two grouped matmuls,
    no weight-concat materialization — see nn/moe.py grouped_swiglu_apply
    for the trade-off). Shared by the XLA MoE chain AND the Pallas
    backend's fallback/backward reference (ADVICE r4: the env switch must
    cover every path or the perf A/B is inconsistent). Weights must
    already be in the compute dtype.
    """
    if os.environ.get("D9D_TPU_MOE_FUSED_GATE_UP", "1") == "1":
        inter = gate_w.shape[-1]
        gate_up_w = jnp.concatenate([gate_w, up_w], axis=-1)
        h_gu = grouped_matmul(x, gate_up_w, group_sizes)  # [M, 2*inter]
        return h_gu[..., :inter], h_gu[..., inter:]
    return (
        grouped_matmul(x, gate_w, group_sizes),
        grouped_matmul(x, up_w, group_sizes),
    )


def grouped_matmul(x: Array, weight: Array, group_sizes: Array) -> Array:
    """Per-expert matmul on expert-sorted rows.

    x: [rows, in], weight: [E, in, out], group_sizes: [E] with
    sum(group_sizes) <= rows (trailing rows produce unspecified values —
    callers mask or pad with a zero expert). The output carries a
    checkpoint name: ``ragged_dot`` is a custom call the stock
    ``checkpoint_dots*`` policies don't match, so the "save_expensive"
    remat policy saves it by name instead of recomputing the experts'
    FLOPs in the backward pass.
    """
    from jax.ad_checkpoint import checkpoint_name

    return checkpoint_name(
        lax.ragged_dot(
            x,
            weight,
            group_sizes.astype(jnp.int32),
            preferred_element_type=x.dtype,
        ),
        "moe_grouped_dot",
    )
