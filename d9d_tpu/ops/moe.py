"""MoE token routing/permutation ops + grouped matmul.

Replaces the reference kernel layer for MoE (SURVEY §2.2):
- nv-grouped-gemm wheel (d9d/kernel/gmm/function.py:10,51) →
  ``jax.lax.ragged_dot`` — XLA's native grouped GEMM, MXU-tiled on TPU,
  differentiable (dI and dW both flow; the reference's GradDirection split
  is owned by the pipelining layer's two-phase VJP instead).
- Triton permute/unpermute kernels (d9d/kernel/moe/permute_with_probs.py:711,
  indices_to_multihot.py:263) → a stable argsort over expert ids + gather;
  XLA fuses the gather into the surrounding computation, and every shape is
  static (N·K rows) as TPU compilation demands.

All functions operate on a flat token dim; callers reshape [B,T,D]→[N,D].
"""

from typing import NamedTuple

import jax.numpy as jnp
from jax import lax

from d9d_tpu.core.types import Array


class TokenSort(NamedTuple):
    """Result of sorting (token, choice) pairs by expert.

    sort_idx: [N*K] position in the flattened (token-major) pair array for
        each sorted row; row r of the permuted layout is pair sort_idx[r].
    token_idx: [N*K] owning token of each sorted row (= sort_idx // K).
    group_sizes: [E] rows per expert, in sorted order.
    """

    sort_idx: Array
    token_idx: Array
    group_sizes: Array


def sort_tokens_by_expert(topk_ids: Array, num_experts: int) -> TokenSort:
    """Stable-sort (token, k) pairs by their routed expert id.

    topk_ids: [N, K] int32 expert assignments.
    """
    n, k = topk_ids.shape
    flat_ids = topk_ids.reshape(n * k)
    sort_idx = jnp.argsort(flat_ids, stable=True)
    group_sizes = jnp.bincount(flat_ids, length=num_experts)
    return TokenSort(
        sort_idx=sort_idx,
        token_idx=sort_idx // k,
        group_sizes=group_sizes.astype(jnp.int32),
    )


def permute_tokens(
    x: Array, probs: Array, sort: TokenSort
) -> tuple[Array, Array]:
    """Gather tokens (and their routing probs) into expert-sorted layout.

    x: [N, D]; probs: [N, K] → ([N*K, D], [N*K]).
    """
    permuted_x = jnp.take(x, sort.token_idx, axis=0)
    permuted_probs = jnp.take(probs.reshape(-1), sort.sort_idx, axis=0)
    return permuted_x, permuted_probs


def unpermute_combine(y: Array, sort: TokenSort, num_tokens: int) -> Array:
    """Scatter-add expert outputs back to their owning tokens.

    y: [N*K, D] (already prob-weighted) → [N, D]. The reverse of
    ``permute_tokens``; gradients flow as the corresponding gather.
    """
    out = jnp.zeros((num_tokens, y.shape[-1]), dtype=y.dtype)
    return out.at[sort.token_idx].add(y)


def grouped_matmul(x: Array, weight: Array, group_sizes: Array) -> Array:
    """Per-expert matmul on expert-sorted rows.

    x: [rows, in], weight: [E, in, out], group_sizes: [E] with
    sum(group_sizes) <= rows (trailing rows produce unspecified values —
    callers mask or pad with a zero expert).
    """
    return lax.ragged_dot(
        x, weight, group_sizes.astype(jnp.int32), preferred_element_type=x.dtype
    )
