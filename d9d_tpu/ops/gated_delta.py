"""Gated delta rule linear attention (recurrent + chunked forms).

TPU equivalent of the fla-core Triton kernels the reference wraps
(``chunk_gated_delta_rule`` imported at d9d/module/block/attention/linear/
gated_deltanet.py:6-8). The recurrence per head, with state
``S ∈ R^{d_k×d_v}``, log-decay ``g_t ≤ 0`` (α=exp g), write strength
``β_t ∈ (0,1)``:

    S_t = α_t·S_{t-1} + β_t·k_t·(v_t − α_t·S_{t-1}ᵀk_t)ᵀ
    o_t = S_tᵀ q_t

- :func:`gated_delta_rule_recurrent` — exact lax.scan over time; the
  correctness oracle, O(T) sequential steps.
- :func:`gated_delta_rule_chunked` — chunkwise WY form (Gated DeltaNet,
  arXiv 2412.06464): within a chunk the implicit per-token recursion is a
  C×C unit-lower-triangular solve; across chunks only the state carries.
  All inner products ride the MXU as [C,C] / [C,d] matmuls, and every
  exponential is of a non-positive number (cumulative decay differences),
  so the math is stable without rescaling tricks.

Shapes: ``q/k [B,T,H,Dk]``, ``v [B,T,H,Dv]``, ``g/beta [B,T,H]``.
Computation runs in fp32 regardless of input dtype (matching fla).
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax

from d9d_tpu.core.types import Array


def l2norm(x: Array, eps: float = 1e-6) -> Array:
    return x * lax.rsqrt(jnp.sum(x * x, axis=-1, keepdims=True) + eps)


def _prep(q, k, v, g, beta, use_qk_l2norm):
    q = q.astype(jnp.float32)
    k = k.astype(jnp.float32)
    v = v.astype(jnp.float32)
    g = g.astype(jnp.float32)
    beta = beta.astype(jnp.float32)
    if use_qk_l2norm:
        q = l2norm(q)
        k = l2norm(k)
    q = q * (q.shape[-1] ** -0.5)
    return q, k, v, g, beta


def gated_delta_rule_recurrent(
    q: Array,
    k: Array,
    v: Array,
    g: Array,
    beta: Array,
    *,
    use_qk_l2norm: bool = True,
    initial_state: Array | None = None,
) -> tuple[Array, Array]:
    """Sequential oracle. Returns (o [B,T,H,Dv], final_state [B,H,Dk,Dv])."""
    q, k, v, g, beta = _prep(q, k, v, g, beta, use_qk_l2norm)
    b, t, h, dk = q.shape
    dv = v.shape[-1]

    s0 = (
        initial_state.astype(jnp.float32)
        if initial_state is not None
        else jnp.zeros((b, h, dk, dv), jnp.float32)
    )

    def step(s, inputs):
        q_t, k_t, v_t, g_t, b_t = inputs  # [B,H,D*] / [B,H]
        alpha = jnp.exp(g_t)[..., None, None]  # [B,H,1,1]
        s = s * alpha
        pred = jnp.einsum("bhkv,bhk->bhv", s, k_t)
        err = (v_t - pred) * b_t[..., None]
        s = s + jnp.einsum("bhk,bhv->bhkv", k_t, err)
        o_t = jnp.einsum("bhkv,bhk->bhv", s, q_t)
        return s, o_t

    xs = (
        q.transpose(1, 0, 2, 3),
        k.transpose(1, 0, 2, 3),
        v.transpose(1, 0, 2, 3),
        g.transpose(1, 0, 2),
        beta.transpose(1, 0, 2),
    )
    s_final, o = lax.scan(step, s0, xs)
    return o.transpose(1, 0, 2, 3), s_final


# d9d-lint: disable=D9D001 — standalone-use decorator; the train/serve paths trace this inside their tracked step programs
@functools.partial(jax.jit, static_argnames=("use_qk_l2norm", "chunk_size"))
def gated_delta_rule_chunked(
    q: Array,
    k: Array,
    v: Array,
    g: Array,
    beta: Array,
    *,
    use_qk_l2norm: bool = True,
    chunk_size: int = 64,
    initial_state: Array | None = None,
) -> tuple[Array, Array]:
    """Chunkwise WY form; numerically matches the recurrent oracle.

    Derivation: with c_i = Σ_{j≤i} g_j (within-chunk cumulative log decay)
    and S₀ the incoming state,

        u_i = v_i − e^{c_i}·S₀ᵀk_i − Σ_{j<i} e^{c_i−c_j}(k_iᵀk_j)β_j u_j
        o_i = e^{c_i}·S₀ᵀq_i + Σ_{j≤i} e^{c_i−c_j}(q_iᵀk_j)β_j u_j
        S_C = e^{c_C}·S₀ + Σ_i e^{c_C−c_i}·β_i·k_i u_iᵀ

    The u-recursion is ``(I + M)u = v − r`` with strictly-lower-triangular
    M — one triangular solve per chunk.
    """
    q, k, v, g, beta = _prep(q, k, v, g, beta, use_qk_l2norm)
    b, t, h, dk = q.shape
    dv = v.shape[-1]
    c = chunk_size

    pad = (-t) % c
    if pad:
        q = jnp.pad(q, ((0, 0), (0, pad), (0, 0), (0, 0)))
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
        g = jnp.pad(g, ((0, 0), (0, pad), (0, 0)))
        beta = jnp.pad(beta, ((0, 0), (0, pad), (0, 0)))
    n_chunks = (t + pad) // c

    # [B,H,N,C,D*] chunked, head-major layouts
    def chunked(x):
        return x.reshape(b, n_chunks, c, h, -1).transpose(0, 3, 1, 2, 4)

    qc, kc, vc = chunked(q), chunked(k), chunked(v)
    gc = g.reshape(b, n_chunks, c, h).transpose(0, 3, 1, 2)
    bc = beta.reshape(b, n_chunks, c, h).transpose(0, 3, 1, 2)

    cum = jnp.cumsum(gc, axis=-1)  # c_i per chunk [B,H,N,C]
    # pairwise decay e^{c_i - c_j}, lower-triangular valid region
    diff = cum[..., :, None] - cum[..., None, :]  # [B,H,N,C,C]
    idx = jnp.arange(c)
    lower = idx[:, None] > idx[None, :]  # strict
    lower_eq = idx[:, None] >= idx[None, :]

    decay_strict = jnp.where(lower, jnp.exp(jnp.where(lower, diff, 0.0)), 0.0)
    decay_incl = jnp.where(lower_eq, jnp.exp(jnp.where(lower_eq, diff, 0.0)), 0.0)

    kk = jnp.einsum("bhnik,bhnjk->bhnij", kc, kc)  # k_iᵀk_j
    qk = jnp.einsum("bhnik,bhnjk->bhnij", qc, kc)  # q_iᵀk_j
    m_mat = decay_strict * kk * bc[..., None, :]  # M_{ij} strict lower
    attn = decay_incl * qk * bc[..., None, :]  # A_{ij} incl diagonal

    eye = jnp.eye(c, dtype=jnp.float32)
    im = eye + m_mat  # unit lower-triangular

    s0 = (
        initial_state.astype(jnp.float32)
        if initial_state is not None
        else jnp.zeros((b, h, dk, dv), jnp.float32)
    )

    def chunk_step(s, inputs):
        q_n, k_n, v_n, cum_n, beta_n, im_n, attn_n = inputs
        # r_i = e^{c_i} S₀ᵀ k_i
        r = jnp.exp(cum_n)[..., None] * jnp.einsum("bhkv,bhik->bhiv", s, k_n)
        rhs = v_n - r
        u = jax.scipy.linalg.solve_triangular(
            im_n, rhs, lower=True, unit_diagonal=True
        )
        o = (
            jnp.exp(cum_n)[..., None] * jnp.einsum("bhkv,bhik->bhiv", s, q_n)
            + jnp.einsum("bhij,bhjv->bhiv", attn_n, u)
        )
        # state to next chunk
        last = cum_n[..., -1]  # c_C
        w = jnp.exp(last[..., None] - cum_n) * beta_n  # e^{c_C - c_i} β_i
        s = jnp.exp(last)[..., None, None] * s + jnp.einsum(
            "bhik,bhiv->bhkv", k_n * w[..., None], u
        )
        return s, o

    xs = tuple(
        x.transpose(2, 0, 1, *range(3, x.ndim))
        for x in (qc, kc, vc, cum, bc, im, attn)
    )
    s_final, o = lax.scan(chunk_step, s0, xs)
    # o: [N,B,H,C,Dv] → [B,T,H,Dv]
    o = o.transpose(1, 0, 3, 2, 4).reshape(b, t + pad, h, dv)
    return o[:, :t], s_final
