"""Rotary position embeddings: frequency computation, scaling laws, application.

TPU-native equivalent of the reference RoPE stack
(d9d/module/block/positional/rope.py:22,76,187 and rope_scaling.py:36-120):
two layout styles (HALF = GPT-NeoX rotate-half, INTERLEAVED = GPT-J pairs),
five scaling laws (none / linear / NTK-aware / YaRN / llama3). Everything here is a
pure function of static config + a positions array, so it jits and shards
trivially (positions can be sharded over the cp axes).
"""

import dataclasses
import enum
import math

import jax.numpy as jnp

from d9d_tpu.core.types import Array


class RopeStyle(enum.Enum):
    HALF = "half"
    INTERLEAVED = "interleaved"


@dataclasses.dataclass(frozen=True)
class RopeScalingNone:
    pass


@dataclasses.dataclass(frozen=True)
class RopeScalingLinear:
    factor: float


@dataclasses.dataclass(frozen=True)
class RopeScalingNtk:
    """NTK-aware scaling: rescales theta so the longest wavelength covers the
    extended context (reference rope_scaling.py:58)."""

    factor: float


@dataclasses.dataclass(frozen=True)
class RopeScalingYarn:
    """YaRN (arXiv 2309.00071): interpolate low-frequency bands, extrapolate
    high-frequency bands, with sqrt-log attention temperature
    (reference rope_scaling.py:120)."""

    factor: float
    original_max_position: int
    beta_fast: float = 32.0
    beta_slow: float = 1.0
    attention_factor: float | None = None


@dataclasses.dataclass(frozen=True)
class RopeScalingLlama3:
    """Llama-3.1 piecewise scaling (HF ``rope_type="llama3"``): wavelengths
    longer than the original context are interpolated by ``factor``,
    shorter than ``original_max_position / high_freq_factor`` are kept,
    and the band between is linearly blended. Beyond-reference scaling law
    (the reference ships none/linear/ntk/yarn only) — needed by the
    Llama-3.1 family presets (models/llama)."""

    factor: float
    original_max_position: int
    low_freq_factor: float = 1.0
    high_freq_factor: float = 4.0

    def __post_init__(self):
        if self.low_freq_factor >= self.high_freq_factor:
            raise ValueError(
                f"llama3 rope scaling needs low_freq_factor "
                f"({self.low_freq_factor}) < high_freq_factor "
                f"({self.high_freq_factor}) — the blend-band denominator "
                f"is their difference"
            )


RopeScaling = (
    RopeScalingNone | RopeScalingLinear | RopeScalingNtk | RopeScalingYarn
    | RopeScalingLlama3
)


def _yarn_correction_dim(num_rotations: float, dim: int, theta: float, max_pos: int) -> float:
    return (dim * math.log(max_pos / (num_rotations * 2 * math.pi))) / (
        2 * math.log(theta)
    )


def compute_rope_frequencies(
    head_dim: int,
    theta: float,
    scaling: RopeScaling = RopeScalingNone(),
) -> tuple[Array, float]:
    """Return (inv_freq [head_dim//2] float32, attention_scale).

    ``attention_scale`` multiplies cos/sin (YaRN temperature); 1.0 otherwise.
    """
    dim = head_dim
    exponents = jnp.arange(0, dim, 2, dtype=jnp.float32) / dim
    inv_freq = 1.0 / (theta**exponents)
    scale = 1.0

    if isinstance(scaling, RopeScalingNone):
        pass
    elif isinstance(scaling, RopeScalingLinear):
        inv_freq = inv_freq / scaling.factor
    elif isinstance(scaling, RopeScalingNtk):
        adjusted_theta = theta * scaling.factor ** (dim / (dim - 2))
        inv_freq = 1.0 / (adjusted_theta**exponents)
    elif isinstance(scaling, RopeScalingYarn):
        low = _yarn_correction_dim(
            scaling.beta_fast, dim, theta, scaling.original_max_position
        )
        high = _yarn_correction_dim(
            scaling.beta_slow, dim, theta, scaling.original_max_position
        )
        low = max(math.floor(low), 0)
        high = min(math.ceil(high), dim // 2 - 1)
        # ramp: 0 where extrapolation (high freq), 1 where interpolation
        ramp = jnp.clip(
            (jnp.arange(dim // 2, dtype=jnp.float32) - low) / max(high - low, 1e-3),
            0.0,
            1.0,
        )
        interp = inv_freq / scaling.factor
        inv_freq = inv_freq * (1 - ramp) + interp * ramp
        if scaling.attention_factor is not None:
            scale = scaling.attention_factor
        else:
            scale = 0.1 * math.log(scaling.factor) + 1.0
    elif isinstance(scaling, RopeScalingLlama3):
        # HF modeling_rope_utils._compute_llama3_parameters semantics
        wavelen = 2 * math.pi / inv_freq
        low_wl = scaling.original_max_position / scaling.low_freq_factor
        high_wl = scaling.original_max_position / scaling.high_freq_factor
        smooth = (
            scaling.original_max_position / wavelen - scaling.low_freq_factor
        ) / (scaling.high_freq_factor - scaling.low_freq_factor)
        blended = (1 - smooth) * inv_freq / scaling.factor + smooth * inv_freq
        inv_freq = jnp.where(
            wavelen > low_wl,
            inv_freq / scaling.factor,
            jnp.where(wavelen < high_wl, inv_freq, blended),
        )
    else:
        raise TypeError(f"unknown rope scaling: {scaling!r}")
    return inv_freq, scale


def make_rope_cos_sin(
    positions: Array,
    inv_freq: Array,
    attention_scale: float = 1.0,
    dtype: jnp.dtype = jnp.float32,
) -> tuple[Array, Array]:
    """cos/sin of shape ``positions.shape + (head_dim//2,)``."""
    angles = positions.astype(jnp.float32)[..., None] * inv_freq
    cos = jnp.cos(angles) * attention_scale
    sin = jnp.sin(angles) * attention_scale
    return cos.astype(dtype), sin.astype(dtype)


def apply_rope(
    x: Array,
    cos: Array,
    sin: Array,
    style: RopeStyle = RopeStyle.HALF,
) -> Array:
    """Rotate ``x [..., T, H, D]`` by cos/sin ``[..., T, D//2]``.

    HALF pairs element i with i + D/2 (GPT-NeoX / HF Llama layout);
    INTERLEAVED pairs 2i with 2i+1 (GPT-J layout). Reference:
    module/block/positional/rope.py:187.
    """
    d_half = x.shape[-1] // 2
    cos = cos[..., None, :]  # broadcast over heads
    sin = sin[..., None, :]
    xf = x.astype(jnp.float32)
    if style == RopeStyle.HALF:
        x1 = xf[..., :d_half]
        x2 = xf[..., d_half:]
        out = jnp.concatenate(
            [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
        )
    elif style == RopeStyle.INTERLEAVED:
        x1 = xf[..., 0::2]
        x2 = xf[..., 1::2]
        r1 = x1 * cos - x2 * sin
        r2 = x2 * cos + x1 * sin
        out = jnp.stack([r1, r2], axis=-1).reshape(xf.shape)
    else:
        raise ValueError(f"unknown rope style: {style}")
    return out.astype(x.dtype)
