"""Ring attention: context-parallel SDPA over a mesh axis.

Beyond-reference capability (SURVEY.md §2.9: the reference reserves
cp_shard/cp_replicate mesh dims but ships no CP implementation — every
model plan raises). Here CP is first-class: the sequence dim is sharded
over the ``cp_s`` mesh axis and attention runs as a ring
(arXiv 2310.01889 style): each device keeps its query block resident and
the K/V blocks rotate around the ring via ``ppermute`` over ICI, with
online-softmax accumulation — peak memory per device is O(T/cp · T/cp)
per block pair, and the rotation overlaps with the block matmuls under
XLA's async collectives.

Layout: contiguous sequence chunks — device ``i`` of the cp ring owns
positions ``[i·T_loc, (i+1)·T_loc)``. Causal masking across chunks falls
out of global position arithmetic (blocks strictly above the diagonal
contribute zero mass through -inf logits; compute is uniform across steps
so the program stays SPMD-static).

``ring_attention`` must be called *inside* ``shard_map`` (it uses
``axis_index``/``ppermute``); ``make_ring_sdpa`` wraps it into an SDPA
backend usable by the attention blocks under plain jit.
"""

import functools
import os
from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from d9d_tpu.core import compat
from d9d_tpu.core.types import Array

_NEG_INF = float("-inf")
_NEG_BIG = -1e30  # finite stand-in: keeps lse arithmetic NaN-free


def _block_logits(q, k, scale):
    """q [B,T,Hkv,G,D] × k [B,S,Hkv,D] → logits [B,Hkv,G,T,S] (fp32)."""
    return jnp.einsum("bthgd,bshd->bhgts", q, k.astype(jnp.float32)) * scale


def _default_impl() -> str:
    return os.environ.get("D9D_TPU_RING_BLOCK", "flash")


def ring_attention(
    q: Array,
    k: Array,
    v: Array,
    *,
    axis_name: str,
    causal: bool = True,
    softmax_scale: float | None = None,
    window_size: int | None = None,
    sinks: Array | None = None,
    q_segments: Array | None = None,
    kv_segments: Array | None = None,
    impl: str | None = None,
) -> Array:
    """Per-shard attention: ``q/k/v [B, T_loc, H(q|kv), D]`` → ``[B, T_loc, Hq, D]``.

    Call inside ``shard_map`` with the sequence dim sharded over
    ``axis_name``. Semantics match :func:`eager_sdpa` on the gathered
    sequence (GQA broadcast, causal, sliding window, learnable sinks,
    packed segments). ``q_segments``/``kv_segments`` are this shard's
    ``[B, T_loc]`` slices of the global packed-sequence ids; the kv slice
    rotates around the ring alongside its K/V block and cross-segment
    pairs are masked out of the online softmax.

    ``impl`` selects the per-step block compute: ``"flash"`` (default; the
    Pallas kernel at the ring chunk's global offsets — never materializes
    the [T_loc, S_loc] logits, skips fully-future blocks) or ``"eager"``
    (fp32 einsum oracle, kept for cross-checks; env override
    ``D9D_TPU_RING_BLOCK``).
    """
    if (q_segments is None) != (kv_segments is None):
        raise ValueError("q_segments and kv_segments must be provided together")
    impl = impl or _default_impl()
    if impl == "flash":
        return _ring_flash(
            q, k, v, axis_name=axis_name, causal=causal,
            softmax_scale=softmax_scale, window_size=window_size, sinks=sinks,
            q_segments=q_segments, kv_segments=kv_segments,
        )
    if impl != "eager":
        raise ValueError(f"unknown ring block impl {impl!r}")
    return _ring_eager(
        q, k, v, axis_name=axis_name, causal=causal,
        softmax_scale=softmax_scale, window_size=window_size, sinks=sinks,
        q_segments=q_segments, kv_segments=kv_segments,
    )


def _ring_shape_checks(q, v):
    b, t_loc, hq, d = q.shape
    _, s_loc, hkv, dv = v.shape
    if hq % hkv != 0:
        raise ValueError(f"query heads {hq} not a multiple of kv heads {hkv}")
    if t_loc != s_loc:
        raise ValueError("ring attention requires equal q/kv shard lengths")
    return b, t_loc, hq, hkv, d, dv


def _ring_flash(
    q, k, v, *, axis_name, causal, softmax_scale, window_size, sinks,
    q_segments, kv_segments,
):
    """Ring steps through the Pallas flash kernel (VERDICT r3 item 2).

    Each step runs :func:`flash_attention_block` on the resident q chunk
    against the rotating k/v chunk at their true global offsets, then
    merges the normalized partials through a logsumexp combine. The
    [T_loc, S_loc] logit tensor never exists; causal future chunks cost
    only the rotation (the kernel's dynamic skip drops their MXU work).
    """
    from d9d_tpu.ops.attention.pallas_flash import (
        combine_attention_chunks,
        flash_attention_block,
    )

    b, t_loc, hq, hkv, d, dv = _ring_shape_checks(q, v)
    cp = lax.axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)

    # ring rotation: device r sends its current kv block to r+1, so after
    # step s device i holds the block originally owned by (i - s) % cp
    perm = [(r, (r + 1) % cp) for r in range(cp)]

    def step(carry, s):
        o, lse, k_blk, v_blk, kseg_blk = carry
        src = (my_idx - s) % cp

        o_blk, lse_blk = flash_attention_block(
            q, k_blk, v_blk,
            q_offset=my_idx * t_loc, k_offset=src * t_loc,
            causal=causal, softmax_scale=softmax_scale,
            window_size=window_size,
            q_segments=q_segments, kv_segments=kseg_blk,
        )
        o, new_lse = combine_attention_chunks(o, lse, o_blk, lse_blk)

        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        if kseg_blk is not None:
            kseg_blk = lax.ppermute(kseg_blk, axis_name, perm)
        return (o, new_lse, k_blk, v_blk, kseg_blk), None

    o0 = jnp.zeros((b, t_loc, hq, dv), jnp.float32)
    lse0 = jnp.full((b, hq, t_loc), _NEG_BIG, jnp.float32)
    (o, lse, _, _, _), _ = lax.scan(
        step, (o0, lse0, k, v, kv_segments), jnp.arange(cp)
    )

    if sinks is not None:
        # sink joins only the global softmax denominator (reference
        # kernel/flash_attn/function.py:34 — autodiff supplies dsink here):
        # o' = o / (1 + exp(sink - lse)).
        z = jnp.clip(sinks.astype(jnp.float32)[None, :, None] - lse, max=60.0)
        inv = (1.0 / (1.0 + jnp.exp(z))).transpose(0, 2, 1)[..., None]
        o = o * inv

    return o.astype(q.dtype)


def _ring_eager(
    q, k, v, *, axis_name, causal, softmax_scale, window_size, sinks,
    q_segments, kv_segments,
):
    """fp32 einsum oracle for the ring step (cross-check / fallback)."""
    b, t_loc, hq, hkv, d, dv = _ring_shape_checks(q, v)
    g = hq // hkv
    scale = softmax_scale if softmax_scale is not None else d**-0.5

    cp = lax.axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    q_pos = my_idx * t_loc + jnp.arange(t_loc)  # global positions [T_loc]

    qf = q.astype(jnp.float32).reshape(b, t_loc, hkv, g, d)

    perm = [(r, (r + 1) % cp) for r in range(cp)]

    def step(carry, s):
        o, m, l, k_blk, v_blk, kseg_blk = carry
        src = (my_idx - s) % cp
        k_pos = src * t_loc + jnp.arange(t_loc)

        logits = _block_logits(qf, k_blk, scale)  # [B,Hkv,G,T,S]
        neg = jnp.asarray(_NEG_INF, logits.dtype)
        qp = q_pos[:, None]
        kp = k_pos[None, :]
        if causal:
            logits = jnp.where(kp <= qp, logits, neg)
        if window_size is not None:
            logits = jnp.where(kp > qp - window_size, logits, neg)
        if kseg_blk is not None:
            same = (
                q_segments[:, None, None, :, None]
                == kseg_blk[:, None, None, None, :]
            )
            logits = jnp.where(same, logits, neg)

        blk_max = jnp.max(logits, axis=-1)  # [B,Hkv,G,T]
        new_m = jnp.maximum(m, blk_max)
        # guard fully-masked-so-far rows (m == new_m == -inf)
        safe_m = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
        alpha = jnp.exp(jnp.where(jnp.isfinite(m), m - safe_m, _NEG_INF))
        p = jnp.exp(logits - safe_m[..., None])  # rows of -inf -> 0
        blk_o = jnp.einsum("bhgts,bshd->bthgd", p, v_blk.astype(jnp.float32))
        o = o * alpha.transpose(0, 3, 1, 2)[..., None] + blk_o
        l = l * alpha + jnp.sum(p, axis=-1)
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        if kseg_blk is not None:
            kseg_blk = lax.ppermute(kseg_blk, axis_name, perm)
        return (o, new_m, l, k_blk, v_blk, kseg_blk), None

    o0 = jnp.zeros((b, t_loc, hkv, g, dv), jnp.float32)
    m0 = jnp.full((b, hkv, g, t_loc), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, hkv, g, t_loc), jnp.float32)
    (o, m, l, _, _, _), _ = lax.scan(
        step, (o0, m0, l0, k, v, kv_segments), jnp.arange(cp)
    )

    if sinks is not None:
        # sink logit joins the global softmax denominator (reference
        # kernel/flash_attn/function.py:34 — autodiff supplies dsink here)
        sink = sinks.astype(jnp.float32).reshape(1, hkv, g, 1)
        new_m = jnp.maximum(m, sink)
        safe_m = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
        alpha = jnp.exp(jnp.where(jnp.isfinite(m), m - safe_m, _NEG_INF))
        l = l * alpha + jnp.exp(sink - safe_m)
        o = o * alpha.transpose(0, 3, 1, 2)[..., None]

    lT = l.transpose(0, 3, 1, 2)[..., None]  # [B,T,Hkv,G,1]
    out = o / jnp.maximum(lT, 1e-30)
    return out.reshape(b, t_loc, hq, dv).astype(q.dtype)


def make_ring_sdpa(
    mesh: Mesh,
    *,
    seq_axis: str = "cp_s",
    batch_axes: Sequence[str] = ("dp_r", "dp_s"),
    head_axes: Sequence[str] = ("tp",),
    impl: str | None = None,
):
    """Build an SDPA backend running ring attention over ``seq_axis``.

    The returned callable takes globally-sharded ``[B, T, H, D]`` arrays
    under jit and shard_maps them: batch over ``batch_axes``, sequence over
    ``seq_axis``, heads over ``head_axes`` (TP composes with CP — the ring
    only moves each device's head slice of K/V).
    """
    qkv_spec = P(tuple(batch_axes), seq_axis, tuple(head_axes), None)
    sink_spec = P(tuple(head_axes))
    seg_spec = P(tuple(batch_axes), seq_axis)

    def ring_sdpa(
        q: Array,
        k: Array,
        v: Array,
        *,
        causal: bool = True,
        softmax_scale: float | None = None,
        window_size: int | None = None,
        sinks: Array | None = None,
        mask: Array | None = None,
        q_segments: Array | None = None,
        kv_segments: Array | None = None,
    ) -> Array:
        if mask is not None:
            raise NotImplementedError(
                "ring attention does not support arbitrary masks; use the "
                "eager/flash backends or express the mask as causal+window"
            )
        if (q_segments is None) != (kv_segments is None):
            raise ValueError(
                "q_segments and kv_segments must be provided together"
            )

        # Resolve the mesh at TRACE time: under the pipeline engine each
        # stage jits against its own pp-less submesh, and a shard_map
        # whose mesh disagrees with the context mesh is an error.
        from d9d_tpu.core.mesh import resolve_ambient_mesh

        m = resolve_ambient_mesh(
            (seq_axis, *batch_axes, *head_axes),
            fallback=mesh,
            what="ring attention",
        )

        # validate divisibility up front: without this, a mis-sized input
        # surfaces as an opaque shard_map in_specs error deep in the jit
        # (and the batch stager silently falls back to batch-only sharding
        # for indivisible sequences, guaranteeing the reshard fails here)
        def _size(axes):
            out = 1
            for a in axes:
                out *= m.shape[a]
            return out

        b, t, hq, _ = q.shape
        hkv = k.shape[2]
        cp = _size((seq_axis,))
        tp_h = _size(head_axes)
        dp = _size(batch_axes)
        if t % cp != 0:
            raise ValueError(
                f"ring attention: seq_len {t} not divisible by the "
                f"'{seq_axis}' axis size {cp}"
            )
        if hq % tp_h != 0 or hkv % tp_h != 0:
            raise ValueError(
                f"ring attention: heads (q={hq}, kv={hkv}) not divisible "
                f"by the head axes {tuple(head_axes)} size {tp_h}"
            )
        if b % dp != 0:
            raise ValueError(
                f"ring attention: batch {b} not divisible by the batch "
                f"axes {tuple(batch_axes)} size {dp}"
            )

        # align activations to the ring layout explicitly — otherwise the
        # partitioner resharding into shard_map's fixed in_specs can fall
        # back to replicate-then-repartition around every attention layer
        q, k, v = (lax.with_sharding_constraint(x, qkv_spec) for x in (q, k, v))

        has_sinks = sinks is not None
        has_segs = q_segments is not None
        in_specs = (qkv_spec,) * 3
        args = (q, k, v)
        if has_sinks:
            in_specs += (sink_spec,)
            args += (sinks,)
        if has_segs:
            in_specs += (seg_spec, seg_spec)
            args += (q_segments, kv_segments)

        @functools.partial(
            compat.shard_map,
            mesh=m,
            in_specs=in_specs,
            out_specs=qkv_spec,
            check_vma=False,
        )
        def run(q, k, v, *rest):
            rest = list(rest)
            s = rest.pop(0) if has_sinks else None
            qseg = rest.pop(0) if has_segs else None
            kseg = rest.pop(0) if has_segs else None
            return ring_attention(
                q, k, v, axis_name=seq_axis, causal=causal,
                softmax_scale=softmax_scale, window_size=window_size,
                sinks=s, q_segments=qseg, kv_segments=kseg, impl=impl,
            )

        return run(*args)

    return ring_sdpa
