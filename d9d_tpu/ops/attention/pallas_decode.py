"""Pallas TPU flash-decode attention over a KV slot cache.

Parity: the reference serves decode through its flash varlen path
(d9d/kernel/flash_attn/function.py:384, flash_attn_varlen_func with
cache seqlens); this is the TPU-native equivalent for the KV-cache
decode step that previously routed to the eager fallback
(pallas_flash.py routes cross-length attention to eager — fine for the
training bench geometry, wrong for serving batches where the [B,H,T,S]
eager logits round-trip HBM every step).

Decode attention is KV-cache-bandwidth-bound: the optimal kernel
streams each (batch, kv-head) cache slice from HBM EXACTLY ONCE and
never materializes logits. Two layout decisions follow:

- The GQA group is the matmul M dimension. ``q [B,T,Hq,D]`` is reshaped
  to ``[B, Hkv, g·T, D]`` (g = Hq/Hkv) so one grid step attends every
  query head of the group against the shared kv block. The training
  kernel's (b, h, q-block, kv-block) grid would re-stream the whole
  cache g times per group — a g× HBM tax that training amortizes over
  large q blocks but decode (T ~ 1) cannot. The cache arrives
  HEADS-MAJOR ``[B, Hkv, S, D]`` — the layout the GQA decode cache
  maintains on write — so the kernel streams it directly; a read-side
  relayout would copy every slot every step and erase the win.
- The kv-block grid dim is innermost and sequential; per-(b, kv-head)
  online-softmax state (m, l, acc over g·T rows) persists in VMEM
  scratch across kv steps, exactly like the training forward.

Slot semantics ride positions: the cache write index ``start`` enters
as a traced SMEM scalar, queries sit at global positions
``start + [0,T)``, keys at their slot index — so causal/window masking
over slots needs no mask tensor, and kv blocks wholly in the causal
future of the last query are skipped (a decode step on a mostly-empty
cache touches only ceil((start+T)/block_kv) blocks). Per-key validity
(ragged left-padded prompts: loop/generate.py's [B,1,1,S] mask) streams
as an int row-vector alongside k/v. Sinks join outside the kernel as
the standard (o, lse) denominator correction (pallas_flash.py:21).

Forward-only by design: decode never differentiates. ``jax.jit``-safe
(static T/S/g; ``start`` traced).
"""

import dataclasses
import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from d9d_tpu.core.types import Array

NEG_BIG = -1e30
LANES = 128

# practical bound on the resident q block (g·T rows): the kernel keeps
# one un-tiled [rows, D] q block + fp32 accumulators per (b, kv-head);
# beyond this, a big prefill chunk is better served by the training
# flash kernel's tiled grid (callers fall back to the eager slot path
# or cap their chunk size — loop/generate.py documents the bound)
MAX_DECODE_ROWS = 1024


def decode_attention_backend() -> str:
    """'pallas' or 'eager' — env-selected like the SDPA backend family.

    ``D9D_TPU_DECODE_ATTN``: ``auto`` (default; pallas on TPU, eager
    elsewhere — interpret-mode pallas is a test vehicle, not a CPU
    serving path), ``pallas``, or ``eager``.
    """
    mode = os.environ.get("D9D_TPU_DECODE_ATTN", "auto")
    if mode == "auto":
        return "pallas" if jax.default_backend() == "tpu" else "eager"
    return mode


@dataclasses.dataclass(frozen=True)
class _DecodeConfig:
    scale: float
    window: int | None
    t: int           # new tokens this step (queries)
    rows: int        # g·T real query rows per (b, kv-head)
    rows_pad: int    # rows padded to the sublane multiple
    s_len: int       # real cache capacity (pre-padding)
    block_kv: int
    has_valid: bool
    interpret: bool
    # int8 KV pools with per-slot scale pools riding behind k/v (paged
    # mode only; never combines with has_valid — the serving loop's
    # paged rows are never left-padded)
    quant: bool = False


def _decode_kernel(*refs, cfg: _DecodeConfig):
    ks_ref = vs_ref = valid_ref = None
    if cfg.quant:
        offs_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref = refs[:6]
        o_ref, lse_ref, m_ref, l_ref, acc_ref = refs[6:]
    elif cfg.has_valid:
        offs_ref, q_ref, k_ref, v_ref, valid_ref = refs[:5]
        o_ref, lse_ref, m_ref, l_ref, acc_ref = refs[5:]
    else:
        offs_ref, q_ref, k_ref, v_ref = refs[:4]
        o_ref, lse_ref, m_ref, l_ref, acc_ref = refs[4:]
    # per-batch-row write index (continuous batching: rows fill at
    # independent rates; a shared index is just the broadcast case)
    start = offs_ref[pl.program_id(0)]
    ik = pl.program_id(2)
    n_kv = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_BIG)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # whole-block skip: every key slot past the LAST query's position is
    # invisible (and with a window, every slot at/before the FIRST
    # query's window floor) — traced predicates, pl.when skips the MXU
    # work. This is what makes a step on a warm-but-not-full cache cost
    # O(start + T), not O(s_max).
    k_lo = ik * cfg.block_kv
    k_hi = k_lo + cfg.block_kv - 1
    skip = k_lo > start + (cfg.t - 1)
    if cfg.window is not None:
        skip |= k_hi <= start - cfg.window

    @pl.when(jnp.logical_not(skip))
    def _compute():
        q = q_ref[0, 0, :, :].astype(jnp.float32)
        k = k_ref[0, 0, :, :].astype(jnp.float32)
        v = v_ref[0, 0, :, :].astype(jnp.float32)
        if cfg.quant:
            # int8 block × per-slot scale column [bkv, 1] broadcast
            # over the feature dim: the dequant rides the same in-VMEM
            # f32 math the kernel already does — HBM streamed the int8
            # bytes, the rescale is free next to the MXU dot
            k = k * ks_ref[0, 0, :, :]
            v = v * vs_ref[0, 0, :, :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * cfg.scale  # [rows_pad, bkv]

        rp, bkv = s.shape
        row = jax.lax.broadcasted_iota(jnp.int32, (rp, bkv), 0)
        k_pos = k_lo + jax.lax.broadcasted_iota(jnp.int32, (rp, bkv), 1)
        # row r = (head-in-group, token i) flattened as ig·T + i, so the
        # query's global slot position is start + r % T
        q_pos = start + jax.lax.rem(row, cfg.t)
        mask = (k_pos < cfg.s_len) & (k_pos <= q_pos) & (row < cfg.rows)
        if cfg.window is not None:
            mask &= k_pos > q_pos - cfg.window
        if valid_ref is not None:
            mask &= valid_ref[0, :, :] != 0  # [1, bkv] key validity
        s = jnp.where(mask, s, NEG_BIG)

        m_prev = m_ref[:, :1]
        m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        # gate by the mask, not just the sentinel: while every key a row
        # has seen is masked, m_new stays NEG_BIG and exp(s - m_new)
        # would be 1 for masked entries — silently emitting mean-of-V.
        # Zeroing masked probabilities keeps l at 0 for such rows, so
        # the finalize epilogue yields exact zeros instead.
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        l_new = alpha * l_ref[:, :1] + p.sum(axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot(
            p, v, preferred_element_type=jnp.float32
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ik == n_kv - 1)
    def _finalize():
        m = m_ref[:, :1]
        l = l_ref[:, :1]
        o_ref[0, 0, :, :] = (acc_ref[:] / jnp.maximum(l, 1e-30)).astype(
            o_ref.dtype
        )
        lse_ref[0, 0, :, :] = m + jnp.log(jnp.maximum(l, 1e-30))


def _pad_to(n: int, m: int) -> int:
    return (-n) % m


def _paged_decode_kernel(offs_ref, pt_ref, *refs, cfg: _DecodeConfig):
    """Paged variant: identical math to :func:`_decode_kernel` — the
    page table is consumed entirely by the kv BlockSpec index maps
    (scalar-prefetch gather), so the kernel body only needs the write
    offsets. Grid step ``ki`` is the row's LOGICAL block ki; its bytes
    stream from pool page ``pt[bi, ki]``."""
    del pt_ref  # consumed by the index maps
    _decode_kernel(offs_ref, *refs, cfg=cfg)


# d9d-lint: disable=D9D001 — standalone-use decorator; serving traces this inside the tracked serve/step program (a TrackedJit cannot be called under a trace)
@functools.partial(jax.jit, static_argnames=("cfg",))
def _paged_decode_call(cfg: _DecodeConfig, q_rows, k_pool, v_pool,
                       offsets, page_table, k_scale=None, v_scale=None):
    """``q_rows [B, Hkv, rows_pad, D]`` vs page pools
    ``k/v [P, Hkv, page_size, D]`` gathered through
    ``page_table [B, n_pages]`` → same outputs as :func:`_decode_call`
    on the contiguous equivalent. The kv-block index map generalizes
    from ``block = ki`` to ``block = page_table[bi, ki]`` — the paging
    claim in one line: the kernel needs a different INDEX, not a
    different algorithm. ``block_kv == page_size`` by construction.

    ``cfg.quant``: k/v pools are int8 and ``k/v_scale [P, Hkv, ps]``
    carry the per-slot dequantization scales — reshaped to a trailing
    unit lane and streamed through the SAME gathering index map as
    their pools (a scale page is just a narrower page), rescaled in
    the kernel's existing in-VMEM f32 accumulation."""
    b, hkv, rp, d = q_rows.shape
    n_pages = page_table.shape[1]

    kv_spec = pl.BlockSpec(
        (1, 1, cfg.block_kv, d),
        lambda bi, hi, ki, offs, pt: (pt[bi, ki], hi, 0, 0),
    )
    scale_specs, scale_bufs = (), ()
    if cfg.quant:
        scale_specs = (
            pl.BlockSpec((1, 1, cfg.block_kv, 1),
                         lambda bi, hi, ki, offs, pt: (pt[bi, ki], hi, 0, 0)),
        ) * 2
        scale_bufs = (k_scale[..., None], v_scale[..., None])

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # offsets, page_table
        grid=(b, hkv, n_pages),
        in_specs=[
            pl.BlockSpec((1, 1, rp, d),
                         lambda bi, hi, ki, offs, pt: (bi, hi, 0, 0)),
            kv_spec,
            kv_spec,
            *scale_specs,
        ],
        out_specs=[
            pl.BlockSpec((1, 1, rp, d),
                         lambda bi, hi, ki, offs, pt: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, rp, 1),
                         lambda bi, hi, ki, offs, pt: (bi, hi, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((rp, LANES), jnp.float32),
            pltpu.VMEM((rp, LANES), jnp.float32),
            pltpu.VMEM((rp, d), jnp.float32),
        ],
    )
    o, lse = pl.pallas_call(
        functools.partial(_paged_decode_kernel, cfg=cfg),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b, hkv, rp, d), q_rows.dtype),
            jax.ShapeDtypeStruct((b, hkv, rp, 1), jnp.float32),
        ],
        compiler_params=(
            None if cfg.interpret else pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary")
            )
        ),
        interpret=cfg.interpret,
    )(offsets, page_table, q_rows, k_pool, v_pool, *scale_bufs)
    return o, lse[..., 0]


# d9d-lint: disable=D9D001 — standalone-use decorator; serving traces this inside the tracked serve/step program (a TrackedJit cannot be called under a trace)
@functools.partial(
    jax.jit,
    static_argnames=("cfg",),
)
def _decode_call(cfg: _DecodeConfig, q_rows, kp, vp, valid, offsets):
    """``q_rows [B, Hkv, rows_pad, D]`` vs cache ``k/v [B, Hkv, S_pad, D]``
    (heads-major — the caller's cache layout, streamed with no relayout)
    → ``(o [B, Hkv, rows_pad, D], lse [B, Hkv, rows_pad])``."""
    b, hkv, rp, d = q_rows.shape
    s_pad = kp.shape[2]
    n_kv = s_pad // cfg.block_kv

    valid_specs, valid_bufs = (), ()
    if cfg.has_valid:
        valid_specs = (
            pl.BlockSpec((1, 1, cfg.block_kv),
                         lambda bi, hi, ki: (bi, 0, ki)),
        )
        valid_bufs = (valid[:, None, :].astype(jnp.int32),)

    o, lse = pl.pallas_call(
        functools.partial(_decode_kernel, cfg=cfg),
        grid=(b, hkv, n_kv),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, rp, d), lambda bi, hi, ki: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, cfg.block_kv, d),
                         lambda bi, hi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, cfg.block_kv, d),
                         lambda bi, hi, ki: (bi, hi, ki, 0)),
            *valid_specs,
        ],
        out_specs=[
            pl.BlockSpec((1, 1, rp, d), lambda bi, hi, ki: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, rp, 1), lambda bi, hi, ki: (bi, hi, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hkv, rp, d), q_rows.dtype),
            jax.ShapeDtypeStruct((b, hkv, rp, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((rp, LANES), jnp.float32),
            pltpu.VMEM((rp, LANES), jnp.float32),
            pltpu.VMEM((rp, d), jnp.float32),
        ],
        compiler_params=(
            None if cfg.interpret else pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary")
            )
        ),
        interpret=cfg.interpret,
    )(offsets, q_rows, kp, vp, *valid_bufs)
    return o, lse[..., 0]


def flash_decode_attention(
    q: Array,
    k_cache: Array,
    v_cache: Array,
    *,
    start: Array,
    softmax_scale: float | None = None,
    window_size: int | None = None,
    sinks: Array | None = None,
    kv_valid: Array | None = None,
    page_table: Array | None = None,
    k_scale: Array | None = None,
    v_scale: Array | None = None,
    block_kv: int = 512,
    interpret: bool | None = None,
) -> Array:
    """Decode-step attention: ``q [B,T,Hq,D]`` (new tokens at cache
    positions ``start + [0,T)``) against the full slot cache
    ``k/v [B,Hkv,S,D]`` (HEADS-MAJOR — the layout
    ``_decode_cache_append_heads_major`` maintains, so the cache streams
    into the kernel with zero per-step relayout) → ``[B,T,Hq,D]``.

    Slot-causal + optional sliding window over global positions;
    ``start`` may be a scalar (one shared write index — the closed-batch
    generate loop) or per-row ``[B]`` (continuous batching: each row's
    cache fills at its own rate). ``kv_valid [B,S]`` masks dead slots
    (left-padded ragged prompts); ``sinks [Hq]`` join the softmax
    denominator via the standard outside-the-kernel correction.
    Forward-only (decode never backpropagates). Semantics match
    ``eager_sdpa(q, cacheᵀ, cacheᵀ, causal=False,
    mask=_decode_slot_mask(...))`` — the parity test drives both — with
    one deliberate divergence: a query row whose EVERY key is masked
    (e.g. ``kv_valid`` zeroing all slots at or before its position)
    produces exact ZEROS here, where the eager oracle's finite softmax
    sentinel yields a uniform mean-of-V. Module callers never hit this
    case (a row's just-written key is always valid), but public callers
    passing custom validity get the guarded-softmax behavior.

    PAGED mode (``page_table [B, n_pages]`` set): ``k/v`` are page
    POOLS ``[P, Hkv, page_size, D]`` and row ``b``'s logical kv block
    ``ki`` streams from pool page ``page_table[b, ki]`` — the block
    index map gathers page ids instead of assuming ``page == ki``
    (``block_kv`` is forced to the page size). Everything else —
    per-row ``start``, whole-block skip, windows, sinks, the online
    softmax — is unchanged, which is exactly why paging is an indexing
    generalization of this kernel rather than a new one. ``kv_valid``
    does not compose with paging (the serving loop never passes it).

    QUANTIZED paged mode (``k_scale``/``v_scale [P, Hkv, page_size]``
    set): the pools are int8 and each slot's feature vector carries a
    f32 scale; the scale pools stream through the same gathering index
    map (one narrow block per page) and the kernel widens
    ``int8 * scale`` inside its existing f32 accumulation — HBM
    traffic per slot drops to D int8 bytes + one f32 scale. Note int8
    TPU tiles are (32, 128): on-chip (non-interpret) runs need
    ``page_size >= 32``; the CPU interpret tier has no such floor.
    """
    b, t, hq, d = q.shape
    _, hkv, s, _ = k_cache.shape
    if hq % hkv != 0:
        raise ValueError(f"query heads {hq} not a multiple of kv heads {hkv}")
    g = hq // hkv
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    rows = g * t
    rp = rows + _pad_to(rows, 8)

    # [B,T,Hq,D] → [B,Hkv,g·T,D], row r = ig·T + i (shared by both the
    # contiguous and paged calls, as are the epilogue slices, the sink
    # fold and the output reshape below — the two paths differ ONLY in
    # how kv blocks are indexed)
    q_rows = (
        q.transpose(0, 2, 1, 3)
        .reshape(b, hkv, g * t, d)
    )
    if rp != rows:
        q_rows = jnp.pad(q_rows, ((0, 0), (0, 0), (0, rp - rows), (0, 0)))
    offsets = jnp.broadcast_to(
        jnp.asarray(start, jnp.int32).reshape(-1), (b,)
    )

    if page_table is not None:
        if kv_valid is not None:
            raise NotImplementedError(
                "paged decode does not take kv_valid (the serving loop's "
                "paged rows are never left-padded)"
            )
        if (k_scale is None) != (v_scale is None):
            raise ValueError("k_scale and v_scale must be set together")
        page_size = k_cache.shape[2]
        n_pages = page_table.shape[1]
        cfg = _DecodeConfig(
            scale=softmax_scale if softmax_scale is not None else d**-0.5,
            window=window_size,
            t=t,
            rows=rows,
            rows_pad=rp,
            s_len=n_pages * page_size,  # every gathered slot addressable
            block_kv=page_size,
            has_valid=False,
            interpret=interpret,
            quant=k_scale is not None,
        )
        o, lse = _paged_decode_call(
            cfg, q_rows, k_cache, v_cache, offsets,
            page_table.astype(jnp.int32),
            k_scale=k_scale, v_scale=v_scale,
        )
    else:
        if k_scale is not None or v_scale is not None:
            raise NotImplementedError(
                "k_scale/v_scale are paged-mode arguments (quantized "
                "pools need a page_table)"
            )
        bkv = min(block_kv, s + _pad_to(s, LANES))
        s_pad = s + _pad_to(s, bkv)

        cfg = _DecodeConfig(
            scale=softmax_scale if softmax_scale is not None else d**-0.5,
            window=window_size,
            t=t,
            rows=rows,
            rows_pad=rp,
            s_len=s,
            block_kv=bkv,
            has_valid=kv_valid is not None,
            interpret=interpret,
        )

        pad_s = s_pad - s
        kp = jnp.pad(k_cache, ((0, 0), (0, 0), (0, pad_s), (0, 0))) if pad_s else k_cache
        vp = jnp.pad(v_cache, ((0, 0), (0, 0), (0, pad_s), (0, 0))) if pad_s else v_cache
        validp = None
        if kv_valid is not None:
            validp = jnp.pad(kv_valid, ((0, 0), (0, pad_s))) if pad_s else kv_valid

        o, lse = _decode_call(cfg, q_rows, kp, vp, validp, offsets)

    o = o[:, :, :rows]
    lse = lse[:, :, :rows]
    if sinks is not None:
        # sink joins only the denominator: o' = o / (1 + exp(sink - lse))
        sink_rows = jnp.repeat(
            sinks.astype(jnp.float32).reshape(hkv, g), t, axis=1
        ).reshape(1, hkv, rows, 1)
        z = jnp.clip(sink_rows - lse[..., None], max=60.0)
        o = (o.astype(jnp.float32) / (1.0 + jnp.exp(z))).astype(o.dtype)

    # [B,Hkv,g·T,D] → [B,T,Hq,D]
    return (
        o.reshape(b, hkv, g, t, d)
        .transpose(0, 3, 1, 2, 4)
        .reshape(b, t, hq, d)
    )
