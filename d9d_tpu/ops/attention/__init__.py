from d9d_tpu.ops.attention.eager import eager_sdpa

__all__ = ["eager_sdpa"]
