"""Pallas TPU flash attention (forward + backward), with GQA, causal,
sliding-window, and learnable attention sinks.

TPU-native replacement for the reference's flash-attn wheel wrapper
(d9d/kernel/flash_attn/function.py:331 — FA4/CuTe with sinks, window,
varlen): an online-softmax forward and a two-kernel backward (dq; dk/dv)
with fp32 accumulation in VMEM scratch. The analytic sink gradient the
reference computes in-kernel (function.py:34) is done here with one cheap
XLA reduction over the saved LSE instead.

Layout: flash-style ``[batch, seq, heads, head_dim]``. The kv-block grid
dim is innermost, so per-(b, h, q-block) running max / denominator / output
accumulators persist in scratch across kv steps (TPU grids execute
sequentially). Causal and window block-skipping happens via ``pl.when`` —
skipped blocks cost a grid step but no MXU work.

Falls back to the eager XLA path for explicit boolean masks or
cross-length (decode) attention — those are not training hot paths.
"""

import dataclasses
import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from d9d_tpu.core.types import Array

NEG_BIG = -1e30
LANES = 128


@dataclasses.dataclass(frozen=True)
class _FlashConfig:
    causal: bool
    scale: float
    window: int | None
    has_sinks: bool
    block_q: int
    block_kv: int
    seq_len: int  # real (unpadded) length
    interpret: bool


def _mask_block(s, cfg: _FlashConfig, iq, ik):
    """Apply causal / window / length masking to one [bq, bkv] logit block."""
    bq, bkv = s.shape
    q_pos = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 0)
    k_pos = ik * bkv + jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 1)
    mask = k_pos < cfg.seq_len
    if cfg.causal:
        mask &= k_pos <= q_pos
    if cfg.window is not None:
        mask &= k_pos > q_pos - cfg.window
    return jnp.where(mask, s, NEG_BIG)


def _skip_block(cfg: _FlashConfig, iq, ik):
    """True when the whole kv block is masked for the whole q block."""
    skip = jnp.asarray(False)
    if cfg.causal:
        skip |= ik * cfg.block_kv > iq * cfg.block_q + cfg.block_q - 1
    if cfg.window is not None:
        skip |= (ik + 1) * cfg.block_kv - 1 <= iq * cfg.block_q - cfg.window
    return skip


def _fwd_kernel(q_ref, k_ref, v_ref, sinks_ref, o_ref, lse_ref,
                m_ref, l_ref, acc_ref, *, cfg: _FlashConfig):
    iq, ik = pl.program_id(2), pl.program_id(3)
    n_kv = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_BIG)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(jnp.logical_not(_skip_block(cfg, iq, ik)))
    def _compute():
        q = q_ref[0, :, 0, :].astype(jnp.float32)
        k = k_ref[0, :, 0, :].astype(jnp.float32)
        v = v_ref[0, :, 0, :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * cfg.scale
        s = _mask_block(s, cfg, iq, ik)

        m_prev = m_ref[:, :1]
        m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_ref[:, :1] + p.sum(axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot(
            p, v, preferred_element_type=jnp.float32
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ik == n_kv - 1)
    def _finalize():
        m = m_ref[:, :1]
        l = l_ref[:, :1]
        if cfg.has_sinks:
            sink = sinks_ref[0].astype(jnp.float32)
            # the sink joins the softmax denominator (but contributes no value)
            m_out = jnp.maximum(m, sink)
            l = l * jnp.exp(m - m_out) + jnp.exp(sink - m_out)
            m = m_out
        o = acc_ref[:] * jnp.exp(m_ref[:, :1] - m) / jnp.maximum(l, 1e-30)
        o_ref[0, :, 0, :] = o.astype(o_ref.dtype)
        lse_ref[0, 0, :] = (m[:, 0] + jnp.log(jnp.maximum(l, 1e-30)[:, 0]))


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_acc, *, cfg: _FlashConfig):
    iq, ik = pl.program_id(2), pl.program_id(3)
    n_kv = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    @pl.when(jnp.logical_not(_skip_block(cfg, iq, ik)))
    def _compute():
        q = q_ref[0, :, 0, :].astype(jnp.float32)
        k = k_ref[0, :, 0, :].astype(jnp.float32)
        v = v_ref[0, :, 0, :].astype(jnp.float32)
        do = do_ref[0, :, 0, :].astype(jnp.float32)
        lse = lse_ref[0, 0, :][:, None]
        delta = delta_ref[0, 0, :][:, None]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * cfg.scale
        s = _mask_block(s, cfg, iq, ik)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * cfg.scale
        dq_acc[:] += jax.lax.dot(ds, k, preferred_element_type=jnp.float32)

    @pl.when(ik == n_kv - 1)
    def _finalize():
        dq_ref[0, :, 0, :] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, cfg: _FlashConfig,
                    n_q_blocks: int):
    ik, inner = pl.program_id(2), pl.program_id(3)
    n_inner = pl.num_programs(3)
    iq = inner % n_q_blocks

    @pl.when(inner == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    @pl.when(jnp.logical_not(_skip_block(cfg, iq, ik)))
    def _compute():
        q = q_ref[0, :, 0, :].astype(jnp.float32)
        k = k_ref[0, :, 0, :].astype(jnp.float32)
        v = v_ref[0, :, 0, :].astype(jnp.float32)
        do = do_ref[0, :, 0, :].astype(jnp.float32)
        lse = lse_ref[0, 0, :][:, None]
        delta = delta_ref[0, 0, :][:, None]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * cfg.scale
        s = _mask_block(s, cfg, iq, ik)
        p = jnp.exp(s - lse)
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * cfg.scale
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(inner == n_inner - 1)
    def _finalize():
        dk_ref[0, :, 0, :] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, :, 0, :] = dv_acc[:].astype(dv_ref.dtype)


def _pad_len(n: int, block: int) -> int:
    return (-n) % block


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash(cfg: _FlashConfig, q, k, v, sinks):
    o, _ = _flash_fwd(cfg, q, k, v, sinks)
    return o


def _flash_fwd(cfg: _FlashConfig, q, k, v, sinks):
    b, t, h, d = q.shape
    _, s, hkv, _ = k.shape
    g = h // hkv
    pad_q, pad_k = _pad_len(t, cfg.block_q), _pad_len(s, cfg.block_kv)
    qp = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0), (0, 0))) if pad_q else q
    kp = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0), (0, 0))) if pad_k else k
    vp = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0), (0, 0))) if pad_k else v
    tq, tk = t + pad_q, s + pad_k
    n_q, n_kv = tq // cfg.block_q, tk // cfg.block_kv

    grid = (b, h, n_q, n_kv)
    kernel = functools.partial(_fwd_kernel, cfg=cfg)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, cfg.block_q, 1, d), lambda bi, hi, qi, ki: (bi, qi, hi, 0)),
            pl.BlockSpec((1, cfg.block_kv, 1, d), lambda bi, hi, qi, ki, g=g: (bi, ki, hi // g, 0)),
            pl.BlockSpec((1, cfg.block_kv, 1, d), lambda bi, hi, qi, ki, g=g: (bi, ki, hi // g, 0)),
            pl.BlockSpec((1,), lambda bi, hi, qi, ki: (hi,)),
        ],
        out_specs=[
            pl.BlockSpec((1, cfg.block_q, 1, d), lambda bi, hi, qi, ki: (bi, qi, hi, 0)),
            pl.BlockSpec((1, 1, cfg.block_q), lambda bi, hi, qi, ki: (bi, hi, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, tq, h, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, tq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((cfg.block_q, LANES), jnp.float32),
            pltpu.VMEM((cfg.block_q, LANES), jnp.float32),
            pltpu.VMEM((cfg.block_q, d), jnp.float32),
        ],
        interpret=cfg.interpret,
    )(qp, kp, vp, sinks)
    o = o[:, :t] if pad_q else o
    return o, (q, k, v, sinks, lse)


def _flash_bwd(cfg: _FlashConfig, residuals, do):
    q, k, v, sinks, lse = residuals
    b, t, h, d = q.shape
    _, s, hkv, _ = k.shape
    g = h // hkv
    pad_q, pad_k = _pad_len(t, cfg.block_q), _pad_len(s, cfg.block_kv)
    # recompute forward output contribution Δ = rowsum(dO ⊙ O) without
    # storing O: O = flash forward (cheap relative to backward, and padded
    # consistently). Instead of rerunning the kernel we use the saved lse
    # only; Δ must come from O, so recompute O via the forward kernel.
    o = _flash(cfg, q, k, v, sinks)
    delta = jnp.einsum("bthd,bthd->bht", do.astype(jnp.float32), o.astype(jnp.float32))

    qp = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0), (0, 0))) if pad_q else q
    kp = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0), (0, 0))) if pad_k else k
    vp = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0), (0, 0))) if pad_k else v
    dop = jnp.pad(do, ((0, 0), (0, pad_q), (0, 0), (0, 0))) if pad_q else do
    deltap = jnp.pad(delta, ((0, 0), (0, 0), (0, pad_q))) if pad_q else delta
    # lse was saved padded already
    tq, tk = t + pad_q, s + pad_k
    n_q, n_kv = tq // cfg.block_q, tk // cfg.block_kv

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, cfg=cfg),
        grid=(b, h, n_q, n_kv),
        in_specs=[
            pl.BlockSpec((1, cfg.block_q, 1, d), lambda bi, hi, qi, ki: (bi, qi, hi, 0)),
            pl.BlockSpec((1, cfg.block_kv, 1, d), lambda bi, hi, qi, ki, g=g: (bi, ki, hi // g, 0)),
            pl.BlockSpec((1, cfg.block_kv, 1, d), lambda bi, hi, qi, ki, g=g: (bi, ki, hi // g, 0)),
            pl.BlockSpec((1, cfg.block_q, 1, d), lambda bi, hi, qi, ki: (bi, qi, hi, 0)),
            pl.BlockSpec((1, 1, cfg.block_q), lambda bi, hi, qi, ki: (bi, hi, qi)),
            pl.BlockSpec((1, 1, cfg.block_q), lambda bi, hi, qi, ki: (bi, hi, qi)),
        ],
        out_specs=pl.BlockSpec(
            (1, cfg.block_q, 1, d), lambda bi, hi, qi, ki: (bi, qi, hi, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((b, tq, h, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((cfg.block_q, d), jnp.float32)],
        interpret=cfg.interpret,
    )(qp, kp, vp, dop, lse, deltap)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, cfg=cfg, n_q_blocks=n_q),
        grid=(b, hkv, n_kv, g * n_q),
        in_specs=[
            pl.BlockSpec(
                (1, cfg.block_q, 1, d),
                lambda bi, hi, ki, t_, n=n_q, g=g: (bi, t_ % n, hi * g + t_ // n, 0),
            ),
            pl.BlockSpec((1, cfg.block_kv, 1, d), lambda bi, hi, ki, t_: (bi, ki, hi, 0)),
            pl.BlockSpec((1, cfg.block_kv, 1, d), lambda bi, hi, ki, t_: (bi, ki, hi, 0)),
            pl.BlockSpec(
                (1, cfg.block_q, 1, d),
                lambda bi, hi, ki, t_, n=n_q, g=g: (bi, t_ % n, hi * g + t_ // n, 0),
            ),
            pl.BlockSpec(
                (1, 1, cfg.block_q),
                lambda bi, hi, ki, t_, n=n_q, g=g: (bi, hi * g + t_ // n, t_ % n),
            ),
            pl.BlockSpec(
                (1, 1, cfg.block_q),
                lambda bi, hi, ki, t_, n=n_q, g=g: (bi, hi * g + t_ // n, t_ % n),
            ),
        ],
        out_specs=[
            pl.BlockSpec((1, cfg.block_kv, 1, d), lambda bi, hi, ki, t_: (bi, ki, hi, 0)),
            pl.BlockSpec((1, cfg.block_kv, 1, d), lambda bi, hi, ki, t_: (bi, ki, hi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, tk, hkv, d), k.dtype),
            jax.ShapeDtypeStruct((b, tk, hkv, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((cfg.block_kv, d), jnp.float32),
            pltpu.VMEM((cfg.block_kv, d), jnp.float32),
        ],
        interpret=cfg.interpret,
    )(qp, kp, vp, dop, lse, deltap)

    dq = dq[:, :t] if pad_q else dq
    dk = dk[:, :s] if pad_k else dk
    dv = dv[:, :s] if pad_k else dv

    if cfg.has_sinks:
        # p_sink[b,h,t] = exp(sink_h - lse); dsink = -Σ p_sink * Δ
        p_sink = jnp.exp(sinks.astype(jnp.float32)[None, :, None] - lse[:, :, :t])
        dsinks = -(p_sink * delta).sum(axis=(0, 2)).astype(sinks.dtype)
    else:
        dsinks = jnp.zeros_like(sinks)
    return dq, dk, dv, dsinks


_flash.defvjp(_flash_fwd, _flash_bwd)


def make_pallas_flash_sdpa(block_q: int = 512, block_kv: int = 512):
    """Build an SdpaBackend backed by the Pallas flash kernel."""

    def sdpa(
        q: Array,
        k: Array,
        v: Array,
        *,
        causal: bool = True,
        softmax_scale: float | None = None,
        window_size: int | None = None,
        sinks: Array | None = None,
        mask: Array | None = None,
    ) -> Array:
        if mask is not None or q.shape[1] != k.shape[1]:
            from d9d_tpu.ops.attention.eager import eager_sdpa

            return eager_sdpa(
                q, k, v, causal=causal, softmax_scale=softmax_scale,
                window_size=window_size, sinks=sinks, mask=mask,
            )
        t = q.shape[1]
        d = q.shape[-1]
        cfg = _FlashConfig(
            causal=causal,
            scale=softmax_scale if softmax_scale is not None else d**-0.5,
            window=window_size,
            has_sinks=sinks is not None,
            block_q=min(block_q, max(8, 2 ** math.ceil(math.log2(max(t, 1))))),
            block_kv=min(block_kv, max(8, 2 ** math.ceil(math.log2(max(t, 1))))),
            seq_len=t,
            interpret=jax.default_backend() != "tpu",
        )
        sinks_arr = (
            sinks if sinks is not None else jnp.zeros((q.shape[2],), jnp.float32)
        )
        return _flash(cfg, q, k, v, sinks_arr)

    return sdpa
