"""Pallas TPU flash attention (forward + backward), with GQA, causal,
sliding-window, learnable attention sinks, and packed-sequence segment ids.

TPU-native replacement for the reference's flash-attn wheel wrapper
(d9d/kernel/flash_attn/function.py:331,384 — FA4/CuTe with sinks, window,
varlen): an online-softmax forward and a two-kernel backward (dq; dk/dv)
with fp32 accumulation in VMEM scratch. Varlen batches map to segment ids
(packed layout), the TPU-friendly equivalent of cu_seqlens.

Public layout is flash-style ``[batch, seq, heads, head_dim]``; internally
tensors run as ``[batch, heads, seq, head_dim]`` so every block puts
(seq, head_dim) in the minor-two positions as the Mosaic tiling rules
require (second-minor %8, minor %128-or-full).

The kv-block grid dim is innermost, so per-(b, h, q-block) running max /
denominator / output accumulators persist in scratch across kv steps (TPU
grids execute sequentially). Causal and window block-skipping happens via
``pl.when`` — skipped blocks cost a grid step but no MXU work.

The sink joins only the softmax denominator, so it is folded in *outside*
the kernel as an elementwise correction on (o, lse); the backward kernels
then see the corrected lse and need no sink plumbing. The analytic dsink
(reference function.py:34) is one XLA reduction over the saved lse.

Falls back to the eager XLA path for explicit boolean masks or
cross-length (decode) attention — those are not training hot paths.
"""

import dataclasses
import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from d9d_tpu.core.types import Array

NEG_BIG = -1e30
LANES = 128


@dataclasses.dataclass(frozen=True)
class _FlashConfig:
    causal: bool
    scale: float
    window: int | None
    has_sinks: bool
    has_segments: bool
    block_q: int
    block_kv: int
    seq_len: int  # real (unpadded) kv length
    interpret: bool
    # When True the kernel takes a leading SMEM int32[2] = [q_offset,
    # k_offset] input and causal/window masking runs on GLOBAL positions
    # (local + offset). This is how ring attention reuses the kernel: each
    # ring step attends a local q chunk against a rotating k/v chunk whose
    # global offsets are device-dependent (traced), so they cannot live in
    # this static config.
    has_positions: bool = False
    # One-pass backward (dq+dk+dv from a single logit recompute) instead
    # of the two-kernel split — see _bwd_fused_kernel. Applied when the
    # dq state fits VMEM (_fused_bwd_fits); sweep via D9D_TPU_FLASH_BWD.
    fused_bwd: bool = False


def _mask_block(s, cfg: _FlashConfig, iq, ik, q_seg, k_seg, qoff=None, koff=None):
    """Apply length / causal / window / segment masking to one [bq, bkv]
    logit block. ``qoff``/``koff`` are traced global-position offsets
    (SMEM scalars) when ``cfg.has_positions``."""
    bq, bkv = s.shape
    q_pos = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 0)
    k_loc = ik * bkv + jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 1)
    mask = k_loc < cfg.seq_len  # padding is local regardless of offsets
    k_pos = k_loc
    if qoff is not None:
        q_pos = q_pos + qoff
        k_pos = k_pos + koff
    if cfg.causal:
        mask &= k_pos <= q_pos
    if cfg.window is not None:
        mask &= k_pos > q_pos - cfg.window
    if q_seg is not None:
        mask &= q_seg == k_seg
    return jnp.where(mask, s, NEG_BIG)


def _skip_block(cfg: _FlashConfig, iq, ik, qoff=None, koff=None):
    """True when the whole kv block is masked for the whole q block.

    Static (python bool arithmetic) without offsets; with traced offsets it
    becomes a scalar predicate — ``pl.when`` accepts both, and on TPU the
    dynamic form still skips the MXU work (e.g. every block of a ring step
    whose kv chunk is entirely in the causal future)."""
    q_lo = iq * cfg.block_q
    q_hi = q_lo + cfg.block_q - 1
    k_lo = ik * cfg.block_kv
    k_hi = k_lo + cfg.block_kv - 1
    if qoff is not None:
        q_lo, q_hi = q_lo + qoff, q_hi + qoff
        k_lo, k_hi = k_lo + koff, k_hi + koff
    skip = jnp.asarray(False)
    if cfg.causal:
        skip |= k_lo > q_hi
    if cfg.window is not None:
        skip |= k_hi <= q_lo - cfg.window
    return skip


def _read_segs(cfg: _FlashConfig, qseg_ref, kseg_ref):
    if not cfg.has_segments:
        return None, None
    # q segs ride a [B, T, 1] column buffer; kv segs a [B, 1, T] row one —
    # singleton minor/second-minor dims are tiling-legal (block == array dim)
    q_seg = qseg_ref[0, :, :]  # [bq, 1]
    k_seg = kseg_ref[0, :, :]  # [1, bkv]
    return q_seg, k_seg


def _read_offsets(cfg: _FlashConfig, refs):
    """Split off the leading SMEM offsets ref when positions are in use."""
    if not cfg.has_positions:
        return None, None, refs
    offs_ref, *rest = refs
    return offs_ref[0], offs_ref[1], tuple(rest)


def _fwd_kernel(*refs, cfg: _FlashConfig):
    qoff, koff, refs = _read_offsets(cfg, refs)
    if cfg.has_segments:
        q_ref, k_ref, v_ref, qseg_ref, kseg_ref = refs[:5]
        o_ref, lse_ref, m_ref, l_ref, acc_ref = refs[5:]
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref = refs
        qseg_ref = kseg_ref = None
    iq, ik = pl.program_id(2), pl.program_id(3)
    n_kv = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_BIG)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(jnp.logical_not(_skip_block(cfg, iq, ik, qoff, koff)))
    def _compute():
        q = q_ref[0, 0, :, :].astype(jnp.float32)
        k = k_ref[0, 0, :, :].astype(jnp.float32)
        v = v_ref[0, 0, :, :].astype(jnp.float32)
        q_seg, k_seg = _read_segs(cfg, qseg_ref, kseg_ref)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * cfg.scale
        s = _mask_block(s, cfg, iq, ik, q_seg, k_seg, qoff, koff)

        m_prev = m_ref[:, :1]
        m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_ref[:, :1] + p.sum(axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot(
            p, v, preferred_element_type=jnp.float32
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ik == n_kv - 1)
    def _finalize():
        m = m_ref[:, :1]
        l = l_ref[:, :1]
        o_ref[0, 0, :, :] = (acc_ref[:] / jnp.maximum(l, 1e-30)).astype(
            o_ref.dtype
        )
        lse_ref[0, 0, :, :] = m + jnp.log(jnp.maximum(l, 1e-30))


def _bwd_dq_kernel(*refs, cfg: _FlashConfig):
    qoff, koff, refs = _read_offsets(cfg, refs)
    if cfg.has_segments:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qseg_ref, kseg_ref = refs[:8]
        dq_ref, dq_acc = refs[8:]
    else:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc = refs
        qseg_ref = kseg_ref = None
    iq, ik = pl.program_id(2), pl.program_id(3)
    n_kv = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    @pl.when(jnp.logical_not(_skip_block(cfg, iq, ik, qoff, koff)))
    def _compute():
        q = q_ref[0, 0, :, :].astype(jnp.float32)
        k = k_ref[0, 0, :, :].astype(jnp.float32)
        v = v_ref[0, 0, :, :].astype(jnp.float32)
        do = do_ref[0, 0, :, :].astype(jnp.float32)
        lse = lse_ref[0, 0, :, :]  # [bq, 1]
        delta = delta_ref[0, 0, :, :]  # [bq, 1]
        q_seg, k_seg = _read_segs(cfg, qseg_ref, kseg_ref)

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * cfg.scale
        s = _mask_block(s, cfg, iq, ik, q_seg, k_seg, qoff, koff)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * cfg.scale
        dq_acc[:] += jax.lax.dot(ds, k, preferred_element_type=jnp.float32)

    @pl.when(ik == n_kv - 1)
    def _finalize():
        dq_ref[0, 0, :, :] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, cfg: _FlashConfig, n_q_blocks: int):
    qoff, koff, refs = _read_offsets(cfg, refs)
    if cfg.has_segments:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qseg_ref, kseg_ref = refs[:8]
        dk_ref, dv_ref, dk_acc, dv_acc = refs[8:]
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = refs
        qseg_ref = kseg_ref = None
    ik, inner = pl.program_id(2), pl.program_id(3)
    n_inner = pl.num_programs(3)
    iq = inner % n_q_blocks

    @pl.when(inner == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    @pl.when(jnp.logical_not(_skip_block(cfg, iq, ik, qoff, koff)))
    def _compute():
        q = q_ref[0, 0, :, :].astype(jnp.float32)
        k = k_ref[0, 0, :, :].astype(jnp.float32)
        v = v_ref[0, 0, :, :].astype(jnp.float32)
        do = do_ref[0, 0, :, :].astype(jnp.float32)
        lse = lse_ref[0, 0, :, :]
        delta = delta_ref[0, 0, :, :]
        q_seg, k_seg = _read_segs(cfg, qseg_ref, kseg_ref)

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * cfg.scale
        s = _mask_block(s, cfg, iq, ik, q_seg, k_seg, qoff, koff)
        p = jnp.exp(s - lse)
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * cfg.scale
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(inner == n_inner - 1)
    def _finalize():
        dk_ref[0, 0, :, :] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0, :, :] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_fused_kernel(*refs, cfg: _FlashConfig, n_q_blocks: int):
    """One-pass backward: dq, dk and dv from a single logit recompute.

    Same grid as the dkv kernel — (b, hkv, kv-block, g·q-block) — but the
    [bq, bkv] logit block, its mask and the ds term are computed ONCE per
    (q, kv) pair instead of once in each of the two split kernels (~20%
    of the backward's matmul work saved, plus q/k/v/do read once). The
    price: dq accumulates across the kv grid dim in a full-[g·Tq, d]
    fp32 VMEM scratch and the dq output block stays resident per
    (b, hkv), so this variant is gated on those fitting VMEM
    (_fused_bwd_fits)."""
    qoff, koff, refs = _read_offsets(cfg, refs)
    if cfg.has_segments:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qseg_ref, kseg_ref = refs[:8]
        dq_ref, dk_ref, dv_ref, dq_acc, dk_acc, dv_acc = refs[8:]
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dq_ref, dk_ref, dv_ref, dq_acc, dk_acc, dv_acc) = refs
        qseg_ref = kseg_ref = None
    ik, inner = pl.program_id(2), pl.program_id(3)
    n_kv = pl.num_programs(2)
    n_inner = pl.num_programs(3)
    iq = inner % n_q_blocks
    ig = inner // n_q_blocks

    @pl.when(jnp.logical_and(ik == 0, inner == 0))
    def _init_dq():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    @pl.when(inner == 0)
    def _init_dkv():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    @pl.when(jnp.logical_not(_skip_block(cfg, iq, ik, qoff, koff)))
    def _compute():
        q = q_ref[0, 0, :, :].astype(jnp.float32)
        k = k_ref[0, 0, :, :].astype(jnp.float32)
        v = v_ref[0, 0, :, :].astype(jnp.float32)
        do = do_ref[0, 0, :, :].astype(jnp.float32)
        lse = lse_ref[0, 0, :, :]
        delta = delta_ref[0, 0, :, :]
        q_seg, k_seg = _read_segs(cfg, qseg_ref, kseg_ref)

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * cfg.scale
        s = _mask_block(s, cfg, iq, ik, q_seg, k_seg, qoff, koff)
        p = jnp.exp(s - lse)
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * cfg.scale
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        row0 = (ig * n_q_blocks + iq) * cfg.block_q
        rows = pl.ds(row0, cfg.block_q)
        dq_acc[rows, :] += jax.lax.dot(
            ds, k, preferred_element_type=jnp.float32
        )

    @pl.when(inner == n_inner - 1)
    def _finalize_kv():
        dk_ref[0, 0, :, :] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0, :, :] = dv_acc[:].astype(dv_ref.dtype)

    @pl.when(jnp.logical_and(ik == n_kv - 1, inner == n_inner - 1))
    def _finalize_q():
        g = n_inner // n_q_blocks
        tq = n_q_blocks * cfg.block_q
        dq_ref[0, :, :, :] = (
            dq_acc[:].reshape(g, tq, dq_ref.shape[-1]).astype(dq_ref.dtype)
        )


def _pad_len(n: int, block: int) -> int:
    return (-n) % block


# VMEM budget for the fused backward's resident dq state (fp32 scratch +
# the revisited output block), leaving room for the streamed q/k/v/do
# blocks in a ~16 MB VMEM
_FUSED_BWD_VMEM_BUDGET = 10 * 1024 * 1024


def _fused_bwd_fits(g: int, tq: int, d: int, out_itemsize: int) -> bool:
    return g * tq * d * (4 + out_itemsize) <= _FUSED_BWD_VMEM_BUDGET


def fused_bwd_applies(
    *, t: int, num_heads: int, num_kv_heads: int, head_dim: int,
    itemsize: int, block_q: int = 1024,
) -> bool:
    """Would ``fused_bwd=True`` actually take the one-pass kernel for this
    shape? The SAME predicate _bwd_call gates on (padded sequence, real
    itemsize) — benches use it to mark rows where the silent fallback to
    the split kernels would otherwise fake an A/B datapoint."""
    block = _clamp_block(block_q, t)
    tq = t + _pad_len(t, block)
    return _fused_bwd_fits(num_heads // num_kv_heads, tq, head_dim, itemsize)


def _env_fused_bwd() -> bool:
    import os

    return os.environ.get("D9D_TPU_FLASH_BWD", "split") == "fused"


def _compiler_params(cfg: _FlashConfig, *, seq_kv: bool = False):
    if cfg.interpret:
        return None
    dims = ("parallel", "parallel",
            "arbitrary" if seq_kv else "parallel", "arbitrary")
    return pltpu.CompilerParams(dimension_semantics=dims)


def _seg_buffers(cfg, q_seg, kv_seg, pad_q, pad_k):
    """Column/row segment-id buffers (padded regions get sentinel ids that
    can never match a real segment or each other)."""
    if not cfg.has_segments:
        return ()
    qs = jnp.pad(q_seg, ((0, 0), (0, pad_q)), constant_values=-1)
    ks = jnp.pad(kv_seg, ((0, 0), (0, pad_k)), constant_values=-2)
    return qs[:, :, None], ks[:, None, :]


def _seg_specs(cfg, block_q_map, block_kv_map):
    if not cfg.has_segments:
        return ()
    return (
        pl.BlockSpec((1, cfg.block_q, 1), block_q_map),
        pl.BlockSpec((1, 1, cfg.block_kv), block_kv_map),
    )


def _to_bhtd(x, pad):
    """[B, T, H, D] → [B, H, T, D] (+ seq padding): blocks must keep
    (seq, head_dim) in the minor-two positions."""
    x = jnp.transpose(x, (0, 2, 1, 3))
    return jnp.pad(x, ((0, 0), (0, 0), (0, pad), (0, 0))) if pad else x


def _offs_args(cfg: _FlashConfig, offsets):
    """Leading SMEM input (spec, buffer) when positions are in use."""
    if not cfg.has_positions:
        return (), ()
    return (pl.BlockSpec(memory_space=pltpu.SMEM),), (offsets,)


def _fwd_call(cfg: _FlashConfig, q, k, v, offsets, q_seg, kv_seg):
    """Raw forward kernel invocation: ``(o [B,T,H,D], lse [B,H,T])``,
    no sink correction."""
    b, t, h, d = q.shape
    _, s, hkv, _ = k.shape
    g = h // hkv
    pad_q, pad_k = _pad_len(t, cfg.block_q), _pad_len(s, cfg.block_kv)
    tq, tk = t + pad_q, s + pad_k
    n_q, n_kv = tq // cfg.block_q, tk // cfg.block_kv

    qp, kp, vp = _to_bhtd(q, pad_q), _to_bhtd(k, pad_k), _to_bhtd(v, pad_k)
    offs_specs, offs_bufs = _offs_args(cfg, offsets)

    grid = (b, h, n_q, n_kv)
    kernel = functools.partial(_fwd_kernel, cfg=cfg)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            *offs_specs,
            pl.BlockSpec((1, 1, cfg.block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, cfg.block_kv, d),
                         lambda bi, hi, qi, ki, g=g: (bi, hi // g, ki, 0)),
            pl.BlockSpec((1, 1, cfg.block_kv, d),
                         lambda bi, hi, qi, ki, g=g: (bi, hi // g, ki, 0)),
            *_seg_specs(
                cfg,
                lambda bi, hi, qi, ki: (bi, qi, 0),
                lambda bi, hi, qi, ki: (bi, 0, ki),
            ),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, cfg.block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, cfg.block_q, 1),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, tq, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, tq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((cfg.block_q, LANES), jnp.float32),
            pltpu.VMEM((cfg.block_q, LANES), jnp.float32),
            pltpu.VMEM((cfg.block_q, d), jnp.float32),
        ],
        compiler_params=_compiler_params(cfg),
        interpret=cfg.interpret,
    )(*offs_bufs, qp, kp, vp,
      *_seg_buffers(cfg, q_seg, kv_seg, pad_q, pad_k))

    o = o[:, :, :t]
    lse = lse[:, :, :t, 0]  # [B, H, T]
    o = jnp.transpose(o, (0, 2, 1, 3))  # back to [B, T, H, D]
    return o, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash(cfg: _FlashConfig, q, k, v, sinks, q_seg, kv_seg):
    o, _ = _flash_fwd(cfg, q, k, v, sinks, q_seg, kv_seg)
    return o


def _flash_fwd(cfg: _FlashConfig, q, k, v, sinks, q_seg, kv_seg):
    o, lse = _fwd_call(cfg, q, k, v, None, q_seg, kv_seg)
    if cfg.has_sinks:
        # sink joins only the denominator: l' = l + exp(sink - m), so
        # o' = o / (1 + exp(sink - lse)) and lse' = lse + log1p(same).
        z = jnp.clip(sinks.astype(jnp.float32)[None, :, None] - lse, max=60.0)
        corr = jnp.exp(z)  # [B, H, T]
        inv = (1.0 / (1.0 + corr)).transpose(0, 2, 1)[..., None]  # [B,T,H,1]
        o = (o.astype(jnp.float32) * inv).astype(o.dtype)
        lse = lse + jnp.log1p(corr)
    return o, (q, k, v, sinks, q_seg, kv_seg, o, lse)


def _bwd_call(cfg: _FlashConfig, q, k, v, do, lse, delta, offsets, q_seg, kv_seg):
    """Raw backward kernel invocations: ``(dq, dk, dv)`` in [B,T,H,D].

    ``delta`` is the per-row correction the kernels subtract inside
    ``ds = p · (dp − delta) · scale`` — pass ``rowsum(dO⊙O)`` for a plain
    output cotangent, or ``rowsum(dO⊙O) − dlse`` when an lse cotangent is
    in play (∂lse/∂s = p, so it folds into the same term)."""
    b, t, h, d = q.shape
    _, s, hkv, _ = k.shape
    g = h // hkv
    pad_q, pad_k = _pad_len(t, cfg.block_q), _pad_len(s, cfg.block_kv)
    tq, tk = t + pad_q, s + pad_k
    n_q, n_kv = tq // cfg.block_q, tk // cfg.block_kv
    offs_specs, offs_bufs = _offs_args(cfg, offsets)

    def col(x, pad):  # [B, H, T] → padded [B, H, Tq, 1]
        x = jnp.pad(x, ((0, 0), (0, 0), (0, pad))) if pad else x
        return x[..., None]

    qp, kp, vp = _to_bhtd(q, pad_q), _to_bhtd(k, pad_k), _to_bhtd(v, pad_k)
    dop = _to_bhtd(do, pad_q)
    lsep, deltap = col(lse, pad_q), col(delta, pad_q)
    segs = _seg_buffers(cfg, q_seg, kv_seg, pad_q, pad_k)

    # grid: (b, hkv, kv-block, g·q-block) — q heads and q blocks share the
    # inner sequential dim so dk/dv accumulate across both
    q_gather = pl.BlockSpec(
        (1, 1, cfg.block_q, d),
        lambda bi, hi, ki, t_, n=n_q, g=g: (bi, hi * g + t_ // n, t_ % n, 0),
    )
    col_gather = pl.BlockSpec(
        (1, 1, cfg.block_q, 1),
        lambda bi, hi, ki, t_, n=n_q, g=g: (bi, hi * g + t_ // n, t_ % n, 0),
    )
    kv_self = pl.BlockSpec((1, 1, cfg.block_kv, d),
                           lambda bi, hi, ki, t_: (bi, hi, ki, 0))
    seg_specs_kv = _seg_specs(
        cfg,
        lambda bi, hi, ki, t_, n=n_q: (bi, t_ % n, 0),
        lambda bi, hi, ki, t_: (bi, 0, ki),
    )

    if cfg.fused_bwd and _fused_bwd_fits(g, tq, d, q.dtype.itemsize):
        # dq block (1, g, tq, d) at a fixed index per (b, hkv): stays
        # resident across the whole kv×q sweep while the scratch
        # accumulates, written once at the last step
        dq_out = pl.BlockSpec(
            (1, g, tq, d), lambda bi, hi, ki, t_: (bi, hi, 0, 0)
        )
        dq, dk, dv = pl.pallas_call(
            functools.partial(_bwd_fused_kernel, cfg=cfg, n_q_blocks=n_q),
            grid=(b, hkv, n_kv, g * n_q),
            in_specs=[
                *offs_specs,
                q_gather, kv_self, kv_self, q_gather, col_gather,
                col_gather, *seg_specs_kv,
            ],
            out_specs=[dq_out, kv_self, kv_self],
            out_shape=[
                jax.ShapeDtypeStruct((b, h, tq, d), q.dtype),
                jax.ShapeDtypeStruct((b, hkv, tk, d), k.dtype),
                jax.ShapeDtypeStruct((b, hkv, tk, d), v.dtype),
            ],
            scratch_shapes=[
                pltpu.VMEM((g * tq, d), jnp.float32),
                pltpu.VMEM((cfg.block_kv, d), jnp.float32),
                pltpu.VMEM((cfg.block_kv, d), jnp.float32),
            ],
            compiler_params=_compiler_params(cfg, seq_kv=True),
            interpret=cfg.interpret,
        )(*offs_bufs, qp, kp, vp, dop, lsep, deltap, *segs)
        dq = jnp.transpose(dq[:, :, :t], (0, 2, 1, 3))
        dk = jnp.transpose(dk[:, :, :s], (0, 2, 1, 3))
        dv = jnp.transpose(dv[:, :, :s], (0, 2, 1, 3))
        return dq, dk, dv


    q_like = pl.BlockSpec((1, 1, cfg.block_q, d),
                          lambda bi, hi, qi, ki: (bi, hi, qi, 0))
    kv_like = pl.BlockSpec((1, 1, cfg.block_kv, d),
                           lambda bi, hi, qi, ki, g=g: (bi, hi // g, ki, 0))
    col_like = pl.BlockSpec((1, 1, cfg.block_q, 1),
                            lambda bi, hi, qi, ki: (bi, hi, qi, 0))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, cfg=cfg),
        grid=(b, h, n_q, n_kv),
        in_specs=[
            *offs_specs,
            q_like, kv_like, kv_like, q_like, col_like, col_like,
            *_seg_specs(
                cfg,
                lambda bi, hi, qi, ki: (bi, qi, 0),
                lambda bi, hi, qi, ki: (bi, 0, ki),
            ),
        ],
        out_specs=q_like,
        out_shape=jax.ShapeDtypeStruct((b, h, tq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((cfg.block_q, d), jnp.float32)],
        compiler_params=_compiler_params(cfg),
        interpret=cfg.interpret,
    )(*offs_bufs, qp, kp, vp, dop, lsep, deltap, *segs)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, cfg=cfg, n_q_blocks=n_q),
        grid=(b, hkv, n_kv, g * n_q),
        in_specs=[
            *offs_specs,
            q_gather, kv_self, kv_self, q_gather, col_gather, col_gather,
            *seg_specs_kv,
        ],
        out_specs=[kv_self, kv_self],
        out_shape=[
            jax.ShapeDtypeStruct((b, hkv, tk, d), k.dtype),
            jax.ShapeDtypeStruct((b, hkv, tk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((cfg.block_kv, d), jnp.float32),
            pltpu.VMEM((cfg.block_kv, d), jnp.float32),
        ],
        compiler_params=_compiler_params(cfg),
        interpret=cfg.interpret,
    )(*offs_bufs, qp, kp, vp, dop, lsep, deltap, *segs)

    dq = jnp.transpose(dq[:, :, :t], (0, 2, 1, 3))
    dk = jnp.transpose(dk[:, :, :s], (0, 2, 1, 3))
    dv = jnp.transpose(dv[:, :, :s], (0, 2, 1, 3))
    return dq, dk, dv


def _flash_bwd(cfg: _FlashConfig, residuals, do):
    q, k, v, sinks, q_seg, kv_seg, o, lse = residuals

    # Δ = rowsum(dO ⊙ O) per (b, h, t); O was saved by the forward.
    delta = jnp.einsum(
        "bthd,bthd->bht", do.astype(jnp.float32), o.astype(jnp.float32)
    )
    dq, dk, dv = _bwd_call(cfg, q, k, v, do, lse, delta, None, q_seg, kv_seg)

    if cfg.has_sinks:
        # p_sink[b,h,t] = exp(sink_h - lse); dsink = -Σ p_sink · Δ
        p_sink = jnp.exp(
            jnp.clip(sinks.astype(jnp.float32)[None, :, None] - lse, max=60.0)
        )
        dsinks = -(p_sink * delta).sum(axis=(0, 2)).astype(sinks.dtype)
    else:
        dsinks = jnp.zeros_like(sinks)
    return dq, dk, dv, dsinks, _zero_cotangent(q_seg), _zero_cotangent(kv_seg)


def _zero_cotangent(x):
    """Zero cotangent matching JAX's expectations: float0 for int arrays."""
    if x is None:
        return None
    if jnp.issubdtype(x.dtype, jnp.floating):
        return jnp.zeros_like(x)
    import numpy as np

    return np.zeros(x.shape, dtype=jax.dtypes.float0)


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash_ol(cfg: _FlashConfig, q, k, v, offsets, q_seg, kv_seg):
    """Flash block returning ``(o, lse)`` — the composable form ring
    attention stitches across devices. Differentiable in BOTH outputs:
    the lse cotangent from the downstream combine folds into the existing
    backward kernels through the delta term (see :func:`_bwd_call`)."""
    return _fwd_call(cfg, q, k, v, offsets, q_seg, kv_seg)


def _flash_ol_fwd(cfg: _FlashConfig, q, k, v, offsets, q_seg, kv_seg):
    o, lse = _fwd_call(cfg, q, k, v, offsets, q_seg, kv_seg)
    return (o, lse), (q, k, v, offsets, q_seg, kv_seg, o, lse)


def _flash_ol_bwd(cfg: _FlashConfig, residuals, cotangents):
    q, k, v, offsets, q_seg, kv_seg, o, lse = residuals
    do, dlse = cotangents
    delta = jnp.einsum(
        "bthd,bthd->bht", do.astype(jnp.float32), o.astype(jnp.float32)
    ) - dlse.astype(jnp.float32)
    dq, dk, dv = _bwd_call(cfg, q, k, v, do, lse, delta, offsets, q_seg, kv_seg)
    return (dq, dk, dv, _zero_cotangent(offsets),
            _zero_cotangent(q_seg), _zero_cotangent(kv_seg))


_flash_ol.defvjp(_flash_ol_fwd, _flash_ol_bwd)


def _clamp_block(block: int, n: int) -> int:
    return min(block, max(8, 2 ** math.ceil(math.log2(max(n, 1)))))


def combine_attention_chunks(
    o: Array, lse: Array, o_new: Array, lse_new: Array
) -> tuple[Array, Array]:
    """Merge two normalized partial attention results ``(o [B,T,H,D],
    lse [B,H,T])`` over disjoint key sets into one — the logsumexp combine
    every :func:`flash_attention_block` consumer (ring steps, chunked
    simulations) must apply. Accumulates in fp32."""
    merged = jnp.logaddexp(lse, lse_new)
    w0 = jnp.exp(lse - merged).transpose(0, 2, 1)[..., None]
    w1 = jnp.exp(lse_new - merged).transpose(0, 2, 1)[..., None]
    out = o.astype(jnp.float32) * w0 + o_new.astype(jnp.float32) * w1
    return out, merged


def flash_attention_block(
    q: Array,
    k: Array,
    v: Array,
    *,
    q_offset: Array | int,
    k_offset: Array | int,
    causal: bool = True,
    softmax_scale: float | None = None,
    window_size: int | None = None,
    q_segments: Array | None = None,
    kv_segments: Array | None = None,
    block_q: int = 1024,
    block_kv: int = 512,
    interpret: bool | None = None,
    fused_bwd: bool | None = None,
) -> tuple[Array, Array]:
    """One flash-attention block at arbitrary global offsets → ``(o, lse)``.

    ``q [B,T,Hq,D]`` attends ``k/v [B,S,Hkv,D]`` as if the q rows sat at
    global positions ``q_offset + [0,T)`` and the keys at
    ``k_offset + [0,S)`` (offsets may be traced, e.g. derived from
    ``lax.axis_index`` inside shard_map). Causal/window masking uses those
    global positions; blocks wholly outside them are skipped on the fly.
    Rows with no visible key come back as ``o=garbage, lse≈-1e30`` — a
    downstream logsumexp-combine weighs them to zero.

    This is the per-ring-step primitive: combine partial results with
    ``new_lse = logaddexp(lse_a, lse_b)`` and
    ``o = exp(lse_a-new_lse)·o_a + exp(lse_b-new_lse)·o_b``. Both outputs
    are differentiable (reference treats attention as always-flash —
    d9d/kernel/flash_attn/function.py:331; this brings the CP ring to the
    same bar).
    """
    t, s, d = q.shape[1], k.shape[1], q.shape[-1]
    if (q_segments is None) != (kv_segments is None):
        raise ValueError("q_segments and kv_segments must be provided together")
    if fused_bwd is None:
        fused_bwd = _env_fused_bwd()
    cfg = _FlashConfig(
        causal=causal,
        scale=softmax_scale if softmax_scale is not None else d**-0.5,
        window=window_size,
        has_sinks=False,
        has_segments=q_segments is not None,
        block_q=_clamp_block(block_q, t),
        block_kv=_clamp_block(block_kv, s),
        seq_len=s,
        interpret=(jax.default_backend() != "tpu"
                   if interpret is None else interpret),
        has_positions=True,
        fused_bwd=fused_bwd,
    )
    offsets = jnp.stack(
        [jnp.asarray(q_offset, jnp.int32), jnp.asarray(k_offset, jnp.int32)]
    )
    return _flash_ol(cfg, q, k, v, offsets, q_segments, kv_segments)


def make_pallas_flash_sdpa(
    block_q: int = 1024,
    block_kv: int = 512,
    fused_bwd: bool | None = None,
):
    """Build an SdpaBackend backed by the Pallas flash kernel.

    Default block sizes follow the r3 on-chip sweep (tools/bench_kernels.py,
    BASELINE.md): 1024x512 won fwd+bwd at every swept shape (t=2048/8192
    d=64, t=4096 d=128) over 512x512 and the smaller tilings; blocks are
    clamped to the padded sequence length below, so small inputs are
    unaffected.

    ``fused_bwd`` selects the one-pass backward (dq+dk+dv from a single
    logit recompute, ~20% fewer backward matmul FLOPs at the cost of a
    resident dq VMEM state — see :func:`_bwd_fused_kernel`). ``None``
    reads ``D9D_TPU_FLASH_BWD`` (``fused``/``split``); default split, the
    r3-measured configuration, until the fused variant is swept on chip.
    """
    if fused_bwd is None:
        fused_bwd = _env_fused_bwd()

    def sdpa(
        q: Array,
        k: Array,
        v: Array,
        *,
        causal: bool = True,
        softmax_scale: float | None = None,
        window_size: int | None = None,
        sinks: Array | None = None,
        mask: Array | None = None,
        q_segments: Array | None = None,
        kv_segments: Array | None = None,
    ) -> Array:
        if mask is not None or q.shape[1] != k.shape[1]:
            from d9d_tpu.ops.attention.eager import eager_sdpa

            return eager_sdpa(
                q, k, v, causal=causal, softmax_scale=softmax_scale,
                window_size=window_size, sinks=sinks, mask=mask,
                q_segments=q_segments, kv_segments=kv_segments,
            )
        if (q_segments is None) != (kv_segments is None):
            raise ValueError(
                "q_segments and kv_segments must be provided together"
            )
        t = q.shape[1]
        d = q.shape[-1]
        cfg = _FlashConfig(
            causal=causal,
            scale=softmax_scale if softmax_scale is not None else d**-0.5,
            window=window_size,
            has_sinks=sinks is not None,
            has_segments=q_segments is not None,
            block_q=_clamp_block(block_q, t),
            block_kv=_clamp_block(block_kv, t),
            seq_len=t,
            interpret=jax.default_backend() != "tpu",
            fused_bwd=fused_bwd,
        )
        sinks_arr = (
            sinks if sinks is not None else jnp.zeros((q.shape[2],), jnp.float32)
        )
        return _flash(cfg, q, k, v, sinks_arr, q_segments, kv_segments)

    return sdpa
