"""Reference (XLA "eager") scaled-dot-product attention.

Feature-parity target of the reference's eager SDPA backend
(d9d/module/block/attention/sdpa/impl/eager.py:9): GQA head broadcasting,
causal masking, sliding window, learnable attention sinks, and explicit
boolean masks — all in one fp32-softmax implementation. This is the
correctness oracle the Pallas flash kernel is tested against, and the
fallback for platforms without Pallas support.

Shape convention is flash-style ``[batch, seq, heads, head_dim]``.
"""

import jax.numpy as jnp

from d9d_tpu.core.types import Array

NEG_INF = float("-inf")


def eager_sdpa(
    q: Array,
    k: Array,
    v: Array,
    *,
    causal: bool = True,
    softmax_scale: float | None = None,
    window_size: int | None = None,
    sinks: Array | None = None,
    mask: Array | None = None,
    q_segments: Array | None = None,
    kv_segments: Array | None = None,
) -> Array:
    """Attention over ``q [B,T,Hq,D]``, ``k/v [B,S,Hkv,D]`` → ``[B,T,Hq,Dv]``.

    - GQA: ``Hq`` must be a multiple of ``Hkv``; kv heads are broadcast.
    - ``window_size``: each query attends to keys in ``(pos-window, pos]``.
    - ``sinks [Hq]``: learnable per-head sink logits joining the softmax
      denominator (attention-sink stabilization; reference
      kernel/flash_attn/function.py:34 handles the analytic dsink — here
      autodiff derives it for free).
    - ``mask``: boolean, broadcastable to ``[B, Hq, T, S]``; True = attend.
    - ``q_segments [B,T]`` / ``kv_segments [B,S]``: packed-sequence ids
      (varlen equivalent — reference flash_attn_varlen_func,
      kernel/flash_attn/function.py:384); attention only within equal ids.
    """
    b, t, hq, d = q.shape
    _, s, hkv, dv = v.shape
    if hq % hkv != 0:
        raise ValueError(f"query heads {hq} not a multiple of kv heads {hkv}")
    g = hq // hkv

    scale = softmax_scale if softmax_scale is not None else d**-0.5

    qf = q.astype(jnp.float32).reshape(b, t, hkv, g, d)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)

    # [B, Hkv, G, T, S]
    logits = jnp.einsum("bthgd,bshd->bhgts", qf, kf) * scale

    neg = jnp.asarray(NEG_INF, logits.dtype)
    q_pos = jnp.arange(t)[:, None] + (s - t)  # align last query with last key
    k_pos = jnp.arange(s)[None, :]
    if causal:
        logits = jnp.where(k_pos <= q_pos, logits, neg)
    if window_size is not None:
        logits = jnp.where(k_pos > q_pos - window_size, logits, neg)
    if mask is not None:
        m = jnp.broadcast_to(mask, (b, hq, t, s)).reshape(b, hkv, g, t, s)
        logits = jnp.where(m, logits, neg)
    if (q_segments is None) != (kv_segments is None):
        raise ValueError("q_segments and kv_segments must be provided together")
    if q_segments is not None:
        seg = q_segments[:, None, None, :, None] == kv_segments[:, None, None, None, :]
        logits = jnp.where(seg, logits, neg)

    if sinks is not None:
        sink = jnp.broadcast_to(
            sinks.astype(jnp.float32).reshape(1, hkv, g, 1, 1), (b, hkv, g, t, 1)
        )
        logits = jnp.concatenate([logits, sink], axis=-1)

    # stable softmax; rows that are fully masked produce zeros, not NaN
    m_max = jnp.max(logits, axis=-1, keepdims=True)
    m_max = jnp.where(jnp.isfinite(m_max), m_max, 0.0)
    unnorm = jnp.exp(logits - m_max)
    denom = jnp.sum(unnorm, axis=-1, keepdims=True)
    probs = unnorm / jnp.maximum(denom, 1e-30)

    if sinks is not None:
        probs = probs[..., :-1]

    out = jnp.einsum("bhgts,bshd->bthgd", probs, vf)
    return out.reshape(b, t, hq, dv).astype(q.dtype)
