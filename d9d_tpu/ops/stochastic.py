"""Stochastic rounding fp32 -> bf16.

TPU equivalent of the reference Triton stochastic-rounding kernels
(d9d/kernel/stochastic/adamw_step.py:97, copy.py:34, ops/round.py): add
uniform random bits below the bf16 mantissa cut and truncate, so the
expected value of the rounded number equals the fp32 input. Used by the
StochasticAdamW optimizer to train directly in bf16 without fp32 master
weights.

Two implementations with identical semantics:

- :func:`stochastic_round_to_bf16` — pure jnp bit-twiddling on
  ``bitcast_convert_type``; XLA fuses it into the surrounding optimizer
  arithmetic, which is usually enough because the op is bandwidth-bound.
- :func:`stochastic_round_to_bf16_pallas` — Pallas TPU kernel using the
  on-chip PRNG (``pltpu.prng_random_bits``), avoiding the cost of
  materializing a jax.random key block.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from d9d_tpu.core.types import Array

_MANTISSA_MASK = 0xFFFF  # bits dropped when truncating fp32 -> bf16
_BF16_MASK = 0xFFFF0000


def _sr_bits(x_bits: Array, rand_bits: Array) -> Array:
    """Core rounding rule on uint32 views: add 16 random low bits, truncate."""
    rnd = rand_bits & jnp.uint32(_MANTISSA_MASK)
    return (x_bits + rnd) & jnp.uint32(_BF16_MASK)


def stochastic_round_to_bf16(x: Array, key: jax.Array) -> Array:
    """Stochastically round ``x`` (any float dtype) to bfloat16.

    E[result] == x exactly (the two candidate bf16 neighbours are chosen
    with probability proportional to proximity). Non-finite values pass
    through deterministic casting.
    """
    xf = x.astype(jnp.float32)
    bits = jax.lax.bitcast_convert_type(xf, jnp.uint32)
    rand = jax.random.bits(key, x.shape, jnp.uint32)
    out = jax.lax.bitcast_convert_type(_sr_bits(bits, rand), jnp.float32)
    return jnp.where(jnp.isfinite(xf), out, xf).astype(jnp.bfloat16)


_LANES = 128
_BLOCK_ROWS = 256


def _sr_kernel(seed_ref, x_ref, out_ref):
    # distinct stream per grid block: hash the block id into the seed
    pltpu.prng_seed(seed_ref[0], pl.program_id(0))
    xf = x_ref[...]
    bits = pltpu.bitcast(xf, jnp.uint32)
    rand = pltpu.bitcast(pltpu.prng_random_bits(xf.shape), jnp.uint32)
    out = pltpu.bitcast(_sr_bits(bits, rand), jnp.float32)
    out_ref[...] = jnp.where(jnp.isfinite(xf), out, xf).astype(jnp.bfloat16)


# d9d-lint: disable=D9D001 — standalone-use decorator; the optimizer traces this inside its tracked update program
@functools.partial(jax.jit, static_argnames=("interpret",))
def stochastic_round_to_bf16_pallas(
    x: Array, seed: Array, *, interpret: bool = False
) -> Array:
    """Pallas TPU stochastic rounding driven by the on-chip PRNG.

    ``seed`` is a scalar int32; reuse across calls yields identical noise,
    so callers should fold the step counter in. The input is processed as
    (rows, 128) VMEM blocks over a 1-D grid.
    """
    n = x.size
    cols = _LANES
    rows = -(-n // cols)
    pad_rows = -(-rows // _BLOCK_ROWS) * _BLOCK_ROWS
    flat = jnp.pad(x.astype(jnp.float32).reshape(-1), (0, pad_rows * cols - n))
    tiled = flat.reshape(pad_rows, cols)

    out = pl.pallas_call(
        _sr_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(pad_rows // _BLOCK_ROWS,),
            in_specs=[pl.BlockSpec((_BLOCK_ROWS, cols), lambda i, seed: (i, 0))],
            out_specs=pl.BlockSpec((_BLOCK_ROWS, cols), lambda i, seed: (i, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((pad_rows, cols), jnp.bfloat16),
        interpret=interpret,
    )(seed.reshape(1).astype(jnp.int32), tiled)
    return out.reshape(-1)[:n].reshape(x.shape)
