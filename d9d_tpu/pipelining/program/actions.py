"""Immutable pipeline action algebra.

Reference: d9d/pipelining/runtime/action.py:80-335 — the reference compiles
every schedule to a ``dict[rank, list[Action]]`` program interpreted by a
dumb executor VM. That design is backend-agnostic and carries over to TPU
unchanged; only the executor's communication primitive differs (the
reference batches NCCL ``isend/irecv``; the TPU runtime moves arrays
between stage device groups with ``jax.device_put`` under a single
controller, letting XLA/ICI overlap transfers with compute).

Action vocabulary (mirroring action.py):
- ``ForwardCompute``       — run stage forward for one microbatch
- ``BackwardFull``         — fused dI+dW backward
- ``BackwardInput``        — input-only backward (zero-bubble split, "B")
- ``BackwardWeight``       — weight-only backward (zero-bubble split, "W")
- ``ForwardSend/Recv``     — activation transfer stage → stage+1
- ``BackwardSend/Recv``    — cotangent transfer stage → stage-1
- ``Compose``              — execute several actions as one overlapped slot
  (DualPipeV's joint forward+backward block)
"""

import dataclasses
from typing import Union

__all__ = [
    "Action",
    "BackwardFull",
    "BackwardInput",
    "BackwardRecv",
    "BackwardSend",
    "BackwardWeight",
    "Compose",
    "ComputeAction",
    "ForwardCompute",
    "ForwardRecv",
    "ForwardSend",
    "PipelineProgram",
    "format_program",
]


@dataclasses.dataclass(frozen=True)
class _StageMicrobatch:
    """Every primitive action is addressed by (global stage id, microbatch)."""

    stage: int
    microbatch: int

    def __post_init__(self) -> None:
        if self.stage < 0 or self.microbatch < 0:
            raise ValueError(f"invalid action address {self}")


class ForwardCompute(_StageMicrobatch):
    def __str__(self) -> str:
        return f"F{self.stage}.{self.microbatch}"


class BackwardFull(_StageMicrobatch):
    """Fused backward: produces both input-grad and weight-grad."""

    def __str__(self) -> str:
        return f"B{self.stage}.{self.microbatch}"


class BackwardInput(_StageMicrobatch):
    """Input-only backward (zero-bubble 'B'); weight grad deferred."""

    def __str__(self) -> str:
        return f"I{self.stage}.{self.microbatch}"


class BackwardWeight(_StageMicrobatch):
    """Deferred weight-only backward (zero-bubble 'W')."""

    def __str__(self) -> str:
        return f"W{self.stage}.{self.microbatch}"


class ForwardSend(_StageMicrobatch):
    """Send ``stage``'s forward output for ``microbatch`` to stage+1's rank."""

    def __str__(self) -> str:
        return f"FS{self.stage}.{self.microbatch}"


class ForwardRecv(_StageMicrobatch):
    """Receive ``stage``'s forward *input* for ``microbatch`` (from stage-1)."""

    def __str__(self) -> str:
        return f"FR{self.stage}.{self.microbatch}"


class BackwardSend(_StageMicrobatch):
    """Send grad w.r.t. ``stage``'s input for ``microbatch`` to stage-1's rank."""

    def __str__(self) -> str:
        return f"BS{self.stage}.{self.microbatch}"


class BackwardRecv(_StageMicrobatch):
    """Receive grad w.r.t. ``stage``'s *output* for ``microbatch`` (from stage+1)."""

    def __str__(self) -> str:
        return f"BR{self.stage}.{self.microbatch}"


@dataclasses.dataclass(frozen=True)
class Compose:
    """Several actions executed as one schedule slot (overlap bundle).

    Reference ComposeAction (action.py:300-335): DualPipeV issues a joint
    forward+backward block so the executor can overlap the two directions.
    """

    actions: tuple["Action", ...]

    def __str__(self) -> str:
        return "(" + "+".join(str(a) for a in self.actions) + ")"


ComputeAction = Union[ForwardCompute, BackwardFull, BackwardInput, BackwardWeight]
Action = Union[
    ForwardCompute,
    BackwardFull,
    BackwardInput,
    BackwardWeight,
    ForwardSend,
    ForwardRecv,
    BackwardSend,
    BackwardRecv,
    Compose,
]

#: A compiled schedule: per-pp-rank ordered action list.
PipelineProgram = dict[int, list[Action]]


def format_program(program: PipelineProgram) -> str:
    """Human-readable program dump (one line per rank) for tests/debugging."""
    lines = []
    for rank in sorted(program):
        lines.append(f"rank {rank}: " + " ".join(str(a) for a in program[rank]))
    return "\n".join(lines)
