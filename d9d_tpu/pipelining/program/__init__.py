from d9d_tpu.pipelining.program.actions import (
    Action,
    BackwardFull,
    BackwardInput,
    BackwardRecv,
    BackwardSend,
    BackwardWeight,
    Compose,
    ForwardCompute,
    ForwardRecv,
    ForwardSend,
    PipelineProgram,
    format_program,
)
from d9d_tpu.pipelining.program.builders import (
    DualPipeVProgramBuilder,
    GPipeProgramBuilder,
    Interleaved1F1BProgramBuilder,
    InferenceProgramBuilder,
    LoopedBFSProgramBuilder,
    ProgramBuilder,
)
from d9d_tpu.pipelining.program.builders import ZeroBubbleVProgramBuilder
from d9d_tpu.pipelining.program.communications import add_communication_ops
from d9d_tpu.pipelining.program.topology import (
    ScheduleStyle,
    ranks_to_stages,
    stage_to_rank,
)
from d9d_tpu.pipelining.program.validate import (
    SimulatedProgram,
    simulate_program,
    validate_program,
)

__all__ = [
    "Action",
    "BackwardFull",
    "BackwardInput",
    "BackwardRecv",
    "BackwardSend",
    "BackwardWeight",
    "Compose",
    "DualPipeVProgramBuilder",
    "ForwardCompute",
    "ForwardRecv",
    "ForwardSend",
    "GPipeProgramBuilder",
    "Interleaved1F1BProgramBuilder",
    "InferenceProgramBuilder",
    "LoopedBFSProgramBuilder",
    "PipelineProgram",
    "ProgramBuilder",
    "ScheduleStyle",
    "SimulatedProgram",
    "ZeroBubbleVProgramBuilder",
    "add_communication_ops",
    "format_program",
    "ranks_to_stages",
    "simulate_program",
    "stage_to_rank",
    "validate_program",
]
