"""Schedule builders: compile (pp, microbatches) → compute-only programs.

Reference: d9d/pipelining/component/program/{bfs.py:14, interleaved.py:17,
zerobubblev.py:15, dualpipev.py:18} — each builder emits per-rank ordered
compute actions; ``add_communication_ops`` then injects transfers and
``validate_program`` proves executability & completeness. Schedules:

- ``GPipeProgramBuilder``        — all-forward-then-all-backward, v=1.
- ``LoopedBFSProgramBuilder``    — breadth-first over virtual stages
  (reference bfs.py, arXiv 2211.05953 "breadth-first pipeline parallel").
- ``Interleaved1F1BProgramBuilder`` — Megatron interleaved 1F1B; with
  ``zero_bubble=True`` the ZB1P variant (arXiv 2401.10241): backward split
  into input-grad (I) actions on the critical path and deferred
  weight-grad (W) actions filling the cooldown bubble.
- ``ZeroBubbleVProgramBuilder``  — ZBV (arXiv 2401.10241 §V): V-placement
  (rank r owns stages r and 2pp-1-r) + split backward, built by a
  readiness-driven list scheduler with ZBV priorities (I > F > W).
- ``DualPipeVProgramBuilder``    — DualPipeV (DeepSeek-V3 tech report):
  V-placement with joint forward+backward ``Compose`` slots so the
  executor can overlap the two directions of different microbatches.
- ``InferenceProgramBuilder``    — forward-only.

All builders are timing *and* dependency correct; gradient exactness does
not depend on slot timing, which only affects bubble fraction on hardware.
"""

import abc

from d9d_tpu.pipelining.program.actions import (
    Action,
    BackwardFull,
    BackwardInput,
    BackwardWeight,
    Compose,
    ForwardCompute,
    PipelineProgram,
)
from d9d_tpu.pipelining.program.topology import (
    ScheduleStyle,
    ranks_to_stages,
    stage_to_rank,
)

__all__ = [
    "DualPipeVProgramBuilder",
    "GPipeProgramBuilder",
    "Interleaved1F1BProgramBuilder",
    "InferenceProgramBuilder",
    "LoopedBFSProgramBuilder",
    "ProgramBuilder",
]


class ProgramBuilder(abc.ABC):
    """Compiles a compute-only program for a fixed topology."""

    style: ScheduleStyle = ScheduleStyle.LOOP

    def __init__(self, pp: int, stages_per_rank: int = 1):
        if pp < 1 or stages_per_rank < 1:
            raise ValueError("pp and stages_per_rank must be >= 1")
        self.pp = pp
        self.stages_per_rank = stages_per_rank

    @property
    def num_stages(self) -> int:
        return self.pp * self.stages_per_rank

    @property
    def stage_owner(self) -> dict[int, int]:
        return {
            s: stage_to_rank(s, self.pp, self.style)
            for s in range(self.num_stages)
        }

    @abc.abstractmethod
    def compose(self, num_microbatches: int) -> PipelineProgram:
        """Emit the per-rank compute-only action lists."""

    def _check_microbatches(self, m: int) -> None:
        if m < 1:
            raise ValueError("num_microbatches must be >= 1")


class GPipeProgramBuilder(ProgramBuilder):
    """All forwards, then all backwards. stages_per_rank must be 1."""

    def __init__(self, pp: int, stages_per_rank: int = 1):
        if stages_per_rank != 1:
            raise ValueError("GPipe does not interleave virtual stages")
        super().__init__(pp, 1)

    def compose(self, num_microbatches: int) -> PipelineProgram:
        self._check_microbatches(num_microbatches)
        program: PipelineProgram = {}
        for r in range(self.pp):
            acts: list[Action] = [
                ForwardCompute(r, mb) for mb in range(num_microbatches)
            ]
            acts += [BackwardFull(r, mb) for mb in range(num_microbatches)]
            program[r] = acts
        return program


class InferenceProgramBuilder(ProgramBuilder):
    """Forward-only (reference factory/config.py inference schedule)."""

    def compose(self, num_microbatches: int) -> PipelineProgram:
        self._check_microbatches(num_microbatches)
        program: PipelineProgram = {}
        for r, stages in ranks_to_stages(
            self.num_stages, self.pp, self.style
        ).items():
            acts: list[Action] = []
            for s in stages:
                acts += [ForwardCompute(s, mb) for mb in range(num_microbatches)]
            program[r] = acts
        return program


class LoopedBFSProgramBuilder(ProgramBuilder):
    """Breadth-first: all microbatches through virtual stage k, then k+1."""

    def compose(self, num_microbatches: int) -> PipelineProgram:
        self._check_microbatches(num_microbatches)
        program: PipelineProgram = {}
        for r in range(self.pp):
            stages = [k * self.pp + r for k in range(self.stages_per_rank)]
            acts: list[Action] = []
            for s in stages:
                acts += [ForwardCompute(s, mb) for mb in range(num_microbatches)]
            for s in reversed(stages):
                acts += [BackwardFull(s, mb) for mb in range(num_microbatches)]
            program[r] = acts
        return program


class Interleaved1F1BProgramBuilder(ProgramBuilder):
    """Megatron interleaved 1F1B; ``zero_bubble=True`` gives ZB1P.

    With stages_per_rank == 1 this reduces to classic non-interleaved 1F1B.
    Interleaving (v > 1) requires num_microbatches % pp == 0 (the Megatron
    constraint — unit groups of pp microbatches cycle through chunks).
    """

    def __init__(self, pp: int, stages_per_rank: int = 1, zero_bubble: bool = False):
        super().__init__(pp, stages_per_rank)
        self.zero_bubble = zero_bubble

    def _unit(self, i: int, rank: int, forward: bool) -> tuple[int, int]:
        """Map work-unit index → (global stage, microbatch) for ``rank``."""
        p, v = self.pp, self.stages_per_rank
        group = i % (p * v)
        chunk = group // p
        if not forward:
            chunk = v - 1 - chunk
        mb = (i // (p * v)) * p + group % p
        return chunk * p + rank, mb

    def compose(self, num_microbatches: int) -> PipelineProgram:
        self._check_microbatches(num_microbatches)
        m, p, v = num_microbatches, self.pp, self.stages_per_rank
        if v > 1 and m % p != 0:
            raise ValueError(
                f"interleaved 1F1B needs num_microbatches % pp == 0 "
                f"(got m={m}, pp={p})"
            )
        total = m * v
        program: PipelineProgram = {}
        for r in range(p):
            if v == 1:
                warmup = min(m, p - 1 - r)
            else:
                warmup = min(total, (p - 1 - r) * 2 + (v - 1) * p)
            acts: list[Action] = []
            pending_w: list[tuple[int, int]] = []

            def bwd(stage: int, mb: int) -> list[Action]:
                if self.zero_bubble:
                    pending_w.append((stage, mb))
                    return [BackwardInput(stage, mb)]
                return [BackwardFull(stage, mb)]

            for i in range(warmup):
                acts.append(ForwardCompute(*self._unit(i, r, forward=True)))
            for i in range(total - warmup):
                acts.append(
                    ForwardCompute(*self._unit(warmup + i, r, forward=True))
                )
                acts += bwd(*self._unit(i, r, forward=False))
                # ZB1P: one deferred W fills the slot vacated by the split B
                # once the steady state is deep enough to have W work queued.
                if self.zero_bubble and len(pending_w) > p - r:
                    acts.append(BackwardWeight(*pending_w.pop(0)))
            for i in range(total - warmup, total):
                acts += bwd(*self._unit(i, r, forward=False))
                if self.zero_bubble and pending_w:
                    acts.append(BackwardWeight(*pending_w.pop(0)))
            while pending_w:
                acts.append(BackwardWeight(*pending_w.pop(0)))
            program[r] = acts
        return program


class _ReadinessScheduler:
    """Synchronous-round list scheduler over the true dependency rules.

    Builds valid programs for topologies where closed-form slot formulas
    get hairy (ZBV / DualPipeV). Each round, every rank picks its highest-
    priority ready compute action based on state at the end of the previous
    round — exactly the information a real rank would have.
    """

    def __init__(self, builder: ProgramBuilder, num_microbatches: int, split: bool):
        self.b = builder
        self.m = num_microbatches
        self.split = split
        self.owner = builder.stage_owner
        self.num_stages = builder.num_stages
        # work remaining per rank
        self.todo: dict[int, set[tuple[str, int, int]]] = {
            r: set() for r in range(builder.pp)
        }
        for s, r in self.owner.items():
            for mb in range(num_microbatches):
                self.todo[r].add(("F", s, mb))
                self.todo[r].add(("I" if split else "B", s, mb))
                if split:
                    self.todo[r].add(("W", s, mb))
        self.done: set[tuple[str, int, int]] = set()

    def _fwd_done(self, s: int, mb: int) -> bool:
        return ("F", s, mb) in self.done

    def _bwd_done(self, s: int, mb: int) -> bool:
        return ("B", s, mb) in self.done or ("I", s, mb) in self.done

    def ready(self, kind: str, s: int, mb: int) -> bool:
        if kind == "F":
            return s == 0 or self._fwd_done(s - 1, mb)
        if kind in ("B", "I"):
            if not self._fwd_done(s, mb):
                return False
            return s == self.num_stages - 1 or self._bwd_done(s + 1, mb)
        if kind == "W":
            return ("I", s, mb) in self.done
        raise ValueError(kind)

    def run(
        self, priority, compose_overlap: bool = False
    ) -> PipelineProgram:
        program: PipelineProgram = {r: [] for r in range(self.b.pp)}
        mk = {
            "F": ForwardCompute,
            "B": BackwardFull,
            "I": BackwardInput,
            "W": BackwardWeight,
        }
        while any(self.todo.values()):
            picked: dict[int, list[tuple[str, int, int]]] = {}
            for r in range(self.b.pp):
                ready = sorted(
                    (w for w in self.todo[r] if self.ready(*w)), key=priority
                )
                if not ready:
                    continue
                chosen = [ready[0]]
                if compose_overlap:
                    # pair one forward with one input-backward of different
                    # microbatches into a joint slot (DualPipeV overlap)
                    kinds = {w[0] for w in chosen}
                    for w in ready[1:]:
                        if w[0] in kinds or len(chosen) == 2:
                            continue
                        if {chosen[0][0], w[0]} == {"F", "I"}:
                            chosen.append(w)
                            break
                picked[r] = chosen
            if not picked:
                raise RuntimeError("list scheduler stalled — invalid topology")
            for r, works in picked.items():
                acts = [mk[k](s, mb) for k, s, mb in works]
                program[r].append(
                    Compose(tuple(acts)) if len(acts) > 1 else acts[0]
                )
                for w in works:
                    self.todo[r].discard(w)
                    self.done.add(w)
        return program


class ZeroBubbleVProgramBuilder(ProgramBuilder):
    """ZBV: V-shaped placement + split backward (arXiv 2401.10241)."""

    style = ScheduleStyle.V

    def __init__(self, pp: int, stages_per_rank: int = 2):
        if stages_per_rank != 2:
            raise ValueError("ZBV is defined for exactly 2 stages per rank")
        super().__init__(pp, 2)

    def compose(self, num_microbatches: int) -> PipelineProgram:
        self._check_microbatches(num_microbatches)
        sched = _ReadinessScheduler(self, num_microbatches, split=True)

        def priority(work: tuple[str, int, int]):
            kind, stage, mb = work
            # ZBV ordering: input-backwards are critical-path (rank 0 owns
            # both the first and last stage, so cotangents turn around
            # immediately); forwards next, deferred weight grads last.
            rank_order = {"I": 0, "F": 1, "W": 2}[kind]
            return (rank_order, mb, -stage)

        return sched.run(priority)


class DualPipeVProgramBuilder(ProgramBuilder):
    """DualPipeV: V placement + joint F/B overlap slots (DeepSeek-V3)."""

    style = ScheduleStyle.V

    def __init__(self, pp: int, stages_per_rank: int = 2):
        if stages_per_rank != 2:
            raise ValueError("DualPipeV is defined for exactly 2 stages per rank")
        super().__init__(pp, 2)

    def compose(self, num_microbatches: int) -> PipelineProgram:
        self._check_microbatches(num_microbatches)
        sched = _ReadinessScheduler(self, num_microbatches, split=True)

        def priority(work: tuple[str, int, int]):
            kind, stage, mb = work
            rank_order = {"I": 0, "F": 1, "W": 2}[kind]
            return (rank_order, mb, -stage)

        return sched.run(priority, compose_overlap=True)
