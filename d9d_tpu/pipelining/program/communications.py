"""Communication-injection pass: compute-only program → full program.

Reference: d9d/pipelining/component/program/communications.py
(``add_communication_ops``) — schedule builders emit compute-only per-rank
programs; this pass inserts the Send/Recv actions for every cross-rank
stage edge. The placement discipline (eager sends immediately after the
producing compute, blocking recvs immediately before the consuming
compute) is deadlock-free by construction: sends never block, and every
recv's matching send depends only on computes strictly earlier in the
stage/microbatch DAG. ``validate_program`` proves it per schedule.
"""

from d9d_tpu.pipelining.program.actions import (
    Action,
    BackwardFull,
    BackwardInput,
    BackwardRecv,
    BackwardSend,
    Compose,
    ForwardCompute,
    ForwardRecv,
    ForwardSend,
    PipelineProgram,
)

__all__ = ["add_communication_ops"]


def _edges_for(
    action: Action, num_stages: int, stage_owner: dict[int, int], rank: int
) -> tuple[list[Action], list[Action]]:
    """(recvs-before, sends-after) required by one primitive compute action."""
    before: list[Action] = []
    after: list[Action] = []
    if isinstance(action, ForwardCompute):
        s, mb = action.stage, action.microbatch
        if s > 0 and stage_owner[s - 1] != rank:
            before.append(ForwardRecv(s, mb))
        if s + 1 < num_stages and stage_owner[s + 1] != rank:
            after.append(ForwardSend(s, mb))
    elif isinstance(action, (BackwardFull, BackwardInput)):
        s, mb = action.stage, action.microbatch
        if s + 1 < num_stages and stage_owner[s + 1] != rank:
            before.append(BackwardRecv(s, mb))
        if s > 0 and stage_owner[s - 1] != rank:
            after.append(BackwardSend(s, mb))
    return before, after


def add_communication_ops(
    program: PipelineProgram,
    *,
    num_stages: int,
    stage_owner: dict[int, int],
) -> PipelineProgram:
    """Insert sends/recvs around every cross-rank compute edge."""
    out: PipelineProgram = {}
    for rank, actions in program.items():
        new: list[Action] = []
        for action in actions:
            if isinstance(action, Compose):
                befores: list[Action] = []
                afters: list[Action] = []
                for member in action.actions:
                    b, a = _edges_for(member, num_stages, stage_owner, rank)
                    befores.extend(b)
                    afters.extend(a)
                new.extend(befores)
                new.append(action)
                new.extend(afters)
            else:
                b, a = _edges_for(action, num_stages, stage_owner, rank)
                new.extend(b)
                new.append(action)
                new.extend(a)
        out[rank] = new
    return out
