"""Program simulator: deadlock/dependency validation + merged linearization.

The reference executes each rank's program on its own process, so bugs in a
schedule builder surface as NCCL hangs. On TPU under a single controller we
can do better: simulate the per-rank programs against the true dependency
rules (blocking recvs, eager sends) and either prove the program executes —
returning one global linearization the runtime can interpret — or report
the exact stuck state. This subsumes the reference's deadlock-safety
analysis in d9d/pipelining/component/program/communications.py.
"""

import dataclasses
from collections.abc import Iterable

from d9d_tpu.pipelining.program.actions import (
    Action,
    BackwardFull,
    BackwardInput,
    BackwardRecv,
    BackwardSend,
    BackwardWeight,
    Compose,
    ForwardCompute,
    ForwardRecv,
    ForwardSend,
    PipelineProgram,
    format_program,
)

__all__ = ["SimulatedProgram", "simulate_program", "validate_program"]


@dataclasses.dataclass(frozen=True)
class SimulatedProgram:
    """Proof of executability: a global order consistent with all deps."""

    #: (rank, action) pairs in one dependency-respecting global order.
    order: tuple[tuple[int, Action], ...]


def _primitive(actions: Iterable[Action]):
    for a in actions:
        if isinstance(a, Compose):
            yield from _primitive(a.actions)
        else:
            yield a


class _SimState:
    def __init__(self, num_stages: int, stage_owner: dict[int, int]):
        self.num_stages = num_stages
        self.stage_owner = stage_owner
        self.done: set[tuple[type, int, int, int]] = set()  # (cls, rank, stage, mb)

    def mark(self, rank: int, a: Action) -> None:
        for p in _primitive([a]):
            self.done.add((type(p), rank, p.stage, p.microbatch))

    def has(self, cls: type, rank: int, stage: int, mb: int) -> bool:
        return (cls, rank, stage, mb) in self.done

    def _fwd_done(self, rank: int, stage: int, mb: int) -> bool:
        return self.has(ForwardCompute, rank, stage, mb)

    def _bwd_done(self, rank: int, stage: int, mb: int) -> bool:
        return self.has(BackwardFull, rank, stage, mb) or self.has(
            BackwardInput, rank, stage, mb
        )

    def ready(self, rank: int, a: Action) -> bool:
        """Can ``rank`` execute ``a`` now? (Composes need every member ready.)"""
        if isinstance(a, Compose):
            # Members may feed each other (e.g. F then BS of another mb);
            # approximate by sequential evaluation with provisional marks.
            snapshot = set(self.done)
            ok = True
            for member in a.actions:
                if not self.ready(rank, member):
                    ok = False
                    break
                self.mark(rank, member)
            self.done = snapshot
            return ok
        s, mb = a.stage, a.microbatch
        if isinstance(a, ForwardCompute):
            if s == 0:
                return True
            if self.stage_owner[s - 1] == rank:
                return self._fwd_done(rank, s - 1, mb)
            return self.has(ForwardRecv, rank, s, mb)
        if isinstance(a, (BackwardFull, BackwardInput)):
            if not self._fwd_done(rank, s, mb):
                return False  # residuals: forward must have run here
            if s == self.num_stages - 1:
                return True  # loss-local cotangent
            if self.stage_owner[s + 1] == rank:
                return self._bwd_done(rank, s + 1, mb)
            return self.has(BackwardRecv, rank, s, mb)
        if isinstance(a, BackwardWeight):
            return self.has(BackwardInput, rank, s, mb)
        if isinstance(a, ForwardSend):
            return self._fwd_done(rank, s, mb)
        if isinstance(a, BackwardSend):
            return self._bwd_done(rank, s, mb)
        if isinstance(a, ForwardRecv):
            src = self.stage_owner[s - 1]
            return self.has(ForwardSend, src, s - 1, mb)
        if isinstance(a, BackwardRecv):
            src = self.stage_owner[s + 1]
            return self.has(BackwardSend, src, s + 1, mb)
        raise TypeError(f"unknown action {a!r}")


def simulate_program(
    program: PipelineProgram,
    *,
    num_stages: int,
    stage_owner: dict[int, int],
) -> SimulatedProgram:
    """Run the blocking-recv/eager-send execution model; raise on deadlock."""
    state = _SimState(num_stages, stage_owner)
    pcs = {r: 0 for r in program}
    order: list[tuple[int, Action]] = []
    total = sum(len(p) for p in program.values())
    while len(order) < total:
        progressed = False
        for rank in sorted(program):
            while pcs[rank] < len(program[rank]):
                action = program[rank][pcs[rank]]
                if not state.ready(rank, action):
                    break
                state.mark(rank, action)
                order.append((rank, action))
                pcs[rank] += 1
                progressed = True
        if not progressed:
            stuck = {
                r: str(program[r][pcs[r]])
                for r in sorted(program)
                if pcs[r] < len(program[r])
            }
            raise RuntimeError(
                f"pipeline program deadlocked; blocked heads per rank: {stuck}\n"
                f"{format_program(program)}"
            )
    return SimulatedProgram(order=tuple(order))


def validate_program(
    program: PipelineProgram,
    *,
    num_stages: int,
    num_microbatches: int,
    stage_owner: dict[int, int],
    train: bool = True,
) -> SimulatedProgram:
    """Full check: executable AND complete (every stage×mb computed once)."""
    sim = simulate_program(
        program, num_stages=num_stages, stage_owner=stage_owner
    )
    counts: dict[tuple[type, int, int], int] = {}
    for rank, action in sim.order:
        for p in _primitive([action]):
            owner = stage_owner.get(p.stage)
            if owner != rank and not isinstance(p, (ForwardRecv, BackwardRecv)):
                raise ValueError(
                    f"rank {rank} runs {p} but stage {p.stage} belongs to {owner}"
                )
            counts[(type(p), p.stage, p.microbatch)] = (
                counts.get((type(p), p.stage, p.microbatch), 0) + 1
            )
    for s in range(num_stages):
        for mb in range(num_microbatches):
            f = counts.get((ForwardCompute, s, mb), 0)
            if f != 1:
                raise ValueError(f"stage {s} mb {mb}: {f} forward computes (want 1)")
            if not train:
                continue
            full = counts.get((BackwardFull, s, mb), 0)
            di = counts.get((BackwardInput, s, mb), 0)
            dw = counts.get((BackwardWeight, s, mb), 0)
            if not (full == 1 and di == 0 and dw == 0) and not (
                full == 0 and di == 1 and dw == 1
            ):
                raise ValueError(
                    f"stage {s} mb {mb}: inconsistent backward "
                    f"(full={full}, input={di}, weight={dw})"
                )
    return sim
