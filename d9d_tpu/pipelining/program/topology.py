"""Stage → rank placement maps.

Reference: d9d/pipelining/component/program/topology.py:17 (``ScheduleStyle``
loop|v and the two placement functions; V zig-zag at :36-52).

- ``loop``: stage ``s`` lives on rank ``s % pp`` — rank r holds stages
  ``r, r+pp, r+2pp, ...`` (interleaved/looped schedules).
- ``v``: consecutive rounds of ``pp`` stages snake down then up, so rank r
  holds stages ``r`` and ``2pp-1-r`` (and so on for deeper V folds) — the
  placement used by ZeroBubbleV / DualPipeV, putting the first and last
  stage on the same rank (embedding + head colocation).
"""

import enum


class ScheduleStyle(enum.Enum):
    LOOP = "loop"
    V = "v"


def stage_to_rank(stage: int, pp: int, style: ScheduleStyle) -> int:
    """Rank owning global ``stage`` under the given placement style."""
    if style is ScheduleStyle.LOOP:
        return stage % pp
    round_idx, pos = divmod(stage, pp)
    return pos if round_idx % 2 == 0 else pp - 1 - pos


def ranks_to_stages(
    num_stages: int, pp: int, style: ScheduleStyle
) -> dict[int, list[int]]:
    """Per-rank ordered list of owned global stage ids."""
    if num_stages % pp != 0:
        raise ValueError(f"num_stages {num_stages} must be a multiple of pp {pp}")
    out: dict[int, list[int]] = {r: [] for r in range(pp)}
    for s in range(num_stages):
        out[stage_to_rank(s, pp, style)].append(s)
    return out
