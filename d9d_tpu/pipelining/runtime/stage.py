"""Per-stage compiled compute: forward + full/split backward.

Reference: d9d/pipelining/infra/stage/stage.py:13 (PipelineStage) and
splitgrad.py (autograd-graph surgery for the zero-bubble dI/dW split).

TPU redesign: there is no autograd graph to mutate. Instead each stage gets
four jitted pure functions — forward, fused backward, input-only backward,
weight-only backward — derived from the stage's forward with ``jax.vjp``.
Residual policy is *rematerialization*: the executor stores only the
stage's small input carry per in-flight microbatch; every backward variant
recomputes the stage forward inside its own jit (XLA fuses it with the
cotangent math). That is the memory-optimal choice for deep pipelines on
TPU (the reference reaches the same point via activation checkpointing),
costs one extra forward per backward direction, and makes the dI/dW split
exact rather than approximated: input-backward computes only the carry
cotangent chain, weight-backward only the parameter grads, matching the
compute split that zero-bubble schedules rely on (splitgrad.py:220,290).
"""

import contextlib
import dataclasses
from typing import Any, Protocol

import flax.linen as nn
import jax
import jax.numpy as jnp

from d9d_tpu.core import compat
from d9d_tpu.core.types import PyTree
from d9d_tpu.pipelining.stage_info import PipelineStageInfo
from d9d_tpu.telemetry import tracked_jit

__all__ = ["PipelineStageRuntime", "StageTask"]


class StageTask(Protocol):
    """How the executor drives one stage of a model for a task.

    Split of responsibilities mirroring the reference's TrainTask +
    LossComputer pair (loop/control/task.py:180,
    component/pipeline_result_processing.py:18): the task defines what a
    microbatch looks like and how the last stage turns activations into a
    weighted loss; the engine owns everything else.
    """

    def split_microbatch(
        self, microbatch: PyTree
    ) -> tuple[PyTree, PyTree, PyTree]:
        """→ (first_stage_carry, per_stage_kwargs, last_stage_state)."""
        ...

    def stage_forward(
        self, module: nn.Module, params: PyTree, carry: PyTree, kwargs: PyTree
    ) -> PyTree:
        """Non-last stage: carry in → carry out."""
        ...

    def last_stage_loss(
        self,
        module: nn.Module,
        params: PyTree,
        carry: PyTree,
        kwargs: PyTree,
        state: PyTree,
    ) -> tuple[jax.Array, jax.Array, dict[str, jax.Array]]:
        """Last stage: → (loss_sum, weight, metrics)."""
        ...

    # Optional — forward-only programs (inference): when a task defines
    # ``last_stage_outputs(module, params, carry, kwargs, state) -> PyTree``
    # the eval executor returns its value per microbatch instead of loss
    # statistics (reference InferenceProcessor,
    # component/pipeline_result_processing.py:79).


def _tree_add(a: PyTree, b: PyTree) -> PyTree:
    return jax.tree.map(lambda x, y: x + y.astype(x.dtype), a, b)


@dataclasses.dataclass
class PipelineStageRuntime:
    """One pipeline stage: module + params + the four compiled functions.

    ``carry_sharding``/``state_sharding`` describe where this stage's
    activations and task state live (its pp submesh); the executor uses
    them as transfer targets.
    """

    info: PipelineStageInfo
    module: nn.Module
    params: PyTree
    task: StageTask
    carry_sharding: Any | None = None
    kwargs_sharding: Any | None = None
    state_sharding: Any | None = None
    grad_dtype: Any | None = None
    # the stage's submesh; scoped ambient during compute so an outer full
    # mesh (jax.set_mesh in MeshParameters.build) never conflicts with this
    # stage's device group, and shard_map-based modules resolve it
    mesh: Any | None = None
    # How zero-bubble schedules pay for the dI/dW split (VERDICT r2 Weak #4):
    # - "remat": dI and dW are independent vjps, each recomputing the stage
    #   forward (2 extra forwards per microbatch vs 1F1B's one). Memory-
    #   minimal: only the input carry persists between I and W actions.
    # - "cache_full": the BackwardInput action runs the fused backward once
    #   (one forward recompute, same FLOPs as 1F1B) and the weight grads
    #   accumulate immediately; the deferred BackwardWeight action becomes
    #   a no-op. Trades the zero-bubble property (the dW slot no longer
    #   holds compute to fill the bubble) for one forward less per mb.
    # - "cache_acts": the TRUE zero-bubble split (arXiv 2401.10241
    #   semantics, r4): the I slot runs one forward + ONLY the carry-
    #   cotangent half of the backward and hands the backward's residuals
    #   to the deferred W slot, which computes the weight grads from them —
    #   same total FLOPs as a fused backward, with dW genuinely off the
    #   inter-stage critical path. Implemented by closure-converting the
    #   stage VJP into a pure jaxpr + residual arrays: the I-slot jit keeps
    #   forward+dI (XLA dead-code-eliminates the dW half), the W-slot jit
    #   keeps dW alone. Costs residual memory between the I and W actions
    #   (what the ZB schedules' memory model budgets for).
    # The better default is workload-dependent — tools/bench_pp.py measures
    # all three; see BASELINE.md.
    residual_policy: str = "remat"

    def __post_init__(self) -> None:
        # device-side attribution: every op a stage function emits carries a
        # "pp_s{k}/<phase>" named-scope prefix in captured traces (reference
        # wraps the same regions in record_function — executor.py:96)
        def scoped(name, fn):
            sid = self.info.stage_index

            def wrapped(*args):
                with jax.named_scope(f"pp_s{sid}/{name}"):
                    return fn(*args)

            return wrapped

        # tracked_jit: each per-action executable gets compile-span /
        # recompile-guard / HBM-inventory accounting under its stage-
        # scoped name (telemetry/introspect.py); dispatch count per
        # action is unchanged
        sid = self.info.stage_index

        def tjit(label, fn, **kw):
            return tracked_jit(fn, name=f"pp_s{sid}/{label}", **kw)

        self._fwd = tjit("fwd", scoped("fwd", self._fwd_impl))
        self._fwd_loss = tjit("fwd_loss", scoped("fwd_loss", self._fwd_loss_impl))
        self._fwd_out = tjit("fwd_out", scoped("fwd_out", self._fwd_out_impl))
        self._bwd_full = tjit("bwd", scoped("bwd", self._bwd_full_impl))
        self._bwd_input = tjit("bwd_dI", scoped("bwd_dI", self._bwd_input_impl))
        self._bwd_weight = tjit("bwd_dW", scoped("bwd_dW", self._bwd_weight_impl))
        self._acc = tjit(
            "grad_acc", scoped("grad_acc", _tree_add), donate_argnums=(0,)
        )
        self._cast = tjit(
            "cast_grads",
            lambda g: jax.tree.map(lambda x: x.astype(self.grad_dtype), g),
        )
        if self.residual_policy not in ("remat", "cache_full", "cache_acts"):
            raise ValueError(
                f"unknown residual_policy {self.residual_policy!r}"
            )
        # cache_acts split: VJP jaxprs recorded while tracing the I-slot
        # jit, keyed by residual signature, replayed by the W-slot jit (the
        # executor always runs I before W for a (stage, mb), so the first
        # W trace for any signature finds its record)
        self._acts_records = {}
        self._bwd_input_acts = tjit(
            "bwd_dI_acts", scoped("bwd_dI_acts", self._bwd_input_acts_impl)
        )
        self._bwd_weight_acts = tjit(
            "bwd_dW_acts", scoped("bwd_dW_acts", self._bwd_weight_acts_impl)
        )

    # ---- forward ---------------------------------------------------------

    def _fwd_impl(self, params, carry, kwargs):
        return self.task.stage_forward(self.module, params, carry, kwargs)

    def _fwd_loss_impl(self, params, carry, kwargs, state):
        return self.task.last_stage_loss(self.module, params, carry, kwargs, state)

    def _fwd_out_impl(self, params, carry, kwargs, state):
        return self.task.last_stage_outputs(
            self.module, params, carry, kwargs, state
        )

    @property
    def has_output_fn(self) -> bool:
        return getattr(self.task, "last_stage_outputs", None) is not None

    def _scoped(self):
        return compat.set_mesh(self.mesh) if self.mesh is not None else (
            contextlib.nullcontext()
        )

    def forward(self, carry, kwargs):
        with self._scoped():
            return self._fwd(self.params, carry, kwargs)

    def forward_loss(self, carry, kwargs, state):
        """Last stage forward → (loss_sum, weight, metrics)."""
        with self._scoped():
            return self._fwd_loss(self.params, carry, kwargs, state)

    def forward_outputs(self, carry, kwargs, state):
        """Last stage forward → task outputs (inference programs)."""
        with self._scoped():
            return self._fwd_out(self.params, carry, kwargs, state)

    # ---- backward (remat: recompute fwd inside each jit) ----------------

    def _loss_of(self, params, carry, kwargs, state):
        loss, weight, metrics = self.task.last_stage_loss(
            self.module, params, carry, kwargs, state
        )
        return loss, (weight, metrics)

    def _bwd_full_impl(self, params, carry, kwargs, cot, state):
        """→ (grad_params, grad_carry, aux). ``cot``/``state`` exclusive."""
        if self.info.is_last:
            grad_fn = jax.value_and_grad(
                self._loss_of, argnums=(0, 1), has_aux=True
            )
            (loss, (weight, metrics)), (gp, gc) = grad_fn(
                params, carry, kwargs, state
            )
            return gp, gc, (loss, weight, metrics)
        _, vjp = jax.vjp(
            lambda p, c: self.task.stage_forward(self.module, p, c, kwargs),
            params,
            carry,
        )
        gp, gc = vjp(cot)
        return gp, gc, None

    def _bwd_input_impl(self, params, carry, kwargs, cot, state):
        """Input-only backward → (grad_carry, aux)."""
        if self.info.is_last:
            if self.info.is_first:
                # single-stage pipeline: tokens are not differentiable, but
                # the loss statistics must still surface from this action
                loss, (weight, metrics) = self._loss_of(
                    params, carry, kwargs, state
                )
                return None, (loss, weight, metrics)
            grad_fn = jax.value_and_grad(
                self._loss_of, argnums=1, has_aux=True
            )
            (loss, (weight, metrics)), gc = grad_fn(params, carry, kwargs, state)
            return gc, (loss, weight, metrics)
        if self.info.is_first:
            # tokens are not differentiable; dI is a structural no-op
            return None, None
        _, vjp = jax.vjp(
            lambda c: self.task.stage_forward(self.module, params, c, kwargs),
            carry,
        )
        (gc,) = vjp(cot)
        return gc, None

    def _bwd_weight_impl(self, params, carry, kwargs, cot, state):
        """Weight-only backward → grad_params."""
        if self.info.is_last:
            gp = jax.grad(
                lambda p: self._loss_of(p, carry, kwargs, state)[0]
            )(params)
            return gp
        _, vjp = jax.vjp(
            lambda p: self.task.stage_forward(self.module, p, carry, kwargs),
            params,
        )
        (gp,) = vjp(cot)
        return gp

    # ---- backward (cache_acts: residual-cached true zero-bubble split) --

    @staticmethod
    def _acts_sig(saved):
        """Shape/dtype signature of a residual payload — the key tying a
        W-slot evaluation to the jaxpr its I slot traced (a retrace for
        different shapes, e.g. a ragged last microbatch, records its own
        entry instead of clobbering shared state)."""
        consts, cot = saved
        return (
            tuple((tuple(x.shape), str(x.dtype)) for x in consts),
            tuple(
                (tuple(x.shape), str(x.dtype)) for x in jax.tree.leaves(cot)
            ),
        )

    def _record_acts(self, vjp, cot, params):
        """Trace the stage VJP once and file it under its residual
        signature. Residual consts that are literally the parameter arrays
        (the dI half's weight references) are NOT carried in ``saved`` —
        the W slot rebuilds them from ``self.params``, so the payload holds
        activations only, not a duplicate copy of the stage weights per
        in-flight microbatch."""
        closed, out_shape = jax.make_jaxpr(vjp, return_shape=True)(cot)
        param_ids = {
            id(leaf): i for i, leaf in enumerate(jax.tree.leaves(params))
        }
        param_slots = {}  # const position → param leaf index
        saved_consts = []
        for pos, const in enumerate(closed.consts):
            j = param_ids.get(id(const))
            if j is None:
                saved_consts.append(const)
            else:
                param_slots[pos] = j
        record = (
            closed.jaxpr,
            jax.tree.structure(out_shape),
            len(closed.consts),
            param_slots,
        )
        saved = (saved_consts, cot)
        self._acts_records[self._acts_sig(saved)] = record
        return saved

    def _bwd_input_acts_impl(self, params, carry, kwargs, cot, state):
        """I slot: forward + carry-cotangent half → (gc, aux, saved).

        ``gc`` comes from a direct vjp call whose weight-grad outputs are
        unused — XLA dead-code-eliminates the dW half from THIS jit. The
        same vjp is traced into a jaxpr filed by residual signature; the
        W slot replays it with only the dW outputs live."""
        if self.info.is_last:
            if self.info.is_first:
                loss, vjp, (weight, metrics) = jax.vjp(
                    lambda p: self._loss_of(p, carry, kwargs, state),
                    params, has_aux=True,
                )
            else:
                loss, vjp, (weight, metrics) = jax.vjp(
                    lambda p, c: self._loss_of(p, c, kwargs, state),
                    params, carry, has_aux=True,
                )
            seed = jnp.ones_like(loss)
            saved = self._record_acts(vjp, seed, params)
            gc = None if self.info.is_first else vjp(seed)[1]
            return gc, (loss, weight, metrics), saved
        if self.info.is_first:
            _, vjp = jax.vjp(
                lambda p: self.task.stage_forward(
                    self.module, p, carry, kwargs
                ),
                params,
            )
            return None, None, self._record_acts(vjp, cot, params)
        _, vjp = jax.vjp(
            lambda p, c: self.task.stage_forward(self.module, p, c, kwargs),
            params, carry,
        )
        saved = self._record_acts(vjp, cot, params)
        gc = vjp(cot)[1]
        return gc, None, saved

    def _bwd_weight_acts_impl(self, params, saved):
        """W slot: weight grads alone, from the I slot's residuals."""
        record = self._acts_records.get(self._acts_sig(saved))
        if record is None:  # pragma: no cover — executor ordering
            raise RuntimeError(
                "cache_acts weight backward before a matching input backward"
            )
        jaxpr, out_tree, n_consts, param_slots = record
        consts_iter = iter(saved[0])
        params_flat = jax.tree.leaves(params)
        consts = [
            params_flat[param_slots[pos]]
            if pos in param_slots else next(consts_iter)
            for pos in range(n_consts)
        ]
        out = jax.core.eval_jaxpr(
            jaxpr, consts, *jax.tree.leaves(saved[1])
        )
        flat_out = jax.tree.unflatten(out_tree, out)
        return flat_out[0]

    def backward_input_acts(self, carry, kwargs, cot=None, state=None):
        with self._scoped():
            return self._bwd_input_acts(self.params, carry, kwargs, cot, state)

    def backward_weight_acts(self, saved):
        with self._scoped():
            return self._bwd_weight_acts(self.params, saved)

    def backward_full(self, carry, kwargs, cot=None, state=None):
        with self._scoped():
            return self._bwd_full(self.params, carry, kwargs, cot, state)

    def backward_input(self, carry, kwargs, cot=None, state=None):
        with self._scoped():
            return self._bwd_input(self.params, carry, kwargs, cot, state)

    def backward_weight(self, carry, kwargs, cot=None, state=None):
        with self._scoped():
            return self._bwd_weight(self.params, carry, kwargs, cot, state)

    # ---- gradient accumulator -------------------------------------------

    def cast_grads(self, grads: PyTree) -> PyTree:
        """First microbatch: adopt grads as the accumulator (cast to
        ``grad_dtype``); preserves the vjp output sharding, so no separate
        zero-init is needed. No-dispatch identity when no cast is wanted."""
        if self.grad_dtype is None:
            return grads
        with self._scoped():
            return self._cast(grads)

    def accumulate(self, acc: PyTree, grads: PyTree) -> PyTree:
        with self._scoped():
            return self._acc(acc, grads)
