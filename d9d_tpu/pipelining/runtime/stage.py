"""Per-stage compiled compute: forward + full/split backward.

Reference: d9d/pipelining/infra/stage/stage.py:13 (PipelineStage) and
splitgrad.py (autograd-graph surgery for the zero-bubble dI/dW split).

TPU redesign: there is no autograd graph to mutate. Instead each stage gets
four jitted pure functions — forward, fused backward, input-only backward,
weight-only backward — derived from the stage's forward with ``jax.vjp``.
Residual policy is *rematerialization*: the executor stores only the
stage's small input carry per in-flight microbatch; every backward variant
recomputes the stage forward inside its own jit (XLA fuses it with the
cotangent math). That is the memory-optimal choice for deep pipelines on
TPU (the reference reaches the same point via activation checkpointing),
costs one extra forward per backward direction, and makes the dI/dW split
exact rather than approximated: input-backward computes only the carry
cotangent chain, weight-backward only the parameter grads, matching the
compute split that zero-bubble schedules rely on (splitgrad.py:220,290).
"""

import contextlib
import dataclasses
from typing import Any, Protocol

import flax.linen as nn
import jax

from d9d_tpu.core.types import PyTree
from d9d_tpu.pipelining.stage_info import PipelineStageInfo

__all__ = ["PipelineStageRuntime", "StageTask"]


class StageTask(Protocol):
    """How the executor drives one stage of a model for a task.

    Split of responsibilities mirroring the reference's TrainTask +
    LossComputer pair (loop/control/task.py:180,
    component/pipeline_result_processing.py:18): the task defines what a
    microbatch looks like and how the last stage turns activations into a
    weighted loss; the engine owns everything else.
    """

    def split_microbatch(
        self, microbatch: PyTree
    ) -> tuple[PyTree, PyTree, PyTree]:
        """→ (first_stage_carry, per_stage_kwargs, last_stage_state)."""
        ...

    def stage_forward(
        self, module: nn.Module, params: PyTree, carry: PyTree, kwargs: PyTree
    ) -> PyTree:
        """Non-last stage: carry in → carry out."""
        ...

    def last_stage_loss(
        self,
        module: nn.Module,
        params: PyTree,
        carry: PyTree,
        kwargs: PyTree,
        state: PyTree,
    ) -> tuple[jax.Array, jax.Array, dict[str, jax.Array]]:
        """Last stage: → (loss_sum, weight, metrics)."""
        ...

    # Optional — forward-only programs (inference): when a task defines
    # ``last_stage_outputs(module, params, carry, kwargs, state) -> PyTree``
    # the eval executor returns its value per microbatch instead of loss
    # statistics (reference InferenceProcessor,
    # component/pipeline_result_processing.py:79).


def _tree_add(a: PyTree, b: PyTree) -> PyTree:
    return jax.tree.map(lambda x, y: x + y.astype(x.dtype), a, b)


@dataclasses.dataclass
class PipelineStageRuntime:
    """One pipeline stage: module + params + the four compiled functions.

    ``carry_sharding``/``state_sharding`` describe where this stage's
    activations and task state live (its pp submesh); the executor uses
    them as transfer targets.
    """

    info: PipelineStageInfo
    module: nn.Module
    params: PyTree
    task: StageTask
    carry_sharding: Any | None = None
    kwargs_sharding: Any | None = None
    state_sharding: Any | None = None
    grad_dtype: Any | None = None
    # the stage's submesh; scoped ambient during compute so an outer full
    # mesh (jax.set_mesh in MeshParameters.build) never conflicts with this
    # stage's device group, and shard_map-based modules resolve it
    mesh: Any | None = None
    # How zero-bubble schedules pay for the dI/dW split (VERDICT r2 Weak #4):
    # - "remat": dI and dW are independent vjps, each recomputing the stage
    #   forward (2 extra forwards per microbatch vs 1F1B's one). Memory-
    #   minimal: only the input carry persists between I and W actions.
    # - "cache_full": the BackwardInput action runs the fused backward once
    #   (one forward recompute, same FLOPs as 1F1B) and the weight grads
    #   accumulate immediately; the deferred BackwardWeight action becomes
    #   a no-op. Trades the zero-bubble property (the dW slot no longer
    #   holds compute to fill the bubble) for one forward less per mb.
    # The better default is workload-dependent — tools/bench_pp.py measures
    # both; see BASELINE.md.
    residual_policy: str = "remat"

    def __post_init__(self) -> None:
        # device-side attribution: every op a stage function emits carries a
        # "pp_s{k}/<phase>" named-scope prefix in captured traces (reference
        # wraps the same regions in record_function — executor.py:96)
        def scoped(name, fn):
            sid = self.info.stage_index

            def wrapped(*args):
                with jax.named_scope(f"pp_s{sid}/{name}"):
                    return fn(*args)

            return wrapped

        self._fwd = jax.jit(scoped("fwd", self._fwd_impl))
        self._fwd_loss = jax.jit(scoped("fwd_loss", self._fwd_loss_impl))
        self._fwd_out = jax.jit(scoped("fwd_out", self._fwd_out_impl))
        self._bwd_full = jax.jit(scoped("bwd", self._bwd_full_impl))
        self._bwd_input = jax.jit(scoped("bwd_dI", self._bwd_input_impl))
        self._bwd_weight = jax.jit(scoped("bwd_dW", self._bwd_weight_impl))
        self._acc = jax.jit(
            scoped("grad_acc", _tree_add), donate_argnums=(0,)
        )
        self._cast = jax.jit(
            lambda g: jax.tree.map(lambda x: x.astype(self.grad_dtype), g)
        )

    # ---- forward ---------------------------------------------------------

    def _fwd_impl(self, params, carry, kwargs):
        return self.task.stage_forward(self.module, params, carry, kwargs)

    def _fwd_loss_impl(self, params, carry, kwargs, state):
        return self.task.last_stage_loss(self.module, params, carry, kwargs, state)

    def _fwd_out_impl(self, params, carry, kwargs, state):
        return self.task.last_stage_outputs(
            self.module, params, carry, kwargs, state
        )

    @property
    def has_output_fn(self) -> bool:
        return getattr(self.task, "last_stage_outputs", None) is not None

    def _scoped(self):
        return jax.set_mesh(self.mesh) if self.mesh is not None else (
            contextlib.nullcontext()
        )

    def forward(self, carry, kwargs):
        with self._scoped():
            return self._fwd(self.params, carry, kwargs)

    def forward_loss(self, carry, kwargs, state):
        """Last stage forward → (loss_sum, weight, metrics)."""
        with self._scoped():
            return self._fwd_loss(self.params, carry, kwargs, state)

    def forward_outputs(self, carry, kwargs, state):
        """Last stage forward → task outputs (inference programs)."""
        with self._scoped():
            return self._fwd_out(self.params, carry, kwargs, state)

    # ---- backward (remat: recompute fwd inside each jit) ----------------

    def _loss_of(self, params, carry, kwargs, state):
        loss, weight, metrics = self.task.last_stage_loss(
            self.module, params, carry, kwargs, state
        )
        return loss, (weight, metrics)

    def _bwd_full_impl(self, params, carry, kwargs, cot, state):
        """→ (grad_params, grad_carry, aux). ``cot``/``state`` exclusive."""
        if self.info.is_last:
            grad_fn = jax.value_and_grad(
                self._loss_of, argnums=(0, 1), has_aux=True
            )
            (loss, (weight, metrics)), (gp, gc) = grad_fn(
                params, carry, kwargs, state
            )
            return gp, gc, (loss, weight, metrics)
        _, vjp = jax.vjp(
            lambda p, c: self.task.stage_forward(self.module, p, c, kwargs),
            params,
            carry,
        )
        gp, gc = vjp(cot)
        return gp, gc, None

    def _bwd_input_impl(self, params, carry, kwargs, cot, state):
        """Input-only backward → (grad_carry, aux)."""
        if self.info.is_last:
            if self.info.is_first:
                # single-stage pipeline: tokens are not differentiable, but
                # the loss statistics must still surface from this action
                loss, (weight, metrics) = self._loss_of(
                    params, carry, kwargs, state
                )
                return None, (loss, weight, metrics)
            grad_fn = jax.value_and_grad(
                self._loss_of, argnums=1, has_aux=True
            )
            (loss, (weight, metrics)), gc = grad_fn(params, carry, kwargs, state)
            return gc, (loss, weight, metrics)
        if self.info.is_first:
            # tokens are not differentiable; dI is a structural no-op
            return None, None
        _, vjp = jax.vjp(
            lambda c: self.task.stage_forward(self.module, params, c, kwargs),
            carry,
        )
        (gc,) = vjp(cot)
        return gc, None

    def _bwd_weight_impl(self, params, carry, kwargs, cot, state):
        """Weight-only backward → grad_params."""
        if self.info.is_last:
            gp = jax.grad(
                lambda p: self._loss_of(p, carry, kwargs, state)[0]
            )(params)
            return gp
        _, vjp = jax.vjp(
            lambda p: self.task.stage_forward(self.module, p, carry, kwargs),
            params,
        )
        (gp,) = vjp(cot)
        return gp

    def backward_full(self, carry, kwargs, cot=None, state=None):
        with self._scoped():
            return self._bwd_full(self.params, carry, kwargs, cot, state)

    def backward_input(self, carry, kwargs, cot=None, state=None):
        with self._scoped():
            return self._bwd_input(self.params, carry, kwargs, cot, state)

    def backward_weight(self, carry, kwargs, cot=None, state=None):
        with self._scoped():
            return self._bwd_weight(self.params, carry, kwargs, cot, state)

    # ---- gradient accumulator -------------------------------------------

    def cast_grads(self, grads: PyTree) -> PyTree:
        """First microbatch: adopt grads as the accumulator (cast to
        ``grad_dtype``); preserves the vjp output sharding, so no separate
        zero-init is needed. No-dispatch identity when no cast is wanted."""
        if self.grad_dtype is None:
            return grads
        with self._scoped():
            return self._cast(grads)

    def accumulate(self, acc: PyTree, grads: PyTree) -> PyTree:
        with self._scoped():
            return self._acc(acc, grads)
