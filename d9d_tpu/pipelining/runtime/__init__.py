from d9d_tpu.pipelining.runtime.executor import (
    PipelineExecutionResult,
    PipelineScheduleExecutor,
)
from d9d_tpu.pipelining.runtime.stage import PipelineStageRuntime, StageTask

__all__ = [
    "PipelineExecutionResult",
    "PipelineScheduleExecutor",
    "PipelineStageRuntime",
    "StageTask",
]
