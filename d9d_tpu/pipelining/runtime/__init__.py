from d9d_tpu.pipelining.runtime.executor import (
    PipelineExecutionResult,
    PipelineScheduleExecutor,
)
from d9d_tpu.pipelining.runtime.fused import FusedPipelineExecutor
from d9d_tpu.pipelining.runtime.stage import PipelineStageRuntime, StageTask

__all__ = [
    "FusedPipelineExecutor",
    "PipelineExecutionResult",
    "PipelineScheduleExecutor",
    "PipelineStageRuntime",
    "StageTask",
]
