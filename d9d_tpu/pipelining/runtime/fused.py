"""Fused MPMD pipeline runtime: device-resident schedule programs.

The legacy ``PipelineScheduleExecutor`` interprets the validated global
linearization one tiny ``tracked_jit`` per action — O(microbatches ×
actions) host dispatches per step, the ≈9% single-controller tax
BASELINE.md measured at a zero-comm pp=2/µB=8 config. This module is
the compile-the-schedule answer (the MPMD pipeline-compilation lineage,
arxiv 2412.14374): a schedule compiler partitions the SAME linearization
into maximal *fusable runs* per rank, traces each run's actions —
compute, grad accumulation, loss-stat summation, the per-stage numerics
vectors, and every same-device send (lowered to an in-program value
rename) — into ONE ``tracked_jit`` program with full donation of the
run's dead activation/grad buffers, and the step loop shrinks to
dispatching a handful of fused programs plus the explicit cross-rank
boundary transfers.

Semantics contract: the run partitioner and the run tracer both consume
the same op descriptors, which are produced by a symbolic replay of the
legacy executor's action handlers (`executor.py`) — every stage function
is invoked through the identical raw ``_*_impl`` with arguments wired
through the identical residual-policy key dataflow, and gradient
accumulation folds in the identical microbatch order. Per-microbatch
results are therefore bit-identical to the legacy action loop
(``tests/pipelining/test_fused_parity.py`` pins this on CPU), which
stays available behind ``runtime="legacy"`` for one release as the
parity oracle. One documented exception: ``cache_acts`` weight grads —
the W slot's replayed VJP jaxpr lands in the same XLA program as its I
slot, and XLA's CSE/fusion of the shared subgraph can reassociate the
long f32 dW reductions (~1e-4 relative worst-case on a real model;
bit-exact on graphs XLA compiles identically in both contexts). Same
math, different float association — grad-exactness vs the sequential
baseline still holds at tolerance for every policy.

Partitioning rule (the wavefront): actions append to their rank's open
run until some action *reads a value produced by another rank's still-
open run* — that producer run is closed (sealed into the dispatch
sequence) first, so every cross-run edge points backward in dispatch
order and the sequence is trivially executable. Cross-rank boundary
transfers (``put_compat`` onto the consumer's submesh — distinct device
sets cannot share one SPMD program) are standalone entries in the same
sequence and close their producer the same way; transfers whose
destination stage declares no sharding (single-device tests, same-
footprint virtual stages) are inlined into the producing program as a
rename instead. At the tiny 1F1B config (one rank, two virtual stages)
the entire step fuses into a single program.

Telemetry: each fused program is tracked as ``pp_fused/r{R}/run{K}``
(compile spans, recompile guard, HBM inventory, d9d-audit capture —
every fused program carries a committed collective-census + donation
contract in ``AUDIT_BASELINE.json``), stage compute keeps its
``pp_s{S}/*`` named scopes inside the trace for device-side
attribution, and the step records ``pp/step`` plus the
``pp/fused_dispatches`` / ``pp/fused_transfers`` / ``pp/fused_programs``
gauges (docs/design/observability.md).

Numerics fold (PR 14 contract): when built with ``numerics=True`` the
per-stage ``pp_numerics/s{S}`` stats vector is computed INSIDE the
owning rank's final fused program, gated by a traced cadence flag
(``lax.cond``) — off-cadence steps run the identical program with the
stats branch producing NaNs, so the cadence adds zero dispatches and
zero recompiles.
"""

import contextlib
import dataclasses
import time
from typing import Any

import jax
import jax.numpy as jnp

from d9d_tpu.core import compat
from d9d_tpu.core.tracing import annotate
from d9d_tpu.core.types import PyTree
from d9d_tpu.pipelining.program.actions import (
    Action,
    BackwardFull,
    BackwardInput,
    BackwardRecv,
    BackwardSend,
    BackwardWeight,
    Compose,
    ForwardCompute,
    ForwardRecv,
    ForwardSend,
    PipelineProgram,
)
from d9d_tpu.pipelining.program.validate import validate_program
from d9d_tpu.pipelining.runtime.executor import PipelineExecutionResult
from d9d_tpu.pipelining.runtime.stage import PipelineStageRuntime
from d9d_tpu.pipelining.runtime.transfer import put_compat
from d9d_tpu.telemetry import get_telemetry, tracked_jit
from d9d_tpu.telemetry import numerics as numerics_mod

__all__ = ["FusedPipelineExecutor"]

# value keys in the dataflow environment (tuples; first element is the
# kind tag). "ext" producers are staged by the host at step start /
# first use; every other key is produced by a run or a transfer.
#   ("carry", mb)      first-stage input carry            ext
#   ("kw", s, mb)      stage kwargs on the stage submesh  ext
#   ("state", mb)      last-stage task state              ext
#   ("nu", s)          second-moment tree for numerics    ext (no donate)
#   ("flag", s)        traced cadence flag                ext (no donate)
#   ("in", s, mb)      carry staged by a Send             run/transfer
#   ("fo", s, mb)      forward output awaiting send       run
#   ("cot", s, mb)     cotangent wrt stage s output       run/transfer
#   ("gin", s, mb)     dI awaiting a BackwardSend         run
#   ("g", s, v)        grad accumulator, version v >= 1   run
#   ("aux", i)         (loss, weight, metrics) triple     run
#   ("saved", s, mb)   cache_acts residual payload        run
#   ("out", mb)        eval per-microbatch output         run
#   ("loss",)/("wsum",)/("met",)  summed loss statistics  run
#   ("num", s)         per-stage numerics vector          run

# donation is restricted to executor-owned intermediates (activations,
# cotangents, grad accumulators, cached residuals, loss auxes): ext keys
# may alias caller-owned arrays (microbatch trees, second moments), and
# donating those would invalidate the caller's buffers mid-step
_DONATABLE_KINDS = ("in", "fo", "cot", "gin", "g", "saved", "aux")

# relative compute weight per op kind, used to apportion a fused run's
# measured wall across its stages on timeline-cadence steps (and carried
# per-op in the RunManifest so offline consumers can do the same with the
# run's XLA cost_analysis FLOPs as the absolute anchor). bwd_full ≈ one
# forward + one backward in a single VJP (the last stage additionally
# folds its fwd_loss in under train); dI/dW splits are each ≈ one unit;
# renames, aux summation and the numerics cond are ~free.
_OP_WEIGHTS = {
    "fwd": 1.0,
    "fwd_loss": 1.0,
    "fwd_out": 1.0,
    "bwd_full": 2.0,
    "bwd_dI": 1.0,
    "bwd_dW": 1.0,
    "bwd_dI_acts": 1.0,
    "bwd_dW_acts": 1.0,
    "send": 0.0,
    "sum_aux": 0.0,
    "numerics": 0.0,
}


@dataclasses.dataclass
class _Op:
    """One legacy-handler-equivalent device action: the unit both the
    partitioner (reads/writes) and the run tracer (meta roles) consume."""

    kind: str
    stage: int
    mb: int
    reads: tuple
    writes: tuple
    meta: dict


class _Run:
    """One fusable run: a maximal contiguous slice of a rank's actions,
    compiled into a single tracked_jit program."""

    __slots__ = (
        "rank", "index", "ops", "param_stages", "input_keys",
        "output_keys", "donate_keys", "drop_after", "fn", "label",
        "stage_shares", "_writes", "_reads",
    )

    def __init__(self, rank: int, index: int):
        self.rank = rank
        self.index = index
        self.ops: list[_Op] = []
        self.param_stages: list[int] = []
        self.input_keys: list[tuple] = []
        self.output_keys: list[tuple] = []
        self.donate_keys: set[tuple] = set()
        self.drop_after: list[tuple] = []
        self.fn = None
        self.label = f"pp.run.r{rank}.{index}"
        self.stage_shares: dict[int, float] = {}
        self._writes: set[tuple] = set()
        self._reads: set[tuple] = set()


class _Transfer:
    """One explicit cross-rank boundary transfer (``put_compat`` onto
    the destination stage's sharding) in the dispatch sequence."""

    __slots__ = ("src", "dst", "dst_stage", "drop_after", "label")

    def __init__(self, src: tuple, dst: tuple, dst_stage: int):
        self.src = src
        self.dst = dst
        self.dst_stage = dst_stage
        self.drop_after: list[tuple] = []
        self.label = f"pp.xfer.s{dst_stage}.mb{src[2]}"


_EXT = "ext"


class FusedPipelineExecutor:
    """Drop-in replacement for ``PipelineScheduleExecutor``: same
    constructor surface plus ``numerics``, same result type, a few fused
    program dispatches per step instead of one per action.

    ``numerics=True`` appends the per-stage ``pp_numerics/s{S}`` stats
    assembly to each owning rank's last run under a traced cadence flag;
    ``step`` then requires ``numerics_moments`` (per-stage second-moment
    trees, ``telemetry/numerics.find_second_moments``) every call and
    returns the stats vectors in ``result.numerics`` (NaN-filled off
    cadence — the flag only flips a ``lax.cond`` branch).
    """

    def __init__(
        self,
        *,
        stages: dict[int, PipelineStageRuntime],
        program: PipelineProgram,
        stage_owner: dict[int, int],
        num_microbatches: int,
        train: bool = True,
        numerics: bool = False,
    ):
        self.stages = stages
        self.num_stages = len(stages)
        self.num_microbatches = num_microbatches
        self.stage_owner = stage_owner
        self.train = train
        self.numerics = numerics and train
        sim = validate_program(
            program,
            num_stages=self.num_stages,
            num_microbatches=num_microbatches,
            stage_owner=stage_owner,
            train=train,
        )
        self.order: tuple[tuple[int, Action], ...] = sim.order
        self._last = self.stages[self.num_stages - 1]
        self._rank_mesh = {
            stage_owner[s]: rt.mesh for s, rt in sorted(stages.items())
        }
        self._grad_final: dict[int, tuple] = {}
        self._aux_keys: list[tuple] = []
        self._ext_consumed: set[tuple] = set()
        entries = self._build_entries()
        self._seq = self._partition(entries)
        self._runs = [e for e in self._seq if isinstance(e, _Run)]
        for run in self._runs:
            self._build_run(run)
        # ext keys staged lazily right before their first consumer
        self._stage_before = self._ext_staging_plan()
        self.num_fused_programs = len(self._runs)
        self.num_transfers = len(self._seq) - len(self._runs)
        self.last_dispatches = 0
        self._tele = get_telemetry()

    # ------------------------------------------------------------------
    # op generation: symbolic replay of the legacy handlers

    def _flat_plan(self) -> list[Action]:
        flat: list[Action] = []

        def add(action: Action) -> None:
            if isinstance(action, Compose):
                for member in action.actions:
                    add(member)
            elif not isinstance(action, (ForwardRecv, BackwardRecv)):
                flat.append(action)

        for _rank, action in self.order:
            add(action)
        return flat

    def _build_entries(self) -> list:
        """The dispatch-ordered entry list: ("op", rank, _Op) device
        actions and ("xfer", src, dst, dst_stage) boundary transfers,
        mirroring ``PipelineScheduleExecutor``'s handlers key for key."""
        entries: list = []
        owner = self.stage_owner
        last_s = self.num_stages - 1
        in_key: dict[tuple[int, int], tuple] = {}
        sent_in: set[tuple] = set()  # ("in", s, mb) written by a Send
        grads_ver: dict[int, int] = {}
        weight_done: set[tuple[int, int]] = set()

        def op(kind, s, mb, reads, writes, **meta):
            reads = tuple(k for k in reads if k is not None)
            writes = tuple(k for k in writes if k is not None)
            entries.append(
                ("op", owner[s] if s >= 0 else owner[last_s],
                 _Op(kind, s, mb, reads, writes, meta))
            )

        def next_aux(s, mb) -> tuple:
            k = ("aux", len(self._aux_keys))
            self._aux_keys.append(k)
            return k

        def bump_grads(s) -> tuple[tuple | None, tuple]:
            v = grads_ver.get(s, 0) + 1
            grads_ver[s] = v
            acc = ("g", s, v - 1) if v > 1 else None
            gout = ("g", s, v)
            self._grad_final[s] = gout
            return acc, gout

        def route(s, mb) -> tuple | None:
            # _route_input_grad: local edge stores the cot directly,
            # cross-rank edges park it for the BackwardSend
            if s == 0:
                return None
            if owner[s - 1] == owner[s]:
                return ("cot", s - 1, mb)
            return ("gin", s, mb)

        def send(s_from, s_to, src, dst):
            if self.stages[s_to].carry_sharding is None:
                # no transfer target: the legacy put is the identity —
                # lower it into the producing program as a rename
                op("send", s_from, src[2], (src,), (dst,), src=src, dst=dst)
            else:
                entries.append(("xfer", src, dst, s_to))

        for action in self._flat_plan():
            s, mb = action.stage, action.microbatch
            stage = self.stages[s]
            is_last = stage.info.is_last

            if isinstance(action, ForwardCompute):
                if s == 0:
                    ik = ("carry", mb)
                elif ("in", s, mb) in sent_in:
                    ik = ("in", s, mb)
                else:
                    ik = ("fo", s - 1, mb)  # same-rank edge: direct pull
                in_key[(s, mb)] = ik
                kw = ("kw", s, mb)
                if is_last:
                    if not self.train:
                        if stage.has_output_fn:
                            op("fwd_out", s, mb,
                               (ik, kw, ("state", mb)), (("out", mb),),
                               carry=ik, kw=kw, state=("state", mb),
                               out=("out", mb))
                        else:
                            aux = next_aux(s, mb)
                            op("fwd_loss", s, mb,
                               (ik, kw, ("state", mb)),
                               (aux, ("out", mb)),
                               carry=ik, kw=kw, state=("state", mb),
                               aux=aux, out=("out", mb))
                    # train: forward folds into the backward
                else:
                    op("fwd", s, mb, (ik, kw), (("fo", s, mb),),
                       carry=ik, kw=kw, out=("fo", s, mb))

            elif isinstance(action, ForwardSend):
                sent_in.add(("in", s + 1, mb))
                send(s, s + 1, ("fo", s, mb), ("in", s + 1, mb))

            elif isinstance(action, BackwardSend):
                send(s, s - 1, ("gin", s, mb), ("cot", s - 1, mb))

            elif isinstance(action, BackwardFull) or (
                isinstance(action, BackwardInput)
                and stage.residual_policy == "cache_full"
            ):
                ik = in_key.pop((s, mb))
                cot = None if is_last else ("cot", s, mb)
                state = ("state", mb) if is_last else None
                aux = next_aux(s, mb) if is_last else None
                acc, gout = bump_grads(s)
                rt = route(s, mb)
                op("bwd_full", s, mb,
                   (ik, ("kw", s, mb), cot, state, acc),
                   (gout, rt, aux),
                   carry=ik, kw=("kw", s, mb), cot=cot, state=state,
                   acc=acc, gout=gout, route=rt, aux=aux)
                if isinstance(action, BackwardInput):
                    weight_done.add((s, mb))

            elif isinstance(action, BackwardInput):
                if stage.residual_policy == "cache_acts":
                    ik = in_key.pop((s, mb))
                    cot = None if is_last else ("cot", s, mb)
                    state = ("state", mb) if is_last else None
                    aux = next_aux(s, mb) if is_last else None
                    rt = route(s, mb)
                    op("bwd_dI_acts", s, mb,
                       (ik, ("kw", s, mb), cot, state),
                       (("saved", s, mb), rt, aux),
                       carry=ik, kw=("kw", s, mb), cot=cot, state=state,
                       saved=("saved", s, mb), route=rt, aux=aux)
                else:  # remat: inputs/cot stay live for the W slot
                    ik = in_key[(s, mb)]
                    cot = None if is_last else ("cot", s, mb)
                    state = ("state", mb) if is_last else None
                    aux = next_aux(s, mb) if is_last else None
                    rt = route(s, mb)
                    op("bwd_dI", s, mb,
                       (ik, ("kw", s, mb), cot, state),
                       (rt, aux),
                       carry=ik, kw=("kw", s, mb), cot=cot, state=state,
                       route=rt, aux=aux)

            elif isinstance(action, BackwardWeight):
                if stage.residual_policy == "cache_acts":
                    acc, gout = bump_grads(s)
                    op("bwd_dW_acts", s, mb,
                       (("saved", s, mb), acc), (gout,),
                       saved=("saved", s, mb), acc=acc, gout=gout)
                elif (s, mb) in weight_done:
                    weight_done.discard((s, mb))  # cache_full: no-op slot
                else:  # remat
                    ik = in_key.pop((s, mb))
                    cot = None if is_last else ("cot", s, mb)
                    state = ("state", mb) if is_last else None
                    acc, gout = bump_grads(s)
                    op("bwd_dW", s, mb,
                       (ik, ("kw", s, mb), cot, state, acc), (gout,),
                       carry=ik, kw=("kw", s, mb), cot=cot, state=state,
                       acc=acc, gout=gout)
            else:  # pragma: no cover
                raise TypeError(f"unknown action {action!r}")

        # numerics fold BEFORE the aux sum: each stats op appends to its
        # rank's still-open run (zero extra dispatches); the aux sum then
        # seals every remaining run
        if self.numerics:
            for s in sorted(self._grad_final):
                op("numerics", s, -1,
                   (self._grad_final[s], ("nu", s), ("flag", s)),
                   (("num", s),),
                   g=self._grad_final[s], nu=("nu", s),
                   flag=("flag", s), num=("num", s))
        if self._aux_keys:
            op("sum_aux", last_s, -1, tuple(self._aux_keys),
               (("loss",), ("wsum",), ("met",)),
               aux_keys=tuple(self._aux_keys))
        return entries

    # ------------------------------------------------------------------
    # wavefront partitioner

    def _result_keys(self) -> set[tuple]:
        keys: set[tuple] = set()
        if self.train:
            keys.update(self._grad_final.values())
            if self.numerics:
                keys.update(("num", s) for s in self._grad_final)
        else:
            keys.update(("out", mb) for mb in range(self.num_microbatches))
        if self._aux_keys:
            keys.update((("loss",), ("wsum",), ("met",)))
        return keys

    def _partition(self, entries: list) -> list:
        open_runs: dict[int, _Run] = {}
        producer: dict[tuple, Any] = {}
        consumers: dict[tuple, list] = {}
        seq: list = []
        counters: dict[int, int] = {}

        def close(rank: int) -> None:
            seq.append(open_runs.pop(rank))

        def consume(entity, key) -> None:
            p = producer.get(key, _EXT)
            if isinstance(p, _Run) and open_runs.get(p.rank) is p and (
                p is not entity
            ):
                close(p.rank)
            if p is _EXT:
                self._ext_consumed.add(key)
            consumers.setdefault(key, []).append(entity)

        for entry in entries:
            if entry[0] == "xfer":
                _, src, dst, dst_stage = entry
                t = _Transfer(src, dst, dst_stage)
                consume(t, src)
                seq.append(t)
                producer[dst] = t
                continue
            _, rank, op = entry
            run = open_runs.get(rank)
            if run is None:
                idx = counters.get(rank, 0)
                counters[rank] = idx + 1
                run = open_runs[rank] = _Run(rank, idx)
            for k in op.reads:
                if k in run._writes:
                    continue  # intra-run edge
                if k not in run._reads:
                    consume(run, k)
                    run._reads.add(k)
                    run.input_keys.append(k)
            for k in op.writes:
                producer[k] = run
                run._writes.add(k)
            if op.kind != "send" and op.kind != "sum_aux":
                if op.stage not in run.param_stages:
                    run.param_stages.append(op.stage)
            run.ops.append(op)
        for rank in sorted(open_runs):
            close(rank)

        # liveness: outputs = values consumed later or returned to the
        # caller; donation = last-consumer, non-result, non-pinned inputs
        results = self._result_keys()
        last_use = {k: lst[-1] for k, lst in consumers.items()}
        for ent in seq:
            if isinstance(ent, _Run):
                ent.param_stages.sort()
                ent.output_keys = [
                    k for op in ent.ops for k in op.writes
                    if consumers.get(k) or k in results
                ]
                ent.donate_keys = {
                    k for k in ent.input_keys
                    if last_use.get(k) is ent
                    and k not in results
                    and k[0] in _DONATABLE_KINDS
                }
                ent.drop_after = [
                    k for k in ent.input_keys
                    if last_use.get(k) is ent and k not in results
                ]
            else:
                ent.drop_after = (
                    [ent.src]
                    if last_use.get(ent.src) is ent
                    and ent.src not in results
                    else []
                )
        return seq

    def _ext_staging_plan(self) -> list[list[tuple]]:
        """Per dispatch-sequence position: the ext kwargs keys to stage
        right before that entity runs (first-use staging; carries/states
        go up front like the legacy executor)."""
        staged: set[tuple] = set()
        plan: list[list[tuple]] = []
        for ent in self._seq:
            keys = ent.input_keys if isinstance(ent, _Run) else [ent.src]
            need = [
                k for k in keys
                if k[0] == "kw" and k in self._ext_consumed
                and k not in staged
            ]
            staged.update(need)
            plan.append(need)
        return plan

    # ------------------------------------------------------------------
    # run tracing: the same op descriptors, interpreted symbolically

    def _build_run(self, run: _Run) -> None:
        stage_ids = tuple(run.param_stages)
        input_keys = tuple(run.input_keys)
        output_keys = tuple(run.output_keys)
        ops = tuple(run.ops)
        n_sp = len(stage_ids)

        def fn(*args):
            stage_args = dict(zip(stage_ids, args[:n_sp]))
            env = dict(zip(input_keys, args[n_sp:]))
            for op in ops:
                self._trace_op(op, stage_args, env)
            return tuple(env[k] for k in output_keys)

        donate = tuple(
            n_sp + i
            for i, k in enumerate(input_keys)
            if k in run.donate_keys
        )
        run.fn = tracked_jit(
            fn,
            name=f"pp_fused/r{run.rank}/run{run.index}",
            donate_argnums=donate,
        )
        # RunManifest: the run's ordered op descriptors, persisted on the
        # program's ExecutableRecord (and therefore the `executable` JSONL
        # sidecar + introspect inventory) at first compile. Offline
        # consumers re-derive the same per-stage apportionment from the
        # per-op `weight` column, anchored to the record's absolute
        # cost_analysis FLOPs.
        run.fn.manifest = {
            "rank": run.rank,
            "index": run.index,
            "ops": [
                {
                    "kind": op.kind,
                    "stage": op.stage,
                    "mb": op.mb,
                    "weight": _OP_WEIGHTS.get(op.kind, 1.0),
                    "reads": [list(k) for k in op.reads],
                    "writes": [list(k) for k in op.writes],
                }
                for op in run.ops
            ],
        }
        # per-stage wall shares for the timeline cadence: kind-weighted
        # op counts, normalized within the run (uniform over the run's
        # param stages when every op is weightless)
        weights: dict[int, float] = {}
        for op in run.ops:
            w = _OP_WEIGHTS.get(op.kind, 1.0)
            if w > 0.0 and op.stage >= 0:
                weights[op.stage] = weights.get(op.stage, 0.0) + w
        total_w = sum(weights.values())
        if total_w > 0.0:
            run.stage_shares = {s: w / total_w for s, w in weights.items()}
        elif run.param_stages:
            u = 1.0 / len(run.param_stages)
            run.stage_shares = {s: u for s in run.param_stages}

    def _trace_op(self, op: _Op, params: dict, env: dict) -> None:
        m = op.meta
        s = op.stage
        kind = op.kind
        if kind == "send":
            env[m["dst"]] = env[m["src"]]
            return
        if kind == "sum_aux":
            self._trace_sum_aux(m["aux_keys"], env)
            return
        if kind == "numerics":
            self._trace_numerics(s, m, params, env)
            return
        stage = self.stages[s]
        cot = env[m["cot"]] if m.get("cot") else None
        state = env[m["state"]] if m.get("state") else None
        if kind == "fwd":
            with jax.named_scope(f"pp_s{s}/fwd"):
                env[m["out"]] = stage._fwd_impl(
                    params[s], env[m["carry"]], env[m["kw"]]
                )
        elif kind == "fwd_loss":
            with jax.named_scope(f"pp_s{s}/fwd_loss"):
                aux = stage._fwd_loss_impl(
                    params[s], env[m["carry"]], env[m["kw"]], state
                )
            env[m["aux"]] = aux
            env[m["out"]] = aux
        elif kind == "fwd_out":
            with jax.named_scope(f"pp_s{s}/fwd_out"):
                env[m["out"]] = stage._fwd_out_impl(
                    params[s], env[m["carry"]], env[m["kw"]], state
                )
        elif kind == "bwd_full":
            with jax.named_scope(f"pp_s{s}/bwd"):
                gp, gc, aux = stage._bwd_full_impl(
                    params[s], env[m["carry"]], env[m["kw"]], cot, state
                )
            if m["aux"]:
                env[m["aux"]] = aux
            self._trace_acc(op, gp, params, env)
            if m["route"]:
                env[m["route"]] = gc
        elif kind == "bwd_dI":
            with jax.named_scope(f"pp_s{s}/bwd_dI"):
                gc, aux = stage._bwd_input_impl(
                    params[s], env[m["carry"]], env[m["kw"]], cot, state
                )
            if m["aux"]:
                env[m["aux"]] = aux
            if m["route"]:
                env[m["route"]] = gc
        elif kind == "bwd_dW":
            with jax.named_scope(f"pp_s{s}/bwd_dW"):
                gp = stage._bwd_weight_impl(
                    params[s], env[m["carry"]], env[m["kw"]], cot, state
                )
            self._trace_acc(op, gp, params, env)
        elif kind == "bwd_dI_acts":
            with jax.named_scope(f"pp_s{s}/bwd_dI_acts"):
                gc, aux, saved = stage._bwd_input_acts_impl(
                    params[s], env[m["carry"]], env[m["kw"]], cot, state
                )
            env[m["saved"]] = saved
            if m["aux"]:
                env[m["aux"]] = aux
            if m["route"]:
                env[m["route"]] = gc
        elif kind == "bwd_dW_acts":
            with jax.named_scope(f"pp_s{s}/bwd_dW_acts"):
                gp = stage._bwd_weight_acts_impl(params[s], env[m["saved"]])
            self._trace_acc(op, gp, params, env)
        else:  # pragma: no cover
            raise TypeError(f"unknown op kind {kind!r}")

    def _trace_acc(self, op: _Op, gp, params: dict, env: dict) -> None:
        """First microbatch adopts (cast) the grads, later ones fold in —
        the exact ``cast_grads``/``accumulate`` order of the legacy
        ``_add_grads``, traced inline."""
        s = op.stage
        m = op.meta
        stage = self.stages[s]
        if m["acc"] is None:
            if stage.grad_dtype is None:
                env[m["gout"]] = gp
            else:
                with jax.named_scope(f"pp_s{s}/cast_grads"):
                    env[m["gout"]] = jax.tree.map(
                        lambda x: x.astype(stage.grad_dtype), gp
                    )
        else:
            with jax.named_scope(f"pp_s{s}/grad_acc"):
                env[m["gout"]] = jax.tree.map(
                    lambda x, y: x + y.astype(x.dtype), env[m["acc"]], gp
                )

    def _trace_sum_aux(self, aux_keys: tuple, env: dict) -> None:
        auxes = [env[k] for k in aux_keys]
        with jax.named_scope("pp/loss_sum"):
            structures = {jax.tree.structure(a) for a in auxes}
            if len(structures) == 1:
                acc = auxes[0]
                for aux in auxes[1:]:
                    acc = jax.tree.map(lambda x, y: x + y, acc, aux)
                loss_sum, weight_sum, metrics_sum = acc
                metrics_sum = dict(metrics_sum)
            else:
                # key-union fallback, mirroring the legacy host merge
                loss_sum = weight_sum = None
                metrics_sum = {}
                for loss, weight, metrics in auxes:
                    loss_sum = loss if loss_sum is None else loss_sum + loss
                    weight_sum = (
                        weight if weight_sum is None else weight_sum + weight
                    )
                    for k, v in metrics.items():
                        metrics_sum[k] = (
                            v if k not in metrics_sum else metrics_sum[k] + v
                        )
        env[("loss",)] = loss_sum
        env[("wsum",)] = weight_sum
        env[("met",)] = metrics_sum

    def _trace_numerics(self, s: int, m: dict, params: dict, env: dict):
        g, nu, flag = env[m["g"]], env[m["nu"]], env[m["flag"]]
        p = params[s]

        def stats(g, nu, p):
            return numerics_mod.stacked_param_rows(
                g, params=None, new_params=p, nu=nu
            ).reshape(-1)

        shape = jax.eval_shape(stats, g, nu, p)
        with jax.named_scope(f"pp_numerics/s{s}/stats"):
            env[m["num"]] = jax.lax.cond(
                flag,
                lambda: stats(g, nu, p),
                lambda: jnp.full(shape.shape, jnp.nan, shape.dtype),
            )

    # ------------------------------------------------------------------
    # step loop: a handful of fused dispatches + boundary transfers

    def _mesh_scope(self, rank: int):
        mesh = self._rank_mesh.get(rank)
        return (
            compat.set_mesh(mesh)
            if mesh is not None
            else contextlib.nullcontext()
        )

    def _stage_ext(self, tree: PyTree, sharding) -> PyTree:
        # ext trees are never donated, so the legacy staging semantics
        # (identity when no sharding is declared) carry over unchanged
        return put_compat(tree, sharding)

    def _emit_timeline(self, run_walls, total: float, tele) -> None:
        """Timeline-cadence attribution: apportion each fused run's
        blocked wall across its stages by the run's kind-weighted op
        shares, then emit the legacy interpreter's exact per-stage gauge
        and counter set (executor.py's host-attributed block) plus the
        ``pp/bubble_frac`` rollup and per-run ``pp/run/r{R}/k{K}/wall_s``.
        Boundary transfers are not timed — their wall reads as bubble on
        every stage, matching the MPMD convention that comm off the
        critical path is idle time."""
        busy = [0.0] * self.num_stages
        for ent, wall in run_walls:
            for s, frac in ent.stage_shares.items():
                busy[s] += wall * frac
            tele.gauge(
                f"pp/run/r{ent.rank}/k{ent.index}/wall_s"
            ).set(wall)
        fracs = []
        for s in range(self.num_stages):
            bubble = max(total - busy[s], 0.0)
            frac = bubble / total if total > 0 else 0.0
            tele.gauge(f"pp/s{s}/busy_s").set(busy[s])
            tele.gauge(f"pp/s{s}/bubble_s").set(bubble)
            tele.gauge(f"pp/s{s}/bubble_frac").set(frac)
            tele.counter(f"pp/s{s}/busy_total_s").add(busy[s])
            tele.counter(f"pp/s{s}/bubble_total_s").add(bubble)
            fracs.append(frac)
        if fracs:
            tele.gauge("pp/bubble_frac").set(sum(fracs) / len(fracs))

    def step(
        self,
        microbatches: list[PyTree],
        *,
        numerics_on: bool = False,
        numerics_moments: dict[int, PyTree] | None = None,
        timeline: bool = False,
    ) -> PipelineExecutionResult:
        if len(microbatches) != self.num_microbatches:
            raise ValueError(
                f"program compiled for {self.num_microbatches} "
                f"microbatches, got {len(microbatches)}"
            )
        if self.numerics and numerics_moments is None:
            raise ValueError(
                "executor built with numerics=True: step() needs "
                "numerics_moments every call (the traced flag only "
                "flips the cond branch; the program signature is fixed)"
            )
        first = self.stages[0]
        last = self._last
        t_step0 = time.perf_counter()
        env: dict[tuple, Any] = {}
        kwargs_h: list[PyTree] = []
        with annotate("pp.stage_inputs"):
            for mb, micro in enumerate(microbatches):
                carry, kw, state = first.task.split_microbatch(micro)
                kwargs_h.append(kw)
                if ("carry", mb) in self._ext_consumed:
                    env[("carry", mb)] = self._stage_ext(
                        carry, first.carry_sharding
                    )
                if ("state", mb) in self._ext_consumed:
                    env[("state", mb)] = self._stage_ext(
                        state, last.state_sharding
                    )
            if self.numerics:
                flag = bool(numerics_on)
                for s in self._grad_final:
                    rt = self.stages[s]
                    env[("nu", s)] = numerics_moments.get(s)
                    flag_sharding = None
                    if rt.mesh is not None:
                        flag_sharding = jax.sharding.NamedSharding(
                            rt.mesh, jax.sharding.PartitionSpec()
                        )
                    env[("flag", s)] = self._stage_ext(
                        jnp.asarray(flag), flag_sharding
                    )

        dispatches = 0
        run_walls: list[tuple[_Run, float]] = []
        for pos, ent in enumerate(self._seq):
            for k in self._stage_before[pos]:
                env[k] = self._stage_ext(
                    kwargs_h[k[2]], self.stages[k[1]].kwargs_sharding
                )
            if isinstance(ent, _Run):
                args = [self.stages[s].params for s in ent.param_stages]
                args += [env[k] for k in ent.input_keys]
                # timeline cadence: serialize the dispatch loop (block per
                # run) so each run's wall is attributable. Off-cadence the
                # only delta is this false host branch — zero added
                # dispatches, transfers, or readbacks (bench-gate pinned).
                t_run = time.perf_counter() if timeline else 0.0
                with annotate(ent.label), self._mesh_scope(ent.rank):
                    outs = ent.fn(*args)
                if timeline:
                    # the timeline plane's one deliberate sync: only on
                    # pp_timeline_every_steps cadence steps (timeline=False
                    # skips it), where serializing the loop IS the measurement
                    # d9d-lint: disable=D9D003 — cadence-only attribution sync
                    jax.block_until_ready(outs)
                    run_walls.append((ent, time.perf_counter() - t_run))
                dispatches += 1
                for k, v in zip(ent.output_keys, outs):
                    env[k] = v
            else:
                with annotate(ent.label):
                    env[ent.dst] = put_compat(
                        env[ent.src],
                        self.stages[ent.dst_stage].carry_sharding,
                    )
            for k in ent.drop_after:
                env.pop(k, None)
        self.last_dispatches = dispatches

        numerics_out = None
        if self.numerics:
            numerics_out = {
                s: env[("num", s)] for s in sorted(self._grad_final)
            }
        total = time.perf_counter() - t_step0
        tele = self._tele
        tele.registry.record_span(
            "pp/step", t_step0, total,
            meta={
                "stages": self.num_stages, "train": self.train,
                "fused": True,
            },
        )
        tele.gauge("pp/fused_dispatches").set(dispatches)
        tele.gauge("pp/fused_transfers").set(self.num_transfers)
        tele.gauge("pp/fused_programs").set(self.num_fused_programs)
        if timeline:
            self._emit_timeline(run_walls, total, tele)

        return PipelineExecutionResult(
            grads=(
                {s: env[k] for s, k in sorted(self._grad_final.items())}
                if self.train
                else None
            ),
            loss_sum=env.get(("loss",)),
            weight_sum=env.get(("wsum",)),
            metrics=dict(env.get(("met",), {})),
            outputs=(
                [env.get(("out", mb)) for mb in range(self.num_microbatches)]
                if not self.train
                else None
            ),
            numerics=numerics_out,
        )
