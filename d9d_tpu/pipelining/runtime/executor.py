"""Pipeline schedule executor: interprets a compiled action program.

Reference: d9d/pipelining/runtime/executor.py:16 (PipelineScheduleExecutor)
— a VM iterating ``program[rank]`` per process, with NCCL P2P at Send/Recv
actions. Under JAX's single controller one executor interprets the *merged*
program (the dependency-proven global linearization from
``validate_program``): every rank's compute is dispatched from one Python
loop, device-to-device transfers happen at Send actions via
``jax.device_put`` onto the consuming stage's sharding, and XLA's async
dispatch provides the overlap the reference gets from per-process
execution — the host races ahead enqueuing work for all stage device
groups while earlier computations are still running.

Because every action costs host dispatch time (BASELINE.md measured ≈9%
at pp=2/µB=8 with zero real communication), the interpretation loop is
pre-compiled at construction: the program is flattened once into a list of
(bound handler, action, trace label) triples — no isinstance chains or
label formatting on the step path — microbatch kwargs are staged onto
stage submeshes through a bounded sliding window ordered by the
schedule's first use (async puts that overlap compute instead of
splitting dispatch gaps mid-schedule, refilled as entries are consumed,
so total staged residency stays O(window + in-flight) rather than
O(stages x microbatches)), and per-microbatch loss statistics are summed
in ONE fused jit at step end instead of one tiny dispatch per microbatch. Each action dispatch is wrapped in a gated
``TraceAnnotation`` (core/tracing.py) mirroring the reference's
``record_function`` per action (runtime/executor.py:96).

Buffer lifecycle (reference computations.py:29,121): the executor stores
per (stage, microbatch) only the input carry (the remat residual) and the
output cotangent between its producing backward and consuming
weight-backward; entries are freed at last use, which bounds pipeline
memory exactly like the reference's per-microbatch caches.
"""

import dataclasses
import time
from typing import Any

import jax

from d9d_tpu.core.tracing import annotate
from d9d_tpu.telemetry import get_telemetry, tracked_jit
from d9d_tpu.core.types import PyTree
from d9d_tpu.pipelining.program.actions import (
    Action,
    BackwardFull,
    BackwardInput,
    BackwardRecv,
    BackwardSend,
    BackwardWeight,
    Compose,
    ForwardCompute,
    ForwardRecv,
    ForwardSend,
    PipelineProgram,
)
from d9d_tpu.pipelining.program.validate import validate_program
from d9d_tpu.pipelining.runtime.stage import PipelineStageRuntime
from d9d_tpu.pipelining.runtime.transfer import put_compat

__all__ = ["PipelineExecutionResult", "PipelineScheduleExecutor"]


@dataclasses.dataclass
class PipelineExecutionResult:
    """Per-step outcome: unscaled per-stage grad sums + loss statistics."""

    grads: dict[int, PyTree] | None  # stage id → Σ_mb grads (unscaled)
    loss_sum: Any
    weight_sum: Any
    metrics: dict[str, Any]
    outputs: list[PyTree] | None = None  # forward-only: last-stage aux per mb
    # fused runtime only: stage id → pp_numerics/s{S} stats vector (NaN
    # off cadence — the traced flag flips a cond branch, not the program)
    numerics: dict[int, Any] | None = None


class _StepState:
    """Per-step mutable buffers (fresh per ``step`` call)."""

    __slots__ = (
        "carries", "states", "inputs", "kwargs_d", "kwargs_h", "kwargs_next",
        "kwargs_staged", "cots", "grad_in", "fwd_out", "grads", "aux",
        "outputs", "weight_done", "saved",
    )

    def __init__(self, num_microbatches: int):
        self.carries: dict[int, PyTree] = {}  # mb → first-stage carry
        self.states: dict[int, PyTree] = {}  # mb → last-stage task state
        # per-(stage, mb) device buffers
        self.inputs: dict[tuple[int, int], PyTree] = {}  # carry in (residual)
        self.kwargs_d: dict[tuple[int, int], PyTree] = {}  # kwargs on submesh
        self.kwargs_h: list[PyTree] = []  # mb → host kwargs tree
        self.kwargs_next: int = 0  # index into the first-use staging order
        self.kwargs_staged: set[tuple[int, int]] = set()  # ever staged
        self.cots: dict[tuple[int, int], PyTree] = {}  # cot wrt stage output
        self.grad_in: dict[tuple[int, int], PyTree] = {}  # dI awaiting send
        self.fwd_out: dict[tuple[int, int], PyTree] = {}  # out awaiting send
        self.grads: dict[int, PyTree] = {}
        self.aux: list[Any] = []  # (loss, weight, metrics) per microbatch
        self.outputs: list[PyTree | None] = [None] * num_microbatches
        # (stage, mb) whose weight grads were already produced at the I slot
        self.weight_done: set[tuple[int, int]] = set()
        # cache_acts: (stage, mb) → backward residuals awaiting the W slot
        self.saved: dict[tuple[int, int], Any] = {}


class PipelineScheduleExecutor:
    """Executes one train/eval step per call.

    ``stages`` maps *global stage id* → runtime. The executor owns no
    parameters — it reads ``stage.params`` at each action, so optimizer
    updates between steps are picked up automatically.
    """

    def __init__(
        self,
        *,
        stages: dict[int, PipelineStageRuntime],
        program: PipelineProgram,
        stage_owner: dict[int, int],
        num_microbatches: int,
        train: bool = True,
    ):
        self.stages = stages
        self.num_stages = len(stages)
        self.num_microbatches = num_microbatches
        self.stage_owner = stage_owner
        self.train = train
        sim = validate_program(
            program,
            num_stages=self.num_stages,
            num_microbatches=num_microbatches,
            stage_owner=stage_owner,
            train=train,
        )
        self.order: tuple[tuple[int, Action], ...] = sim.order
        self._last = self.stages[self.num_stages - 1]
        self._sum_aux = None  # built lazily (jit over the aux list pytree)
        self._plan = self._compile_plan()
        self._tele = get_telemetry()

    # ------------------------------------------------------------------
    # plan compilation: one (handler, action, label) triple per action,
    # Compose flattened — the step loop does zero type dispatch

    _HANDLERS = {
        ForwardCompute: "_act_forward",
        ForwardSend: "_act_forward_send",
        BackwardFull: "_act_backward_full",
        BackwardInput: "_act_backward_input",
        BackwardWeight: "_act_backward_weight",
        BackwardSend: "_act_backward_send",
    }

    _LABELS = {
        ForwardCompute: "fwd",
        ForwardSend: "fwd_send",
        BackwardFull: "bwd",
        BackwardInput: "bwd_dI",
        BackwardWeight: "bwd_dW",
        BackwardSend: "bwd_send",
    }

    def _compile_plan(self):
        plan = []

        def add(action: Action) -> None:
            if isinstance(action, Compose):
                for member in action.actions:
                    add(member)
                return
            if isinstance(action, (ForwardRecv, BackwardRecv)):
                return  # transfers already target the consumer at the Send
            name = self._HANDLERS.get(type(action))
            if name is None:  # pragma: no cover
                raise TypeError(f"unknown action {action!r}")
            label = (
                f"pp.{self._LABELS[type(action)]}"
                f".s{action.stage}.mb{action.microbatch}"
            )
            plan.append((getattr(self, name), action, label))

        for _rank, action in self.order:
            add(action)
        # kwargs staging order: (stage, mb) pairs by FIRST use in the plan
        # (sends never read kwargs) — the sliding window stages whatever
        # the schedule needs soonest, regardless of stage
        seen: set[tuple[int, int]] = set()
        first_use: list[tuple[int, int]] = []
        for _h, action, _l in plan:
            if isinstance(action, (ForwardSend, BackwardSend)):
                continue
            key = (action.stage, action.microbatch)
            if key not in seen:
                seen.add(key)
                first_use.append(key)
        self._kwargs_first_use = tuple(first_use)
        return tuple(plan)

    # ------------------------------------------------------------------

    @staticmethod
    def _put(tree: PyTree, sharding) -> PyTree:
        return put_compat(tree, sharding)

    def step(self, microbatches: list[PyTree]) -> PipelineExecutionResult:
        """Run the program over ``microbatches`` (list of host/device pytrees)."""
        if len(microbatches) != self.num_microbatches:
            raise ValueError(
                f"program compiled for {self.num_microbatches} microbatches, "
                f"got {len(microbatches)}"
            )
        first = self.stages[0]
        last = self._last

        t_step0 = time.perf_counter()
        # per-stage busy seconds, host-attributed: time this single
        # controller spends dispatching each stage's actions. Under XLA
        # async dispatch this measures the dispatch loop (the quantity the
        # trace-annotation tables attribute); the residual
        # ``step − busy`` is that stage's per-step bubble from the host's
        # point of view — the observable MPMD-pipeline schedule tuning
        # actually optimizes (docs/design/observability.md).
        busy = [0.0] * self.num_stages

        st = _StepState(self.num_microbatches)
        with annotate("pp.stage_inputs"):
            for mb, micro in enumerate(microbatches):
                carry, kw, state = first.task.split_microbatch(micro)
                st.carries[mb] = self._put(carry, first.carry_sharding)
                st.kwargs_h.append(kw)
                st.states[mb] = self._put(state, last.state_sharding)
            # pre-stage a bounded window of kwargs in the schedule's
            # first-use order: the puts are async and overlap the first
            # computes instead of splitting dispatch gaps mid-schedule,
            # while TOTAL staged residency stays O(window + in-flight)
            # instead of O(stages x microbatches) — each consumed entry
            # refills the window (_drop_kwargs)
            window = min(
                len(self._kwargs_first_use), 2 * self.num_stages + 2
            )
            for key in self._kwargs_first_use[:window]:
                self._stage_kwargs(st, *key)
            st.kwargs_next = window

        for handler, action, label in self._plan:
            with annotate(label):
                t_act = time.perf_counter()
                handler(st, action)
                busy[action.stage] += time.perf_counter() - t_act

        loss_sum = weight_sum = None
        metrics_sum: dict[str, Any] = {}
        if st.aux:
            # one fused jit sums every microbatch's (loss, weight, metrics)
            # on the last stage's devices — replaces per-microbatch scalar
            # dispatches on the action path. Fusable only when every
            # microbatch produced the same aux structure; a task emitting
            # different metric keys per microbatch falls back to the
            # key-unioning merge.
            with annotate("pp.loss_sum"), last._scoped():
                structures = {jax.tree.structure(a) for a in st.aux}
                if len(structures) == 1:
                    if self._sum_aux is None:
                        self._sum_aux = tracked_jit(
                            lambda auxes: jax.tree.reduce(
                                lambda a, b: jax.tree.map(
                                    lambda x, y: x + y, a, b
                                ),
                                auxes,
                                is_leaf=lambda t: isinstance(t, tuple)
                                and len(t) == 3,
                            ),
                            name="pp/loss_sum",
                        )
                    loss_sum, weight_sum, metrics_sum = self._sum_aux(st.aux)
                else:
                    for loss, weight, metrics in st.aux:
                        loss_sum = loss if loss_sum is None else loss_sum + loss
                        weight_sum = (
                            weight if weight_sum is None
                            else weight_sum + weight
                        )
                        for k, v in metrics.items():
                            metrics_sum[k] = (
                                v if k not in metrics_sum
                                else metrics_sum[k] + v
                            )

        total = time.perf_counter() - t_step0
        tele = self._tele
        tele.registry.record_span(
            "pp/step", t_step0, total,
            meta={"stages": self.num_stages, "train": self.train},
        )
        for s in range(self.num_stages):
            bubble = max(total - busy[s], 0.0)
            tele.gauge(f"pp/s{s}/busy_s").set(busy[s])
            tele.gauge(f"pp/s{s}/bubble_s").set(bubble)
            tele.gauge(f"pp/s{s}/bubble_frac").set(
                bubble / total if total > 0 else 0.0
            )
            tele.counter(f"pp/s{s}/busy_total_s").add(busy[s])
            tele.counter(f"pp/s{s}/bubble_total_s").add(bubble)

        return PipelineExecutionResult(
            grads=st.grads if self.train else None,
            loss_sum=loss_sum,
            weight_sum=weight_sum,
            metrics=metrics_sum,
            outputs=st.outputs if not self.train else None,
        )

    # ------------------------------------------------------------------
    # shared helpers

    def _stage_kwargs(self, st: _StepState, s: int, mb: int) -> None:
        st.kwargs_staged.add((s, mb))
        st.kwargs_d[(s, mb)] = self._put(
            st.kwargs_h[mb], self.stages[s].kwargs_sharding
        )

    def _kwargs(self, st: _StepState, s: int, mb: int) -> PyTree:
        kw = st.kwargs_d.get((s, mb))
        if kw is None:  # outside the pre-staged window: stage on demand
            self._stage_kwargs(st, s, mb)
            kw = st.kwargs_d[(s, mb)]
        return kw

    def _drop_kwargs(self, st: _StepState, s: int, mb: int) -> None:
        """Free a consumed kwargs buffer and refill the staging window
        with the next first-use entry not already staged."""
        st.kwargs_d.pop((s, mb), None)
        order = self._kwargs_first_use
        nxt = st.kwargs_next
        while nxt < len(order) and order[nxt] in st.kwargs_staged:
            nxt += 1
        if nxt < len(order):
            self._stage_kwargs(st, *order[nxt])
            nxt += 1
        st.kwargs_next = nxt

    def _add_grads(self, st: _StepState, s: int, gp: PyTree) -> None:
        stage = self.stages[s]
        if s not in st.grads:
            # d9d-lint: disable=D9D008 — legacy parity oracle: the per-action interpreter stays one release as the fused runtime's bit-exactness reference
            st.grads[s] = stage.cast_grads(gp)
        else:
            # d9d-lint: disable=D9D008 — legacy parity oracle (see cast_grads above)
            st.grads[s] = stage.accumulate(st.grads[s], gp)

    def _route_input_grad(
        self, st: _StepState, s: int, mb: int, gc: PyTree
    ) -> None:
        """Store dI for the downstream (stage-1) consumer."""
        if s == 0:
            return
        if self.stage_owner[s - 1] == self.stage_owner[s]:
            st.cots[(s - 1, mb)] = gc  # local edge: no send action exists
        else:
            st.grad_in[(s, mb)] = gc  # cross-rank: BackwardSend moves it

    # ------------------------------------------------------------------
    # action handlers (one per action type, bound into the plan)

    def _act_forward(self, st: _StepState, action: Action) -> None:
        s, mb = action.stage, action.microbatch
        stage = self.stages[s]
        if s == 0:
            st.inputs[(0, mb)] = st.carries.pop(mb)
        elif (s, mb) not in st.inputs:
            # same-rank edge: pull directly from the producing stage
            st.inputs[(s, mb)] = st.fwd_out.pop((s - 1, mb))
        carry = st.inputs[(s, mb)]
        kw = self._kwargs(st, s, mb)
        if stage.info.is_last:
            if not self.train:
                if stage.has_output_fn:
                    st.outputs[mb] = stage.forward_outputs(
                        carry, kw, st.states[mb]
                    )
                else:
                    # d9d-lint: disable=D9D008 — legacy parity oracle (one dispatch per action is this interpreter's contract)
                    aux = stage.forward_loss(carry, kw, st.states[mb])
                    st.aux.append(aux)
                    st.outputs[mb] = aux
                st.inputs.pop((s, mb), None)
                self._drop_kwargs(st, s, mb)  # eval: forward is last use
            # train: forward is folded into the backward's
            # value_and_grad (remat), nothing to run here
        else:
            # d9d-lint: disable=D9D008 — legacy parity oracle (one dispatch per action is this interpreter's contract)
            st.fwd_out[(s, mb)] = stage.forward(carry, kw)
            if not self.train:
                st.inputs.pop((s, mb), None)
                self._drop_kwargs(st, s, mb)  # eval: forward is last use

    def _act_forward_send(self, st: _StepState, action: Action) -> None:
        s, mb = action.stage, action.microbatch
        out = st.fwd_out.pop((s, mb))
        nxt = self.stages[s + 1]
        st.inputs[(s + 1, mb)] = self._put(out, nxt.carry_sharding)

    def _act_backward_full(self, st: _StepState, action: Action) -> None:
        s, mb = action.stage, action.microbatch
        stage = self.stages[s]
        cot = None if stage.info.is_last else st.cots.pop((s, mb))
        state = st.states.get(mb) if stage.info.is_last else None
        # d9d-lint: disable=D9D008 — legacy parity oracle (one dispatch per action is this interpreter's contract)
        gp, gc, aux = stage.backward_full(
            st.inputs.pop((s, mb)), self._kwargs(st, s, mb), cot, state
        )
        self._drop_kwargs(st, s, mb)
        if aux is not None:
            st.aux.append(aux)
        self._add_grads(st, s, gp)
        self._route_input_grad(st, s, mb, gc)

    def _act_backward_input(self, st: _StepState, action: Action) -> None:
        s, mb = action.stage, action.microbatch
        stage = self.stages[s]
        if stage.residual_policy == "cache_acts":
            # true zero-bubble split: dI + residual capture now, dW at the
            # deferred W slot from the captured residuals
            cot = None if stage.info.is_last else st.cots.pop((s, mb), None)
            state = st.states.get(mb) if stage.info.is_last else None
            # d9d-lint: disable=D9D008 — legacy parity oracle (one dispatch per action is this interpreter's contract)
            gc, aux, saved = stage.backward_input_acts(
                st.inputs.pop((s, mb)), self._kwargs(st, s, mb), cot, state
            )
            self._drop_kwargs(st, s, mb)  # residuals replace kwargs reuse
            st.saved[(s, mb)] = saved
            if aux is not None:
                st.aux.append(aux)
            if gc is not None:
                self._route_input_grad(st, s, mb, gc)
            return
        if stage.residual_policy == "cache_full":
            # fused backward at the I slot: weight grads accumulate
            # now, the deferred BackwardWeight becomes a no-op
            cot = None if stage.info.is_last else st.cots.pop((s, mb), None)
            state = st.states.get(mb) if stage.info.is_last else None
            # d9d-lint: disable=D9D008 — legacy parity oracle (one dispatch per action is this interpreter's contract)
            gp, gc, aux = stage.backward_full(
                st.inputs.pop((s, mb)), self._kwargs(st, s, mb), cot, state
            )
            self._drop_kwargs(st, s, mb)
            if aux is not None:
                st.aux.append(aux)
            self._add_grads(st, s, gp)
            self._route_input_grad(st, s, mb, gc)
            st.weight_done.add((s, mb))
            return
        cot = None if stage.info.is_last else st.cots.get((s, mb))
        state = st.states.get(mb) if stage.info.is_last else None
        # d9d-lint: disable=D9D008 — legacy parity oracle (one dispatch per action is this interpreter's contract)
        gc, aux = stage.backward_input(
            st.inputs[(s, mb)], self._kwargs(st, s, mb), cot, state
        )
        if aux is not None:
            st.aux.append(aux)
        if gc is not None:
            self._route_input_grad(st, s, mb, gc)
        # inputs/cot stay alive for the deferred weight backward

    def _act_backward_weight(self, st: _StepState, action: Action) -> None:
        s, mb = action.stage, action.microbatch
        stage = self.stages[s]
        if stage.residual_policy == "cache_acts":
            # d9d-lint: disable=D9D008 — legacy parity oracle (one dispatch per action is this interpreter's contract)
            gp = stage.backward_weight_acts(st.saved.pop((s, mb)))
            self._add_grads(st, s, gp)
            return
        if (s, mb) in st.weight_done:
            st.weight_done.discard((s, mb))
            return
        kw = self._kwargs(st, s, mb)
        cot = None if stage.info.is_last else st.cots.pop((s, mb), None)
        state = st.states.get(mb) if stage.info.is_last else None
        # d9d-lint: disable=D9D008 — legacy parity oracle (one dispatch per action is this interpreter's contract)
        gp = stage.backward_weight(st.inputs.pop((s, mb)), kw, cot, state)
        self._drop_kwargs(st, s, mb)
        self._add_grads(st, s, gp)

    def _act_backward_send(self, st: _StepState, action: Action) -> None:
        s, mb = action.stage, action.microbatch
        g = st.grad_in.pop((s, mb))
        prev = self.stages[s - 1]
        st.cots[(s - 1, mb)] = self._put(g, prev.carry_sharding)
