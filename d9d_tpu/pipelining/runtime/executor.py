"""Pipeline schedule executor: interprets a compiled action program.

Reference: d9d/pipelining/runtime/executor.py:16 (PipelineScheduleExecutor)
— a VM iterating ``program[rank]`` per process, with NCCL P2P at Send/Recv
actions. Under JAX's single controller one executor interprets the *merged*
program (the dependency-proven global linearization from
``validate_program``): every rank's compute is dispatched from one Python
loop, device-to-device transfers happen at Send actions via
``jax.device_put`` onto the consuming stage's sharding, and XLA's async
dispatch provides the overlap the reference gets from per-process
execution — the host races ahead enqueuing work for all stage device
groups while earlier computations are still running.

Buffer lifecycle (reference computations.py:29,121): the executor stores
per (stage, microbatch) only the input carry (the remat residual) and the
output cotangent between its producing backward and consuming
weight-backward; entries are freed at last use, which bounds pipeline
memory exactly like the reference's per-microbatch caches.
"""

import dataclasses
from typing import Any

import jax

from d9d_tpu.core.types import PyTree
from d9d_tpu.pipelining.program.actions import (
    Action,
    BackwardFull,
    BackwardInput,
    BackwardRecv,
    BackwardSend,
    BackwardWeight,
    Compose,
    ForwardCompute,
    ForwardRecv,
    ForwardSend,
    PipelineProgram,
)
from d9d_tpu.pipelining.program.validate import validate_program
from d9d_tpu.pipelining.runtime.stage import PipelineStageRuntime
from d9d_tpu.pipelining.runtime.transfer import put_compat

__all__ = ["PipelineExecutionResult", "PipelineScheduleExecutor"]


@dataclasses.dataclass
class PipelineExecutionResult:
    """Per-step outcome: unscaled per-stage grad sums + loss statistics."""

    grads: dict[int, PyTree] | None  # stage id → Σ_mb grads (unscaled)
    loss_sum: Any
    weight_sum: Any
    metrics: dict[str, Any]
    outputs: list[PyTree] | None = None  # forward-only: last-stage aux per mb


class PipelineScheduleExecutor:
    """Executes one train/eval step per call.

    ``stages`` maps *global stage id* → runtime. The executor owns no
    parameters — it reads ``stage.params`` at each action, so optimizer
    updates between steps are picked up automatically.
    """

    def __init__(
        self,
        *,
        stages: dict[int, PipelineStageRuntime],
        program: PipelineProgram,
        stage_owner: dict[int, int],
        num_microbatches: int,
        train: bool = True,
    ):
        self.stages = stages
        self.num_stages = len(stages)
        self.num_microbatches = num_microbatches
        self.stage_owner = stage_owner
        self.train = train
        sim = validate_program(
            program,
            num_stages=self.num_stages,
            num_microbatches=num_microbatches,
            stage_owner=stage_owner,
            train=train,
        )
        self.order: tuple[tuple[int, Action], ...] = sim.order

    # ------------------------------------------------------------------

    @staticmethod
    def _put(tree: PyTree, sharding) -> PyTree:
        return put_compat(tree, sharding)

    def step(self, microbatches: list[PyTree]) -> PipelineExecutionResult:
        """Run the program over ``microbatches`` (list of host/device pytrees)."""
        if len(microbatches) != self.num_microbatches:
            raise ValueError(
                f"program compiled for {self.num_microbatches} microbatches, "
                f"got {len(microbatches)}"
            )
        first = self.stages[0]
        last = self.stages[self.num_stages - 1]

        carries: dict[int, PyTree] = {}  # mb → first-stage carry
        kwargs_h: dict[int, PyTree] = {}  # mb → host kwargs tree
        states: dict[int, PyTree] = {}  # mb → last-stage task state
        for mb, micro in enumerate(microbatches):
            carry, kw, state = first.task.split_microbatch(micro)
            carries[mb] = self._put(carry, first.carry_sharding)
            kwargs_h[mb] = kw
            states[mb] = self._put(state, last.state_sharding)

        # per-(stage, mb) device buffers
        inputs: dict[tuple[int, int], PyTree] = {}  # carry in (remat residual)
        kwargs_d: dict[tuple[int, int], PyTree] = {}  # kwargs on stage submesh
        cots: dict[tuple[int, int], PyTree] = {}  # cotangent wrt stage output
        grad_in: dict[tuple[int, int], PyTree] = {}  # input grad awaiting send
        fwd_out: dict[tuple[int, int], PyTree] = {}  # output awaiting send/use

        grads: dict[int, PyTree] = {}
        loss_sum = weight_sum = None
        metrics_sum: dict[str, Any] = {}
        outputs: list[PyTree | None] = [None] * self.num_microbatches
        # (stage, mb) whose weight grads were already produced at the I slot
        weight_done: set[tuple[int, int]] = set()

        def stage_kwargs(s: int, mb: int) -> PyTree:
            if (s, mb) not in kwargs_d:
                kwargs_d[(s, mb)] = self._put(
                    kwargs_h[mb], self.stages[s].kwargs_sharding
                )
            return kwargs_d[(s, mb)]

        def add_loss(aux):
            nonlocal loss_sum, weight_sum
            loss, weight, metrics = aux
            # scalar accumulation runs on the last stage's devices; scope its
            # mesh so an ambient full mesh never conflicts with them
            with last._scoped():
                loss_sum = loss if loss_sum is None else loss_sum + loss
                weight_sum = (
                    weight if weight_sum is None else weight_sum + weight
                )
                for k, v in metrics.items():
                    metrics_sum[k] = (
                        v if k not in metrics_sum else metrics_sum[k] + v
                    )

        def add_grads(s: int, gp: PyTree):
            stage = self.stages[s]
            if s not in grads:
                grads[s] = stage.cast_grads(gp)
            else:
                grads[s] = stage.accumulate(grads[s], gp)

        def route_input_grad(s: int, mb: int, gc: PyTree):
            """Store dI for the downstream (stage-1) consumer."""
            if s == 0:
                return
            if self.stage_owner[s - 1] == self.stage_owner[s]:
                cots[(s - 1, mb)] = gc  # local edge: no send action exists
            else:
                grad_in[(s, mb)] = gc  # cross-rank: BackwardSend will move it

        def execute(action: Action) -> None:
            if isinstance(action, Compose):
                for member in action.actions:
                    execute(member)
                return
            s, mb = action.stage, action.microbatch
            stage = self.stages[s]
            if isinstance(action, ForwardCompute):
                if s == 0:
                    inputs[(0, mb)] = carries.pop(mb)
                elif (s, mb) not in inputs:
                    # same-rank edge: pull directly from the producing stage
                    inputs[(s, mb)] = fwd_out.pop((s - 1, mb))
                carry = inputs[(s, mb)]
                kw = stage_kwargs(s, mb)
                if stage.info.is_last:
                    if not self.train:
                        if stage.has_output_fn:
                            outputs[mb] = stage.forward_outputs(
                                carry, kw, states[mb]
                            )
                        else:
                            aux = stage.forward_loss(carry, kw, states[mb])
                            add_loss(aux)
                            outputs[mb] = aux
                        inputs.pop((s, mb), None)
                    # train: forward is folded into the backward's
                    # value_and_grad (remat), nothing to run here
                else:
                    fwd_out[(s, mb)] = stage.forward(carry, kw)
                    if not self.train:
                        inputs.pop((s, mb), None)
            elif isinstance(action, ForwardSend):
                out = fwd_out.pop((s, mb))
                nxt = self.stages[s + 1]
                inputs[(s + 1, mb)] = self._put(out, nxt.carry_sharding)
            elif isinstance(action, ForwardRecv):
                pass  # transfer already targeted this stage at the Send
            elif isinstance(action, BackwardFull):
                cot = None if stage.info.is_last else cots.pop((s, mb))
                state = states.get(mb) if stage.info.is_last else None
                gp, gc, aux = stage.backward_full(
                    inputs.pop((s, mb)), stage_kwargs(s, mb), cot, state
                )
                kwargs_d.pop((s, mb), None)
                if aux is not None:
                    add_loss(aux)
                add_grads(s, gp)
                route_input_grad(s, mb, gc)
            elif isinstance(action, BackwardInput):
                if stage.residual_policy == "cache_full":
                    # fused backward at the I slot: weight grads accumulate
                    # now, the deferred BackwardWeight becomes a no-op
                    cot = None if stage.info.is_last else cots.pop((s, mb), None)
                    state = states.get(mb) if stage.info.is_last else None
                    gp, gc, aux = stage.backward_full(
                        inputs.pop((s, mb)), stage_kwargs(s, mb), cot, state
                    )
                    kwargs_d.pop((s, mb), None)
                    if aux is not None:
                        add_loss(aux)
                    add_grads(s, gp)
                    route_input_grad(s, mb, gc)
                    weight_done.add((s, mb))
                    return
                cot = None if stage.info.is_last else cots.get((s, mb))
                state = states.get(mb) if stage.info.is_last else None
                gc, aux = stage.backward_input(
                    inputs[(s, mb)], stage_kwargs(s, mb), cot, state
                )
                if aux is not None:
                    add_loss(aux)
                if gc is not None:
                    route_input_grad(s, mb, gc)
                # inputs/cot stay alive for the deferred weight backward
            elif isinstance(action, BackwardWeight):
                if (s, mb) in weight_done:
                    weight_done.discard((s, mb))
                    return
                kw = stage_kwargs(s, mb)
                cot = None if stage.info.is_last else cots.pop((s, mb), None)
                state = states.get(mb) if stage.info.is_last else None
                gp = stage.backward_weight(inputs.pop((s, mb)), kw, cot, state)
                kwargs_d.pop((s, mb), None)
                add_grads(s, gp)
            elif isinstance(action, BackwardSend):
                g = grad_in.pop((s, mb))
                prev = self.stages[s - 1]
                cots[(s - 1, mb)] = self._put(g, prev.carry_sharding)
            elif isinstance(action, BackwardRecv):
                pass
            else:  # pragma: no cover
                raise TypeError(f"unknown action {action!r}")

        for _rank, action in self.order:
            execute(action)

        return PipelineExecutionResult(
            grads=grads if self.train else None,
            loss_sum=loss_sum,
            weight_sum=weight_sum,
            metrics=metrics_sum,
            outputs=outputs if not self.train else None,
        )
