"""Cross-stage-mesh transfers that work on every runtime.

Pipeline stages live on different submeshes, and the executor/optimizer
move activations, grad-norm scalars, and clip factors between them with
``jax.device_put``. Runtimes differ in what they accept for a
device->device copy between *different device sets*: TPU (TFRT) supports
it (experimentally) — the fast path — while CPU multi-controller rejects
it. ``put_compat`` falls back to reassembling from addressable shards:
for every destination device this process owns, the matching global slice
must already live on a source device this process owns, which holds for
replicated values (every process has a local copy) and for pipeline
layouts that keep stage boundaries process-local (interleave processes
across the non-pp axes). Single-device copies are always legal and stay
async — no host round-trip.
"""

import jax

from d9d_tpu.core.types import PyTree

__all__ = ["put_compat"]


def _tuple_index(idx) -> tuple:
    return tuple(
        (s.start, s.stop, s.step) if isinstance(s, slice) else s for s in idx
    )


def _shardwise_put(x: jax.Array, sharding) -> jax.Array:
    if not hasattr(x, "addressable_shards"):
        return jax.device_put(x, sharding)
    by_index = {}
    for s in x.addressable_shards:
        by_index.setdefault(_tuple_index(s.index), s.data)
    idx_map = sharding.devices_indices_map(x.shape)
    pieces = []
    for dev in sharding.addressable_devices:
        key = _tuple_index(idx_map[dev])
        if key not in by_index:
            raise ValueError(
                "pipeline stage transfer needs a slice this process does "
                "not own; lay pp stages out so every process holds the "
                "same global slices on both sides of a stage boundary "
                "(interleave processes across the non-pp axes), or use a "
                "runtime with cross-host device transfers"
            )
        pieces.append(jax.device_put(by_index[key], dev))
    return jax.make_array_from_single_device_arrays(x.shape, sharding, pieces)


# Whether this runtime accepts a direct device_put between different
# device sets (TPU/TFRT: yes; CPU multi-controller: no). Classified once:
# when the first cross-set payload put raises ValueError, a tiny dedicated
# probe REPLICATING the failure mode (an array on a source device moved
# onto the destination sharding's device set) decides whether that was a
# capability limit (→ shard-wise fallback forever) or a real error in the
# payload itself (→ re-raised, never masked) (ADVICE r3).
_cross_set_direct: bool | None = None


def _probe_cross_set(src_device, dst_sharding) -> bool:
    """Can this runtime device_put onto a different-device-set sharding?"""
    import numpy as np

    from jax.sharding import NamedSharding, PartitionSpec

    probe = jax.device_put(np.zeros((1,), np.float32), src_device)
    replicated = NamedSharding(dst_sharding.mesh, PartitionSpec())
    try:
        jax.block_until_ready(jax.device_put(probe, replicated))
    except ValueError:
        return False
    return True


def put_compat(tree: PyTree, sharding) -> PyTree:
    """``jax.device_put`` onto ``sharding``, with the shard-wise fallback
    for runtimes that reject different-device-set copies. Same-set puts
    and host->device stages always take the direct path, so unrelated
    device_put failures surface unmasked there."""
    global _cross_set_direct
    if sharding is None:
        return tree

    dst_set = getattr(sharding, "device_set", None)

    def one(x):
        global _cross_set_direct
        src = getattr(x, "sharding", None)
        cross = (
            src is not None
            and dst_set is not None
            and getattr(src, "device_set", dst_set) != dst_set
        )
        if not cross or _cross_set_direct is True:
            return jax.device_put(x, sharding)
        if _cross_set_direct is False:
            return _shardwise_put(x, sharding)
        try:
            out = jax.device_put(x, sharding)
        except ValueError:
            if _probe_cross_set(
                next(iter(src.addressable_devices)), sharding
            ):
                # runtime CAN do cross-set puts — the payload itself is
                # broken; don't let the fallback mask its error
                _cross_set_direct = True
                raise
            _cross_set_direct = False
            return _shardwise_put(x, sharding)
        _cross_set_direct = True
        return out

    return jax.tree.map(one, tree)
