"""Pipeline stage identity and layer distribution.

Reference: d9d/pipelining/api/module.py:8 (``PipelineStageInfo``) and
:38-102 (``distribute_layers_for_pipeline_stage`` — virtual-stage aware).
Models consume this to build only their slice of the layer stack; it is
meaningful even without a pipeline runtime (num_stages=1 = whole model).
"""

import dataclasses


@dataclasses.dataclass(frozen=True)
class PipelineStageInfo:
    """Identity of one stage in a pipeline of ``num_stages`` stages.

    With interleaved (looped/V) schedules a rank holds several *virtual*
    stages; ``stage_index`` numbers stages globally in topological order.
    """

    stage_index: int = 0
    num_stages: int = 1

    def __post_init__(self) -> None:
        if not 0 <= self.stage_index < self.num_stages:
            raise ValueError(
                f"stage_index {self.stage_index} out of range for "
                f"{self.num_stages} stages"
            )

    @property
    def is_first(self) -> bool:
        return self.stage_index == 0

    @property
    def is_last(self) -> bool:
        return self.stage_index == self.num_stages - 1


def distribute_layers_for_pipeline_stage(
    num_layers: int, stage: PipelineStageInfo
) -> range:
    """Global layer ids owned by ``stage``.

    Layers are split as evenly as possible; the *later* stages get the
    smaller shares (first stages also own embeddings, but embeddings are
    cheap next to a layer — matching the reference's bias of giving
    remainder layers to earlier stages, api/module.py:38-102).
    """
    base, rem = divmod(num_layers, stage.num_stages)
    sizes = [base + (1 if i < rem else 0) for i in range(stage.num_stages)]
    start = sum(sizes[: stage.stage_index])
    return range(start, start + sizes[stage.stage_index])
