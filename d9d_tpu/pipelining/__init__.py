from d9d_tpu.pipelining.factory import (
    DualPipeVScheduleConfig,
    GPipeScheduleConfig,
    Interleaved1F1BScheduleConfig,
    InferenceScheduleConfig,
    LoopedBFSScheduleConfig,
    PipelineScheduleConfig,
    ZeroBubble1PScheduleConfig,
    ZeroBubbleVScheduleConfig,
    build_program_builder,
)
from d9d_tpu.pipelining.runtime import (
    FusedPipelineExecutor,
    PipelineExecutionResult,
    PipelineScheduleExecutor,
    PipelineStageRuntime,
    StageTask,
)
from d9d_tpu.pipelining.stage_info import (
    PipelineStageInfo,
    distribute_layers_for_pipeline_stage,
)

__all__ = [
    "DualPipeVScheduleConfig",
    "FusedPipelineExecutor",
    "GPipeScheduleConfig",
    "Interleaved1F1BScheduleConfig",
    "InferenceScheduleConfig",
    "LoopedBFSScheduleConfig",
    "PipelineExecutionResult",
    "PipelineScheduleConfig",
    "PipelineScheduleExecutor",
    "PipelineStageInfo",
    "PipelineStageRuntime",
    "StageTask",
    "ZeroBubble1PScheduleConfig",
    "ZeroBubbleVScheduleConfig",
    "build_program_builder",
    "distribute_layers_for_pipeline_stage",
]
