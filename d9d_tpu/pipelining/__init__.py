from d9d_tpu.pipelining.stage_info import (
    PipelineStageInfo,
    distribute_layers_for_pipeline_stage,
)

__all__ = ["PipelineStageInfo", "distribute_layers_for_pipeline_stage"]
