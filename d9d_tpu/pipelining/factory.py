"""Schedule factory: pydantic config → program builder.

Reference: d9d/pipelining/factory/{config.py:6-78, registry.py, factory.py:92}
— a discriminated-union schedule config resolved through a registry. The
TPU build keeps the config surface; "building" a schedule is composing the
program + comm injection + validation (the executor is wired by the loop).
"""

from typing import Annotated, Literal, Union

import pydantic

from d9d_tpu.pipelining.program.builders import (
    DualPipeVProgramBuilder,
    GPipeProgramBuilder,
    Interleaved1F1BProgramBuilder,
    InferenceProgramBuilder,
    LoopedBFSProgramBuilder,
    ProgramBuilder,
    ZeroBubbleVProgramBuilder,
)

__all__ = [
    "DualPipeVScheduleConfig",
    "GPipeScheduleConfig",
    "Interleaved1F1BScheduleConfig",
    "InferenceScheduleConfig",
    "LoopedBFSScheduleConfig",
    "PipelineScheduleConfig",
    "ZeroBubble1PScheduleConfig",
    "ZeroBubbleVScheduleConfig",
    "build_program_builder",
]


class _RuntimeChoice(pydantic.BaseModel):
    """Executor selection, shared by every schedule config.

    "fused" is the compiled-run MPMD executor (runtime/fused.py): a few
    device-resident programs per step. "legacy" keeps the per-action
    interpreter (runtime/executor.py) — the bit-exact parity oracle,
    scheduled for removal one release after the fused default landed.
    """

    runtime: Literal["fused", "legacy"] = "fused"


class GPipeScheduleConfig(_RuntimeChoice):
    kind: Literal["gpipe"] = "gpipe"
    residual_policy: Literal["remat", "cache_full", "cache_acts"] = "remat"


class InferenceScheduleConfig(_RuntimeChoice):
    kind: Literal["inference"] = "inference"
    stages_per_rank: int = 1


class LoopedBFSScheduleConfig(_RuntimeChoice):
    kind: Literal["looped_bfs"] = "looped_bfs"
    residual_policy: Literal["remat", "cache_full", "cache_acts"] = "remat"
    stages_per_rank: int = 1


class Interleaved1F1BScheduleConfig(_RuntimeChoice):
    kind: Literal["interleaved_1f1b"] = "interleaved_1f1b"
    residual_policy: Literal["remat", "cache_full", "cache_acts"] = "remat"
    stages_per_rank: int = 1


# Zero-bubble schedules default to cache_full per the r3 on-chip
# microbench (tools/bench_pp.py, BASELINE.md): with 2 virtual stages on one
# chip, zb1p/remat ran 30% slower than 1F1B (each dI and dW phase recomputes
# the stage forward) while zb1p/cache_full tied it. remat remains available
# for memory-bound real-PP runs where filling bubbles with W-compute pays.
#
# r4 adds "cache_acts" — the true zero-bubble split (dW at the W slot from
# saved residuals, 1F1B FLOPs; see runtime/stage.py). The dependency-level
# simulation (tools/pp_makespan.py, BASELINE.md r4 table) shows it strictly
# dominating both other policies at every multi-rank config (−12.6% vs 1F1B
# at pp=8/µB=8); it stays opt-in until the residual write+read tax between
# the I and W jits is measured on chip (queued in run_tpu_benches.sh).


class ZeroBubble1PScheduleConfig(_RuntimeChoice):
    kind: Literal["zero_bubble_1p"] = "zero_bubble_1p"
    residual_policy: Literal["remat", "cache_full", "cache_acts"] = "cache_full"
    stages_per_rank: int = 1


class ZeroBubbleVScheduleConfig(_RuntimeChoice):
    kind: Literal["zero_bubble_v"] = "zero_bubble_v"
    residual_policy: Literal["remat", "cache_full", "cache_acts"] = "cache_full"


class DualPipeVScheduleConfig(_RuntimeChoice):
    kind: Literal["dual_pipe_v"] = "dual_pipe_v"
    residual_policy: Literal["remat", "cache_full", "cache_acts"] = "cache_full"


PipelineScheduleConfig = Annotated[
    Union[
        GPipeScheduleConfig,
        InferenceScheduleConfig,
        LoopedBFSScheduleConfig,
        Interleaved1F1BScheduleConfig,
        ZeroBubble1PScheduleConfig,
        ZeroBubbleVScheduleConfig,
        DualPipeVScheduleConfig,
    ],
    pydantic.Field(discriminator="kind"),
]


def build_program_builder(
    config: PipelineScheduleConfig, pp: int
) -> ProgramBuilder:
    if isinstance(config, GPipeScheduleConfig):
        return GPipeProgramBuilder(pp)
    if isinstance(config, InferenceScheduleConfig):
        return InferenceProgramBuilder(pp, config.stages_per_rank)
    if isinstance(config, LoopedBFSScheduleConfig):
        return LoopedBFSProgramBuilder(pp, config.stages_per_rank)
    if isinstance(config, Interleaved1F1BScheduleConfig):
        return Interleaved1F1BProgramBuilder(pp, config.stages_per_rank)
    if isinstance(config, ZeroBubble1PScheduleConfig):
        return Interleaved1F1BProgramBuilder(
            pp, config.stages_per_rank, zero_bubble=True
        )
    if isinstance(config, ZeroBubbleVScheduleConfig):
        return ZeroBubbleVProgramBuilder(pp)
    if isinstance(config, DualPipeVScheduleConfig):
        return DualPipeVProgramBuilder(pp)
    raise TypeError(f"unknown schedule config {config!r}")
