"""Per-stage optimizer aggregate for pipeline-parallel training.

Reference: d9d/pipelining/training/optimizer.py:10 (``PipelinedOptimizer``)
and scheduler.py (``PipelinedLRScheduler``) — one logical optimizer over
the disjoint per-stage parameter groups a pipeline rank owns.

TPU redesign: stages live on *different submeshes*, so there is no single
jit spanning them. Instead each stage gets its own jitted update, and the
cross-stage scalars (gradient norm, loss-weight scale) flow as tiny device
arrays: per-stage squared norms hop to the last stage's devices, one fused
jit there computes the global clip/scale factor (sum-then-scale semantics +
reference's ND grad-norm contract, internals/grad_norm/norm.py:99), and the
factor hops back to each stage. Everything stays in XLA's async stream —
no host sync on the step path.
"""

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
import optax

from d9d_tpu.core import compat
from d9d_tpu.core.protocol import OptimizerProtocol
from d9d_tpu.core.tracing import annotate
from d9d_tpu.core.types import PyTree
from d9d_tpu.pipelining.runtime.transfer import put_compat
from d9d_tpu.telemetry import numerics as numerics_mod
from d9d_tpu.telemetry import tracked_jit

__all__ = ["PipelinedOptimizer"]


@dataclasses.dataclass
class PipelinedOptimizer:
    """One optimizer instance per pipeline stage, stepped as a unit.

    ``shardings`` maps stage id → a NamedSharding on that stage's submesh
    used to place the broadcast scale factor (any fully-replicated sharding
    on the stage's devices works).
    """

    optimizer: "optax.GradientTransformation | OptimizerProtocol"
    scalar_shardings: dict[int, Any]
    max_grad_norm: float | None = 1.0
    # step anomaly guard (docs/design/resilience.md): when True,
    # step_guarded() freezes every stage's param/moment update on a
    # non-finite global grad norm or loss via an in-device select —
    # the ok flag rides the same scalar hops as the clip factor, so the
    # guard adds no dispatches and no readbacks to the step
    anomaly_freeze: bool = False
    # ZeRO optimizer-state sharding (parallel/zero.py): shard each
    # stage's fp32 masters/moments over this axis of its submesh —
    # grads reduce-scattered at the update entry, the update computed
    # on the 1/N shard, new params all-gathered back. The per-stage
    # sharding tables are computed in init() from the concrete states,
    # so the jitted updates are built lazily per stage. None = off.
    zero_axis: str | None = None

    def __post_init__(self) -> None:
        def sq_norm(grads):
            with jax.named_scope("pp_opt/sq_norm"):
                return optax.global_norm(grads) ** 2

        def combine(sq_norms, weight_sum, max_norm):
            # grads are Σ_mb sums: scale by 1/Σweight, then clip the norm of
            # the *scaled* grads — norm(g/w) = sqrt(Σ sq)/w
            with jax.named_scope("pp_opt/combine"):
                inv_w = 1.0 / jnp.maximum(weight_sum, 1e-8)
                norm = jnp.sqrt(sum(sq_norms)) * inv_w
                clip = (
                    jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))
                    if max_norm is not None
                    else 1.0
                )
                return norm, inv_w * clip

        def combine_guarded(sq_norms, weight_sum, loss_sum, guard, max_norm):
            # the unguarded combine, plus finiteness of the two scalars
            # the step already materializes and a [streak, total] device
            # carry — nothing here forces a host sync
            with jax.named_scope("pp_opt/combine_guarded"):
                norm, factor = combine(sq_norms, weight_sum, max_norm)
                ok = jnp.isfinite(norm) & jnp.isfinite(loss_sum)
                anomaly = jnp.logical_not(ok).astype(jnp.int32)
                streak = jnp.where(ok, 0, guard[0] + 1)
                total = guard[1] + anomaly
                new_guard = jnp.stack([streak, total])
                # metric copies come out of the same jit: the guard adds
                # zero eager op dispatches to the engine's step
                metrics = {
                    "resilience/anomaly": anomaly.astype(jnp.float32),
                    "resilience/anomaly_streak": streak.astype(jnp.float32),
                    "resilience/anomaly_total": total.astype(jnp.float32),
                }
                return norm, factor, ok, new_guard, metrics

        # tracked_jit (telemetry/introspect.py): these run EVERY step —
        # per-stage sq_norm/update plus the anchor-stage combine — so
        # their compiles/recompiles must be visible to the guard like
        # the rest of the step path. Per-stage executables get per-stage
        # names (pp_opt/s{S}/...) because the hbm/{name}/* gauges are
        # set per compile: one shared name across stages of different
        # sizes would last-write-wins blend their claims (the PR 9
        # gauge-conflation class). The combine runs only on the anchor
        # stage, so one name suffices.
        self._sq_norm_impl = sq_norm
        self._sq_norm_fns: dict[int, Any] = {}
        self._combine = tracked_jit(
            functools.partial(combine, max_norm=self.max_grad_norm),
            name="pp_opt/combine",
        )
        self._combine_guarded = tracked_jit(
            functools.partial(combine_guarded, max_norm=self.max_grad_norm),
            name="pp_opt/combine_guarded",
        )
        # per-stage jitted update pairs, built lazily on first use;
        # zero-enabled stages get theirs swapped in by init() (per-stage
        # sharding tables baked into the traced program)
        self._stage_fns: dict[int, tuple] = {}
        # per-stage numerics stats executables (telemetry/numerics.py):
        # built lazily like the update pairs, dispatched by the engine
        # ONLY on cadence steps — off-cadence PP steps add zero
        # dispatches to the single-controller loop
        self._numerics_fns: dict[int, Any] = {}
        self.zero_shardings: dict[int, Any] = {}

    def _stage_sq_norm(self, stage: int):
        fn = self._sq_norm_fns.get(stage)
        if fn is None:
            fn = self._sq_norm_fns[stage] = tracked_jit(
                self._sq_norm_impl, name=f"pp_opt/s{stage}/sq_norm"
            )
        return fn

    def _build_update_fns(self, opt, scope: str) -> tuple:
        """(update, update_guarded) jits closed over ``opt`` — one pair
        per stage (the ZeRO wrapper bakes its per-stage sharding tables
        into the traced program). ``scope`` (``pp_opt/s{S}``) keys the
        tracked names so each stage's ``hbm/*`` gauges stay distinct."""
        accepts_fp32 = getattr(opt, "accepts_fp32_grads", False)
        apply_updates = getattr(opt, "apply_updates", optax.apply_updates)
        freeze = self.anomaly_freeze

        def update(params, opt_state, grads, factor):
            with jax.named_scope("pp_opt/update"):
                grads = jax.tree.map(lambda g: g * factor, grads)
                if not accepts_fp32:
                    grads = jax.tree.map(
                        lambda g, p: g.astype(p.dtype), grads, params
                    )
                updates, opt_state = opt.update(grads, opt_state, params)
                return apply_updates(params, updates), opt_state

        def update_guarded(params, opt_state, grads, factor, ok):
            with jax.named_scope("pp_opt/update_guarded"):
                new_params, new_state = update(
                    params, opt_state, grads, factor
                )
                if freeze:
                    new_params = jax.tree.map(
                        lambda new, old: jnp.where(ok, new, old),
                        new_params, params,
                    )
                    new_state = jax.tree.map(
                        lambda new, old: jnp.where(ok, new, old),
                        new_state, opt_state,
                    )
                return new_params, new_state

        return (
            tracked_jit(
                update, name=f"{scope}/update", donate_argnums=(0, 1, 2)
            ),
            tracked_jit(
                update_guarded, name=f"{scope}/update_guarded",
                donate_argnums=(0, 1, 2),
            ),
        )

    def _stage_update_fns(self, stage: int) -> tuple:
        fns = self._stage_fns.get(stage)
        if fns is None:
            fns = self._stage_fns[stage] = self._build_update_fns(
                self.optimizer, scope=f"pp_opt/s{stage}"
            )
        return fns

    def _scoped(self, stage: int):
        return compat.set_mesh(self.scalar_shardings[stage].mesh)

    # -- per-stage numerics (docs/design/observability.md) -------------

    def stage_numerics(self, stage: int, params, grads, opt_state):
        """One stage's per-leaf numerics rows as a flat f32 device
        array (``telemetry/numerics.py`` layout, param rows only).

        Dispatched BEFORE the update (the update executables donate
        params/opt_state/grads, so post-update those buffers are gone);
        the update:param ratio column is therefore NaN under PP —
        cross-stage *grad/param/moment* skew is the signal this surface
        exists for. One ``pp_numerics/s{S}/stats`` executable per stage:
        per-stage names keep the ``hbm/*`` gauges distinct, like the
        update pairs.
        """
        fn = self._numerics_fns.get(stage)
        if fn is None:
            def stats(params, grads, opt_state):
                nu = numerics_mod.find_second_moments(opt_state, params)
                return numerics_mod.stacked_param_rows(
                    grads, params=None, new_params=params, nu=nu
                ).reshape(-1)

            fn = self._numerics_fns[stage] = tracked_jit(
                stats, name=f"pp_numerics/s{stage}/stats"
            )
        with self._scoped(stage):
            return fn(params, grads, opt_state)

    def init(self, stage_params: dict[int, PyTree]) -> dict[int, PyTree]:
        from d9d_tpu.core.tree_sharding import replicate_uncommitted

        out = {}
        for s, p in stage_params.items():
            with self._scoped(s):
                # replicate constraint-free scalars (step counters) onto
                # the stage submesh so their placement survives a
                # checkpoint round-trip (see trainer init note)
                out[s] = replicate_uncommitted(
                    # d9d-lint: disable=D9D001 — one-shot per-stage init, not steady-state
                    jax.jit(self.optimizer.init)(p),
                    self.scalar_shardings[s].mesh,
                )
            if self.zero_axis is not None:
                out[s] = self._enable_zero(s, p, out[s])
        return out

    def _enable_zero(self, stage: int, params: PyTree, state: PyTree):
        """Shard ``stage``'s optimizer state over ``zero_axis`` and swap
        in a ZeRO-wrapped update pair for that stage. The anomaly-guard
        freeze select stays elementwise, so frozen moments freeze
        shard-local — PR 5 semantics preserved on sharded state."""
        from d9d_tpu.parallel.zero import (
            ZeroShardedOptimizer,
            build_zero_sharding,
            place_tree,
        )

        mesh = self.scalar_shardings[stage].mesh
        if self.zero_axis not in mesh.shape:
            raise ValueError(
                f"zero_axis {self.zero_axis!r} not in stage {stage}'s "
                f"submesh axes {tuple(mesh.shape)}"
            )
        zero = build_zero_sharding(
            params=params, opt_state=state, mesh=mesh, axis=self.zero_axis
        )
        self.zero_shardings[stage] = zero
        self._stage_fns[stage] = self._build_update_fns(
            ZeroShardedOptimizer(self.optimizer, zero),
            scope=f"pp_opt/s{stage}",
        )
        return place_tree(state, zero.state_shardings)

    def step(
        self,
        stage_params: dict[int, PyTree],
        opt_states: dict[int, PyTree],
        stage_grads: dict[int, PyTree],
        weight_sum: jax.Array,
    ) -> tuple[dict[int, PyTree], dict[int, PyTree], jax.Array]:
        """→ (new_params, new_opt_states, grad_norm_of_scaled_grads)."""
        last = max(self.scalar_shardings)
        anchor = self.scalar_shardings[last]
        with annotate("pp_opt.sq_norms"):
            sq_local = []
            for s in sorted(stage_grads):
                with self._scoped(s):
                    sq_local.append(self._stage_sq_norm(s)(stage_grads[s]))
            # batched hop: all per-stage scalars move to the anchor stage
            # from one call site (VERDICT r3 item 3)
            sq_norms = put_compat(sq_local, anchor)
        with annotate("pp_opt.combine"), self._scoped(last):
            norm, factor = self._combine(sq_norms, weight_sum)

        new_params: dict[int, PyTree] = {}
        new_states: dict[int, PyTree] = {}
        with annotate("pp_opt.update"):
            for s in sorted(stage_params):
                f = put_compat(factor, self.scalar_shardings[s])
                update, _ = self._stage_update_fns(s)
                with self._scoped(s):
                    new_params[s], new_states[s] = update(
                        stage_params[s], opt_states[s], stage_grads[s], f
                    )
        return new_params, new_states, norm

    # -- anomaly-guarded stepping (docs/design/resilience.md) ----------

    def init_guard_state(self) -> jax.Array:
        """Fresh device-resident [streak, total] carry on the anchor
        (last) stage's devices."""
        last = max(self.scalar_shardings)
        with self._scoped(last):
            return jnp.zeros((2,), jnp.int32)

    def step_guarded(
        self,
        stage_params: dict[int, PyTree],
        opt_states: dict[int, PyTree],
        stage_grads: dict[int, PyTree],
        weight_sum: jax.Array,
        loss_sum: jax.Array,
        guard_state: jax.Array,
    ) -> tuple[
        dict[int, PyTree], dict[int, PyTree], jax.Array, dict, jax.Array
    ]:
        """:meth:`step` with the step anomaly guard threaded through:
        → (new_params, new_opt_states, grad_norm, guard_metrics,
        guard_state).

        ``guard_metrics`` (``resilience/*`` f32 scalars on the anchor
        stage) and the carry stay on device; the engine folds them into
        its metric dict for the trainer's cadence-rate host inspection.
        """
        last = max(self.scalar_shardings)
        anchor = self.scalar_shardings[last]
        with annotate("pp_opt.sq_norms"):
            sq_local = []
            for s in sorted(stage_grads):
                with self._scoped(s):
                    sq_local.append(self._stage_sq_norm(s)(stage_grads[s]))
            sq_norms = put_compat(sq_local, anchor)
        with annotate("pp_opt.combine"), self._scoped(last):
            norm, factor, ok, guard_state, guard_metrics = (
                self._combine_guarded(
                    sq_norms, weight_sum, loss_sum, guard_state
                )
            )

        new_params: dict[int, PyTree] = {}
        new_states: dict[int, PyTree] = {}
        with annotate("pp_opt.update"):
            for s in sorted(stage_params):
                # the ok flag rides the same hop as the clip factor: one
                # put per stage either way, no extra dispatches
                f, ok_s = put_compat((factor, ok), self.scalar_shardings[s])
                _, update_guarded = self._stage_update_fns(s)
                with self._scoped(s):
                    new_params[s], new_states[s] = update_guarded(
                        stage_params[s], opt_states[s], stage_grads[s],
                        f, ok_s,
                    )
        return new_params, new_states, norm, guard_metrics, guard_state
