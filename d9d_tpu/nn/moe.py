"""Mixture-of-Experts stack: router, grouped experts, shared expert, layer.

Parity: reference d9d/module/block/moe/* (router.py:23, grouped_linear.py:12,
grouped_experts.py:10, shared_expert.py:21, layer.py:16) and its
communication handlers (communications/{naive,deepep}.py).

TPU-native design:
- Grouped GEMM is ``lax.ragged_dot`` on expert-sorted rows (static N·K
  shape) instead of the nv-grouped-gemm wheel.
- The local (no-EP) path is the reference's NoCommunicationHandler: a
  stable argsort permute, expert compute, scatter-add combine.
- The EP path replaces DeepEP's NVSHMEM all-to-all with a
  ``ragged_all_to_all`` dispatch/compute/combine flow inside a
  ``shard_map`` over the expert mesh axes (ops/ep_dispatch.py): tokens
  travel only to their experts' owners and per-shard grouped-GEMM work is
  ``N·k/ep`` (+capacity padding), differentiable end to end with the
  backward re-crossing the network like DeepEP's dispatch/combine pair
  (deepep.py:91-150).
- Load stats are sown into the ``moe_stats`` collection instead of a
  mutable buffer (layer.py:16 tokens_per_expert).
"""

import dataclasses
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from d9d_tpu.core import compat
from d9d_tpu.core.types import Array
from d9d_tpu.nn import logical_axes as la
from d9d_tpu.nn.mlp import SwiGLU
from d9d_tpu.ops.ep_dispatch import ep_dispatch_compute_combine
from d9d_tpu.ops.moe import (
    gate_up_grouped_matmul,
    grouped_matmul,
    permute_tokens,
    sort_tokens_by_expert,
    unpermute_combine,
)
from d9d_tpu.ops.moe_pallas import fused_moe_ffn_apply, moe_ffn_backend
from d9d_tpu.ops.swiglu import silu_mul


@dataclasses.dataclass(frozen=True)
class SharedExpertParameters:
    """Config for the optional shared expert (reference shared_expert.py:8)."""

    intermediate_size: int
    enable_gate: bool = False


class TopKRouter(nn.Module):
    """Softmax gate → optional expert bias → top-k → optional renorm.

    Reference router.py:23. The expert bias (loss-free load balancing) is a
    non-trainable variable in the ``moe_buffers`` collection, updated
    outside the gradient path.
    """

    dim: int
    num_experts: int
    top_k: int
    renormalize_probabilities: bool = True
    enable_expert_bias: bool = False
    # group-limited routing (DeepSeek ``group_limited_greedy``): experts
    # partition into ``n_group`` groups, each scored by its best expert;
    # only experts in the top ``topk_group`` groups are eligible for the
    # global top-k. n_group == 1 is plain top-k.
    n_group: int = 1
    topk_group: int = 1
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, hidden: Array) -> tuple[Array, Array]:
        """hidden [..., D] → (indices [..., K] int32, probs [..., K] fp32)."""
        scores = nn.Dense(
            self.num_experts,
            use_bias=False,
            name="gate",
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), (la.EMBED, None)
            ),
        )(hidden)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)

        # selection scores may differ from the returned probs (bias joins
        # selection only; group-limited routing masks ineligible groups)
        sel = probs
        if self.enable_expert_bias:
            bias = self.variable(
                "moe_buffers",
                "expert_bias",
                lambda: jnp.zeros((self.num_experts,), jnp.float32),
            ).value
            sel = sel + bias
        if self.n_group > 1:
            if self.num_experts % self.n_group != 0:
                raise ValueError(
                    f"num_experts {self.num_experts} not divisible by "
                    f"n_group {self.n_group}"
                )
            per = self.num_experts // self.n_group
            group_score = sel.reshape(
                *sel.shape[:-1], self.n_group, per
            ).max(axis=-1)
            _, top_g = lax.top_k(group_score, self.topk_group)
            gmask = (
                jax.nn.one_hot(top_g, self.n_group, dtype=jnp.bool_)
                .any(axis=-2)
            )
            emask = jnp.repeat(gmask, per, axis=-1)
            sel = jnp.where(emask, sel, -jnp.inf)
        _, selected_idx = lax.top_k(sel, self.top_k)
        selected_probs = jnp.take_along_axis(probs, selected_idx, axis=-1)

        if self.renormalize_probabilities:
            selected_probs = selected_probs / (
                selected_probs.sum(axis=-1, keepdims=True) + 1e-20
            )
        return selected_idx.astype(jnp.int32), selected_probs


class GroupedSwiGLU(nn.Module):
    """E parallel SwiGLU experts over grouped GEMM (reference
    grouped_experts.py:10 + grouped_linear.py:12). Weights are [E, in, out]
    with the ``expert`` logical axis on dim 0 so an EP plan shards experts
    across the expert mesh axes."""

    hidden_dim: int
    intermediate_dim: int
    num_experts: int
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32

    def setup(self) -> None:
        def weight(name, din, dout, ax_in, ax_out):
            init = nn.initializers.variance_scaling(
                1.0 / 3.0, "fan_in", "uniform", in_axis=1, out_axis=2
            )
            return self.param(
                name,
                nn.with_logical_partitioning(init, (la.EXPERT, ax_in, ax_out)),
                (self.num_experts, din, dout),
                self.param_dtype,
            )

        self.gate_weight = weight(
            "gate_proj",
            self.hidden_dim,
            self.intermediate_dim,
            la.EXPERT_EMBED,
            la.EXPERT_MLP,
        )
        self.up_weight = weight(
            "up_proj",
            self.hidden_dim,
            self.intermediate_dim,
            la.EXPERT_EMBED,
            la.EXPERT_MLP,
        )
        self.down_weight = weight(
            "down_proj",
            self.intermediate_dim,
            self.hidden_dim,
            la.EXPERT_MLP,
            la.EXPERT_EMBED,
        )

    def __call__(
        self, permuted_x: Array, permuted_probs: Array, group_sizes: Array
    ) -> Array:
        """Expert-sorted rows [M, D] + probs [M] → weighted outputs [M, D]."""
        return grouped_swiglu_apply(
            permuted_x,
            permuted_probs,
            group_sizes,
            self.gate_weight,
            self.up_weight,
            self.down_weight,
            self.dtype,
        )


def grouped_swiglu_apply(
    permuted_x: Array,
    permuted_probs: Array,
    group_sizes: Array,
    gate_w: Array,
    up_w: Array,
    down_w: Array,
    dtype: jnp.dtype,
) -> Array:
    """Functional core shared by the local path and the EP shard_map body.

    Gate and up projections run as ONE grouped matmul over a runtime
    concatenation ``[E, in, 2*inter]``: the expert-sorted activation rows
    stream from HBM once instead of twice and per-expert M-tiles are
    reused across both projections, while parameters (and therefore
    checkpoints, HF mappers, PEFT and sharding plans) stay separate
    gate/up tensors.

    Caveat (ADVICE r3): because ragged_dot is an opaque custom call, XLA
    materializes the concatenated weight copy each forward (again in the
    backward under remat) — one extra full-weight write+read per MoE layer
    per microbatch. Measured a net win at the r3-swept config (64E × i256,
    bf16), but tools/roofline.py predicts the copy INVERTS at µBS=1 with
    fp32 master weights (the concat becomes the largest single HBM term);
    ``D9D_TPU_MOE_FUSED_GATE_UP=0`` switches to two grouped matmuls for
    the on-chip A/B (run_tpu_benches.sh).
    """
    x = permuted_x.astype(dtype)
    g, u = gate_up_grouped_matmul(
        x, gate_w.astype(dtype), up_w.astype(dtype), group_sizes
    )
    hidden = silu_mul(g, u)
    out = grouped_matmul(hidden, down_w.astype(dtype), group_sizes)
    return out * permuted_probs[:, None].astype(dtype)


class SharedSwiGLU(nn.Module):
    """Always-on expert with optional sigmoid gate (reference
    shared_expert.py:21)."""

    hidden_size: int
    params_config: SharedExpertParameters
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: Array) -> Array:
        out = SwiGLU(
            hidden_size=self.hidden_size,
            intermediate_size=self.params_config.intermediate_size,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name="expert",
        )(x)
        if self.params_config.enable_gate:
            gate = nn.Dense(
                1,
                use_bias=False,
                name="gate",
                dtype=self.dtype,
                param_dtype=self.param_dtype,
            )(x)
            out = out * nn.sigmoid(gate)
        return out


class MoELayer(nn.Module):
    """Router + dispatch + grouped experts + combine (+ shared expert).

    ``ep_axes`` selects the communication handler, mirroring the
    reference's enable_distributed_communicator (layer.py:67):
    - None → local permute only (NoCommunicationHandler).
    - mesh axis tuple → shard_map EP flow over those axes. Expert weights
      must be sharded over ``ep_axes`` on the expert dim (the EP plan
      arranges this).

    Token layout for the EP flow:
    - ``token_axes=None`` (legacy) → tokens are flattened [B·T, D] and
      resharded over ``ep_axes`` at the layer boundary. Correct, but the
      boundary reshard is a real all-to-all the partitioner may implement
      as replicate+slice, and any ep axis that carries no tokens upstream
      (e.g. tp) replicates the dense compute.
    - ``token_axes=(batch_axes, seq_axes)`` → the shard_map rides the
      residual activation layout [B@batch_axes, T@seq_axes, D] directly
      (zero boundary reshard). ep axes that don't shard tokens upstream
      (tp / cp_replicate) subdivide each device's local tokens by their
      axis index — Megatron-sequence-parallel style — and an all-gather
      over those axes after combine restores the local block, so every
      device in the ep fiber owns a disjoint token set and no compute is
      duplicated.
    """

    hidden_dim: int
    intermediate_dim_grouped: int
    num_grouped_experts: int
    top_k: int
    router_renormalize_probabilities: bool = True
    router_enable_expert_bias: bool = False
    # group-limited routing (see TopKRouter.n_group / topk_group)
    router_n_group: int = 1
    router_topk_group: int = 1
    shared_expert: Optional[SharedExpertParameters] = None
    ep_axes: Optional[tuple[str, ...]] = None
    # (batch_axes, seq_axes) of the residual activation layout — see class
    # docstring; None keeps the legacy flatten+reshard EP flow
    token_axes: Optional[tuple[tuple[str, ...], tuple[str, ...]]] = None
    # receive-buffer rows per shard = capacity_factor × n_loc·k (rounded) —
    # this is also the per-shard grouped-GEMM row count, so a factor like
    # 2.0 gives the N·k/ep compute scaling; overflow drops assignment tails
    # deterministically, contributing exact zeros (DeepSeek capacity style).
    # None = dropless worst-case buffer (n_loc·k·ep rows): exact results,
    # but memory AND compute back at all-gather scale — use it for parity
    # testing or tiny EP degrees, set a factor for production
    ep_capacity_factor: Optional[float] = None
    # DeepSeek routed_scaling_factor: multiplies the routed experts'
    # combined output (not the shared expert)
    routed_scaling: float = 1.0
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32

    def setup(self) -> None:
        self.router = TopKRouter(
            dim=self.hidden_dim,
            num_experts=self.num_grouped_experts,
            top_k=self.top_k,
            renormalize_probabilities=self.router_renormalize_probabilities,
            enable_expert_bias=self.router_enable_expert_bias,
            n_group=self.router_n_group,
            topk_group=self.router_topk_group,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
        )
        self.grouped_experts = GroupedSwiGLU(
            hidden_dim=self.hidden_dim,
            intermediate_dim=self.intermediate_dim_grouped,
            num_experts=self.num_grouped_experts,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
        )
        if self.shared_expert is not None:
            self.shared_expert_module = SharedSwiGLU(
                hidden_size=self.hidden_dim,
                params_config=self.shared_expert,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
            )

    def __call__(self, hidden: Array) -> Array:
        """[B, T, D] → [B, T, D]."""
        orig_shape = hidden.shape

        # router + shared expert run on the 3D layout: flattening first
        # would detour [B@dp, T@cp] activations through a fused-token
        # sharding and back (replicate-reshard at scale)
        shared = None
        if self.shared_expert is not None:
            shared = self.shared_expert_module(hidden)

        topk_ids, topk_probs = self.router(hidden)  # [B, T, K]

        # load-balancing stats (reference tokens_per_expert buffer):
        # collected when callers apply with mutable=["moe_stats"]
        self.sow(
            "moe_stats",
            "tokens_per_expert",
            jnp.bincount(
                topk_ids.reshape(-1), length=self.num_grouped_experts
            ),
            reduce_fn=lambda a, b: a + b,
            init_fn=lambda: jnp.zeros(
                (self.num_grouped_experts,), jnp.int32
            ),
        )

        if self.ep_axes is None:
            k = topk_ids.shape[-1]
            out = self._forward_local(
                hidden.reshape(-1, orig_shape[-1]),
                topk_ids.reshape(-1, k),
                topk_probs.reshape(-1, k),
            ).reshape(orig_shape)
        else:
            out = self._forward_ep(hidden, topk_ids, topk_probs)

        if self.routed_scaling != 1.0:
            # DeepSeek-style scale on the ROUTED output only (HF
            # DeepseekV2MoE: routed * factor + shared)
            out = out * jnp.asarray(self.routed_scaling, out.dtype)
        if shared is not None:
            out = out + shared
        return out

    # --- local permute path (reference communications/naive.py) ----------

    def _forward_local(
        self, x: Array, topk_ids: Array, topk_probs: Array
    ) -> Array:
        sort = sort_tokens_by_expert(topk_ids, self.num_grouped_experts)
        if moe_ffn_backend() in ("pallas", "pallas_gather"):
            # one fused Pallas kernel over the group-aligned layout: the
            # [M, 2*inter]/[M, inter] intermediates and the gate+up weight
            # concat never touch HBM (ops/moe_pallas.py; backward runs
            # the XLA chain below via custom_vjp — identical math).
            # pallas_gather additionally keeps x resident in VMEM and
            # gathers rows in-kernel (no HBM aligned activation buffer),
            # and by default folds the combine in too: the kernel
            # scatter-accumulates token-major [N, D] output in VMEM, so
            # the expert-sorted y rows never hit HBM either
            # (D9D_TPU_MOE_COMBINE=unfused for the A/B)
            return fused_moe_ffn_apply(
                x, topk_probs, sort,
                self.grouped_experts.gate_weight,
                self.grouped_experts.up_weight,
                self.grouped_experts.down_weight,
                self.dtype,
                num_experts=self.num_grouped_experts,
            )
        permuted_x, permuted_probs = permute_tokens(x, topk_probs, sort)
        y = self.grouped_experts(permuted_x, permuted_probs, sort.group_sizes)
        return unpermute_combine(y, sort, x.shape[0]).astype(x.dtype)

    # --- EP path (reference communications/deepep.py, re-designed) -------

    def _forward_ep(
        self, hidden: Array, topk_ids: Array, topk_probs: Array
    ) -> Array:
        """hidden [B, T, D], ids/probs [B, T, K] → [B, T, D]."""
        from d9d_tpu.core.mesh import resolve_ambient_mesh

        ep_axes = tuple(self.ep_axes)
        mesh = resolve_ambient_mesh(ep_axes, what="MoE EP path")
        ep_size = 1
        for a in ep_axes:
            ep_size *= mesh.shape[a]
        num_experts = self.num_grouped_experts
        if num_experts % ep_size != 0:
            raise ValueError(
                f"num_experts {num_experts} not divisible by ep size {ep_size}"
            )
        e_loc = num_experts // ep_size
        dtype = self.dtype
        capacity = self.ep_capacity_factor

        def expert_weights():
            return (
                self.grouped_experts.gate_weight,
                self.grouped_experts.up_weight,
                self.grouped_experts.down_weight,
            )

        def dispatch_local(x_loc, ids_loc, probs_loc, gate_w, up_w, down_w):
            def expert_fn(rows, group_sizes):
                return grouped_swiglu_apply(
                    rows,
                    jnp.ones((rows.shape[0],), jnp.float32),
                    group_sizes,
                    gate_w,
                    up_w,
                    down_w,
                    dtype,
                )

            return ep_dispatch_compute_combine(
                x_loc,
                ids_loc,
                probs_loc,
                expert_fn,
                ep_axes=ep_axes,
                e_loc=e_loc,
                ep_world=ep_size,
                capacity_factor=capacity,
            )

        if self.token_axes is None:
            # legacy flow: flatten tokens globally, reshard over ep_axes
            d = hidden.shape[-1]
            k = topk_ids.shape[-1]
            out = compat.shard_map(
                dispatch_local,
                mesh=mesh,
                in_specs=(P(ep_axes, None),) * 3
                + (P(ep_axes, None, None),) * 3,
                out_specs=P(ep_axes, None),
                axis_names=set(ep_axes),
            )(
                hidden.reshape(-1, d),
                topk_ids.reshape(-1, k),
                topk_probs.reshape(-1, k),
                *expert_weights(),
            )
            return out.reshape(hidden.shape).astype(hidden.dtype)

        # token-layout flow: ride the residual sharding, no boundary reshard
        batch_axes, seq_axes = (tuple(a) for a in self.token_axes)
        token_carrying = set(batch_axes) | set(seq_axes)
        dup_axes = tuple(a for a in ep_axes if a not in token_carrying)
        dup = 1
        for a in dup_axes:
            dup *= mesh.shape[a]
        tok_spec = P(batch_axes, seq_axes, None)

        def ep_body(x_loc, ids_loc, probs_loc, gate_w, up_w, down_w):
            b_loc, t_loc, d = x_loc.shape
            n_full = b_loc * t_loc
            x_flat = x_loc.reshape(n_full, d)
            ids_flat = ids_loc.reshape(n_full, -1)
            probs_flat = probs_loc.reshape(n_full, -1)

            if dup > 1:
                # ep axes that shard no tokens upstream see a replicated
                # local block: subdivide ownership by axis index so the ep
                # fiber's token sets stay disjoint (Megatron-SP style)
                if n_full % dup != 0:
                    raise ValueError(
                        f"local token count {n_full} not divisible by the "
                        f"non-token ep axes {dup_axes} (size {dup})"
                    )
                n_own = n_full // dup
                idx = lax.axis_index(dup_axes)
                start = idx * n_own
                x_flat = lax.dynamic_slice_in_dim(x_flat, start, n_own)
                ids_flat = lax.dynamic_slice_in_dim(ids_flat, start, n_own)
                probs_flat = lax.dynamic_slice_in_dim(probs_flat, start, n_own)

            out = dispatch_local(
                x_flat, ids_flat, probs_flat, gate_w, up_w, down_w
            )

            if dup > 1:
                # restore the full local block (and with it, replication
                # over the non-token ep axes the out_spec declares)
                out = lax.all_gather(out, dup_axes, axis=0, tiled=True)
            return out.reshape(b_loc, t_loc, d)

        out = compat.shard_map(
            ep_body,
            mesh=mesh,
            in_specs=(tok_spec,) * 3 + (P(ep_axes, None, None),) * 3,
            out_specs=tok_spec,
            # the tiled all_gather over dup_axes makes the output invariant
            # there, which vma inference cannot see statically
            check_vma=False,
        )(hidden, topk_ids, topk_probs, *expert_weights())
        return out.astype(hidden.dtype)
