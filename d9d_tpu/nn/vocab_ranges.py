"""Shared named-vocab-range parameter handling.

Both the token embedding and the LM head store the vocabulary as an
ordered dict of named ranges, each its own parameter (reference:
module/block/embedding/shard_token_embedding.py:26 and
module/block/head/language_modelling.py:14). This helper is the single
owner of that layout so embedding and head checkpoint structures cannot
diverge.
"""

from collections.abc import Callable

import flax.linen as nn
import jax.numpy as jnp

from d9d_tpu.core.types import Array
from d9d_tpu.nn import logical_axes as la

VocabRanges = tuple[tuple[str, int], ...]


def make_vocab_range_params(
    param_fn: Callable,
    prefix: str,
    vocab_ranges: VocabRanges,
    hidden_size: int,
    param_dtype: jnp.dtype,
    initializer: nn.initializers.Initializer,
) -> list[Array]:
    """Create one [size, hidden] param per named range, logical
    (vocab, vocab_features)."""
    return [
        param_fn(
            f"{prefix}_{name}",
            nn.with_logical_partitioning(
                initializer, (la.VOCAB, la.VOCAB_FEATURES)
            ),
            (size, hidden_size),
            param_dtype,
        )
        for name, size in vocab_ranges
    ]


def concat_vocab_ranges(tables: list[Array]) -> Array:
    return tables[0] if len(tables) == 1 else jnp.concatenate(tables, axis=0)
