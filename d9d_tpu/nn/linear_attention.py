"""GatedDeltaNet linear-attention block.

Reference: d9d/module/block/attention/linear/gated_deltanet.py:232 (block),
:17 (CausalShortDepthwiseConv1d), :68 (LogSigmoidDecayGate), :103
(MambaDecayGate). The fla-core Triton kernels
(chunk_gated_delta_rule / causal_conv1d / fused_kda_gate) map to:
ops/gated_delta.py (chunked WY scan), a depthwise lax conv, and inline
gate math — all fused by XLA.
"""

import enum
import math
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from d9d_tpu.core.types import Array
from d9d_tpu.nn import logical_axes as la
from d9d_tpu.nn.norm import RMSNorm
from d9d_tpu.ops.gated_delta import (
    gated_delta_rule_chunked,
    gated_delta_rule_recurrent,
)
from d9d_tpu.ops.swiglu import silu_mul


class CausalShortConv1d(nn.Module):
    """Causal depthwise conv over time with SiLU (reference :17; fla's
    causal_conv1d). Weight [channels, kernel]."""

    channels: int
    kernel_size: int
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: Array) -> Array:  # [B,T,C]
        def conv_init(key, shape, dtype):
            # torch depthwise-conv default (kaiming_uniform a=√5):
            # U(-1/√K, 1/√K) with fan_in = kernel taps, NOT channels
            bound = shape[-1] ** -0.5
            return jax.random.uniform(key, shape, dtype, -bound, bound)

        w = self.param(
            "weight",
            nn.with_logical_partitioning(conv_init, (la.HEADS, None)),
            (self.channels, self.kernel_size),
            self.param_dtype,
        )
        xf = x.astype(jnp.float32)
        pad = self.kernel_size - 1
        xp = jnp.pad(xf, ((0, 0), (pad, 0), (0, 0)))
        out = _depthwise_causal_conv(xp, w.astype(jnp.float32))
        return jax.nn.silu(out).astype(x.dtype)


def _depthwise_causal_conv(xp: Array, w: Array) -> Array:
    """xp [B, T+K-1, C] ⊛ w [C, K] → [B, T, C] (per-channel FIR).

    Tap convention matches torch ``F.conv1d`` with left pad K-1 (fla's
    causal_conv1d): ``y_t = Σ_j w[:, j] · x_{t-(K-1)+j}`` — the *last*
    weight column multiplies the current token. K is tiny (2-4); the
    unrolled form fuses into K fma passes.
    """
    k = w.shape[1]
    t = xp.shape[1] - (k - 1)
    out = jnp.zeros((xp.shape[0], t, xp.shape[2]), xp.dtype)
    for j in range(k):
        out = out + xp[:, j : j + t, :] * w[None, None, :, j]
    return out


class DecayGateKind(str, enum.Enum):
    mamba = "mamba"
    logsigmoid = "logsigmoid"


class LogSigmoidDecayGate(nn.Module):
    """g = logsigmoid(Wx) / τ ∈ (-∞, 0] (reference :68; GLA/HGRN-2)."""

    hidden_size: int
    num_heads: int
    normalizer: float = 16.0
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: Array) -> Array:
        z = nn.Dense(
            self.num_heads, use_bias=False, name="proj", dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), (la.EMBED, la.HEADS)
            ),
        )(x)
        return jax.nn.log_sigmoid(z.astype(jnp.float32)) / self.normalizer


def _dt_bias_init(dt_min: float, dt_max: float, floor: float):
    def init(key, shape, dtype):
        u = jax.random.uniform(key, shape, jnp.float32)
        dt = jnp.exp(u * (math.log(dt_max) - math.log(dt_min)) + math.log(dt_min))
        dt = jnp.maximum(dt, floor)
        # inverse softplus so softplus(dt_bias) == dt at init
        return (dt + jnp.log(-jnp.expm1(-dt))).astype(dtype)

    return init


def _a_log_init(normalizer: float):
    def init(key, shape, dtype):
        return jnp.log(
            jax.random.uniform(key, shape, jnp.float32, 1e-4, normalizer)
        ).astype(dtype)

    return init


class MambaDecayGate(nn.Module):
    """g = −exp(A_log)·softplus(Wx + dt_bias) (reference :103; the
    fused_kda_gate math; Mamba-2 / Qwen3-Next style)."""

    hidden_size: int
    num_heads: int
    normalizer: float = 16.0
    dt_min: float = 0.001
    dt_max: float = 0.1
    dt_init_floor: float = 1e-4
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: Array) -> Array:
        z = nn.Dense(
            self.num_heads, use_bias=False, name="proj", dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), (la.EMBED, la.HEADS)
            ),
        )(x)
        a_log = self.param(
            "A_log",
            nn.with_logical_partitioning(
                _a_log_init(self.normalizer), (la.HEADS,)
            ),
            (self.num_heads,),
            jnp.float32,
        )
        dt_bias = self.param(
            "dt_bias",
            nn.with_logical_partitioning(
                _dt_bias_init(self.dt_min, self.dt_max, self.dt_init_floor),
                (la.HEADS,),
            ),
            (self.num_heads,),
            jnp.float32,
        )
        zf = z.astype(jnp.float32)
        return -jnp.exp(a_log) * jax.nn.softplus(zf + dt_bias)


class GatedDeltaNet(nn.Module):
    """Gated DeltaNet block (reference :232): fused QKV projection → causal
    short conv → decay/write gates → GQA head expansion → chunked gated
    delta rule → per-head RMSNorm → SiLU output gate → output projection."""

    hidden_size: int
    num_qk_heads: int
    num_v_heads: int
    head_qk_dim: int
    head_v_dim: int
    conv_size: int = 4
    norm_eps: float = 1e-6
    decay_gate: DecayGateKind = DecayGateKind.mamba
    use_qk_l2norm: bool = True
    chunk_size: int = 64
    # Autoregressive decode (loop/generate.py): carries the recurrent
    # delta-rule state [B, Hv, Dk, Dv] and the conv's (K-1)-token input
    # tail in the "cache" collection — this is the linear-attention decode
    # advantage: O(1) state per token instead of a growing KV cache.
    decode: bool = False
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: Array, mask: Optional[Array] = None) -> Array:
        b, t, _ = x.shape
        hqk, hv = self.num_qk_heads, self.num_v_heads
        if hv % hqk != 0:
            raise ValueError(
                f"num_v_heads ({hv}) must be divisible by num_qk_heads ({hqk})"
            )
        groups = hv // hqk
        dqk, dv = self.head_qk_dim, self.head_v_dim
        q_dim = k_dim = hqk * dqk
        v_dim = hv * dv

        if mask is not None:
            x = x * mask[..., None].astype(x.dtype)

        def proj(features, name, axes):
            return nn.Dense(
                features, use_bias=False, name=name, dtype=self.dtype,
                param_dtype=self.param_dtype,
                kernel_init=nn.with_logical_partitioning(
                    nn.initializers.lecun_normal(), axes
                ),
            )

        qkv = proj(q_dim + k_dim + v_dim, "qkv_proj", (la.EMBED, la.HEADS))(x)
        conv = CausalShortConv1d(
            channels=q_dim + k_dim + v_dim,
            kernel_size=self.conv_size,
            name="qkv_conv1d",
            param_dtype=self.param_dtype,
        )
        if self.decode and self.conv_size > 1:
            # prepend the true previous K-1 pre-conv inputs (zeros on the
            # first call = the left pad the full path uses), conv over the
            # joined window, keep the new t outputs
            tail_len = self.conv_size - 1
            tail = self.variable(
                "cache", "conv_tail",
                lambda: jnp.zeros(
                    (b, tail_len, q_dim + k_dim + v_dim), self.dtype
                ),
            )
            joined = jnp.concatenate(
                [tail.value, qkv.astype(self.dtype)], axis=1
            )
            tail.value = joined[:, -tail_len:]
            qkv = conv(joined)[:, -t:]
        else:
            qkv = conv(qkv)
        q, k, v = jnp.split(qkv, [q_dim, q_dim + k_dim], axis=-1)
        q = q.reshape(b, t, hqk, dqk)
        k = k.reshape(b, t, hqk, dqk)
        v = v.reshape(b, t, hv, dv)
        if groups > 1:
            q = jnp.repeat(q, groups, axis=2)
            k = jnp.repeat(k, groups, axis=2)

        gate_cls = (
            MambaDecayGate
            if self.decay_gate == DecayGateKind.mamba
            else LogSigmoidDecayGate
        )
        g = gate_cls(
            hidden_size=self.hidden_size, num_heads=hv, name="decay_gate",
            dtype=self.dtype, param_dtype=self.param_dtype,
        )(x)
        beta = nn.sigmoid(
            proj(hv, "b_proj", (la.EMBED, la.HEADS))(x).astype(jnp.float32)
        )

        if self.decode:
            state = self.variable(
                "cache", "delta_state",
                lambda: jnp.zeros((b, hv, dqk, dv), jnp.float32),
            )
            if t == 1:
                out, s_final = gated_delta_rule_recurrent(
                    q, k, v, g, beta,
                    use_qk_l2norm=self.use_qk_l2norm,
                    initial_state=state.value,
                )
            else:  # prefill: chunked WY form, threading the state
                out, s_final = gated_delta_rule_chunked(
                    q, k, v, g, beta,
                    use_qk_l2norm=self.use_qk_l2norm,
                    chunk_size=self.chunk_size,
                    initial_state=state.value,
                )
            state.value = s_final
        else:
            out, _ = gated_delta_rule_chunked(
                q, k, v, g, beta,
                use_qk_l2norm=self.use_qk_l2norm,
                chunk_size=self.chunk_size,
            )

        out = RMSNorm(dv, eps=self.norm_eps, name="out_norm",
                      param_dtype=self.param_dtype)(out.astype(self.dtype))
        out = out.reshape(b, t, v_dim)
        gate = proj(v_dim, "g_proj", (la.EMBED, la.HEADS))(x)
        out = silu_mul(gate, out)
        return proj(self.hidden_size, "o_proj", (la.HEADS, la.EMBED))(out)
