"""RMSNorm block (reference: d9d/module/block/normalization/rms_norm.py:8)."""

import flax.linen as nn
import jax.numpy as jnp

from d9d_tpu.ops import rms_norm


class RMSNorm(nn.Module):
    """Root-mean-square layer norm with optional zero-centered weight.

    ``zero_centered=True`` stores the scale as an offset from 1 (DeepSeek
    style), so fresh init (zeros) is an identity scale either way.
    """

    hidden_size: int
    eps: float = 1e-6
    zero_centered: bool = False
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        init = nn.initializers.zeros if self.zero_centered else nn.initializers.ones
        weight = self.param(
            "weight",
            nn.with_logical_partitioning(init, (None,)),
            (self.hidden_size,),
            self.param_dtype,
        )
        return rms_norm(x, weight, eps=self.eps, zero_centered=self.zero_centered)
