"""Token embeddings with named vocabulary ranges.

Reference: d9d/module/block/embedding/shard_token_embedding.py:26
(``SplitTokenEmbeddings``) — the vocabulary is declared as an ordered dict
of named ranges (e.g. {"text": 151k, "special": 1k}); each range is a
separate parameter so checkpoints can remap/extend vocabularies per range.
Lookup concatenates the ranges logically. On TPU the concat embedding table
is gathered with one ``take``; the vocab axis carries the ``vocab`` logical
axis so a TP plan shards the lookup (XLA lowers the cross-shard gather to a
masked-sum + psum, the same trick the reference implements by hand).
"""

import flax.linen as nn
import jax.numpy as jnp

from d9d_tpu.core.types import Array
from d9d_tpu.nn.vocab_ranges import concat_vocab_ranges, make_vocab_range_params


class TokenEmbedding(nn.Module):
    """Embedding over named vocab ranges, stored as separate params."""

    vocab_ranges: tuple[tuple[str, int], ...]  # ordered (name, size)
    hidden_size: int
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32

    @property
    def vocab_size(self) -> int:
        return sum(size for _, size in self.vocab_ranges)

    @nn.compact
    def __call__(self, token_ids: Array) -> Array:
        tables = make_vocab_range_params(
            self.param,
            "embedding",
            self.vocab_ranges,
            self.hidden_size,
            self.param_dtype,
            nn.initializers.normal(stddev=1.0),
        )
        table = concat_vocab_ranges(tables)
        return jnp.take(table, token_ids, axis=0).astype(self.dtype)
