from d9d_tpu.nn.attention import GroupedQueryAttention
from d9d_tpu.nn.decoder import DecoderLayer
from d9d_tpu.nn.embedding import TokenEmbedding
from d9d_tpu.nn.heads import ClassificationHead, EmbeddingHead, LanguageModellingHead
from d9d_tpu.nn.mlp import SwiGLU
from d9d_tpu.nn.moe import (
    GroupedSwiGLU,
    MoELayer,
    SharedExpertParameters,
    SharedSwiGLU,
    TopKRouter,
)
from d9d_tpu.nn.norm import RMSNorm

__all__ = [
    "GroupedQueryAttention",
    "DecoderLayer",
    "TokenEmbedding",
    "ClassificationHead",
    "EmbeddingHead",
    "LanguageModellingHead",
    "SwiGLU",
    "GroupedSwiGLU",
    "MoELayer",
    "SharedExpertParameters",
    "SharedSwiGLU",
    "TopKRouter",
    "RMSNorm",
]
