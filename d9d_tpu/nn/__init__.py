from d9d_tpu.nn.attention import (
    GroupedQueryAttention,
    LowRankProjection,
    MultiHeadLatentAttention,
)
from d9d_tpu.nn.decoder import DecoderLayer
from d9d_tpu.nn.embedding import TokenEmbedding
from d9d_tpu.nn.heads import ClassificationHead, EmbeddingHead, LanguageModellingHead
from d9d_tpu.nn.hidden_states import (
    HiddenStatesAggregationMode,
    HiddenStatesAggregatorMean,
    HiddenStatesAggregatorNoOp,
    create_hidden_states_aggregator,
    masked_mean_pool,
)
from d9d_tpu.nn.linear_attention import (
    CausalShortConv1d,
    DecayGateKind,
    GatedDeltaNet,
    LogSigmoidDecayGate,
    MambaDecayGate,
)
from d9d_tpu.nn.mlp import SwiGLU
from d9d_tpu.nn.moe import (
    GroupedSwiGLU,
    MoELayer,
    SharedExpertParameters,
    SharedSwiGLU,
    TopKRouter,
)
from d9d_tpu.nn.norm import RMSNorm

__all__ = [
    "GroupedQueryAttention",
    "LowRankProjection",
    "MultiHeadLatentAttention",
    "DecoderLayer",
    "TokenEmbedding",
    "ClassificationHead",
    "EmbeddingHead",
    "LanguageModellingHead",
    "HiddenStatesAggregationMode",
    "HiddenStatesAggregatorMean",
    "HiddenStatesAggregatorNoOp",
    "create_hidden_states_aggregator",
    "masked_mean_pool",
    "CausalShortConv1d",
    "DecayGateKind",
    "GatedDeltaNet",
    "LogSigmoidDecayGate",
    "MambaDecayGate",
    "SwiGLU",
    "GroupedSwiGLU",
    "MoELayer",
    "SharedExpertParameters",
    "SharedSwiGLU",
    "TopKRouter",
    "RMSNorm",
]
