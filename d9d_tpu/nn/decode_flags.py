"""Trace-time decode-phase flags and the cache write-index traversal.

Chunked prefill (loop/generate.py ``prefill_chunk_size``) feeds a long
prompt through the decode cache in bounded pieces. Whether a multi-token
call is the FIRST chunk (empty cache — the flash prefill fast path
applies) or a CONTINUATION (the new tokens must attend the slot cache)
is static knowledge the caller has and the attention module needs, but
the cache write index is traced — so the fact travels as a trace-time
context flag, not data. ``generate()`` wraps continuation-chunk calls in
:func:`continuation_chunk`; attention modules read
:func:`in_continuation_chunk` while tracing (chunk calls are unrolled,
each traced under its own flag value).
"""

import contextlib
import contextvars

_continuation = contextvars.ContextVar(
    "d9d_tpu_decode_continuation", default=False
)


@contextlib.contextmanager
def continuation_chunk():
    """Mark model calls in this block as continuation prefill chunks:
    multi-token decode-mode calls attend the slot cache (valid at any
    cache index) instead of taking the empty-cache prefill fast path."""
    token = _continuation.set(True)
    try:
        yield
    finally:
        _continuation.reset(token)


def in_continuation_chunk() -> bool:
    return _continuation.get()


def map_cache_index(cache, fn):
    """Apply ``fn`` to every decode write-index leaf of a cache pytree.

    The ONE place that encodes how those leaves are identified
    (``path[-1] == "cache_index"`` — the name ``_decode_cache_index``
    declares in every attention module), so the serving loop's per-row
    seeding/pinning and speculative decoding's rewind can't drift from
    each other or from a future leaf rename. Trace-safe: pure pytree
    surgery, callable inside jit.
    """
    from flax.traverse_util import flatten_dict, unflatten_dict

    flat = flatten_dict(cache)
    for path in list(flat):
        if path[-1] == "cache_index":
            flat[path] = fn(flat[path])
    return unflatten_dict(flat)


# -- paged KV cache plumbing (docs/design/generation.md) ----------------

# cache leaves that hold per-token sequence content and can be paged:
# leaf name → axis that indexes cache slots in the DENSE layout. The
# serving loop's paged mode converts exactly these into page pools
# ([num_pages, ..., page_size, ...] with the slot axis shrunk to
# page_size and a leading page axis) and seeds a sibling ``page_table``
# leaf; attention modules detect that sibling and indirect through it.
PAGED_CACHE_LEAVES = {
    "cached_key": 2,       # GQA heads-major [B, Hkv, S, D]
    "cached_value": 2,
    "cached_latent": 1,    # MLA [B, S, r]
    "cached_rope_key": 1,  # MLA [B, S, d_rope]
}

PAGE_TABLE_LEAF = "page_table"

# quantized-KV sibling leaves (docs/design/generation.md "Low-precision
# serving"): when a pool leaf is stored int8, a second pool named
# ``<leaf>_scale`` rides next to it holding the per-(page, slot[, head])
# dequantization scales — just more paged cache leaves sharing the SAME
# page table, so the allocator, prefix cache and continuation handoff
# treat value pages and scale pages identically. Attention modules
# detect quantization by the presence of the sibling scale leaf.
PAGED_SCALE_SUFFIX = "_scale"
PAGED_SCALE_LEAVES = {
    name + PAGED_SCALE_SUFFIX: axis
    for name, axis in PAGED_CACHE_LEAVES.items()
}


def map_page_table(cache, fn):
    """Apply ``fn`` to every ``page_table`` leaf of a cache pytree (the
    paged counterpart of :func:`map_cache_index`; the serving loop uses
    it to push the host allocator's table mirror and to pin dead rows'
    tables to the garbage page in-device). No-op on unpaged caches."""
    from flax.traverse_util import flatten_dict, unflatten_dict

    flat = flatten_dict(cache)
    hit = False
    for path in list(flat):
        if path[-1] == PAGE_TABLE_LEAF:
            flat[path] = fn(flat[path])
            hit = True
    return unflatten_dict(flat) if hit else cache


def zero_rows_skip_paged(cache, row_mask):
    """Zero ``row_mask``-selected batch rows of every PER-ROW cache leaf,
    skipping page pools and page tables (which have no batch-leading
    dim — pools are shared across rows, and admitted rows' table rows
    are written by the host allocator, not zeroed). The paged-mode
    sibling of ``loop/serve.py``'s ``_zero_row``; trace-safe."""
    import jax.numpy as jnp
    from flax.traverse_util import flatten_dict, unflatten_dict

    skip = (
        set(PAGED_CACHE_LEAVES) | set(PAGED_SCALE_LEAVES) | {PAGE_TABLE_LEAF}
    )
    flat = flatten_dict(cache)
    for path, x in list(flat.items()):
        if path[-1] in skip:
            continue
        m = row_mask.reshape((-1,) + (1,) * (x.ndim - 1))
        flat[path] = jnp.where(m, jnp.zeros_like(x), x)
    return unflatten_dict(flat)
