"""Trace-time decode-phase flags.

Chunked prefill (loop/generate.py ``prefill_chunk_size``) feeds a long
prompt through the decode cache in bounded pieces. Whether a multi-token
call is the FIRST chunk (empty cache — the flash prefill fast path
applies) or a CONTINUATION (the new tokens must attend the slot cache)
is static knowledge the caller has and the attention module needs, but
the cache write index is traced — so the fact travels as a trace-time
context flag, not data. ``generate()`` wraps continuation-chunk calls in
:func:`continuation_chunk`; attention modules read
:func:`in_continuation_chunk` while tracing (chunk calls are unrolled,
each traced under its own flag value).
"""

import contextlib
import contextvars

_continuation = contextvars.ContextVar(
    "d9d_tpu_decode_continuation", default=False
)


@contextlib.contextmanager
def continuation_chunk():
    """Mark model calls in this block as continuation prefill chunks:
    multi-token decode-mode calls attend the slot cache (valid at any
    cache index) instead of taking the empty-cache prefill fast path."""
    token = _continuation.set(True)
    try:
        yield
    finally:
        _continuation.reset(token)


def in_continuation_chunk() -> bool:
    return _continuation.get()
