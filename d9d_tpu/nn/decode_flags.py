"""Trace-time decode-phase flags and the cache write-index traversal.

Chunked prefill (loop/generate.py ``prefill_chunk_size``) feeds a long
prompt through the decode cache in bounded pieces. Whether a multi-token
call is the FIRST chunk (empty cache — the flash prefill fast path
applies) or a CONTINUATION (the new tokens must attend the slot cache)
is static knowledge the caller has and the attention module needs, but
the cache write index is traced — so the fact travels as a trace-time
context flag, not data. ``generate()`` wraps continuation-chunk calls in
:func:`continuation_chunk`; attention modules read
:func:`in_continuation_chunk` while tracing (chunk calls are unrolled,
each traced under its own flag value).
"""

import contextlib
import contextvars

_continuation = contextvars.ContextVar(
    "d9d_tpu_decode_continuation", default=False
)


@contextlib.contextmanager
def continuation_chunk():
    """Mark model calls in this block as continuation prefill chunks:
    multi-token decode-mode calls attend the slot cache (valid at any
    cache index) instead of taking the empty-cache prefill fast path."""
    token = _continuation.set(True)
    try:
        yield
    finally:
        _continuation.reset(token)


def in_continuation_chunk() -> bool:
    return _continuation.get()


def map_cache_index(cache, fn):
    """Apply ``fn`` to every decode write-index leaf of a cache pytree.

    The ONE place that encodes how those leaves are identified
    (``path[-1] == "cache_index"`` — the name ``_decode_cache_index``
    declares in every attention module), so the serving loop's per-row
    seeding/pinning and speculative decoding's rewind can't drift from
    each other or from a future leaf rename. Trace-safe: pure pytree
    surgery, callable inside jit.
    """
    from flax.traverse_util import flatten_dict, unflatten_dict

    flat = flatten_dict(cache)
    for path in list(flat):
        if path[-1] == "cache_index":
            flat[path] = fn(flat[path])
    return unflatten_dict(flat)
