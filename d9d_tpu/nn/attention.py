"""Grouped-query attention block.

Reference: d9d/module/block/attention/grouped_query.py:10 — QKV projections
→ optional per-head QK RMSNorm → (optionally partial) RoPE → pluggable SDPA
backend → optional sigmoid output gate → output projection. Feature surface
covers Qwen3 (qk-norm), GPT-OSS-style sinks, and sliding-window models.
"""

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from d9d_tpu.core.types import Array
from d9d_tpu.nn import logical_axes as la
from d9d_tpu.nn.norm import RMSNorm
from d9d_tpu.nn.sdpa.protocol import SdpaBackend
from d9d_tpu.ops import RopeStyle, apply_rope


class GroupedQueryAttention(nn.Module):
    hidden_size: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    sdpa: SdpaBackend
    qk_norm: bool = False
    qk_norm_eps: float = 1e-6
    rope_style: RopeStyle = RopeStyle.HALF
    rope_fraction: float = 1.0
    use_sinks: bool = False
    use_output_gate: bool = False
    window_size: int | None = None
    softmax_scale: float | None = None
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(
        self,
        x: Array,
        cos: Array,
        sin: Array,
        mask: Optional[Array] = None,
    ) -> Array:
        b, t, _ = x.shape
        h, hkv, d = self.num_heads, self.num_kv_heads, self.head_dim
        if h % hkv != 0:
            raise ValueError(f"num_heads {h} not divisible by num_kv_heads {hkv}")

        def proj(features, name, axes):
            return nn.Dense(
                features,
                use_bias=False,
                name=name,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                kernel_init=nn.with_logical_partitioning(
                    nn.initializers.lecun_normal(), axes
                ),
            )

        q = proj(h * d, "q_proj", (la.EMBED, la.HEADS))(x).reshape(b, t, h, d)
        k = proj(hkv * d, "k_proj", (la.EMBED, la.KV_HEADS))(x).reshape(b, t, hkv, d)
        v = proj(hkv * d, "v_proj", (la.EMBED, la.KV_HEADS))(x).reshape(b, t, hkv, d)

        if self.qk_norm:
            q = RMSNorm(d, eps=self.qk_norm_eps, name="q_norm", param_dtype=self.param_dtype)(q)
            k = RMSNorm(d, eps=self.qk_norm_eps, name="k_norm", param_dtype=self.param_dtype)(k)

        # Partial RoPE: rotate the first `rot` dims, pass the rest through.
        # cos/sin must cover >= rot//2 frequencies; for NeoX-style partial
        # rotary semantics the *model* computes frequencies over the rotary
        # dim (not head_dim) and passes them here — this block only slices.
        rot = int(d * self.rope_fraction)
        if rot % 2 != 0:
            raise ValueError(
                f"rotary dim must be even: head_dim={d} * "
                f"rope_fraction={self.rope_fraction} gives {rot}"
            )
        if rot:
            cos_r, sin_r = cos[..., : rot // 2], sin[..., : rot // 2]
            if rot < d:
                q = jnp.concatenate(
                    [apply_rope(q[..., :rot], cos_r, sin_r, self.rope_style), q[..., rot:]],
                    axis=-1,
                )
                k = jnp.concatenate(
                    [apply_rope(k[..., :rot], cos_r, sin_r, self.rope_style), k[..., rot:]],
                    axis=-1,
                )
            else:
                q = apply_rope(q, cos_r, sin_r, self.rope_style)
                k = apply_rope(k, cos_r, sin_r, self.rope_style)

        sinks = None
        if self.use_sinks:
            sinks = self.param(
                "sinks",
                nn.with_logical_partitioning(nn.initializers.zeros, (la.HEADS,)),
                (h,),
                self.param_dtype,
            )

        attn = self.sdpa(
            q,
            k,
            v,
            causal=True,
            softmax_scale=self.softmax_scale,
            window_size=self.window_size,
            sinks=sinks,
            mask=mask,
        )

        out = attn.reshape(b, t, h * d)
        if self.use_output_gate:
            gate = proj(h * d, "gate_proj", (la.EMBED, la.HEADS))(x)
            out = out * nn.sigmoid(gate)
        return proj(self.hidden_size, "o_proj", (la.HEADS, la.EMBED))(out)
