"""Grouped-query attention block.

Reference: d9d/module/block/attention/grouped_query.py:10 — QKV projections
→ optional per-head QK RMSNorm → (optionally partial) RoPE → pluggable SDPA
backend → optional sigmoid output gate → output projection. Feature surface
covers Qwen3 (qk-norm), GPT-OSS-style sinks, and sliding-window models.
"""

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from d9d_tpu.core.types import Array
from d9d_tpu.nn import logical_axes as la
from d9d_tpu.nn.norm import RMSNorm
from d9d_tpu.nn.sdpa.protocol import SdpaBackend
from d9d_tpu.ops import RopeStyle, apply_rope


def _decode_contract_checks(start, t: int, s_max: int):
    """Functionalized assertions for the two traced decode contracts
    (ADVICE r4): the multi-token prefill FAST PATH is only valid on an
    empty cache (continuation chunks — ``in_continuation_chunk()`` —
    take the slot-cache path instead and are valid at any index), and
    the cache must never overflow (past capacity,
    ``dynamic_update_slice`` clamps and attention silently degrades).
    ``checkify.debug_check`` is a no-op in plain jit but fails loudly
    when the caller wraps with ``checkify.checkify`` — which the decode
    contract tests do; ``loop/generate.py`` additionally enforces both
    bounds statically before tracing.
    """
    from jax.experimental import checkify

    from d9d_tpu.nn.decode_flags import in_continuation_chunk

    # jnp.all: start may be per-row [B] (continuous batching)
    checkify.debug_check(
        jnp.all(start + t <= s_max),
        f"decode cache overflow: cache index + {t} new tokens exceed "
        f"decode_max_length={s_max}",
    )
    if t > 1 and not in_continuation_chunk():
        checkify.debug_check(
            jnp.all(start == 0),
            f"decode prefill (t={t} > 1) requires an empty cache "
            f"(the fast path attends only the new tokens); wrap "
            f"continuation chunks in "
            f"d9d_tpu.nn.decode_flags.continuation_chunk()",
        )


def _decode_cache_index(module: nn.Module):
    """The module's decode write-index variable (declare once per trace —
    flax forbids re-declaring a name within one __call__).

    Initialized SCALAR (one shared index — the closed-batch generate
    loop). A serving loop may seed the cache collection with a per-row
    ``[B]`` index instead (flax returns the provided value untouched);
    every consumer below handles both ranks — this is how continuous
    batching (loop/serve.py) lets each row's cache fill at its own rate
    without any module plumbing.
    """
    return module.variable(
        "cache", "cache_index", lambda: jnp.zeros((), jnp.int32)
    )


def _decode_page_table(module: nn.Module):
    """The module's page-table cache variable if the serving loop seeded
    one (paged KV mode, loop/serve.py), else None. Presence of the leaf
    IS the mode flag: the serving loop converts this module's sequence
    caches into page pools in the same pass that seeds the table, so
    the two can't disagree."""
    from d9d_tpu.nn.decode_flags import PAGE_TABLE_LEAF

    if not module.has_variable("cache", PAGE_TABLE_LEAF):
        return None
    return module.variable("cache", PAGE_TABLE_LEAF, lambda: None).value


def _paged_write_checks(start, t: int, mask) -> None:
    """The paged cache is a serving-loop construct: the loop feeds one
    token per row per step (prompts are teacher-forced), never passes a
    slot mask, and seeds per-row write indices. Anything else reaching a
    paged module is a caller bug — fail loudly, not approximately."""
    if t != 1:
        raise NotImplementedError(
            "paged decode caches serve single-token steps only (the "
            "serving loop teacher-forces prompts token-by-token); got "
            f"t={t}"
        )
    if mask is not None:
        raise NotImplementedError(
            "paged decode does not take a slot mask (paged rows are "
            "never left-padded)"
        )
    if jnp.ndim(start) == 0:
        raise ValueError(
            "paged decode needs per-row [B] write indices (the serving "
            "loop seeds them); got a scalar cache_index"
        )


def _paged_slot(page_table, start, page_size: int):
    """Row-wise (page, offset) for logical slot ``start [B]``: the page
    id gathered from the table, the offset within it. Dead/idle rows
    (serve.py pins their ``start`` to 0 and their table row to 0) land
    on the reserved garbage page."""
    page = jnp.take_along_axis(
        page_table, (start // page_size)[:, None], axis=1
    )[:, 0]
    return page, start % page_size


def _paged_scale_var(module: nn.Module, name: str):
    """The sibling scale-pool variable of pool leaf ``name`` if the
    serving loop seeded one (``kv_quant`` mode, loop/serve.py), else
    None. Like the page table, presence of the leaf IS the mode flag:
    the loop creates int8 pools and their scale pools in the same
    pass, so the two cannot disagree."""
    from d9d_tpu.nn.decode_flags import PAGED_SCALE_SUFFIX

    scale_name = name + PAGED_SCALE_SUFFIX
    if not module.has_variable("cache", scale_name):
        return None
    return module.variable("cache", scale_name, lambda: None)


def _quantize_rows(v):
    """Symmetric int8 quantization of feature vectors: ``v [..., D]`` →
    ``(int8 [..., D], f32 scale [...])`` with scale = absmax/127 per
    leading index. An all-zero vector gets scale 0 and quantizes to
    exact zeros (dequant reproduces them — garbage-page writes stay
    harmless)."""
    vf = v.astype(jnp.float32)
    scale = jnp.max(jnp.abs(vf), axis=-1) / 127.0
    safe = jnp.where(scale > 0.0, scale, 1.0)
    q = jnp.clip(
        jnp.round(vf / safe[..., None]), -127, 127
    ).astype(jnp.int8)
    return q, scale


def _decode_cache_append(module: nn.Module, value, name: str, s_max: int,
                         start, page_table=None):
    """Append ``value [B, T, ...]`` at cache slot ``start`` (scalar, or
    per-row ``[B]`` for continuous batching).

    One definition for every decode cache (GQA k/v, MLA latent/rope key).
    Capacity contract: callers must never feed more than ``s_max`` total
    tokens — the write index is traced, so this cannot be checked here;
    past the end ``dynamic_update_slice`` clamps and outputs silently
    degrade (loop/generate.py enforces the bound statically up front).
    Returns the full cache buffer.

    With ``page_table [B, n_pages]`` the buffer is a page POOL
    ``[P, page_size, ...]`` (seeded by the serving loop); the one new
    token scatters to ``(page_table[b, start // ps], start % ps)`` and
    the CONTIGUOUS PER-ROW VIEW ``[B, n_pages·ps, ...]`` is returned —
    gathered once per step, the same traffic class as attending the
    cache at all (MLA's decode paths consume the full buffer anyway).
    """
    from jax import lax

    b = value.shape[0]
    if page_table is not None:
        ref = module.variable("cache", name, lambda: None)
        pool = ref.value  # [P, ps, ...]
        ps = pool.shape[1]
        page, off = _paged_slot(page_table, start, ps)
        sref = _paged_scale_var(module, name)
        if sref is not None:
            # int8 pool (kv_quant): quantize the one new row at the
            # scatter, dequantize the whole gathered view at the read —
            # consumers see the value dtype either way
            qv, sc = _quantize_rows(value[:, 0])
            ref.value = pool.at[page, off].set(qv)
            sref.value = sref.value.at[page, off].set(sc)
            g = ref.value[page_table]       # [B, n, ps, ...] int8
            gs = sref.value[page_table]     # [B, n, ps] f32
            g = (g.astype(jnp.float32) * gs[..., None]).astype(value.dtype)
        else:
            ref.value = pool.at[page, off].set(value[:, 0])
            g = ref.value[page_table]  # [B, n_pages, ps, ...]
        return g.reshape((b, -1) + g.shape[3:])
    ref = module.variable(
        "cache", name,
        lambda: jnp.zeros((b, s_max) + value.shape[2:], value.dtype),
    )
    if jnp.ndim(start) == 0:
        ref.value = lax.dynamic_update_slice(
            ref.value, value, (0, start) + (0,) * (value.ndim - 2)
        )
    else:
        ref.value = jax.vmap(
            lambda c, v, s: lax.dynamic_update_slice(
                c, v, (s,) + (0,) * (v.ndim - 1)
            )
        )(ref.value, value, start)
    return ref.value


def _decode_cache_append_heads_major(module: nn.Module, value, name: str,
                                     s_max: int, start, page_table=None):
    """Append ``value [B, T, H, D]`` at cache slot ``start`` of a
    HEADS-MAJOR cache buffer ``[B, H, s_max, D]``.

    GQA decode caches store the flash-decode kernel's streaming layout
    (ops/attention/pallas_decode.py) so the per-step attention never
    relayouts the cache — the write-side transpose touches only the T
    new tokens (T = 1 on decode steps), while a read-side transpose
    would copy all ``s_max`` slots every step. Same capacity contract
    as :func:`_decode_cache_append`.

    With ``page_table [B, n_pages]`` the buffer is a heads-major page
    POOL ``[P, H, page_size, D]``; the one new token scatters to its
    row's (page, offset) and the POOL is returned — the flash-decode
    kernel streams it directly through the gathering block index map
    (no per-step relayout, exactly like the dense layout), and the
    eager fallback gathers a contiguous view via
    :func:`_gather_pages_heads_major`.
    """
    from jax import lax

    b, _, h, d = value.shape
    if page_table is not None:
        ref = module.variable("cache", name, lambda: None)
        pool = ref.value  # [P, H, ps, D]
        ps = pool.shape[2]
        page, off = _paged_slot(page_table, start, ps)
        sref = _paged_scale_var(module, name)
        if sref is not None:
            # int8 pool (kv_quant): per-(row, head) scales land in the
            # [P, H, ps] scale pool at the same (page, offset); readers
            # (the flash kernel's scale BlockSpec / the quantized eager
            # gather) dequantize — the raw int8 pool is returned
            qv, sc = _quantize_rows(value[:, 0])  # [B,H,D] i8, [B,H] f32
            ref.value = pool.at[page, :, off, :].set(qv)
            sref.value = sref.value.at[page, :, off].set(sc)
            return ref.value
        ref.value = pool.at[page, :, off, :].set(value[:, 0])
        return ref.value
    ref = module.variable(
        "cache", name,
        lambda: jnp.zeros((b, h, s_max, d), value.dtype),
    )
    vt = jnp.transpose(value, (0, 2, 1, 3))
    if jnp.ndim(start) == 0:
        ref.value = lax.dynamic_update_slice(
            ref.value, vt, (0, 0, start, 0)
        )
    else:  # per-row [B] write indices (continuous batching)
        ref.value = jax.vmap(
            lambda c, v, s: lax.dynamic_update_slice(c, v, (0, s, 0))
        )(ref.value, vt, start)
    return ref.value


def _gather_pages_heads_major(pool, page_table):
    """Contiguous per-row view of a heads-major page pool:
    ``[P, H, ps, D]`` gathered through ``[B, n]`` →
    ``[B, H, n·ps, D]`` — the eager fallback's (and the parity
    oracle's) bridge back to the dense layout. Slot order is preserved,
    so outputs are bitwise what the dense cache would produce."""
    g = pool[page_table]  # [B, n, H, ps, D]
    b, n, h, ps, d = g.shape
    return g.transpose(0, 2, 1, 3, 4).reshape(b, h, n * ps, d)


def _gather_pages_heads_major_quant(pool, scale_pool, page_table, dtype):
    """Quantized sibling of :func:`_gather_pages_heads_major`: gather
    the int8 pool AND its ``[P, H, ps]`` scale pool through the same
    table, widen ``int8 * scale`` per slot, return the dense view in
    the module compute dtype. This is the CPU-tier parity anchor: the
    flash kernel's in-VMEM rescale must match this eager math."""
    g = pool[page_table]            # [B, n, H, ps, D] int8
    gs = scale_pool[page_table]     # [B, n, H, ps] f32
    b, n, h, ps, d = g.shape
    wide = (g.astype(jnp.float32) * gs[..., None]).astype(dtype)
    return wide.transpose(0, 2, 1, 3, 4).reshape(b, h, n * ps, d)


def _check_slot_mask(mask, s_max: int):
    """Shared decode mask contract: 4D broadcastable to
    ``[B, Hq, T, s_max]`` with the key axis indexing CACHE SLOTS
    (loop/generate.py passes ``[B, 1, 1, S_max]`` key-validity for
    left-padded ragged prompts; slot order equals time order per row, so
    causality stays slot-based). 2D/3D token-position masks are rejected
    — their shape can coincide with the slot layout and silently mean the
    wrong thing."""
    if mask is not None and (mask.ndim != 4 or mask.shape[-1] != s_max):
        raise NotImplementedError(
            "decode mode accepts only a 4D [B, Hq, T, decode_max_length] "
            f"cache-slot mask (loop/generate.py's form); got {mask.shape}"
        )


def _decode_slot_mask(start, t: int, s_max: int, window_size, mask):
    """Slot-based causal (+window, +caller) mask for decode attention
    (mask contract: :func:`_check_slot_mask`). ``start`` scalar →
    ``[1, 1, t, s_max]``; per-row ``[B]`` → ``[B, 1, t, s_max]``."""
    _check_slot_mask(mask, s_max)
    if jnp.ndim(start) == 0:
        q_abs = start + jnp.arange(t, dtype=jnp.int32)[:, None]
        k_pos = jnp.arange(s_max, dtype=jnp.int32)[None, :]
        dec_mask = (k_pos <= q_abs)[None, None]  # [1, 1, t, s_max]
        if window_size is not None:
            dec_mask &= (k_pos > q_abs - window_size)[None, None]
    else:
        q_abs = (
            start[:, None, None]
            + jnp.arange(t, dtype=jnp.int32)[None, :, None]
        )  # [B, t, 1]
        k_pos = jnp.arange(s_max, dtype=jnp.int32)[None, None, :]
        dec_mask = (k_pos <= q_abs)[:, None]  # [B, 1, t, s_max]
        if window_size is not None:
            dec_mask &= (k_pos > q_abs - window_size)[:, None]
    if mask is not None:
        dec_mask = dec_mask & mask
    return dec_mask


def _prefill_segments(mask, t: int, s_max: int) -> dict:
    """Segment-id kwargs expressing a slot-validity mask during a prefill
    that attends only the new tokens (left pads get id 0, real tokens 1 —
    real queries then never see pad keys; pad rows' outputs are don't-care
    positions discarded downstream). Only the key-validity FORM
    ``[B, 1, 1, s_max]`` is expressible as segments, so head/query-varying
    masks are rejected rather than silently collapsed."""
    if mask is None:
        return {}
    _check_slot_mask(mask, s_max)
    if mask.shape[1] != 1 or mask.shape[2] != 1:
        raise NotImplementedError(
            "the decode prefill fast path supports only key-validity "
            f"masks [B, 1, 1, s_max]; got {mask.shape}"
        )
    seg = mask[:, 0, 0, :t].astype(jnp.int32)
    return {"q_segments": seg, "kv_segments": seg}


class _ProjKernel(nn.Module):
    """Declare a Dense-compatible kernel (``<name>/kernel``, shape
    ``[in, features]``, lecun-normal, logical axes) and return it raw —
    lets the fused-QKV path own the matmul while the parameter pytree
    stays identical to three ``nn.Dense`` modules."""

    features: int
    axes: tuple
    param_dtype: jnp.dtype

    @nn.compact
    def __call__(self, in_features: int) -> Array:
        return self.param(
            "kernel",
            nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), self.axes
            ),
            (in_features, self.features),
            self.param_dtype,
        )


class GroupedQueryAttention(nn.Module):
    hidden_size: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    sdpa: SdpaBackend
    qk_norm: bool = False
    qk_norm_eps: float = 1e-6
    # zero-centered qk-norm weights (Qwen3-Next style: scale = 1 + w)
    qk_norm_zero_centered: bool = False
    rope_style: RopeStyle = RopeStyle.HALF
    rope_fraction: float = 1.0
    use_sinks: bool = False
    use_output_gate: bool = False
    window_size: int | None = None
    softmax_scale: float | None = None
    # One matmul for q/k/v over a runtime kernel concat (the activation
    # rows stream from HBM once instead of three times; same math, same
    # parameter pytree — q_proj/k_proj/v_proj kernels stay separate for
    # checkpoints/HF/PEFT/plans). Off by default: under tensor parallelism
    # the concat crosses the tp-sharded head dim and XLA must reshard the
    # kernels; single-chip benches enable it (D9D_BENCH_FUSED_QKV).
    fused_qkv: bool = False
    # Autoregressive decode mode (loop/generate.py), on when > 0:
    # maintains KV-cache variables in the "cache" collection
    # (cached_key/cached_value of this static length + a write index) and
    # attends new tokens against the cache. 0 keeps the training path
    # byte-identical.
    decode_max_length: int = 0
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(
        self,
        x: Array,
        cos: Array,
        sin: Array,
        mask: Optional[Array] = None,
    ) -> Array:
        b, t, _ = x.shape
        h, hkv, d = self.num_heads, self.num_kv_heads, self.head_dim
        if h % hkv != 0:
            raise ValueError(f"num_heads {h} not divisible by num_kv_heads {hkv}")

        def proj(features, name, axes):
            return nn.Dense(
                features,
                use_bias=False,
                name=name,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                kernel_init=nn.with_logical_partitioning(
                    nn.initializers.lecun_normal(), axes
                ),
            )

        if self.fused_qkv:
            # enforce the documented TP constraint: the runtime kernel
            # concat crosses the tp-sharded head dim, so XLA would reshard
            # the kernels every step — fail loudly instead of silently
            # regressing (tp in the ambient mesh is how the plans shard
            # HEADS/KV_HEADS)
            from d9d_tpu.core.compat import get_abstract_mesh

            mesh = get_abstract_mesh()
            if mesh is not None and dict(mesh.shape).get("tp", 1) > 1:
                raise ValueError(
                    "fused_qkv=True under a tp>1 mesh would reshard the "
                    "q/k/v kernels every step; use fused_qkv=False with "
                    "tensor parallelism"
                )
            in_f = x.shape[-1]

            def kernel(features, name, axes):
                # identical param path ("<name>/kernel"), shape and init
                # stream as nn.Dense, so checkpoints and plans are
                # indistinguishable from the unfused layout
                return _ProjKernel(
                    features=features, axes=axes,
                    param_dtype=self.param_dtype, name=name,
                )(in_f)

            w = jnp.concatenate(
                [
                    kernel(h * d, "q_proj", (la.EMBED, la.HEADS)),
                    kernel(hkv * d, "k_proj", (la.EMBED, la.KV_HEADS)),
                    kernel(hkv * d, "v_proj", (la.EMBED, la.KV_HEADS)),
                ],
                axis=-1,
            ).astype(self.dtype)
            qkv = x.astype(self.dtype) @ w
            q = qkv[..., : h * d].reshape(b, t, h, d)
            k = qkv[..., h * d : (h + hkv) * d].reshape(b, t, hkv, d)
            v = qkv[..., (h + hkv) * d :].reshape(b, t, hkv, d)
        else:
            q = proj(h * d, "q_proj", (la.EMBED, la.HEADS))(x).reshape(b, t, h, d)
            k = proj(hkv * d, "k_proj", (la.EMBED, la.KV_HEADS))(x).reshape(b, t, hkv, d)
            v = proj(hkv * d, "v_proj", (la.EMBED, la.KV_HEADS))(x).reshape(b, t, hkv, d)

        if self.qk_norm:
            q = RMSNorm(d, eps=self.qk_norm_eps, name="q_norm",
                        zero_centered=self.qk_norm_zero_centered,
                        param_dtype=self.param_dtype)(q)
            k = RMSNorm(d, eps=self.qk_norm_eps, name="k_norm",
                        zero_centered=self.qk_norm_zero_centered,
                        param_dtype=self.param_dtype)(k)

        # Partial RoPE: rotate the first `rot` dims, pass the rest through.
        # cos/sin must cover >= rot//2 frequencies; for NeoX-style partial
        # rotary semantics the *model* computes frequencies over the rotary
        # dim (not head_dim) and passes them here — this block only slices.
        rot = int(d * self.rope_fraction)
        if rot % 2 != 0:
            raise ValueError(
                f"rotary dim must be even: head_dim={d} * "
                f"rope_fraction={self.rope_fraction} gives {rot}"
            )
        if rot:
            cos_r, sin_r = cos[..., : rot // 2], sin[..., : rot // 2]
            if rot < d:
                q = jnp.concatenate(
                    [apply_rope(q[..., :rot], cos_r, sin_r, self.rope_style), q[..., rot:]],
                    axis=-1,
                )
                k = jnp.concatenate(
                    [apply_rope(k[..., :rot], cos_r, sin_r, self.rope_style), k[..., rot:]],
                    axis=-1,
                )
            else:
                q = apply_rope(q, cos_r, sin_r, self.rope_style)
                k = apply_rope(k, cos_r, sin_r, self.rope_style)

        sinks = None
        if self.use_sinks:
            sinks = self.param(
                "sinks",
                nn.with_logical_partitioning(nn.initializers.zeros, (la.HEADS,)),
                (h,),
                self.param_dtype,
            )

        if self.decode_max_length > 0:
            attn = self._decode_attend(q, k, v, sinks, mask, b, t)
        else:
            attn = self.sdpa(
                q,
                k,
                v,
                causal=True,
                softmax_scale=self.softmax_scale,
                window_size=self.window_size,
                sinks=sinks,
                mask=mask,
            )
        # named so the "save_expensive" remat policy can keep the flash
        # kernel's output instead of re-running it in the backward pass
        attn = checkpoint_name(attn, "sdpa_out")

        out = attn.reshape(b, t, h * d)
        if self.use_output_gate:
            gate = proj(h * d, "gate_proj", (la.EMBED, la.HEADS))(x)
            out = out * nn.sigmoid(gate)
        return proj(self.hidden_size, "o_proj", (la.HEADS, la.EMBED))(out)

    def _decode_attend(self, q, k, v, sinks, mask, b, t):
        """KV-cache attention: write the new k/v at the cache index, then
        attend against the full static-length cache.

        Per-step attention is cache-bandwidth-bound; on TPU it runs the
        Pallas flash-decode kernel (ops/attention/pallas_decode.py):
        streams each (batch, kv-head) cache slice from HBM exactly once
        with the GQA group as the matmul M dim, skips slots past the
        write index, and never materializes [B,H,T,S] logits — the
        eager oracle remains the fallback (non-TPU, or masks beyond the
        key-validity form) and the parity reference. Cache mechanics +
        capacity/mask contracts: the module-level ``_decode_cache_append``
        / ``_decode_slot_mask`` helpers.
        """
        from d9d_tpu.nn.decode_flags import in_continuation_chunk
        from d9d_tpu.ops.attention.eager import eager_sdpa
        from d9d_tpu.ops.attention.pallas_decode import (
            MAX_DECODE_ROWS,
            decode_attention_backend,
            flash_decode_attention,
        )

        s_max = self.decode_max_length
        idx = _decode_cache_index(self)
        start = idx.value
        _decode_contract_checks(start, t, s_max)
        page_table = _decode_page_table(self)
        if page_table is not None:
            # paged serving mode (loop/serve.py): one token per row per
            # step through page pools; the flash path streams the pool
            # through the gathering block index map, the eager oracle
            # gathers a contiguous per-row view
            _paged_write_checks(start, t, mask)
            k_pool = _decode_cache_append_heads_major(
                self, k.astype(self.dtype), "cached_key", s_max, start,
                page_table=page_table,
            )
            v_pool = _decode_cache_append_heads_major(
                self, v.astype(self.dtype), "cached_value", s_max, start,
                page_table=page_table,
            )
            idx.value = start + t
            # kv_quant mode (loop/serve.py): the appends above wrote
            # int8 + per-slot scales; both read paths dequantize
            k_scale = v_scale = None
            if self.has_variable("cache", "cached_key_scale"):
                k_scale = self.get_variable("cache", "cached_key_scale")
                v_scale = self.get_variable("cache", "cached_value_scale")
            rows = (self.num_heads // self.num_kv_heads) * t
            if (
                decode_attention_backend() == "pallas"
                and rows <= MAX_DECODE_ROWS
            ):
                return flash_decode_attention(
                    q, k_pool, v_pool,
                    start=start,
                    softmax_scale=self.softmax_scale,
                    window_size=self.window_size,
                    sinks=sinks,
                    page_table=page_table,
                    k_scale=k_scale,
                    v_scale=v_scale,
                )
            if k_scale is not None:
                keys = _gather_pages_heads_major_quant(
                    k_pool, k_scale, page_table, self.dtype
                )
                values = _gather_pages_heads_major_quant(
                    v_pool, v_scale, page_table, self.dtype
                )
            else:
                keys = _gather_pages_heads_major(k_pool, page_table)
                values = _gather_pages_heads_major(v_pool, page_table)
            s_virt = keys.shape[2]
            return eager_sdpa(
                q,
                jnp.transpose(keys, (0, 2, 1, 3)),
                jnp.transpose(values, (0, 2, 1, 3)),
                causal=False,
                softmax_scale=self.softmax_scale,
                sinks=sinks,
                mask=_decode_slot_mask(
                    start, t, s_virt, self.window_size, None
                ),
            )
        # heads-major [B, Hkv, s_max, D]: the flash-decode kernel's
        # streaming layout, written in place (no per-step cache relayout)
        keys = _decode_cache_append_heads_major(
            self, k.astype(self.dtype), "cached_key", s_max, start
        )
        values = _decode_cache_append_heads_major(
            self, v.astype(self.dtype), "cached_value", s_max, start
        )
        idx.value = start + t
        if t > 1 and not in_continuation_chunk():
            # PREFILL fast path: attend the new tokens against themselves
            # through the training SDPA (flash on TPU) — the eager slot
            # path would materialize [t, s_max] logits, which explodes
            # for long prompts. Valid only when the cache was empty
            # (start == 0), which is exactly how loop/generate.py issues
            # its first (or only) multi-token call; start is traced, so
            # the contract is asserted via checkify
            # (_decode_contract_checks) and enforced statically by
            # generate(). Continuation prefill chunks (chunked prefill,
            # loop/generate.py prefill_chunk_size) fall through to the
            # slot-cache path below, which is valid at any cache index.
            return self.sdpa(
                q, k, v,
                causal=True,
                softmax_scale=self.softmax_scale,
                window_size=self.window_size,
                sinks=sinks,
                **_prefill_segments(mask, t, s_max),
            )
        key_validity_mask = mask is None or (
            mask.ndim == 4 and mask.shape[1] == 1 and mask.shape[2] == 1
        )
        rows = (self.num_heads // self.num_kv_heads) * t
        if (
            decode_attention_backend() == "pallas"
            and key_validity_mask
            and rows <= MAX_DECODE_ROWS
        ):
            _check_slot_mask(mask, s_max)
            return flash_decode_attention(
                q, keys, values,
                start=start,
                softmax_scale=self.softmax_scale,
                window_size=self.window_size,
                sinks=sinks,
                kv_valid=None if mask is None else mask[:, 0, 0, :],
            )
        return eager_sdpa(
            q,
            jnp.transpose(keys, (0, 2, 1, 3)),
            jnp.transpose(values, (0, 2, 1, 3)),
            causal=False,
            softmax_scale=self.softmax_scale,
            sinks=sinks,
            mask=_decode_slot_mask(start, t, s_max, self.window_size, mask),
        )


def _decompress_kv(c, k_rope, w, num_heads: int, d_nope: int, dtype):
    """Expand MLA latents through kv_up: ``c [B,S,r]`` + shared rotated
    rope key ``k_rope [B,S,d_rope]`` → ``(k [B,S,H,d_nope+d_rope],
    v [B,S,H,d_v])`` with the single-head rope key broadcast to every
    head (MQA-style). One definition for the prefill/training body and
    the decompressed-decode oracle so their layouts cannot drift."""
    b, s = c.shape[:2]
    kv = (c.astype(dtype) @ w.astype(dtype)).reshape(b, s, num_heads, -1)
    k_nope, v = kv[..., :d_nope], kv[..., d_nope:]
    d_rope = k_rope.shape[-1]
    k = jnp.concatenate(
        [
            k_nope,
            jnp.broadcast_to(
                k_rope[:, :, None, :], (b, s, num_heads, d_rope)
            ).astype(k_nope.dtype),
        ],
        axis=-1,
    )
    return k, v


class LowRankProjection(nn.Module):
    """down-proj → RMSNorm → up-proj (reference
    d9d/module/block/attention/multi_head_latent.py:11)."""

    bottleneck: int
    features: int
    norm_eps: float = 1e-6
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: Array) -> Array:
        def proj(features, name, axes):
            return nn.Dense(
                features,
                use_bias=False,
                name=name,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                kernel_init=nn.with_logical_partitioning(
                    nn.initializers.lecun_normal(), axes
                ),
            )

        x = proj(self.bottleneck, "down_proj", (la.EMBED, None))(x)
        x = RMSNorm(self.bottleneck, eps=self.norm_eps, name="norm",
                    param_dtype=self.param_dtype)(x)
        return proj(self.features, "up_proj", (None, la.HEADS))(x)


class MultiHeadLatentAttention(nn.Module):
    """DeepSeek-V2 MLA (reference multi_head_latent.py:46).

    Q through an optional low-rank bottleneck; K/V through a shared latent
    compression whose up-projection yields per-head content (no-RoPE) keys
    and values; a decoupled single-head RoPE sub-vector is broadcast to all
    heads (MQA-style). V is zero-padded to the qk head dim so any SDPA
    backend (flash/ring included) can run it, then un-padded.
    """

    hidden_size: int
    num_heads: int
    qk_nope_head_dim: int
    qk_rope_head_dim: int
    v_head_dim: int
    kv_lora_rank: int
    sdpa: SdpaBackend
    q_lora_rank: int | None = None
    norm_eps: float = 1e-6
    rope_style: RopeStyle = RopeStyle.HALF
    # None -> d_qk**-0.5; DeepSeek yarn checkpoints fold an mscale
    # temperature into the attention scale (models/deepseek presets)
    softmax_scale: float | None = None
    # Latent-cache decode mode when > 0 (MLA's inference advantage: the
    # cache holds kv_lora_rank + qk_rope_head_dim floats per token — the
    # compressed latent plus the shared rotated rope key — instead of
    # num_heads*(d_nope+d_v)). Single-token steps run the ABSORBED form
    # (kv_up folded into the query/output sides, attention in rank space
    # — no per-step decompression); prefill (t > 1) decompresses once.
    decode_max_length: int = 0
    # False: single-token steps instead decompress EVERY cache slot
    # through kv_up and attend over the slot cache — the cost the
    # absorbed trick avoids. Kept as the absorbed form's correctness
    # oracle and the honest half of the bench A/B (ADVICE r4: timing a
    # t=2 prefill on a warm cache measures neither).
    decode_absorbed: bool = True
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(
        self,
        x: Array,
        cos: Array,
        sin: Array,
        mask: Optional[Array] = None,
    ) -> Array:
        b, t, _ = x.shape
        h = self.num_heads
        d_nope, d_rope = self.qk_nope_head_dim, self.qk_rope_head_dim
        d_qk = d_nope + d_rope
        d_v = self.v_head_dim
        scale = (
            self.softmax_scale if self.softmax_scale is not None
            else d_qk**-0.5
        )
        if d_v > d_qk:
            raise ValueError(
                f"v_head_dim ({d_v}) must not exceed qk head dim ({d_qk})"
            )

        def proj(features, name, axes):
            return nn.Dense(
                features,
                use_bias=False,
                name=name,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                kernel_init=nn.with_logical_partitioning(
                    nn.initializers.lecun_normal(), axes
                ),
            )

        # --- Q (direct or low-rank) ---
        if self.q_lora_rank is not None:
            q = LowRankProjection(
                bottleneck=self.q_lora_rank,
                features=h * d_qk,
                norm_eps=self.norm_eps,
                name="q_proj",
                dtype=self.dtype,
                param_dtype=self.param_dtype,
            )(x)
        else:
            q = proj(h * d_qk, "q_proj", (la.EMBED, la.HEADS))(x)
        q = q.reshape(b, t, h, d_qk)
        q_nope, q_rope = q[..., :d_nope], q[..., d_nope:]
        q_rope = apply_rope(q_rope, cos[..., : d_rope // 2],
                            sin[..., : d_rope // 2], self.rope_style)
        q = jnp.concatenate([q_nope, q_rope], axis=-1)

        # --- KV latent + decoupled shared rope key ---
        kv = proj(self.kv_lora_rank + d_rope, "kv_down_proj", (la.EMBED, None))(x)
        c_kv, k_rope = kv[..., : self.kv_lora_rank], kv[..., self.kv_lora_rank:]
        c_kv = RMSNorm(self.kv_lora_rank, eps=self.norm_eps,
                       name="kv_down_norm", param_dtype=self.param_dtype)(c_kv)
        # rotate the shared rope key at ITS OWN positions before any
        # caching (write-time rope, like the KV cache's rotated keys)
        k_rope = apply_rope(
            k_rope[:, :, None, :], cos[..., : d_rope // 2],
            sin[..., : d_rope // 2], self.rope_style,
        )[:, :, 0, :]

        # kernel declared raw (same "kv_up_proj/kernel" param path and init
        # as the nn.Dense it replaces — checkpoints/mappers/plans are
        # unchanged) so the absorbed decode path below can fold it into
        # the query/output sides instead of decompressing the cache
        kv_up_w = _ProjKernel(
            features=h * (d_nope + d_v), axes=(None, la.HEADS),
            param_dtype=self.param_dtype, name="kv_up_proj",
        )(self.kv_lora_rank)

        decode = self.decode_max_length > 0
        prefill_segs = {}
        if decode:
            s_max = self.decode_max_length
            idx = _decode_cache_index(self)
            start = idx.value
            _decode_contract_checks(start, t, s_max)
            page_table = _decode_page_table(self)
            if page_table is not None:
                # paged serving mode: the latent/rope-key pools scatter
                # the one new token and hand back the gathered per-row
                # view — both decode paths below consume the full
                # buffer anyway, so they run unchanged on it (masks are
                # built over the gathered length)
                _paged_write_checks(start, t, mask)
            cached_c = _decode_cache_append(
                self, c_kv.astype(self.dtype), "cached_latent", s_max,
                start, page_table=page_table,
            )
            cached_r = _decode_cache_append(
                self, k_rope.astype(self.dtype), "cached_rope_key", s_max,
                start, page_table=page_table,
            )
            idx.value = start + t
            from d9d_tpu.nn.decode_flags import in_continuation_chunk

            if t == 1 or in_continuation_chunk():
                dec_mask = _decode_slot_mask(
                    start, t, cached_c.shape[1], None, mask
                )
                if t == 1 and self.decode_absorbed:
                    # ABSORBED form (DeepSeek-V2 decode trick): fold
                    # W_up^K into the query and W_up^V into the output —
                    # q_nope^T (W_k c) == (W_k^T q_nope)^T c — so
                    # attention runs in rank space against the latent
                    # cache directly, with no per-step decompression of
                    # s_max slots
                    out = self._absorbed_attend(
                        q_nope, q_rope, cached_c, cached_r, kv_up_w,
                        dec_mask, d_qk, d_nope, d_v,
                    )
                else:
                    # decompressed slot attention: the single-step
                    # oracle (decode_absorbed=False) and the
                    # continuation-prefill-chunk path — a chunk
                    # amortizes the one full-cache decompression over
                    # its t tokens (the vLLM-style MLA chunk recipe)
                    out = self._decompressed_attend(
                        q, cached_c, cached_r, kv_up_w, dec_mask,
                        d_qk, d_nope,
                    )
                out = checkpoint_name(out, "sdpa_out")
                return proj(self.hidden_size, "o_proj",
                            (la.HEADS, la.EMBED))(out.reshape(b, t, h * d_v))
            # prefill (t > 1): decompress only the NEW tokens and attend
            # them causally through the training SDPA — valid for the
            # first call (start == 0), which is how loop/generate.py
            # issues its first (or only) multi-token call (contract at
            # GroupedQueryAttention._decode_attend; continuation chunks
            # took the slot path above)
            prefill_segs = _prefill_segments(mask, t, s_max)
        k, v = _decompress_kv(c_kv, k_rope, kv_up_w, h, d_nope, self.dtype)

        # pad V: softmax(QKᵀ)·[V|0] = [out|0] (reference :199-207)
        pad = d_qk - d_v
        if pad > 0:
            v = jnp.pad(v, ((0, 0), (0, 0), (0, 0), (0, pad)))

        if decode:  # t > 1 prefill over just the new tokens
            out = self.sdpa(
                q, k, v, causal=True, softmax_scale=scale,
                **prefill_segs,
            )
        else:
            out = self.sdpa(
                q, k, v, causal=True, softmax_scale=scale, mask=mask
            )
        out = checkpoint_name(out, "sdpa_out")
        if pad > 0:
            out = out[..., :d_v]
        out = out.reshape(b, t, h * d_v)
        return proj(self.hidden_size, "o_proj", (la.HEADS, la.EMBED))(out)

    def _decompressed_attend(self, q, c, k_rope, w, dec_mask,
                             d_qk, d_nope):
        """Non-absorbed decode: decompress every cache slot through kv_up
        each step (O(s_max·r·h·(d_nope+d_v)) per token — the traffic the
        absorbed form avoids) and attend over the slot cache. Serves as
        the absorbed path's correctness oracle and the honest
        'decompressed' leg of tools/bench_kernels.py mla_decode.
        """
        from d9d_tpu.ops.attention.eager import eager_sdpa

        k, v = _decompress_kv(
            c, k_rope, w, self.num_heads, d_nope, self.dtype
        )
        scale = (
            self.softmax_scale if self.softmax_scale is not None
            else d_qk**-0.5
        )
        return eager_sdpa(
            q, k, v, causal=False, softmax_scale=scale, mask=dec_mask
        )

    def _absorbed_attend(self, q_nope, q_rope, c, k_rope, w, dec_mask,
                         d_qk, d_nope, d_v):
        """Rank-space attention against the latent cache (fp32).

        scores = (W_k^T q_nope)^T c + q_rope^T k_rope; the value side
        stays latent until one final fold through W_v. Per step this
        costs O(t·h·r·(d_nope+d_v)) absorption + O(t·h·s·r) attention
        instead of decompressing all s_max slots through kv_up.
        """
        h = self.num_heads
        r = self.kv_lora_rank
        wk = w.astype(jnp.float32).reshape(r, h, d_nope + d_v)
        wv = wk[..., d_nope:]
        wk = wk[..., :d_nope]
        qn = q_nope.astype(jnp.float32)
        qr = q_rope.astype(jnp.float32)
        cf = c.astype(jnp.float32)
        rf = k_rope.astype(jnp.float32)
        q_abs = jnp.einsum("bthd,rhd->bthr", qn, wk)
        scale = (
            self.softmax_scale if self.softmax_scale is not None
            else d_qk**-0.5
        )
        scores = (
            jnp.einsum("bthr,bsr->bhts", q_abs, cf)
            + jnp.einsum("bthd,bsd->bhts", qr, rf)
        ) * scale
        neg_big = jnp.asarray(-1e30, scores.dtype)
        scores = jnp.where(dec_mask, scores, neg_big)
        # finite mask sentinel (not -inf): a fully-masked row must produce
        # zeros like eager_sdpa's guarded softmax, not NaN
        p = jax.nn.softmax(scores, axis=-1)
        p = jnp.where(
            jnp.any(dec_mask, axis=-1, keepdims=True), p, 0.0
        )
        out_lat = jnp.einsum("bhts,bsr->bthr", p, cf)
        out = jnp.einsum("bthr,rhd->bthd", out_lat, wv)
        return out.astype(self.dtype)
