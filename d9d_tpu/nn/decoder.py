"""Pre-norm transformer decoder layer (reference:
d9d/module/model/qwen3_dense/decoder_layer.py:79)."""

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from d9d_tpu.core.types import Array
from d9d_tpu.nn.attention import GroupedQueryAttention
from d9d_tpu.nn.mlp import SwiGLU
from d9d_tpu.nn.norm import RMSNorm
from d9d_tpu.nn.sdpa.protocol import SdpaBackend
from d9d_tpu.ops import RopeStyle


class DecoderLayer(nn.Module):
    hidden_size: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    intermediate_size: int
    sdpa: SdpaBackend
    qk_norm: bool = True
    rope_style: RopeStyle = RopeStyle.HALF
    window_size: int | None = None
    use_sinks: bool = False
    use_output_gate: bool = False
    fused_qkv: bool = False
    norm_eps: float = 1e-6
    # KV-cache decode mode when > 0 (see GroupedQueryAttention)
    decode_max_length: int = 0
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(
        self, x: Array, cos: Array, sin: Array, mask: Optional[Array] = None
    ) -> Array:
        # named scopes (trace-time only, zero runtime cost): attach the
        # module path to the attention/MLP HLO so profiler traces and
        # trace_summary's device-scope table attribute per-block time —
        # the same paths the numerics plane's taps mirror
        with jax.named_scope("decoder/attn"):
            attn_out = GroupedQueryAttention(
                hidden_size=self.hidden_size,
                num_heads=self.num_heads,
                num_kv_heads=self.num_kv_heads,
                head_dim=self.head_dim,
                sdpa=self.sdpa,
                qk_norm=self.qk_norm,
                rope_style=self.rope_style,
                window_size=self.window_size,
                use_sinks=self.use_sinks,
                use_output_gate=self.use_output_gate,
                fused_qkv=self.fused_qkv,
                decode_max_length=self.decode_max_length,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                name="self_attn",
            )(
                RMSNorm(self.hidden_size, eps=self.norm_eps, name="input_layernorm")(x),
                cos,
                sin,
                mask,
            )
        x = x + attn_out
        with jax.named_scope("decoder/mlp"):
            mlp_out = SwiGLU(
                hidden_size=self.hidden_size,
                intermediate_size=self.intermediate_size,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                name="mlp",
            )(
                RMSNorm(
                    self.hidden_size, eps=self.norm_eps, name="post_attention_layernorm"
                )(x)
            )
        return x + mlp_out
