"""Logical axis vocabulary for parameter partitioning.

The reference attaches parallelism to modules imperatively (DTensor
``ParallelStyle``s, d9d/module/parallelism/style/*). The TPU-native design
instead annotates every parameter with *logical* axis names at definition
time; a parallelism *plan* is then just a table mapping logical names to
mesh axes (see d9d_tpu/parallel/plan.py). Same separation of concerns —
model code never mentions mesh axes — but it compiles to XLA SPMD sharding
instead of eager collectives.
"""

# Embedding / residual stream width.
EMBED = "embed"
# Vocabulary dimension.
VOCAB = "vocab"
# Feature dim of vocab-range tables (embedding + LM head). Distinct from
# EMBED so ZeRO-3 plans can shard these tables on their (large) vocab dim
# instead: sharding the feature dim puts the table's layout at war with
# sequence-parallel activations (t@cp vs e@cp) and forces the partitioner
# into replicate-reshard at every lookup.
VOCAB_FEATURES = "vocab_features"
# FFN intermediate width.
MLP = "mlp"
# Attention query heads (x head_dim fused projections are split on heads).
HEADS = "heads"
# Attention kv heads.
KV_HEADS = "kv_heads"
# Per-head feature dim.
HEAD_DIM = "head_dim"
# Expert index dim of MoE grouped weights.
EXPERT = "expert"
# In/out feature dims of grouped expert weights (distinct from dense
# EMBED/MLP so EP plans can leave them unsharded while FSDP shards dense).
EXPERT_EMBED = "expert_embed"
EXPERT_MLP = "expert_mlp"
# Classification classes.
CLASSES = "classes"
