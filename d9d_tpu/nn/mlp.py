"""SwiGLU feed-forward block (reference: d9d/module/block/ffn/swiglu.py:8)."""

import flax.linen as nn
import jax.numpy as jnp

from d9d_tpu.nn import logical_axes as la
from d9d_tpu.ops import silu_mul


class SwiGLU(nn.Module):
    """gate/up/down projections around the fused silu-mul op.

    Weights carry logical axes (embed, mlp) / (mlp, embed): a TP plan maps
    ``mlp`` to the tp mesh axis (column-split gate/up, row-split down) and
    XLA inserts the single all-reduce after the down projection.
    """

    hidden_size: int
    intermediate_size: int
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        dense = lambda features, name, axes: nn.Dense(  # noqa: E731
            features,
            use_bias=False,
            name=name,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), axes
            ),
        )
        gate = dense(self.intermediate_size, "gate_proj", (la.EMBED, la.MLP))(x)
        up = dense(self.intermediate_size, "up_proj", (la.EMBED, la.MLP))(x)
        return dense(self.hidden_size, "down_proj", (la.MLP, la.EMBED))(
            silu_mul(gate, up)
        )
