from d9d_tpu.nn.sdpa.config import (
    SdpaBackendConfig,
    SdpaEagerConfig,
    SdpaPallasFlashConfig,
    SdpaRingConfig,
)
from d9d_tpu.nn.sdpa.factory import build_sdpa_backend
from d9d_tpu.nn.sdpa.protocol import SdpaBackend

__all__ = [
    "SdpaBackend",
    "SdpaBackendConfig",
    "SdpaEagerConfig",
    "SdpaPallasFlashConfig",
    "SdpaRingConfig",
    "build_sdpa_backend",
]
