"""SDPA backend protocol (reference: d9d/module/block/attention/sdpa/protocol.py:6)."""

from typing import Protocol

from d9d_tpu.core.types import Array


class SdpaBackend(Protocol):
    """A scaled-dot-product-attention implementation.

    All backends accept the full feature surface; ones that cannot honor an
    argument must raise, never silently ignore (matching the reference's
    backend contract).
    """

    def __call__(
        self,
        q: Array,
        k: Array,
        v: Array,
        *,
        causal: bool = True,
        softmax_scale: float | None = None,
        window_size: int | None = None,
        sinks: Array | None = None,
        mask: Array | None = None,
        q_segments: Array | None = None,
        kv_segments: Array | None = None,
    ) -> Array: ...
