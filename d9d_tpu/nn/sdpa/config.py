"""SDPA backend configs — pydantic discriminated union.

Reference pattern: d9d/module/block/attention/sdpa/config.py:8-76 and the
backend-selection DEP (deps/0008-dep-backend-selection.md): every backend
family gets a typed config union + a factory with auto-detection + one env
override channel carrying a JSON-encoded config.
"""

from typing import Annotated, Literal, Union

import pydantic


class SdpaEagerConfig(pydantic.BaseModel):
    """Pure-XLA attention. Full feature surface; the correctness oracle."""

    type: Literal["eager"] = "eager"


class SdpaPallasFlashConfig(pydantic.BaseModel):
    """Pallas flash-attention kernel (TPU only)."""

    type: Literal["pallas_flash"] = "pallas_flash"
    block_q: int = 1024
    block_kv: int = 512
    # one-pass backward (see ops/attention/pallas_flash._bwd_fused_kernel);
    # None = env D9D_TPU_FLASH_BWD ("fused"/"split"), default split
    fused_bwd: bool | None = None


class SdpaRingConfig(pydantic.BaseModel):
    """Ring attention over the context-parallel mesh axis (ops/attention/
    ring.py). Requires the model's sequence dim sharded over ``seq_axis``."""

    type: Literal["ring"] = "ring"
    seq_axis: str = "cp_s"
    batch_axes: tuple[str, ...] = ("dp_r", "dp_s")
    head_axes: tuple[str, ...] = ("tp",)


SdpaBackendConfig = Annotated[
    Union[SdpaEagerConfig, SdpaPallasFlashConfig, SdpaRingConfig],
    pydantic.Field(discriminator="type"),
]
