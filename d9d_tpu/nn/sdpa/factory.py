"""SDPA backend factory with auto-detection and env override.

Reference: d9d/module/block/attention/sdpa/factory.py:42 (auto order
flash4 > flash2 > torch > eager, env ``D9D_BACKEND_AUTO_SDPA``). Here the
order is pallas_flash (TPU) > eager, and the override channel is
``D9D_TPU_BACKEND_SDPA`` carrying a JSON-encoded config.
"""

import functools
import json
import os

import jax
import pydantic

from d9d_tpu.nn.sdpa.config import (
    SdpaBackendConfig,
    SdpaEagerConfig,
    SdpaPallasFlashConfig,
    SdpaRingConfig,
)
from d9d_tpu.nn.sdpa.protocol import SdpaBackend

ENV_OVERRIDE = "D9D_TPU_BACKEND_SDPA"

_adapter = pydantic.TypeAdapter(SdpaBackendConfig)


def _auto_config() -> SdpaBackendConfig:
    if os.environ.get(ENV_OVERRIDE):
        return _adapter.validate_python(json.loads(os.environ[ENV_OVERRIDE]))
    if jax.default_backend() == "tpu":
        try:  # auto mode degrades gracefully if the kernel is unavailable
            import d9d_tpu.ops.attention.pallas_flash  # noqa: F401

            return SdpaPallasFlashConfig()
        except ImportError:
            return SdpaEagerConfig()
    return SdpaEagerConfig()


def build_sdpa_backend(config: SdpaBackendConfig | None = None) -> SdpaBackend:
    """Build a backend; ``None`` = auto-detect (env override wins)."""
    if config is None:
        config = _auto_config()
    if isinstance(config, SdpaEagerConfig):
        from d9d_tpu.ops.attention.eager import eager_sdpa

        return eager_sdpa
    if isinstance(config, SdpaPallasFlashConfig):
        from d9d_tpu.ops.attention.pallas_flash import make_pallas_flash_sdpa

        return make_pallas_flash_sdpa(
            block_q=config.block_q, block_kv=config.block_kv,
            fused_bwd=config.fused_bwd,
        )
    if isinstance(config, SdpaRingConfig):
        from d9d_tpu.core.mesh import resolve_ambient_mesh
        from d9d_tpu.ops.attention.ring import make_ring_sdpa

        mesh = resolve_ambient_mesh(
            (config.seq_axis, *config.batch_axes, *config.head_axes),
            what="ring sdpa",
        )
        return make_ring_sdpa(
            mesh,
            seq_axis=config.seq_axis,
            batch_axes=config.batch_axes,
            head_axes=config.head_axes,
        )
    raise TypeError(f"unknown sdpa config: {config!r}")


@functools.cache
def default_sdpa_backend() -> SdpaBackend:
    return build_sdpa_backend()
