"""Model heads: language modelling (fused CE), classification, embedding.

Reference: d9d/module/block/head/{language_modelling.py:14,
classification.py:7, embedding.py:8}.
"""

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from d9d_tpu.core.types import Array
from d9d_tpu.nn import logical_axes as la
from d9d_tpu.nn.vocab_ranges import concat_vocab_ranges, make_vocab_range_params
from d9d_tpu.ops import LM_IGNORE_INDEX, linear_cross_entropy


class LanguageModellingHead(nn.Module):
    """LM head over named vocab ranges with fused linear+CE loss.

    ``__call__`` returns per-token loss (never materializing full logits,
    reference language_modelling.py:14 via CCE); ``logits`` returns raw
    logits for inference/eval paths.
    """

    vocab_ranges: tuple[tuple[str, int], ...]
    hidden_size: int
    ce_chunk_size: "int | str" = "auto"
    logit_softcap: float | None = None
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32

    def setup(self) -> None:
        self._tables = make_vocab_range_params(
            self.param,
            "head",
            self.vocab_ranges,
            self.hidden_size,
            self.param_dtype,
            nn.initializers.lecun_normal(),
        )

    def _weight(self) -> Array:
        return concat_vocab_ranges(self._tables)

    def __call__(self, hidden: Array, labels: Array) -> Array:
        """hidden [B,T,D], labels [B,T] → per-token loss [B,T] (fp32)."""
        w = self._weight()
        b, t, d = hidden.shape
        # CE matmul policy follows the activation dtype (linear_ce default):
        # bf16 models take the full-rate MXU path, fp32 models stay exact
        loss = linear_cross_entropy(
            hidden.reshape(b * t, d).astype(self.dtype),
            w,
            labels.reshape(b * t),
            chunk_size=self.ce_chunk_size,
            logit_softcap=self.logit_softcap,
        )
        return loss.reshape(b, t)

    def logits(self, hidden: Array) -> Array:
        w = self._weight()
        return hidden.astype(jnp.float32) @ w.astype(jnp.float32).T


class ClassificationHead(nn.Module):
    """Linear classifier over a pooled hidden state (reference classification.py:7)."""

    hidden_size: int
    num_classes: int
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, hidden: Array) -> Array:
        return nn.Dense(
            self.num_classes,
            use_bias=False,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), (la.EMBED, la.CLASSES)
            ),
            name="classifier",
        )(hidden).astype(jnp.float32)


class EmbeddingHead(nn.Module):
    """Mean-pool + L2-normalize sentence embeddings (reference embedding.py:8)."""

    @nn.compact
    def __call__(self, hidden: Array, pooling_mask: Optional[Array] = None) -> Array:
        """hidden [B,T,D], pooling_mask [B,T] (1 = include) → [B,D] fp32."""
        h = hidden.astype(jnp.float32)
        if pooling_mask is None:
            pooled = h.mean(axis=1)
        else:
            m = pooling_mask.astype(jnp.float32)[..., None]
            pooled = (h * m).sum(axis=1) / jnp.maximum(m.sum(axis=1), 1.0)
        norm = jnp.linalg.norm(pooled, axis=-1, keepdims=True)
        return pooled / jnp.maximum(norm, 1e-12)


__all__ = [
    "LM_IGNORE_INDEX",
    "LanguageModellingHead",
    "ClassificationHead",
    "EmbeddingHead",
]
