"""Per-layer hidden-state aggregation.

Reference: d9d/module/block/hidden_states_aggregator/{base,mean,noop,
factory}.py — models snapshot per-layer hidden states (masked-mean pooled)
across pipeline stages for aux losses / analysis; ``pack_with_snapshot``
prepends the snapshot arriving from the previous stage. The torch version
is a stateful object mutated during forward; under jit the same contract
works because the aggregator lives only within one traced call (the model
constructs it per forward, reference qwen3 model.py usage).
"""

import enum

import jax.numpy as jnp

from d9d_tpu.core.types import Array


class HiddenStatesAggregationMode(str, enum.Enum):
    no = "no"
    mean = "mean"


def masked_mean_pool(hidden_states: Array, agg_mask: Array) -> Array:
    """[B,T,D] pooled to [B,D] over mask-valid tokens (fp32 math)."""
    h = hidden_states.astype(jnp.float32)
    m = agg_mask.astype(jnp.float32)
    num = jnp.maximum(m.sum(axis=1)[:, None], 1.0)
    return ((h * m[:, :, None]).sum(axis=1) / num).astype(hidden_states.dtype)


class HiddenStatesAggregatorNoOp:
    def add_hidden_states(self, hidden_states: Array) -> None:
        pass

    def pack_with_snapshot(self, snapshot: Array | None) -> Array | None:
        return None


class HiddenStatesAggregatorMean:
    """Pools each added layer's states immediately; packing stacks the
    layer snapshots [L,B,D] and prepends the previous-stage snapshot."""

    def __init__(self, agg_mask: Array):
        self._agg_mask = agg_mask
        self._collected: list[Array] = []

    def add_hidden_states(self, hidden_states: Array) -> None:
        self._collected.append(masked_mean_pool(hidden_states, self._agg_mask))

    def pack_with_snapshot(self, snapshot: Array | None) -> Array | None:
        if not self._collected:
            return None
        stacked = jnp.stack(self._collected, axis=0)
        self._collected.clear()
        if snapshot is not None:
            stacked = jnp.concatenate([snapshot, stacked], axis=0)
        return stacked


def create_hidden_states_aggregator(
    mode: HiddenStatesAggregationMode, agg_mask: Array | None
):
    if mode == HiddenStatesAggregationMode.no:
        return HiddenStatesAggregatorNoOp()
    if mode == HiddenStatesAggregationMode.mean:
        if agg_mask is None:
            raise ValueError("mean aggregation requires an aggregation mask")
        return HiddenStatesAggregatorMean(agg_mask)
    raise ValueError(f"unknown hidden states aggregation mode: {mode}")
