"""Llama-3 model family (BASELINE.md config 4: Llama-3-70B-class 4D runs).

Llama-3's decoder is architecturally the Qwen3-dense stack minus the
q/k RMSNorms (and with Llama's rope theta / vocab): HF even uses the
same per-layer tensor names (``model.layers.N.self_attn.q_proj`` ...).
So the family is expressed as presets over :class:`Qwen3DenseConfig`
with ``qk_norm=False`` plus thin aliases — checkpoints, sharding plans,
pipelining stages, PEFT and the HF mappers (which already gate the
q/k-norm entries on ``config.qk_norm``,
models/qwen3/huggingface.py:159) all apply unchanged. Llama-3.1 long
context rides the ``llama3`` rope-scaling law (ops/rope.py
RopeScalingLlama3 — a scaling type beyond the reference's four).

Reference parity note: the reference ships only Qwen3 models
(d9d/module/model/); this family is beyond-reference surface for the
config-4 baseline target.
"""

from d9d_tpu.models.qwen3.config import Qwen3DenseConfig
from d9d_tpu.models.qwen3.dense import (
    Qwen3DenseBackbone as LlamaBackbone,
    Qwen3DenseCausalLM as LlamaCausalLM,
    Qwen3DenseForClassification as LlamaForClassification,
    Qwen3DenseForEmbedding as LlamaForEmbedding,
)
from d9d_tpu.models.qwen3.huggingface import (
    qwen3_dense_from_hf_mapper as llama_from_hf_mapper,
    qwen3_dense_to_hf_mapper as llama_to_hf_mapper,
)
from d9d_tpu.ops import RopeScalingLlama3

LlamaConfig = Qwen3DenseConfig  # same static surface; qk_norm=False


def llama3_tiny(vocab_size: int = 256) -> Qwen3DenseConfig:
    """2-layer CPU-runnable Llama-3-shaped config (tests / smoke)."""
    return Qwen3DenseConfig(
        vocab_ranges=(("default", vocab_size),),
        hidden_size=64,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        head_dim=16,
        intermediate_size=128,
        qk_norm=False,
        rope_theta=500_000.0,
        remat=False,
    )


def llama3_8b(vocab_size: int = 128_256) -> Qwen3DenseConfig:
    return Qwen3DenseConfig(
        vocab_ranges=(("default", vocab_size),),
        hidden_size=4096,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        head_dim=128,
        intermediate_size=14_336,
        qk_norm=False,
        rope_theta=500_000.0,
        norm_eps=1e-5,
    )


def llama31_8b(vocab_size: int = 128_256) -> Qwen3DenseConfig:
    """Llama-3.1: 128k context via the llama3 piecewise rope scaling."""
    return Qwen3DenseConfig(
        vocab_ranges=(("default", vocab_size),),
        hidden_size=4096,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        head_dim=128,
        intermediate_size=14_336,
        qk_norm=False,
        rope_theta=500_000.0,
        norm_eps=1e-5,
        rope_scaling=RopeScalingLlama3(
            factor=8.0,
            original_max_position=8192,
            low_freq_factor=1.0,
            high_freq_factor=4.0,
        ),
    )


def llama3_70b(vocab_size: int = 128_256) -> Qwen3DenseConfig:
    """The BASELINE config-4 geometry (PP x TP x FSDP pod-slice runs)."""
    return Qwen3DenseConfig(
        vocab_ranges=(("default", vocab_size),),
        hidden_size=8192,
        num_layers=80,
        num_heads=64,
        num_kv_heads=8,
        head_dim=128,
        intermediate_size=28_672,
        qk_norm=False,
        rope_theta=500_000.0,
        norm_eps=1e-5,
    )


__all__ = [
    "LlamaBackbone",
    "LlamaCausalLM",
    "LlamaConfig",
    "LlamaForClassification",
    "LlamaForEmbedding",
    "llama3_tiny",
    "llama3_8b",
    "llama31_8b",
    "llama3_70b",
    "llama_from_hf_mapper",
    "llama_to_hf_mapper",
]
