"""Bidirectional HuggingFace ⇄ d9d_tpu state mappers for Qwen3 dense + MoE.

Parity: reference d9d/module/model/qwen3_dense/huggingface.py (234 LoC of
bidirectional mappers). Layout differences handled here:

- torch ``nn.Linear.weight`` is [out, in]; flax ``Dense.kernel`` is
  [in, out] → transpose.
- embedding/lm_head are [vocab, hidden] on both sides → split/concat over
  named vocab ranges only.
- flax param tree keys are dotted under the ``params.`` root:
  ``params.model.layers_{i}.self_attn.q_proj.kernel``.
"""

import numpy as np

from d9d_tpu.model_state.mapper import (
    ModelStateMapper,
    ModelStateMapperParallel,
    ModelStateMapperRename,
    StateDict,
    StateGroup,
)
from d9d_tpu.models.qwen3.config import Qwen3DenseConfig

_P = "params."


class _TransposedRename(ModelStateMapper):
    """[out,in] ⇄ [in,out] weight movement with a rename in one group."""

    def __init__(self, name_from: str, name_to: str):
        self._name_from = name_from
        self._name_to = name_to

    def state_dependency_groups(self) -> frozenset[StateGroup]:
        return frozenset(
            [
                StateGroup(
                    inputs=frozenset([self._name_from]),
                    outputs=frozenset([self._name_to]),
                )
            ]
        )

    def apply(self, group: StateDict) -> StateDict:
        return {self._name_to: np.swapaxes(group[self._name_from], 0, 1)}


class _SplitRanges(ModelStateMapper):
    """Split one [vocab, ...] tensor into named ranges of given sizes."""

    def __init__(self, source: str, targets: list[tuple[str, int]]):
        self._source = source
        self._targets = list(targets)

    def state_dependency_groups(self) -> frozenset[StateGroup]:
        return frozenset(
            [
                StateGroup(
                    inputs=frozenset([self._source]),
                    outputs=frozenset(n for n, _ in self._targets),
                )
            ]
        )

    def apply(self, group: StateDict) -> StateDict:
        tensor = np.asarray(group[self._source])
        total = sum(s for _, s in self._targets)
        if tensor.shape[0] != total:
            raise ValueError(
                f"{self._source}: vocab dim {tensor.shape[0]} != "
                f"sum of ranges {total}"
            )
        out: StateDict = {}
        offset = 0
        for name, size in self._targets:
            out[name] = np.ascontiguousarray(tensor[offset : offset + size])
            offset += size
        return out


class _ConcatRanges(ModelStateMapper):
    """Inverse of _SplitRanges."""

    def __init__(self, sources: list[str], target: str):
        self._sources = list(sources)
        self._target = target

    def state_dependency_groups(self) -> frozenset[StateGroup]:
        return frozenset(
            [
                StateGroup(
                    inputs=frozenset(self._sources),
                    outputs=frozenset([self._target]),
                )
            ]
        )

    def apply(self, group: StateDict) -> StateDict:
        return {
            self._target: np.concatenate(
                [group[s] for s in self._sources], axis=0
            )
        }

def _embed_head_from_hf_mappers(
    config,
    *,
    tie_word_embeddings: bool,
    include_embed: bool,
    include_head: bool,
) -> list[ModelStateMapper]:
    """Shared embed/norm/head mappers for the HF->d9d direction, handling
    the tied-embedding fanout up front (one group feeds both families)."""
    embed_targets = [
        (f"{_P}model.embed_tokens.embedding_{n}", s)
        for n, s in config.vocab_ranges
    ]
    head_targets = [
        (f"{_P}lm_head.head_{n}", s) for n, s in config.vocab_ranges
    ]
    mappers: list[ModelStateMapper] = []
    if include_head:
        mappers.append(
            ModelStateMapperRename("model.norm.weight", f"{_P}model.norm.weight")
        )
    if tie_word_embeddings and include_embed and include_head:
        mappers.append(
            _SplitRangesFanout(
                "model.embed_tokens.weight", embed_targets, head_targets
            )
        )
        return mappers
    if include_embed:
        mappers.append(_SplitRanges("model.embed_tokens.weight", embed_targets))
    if include_head:
        source = (
            "model.embed_tokens.weight"
            if tie_word_embeddings
            else "lm_head.weight"
        )
        mappers.append(_SplitRanges(source, head_targets))
    return mappers


def _layer_pairs(config: Qwen3DenseConfig, i: int) -> list[tuple[str, str, bool]]:
    """(hf_name, d9d_name, transposed) for one decoder layer."""
    hf = f"model.layers.{i}"
    us = f"{_P}model.layers_{i}"
    pairs: list[tuple[str, str, bool]] = []
    for proj in ("q_proj", "k_proj", "v_proj", "o_proj"):
        pairs.append(
            (f"{hf}.self_attn.{proj}.weight", f"{us}.self_attn.{proj}.kernel", True)
        )
    if config.use_output_gate:
        pairs.append(
            (f"{hf}.self_attn.gate_proj.weight", f"{us}.self_attn.gate_proj.kernel", True)
        )
    if config.qk_norm:
        pairs.append((f"{hf}.self_attn.q_norm.weight", f"{us}.self_attn.q_norm.weight", False))
        pairs.append((f"{hf}.self_attn.k_norm.weight", f"{us}.self_attn.k_norm.weight", False))
    if config.use_sinks:
        pairs.append((f"{hf}.self_attn.sinks", f"{us}.self_attn.sinks", False))
    for proj in ("gate_proj", "up_proj", "down_proj"):
        pairs.append((f"{hf}.mlp.{proj}.weight", f"{us}.mlp.{proj}.kernel", True))
    pairs.append((f"{hf}.input_layernorm.weight", f"{us}.input_layernorm.weight", False))
    pairs.append(
        (f"{hf}.post_attention_layernorm.weight", f"{us}.post_attention_layernorm.weight", False)
    )
    return pairs


def qwen3_dense_from_hf_mapper(
    config: Qwen3DenseConfig,
    *,
    tie_word_embeddings: bool = False,
    layers: list[int] | None = None,
    include_embed: bool = True,
    include_head: bool = True,
) -> ModelStateMapper:
    """HF checkpoint names → d9d_tpu CausalLM param names.

    ``layers``/``include_*`` restrict the mapper to one pipeline stage's
    params (reference huggingface.py builds stage-aware mappers the same
    way).
    """
    mappers = _embed_head_from_hf_mappers(
        config,
        tie_word_embeddings=tie_word_embeddings,
        include_embed=include_embed,
        include_head=include_head,
    )
    for i in layers if layers is not None else range(config.num_layers):
        for hf_name, our_name, transposed in _layer_pairs(config, i):
            mappers.append(
                _TransposedRename(hf_name, our_name)
                if transposed
                else ModelStateMapperRename(hf_name, our_name)
            )
    return ModelStateMapperParallel(mappers)


class _SplitRangesFanout(ModelStateMapper):
    """Split one tensor into two parallel families of named ranges (tied
    embeddings: the same HF table feeds embed_tokens and lm_head)."""

    def __init__(
        self,
        source: str,
        targets_a: list[tuple[str, int]],
        targets_b: list[tuple[str, int]],
    ):
        self._split_a = _SplitRanges(source, targets_a)
        self._split_b = _SplitRanges(source, targets_b)
        self._source = source
        self._outputs = frozenset(
            [n for n, _ in targets_a] + [n for n, _ in targets_b]
        )

    def state_dependency_groups(self) -> frozenset[StateGroup]:
        return frozenset(
            [
                StateGroup(
                    inputs=frozenset([self._source]), outputs=self._outputs
                )
            ]
        )

    def apply(self, group: StateDict) -> StateDict:
        out = self._split_a.apply(group)
        out.update(self._split_b.apply(group))
        return out


def qwen3_dense_to_hf_mapper(
    config: Qwen3DenseConfig,
    *,
    tie_word_embeddings: bool = False,
    layers: list[int] | None = None,
    include_embed: bool = True,
    include_head: bool = True,
) -> ModelStateMapper:
    """d9d_tpu CausalLM param names → HF checkpoint names."""
    mappers: list[ModelStateMapper] = []
    if include_embed:
        mappers.append(
            _ConcatRanges(
                [
                    f"{_P}model.embed_tokens.embedding_{n}"
                    for n, _ in config.vocab_ranges
                ],
                "model.embed_tokens.weight",
            )
        )
    for i in layers if layers is not None else range(config.num_layers):
        for hf_name, our_name, transposed in _layer_pairs(config, i):
            mappers.append(
                _TransposedRename(our_name, hf_name)
                if transposed
                else ModelStateMapperRename(our_name, hf_name)
            )
    if include_head:
        mappers.append(
            ModelStateMapperRename(f"{_P}model.norm.weight", "model.norm.weight")
        )
        if not tie_word_embeddings:
            mappers.append(
                _ConcatRanges(
                    [
                        f"{_P}lm_head.head_{n}"
                        for n, _ in config.vocab_ranges
                    ],
                    "lm_head.weight",
                )
            )
        # tied: lm_head params are simply not exported
    return ModelStateMapperParallel(mappers)


# --- Qwen3-MoE ------------------------------------------------------------
# Reference: d9d/module/model/qwen3_moe/huggingface.py:118,290 (incl. the
# v4 ModuleList experts format: one [out,in] weight per expert, stacked
# here into our grouped [E, in, out] layout).


class _StackExpertsTransposed(ModelStateMapper):
    """E per-expert [out,in] weights → one grouped [E, in, out] tensor."""

    def __init__(self, sources: list[str], target: str):
        self._sources = list(sources)
        self._target = target

    def state_dependency_groups(self) -> frozenset[StateGroup]:
        return frozenset(
            [
                StateGroup(
                    inputs=frozenset(self._sources),
                    outputs=frozenset([self._target]),
                )
            ]
        )

    def apply(self, group: StateDict) -> StateDict:
        stacked = np.stack(
            [np.swapaxes(group[s], 0, 1) for s in self._sources], axis=0
        )
        return {self._target: np.ascontiguousarray(stacked)}


class _UnstackExpertsTransposed(ModelStateMapper):
    """Inverse of _StackExpertsTransposed."""

    def __init__(self, source: str, targets: list[str]):
        self._source = source
        self._targets = list(targets)

    def state_dependency_groups(self) -> frozenset[StateGroup]:
        return frozenset(
            [
                StateGroup(
                    inputs=frozenset([self._source]),
                    outputs=frozenset(self._targets),
                )
            ]
        )

    def apply(self, group: StateDict) -> StateDict:
        tensor = np.asarray(group[self._source])
        return {
            name: np.ascontiguousarray(np.swapaxes(tensor[e], 0, 1))
            for e, name in enumerate(self._targets)
        }


class _FusedExpertsFromHF(ModelStateMapper):
    """HF v5 fused ``gate_up_proj`` [E, 2i, h] → grouped gate/up [E, h, i].

    Reference huggingface.py FUSED branch (:60-81): transpose the last two
    dims, then chunk the last dim into (gate, up)."""

    def __init__(self, source: str, gate_target: str, up_target: str):
        self._source = source
        self._gate = gate_target
        self._up = up_target

    def state_dependency_groups(self) -> frozenset[StateGroup]:
        return frozenset(
            [
                StateGroup(
                    inputs=frozenset([self._source]),
                    outputs=frozenset([self._gate, self._up]),
                )
            ]
        )

    def apply(self, group: StateDict) -> StateDict:
        t = np.swapaxes(np.asarray(group[self._source]), -1, -2)
        if t.shape[-1] % 2 != 0:
            raise ValueError(
                f"{self._source}: fused gate_up dim {t.shape[-1]} is odd"
            )
        half = t.shape[-1] // 2
        return {
            self._gate: np.ascontiguousarray(t[..., :half]),
            self._up: np.ascontiguousarray(t[..., half:]),
        }


class _FusedExpertsToHF(ModelStateMapper):
    """Inverse of _FusedExpertsFromHF: concat (gate, up) on the last dim,
    then transpose the last two dims back to the HF fused layout."""

    def __init__(self, gate_source: str, up_source: str, target: str):
        self._gate = gate_source
        self._up = up_source
        self._target = target

    def state_dependency_groups(self) -> frozenset[StateGroup]:
        return frozenset(
            [
                StateGroup(
                    inputs=frozenset([self._gate, self._up]),
                    outputs=frozenset([self._target]),
                )
            ]
        )

    def apply(self, group: StateDict) -> StateDict:
        fused = np.concatenate(
            [np.asarray(group[self._gate]), np.asarray(group[self._up])],
            axis=-1,
        )
        return {self._target: np.ascontiguousarray(np.swapaxes(fused, -1, -2))}


class _TransposedRenameLast2(ModelStateMapper):
    """Rename + swap the LAST two dims (3D grouped expert tensors)."""

    def __init__(self, name_from: str, name_to: str):
        self._name_from = name_from
        self._name_to = name_to

    def state_dependency_groups(self) -> frozenset[StateGroup]:
        return frozenset(
            [
                StateGroup(
                    inputs=frozenset([self._name_from]),
                    outputs=frozenset([self._name_to]),
                )
            ]
        )

    def apply(self, group: StateDict) -> StateDict:
        return {
            self._name_to: np.ascontiguousarray(
                np.swapaxes(np.asarray(group[self._name_from]), -1, -2)
            )
        }


def _moe_attention_pairs(config, i: int) -> list[tuple[str, str, bool]]:
    hf = f"model.layers.{i}"
    us = f"{_P}model.layers_{i}"
    pairs: list[tuple[str, str, bool]] = []
    for proj in ("q_proj", "k_proj", "v_proj", "o_proj"):
        pairs.append(
            (f"{hf}.self_attn.{proj}.weight", f"{us}.self_attn.{proj}.kernel", True)
        )
    if config.qk_norm:
        pairs.append((f"{hf}.self_attn.q_norm.weight", f"{us}.self_attn.q_norm.weight", False))
        pairs.append((f"{hf}.self_attn.k_norm.weight", f"{us}.self_attn.k_norm.weight", False))
    pairs.append((f"{hf}.input_layernorm.weight", f"{us}.input_layernorm.weight", False))
    pairs.append(
        (f"{hf}.post_attention_layernorm.weight", f"{us}.post_attention_layernorm.weight", False)
    )
    return pairs


def qwen3_moe_from_hf_mapper(
    config,
    *,
    tie_word_embeddings: bool = False,
    layers: list[int] | None = None,
    include_embed: bool = True,
    include_head: bool = True,
    experts_format: str = "module_list",
) -> ModelStateMapper:
    """HF Qwen3MoE checkpoint names → d9d_tpu Qwen3MoeCausalLM params.

    ``experts_format`` selects the HF expert-weight layout (reference
    huggingface.py:29-83): "module_list" = transformers v4.x per-expert
    Linear weights; "fused" = v5.x 3D ``experts.gate_up_proj`` /
    ``experts.down_proj`` tensors.
    """
    if experts_format not in ("module_list", "fused"):
        raise ValueError(f"unknown experts_format {experts_format!r}")
    mappers = _embed_head_from_hf_mappers(
        config,
        tie_word_embeddings=tie_word_embeddings,
        include_embed=include_embed,
        include_head=include_head,
    )
    for i in layers if layers is not None else range(config.num_layers):
        hf = f"model.layers.{i}"
        us = f"{_P}model.layers_{i}"
        for hf_name, our_name, transposed in _moe_attention_pairs(config, i):
            mappers.append(
                _TransposedRename(hf_name, our_name)
                if transposed
                else ModelStateMapperRename(hf_name, our_name)
            )
        if i in config.mlp_only_layers:
            for proj in ("gate_proj", "up_proj", "down_proj"):
                mappers.append(
                    _TransposedRename(
                        f"{hf}.mlp.{proj}.weight", f"{us}.mlp.{proj}.kernel"
                    )
                )
        else:
            mappers.append(
                _TransposedRename(
                    f"{hf}.mlp.gate.weight", f"{us}.mlp.router.gate.kernel"
                )
            )
            if experts_format == "fused":
                mappers.append(
                    _FusedExpertsFromHF(
                        f"{hf}.mlp.experts.gate_up_proj",
                        f"{us}.mlp.grouped_experts.gate_proj",
                        f"{us}.mlp.grouped_experts.up_proj",
                    )
                )
                mappers.append(
                    _TransposedRenameLast2(
                        f"{hf}.mlp.experts.down_proj",
                        f"{us}.mlp.grouped_experts.down_proj",
                    )
                )
            else:
                for proj in ("gate_proj", "up_proj", "down_proj"):
                    mappers.append(
                        _StackExpertsTransposed(
                            [
                                f"{hf}.mlp.experts.{e}.{proj}.weight"
                                for e in range(config.num_experts)
                            ],
                            f"{us}.mlp.grouped_experts.{proj}",
                        )
                    )
    return ModelStateMapperParallel(mappers)


def qwen3_moe_to_hf_mapper(
    config,
    *,
    tie_word_embeddings: bool = False,
    layers: list[int] | None = None,
    include_embed: bool = True,
    include_head: bool = True,
    experts_format: str = "module_list",
) -> ModelStateMapper:
    """d9d_tpu Qwen3MoeCausalLM params → HF Qwen3MoE checkpoint names.

    ``experts_format``: see :func:`qwen3_moe_from_hf_mapper`.
    """
    if experts_format not in ("module_list", "fused"):
        raise ValueError(f"unknown experts_format {experts_format!r}")
    mappers: list[ModelStateMapper] = []
    if include_embed:
        mappers.append(
            _ConcatRanges(
                [
                    f"{_P}model.embed_tokens.embedding_{n}"
                    for n, _ in config.vocab_ranges
                ],
                "model.embed_tokens.weight",
            )
        )
    for i in layers if layers is not None else range(config.num_layers):
        hf = f"model.layers.{i}"
        us = f"{_P}model.layers_{i}"
        for hf_name, our_name, transposed in _moe_attention_pairs(config, i):
            mappers.append(
                _TransposedRename(our_name, hf_name)
                if transposed
                else ModelStateMapperRename(our_name, hf_name)
            )
        if i in config.mlp_only_layers:
            for proj in ("gate_proj", "up_proj", "down_proj"):
                mappers.append(
                    _TransposedRename(
                        f"{us}.mlp.{proj}.kernel", f"{hf}.mlp.{proj}.weight"
                    )
                )
        else:
            mappers.append(
                _TransposedRename(
                    f"{us}.mlp.router.gate.kernel", f"{hf}.mlp.gate.weight"
                )
            )
            if experts_format == "fused":
                mappers.append(
                    _FusedExpertsToHF(
                        f"{us}.mlp.grouped_experts.gate_proj",
                        f"{us}.mlp.grouped_experts.up_proj",
                        f"{hf}.mlp.experts.gate_up_proj",
                    )
                )
                mappers.append(
                    _TransposedRenameLast2(
                        f"{us}.mlp.grouped_experts.down_proj",
                        f"{hf}.mlp.experts.down_proj",
                    )
                )
            else:
                for proj in ("gate_proj", "up_proj", "down_proj"):
                    mappers.append(
                        _UnstackExpertsTransposed(
                            f"{us}.mlp.grouped_experts.{proj}",
                            [
                                f"{hf}.mlp.experts.{e}.{proj}.weight"
                                for e in range(config.num_experts)
                            ],
                        )
                    )
    if include_head:
        mappers.append(
            ModelStateMapperRename(f"{_P}model.norm.weight", "model.norm.weight")
        )
        if not tie_word_embeddings:
            mappers.append(
                _ConcatRanges(
                    [f"{_P}lm_head.head_{n}" for n, _ in config.vocab_ranges],
                    "lm_head.weight",
                )
            )
    return ModelStateMapperParallel(mappers)
