from d9d_tpu.models.qwen3.config import Qwen3DenseConfig
from d9d_tpu.models.qwen3.dense import (
    Qwen3DenseBackbone,
    Qwen3DenseCausalLM,
    Qwen3DenseForClassification,
    Qwen3DenseForEmbedding,
)

__all__ = [
    "Qwen3DenseConfig",
    "Qwen3DenseBackbone",
    "Qwen3DenseCausalLM",
    "Qwen3DenseForClassification",
    "Qwen3DenseForEmbedding",
]
