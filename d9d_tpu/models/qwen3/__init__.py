from d9d_tpu.models.qwen3.config import Qwen3DenseConfig
from d9d_tpu.models.qwen3.moe import (
    Qwen3MoeBackbone,
    Qwen3MoeCausalLM,
    Qwen3MoeConfig,
    Qwen3MoeDecoderLayer,
    Qwen3MoeForClassification,
    Qwen3MoeForEmbedding,
)
from d9d_tpu.models.qwen3.dense import (
    Qwen3DenseBackbone,
    Qwen3DenseCausalLM,
    Qwen3DenseForClassification,
    Qwen3DenseForEmbedding,
)

__all__ = [
    "Qwen3DenseConfig",
    "Qwen3DenseBackbone",
    "Qwen3DenseCausalLM",
    "Qwen3DenseForClassification",
    "Qwen3DenseForEmbedding",
    "Qwen3MoeBackbone",
    "Qwen3MoeCausalLM",
    "Qwen3MoeConfig",
    "Qwen3MoeDecoderLayer",
    "Qwen3MoeForClassification",
    "Qwen3MoeForEmbedding",
]
