"""Qwen3-dense model family: stage-aware backbone + task heads.

Reference: d9d/module/model/qwen3_dense/model.py (stage-aware backbone with
layers keyed by *global* layer id) and the head variants. The backbone takes
token ids on the first pipeline stage and hidden states on later stages;
only the last stage applies the final norm / head. Layer params are named
``layers_{global_id}`` so checkpoints are stage-layout independent —
repartitioning the pipeline never remaps weights.
"""

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding

from d9d_tpu.core.types import Array
from d9d_tpu.models.qwen3.config import Qwen3DenseConfig
from d9d_tpu.nn.decoder import DecoderLayer
from d9d_tpu.nn.embedding import TokenEmbedding
from d9d_tpu.nn.heads import ClassificationHead, EmbeddingHead, LanguageModellingHead
from d9d_tpu.nn.norm import RMSNorm
from d9d_tpu.nn.sdpa.protocol import SdpaBackend
from d9d_tpu.ops import compute_rope_frequencies, make_rope_cos_sin
from d9d_tpu.pipelining import (
    PipelineStageInfo,
    distribute_layers_for_pipeline_stage,
)
from d9d_tpu.telemetry import numerics


def _remat_policy(name: str):
    """Map a config string to a jax.checkpoint policy (None = save nothing).

    ``save_expensive`` keeps every plain matmul output (dots-no-batch-dims)
    PLUS the named expensive ops the stock dot policies can't see — the
    Pallas flash output ("sdpa_out") and the MoE grouped-matmul outputs and
    their permuted input rows ("moe_grouped_dot"/"moe_permuted_rows") —
    so backward recomputes only cheap elementwise work. Costs activation
    memory proportional to layer width; "full" remains the default for
    memory-bound configs.
    """
    if name == "full":
        return None
    if name == "dots_no_batch":
        return jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims
    if name == "save_expensive":
        return jax.checkpoint_policies.save_from_both_policies(
            jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
            jax.checkpoint_policies.save_only_these_names(
                "sdpa_out", "moe_grouped_dot", "moe_permuted_rows"
            ),
        )
    raise ValueError(f"unknown remat_policy {name!r}")


class Qwen3DenseBackbone(nn.Module):
    config: Qwen3DenseConfig
    sdpa: SdpaBackend
    # KV-cache decode mode (loop/generate.py): 0 = training/eval path
    decode_max_length: int = 0
    stage: PipelineStageInfo = PipelineStageInfo()
    # residual-stream [B, T, E] sharding pin: anchors SPMD propagation at
    # every layer boundary so activation layouts can't drift into fused
    # batch shardings that force replicate-reshard at attention (the ring
    # SDPA wants [b@dp, t@cp_s, h@tp]) — see VERDICT r2 Weak #2
    act_sharding: Optional[NamedSharding] = None
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32

    def _pin(self, x: Array) -> Array:
        if self.act_sharding is not None:
            return lax.with_sharding_constraint(x, self.act_sharding)
        return x

    @nn.compact
    def __call__(
        self,
        x: Array,
        positions: Array,
        mask: Optional[Array] = None,
    ) -> Array:
        cfg = self.config
        if self.stage.is_first:
            x = TokenEmbedding(
                vocab_ranges=cfg.vocab_ranges,
                hidden_size=cfg.hidden_size,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                name="embed_tokens",
            )(x)
        else:
            x = x.astype(self.dtype)
        x = self._pin(x)

        inv_freq, att_scale = compute_rope_frequencies(
            cfg.head_dim, cfg.rope_theta, cfg.rope_scaling
        )
        cos, sin = make_rope_cos_sin(positions, inv_freq, att_scale)

        layer_cls = DecoderLayer
        # remat is a backward-pass tool; decode is forward-only and its
        # mutable cache variables don't compose with nn.remat
        if cfg.remat and self.decode_max_length == 0:
            layer_cls = nn.remat(
                DecoderLayer,
                prevent_cse=False,
                policy=_remat_policy(cfg.remat_policy),
            )

        for gid in distribute_layers_for_pipeline_stage(cfg.num_layers, self.stage):
            x = layer_cls(
                hidden_size=cfg.hidden_size,
                num_heads=cfg.num_heads,
                num_kv_heads=cfg.num_kv_heads,
                head_dim=cfg.head_dim,
                intermediate_size=cfg.intermediate_size,
                sdpa=self.sdpa,
                qk_norm=cfg.qk_norm,
                window_size=cfg.window_size,
                use_sinks=cfg.use_sinks,
                use_output_gate=cfg.use_output_gate,
                fused_qkv=cfg.fused_qkv,
                norm_eps=cfg.norm_eps,
                decode_max_length=self.decode_max_length,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                name=f"layers_{gid}",
            )(x, cos, sin, mask)
            x = self._pin(x)
            # numerics plane (telemetry/numerics.py): tap each layer's
            # residual-stream output HERE — outside the (possible)
            # nn.remat boundary — named by the layer's module path.
            # A no-op unless a numerics-enabled train step is tracing.
            numerics.tap(f"layers_{gid}", x)

        if self.stage.is_last:
            x = RMSNorm(cfg.hidden_size, eps=cfg.norm_eps, name="norm")(x)
            numerics.tap("norm", x)
        return x


class Qwen3DenseCausalLM(nn.Module):
    """Backbone + fused-CE LM head.

    On the last stage, ``__call__`` with labels returns per-token loss
    ``[B, T]``; non-last stages return the hidden state to send downstream.
    ``logits`` serves inference.
    """

    config: Qwen3DenseConfig
    sdpa: SdpaBackend
    stage: PipelineStageInfo = PipelineStageInfo()
    ce_chunk_size: "int | str" = "auto"
    act_sharding: Optional[NamedSharding] = None
    # KV-cache decode mode (loop/generate.py): 0 = training/eval path
    decode_max_length: int = 0
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32

    def setup(self) -> None:
        self.model = Qwen3DenseBackbone(
            config=self.config,
            sdpa=self.sdpa,
            stage=self.stage,
            act_sharding=self.act_sharding,
            decode_max_length=self.decode_max_length,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
        )
        if self.stage.is_last:
            self.lm_head = LanguageModellingHead(
                vocab_ranges=self.config.vocab_ranges,
                hidden_size=self.config.hidden_size,
                ce_chunk_size=self.ce_chunk_size,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
            )

    def __call__(
        self,
        x: Array,
        positions: Array,
        labels: Optional[Array] = None,
        mask: Optional[Array] = None,
    ) -> Array:
        h = self.model(x, positions, mask)
        if self.stage.is_last and labels is not None:
            return self.lm_head(h, labels)
        return h

    def logits(
        self, x: Array, positions: Array, mask: Optional[Array] = None
    ) -> Array:
        h = self.model(x, positions, mask)
        if not self.stage.is_last:
            return h
        return self.lm_head.logits(h)

    def logits_last(
        self, x: Array, positions: Array, mask: Optional[Array] = None
    ) -> Array:
        """Logits for the LAST position only ``[B, 1, V]`` — the prefill
        fast path (loop/generate.py): the backbone runs over the full
        prompt (writing caches in decode mode) but the LM head matmul
        covers one row instead of P."""
        h = self.model(x, positions, mask)
        if not self.stage.is_last:
            return h
        return self.lm_head.logits(h[:, -1:])


class Qwen3DenseForClassification(nn.Module):
    """Backbone + last-token classification head (reference model.py heads)."""

    config: Qwen3DenseConfig
    sdpa: SdpaBackend
    num_classes: int = 2
    stage: PipelineStageInfo = PipelineStageInfo()
    act_sharding: Optional[NamedSharding] = None
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(
        self,
        x: Array,
        positions: Array,
        pooling_mask: Optional[Array] = None,
        mask: Optional[Array] = None,
    ) -> Array:
        h = Qwen3DenseBackbone(
            config=self.config,
            sdpa=self.sdpa,
            stage=self.stage,
            act_sharding=self.act_sharding,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name="model",
        )(x, positions, mask)
        if not self.stage.is_last:
            return h
        if pooling_mask is None:
            pooled = h[:, -1]
        else:
            idx = jnp.maximum(pooling_mask.sum(axis=-1) - 1, 0)
            pooled = jnp.take_along_axis(h, idx[:, None, None], axis=1)[:, 0]
        return ClassificationHead(
            hidden_size=self.config.hidden_size,
            num_classes=self.num_classes,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
        )(pooled)


class Qwen3DenseForEmbedding(nn.Module):
    """Backbone + pooled L2-normalized embedding head."""

    config: Qwen3DenseConfig
    sdpa: SdpaBackend
    stage: PipelineStageInfo = PipelineStageInfo()
    act_sharding: Optional[NamedSharding] = None
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(
        self,
        x: Array,
        positions: Array,
        pooling_mask: Optional[Array] = None,
        mask: Optional[Array] = None,
    ) -> Array:
        h = Qwen3DenseBackbone(
            config=self.config,
            sdpa=self.sdpa,
            stage=self.stage,
            act_sharding=self.act_sharding,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name="model",
        )(x, positions, mask)
        if not self.stage.is_last:
            return h
        return EmbeddingHead()(h, pooling_mask)
