"""Bidirectional HuggingFace ⇄ d9d_tpu mappers for the Qwen3-Next hybrid
family (GDN linear-attention + gated attention + MoE with shared expert).

Beyond-reference capability: the reference ships no hybrid model family at
all (SURVEY §2.4 — Qwen3 dense + MoE only); transformers ≥4.57 ships
Qwen3Next, so the interop target is HF's layout directly:

- attention ``q_proj`` fuses query and output gate per head
  ([h, 2·dk] chunks) — split into our separate q/gate kernels;
- linear-attention ``in_proj_qkvz`` packs [q|k|v|z] per *key-head group*
  ([ng, dk|dk|r·dv|r·dv]) and ``in_proj_ba`` packs [b|a] per group —
  de-interleaved into our flat q|k|v packing, ``g_proj``, ``b_proj`` and
  the Mamba decay gate's projection;
- conv1d weights drop torch's depthwise middle axis;
- every norm except the GDN gated output norm is zero-centered on both
  sides, so weights transfer unchanged.
"""

import numpy as np

from d9d_tpu.model_state.mapper import (
    ModelStateMapper,
    ModelStateMapperParallel,
    ModelStateMapperRename,
    StateDict,
    StateGroup,
)
from d9d_tpu.models.qwen3.huggingface import (
    _ConcatRanges,
    _embed_head_from_hf_mappers,
    _StackExpertsTransposed,
    _TransposedRename,
    _UnstackExpertsTransposed,
)

_P = "params."


class _OneToOne(ModelStateMapper):
    """Base for single-input single-output array transforms."""

    def __init__(self, name_from: str, name_to: str):
        self._from = name_from
        self._to = name_to

    def state_dependency_groups(self) -> frozenset[StateGroup]:
        return frozenset(
            [
                StateGroup(
                    inputs=frozenset([self._from]),
                    outputs=frozenset([self._to]),
                )
            ]
        )


class _ConvSqueezeFromHF(_OneToOne):
    """torch depthwise Conv1d weight [C, 1, K] → ours [C, K]."""

    def apply(self, group: StateDict) -> StateDict:
        return {self._to: np.asarray(group[self._from])[:, 0, :]}


class _ConvUnsqueezeToHF(_OneToOne):
    def apply(self, group: StateDict) -> StateDict:
        return {self._to: np.asarray(group[self._from])[:, None, :]}


class _SplitColumns(ModelStateMapper):
    """Transpose a torch [out, in] weight to [in, out], then split the out
    dim into named column groups given by ``plan: [(target, idx_array)]``.
    The index arrays must partition range(out)."""

    def __init__(self, source: str, plan: list[tuple[str, np.ndarray]]):
        self._source = source
        self._plan = plan

    def state_dependency_groups(self) -> frozenset[StateGroup]:
        return frozenset(
            [
                StateGroup(
                    inputs=frozenset([self._source]),
                    outputs=frozenset(t for t, _ in self._plan),
                )
            ]
        )

    def apply(self, group: StateDict) -> StateDict:
        w = np.swapaxes(np.asarray(group[self._source]), 0, 1)  # [in, out]
        return {
            t: np.ascontiguousarray(w[:, idx]) for t, idx in self._plan
        }


class _MergeColumns(ModelStateMapper):
    """Inverse of _SplitColumns: scatter named column groups back into a
    single [out, in] torch weight."""

    def __init__(
        self, target: str, plan: list[tuple[str, np.ndarray]], out_dim: int
    ):
        self._target = target
        self._plan = plan
        self._out_dim = out_dim

    def state_dependency_groups(self) -> frozenset[StateGroup]:
        return frozenset(
            [
                StateGroup(
                    inputs=frozenset(s for s, _ in self._plan),
                    outputs=frozenset([self._target]),
                )
            ]
        )

    def apply(self, group: StateDict) -> StateDict:
        first = np.asarray(group[self._plan[0][0]])
        in_dim = first.shape[0]
        w = np.zeros((in_dim, self._out_dim), first.dtype)
        for src, idx in self._plan:
            w[:, idx] = np.asarray(group[src])
        return {self._target: np.ascontiguousarray(np.swapaxes(w, 0, 1))}


def _qkvz_plan(cfg) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Column indices of (q, k, v, z) inside HF's in_proj_qkvz out dim."""
    ng = cfg.gdn_qk_heads or cfg.num_kv_heads
    hv = cfg.gdn_v_heads or cfg.num_heads
    dk = cfg.gdn_head_qk_dim or cfg.head_dim
    dv = cfg.gdn_head_v_dim or cfg.head_dim
    r = hv // ng
    s = 2 * dk + 2 * r * dv
    q, k, v, z = [], [], [], []
    for i in range(ng):
        base = i * s
        q.extend(range(base, base + dk))
        k.extend(range(base + dk, base + 2 * dk))
        v.extend(range(base + 2 * dk, base + 2 * dk + r * dv))
        z.extend(range(base + 2 * dk + r * dv, base + s))
    return (np.array(q), np.array(k), np.array(v), np.array(z))


def _ba_plan(cfg) -> tuple[np.ndarray, np.ndarray]:
    ng = cfg.gdn_qk_heads or cfg.num_kv_heads
    hv = cfg.gdn_v_heads or cfg.num_heads
    r = hv // ng
    b, a = [], []
    for i in range(ng):
        base = i * 2 * r
        b.extend(range(base, base + r))
        a.extend(range(base + r, base + 2 * r))
    return np.array(b), np.array(a)


def _qgate_plan(cfg) -> tuple[np.ndarray, np.ndarray]:
    """(q, gate) column indices inside HF's fused attention q_proj."""
    h, d = cfg.num_heads, cfg.head_dim
    q, g = [], []
    for i in range(h):
        base = i * 2 * d
        q.extend(range(base, base + d))
        g.extend(range(base + d, base + 2 * d))
    return np.array(q), np.array(g)


def _linear_layer_from_hf(cfg, i: int) -> list[ModelStateMapper]:
    hf = f"model.layers.{i}.linear_attn"
    us = f"{_P}model.layers_{i}.linear_attn"
    qi, ki, vi, zi = _qkvz_plan(cfg)
    bi, ai = _ba_plan(cfg)
    qkv = np.concatenate([qi, ki, vi])
    return [
        _SplitColumns(
            f"{hf}.in_proj_qkvz.weight",
            [(f"{us}.qkv_proj.kernel", qkv), (f"{us}.g_proj.kernel", zi)],
        ),
        _SplitColumns(
            f"{hf}.in_proj_ba.weight",
            [
                (f"{us}.b_proj.kernel", bi),
                (f"{us}.decay_gate.proj.kernel", ai),
            ],
        ),
        _ConvSqueezeFromHF(
            f"{hf}.conv1d.weight", f"{us}.qkv_conv1d.weight"
        ),
        ModelStateMapperRename(f"{hf}.dt_bias", f"{us}.decay_gate.dt_bias"),
        ModelStateMapperRename(f"{hf}.A_log", f"{us}.decay_gate.A_log"),
        ModelStateMapperRename(f"{hf}.norm.weight", f"{us}.out_norm.weight"),
        _TransposedRename(f"{hf}.out_proj.weight", f"{us}.o_proj.kernel"),
    ]


def _linear_layer_to_hf(cfg, i: int) -> list[ModelStateMapper]:
    hf = f"model.layers.{i}.linear_attn"
    us = f"{_P}model.layers_{i}.linear_attn"
    qi, ki, vi, zi = _qkvz_plan(cfg)
    bi, ai = _ba_plan(cfg)
    qkv = np.concatenate([qi, ki, vi])
    return [
        _MergeColumns(
            f"{hf}.in_proj_qkvz.weight",
            [(f"{us}.qkv_proj.kernel", qkv), (f"{us}.g_proj.kernel", zi)],
            out_dim=len(qkv) + len(zi),
        ),
        _MergeColumns(
            f"{hf}.in_proj_ba.weight",
            [
                (f"{us}.b_proj.kernel", bi),
                (f"{us}.decay_gate.proj.kernel", ai),
            ],
            out_dim=len(bi) + len(ai),
        ),
        _ConvUnsqueezeToHF(
            f"{us}.qkv_conv1d.weight", f"{hf}.conv1d.weight"
        ),
        ModelStateMapperRename(f"{us}.decay_gate.dt_bias", f"{hf}.dt_bias"),
        ModelStateMapperRename(f"{us}.decay_gate.A_log", f"{hf}.A_log"),
        ModelStateMapperRename(f"{us}.out_norm.weight", f"{hf}.norm.weight"),
        _TransposedRename(f"{us}.o_proj.kernel", f"{hf}.out_proj.weight"),
    ]


def _attn_layer_pairs(cfg, i: int) -> list[tuple[str, str, bool]]:
    hf = f"model.layers.{i}.self_attn"
    us = f"{_P}model.layers_{i}.self_attn"
    return [
        (f"{hf}.k_proj.weight", f"{us}.k_proj.kernel", True),
        (f"{hf}.v_proj.weight", f"{us}.v_proj.kernel", True),
        (f"{hf}.o_proj.weight", f"{us}.o_proj.kernel", True),
        (f"{hf}.q_norm.weight", f"{us}.q_norm.weight", False),
        (f"{hf}.k_norm.weight", f"{us}.k_norm.weight", False),
    ]


def _moe_mlp_from_hf(cfg, i: int) -> list[ModelStateMapper]:
    hf = f"model.layers.{i}.mlp"
    us = f"{_P}model.layers_{i}.mlp"
    mappers: list[ModelStateMapper] = [
        _TransposedRename(f"{hf}.gate.weight", f"{us}.router.gate.kernel"),
    ]
    for proj in ("gate_proj", "up_proj", "down_proj"):
        mappers.append(
            _StackExpertsTransposed(
                [
                    f"{hf}.experts.{e}.{proj}.weight"
                    for e in range(cfg.num_experts)
                ],
                f"{us}.grouped_experts.{proj}",
            )
        )
    if cfg.shared_expert is not None:
        for proj in ("gate_proj", "up_proj", "down_proj"):
            mappers.append(
                _TransposedRename(
                    f"{hf}.shared_expert.{proj}.weight",
                    f"{us}.shared_expert_module.expert.{proj}.kernel",
                )
            )
        mappers.append(
            _TransposedRename(
                f"{hf}.shared_expert_gate.weight",
                f"{us}.shared_expert_module.gate.kernel",
            )
        )
    return mappers


def _moe_mlp_to_hf(cfg, i: int) -> list[ModelStateMapper]:
    hf = f"model.layers.{i}.mlp"
    us = f"{_P}model.layers_{i}.mlp"
    mappers: list[ModelStateMapper] = [
        _TransposedRename(f"{us}.router.gate.kernel", f"{hf}.gate.weight"),
    ]
    for proj in ("gate_proj", "up_proj", "down_proj"):
        mappers.append(
            _UnstackExpertsTransposed(
                f"{us}.grouped_experts.{proj}",
                [
                    f"{hf}.experts.{e}.{proj}.weight"
                    for e in range(cfg.num_experts)
                ],
            )
        )
    if cfg.shared_expert is not None:
        for proj in ("gate_proj", "up_proj", "down_proj"):
            mappers.append(
                _TransposedRename(
                    f"{us}.shared_expert_module.expert.{proj}.kernel",
                    f"{hf}.shared_expert.{proj}.weight",
                )
            )
        mappers.append(
            _TransposedRename(
                f"{us}.shared_expert_module.gate.kernel",
                f"{hf}.shared_expert_gate.weight",
            )
        )
    return mappers


def qwen3_next_from_hf_mapper(
    config,
    *,
    tie_word_embeddings: bool = False,
    layers: list[int] | None = None,
    include_embed: bool = True,
    include_head: bool = True,
) -> ModelStateMapper:
    """HF Qwen3Next checkpoint names → d9d_tpu hybrid Qwen3MoeCausalLM."""
    mappers = _embed_head_from_hf_mappers(
        config,
        tie_word_embeddings=tie_word_embeddings,
        include_embed=include_embed,
        include_head=include_head,
    )
    for i in layers if layers is not None else range(config.num_layers):
        us = f"{_P}model.layers_{i}"
        hf = f"model.layers.{i}"
        mappers.append(
            ModelStateMapperRename(
                f"{hf}.input_layernorm.weight", f"{us}.input_layernorm.weight"
            )
        )
        mappers.append(
            ModelStateMapperRename(
                f"{hf}.post_attention_layernorm.weight",
                f"{us}.post_attention_layernorm.weight",
            )
        )
        if i in config.linear_attention_layers:
            mappers += _linear_layer_from_hf(config, i)
        else:
            qi, gi = _qgate_plan(config)
            mappers.append(
                _SplitColumns(
                    f"{hf}.self_attn.q_proj.weight",
                    [
                        (f"{us}.self_attn.q_proj.kernel", qi),
                        (f"{us}.self_attn.gate_proj.kernel", gi),
                    ],
                )
            )
            for hf_name, our_name, transposed in _attn_layer_pairs(config, i):
                mappers.append(
                    _TransposedRename(hf_name, our_name)
                    if transposed
                    else ModelStateMapperRename(hf_name, our_name)
                )
        mappers += _moe_mlp_from_hf(config, i)
    return ModelStateMapperParallel(mappers)


def qwen3_next_to_hf_mapper(
    config,
    *,
    tie_word_embeddings: bool = False,
    layers: list[int] | None = None,
    include_embed: bool = True,
    include_head: bool = True,
) -> ModelStateMapper:
    """d9d_tpu hybrid Qwen3MoeCausalLM params → HF Qwen3Next names."""
    mappers: list[ModelStateMapper] = []
    if include_embed:
        mappers.append(
            _ConcatRanges(
                [
                    f"{_P}model.embed_tokens.embedding_{n}"
                    for n, _ in config.vocab_ranges
                ],
                "model.embed_tokens.weight",
            )
        )
    for i in layers if layers is not None else range(config.num_layers):
        us = f"{_P}model.layers_{i}"
        hf = f"model.layers.{i}"
        mappers.append(
            ModelStateMapperRename(
                f"{us}.input_layernorm.weight", f"{hf}.input_layernorm.weight"
            )
        )
        mappers.append(
            ModelStateMapperRename(
                f"{us}.post_attention_layernorm.weight",
                f"{hf}.post_attention_layernorm.weight",
            )
        )
        if i in config.linear_attention_layers:
            mappers += _linear_layer_to_hf(config, i)
        else:
            qi, gi = _qgate_plan(config)
            mappers.append(
                _MergeColumns(
                    f"{hf}.self_attn.q_proj.weight",
                    [
                        (f"{us}.self_attn.q_proj.kernel", qi),
                        (f"{us}.self_attn.gate_proj.kernel", gi),
                    ],
                    out_dim=len(qi) + len(gi),
                )
            )
            for hf_name, our_name, transposed in _attn_layer_pairs(config, i):
                mappers.append(
                    _TransposedRename(our_name, hf_name)
                    if transposed
                    else ModelStateMapperRename(our_name, hf_name)
                )
        mappers += _moe_mlp_to_hf(config, i)
    if include_head:
        mappers.append(
            ModelStateMapperRename(
                f"{_P}model.norm.weight", "model.norm.weight"
            )
        )
        if not tie_word_embeddings:
            mappers.append(
                _ConcatRanges(
                    [f"{_P}lm_head.head_{n}" for n, _ in config.vocab_ranges],
                    "lm_head.weight",
                )
            )
    return ModelStateMapperParallel(mappers)
