"""Qwen3-dense model family configuration.

Reference: d9d/module/model/qwen3_dense/params.py:90. Pure-static dataclass
(hashable) so it can live inside jitted closures and flax module attributes.
"""

import dataclasses

from d9d_tpu.ops import RopeScaling, RopeScalingNone


@dataclasses.dataclass(frozen=True)
class Qwen3DenseConfig:
    vocab_ranges: tuple[tuple[str, int], ...]
    hidden_size: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    intermediate_size: int
    rope_theta: float = 1_000_000.0
    rope_scaling: RopeScaling = RopeScalingNone()
    qk_norm: bool = True
    norm_eps: float = 1e-6
    window_size: int | None = None
    use_sinks: bool = False
    use_output_gate: bool = False
    # single matmul for q/k/v (runtime kernel concat; see
    # nn/attention.py fused_qkv — leave off for TP plans)
    fused_qkv: bool = False
    remat: bool = True
    # "full" recomputes everything in backward (minimum memory, ~8N HFU);
    # "dots_no_batch" saves matmul outputs with no batch dims (XLA's
    # checkpoint_dots_with_no_batch_dims policy) — fewer recomputed FLOPs
    # for more activation memory. Measured via bench.py on chip.
    remat_policy: str = "full"

    @property
    def vocab_size(self) -> int:
        return sum(s for _, s in self.vocab_ranges)

    @staticmethod
    def tiny(vocab_size: int = 256) -> "Qwen3DenseConfig":
        """2-layer CPU-runnable config (BASELINE.md config 1)."""
        return Qwen3DenseConfig(
            vocab_ranges=(("default", vocab_size),),
            hidden_size=64,
            num_layers=2,
            num_heads=4,
            num_kv_heads=2,
            head_dim=16,
            intermediate_size=128,
            remat=False,
        )

    @staticmethod
    def qwen3_8b(vocab_size: int = 151_936) -> "Qwen3DenseConfig":
        return Qwen3DenseConfig(
            vocab_ranges=(("default", vocab_size),),
            hidden_size=4096,
            num_layers=36,
            num_heads=32,
            num_kv_heads=8,
            head_dim=128,
            intermediate_size=12_288,
        )
