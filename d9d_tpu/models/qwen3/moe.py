"""Qwen3-MoE model family: stage-aware backbone + task heads.

Reference: d9d/module/model/qwen3_moe/model.py:29,221,322,425 and
params.py:4-93. Same structure as the dense family, with the FFN replaced
by an MoE layer (all layers sparse by default; ``mlp_only_layers`` keeps
specific layers dense, matching HF Qwen3MoE semantics).
"""

import dataclasses
from typing import Optional

import flax.linen as nn
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding

from d9d_tpu.core.types import Array
from d9d_tpu.nn.attention import GroupedQueryAttention
from d9d_tpu.nn.embedding import TokenEmbedding
from d9d_tpu.nn.heads import (
    ClassificationHead,
    EmbeddingHead,
    LanguageModellingHead,
)
from d9d_tpu.nn.mlp import SwiGLU
from d9d_tpu.nn.moe import MoELayer, SharedExpertParameters
from d9d_tpu.nn.norm import RMSNorm
from d9d_tpu.nn.sdpa.protocol import SdpaBackend
from d9d_tpu.ops import (
    RopeScaling,
    RopeScalingNone,
    compute_rope_frequencies,
    make_rope_cos_sin,
)
from d9d_tpu.telemetry import numerics
from d9d_tpu.pipelining import (
    PipelineStageInfo,
    distribute_layers_for_pipeline_stage,
)


@dataclasses.dataclass(frozen=True)
class MLAParameters:
    """Multi-head-latent attention geometry (DeepSeek-V2 family;
    nn/attention.py MultiHeadLatentAttention). When set on a config,
    every attention layer runs MLA instead of GQA and rope frequencies
    are computed over ``qk_rope_head_dim``."""

    kv_lora_rank: int
    qk_nope_head_dim: int
    qk_rope_head_dim: int
    v_head_dim: int
    q_lora_rank: Optional[int] = None
    # override the default d_qk**-0.5 (DeepSeek yarn mscale: the
    # checkpoint's softmax scale carries a yarn temperature factor)
    softmax_scale: Optional[float] = None


@dataclasses.dataclass(frozen=True)
class Qwen3MoeConfig:
    vocab_ranges: tuple[tuple[str, int], ...]
    hidden_size: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    moe_intermediate_size: int
    num_experts: int
    num_experts_per_tok: int
    # dense FFN width for layers listed in mlp_only_layers
    intermediate_size: int = 0
    mlp_only_layers: tuple[int, ...] = ()
    shared_expert: Optional[SharedExpertParameters] = None
    norm_topk_prob: bool = True
    rope_theta: float = 1_000_000.0
    rope_scaling: RopeScaling = RopeScalingNone()
    qk_norm: bool = True
    norm_eps: float = 1e-6
    remat: bool = True
    # see Qwen3DenseConfig.remat_policy
    remat_policy: str = "full"
    # Qwen3-Next attention features: sigmoid output gate on attention
    # layers, partial rotary (frequencies computed over the rotary dim),
    # zero-centered RMSNorm weights (scale = 1 + w) on every norm except
    # the GDN gated output norm
    use_output_gate: bool = False
    # single matmul for q/k/v (see nn/attention.py fused_qkv)
    fused_qkv: bool = False
    rope_fraction: float = 1.0
    zero_centered_norms: bool = False
    # mesh axes carrying expert parallelism; None = local experts
    ep_axes: Optional[tuple[str, ...]] = None
    # (batch_axes, seq_axes) of the residual activation layout; when set,
    # the EP flow shard_maps over this layout directly (no boundary
    # reshard) — see MoELayer.token_axes
    moe_token_axes: Optional[tuple[tuple[str, ...], tuple[str, ...]]] = None
    # Hybrid linear-attention layers (beyond-reference; Qwen3-Next-style
    # 3:1 GDN:attention stacks): listed layer indices swap GQA for a
    # GatedDeltaNet block. Geometry defaults derive from the attention
    # dims when the gdn_* fields are 0.
    linear_attention_layers: tuple[int, ...] = ()
    gdn_qk_heads: int = 0
    gdn_v_heads: int = 0
    gdn_head_qk_dim: int = 0
    gdn_head_v_dim: int = 0
    gdn_conv_size: int = 4
    # EP dispatch buffer sizing (see MoELayer.ep_capacity_factor): a factor
    # like 2.0 gives N·k/ep per-shard compute with deterministic drops;
    # None = dropless worst-case buffer
    ep_capacity_factor: Optional[float] = None
    # MLA attention on every (non-GDN) layer when set — the DeepSeek-V2
    # family rides this backbone (models/deepseek/)
    mla: Optional[MLAParameters] = None
    # DeepSeek routed_scaling_factor (routed experts' output only)
    routed_scaling_factor: float = 1.0
    # group-limited routing (DeepSeek group_limited_greedy; see
    # TopKRouter.n_group / topk_group); 1 = plain top-k
    router_n_group: int = 1
    router_topk_group: int = 1

    @property
    def vocab_size(self) -> int:
        return sum(s for _, s in self.vocab_ranges)

    @staticmethod
    def tiny(vocab_size: int = 256, ep_axes=None) -> "Qwen3MoeConfig":
        return Qwen3MoeConfig(
            vocab_ranges=(("default", vocab_size),),
            hidden_size=64,
            num_layers=2,
            num_heads=4,
            num_kv_heads=2,
            head_dim=16,
            moe_intermediate_size=64,
            num_experts=8,
            num_experts_per_tok=2,
            remat=False,
            ep_axes=ep_axes,
        )

    @staticmethod
    def hybrid_tiny(vocab_size: int = 256, ep_axes=None) -> "Qwen3MoeConfig":
        """CPU-runnable hybrid: GDN on 3 of 4 layers (Qwen3-Next 3:1 ratio)."""
        return Qwen3MoeConfig(
            vocab_ranges=(("default", vocab_size),),
            hidden_size=64,
            num_layers=4,
            num_heads=4,
            num_kv_heads=2,
            head_dim=16,
            moe_intermediate_size=64,
            num_experts=8,
            num_experts_per_tok=2,
            remat=False,
            ep_axes=ep_axes,
            linear_attention_layers=(0, 1, 2),
        )

    @staticmethod
    def qwen3_next_80b_a3b(vocab_size: int = 151_936, ep_axes=None) -> "Qwen3MoeConfig":
        """Qwen3-Next-80B-A3B geometry: 3:1 GDN:attention hybrid + MoE
        (beyond-reference flagship for the linear-attention family;
        BASELINE config 5). Matches HF transformers' Qwen3Next semantics:
        gated attention output, partial rotary (0.25, frequencies over the
        rotary dim), zero-centered norms, gated shared expert."""
        return Qwen3MoeConfig(
            vocab_ranges=(("default", vocab_size),),
            hidden_size=2048,
            num_layers=48,
            num_heads=16,
            num_kv_heads=2,
            head_dim=256,
            moe_intermediate_size=512,
            num_experts=512,
            num_experts_per_tok=10,
            shared_expert=SharedExpertParameters(
                intermediate_size=512, enable_gate=True
            ),
            ep_axes=ep_axes,
            linear_attention_layers=tuple(
                i for i in range(48) if i % 4 != 3
            ),
            gdn_qk_heads=16,
            gdn_v_heads=32,
            gdn_head_qk_dim=128,
            gdn_head_v_dim=128,
            use_output_gate=True,
            rope_fraction=0.25,
            zero_centered_norms=True,
            rope_theta=10_000_000.0,
        )

    @staticmethod
    def qwen3_30b_a3b(vocab_size: int = 151_936, ep_axes=None) -> "Qwen3MoeConfig":
        """Qwen3-30B-A3B geometry (flagship MoE, BASELINE config 3)."""
        return Qwen3MoeConfig(
            vocab_ranges=(("default", vocab_size),),
            hidden_size=2048,
            num_layers=48,
            num_heads=32,
            num_kv_heads=4,
            head_dim=128,
            moe_intermediate_size=768,
            num_experts=128,
            num_experts_per_tok=8,
            ep_axes=ep_axes,
        )


class Qwen3MoeDecoderLayer(nn.Module):
    config: Qwen3MoeConfig
    sdpa: SdpaBackend
    layer_idx: int
    # KV-cache / GDN-state decode mode (loop/generate.py); 0 = training
    decode_max_length: int = 0
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(
        self,
        x: Array,
        cos: Array,
        sin: Array,
        mask: Optional[Array] = None,
        padding_mask: Optional[Array] = None,
    ) -> Array:
        cfg = self.config
        zc = cfg.zero_centered_norms
        normed = RMSNorm(
            cfg.hidden_size, eps=cfg.norm_eps, zero_centered=zc,
            name="input_layernorm",
        )(x)
        if self.layer_idx in cfg.linear_attention_layers:
            from d9d_tpu.nn.linear_attention import GatedDeltaNet

            # GDN zeroes padded positions before the conv/recurrence (HF
            # Qwen3Next's apply_mask_to_padding_states); the sdpa-style
            # ``mask`` cannot express this, so padded batches must pass the
            # [B, T] ``padding_mask`` alongside it
            attn_out = GatedDeltaNet(
                hidden_size=cfg.hidden_size,
                num_qk_heads=cfg.gdn_qk_heads or cfg.num_kv_heads,
                num_v_heads=cfg.gdn_v_heads or cfg.num_heads,
                head_qk_dim=cfg.gdn_head_qk_dim or cfg.head_dim,
                head_v_dim=cfg.gdn_head_v_dim or cfg.head_dim,
                conv_size=cfg.gdn_conv_size,
                norm_eps=cfg.norm_eps,
                decode=self.decode_max_length > 0,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                name="linear_attn",
            )(normed, padding_mask)
        elif cfg.mla is not None:
            from d9d_tpu.nn.attention import MultiHeadLatentAttention

            attn_out = MultiHeadLatentAttention(
                hidden_size=cfg.hidden_size,
                num_heads=cfg.num_heads,
                qk_nope_head_dim=cfg.mla.qk_nope_head_dim,
                qk_rope_head_dim=cfg.mla.qk_rope_head_dim,
                v_head_dim=cfg.mla.v_head_dim,
                kv_lora_rank=cfg.mla.kv_lora_rank,
                q_lora_rank=cfg.mla.q_lora_rank,
                softmax_scale=cfg.mla.softmax_scale,
                sdpa=self.sdpa,
                norm_eps=cfg.norm_eps,
                decode_max_length=self.decode_max_length,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                name="self_attn",
            )(normed, cos, sin, mask)
        else:
            attn_out = GroupedQueryAttention(
                hidden_size=cfg.hidden_size,
                num_heads=cfg.num_heads,
                num_kv_heads=cfg.num_kv_heads,
                head_dim=cfg.head_dim,
                sdpa=self.sdpa,
                qk_norm=cfg.qk_norm,
                qk_norm_zero_centered=zc,
                use_output_gate=cfg.use_output_gate,
                fused_qkv=cfg.fused_qkv,
                rope_fraction=cfg.rope_fraction,
                decode_max_length=self.decode_max_length,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                name="self_attn",
            )(normed, cos, sin, mask)
        x = x + attn_out
        h = RMSNorm(
            cfg.hidden_size, eps=cfg.norm_eps, zero_centered=zc,
            name="post_attention_layernorm",
        )(x)
        if self.layer_idx in cfg.mlp_only_layers:
            mlp_out = SwiGLU(
                hidden_size=cfg.hidden_size,
                intermediate_size=cfg.intermediate_size,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                name="mlp",
            )(h)
        else:
            mlp_out = MoELayer(
                hidden_dim=cfg.hidden_size,
                intermediate_dim_grouped=cfg.moe_intermediate_size,
                num_grouped_experts=cfg.num_experts,
                top_k=cfg.num_experts_per_tok,
                router_renormalize_probabilities=cfg.norm_topk_prob,
                shared_expert=cfg.shared_expert,
                ep_axes=cfg.ep_axes,
                token_axes=cfg.moe_token_axes,
                ep_capacity_factor=cfg.ep_capacity_factor,
                routed_scaling=cfg.routed_scaling_factor,
                router_n_group=cfg.router_n_group,
                router_topk_group=cfg.router_topk_group,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                name="mlp",
            )(h)
        return x + mlp_out


class Qwen3MoeBackbone(nn.Module):
    config: Qwen3MoeConfig
    sdpa: SdpaBackend
    stage: PipelineStageInfo = PipelineStageInfo()
    # residual-stream [B, T, E] sharding pin — see Qwen3DenseBackbone
    act_sharding: Optional[NamedSharding] = None
    # KV-cache / GDN-state decode mode (loop/generate.py); 0 = training
    decode_max_length: int = 0
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32

    def _pin(self, x: Array) -> Array:
        if self.act_sharding is not None:
            return lax.with_sharding_constraint(x, self.act_sharding)
        return x

    @nn.compact
    def __call__(
        self,
        x: Array,
        positions: Array,
        mask: Optional[Array] = None,
        padding_mask: Optional[Array] = None,
    ) -> Array:
        cfg = self.config
        if self.stage.is_first:
            x = TokenEmbedding(
                vocab_ranges=cfg.vocab_ranges,
                hidden_size=cfg.hidden_size,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                name="embed_tokens",
            )(x)
        else:
            x = x.astype(self.dtype)
        x = self._pin(x)

        # partial rotary (rope_fraction < 1): frequencies are computed over
        # the rotary dim, not head_dim (NeoX/Qwen3-Next semantics). MLA
        # (DeepSeek) rotates only its decoupled rope sub-vector.
        rotary_dim = (
            cfg.mla.qk_rope_head_dim if cfg.mla is not None
            else int(cfg.head_dim * cfg.rope_fraction)
        )
        inv_freq, att_scale = compute_rope_frequencies(
            rotary_dim, cfg.rope_theta, cfg.rope_scaling
        )
        cos, sin = make_rope_cos_sin(positions, inv_freq, att_scale)

        layer_cls = Qwen3MoeDecoderLayer
        # remat is a backward-pass tool; decode is forward-only and its
        # mutable cache variables don't compose with nn.remat
        if cfg.remat and self.decode_max_length == 0:
            from d9d_tpu.models.qwen3.dense import _remat_policy

            layer_cls = nn.remat(
                Qwen3MoeDecoderLayer,
                prevent_cse=False,
                policy=_remat_policy(cfg.remat_policy),
            )

        for gid in distribute_layers_for_pipeline_stage(cfg.num_layers, self.stage):
            x = layer_cls(
                config=cfg,
                sdpa=self.sdpa,
                layer_idx=gid,
                decode_max_length=self.decode_max_length,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                name=f"layers_{gid}",
            )(x, cos, sin, mask, padding_mask)
            x = self._pin(x)
            # numerics plane (telemetry/numerics.py): tap each layer's
            # residual-stream output HERE — outside the (possible)
            # nn.remat boundary — named by the layer's module path.
            # A no-op unless a numerics-enabled train step is tracing.
            numerics.tap(f"layers_{gid}", x)

        if self.stage.is_last:
            x = RMSNorm(
                cfg.hidden_size, eps=cfg.norm_eps,
                zero_centered=cfg.zero_centered_norms, name="norm",
            )(x)
            numerics.tap("norm", x)
        return x


class Qwen3MoeCausalLM(nn.Module):
    """Backbone + fused-CE LM head (reference model.py:221)."""

    config: Qwen3MoeConfig
    sdpa: SdpaBackend
    stage: PipelineStageInfo = PipelineStageInfo()
    ce_chunk_size: "int | str" = "auto"
    act_sharding: Optional[NamedSharding] = None
    # KV-cache / GDN-state decode mode (loop/generate.py); 0 = training
    decode_max_length: int = 0
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32

    def setup(self) -> None:
        self.model = Qwen3MoeBackbone(
            config=self.config,
            sdpa=self.sdpa,
            stage=self.stage,
            act_sharding=self.act_sharding,
            decode_max_length=self.decode_max_length,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
        )
        if self.stage.is_last:
            self.lm_head = LanguageModellingHead(
                vocab_ranges=self.config.vocab_ranges,
                hidden_size=self.config.hidden_size,
                ce_chunk_size=self.ce_chunk_size,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
            )

    def __call__(
        self,
        x: Array,
        positions: Array,
        labels: Optional[Array] = None,
        mask: Optional[Array] = None,
        padding_mask: Optional[Array] = None,
    ) -> Array:
        h = self.model(x, positions, mask, padding_mask)
        if self.stage.is_last and labels is not None:
            return self.lm_head(h, labels)
        return h

    def logits(
        self,
        x: Array,
        positions: Array,
        mask: Optional[Array] = None,
        padding_mask: Optional[Array] = None,
    ) -> Array:
        h = self.model(x, positions, mask, padding_mask)
        if not self.stage.is_last:
            return h
        return self.lm_head.logits(h)

    def logits_last(
        self,
        x: Array,
        positions: Array,
        mask: Optional[Array] = None,
        padding_mask: Optional[Array] = None,
    ) -> Array:
        """Last-position logits ``[B, 1, V]`` — see the dense twin."""
        h = self.model(x, positions, mask, padding_mask)
        if not self.stage.is_last:
            return h
        return self.lm_head.logits(h[:, -1:])


class Qwen3MoeForClassification(nn.Module):
    """Backbone + last-token classification head (reference model.py:322)."""

    config: Qwen3MoeConfig
    sdpa: SdpaBackend
    num_classes: int = 2
    stage: PipelineStageInfo = PipelineStageInfo()
    act_sharding: Optional[NamedSharding] = None
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(
        self,
        x: Array,
        positions: Array,
        pooling_mask: Optional[Array] = None,
        mask: Optional[Array] = None,
        padding_mask: Optional[Array] = None,
    ) -> Array:
        h = Qwen3MoeBackbone(
            config=self.config,
            sdpa=self.sdpa,
            stage=self.stage,
            act_sharding=self.act_sharding,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name="model",
        )(x, positions, mask, padding_mask)
        if not self.stage.is_last:
            return h
        if pooling_mask is None:
            pooled = h[:, -1]
        else:
            idx = jnp.maximum(pooling_mask.sum(axis=-1) - 1, 0)
            pooled = jnp.take_along_axis(h, idx[:, None, None], axis=1)[:, 0]
        return ClassificationHead(
            hidden_size=self.config.hidden_size,
            num_classes=self.num_classes,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
        )(pooled)


class Qwen3MoeForEmbedding(nn.Module):
    """Backbone + pooled L2-normalized embedding head (reference model.py:425)."""

    config: Qwen3MoeConfig
    sdpa: SdpaBackend
    stage: PipelineStageInfo = PipelineStageInfo()
    act_sharding: Optional[NamedSharding] = None
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(
        self,
        x: Array,
        positions: Array,
        pooling_mask: Optional[Array] = None,
        mask: Optional[Array] = None,
        padding_mask: Optional[Array] = None,
    ) -> Array:
        h = Qwen3MoeBackbone(
            config=self.config,
            sdpa=self.sdpa,
            stage=self.stage,
            act_sharding=self.act_sharding,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name="model",
        )(x, positions, mask, padding_mask)
        if not self.stage.is_last:
            return h
        return EmbeddingHead()(h, pooling_mask)
