"""Model families: Qwen3 (dense / MoE / Next-hybrid — reference parity),
Llama-3 (beyond-reference, BASELINE config 4), and DeepSeek-V2
(beyond-reference: MLA latent attention + shared-expert MoE)."""

from d9d_tpu.models import deepseek, llama, qwen3

__all__ = ["deepseek", "llama", "qwen3"]
