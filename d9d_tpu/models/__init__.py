"""Model families: Qwen3 (dense / MoE / Next-hybrid — reference parity)
and Llama-3 (beyond-reference, BASELINE config 4)."""

from d9d_tpu.models import llama, qwen3

__all__ = ["llama", "qwen3"]
