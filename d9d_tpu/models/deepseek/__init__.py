"""DeepSeek-V2 model family: MLA attention + shared-expert MoE.

Beyond-reference family (the reference ships only Qwen3 models,
d9d/module/model/): DeepSeek-V2's decoder is the Qwen3-MoE stack with
MultiHeadLatentAttention in place of GQA (``Qwen3MoeConfig.mla``),
dense first-k layers (``mlp_only_layers`` = HF
``first_k_dense_replace``), ungated shared experts
(``SharedExpertParameters(enable_gate=False)``, width =
``n_shared_experts * moe_intermediate_size``), and the
``routed_scaling_factor`` on the routed experts' output — so
sharding plans, pipelining stages, PEFT, generation (latent-cache
decode incl. the absorbed rank-space form) and serving
(ContinuousBatcher / speculative_generate) all apply unchanged.

Checkpoint-fidelity status vs transformers ``DeepseekV2ForCausalLM``:
router semantics match the HF configs (``norm_topk_prob=False`` raw
softmax weights; the 236B preset's ``group_limited_greedy`` routing via
``router_n_group/topk_group``), the yarn long-context scaling and its
mscale attention temperature are configured per the published configs
(``_yarn_mscale``), and the parameter LAYOUT maps 1:1 onto the MLA/MoE
blocks — but no HF weight mapper or logits-parity test exists yet, so
treat checkpoint loading as future work (the Qwen3/Llama/Next families
are the logits-parity-tested interop surface).
"""

from d9d_tpu.models.qwen3.moe import (
    MLAParameters,
    Qwen3MoeBackbone as DeepseekBackbone,
    Qwen3MoeCausalLM as DeepseekCausalLM,
    Qwen3MoeConfig,
)
from d9d_tpu.nn.moe import SharedExpertParameters
from d9d_tpu.ops import RopeScalingYarn

DeepseekConfig = Qwen3MoeConfig  # same static surface; mla set


def _yarn_mscale(factor: float, mscale: float) -> float:
    """DeepSeek yarn_get_mscale: the attention-temperature term the
    checkpoints fold into the softmax scale (mscale == mscale_all_dim
    in both published configs, so cos/sin stay unscaled and the scale
    adjustment is mscale(factor)**2 on d_qk**-0.5)."""
    import math

    return 0.1 * mscale * math.log(factor) + 1.0 if factor > 1 else 1.0


def _deepseek_yarn() -> RopeScalingYarn:
    """The yarn scaling both published DeepSeek-V2 configs ship
    (factor 40 over a 4096 original context; attention_factor 1.0
    because the temperature rides the softmax scale instead)."""
    return RopeScalingYarn(
        factor=40.0,
        original_max_position=4096,
        beta_fast=32.0,
        beta_slow=1.0,
        attention_factor=1.0,
    )


def deepseek_v2_tiny(vocab_size: int = 256) -> Qwen3MoeConfig:
    """CPU-runnable DeepSeek-V2-shaped config (tests / smoke): MLA on
    every layer, first layer dense, 1 ungated shared expert."""
    return Qwen3MoeConfig(
        vocab_ranges=(("default", vocab_size),),
        hidden_size=64,
        num_layers=2,
        num_heads=4,
        num_kv_heads=4,  # unused by MLA; kept for config invariants
        head_dim=16,
        moe_intermediate_size=32,
        num_experts=8,
        num_experts_per_tok=2,
        intermediate_size=128,
        mlp_only_layers=(0,),
        shared_expert=SharedExpertParameters(
            intermediate_size=32, enable_gate=False
        ),
        mla=MLAParameters(
            kv_lora_rank=32,
            qk_nope_head_dim=16,
            qk_rope_head_dim=8,
            v_head_dim=16,
            q_lora_rank=None,
        ),
        routed_scaling_factor=1.0,
        norm_topk_prob=False,
        qk_norm=False,
        rope_theta=10_000.0,
        remat=False,
    )


def deepseek_v2_lite(vocab_size: int = 102_400) -> Qwen3MoeConfig:
    """DeepSeek-V2-Lite geometry (15.7B total / 2.4B active): 27 layers,
    MLA with rank-512 latents and no q compression, 64 routed + 2
    shared experts, first layer dense."""
    return Qwen3MoeConfig(
        vocab_ranges=(("default", vocab_size),),
        hidden_size=2048,
        num_layers=27,
        num_heads=16,
        num_kv_heads=16,
        head_dim=128,
        moe_intermediate_size=1408,
        num_experts=64,
        num_experts_per_tok=6,
        intermediate_size=10_944,
        mlp_only_layers=(0,),
        shared_expert=SharedExpertParameters(
            intermediate_size=2 * 1408, enable_gate=False
        ),
        mla=MLAParameters(
            kv_lora_rank=512,
            qk_nope_head_dim=128,
            qk_rope_head_dim=64,
            v_head_dim=128,
            q_lora_rank=None,
            softmax_scale=(128 + 64) ** -0.5
            * _yarn_mscale(40.0, 0.707) ** 2,
        ),
        routed_scaling_factor=1.0,
        norm_topk_prob=False,
        qk_norm=False,
        rope_theta=10_000.0,
        rope_scaling=_deepseek_yarn(),
    )


def deepseek_v2(vocab_size: int = 102_400) -> Qwen3MoeConfig:
    """DeepSeek-V2 geometry (236B total / 21B active): 60 layers, MLA
    with q compression (rank 1536), 160 routed + 2 shared experts,
    routed output scaled 16x."""
    return Qwen3MoeConfig(
        vocab_ranges=(("default", vocab_size),),
        hidden_size=5120,
        num_layers=60,
        num_heads=128,
        num_kv_heads=128,
        head_dim=128,
        moe_intermediate_size=1536,
        num_experts=160,
        num_experts_per_tok=6,
        intermediate_size=12_288,
        mlp_only_layers=(0,),
        shared_expert=SharedExpertParameters(
            intermediate_size=2 * 1536, enable_gate=False
        ),
        mla=MLAParameters(
            kv_lora_rank=512,
            qk_nope_head_dim=128,
            qk_rope_head_dim=64,
            v_head_dim=128,
            q_lora_rank=1536,
            softmax_scale=(128 + 64) ** -0.5
            * _yarn_mscale(40.0, 0.707) ** 2,
        ),
        routed_scaling_factor=16.0,
        norm_topk_prob=False,
        router_n_group=8,
        router_topk_group=3,
        qk_norm=False,
        rope_theta=10_000.0,
        rope_scaling=_deepseek_yarn(),
    )
