from d9d_tpu.dataset.padding import (
    PaddingSide1D,
    TokenPoolingType,
    pad_stack_1d,
    token_pooling_mask_from_attention_mask,
)
from d9d_tpu.dataset.sharded import (
    BufferSortedDataset,
    Dataset,
    DatasetImplementingSortKeyProtocol,
    ShardIndexingMode,
    ShardedDataset,
    shard_dataset_data_parallel,
)

__all__ = [
    "BufferSortedDataset",
    "Dataset",
    "DatasetImplementingSortKeyProtocol",
    "PaddingSide1D",
    "ShardIndexingMode",
    "ShardedDataset",
    "TokenPoolingType",
    "pad_stack_1d",
    "shard_dataset_data_parallel",
    "token_pooling_mask_from_attention_mask",
]
