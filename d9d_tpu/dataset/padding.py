"""Batch padding/stacking + pooling-mask utilities.

Parity: reference d9d/dataset/padding.py (pad_stack_1d with left/right side
and pad-to-multiple) and d9d/dataset/pooling.py
(token_pooling_mask_from_attention_mask: first/last/all). numpy-native:
collation happens on host before device_put; pad_to_multiple_of matters
doubly on TPU, where stable shapes avoid recompilation.
"""

from collections.abc import Sequence
from enum import Enum

import numpy as np


class PaddingSide1D(str, Enum):
    left = "left"
    right = "right"


def pad_stack_1d(
    items: Sequence[np.ndarray],
    pad_value: int,
    padding_side: PaddingSide1D = PaddingSide1D.right,
    pad_to_multiple_of: int | None = None,
) -> np.ndarray:
    """Stack 1D arrays into [batch, max_len], padding to the longest
    (optionally rounded up to a multiple)."""
    if not items:
        raise ValueError("Cannot stack 0 items")
    if pad_to_multiple_of is not None and pad_to_multiple_of <= 0:
        raise ValueError("pad_to_multiple_of should be > 0")

    max_len = max(x.shape[0] for x in items)
    if pad_to_multiple_of is not None:
        remainder = max_len % pad_to_multiple_of
        if remainder != 0:
            max_len += pad_to_multiple_of - remainder

    out = np.full((len(items), max_len), pad_value, dtype=np.asarray(items[0]).dtype)
    for i, x in enumerate(items):
        x = np.asarray(x)
        if padding_side == PaddingSide1D.right:
            out[i, : x.shape[0]] = x
        elif padding_side == PaddingSide1D.left:
            out[i, max_len - x.shape[0] :] = x
        else:
            raise ValueError("Unknown padding side")
    return out


class TokenPoolingType(str, Enum):
    first = "first"
    last = "last"
    all = "all"


def token_pooling_mask_from_attention_mask(
    attention_mask: np.ndarray, pooling_type: TokenPoolingType
) -> np.ndarray:
    """Binary [B, T] mask selecting tokens to pool (CLS / last non-pad / all)."""
    attention_mask = np.asarray(attention_mask)
    match pooling_type:
        case TokenPoolingType.first:
            mask = np.zeros_like(attention_mask, dtype=np.int64)
            mask[:, 0] = 1
            return mask
        case TokenPoolingType.last:
            batch_indices = np.arange(attention_mask.shape[0])
            last_token = attention_mask.sum(axis=1) - 1
            mask = np.zeros_like(attention_mask, dtype=np.int64)
            mask[batch_indices, last_token] = 1
            return mask
        case TokenPoolingType.all:
            return attention_mask.astype(np.int64)
    raise ValueError(f"Unknown pooling type: {pooling_type}")
