"""Data-parallel dataset sharding.

Parity target: reference d9d/dataset/sharded.py:38 (ShardedDataset with
sequential/chunked indexing and pad-to-equal-length) and
d9d/dataset/buffer_sorted.py:38 (buffered length sorting). TPU-native note:
under single-controller JAX each *process* feeds its addressable slice of
the global batch (``jax.make_array_from_process_local_data``), so the
natural shard axis is the process, not the per-device dp rank;
``shard_dataset_data_parallel`` derives (total, current) from
``jax.process_{count,index}``.
"""

import base64
import pickle
import random
from enum import Enum
from typing import Any, Protocol, Sized, TypeVar

import jax

_T_co = TypeVar("_T_co", covariant=True)


class Dataset(Protocol[_T_co]):
    def __len__(self) -> int:
        ...

    def __getitem__(self, index: int) -> _T_co:
        ...


class ShardIndexingMode(str, Enum):
    """sequential = round-robin across shards; chunked = contiguous blocks."""

    sequential = "sequential"
    chunked = "chunked"


class ShardedDataset:
    """A view onto one shard of an underlying dataset.

    With ``pad_to_equal_size_across_shards`` every shard reports the
    ceiling length and out-of-range reads clamp to the dataset's final
    element — data-parallel groups must never diverge in step count
    (reference rationale, sharded.py:44).
    """

    def __init__(
        self,
        dataset: Dataset[_T_co],
        total_shards: int,
        current_shard: int,
        indexing_mode: ShardIndexingMode = ShardIndexingMode.sequential,
        pad_to_equal_size_across_shards: bool = True,
    ):
        if not isinstance(dataset, Sized):
            raise ValueError(
                "sharding needs a sized dataset (no __len__ found)"
            )
        if not 0 <= current_shard < total_shards:
            raise ValueError(
                f"shard index {current_shard} invalid for a "
                f"{total_shards}-way split"
            )
        self._dataset = dataset
        self._total_shards = total_shards
        self._current_shard = current_shard
        self._indexing_mode = indexing_mode
        self._pad = pad_to_equal_size_across_shards

    # Layout: sequential interleaves shards with stride = total_shards;
    # chunked hands each shard one contiguous block of ceil(n/shards).

    @property
    def _padded_len(self) -> int:
        return -(-len(self._dataset) // self._total_shards)

    @property
    def _true_len(self) -> int:
        n, k, me = len(self._dataset), self._total_shards, self._current_shard
        if self._indexing_mode is ShardIndexingMode.sequential:
            return n // k + (1 if me < n % k else 0)
        start = self._padded_len * me
        return min(self._padded_len, max(0, n - start))

    def _global_index(self, index: int) -> int:
        if self._indexing_mode is ShardIndexingMode.sequential:
            return index * self._total_shards + self._current_shard
        return self._padded_len * self._current_shard + index

    def __len__(self) -> int:
        return self._padded_len if self._pad else self._true_len

    def __getitem__(self, index: int) -> _T_co:
        if not 0 <= index < len(self):
            raise IndexError(index)
        # padding reads (only possible with pad enabled) clamp to the end
        g = min(self._global_index(index), len(self._dataset) - 1)
        return self._dataset[g]

    def state_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "total_shards": self._total_shards,
            "current_shard": self._current_shard,
        }
        if hasattr(self._dataset, "state_dict"):
            out["dataset"] = self._dataset.state_dict()
        return out

    def load_state_dict(self, state_dict: dict[str, Any]) -> None:
        if state_dict["total_shards"] != self._total_shards:
            raise ValueError(
                f"cannot restore a {state_dict['total_shards']}-way shard "
                f"state into a {self._total_shards}-way split"
            )
        self._current_shard = state_dict["current_shard"]
        if hasattr(self._dataset, "load_state_dict"):
            self._dataset.load_state_dict(state_dict["dataset"])


def shard_dataset_data_parallel(
    dataset: Dataset[_T_co],
    indexing_mode: ShardIndexingMode = ShardIndexingMode.sequential,
    pad_to_equal_size_across_shards: bool = True,
) -> ShardedDataset:
    """Shard over JAX processes (each feeds its local devices' batch slice)."""
    return ShardedDataset(
        dataset=dataset,
        total_shards=jax.process_count(),
        current_shard=jax.process_index(),
        indexing_mode=indexing_mode,
        pad_to_equal_size_across_shards=pad_to_equal_size_across_shards,
    )


class DatasetImplementingSortKeyProtocol(Protocol[_T_co]):
    """Dataset that can expose a sort key (e.g. length) without loading items."""

    def __len__(self) -> int:
        ...

    def sort_key(self, index: int) -> Any:
        ...

    def __getitem__(self, item: int) -> _T_co:
        ...


class BufferSortedDataset:
    """Buffered length-sorting with pack-level + intra-pack shuffling.

    Groups similar-length items (minimizing padding waste) while keeping
    stochasticity: materialize a window of ``buffer_size`` indices, order
    it by (sort_key, random jitter), cut into ``pack_size`` packs, then
    shuffle the packs and the items inside each pack.
    """

    def __init__(
        self,
        base_dataset: DatasetImplementingSortKeyProtocol[_T_co],
        buffer_size: int,
        pack_size: int,
        init_seed: int | None = None,
    ):
        self._base = base_dataset
        self._window = buffer_size
        self._pack = pack_size
        self._rng = random.Random(
            init_seed ^ 0x105E7 if init_seed is not None else None
        )
        self._order: list[int] = []  # global indices, current window only
        self._window_id = -1

    def _fill_window(self, window_id: int) -> None:
        lo = window_id * self._window
        hi = min(lo + self._window, len(self._base))
        decorated = sorted(
            (self._base.sort_key(g), self._rng.random(), g)
            for g in range(lo, hi)
        )
        ranked = [g for _, _, g in decorated]
        packs = [
            ranked[i : i + self._pack]
            for i in range(0, len(ranked), self._pack)
        ]
        self._rng.shuffle(packs)
        for pack in packs:
            self._rng.shuffle(pack)
        self._order = [g for pack in packs for g in pack]
        self._window_id = window_id

    def __getitem__(self, index: int) -> _T_co:
        window_id, offset = divmod(index, self._window)
        if self._window_id != window_id:
            self._fill_window(window_id)
        return self._base[self._order[offset]]

    def __len__(self) -> int:
        return len(self._base)

    def state_dict(self) -> dict[str, Any]:
        # base64-wrap the pickled RNG state: loader state rides the job
        # checkpoint's JSON meta item, which cannot carry raw bytes
        out: dict[str, Any] = {
            "rng": base64.b64encode(pickle.dumps(self._rng.getstate())).decode(),
            "window_id": self._window_id,
            "order": self._order,
        }
        if hasattr(self._base, "state_dict"):
            out["base_dataset"] = self._base.state_dict()
        return out

    def load_state_dict(self, state_dict: dict[str, Any]) -> None:
        self._rng.setstate(pickle.loads(base64.b64decode(state_dict["rng"])))
        self._window_id = state_dict["window_id"]
        self._order = list(state_dict["order"])
        if hasattr(self._base, "load_state_dict"):
            self._base.load_state_dict(state_dict["base_dataset"])
