"""Data-parallel dataset sharding.

Parity: reference d9d/dataset/sharded.py:38 (ShardedDataset with
sequential/chunked indexing and pad-to-equal-length) and
shard_dataset_data_parallel. TPU-native note: under single-controller JAX,
each *process* feeds its addressable slice of the global batch
(``jax.make_array_from_process_local_data``), so the natural shard axis is
the process, not the per-device dp rank; ``shard_dataset_data_parallel``
derives (total, current) from ``jax.process_{count,index}``.
"""

import base64
import math
import pickle
from enum import Enum
from typing import Any, Protocol, Sized, TypeVar

import jax

_T_co = TypeVar("_T_co", covariant=True)


class Dataset(Protocol[_T_co]):
    def __len__(self) -> int:
        ...

    def __getitem__(self, index: int) -> _T_co:
        ...


class ShardIndexingMode(str, Enum):
    """sequential = round-robin across shards; chunked = contiguous blocks."""

    sequential = "sequential"
    chunked = "chunked"


class ShardedDataset:
    """A view onto one shard of an underlying dataset.

    With ``pad_to_equal_size_across_shards`` every shard reports the ceiling
    length and out-of-range reads clamp to the last element — required so
    data-parallel groups never diverge in step count (reference rationale,
    sharded.py:44).
    """

    def __init__(
        self,
        dataset: Dataset[_T_co],
        total_shards: int,
        current_shard: int,
        indexing_mode: ShardIndexingMode = ShardIndexingMode.sequential,
        pad_to_equal_size_across_shards: bool = True,
    ):
        if not isinstance(dataset, Sized):
            raise ValueError("Dataset should implement __len__ method")
        if not 0 <= current_shard < total_shards:
            raise ValueError(
                f"current_shard {current_shard} out of range for "
                f"{total_shards} shards"
            )
        self._dataset = dataset
        self._total_shards = total_shards
        self._current_shard = current_shard
        self._indexing_mode = indexing_mode
        self._pad = pad_to_equal_size_across_shards

    def _base_index_unsafe(self, index: int) -> int:
        match self._indexing_mode:
            case ShardIndexingMode.sequential:
                return index * self._total_shards + self._current_shard
            case ShardIndexingMode.chunked:
                ceil_len = math.ceil(len(self._dataset) / self._total_shards)
                return ceil_len * self._current_shard + index
        raise ValueError(f"Unknown shard indexing mode: {self._indexing_mode}")

    def __getitem__(self, index: int) -> _T_co:
        if index < 0 or index >= len(self):
            raise IndexError(index)
        base_index = self._base_index_unsafe(index)
        if base_index >= len(self._dataset):
            base_index = len(self._dataset) - 1
        return self._dataset[base_index]

    def __len__(self) -> int:
        n = len(self._dataset)
        ceil_len = math.ceil(n / self._total_shards)
        if self._pad:
            return ceil_len
        remainder = n % self._total_shards
        match self._indexing_mode:
            case ShardIndexingMode.sequential:
                full = n // self._total_shards
                return full + 1 if self._current_shard < remainder else full
            case ShardIndexingMode.chunked:
                # actual items in [ceil_len*shard, min(n, ceil_len*(shard+1)))
                start = ceil_len * self._current_shard
                return max(0, min(n - start, ceil_len))
        raise ValueError(f"Unknown ShardIndexingMode: {self._indexing_mode}")

    def state_dict(self) -> dict[str, Any]:
        dct: dict[str, Any] = {
            "total_shards": self._total_shards,
            "current_shard": self._current_shard,
        }
        if hasattr(self._dataset, "state_dict"):
            dct["dataset"] = self._dataset.state_dict()
        return dct

    def load_state_dict(self, state_dict: dict[str, Any]) -> None:
        if state_dict["total_shards"] != self._total_shards:
            raise ValueError("Shard count mismatch")
        self._current_shard = state_dict["current_shard"]
        if hasattr(self._dataset, "load_state_dict"):
            self._dataset.load_state_dict(state_dict["dataset"])


def shard_dataset_data_parallel(
    dataset: Dataset[_T_co],
    indexing_mode: ShardIndexingMode = ShardIndexingMode.sequential,
    pad_to_equal_size_across_shards: bool = True,
) -> ShardedDataset:
    """Shard over JAX processes (each feeds its local devices' batch slice)."""
    return ShardedDataset(
        dataset=dataset,
        total_shards=jax.process_count(),
        current_shard=jax.process_index(),
        indexing_mode=indexing_mode,
        pad_to_equal_size_across_shards=pad_to_equal_size_across_shards,
    )


class DatasetImplementingSortKeyProtocol(Protocol[_T_co]):
    """Dataset that can expose a sort key (e.g. length) without loading items."""

    def __len__(self) -> int:
        ...

    def sort_key(self, index: int) -> Any:
        ...

    def __getitem__(self, item: int) -> _T_co:
        ...


class BufferSortedDataset:
    """Buffered length-sorting with pack-level + intra-pack shuffling.

    Parity: reference d9d/dataset/buffer_sorted.py:38. Groups similar-length
    items (minimizing padding) while keeping stochasticity: take a buffer of
    ``buffer_size`` indices, sort by (sort_key, random tiebreak), cut into
    ``pack_size`` packs, shuffle packs, shuffle within packs.
    """

    def __init__(
        self,
        base_dataset: DatasetImplementingSortKeyProtocol[_T_co],
        buffer_size: int,
        pack_size: int,
        init_seed: int | None = None,
    ):
        import random

        self._base_dataset = base_dataset
        self._buffer_size = buffer_size
        self._pack_size = pack_size
        self._rng = random.Random(
            init_seed ^ 0x105E7 if init_seed is not None else None
        )
        self._buffer_indices: list[int] = []
        self._buffer_idx: int = -1

    def _update_buffer_idx(self, buffer_idx: int) -> None:
        select_start = buffer_idx * self._buffer_size
        select_end = min(
            (buffer_idx + 1) * self._buffer_size, len(self._base_dataset)
        )
        base_idx = list(range(select_start, select_end))
        sort_keys = [
            (self._base_dataset.sort_key(idx), self._rng.random())
            for idx in base_idx
        ]
        local_idx = sorted(range(len(base_idx)), key=lambda i: sort_keys[i])
        packs = [
            local_idx[i : i + self._pack_size]
            for i in range(0, len(local_idx), self._pack_size)
        ]
        self._rng.shuffle(packs)
        for pack in packs:
            self._rng.shuffle(pack)
        flat = [y for pack in packs for y in pack]
        self._buffer_indices = [base_idx[i] for i in flat]
        self._buffer_idx = buffer_idx

    def __getitem__(self, index: int) -> _T_co:
        needs = index // self._buffer_size
        if self._buffer_idx != needs:
            self._update_buffer_idx(needs)
        return self._base_dataset[self._buffer_indices[index % self._buffer_size]]

    def __len__(self) -> int:
        return len(self._base_dataset)

    def state_dict(self) -> dict[str, Any]:
        # base64-wrap the pickled RNG state: loader state rides the job
        # checkpoint's JSON meta item, which cannot carry raw bytes
        ret: dict[str, Any] = {
            "seed": base64.b64encode(pickle.dumps(self._rng.getstate())).decode(),
            "buffer_idx": self._buffer_idx,
            "buffer_indices": self._buffer_indices,
        }
        if hasattr(self._base_dataset, "state_dict"):
            ret["base_dataset"] = self._base_dataset.state_dict()
        return ret

    def load_state_dict(self, state_dict: dict[str, Any]) -> None:
        self._rng.setstate(pickle.loads(base64.b64decode(state_dict["seed"])))
        self._buffer_idx = state_dict["buffer_idx"]
        self._buffer_indices = state_dict["buffer_indices"]
        if hasattr(self._base_dataset, "load_state_dict"):
            self._base_dataset.load_state_dict(state_dict["base_dataset"])
