"""Inference loop: forward-only mirror of the Trainer.

Reference: d9d/loop/run/inference.py:55,176 (InferenceConfigurator/
Inference) + loop/control/task.py:262 (InferenceTask) + the
InferenceProcessor path (component/pipeline_result_processing.py:79).
The jitted forward scans microbatches exactly like the train step; the
task's ``process_outputs`` runs host-side per batch (generation decode,
metric accumulation, writing predictions...).
"""

import abc
import logging
import time
from typing import Any

import flax.linen as nn
import jax
import numpy as np
from jax import lax

from d9d_tpu.core.mesh import MeshContext
from d9d_tpu.core.types import Array, PyTree
from d9d_tpu.loop import event as ev
from d9d_tpu.loop.components.batch_staging import (
    make_batch_stager,
    split_microbatches,
)
from d9d_tpu.loop.config import InferenceConfig
from d9d_tpu.loop.control.providers import DatasetProvider, ModelProvider
from d9d_tpu.loop.event import EventBus
from d9d_tpu.loop.model_factory import init_sharded_params
from d9d_tpu.pipelining import PipelineStageInfo
from d9d_tpu.telemetry import tracked_jit

logger = logging.getLogger("d9d_tpu.inference")


class InferenceTask(abc.ABC):
    """What to compute per batch (reference loop/control/task.py:262)."""

    @abc.abstractmethod
    def prepare_batch(self, batch: PyTree) -> PyTree:
        """Host-side: raw loader batch → device-ready arrays."""

    @abc.abstractmethod
    def forward_fn(
        self, module: nn.Module, params: PyTree, microbatch: PyTree, rng: Array
    ) -> PyTree:
        """Pure, runs under jit → output pytree (stacked over microbatches)."""

    @abc.abstractmethod
    def process_outputs(self, outputs: PyTree) -> Any:
        """Host-side, per batch: consume forward outputs (already on host)."""


class PipelineInferenceTask(InferenceTask):
    """An InferenceTask that can also drive a forward-only pipeline
    program (reference loop/run/inference.py:55,176 wiring the inference
    schedule from pipelining/factory/config.py:6-78).

    Mirrors PipelineTrainTask's stage decomposition, with
    ``last_stage_outputs`` in place of the loss: the executor returns its
    value per microbatch and the loop hands the host copy to
    ``process_outputs``.
    """

    @abc.abstractmethod
    def sample_microbatch(self, microbatch_size: int, seq_len: int) -> PyTree:
        """Zero-filled microbatch matching ``prepare_batch``'s output."""

    @abc.abstractmethod
    def split_microbatch(
        self, microbatch: PyTree
    ) -> tuple[PyTree, PyTree, PyTree]:
        """→ (first_stage_carry, per_stage_kwargs, last_stage_state)."""

    @abc.abstractmethod
    def stage_forward(
        self, module: nn.Module, params: PyTree, carry: PyTree, kwargs: PyTree
    ) -> PyTree:
        """Non-last stage: carry in → carry out."""

    @abc.abstractmethod
    def last_stage_outputs(
        self,
        module: nn.Module,
        params: PyTree,
        carry: PyTree,
        kwargs: PyTree,
        state: PyTree,
    ) -> PyTree:
        """Last stage: → output pytree for this microbatch."""

    @abc.abstractmethod
    def stage_init(
        self,
        module: nn.Module,
        rng: Array,
        carry: PyTree,
        kwargs: PyTree,
        state: PyTree,
        is_last: bool,
    ) -> PyTree:
        """Initialize one stage's variables."""


class Inference:
    """Forward-only runner.

    ``params`` is normally handed over from a Trainer (colocated eval) or
    loaded via model_state; if omitted, fresh initialization is used.
    """

    def __init__(
        self,
        *,
        ctx: MeshContext,
        config: InferenceConfig,
        model_provider: ModelProvider,
        dataset_provider: DatasetProvider,
        task: InferenceTask,
        params: PyTree | None = None,
        microbatch_size: int | None = None,
        event_bus: EventBus | None = None,
    ):
        self.ctx = ctx
        self.config = config
        self.task = task
        self.events = event_bus if event_bus is not None else EventBus()
        self.events.emit(ev.EVENT_INFER_CONFIG_STARTED, inference=self)

        self.microbatch_size = microbatch_size or config.batch_size
        if config.batch_size % self.microbatch_size != 0:
            raise ValueError(
                f"batch_size {config.batch_size} not divisible by "
                f"microbatch_size {self.microbatch_size}"
            )
        self.num_microbatches = config.batch_size // self.microbatch_size

        rng = jax.random.PRNGKey(config.seed)
        self.init_rng, self.step_rng = jax.random.split(rng)
        self.pp_engine = None
        self.module = None
        self._forward = None
        self._stage = None

        if ctx.pp_size > 1:
            if not isinstance(task, PipelineInferenceTask):
                raise TypeError(
                    "pipeline-parallel inference needs a "
                    "PipelineInferenceTask (the task defines the stage "
                    f"carry decomposition); got {type(task).__name__}"
                )
            from d9d_tpu.loop.pipeline_driver import PipelineInferenceEngine

            self.pp_engine = PipelineInferenceEngine(
                ctx=ctx,
                model_provider=model_provider,
                task=task,
                num_microbatches=self.num_microbatches,
                microbatch_size=self.microbatch_size,
                seq_len=config.seq_len,
                init_rng=self.init_rng,
                stage_params=params,
            )
        else:
            self.module = model_provider.build_module(PipelineStageInfo())
            plan = model_provider.build_plan(ctx)
            if params is not None:
                # handed-over params (trainer snapshot or a restored
                # checkpoint) can carry uncommitted scalar leaves whose
                # single-device placement conflicts with the mesh-placed
                # majority at the first forward — the same latent
                # placement class as the PR 5 resume bug
                from d9d_tpu.core.tree_sharding import replicate_uncommitted

                self.params = replicate_uncommitted(params, ctx.mesh)
            else:
                sample = model_provider.sample_inputs(
                    self.microbatch_size, config.seq_len
                )
                self.params, _ = init_sharded_params(
                    self.module, sample, self.init_rng, ctx, plan
                )

            n_mb = self.num_microbatches
            task_fwd = task.forward_fn
            module = self.module

            def forward(params, batch, rng):
                def body(_, mb_and_idx):
                    mb, idx = mb_and_idx
                    out = task_fwd(
                        module, params, mb, jax.random.fold_in(rng, idx)
                    )
                    return None, out

                _, outs = lax.scan(
                    body, None, (batch, jax.numpy.arange(n_mb))
                )
                return outs  # leading dims [n_mb, mb, ...]

            # tracked (telemetry/introspect.py): the per-batch forward is
            # the inference hot path — compiles/HBM claim must be visible
            self._forward = tracked_jit(forward, name="infer/forward")
            self._stage = make_batch_stager(
                ctx,
                num_microbatches=self.num_microbatches,
                microbatch_size=self.microbatch_size,
                seq_len=config.seq_len,
            )
        self.dataset_provider = dataset_provider
        self.events.emit(ev.EVENT_INFER_READY, inference=self)

    def _stage_batch(self, raw: PyTree) -> PyTree:
        prepared = self.task.prepare_batch(raw)
        if self.pp_engine is None:
            return self._stage(prepared)
        return split_microbatches(
            prepared,
            num_microbatches=self.num_microbatches,
            microbatch_size=self.microbatch_size,
        )

    def _forward_batch(self, batch: PyTree, rng) -> PyTree:
        """→ host outputs with leading dim = batch size."""
        if self.pp_engine is not None:
            outs = self.pp_engine.forward(batch)  # list per microbatch
            host = [jax.tree.map(np.asarray, o) for o in outs]
            return jax.tree.map(
                lambda *xs: np.concatenate(xs, axis=0), *host
            )
        return self._fetch_outputs(self._forward(self.params, batch, rng))

    @staticmethod
    def _fetch_outputs(outs: PyTree) -> PyTree:
        """Jitted-path device outputs ``[n_mb, mb, ...]`` → host arrays
        with leading dim = batch size (this blocks on the dispatch)."""
        return jax.tree.map(
            lambda x: np.asarray(x).reshape(-1, *x.shape[2:]), outs
        )

    def _finish_batch(self, outs: PyTree) -> Any:
        """Fetch a dispatched forward's outputs and run the host-side
        task processing on them."""
        return self.task.process_outputs(self._fetch_outputs(outs))

    def infer(self) -> list[Any]:
        """Run the whole dataset; returns task.process_outputs results.

        On the jitted (non-PP) path the loop is pipelined one batch
        deep: batch ``i`` is DISPATCHED (async — XLA returns futures)
        before batch ``i-1``'s outputs are fetched to the host, so the
        device computes batch ``i`` while the host pays the readback and
        ``process_outputs`` cost of batch ``i-1``. Results come back in
        dataset order; each batch's bounded event covers its staging and
        dispatch plus the previous batch's host-side processing, and the
        final in-flight batch drains under one more bounded event
        (``index = number of batches``) so event handlers that bound
        hangs always have a batch event open while device work or
        readback is outstanding. The PP engine path stays synchronous
        (the executor is host-driven).
        """
        results: list[Any] = []
        t0 = time.perf_counter()
        inflight: PyTree | None = None  # dispatched, not yet fetched
        for i, raw in enumerate(iter(self.dataset_provider.build())):
            with self.events.bounded(ev.EVENT_INFER_BATCH, inference=self, index=i):
                batch = self._stage_batch(raw)
                rng = jax.random.fold_in(self.step_rng, i)
                if self.pp_engine is not None:
                    host = self._forward_batch(batch, rng)
                    results.append(self.task.process_outputs(host))
                else:
                    outs = self._forward(self.params, batch, rng)
                    if inflight is not None:
                        results.append(self._finish_batch(inflight))
                    inflight = outs
            if (i + 1) % self.config.log_every == 0:
                logger.info(
                    "inference batch %d (%.2fs)", i + 1, time.perf_counter() - t0
                )
        if inflight is not None:
            with self.events.bounded(
                ev.EVENT_INFER_BATCH, inference=self, index=len(results) + 1
            ):
                results.append(self._finish_batch(inflight))
        self.events.emit(ev.EVENT_INFER_FINISHED, inference=self)
        return results
