"""Autoregressive generation over KV-cache decode models.

Beyond-reference capability (the reference's ``Inference`` is forward-only
batch scoring — d9d/loop/inference.py; it has no sampling loop): build a
model with ``decode_max_length >= prompt_len + max_new_tokens - 1`` and
this module runs prefill + a ``lax.scan`` decode loop as ONE jitted
program — no host round-trip per token, XLA-friendly static shapes
throughout.

The cache rides flax's ``"cache"`` collection (written by
``GroupedQueryAttention._decode_attend`` / the GDN decode state), so the
loop is model-agnostic: anything exposing a ``logits`` method and the
cache collection decodes here (Qwen3 dense, MoE, the GDN hybrid, Llama).

Ragged batches are LEFT-padded: pass ``prompt_lengths [B]`` and rows
shorter than the padded width get per-row rope positions
(``0..L-1`` right-aligned) plus a key-validity mask over their pad slots
(cache-slot order equals time order per row, so causality stays
slot-based — see ``GroupedQueryAttention._decode_attend``). GDN layers
receive the matching ``padding_mask`` when the model accepts one.

Sampling: ``temperature=0`` is greedy argmax; otherwise
``jax.random.categorical`` over ``logits / temperature``, optionally
truncated to the ``top_k`` highest-probability tokens and/or the smallest
set with cumulative probability ``top_p`` (nucleus sampling; both given =
top-k first, then nucleus over the survivors — the HF composition order).
``eos_id`` freezes finished rows (they keep emitting ``eos_id`` so shapes
stay static).
"""

import inspect
from typing import Any, Optional

import jax
import jax.numpy as jnp

from d9d_tpu.core.types import Array


def _top_k_filter(logits: Array, top_k: int) -> Array:
    """Mask logits below the k-th largest to -inf (lax.top_k selection —
    no full-vocab sort inside the per-token decode step)."""
    kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
    return jnp.where(logits >= kth, logits, -jnp.inf)


def _nucleus_filter(logits: Array, top_p: float) -> Array:
    """Mask logits outside the smallest cumulative-``top_p`` set to -inf
    (the most probable token always survives)."""
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # keep tokens while the cumulative mass BEFORE them is < top_p
    keep_sorted = (cum - probs) < top_p
    cutoff = jnp.min(
        jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True
    )
    return jnp.where(logits >= cutoff, logits, -jnp.inf)


def generate(
    model,
    params: Any,
    prompt_ids: Array,
    *,
    max_new_tokens: int,
    prompt_lengths: Optional[Array] = None,
    temperature: float = 0.0,
    top_p: float | None = None,
    top_k: int | None = None,
    rng: Optional[jax.Array] = None,
    eos_id: int | None = None,
    prefill_chunk_size: int | None = None,
) -> Array:
    """``prompt_ids [B, P]`` int32 → generated ids ``[B, max_new_tokens]``.

    ``model`` must be built with
    ``decode_max_length >= P + max_new_tokens - 1`` (the final sampled
    token is returned, never fed back). Ragged batches: left-pad to width
    P and pass ``prompt_lengths [B]``. The whole prefill + decode scan
    jits as one program; call under ``jax.jit`` for repeat use.

    ``prefill_chunk_size``: feed the prompt through the cache in chunks
    of at most this many tokens (long-context serving: prefill
    activation memory stays O(chunk) instead of O(P)). The first chunk
    runs the flash prefill fast path; continuation chunks attend the
    slot cache (``d9d_tpu.nn.decode_flags.continuation_chunk``) —
    results are exact, not approximate. Keep the chunk at or below
    ``MAX_DECODE_ROWS // (Hq/Hkv)`` (ops/attention/pallas_decode.py) so
    GQA continuation chunks ride the flash-decode kernel on TPU rather
    than the eager ``[t, s_max]`` fallback.
    """
    if temperature > 0.0 and rng is None:
        raise ValueError("temperature > 0 needs an rng key")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if top_k is not None and top_k < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k}")
    if (top_p is not None or top_k is not None) and temperature == 0.0:
        raise ValueError(
            "top_p/top_k have no effect with temperature=0 (greedy "
            "argmax); set a temperature to sample"
        )
    dml = getattr(model, "decode_max_length", 0)
    b, p = prompt_ids.shape
    # the final sampled token is returned, never fed back, so the cache
    # holds at most p + max_new_tokens - 1 positions
    if dml < p + max_new_tokens - 1:
        raise ValueError(
            f"model.decode_max_length={dml} < prompt {p} + "
            f"max_new_tokens {max_new_tokens} - 1"
        )
    rng = rng if rng is not None else jax.random.PRNGKey(0)

    def sample(logits, key):
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        scaled = logits.astype(jnp.float32) / temperature
        if top_k is not None and top_k < logits.shape[-1]:
            scaled = _top_k_filter(scaled, top_k)
        if top_p is not None and top_p < 1.0:
            scaled = _nucleus_filter(scaled, top_p)
        return jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)

    # per-row geometry: row i's real tokens sit right-aligned in
    # [pad_i, P) with pad_i = P - L_i; logical positions are 0..L_i-1
    if prompt_lengths is not None:
        lengths = prompt_lengths.astype(jnp.int32)
        pad = p - lengths  # [B]
        positions = jnp.maximum(
            jnp.arange(p, dtype=jnp.int32)[None, :] - pad[:, None], 0
        )
        key_valid = (
            jnp.arange(dml, dtype=jnp.int32)[None, :] >= pad[:, None]
        )[:, None, None, :]  # [B,1,1,S_max]; decode slots (>= P) valid
        pad_mask = (
            jnp.arange(p, dtype=jnp.int32)[None, :] >= pad[:, None]
        )  # [B, P] real-token mask for GDN layers
    else:
        lengths = jnp.full((b,), p, jnp.int32)
        positions = jnp.broadcast_to(jnp.arange(p, dtype=jnp.int32), (b, p))
        key_valid = None
        pad_mask = None

    prefill_method = getattr(model, "logits_last", None) or model.logits
    accepts_padding = "padding_mask" in inspect.signature(
        prefill_method
    ).parameters

    def call(variables, ids, pos, padding_mask):
        # logits_last == logits at t=1, so one method serves both phases
        kwargs = {"mask": key_valid}
        if accepts_padding:
            kwargs["padding_mask"] = padding_mask
        return model.apply(
            variables, ids, pos,
            method=prefill_method,
            mutable=["cache"],
            **kwargs,
        )

    # prefill: write every layer's cache; only the last position's
    # logits are needed (logits_last fast path). With a chunk size, the
    # prompt streams through in bounded pieces — chunk 0 on the empty
    # cache (fast path), the rest as slot-cache continuation chunks
    # (static Python loop: each chunk traces once with static shapes)
    if prefill_chunk_size is not None and prefill_chunk_size < 1:
        raise ValueError(
            f"prefill_chunk_size must be >= 1, got {prefill_chunk_size}"
        )
    from d9d_tpu.nn.decode_flags import continuation_chunk

    ids = prompt_ids.astype(jnp.int32)
    chunk = prefill_chunk_size if prefill_chunk_size is not None else p
    logits, state = call(
        {"params": params}, ids[:, :chunk], positions[:, :chunk],
        None if pad_mask is None else pad_mask[:, :chunk],
    )
    for lo in range(chunk, p, chunk):
        hi = min(lo + chunk, p)
        with continuation_chunk():
            logits, state = call(
                {"params": params, "cache": state["cache"]},
                ids[:, lo:hi], positions[:, lo:hi],
                None if pad_mask is None else pad_mask[:, lo:hi],
            )
    key, sub = jax.random.split(rng)
    token = sample(logits[:, -1], sub)
    done = (
        token == eos_id if eos_id is not None
        else jnp.zeros((b,), jnp.bool_)
    )

    step_pad = (
        jnp.ones((b, 1), jnp.bool_) if accepts_padding else None
    )

    def step(carry, _):
        cache, tok, pos, key, dn = carry
        key, sub = jax.random.split(key)
        logits_t, new_cache = call(
            {"params": params, "cache": cache},
            tok[:, None], pos[:, None], step_pad,
        )
        nxt = sample(logits_t[:, -1], sub)
        if eos_id is not None:
            nxt = jnp.where(dn, eos_id, nxt)
            dn = dn | (nxt == eos_id)
        return (new_cache["cache"], nxt, pos + 1, key, dn), nxt

    if max_new_tokens == 1:
        return token[:, None]
    carry = (state["cache"], token, lengths, key, done)
    _, rest = jax.lax.scan(step, carry, None, length=max_new_tokens - 1)
    # prefill sampled the first generated token; each scan step sampled
    # the next one
    return jnp.concatenate(
        [token[:, None], jnp.moveaxis(rest, 0, 1)], axis=1
    )
