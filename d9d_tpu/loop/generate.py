"""Autoregressive generation over KV-cache decode models.

Beyond-reference capability (the reference's ``Inference`` is forward-only
batch scoring — d9d/loop/inference.py; it has no sampling loop): build a
model with ``decode_max_length = prompt_len + max_new_tokens`` and this
module runs prefill + a ``lax.scan`` decode loop as ONE jitted program —
no host round-trip per token, XLA-friendly static shapes throughout.

The cache rides flax's ``"cache"`` collection (written by
``GroupedQueryAttention._decode_attend`` / the GDN decode state), so the
loop is model-agnostic: anything exposing a ``logits`` method and the
cache collection decodes here (Qwen3 dense, MoE, the GDN hybrid, Llama).

Sampling: ``temperature=0`` is greedy argmax; otherwise
``jax.random.categorical`` over ``logits / temperature``. ``eos_id``
freezes finished rows (they keep emitting ``eos_id`` so shapes stay
static).
"""

from typing import Any, Optional

import jax
import jax.numpy as jnp

from d9d_tpu.core.types import Array


def generate(
    model,
    params: Any,
    prompt_ids: Array,
    *,
    max_new_tokens: int,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
    eos_id: int | None = None,
) -> Array:
    """``prompt_ids [B, P]`` int32 → generated ids ``[B, max_new_tokens]``.

    ``model`` must be built with ``decode_max_length >= P + max_new_tokens``
    (its KV caches are that static length). The whole prefill + decode
    scan jits as one program; call under ``jax.jit`` for repeat use —
    retracing only happens when shapes change.
    """
    if temperature > 0.0 and rng is None:
        raise ValueError("temperature > 0 needs an rng key")
    dml = getattr(model, "decode_max_length", 0)
    b, p = prompt_ids.shape
    # the final sampled token is returned, never fed back, so the cache
    # holds at most p + max_new_tokens - 1 positions
    if dml < p + max_new_tokens - 1:
        raise ValueError(
            f"model.decode_max_length={dml} < prompt {p} + "
            f"max_new_tokens {max_new_tokens} - 1"
        )
    rng = rng if rng is not None else jax.random.PRNGKey(0)

    def sample(logits, key):
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return jax.random.categorical(
            key, logits.astype(jnp.float32) / temperature, axis=-1
        ).astype(jnp.int32)

    # prefill: run the whole prompt once, writing every layer's cache;
    # only the last position's logits are needed, so use the
    # head-on-one-row method when the model provides it
    positions = jnp.broadcast_to(
        jnp.arange(p, dtype=jnp.int32), (b, p)
    )
    prefill_method = getattr(model, "logits_last", None) or model.logits
    logits, state = model.apply(
        {"params": params},
        prompt_ids.astype(jnp.int32),
        positions,
        method=prefill_method,
        mutable=["cache"],
    )
    key, sub = jax.random.split(rng)
    token = sample(logits[:, -1], sub)
    done = (
        token == eos_id if eos_id is not None
        else jnp.zeros((b,), jnp.bool_)
    )

    def step(carry, _):
        cache, tok, pos, key, dn = carry
        key, sub = jax.random.split(key)
        logits_t, new_cache = model.apply(
            {"params": params, "cache": cache},
            tok[:, None],
            jnp.full((b, 1), pos, jnp.int32),
            method=model.logits,
            mutable=["cache"],
        )
        nxt = sample(logits_t[:, -1], sub)
        if eos_id is not None:
            nxt = jnp.where(dn, eos_id, nxt)
            dn = dn | (nxt == eos_id)
        return (new_cache["cache"], nxt, pos + 1, key, dn), nxt

    if max_new_tokens == 1:
        return token[:, None]
    carry = (state["cache"], token, jnp.int32(p), key, done)
    _, rest = jax.lax.scan(step, carry, None, length=max_new_tokens - 1)
    # prefill sampled the first generated token; each scan step sampled
    # the next one
    return jnp.concatenate(
        [token[:, None], jnp.moveaxis(rest, 0, 1)], axis=1
    )
