from d9d_tpu.loop.components.batch_maths import BatchMaths
from d9d_tpu.loop.components.stepper import StepActionPeriod, Stepper
from d9d_tpu.loop.config import InferenceConfig, TrainerConfig
from d9d_tpu.loop.control.providers import (
    AdamWProvider,
    DatasetProvider,
    ModelProvider,
    OptimizerProvider,
)
from d9d_tpu.loop.control.task import TrainTask
from d9d_tpu.loop.model_factory import init_sharded_params
from d9d_tpu.loop.tasks import CausalLMTask
from d9d_tpu.loop.train import Trainer
from d9d_tpu.loop.train_step import build_train_step

__all__ = [
    "BatchMaths",
    "StepActionPeriod",
    "Stepper",
    "InferenceConfig",
    "TrainerConfig",
    "AdamWProvider",
    "DatasetProvider",
    "ModelProvider",
    "OptimizerProvider",
    "TrainTask",
    "init_sharded_params",
    "CausalLMTask",
    "Trainer",
    "build_train_step",
]
