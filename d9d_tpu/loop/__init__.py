from d9d_tpu.loop.components.batch_maths import BatchMaths
from d9d_tpu.loop.components.checkpointer import StateCheckpointer
from d9d_tpu.loop.components.data_loader import (
    DataFetchError,
    StatefulDataLoader,
    default_collate,
)
from d9d_tpu.loop.components.garbage_collector import ManualGarbageCollector
from d9d_tpu.loop.components.job_profiler import JobProfiler
from d9d_tpu.loop.components.stepper import StepActionPeriod, Stepper
from d9d_tpu.loop.components.timeout_manager import TimeoutManager
from d9d_tpu.loop.config import InferenceConfig, TrainerConfig
from d9d_tpu.loop.control.providers import (
    AdamWProvider,
    DatasetProvider,
    ModelProvider,
    OptimizerProvider,
)
from d9d_tpu.loop.control.task import PipelineTrainTask, TrainTask
from d9d_tpu.loop.event import EventBus
from d9d_tpu.loop.generate import generate
from d9d_tpu.loop.serve import (
    ContinuousBatcher,
    QueueFullError,
    ServeStalledError,
)
from d9d_tpu.loop.speculative import speculative_generate
from d9d_tpu.loop.inference import (
    Inference,
    InferenceTask,
    PipelineInferenceTask,
)
from d9d_tpu.loop.model_factory import init_sharded_params
from d9d_tpu.loop.tasks import (
    CausalLMTask,
    EmbeddingContrastiveTask,
    SequenceClassificationTask,
)
from d9d_tpu.loop.train import Trainer
from d9d_tpu.loop.train_step import build_train_step

__all__ = [
    "BatchMaths",
    "Inference",
    "InferenceTask",
    "PipelineInferenceTask",
    "PipelineTrainTask",
    "StateCheckpointer",
    "DataFetchError",
    "StatefulDataLoader",
    "default_collate",
    "ManualGarbageCollector",
    "JobProfiler",
    "StepActionPeriod",
    "Stepper",
    "TimeoutManager",
    "InferenceConfig",
    "TrainerConfig",
    "AdamWProvider",
    "DatasetProvider",
    "ModelProvider",
    "OptimizerProvider",
    "TrainTask",
    "EventBus",
    "init_sharded_params",
    "CausalLMTask",
    "EmbeddingContrastiveTask",
    "SequenceClassificationTask",
    "Trainer",
    "build_train_step",
    "generate",
    "ContinuousBatcher",
    "QueueFullError",
    "ServeStalledError",
    "speculative_generate",
]
