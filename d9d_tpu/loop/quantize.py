"""Int8 weight stream for serving (docs/design/generation.md
"Low-precision serving").

Decode is HBM-bandwidth-bound: roofline attributes most of a decode
step to streaming the parameters, so halving (vs bf16; quartering vs
f32) the bytes of the big matmul kernels is the largest single-chip
serving lever after the paged KV pool. The shape of the pass follows
from the install contract (loop/serve.py): weights are TRACED
arguments of the decode executables, so a quantized tree must be a
pytree ``ContinuousBatcher.install_weights`` accepts unchanged —
:func:`quantize_for_serving` replaces each eligible 2-D kernel leaf
with a sub-dict

    {"qvalue": int8 [in, out], "scale": <orig dtype> [out]}

(symmetric per-out-channel absmax/127 — each output channel's scale
rides in the layout the dequantizing matmul consumes, the
block-native-scales argument), and :func:`dequantize_params` — called
INSIDE the traced ``_model_step`` — widens ``qvalue * scale`` back so
XLA streams int8 from HBM and fuses the rescale into the matmul's
operand read. Installing a quantized tree changes the executable's
dtype signature exactly once (tree structure + dtypes differ from the
bf16 tree), then every later quantized publish reuses the compiled
program: zero steady-state recompiles, the same contract as any other
publish.

What stays wide, and why: embeddings and the logits head set output
quality disproportionately (and the head's matmul feeds sampling
directly); norms/biases/scalars are bandwidth-trivial. Eligibility is
therefore *2-D leaves named ``kernel``* outside embed/head scopes —
everything else passes through untouched.

Cold-path module: runs once per publish on the host/donor side, never
inside a hot loop — deliberately no jit here (D9D001 keeps
``d9d_tpu/loop/`` free of untracked jits; tracing happens where the
consuming executable already traces).
"""

from typing import Optional

import jax.numpy as jnp
from flax.traverse_util import flatten_dict, unflatten_dict

__all__ = [
    "dequantize_params",
    "is_quantized_tree",
    "quantize_for_serving",
]

QVALUE_LEAF = "qvalue"
SCALE_LEAF = "scale"

# module scopes whose kernels stay wide: token embedding (often tied),
# and the logits head — the two ends of the network where quantization
# error lands directly on the sampled distribution
_SKIP_SCOPES = ("embed", "lm_head", "logits", "head")


def _eligible(path: tuple, leaf) -> bool:
    if path[-1] != "kernel" or getattr(leaf, "ndim", 0) != 2:
        return False
    return not any(
        any(tok in part for tok in _SKIP_SCOPES)
        for part in path[:-1]
    )


def quantize_for_serving(params, *, skip: Optional[tuple] = None):
    """Per-out-channel symmetric int8 quantization of the big matmul
    kernels of a served tree; returns a NEW tree (input untouched) in
    which each eligible leaf became ``{"qvalue", "scale"}``.

    ``skip``: extra scope-name substrings to leave wide (on top of the
    embed/head defaults). Zero columns quantize to scale 0 — dequant
    reproduces the exact zeros, no epsilon drift.

    Partitioning boxes (``LogicallyPartitioned``) are unboxed first:
    the quantized tree's structure differs from the training tree
    anyway (that's the one-time recompile), and serving placement
    comes from the actual device buffers, not the logical axis
    metadata."""
    from flax.core import meta

    params = meta.unbox(params)
    flat = flatten_dict(params)
    out = {}
    for path, leaf in flat.items():
        if not _eligible(path, leaf) or (
            skip and any(
                any(tok in part for tok in skip) for part in path[:-1]
            )
        ):
            out[path] = leaf
            continue
        amax = jnp.max(jnp.abs(leaf.astype(jnp.float32)), axis=0)
        scale = amax / 127.0
        q = jnp.where(
            scale > 0.0,
            jnp.round(leaf.astype(jnp.float32) / jnp.where(
                scale > 0.0, scale, 1.0
            )),
            0.0,
        )
        out[path + (QVALUE_LEAF,)] = jnp.clip(q, -127, 127).astype(jnp.int8)
        out[path + (SCALE_LEAF,)] = scale.astype(leaf.dtype)
    return unflatten_dict(out)


def _quantized_paths(flat) -> list:
    # a leaf named "scale" alone is NOT a marker (norm params are
    # literally named scale); only the qvalue sibling makes the pair a
    # quantized kernel
    return [p for p in flat if p[-1] == QVALUE_LEAF]


def is_quantized_tree(params) -> bool:
    """True if the tree carries at least one quantized kernel."""
    return bool(_quantized_paths(flatten_dict(params)))


def dequantize_params(params):
    """Widen every quantized kernel back to ``qvalue * scale`` (the
    scale's dtype). Trace-safe and intended to run traced: under jit
    the int8 leaf is the program input and the widening fuses into the
    consuming matmul. Structural no-op on unquantized trees (returns
    the input object, so non-quantized callers pay nothing)."""
    flat = flatten_dict(params)
    qpaths = _quantized_paths(flat)
    if not qpaths:
        return params
    for qpath in qpaths:
        kernel_path = qpath[:-1]
        scale = flat.pop(kernel_path + (SCALE_LEAF,))
        q = flat.pop(qpath)
        flat[kernel_path] = q.astype(scale.dtype) * scale[None, :]
    return unflatten_dict(flat)
