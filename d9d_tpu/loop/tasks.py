"""Built-in task implementations."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from d9d_tpu.core.types import Array, PyTree
from d9d_tpu.loop.control.task import PipelineTrainTask, TrainTask
from d9d_tpu.ops import LM_IGNORE_INDEX


def _moe_load_metrics(updates: PyTree) -> dict[str, Array]:
    """Expert load-balance statistics from sown ``moe_stats``
    (reference tokens_per_expert buffer, module/block/moe/layer.py:16).

    Emits the raw per-expert assignment-count vector (summed over layers)
    so the engine's microbatch scan sums it exactly; the max/total ratio
    is taken host-side in ``metrics_postprocess`` — taking max per
    microbatch first would bias the share upward with small microbatches.
    Covers the logged step (not the whole log window). Single-program path
    only: under pipeline parallelism the executor's metric channel carries
    last-stage loss statistics and this metric is absent. Empty dict for
    dense models."""
    stats = updates.get("moe_stats") if updates else None
    if not stats:
        return {}
    counts = [
        (leaf[0] if isinstance(leaf, tuple) else leaf).astype(jnp.float32)
        for leaf in jax.tree.leaves(
            stats, is_leaf=lambda x: isinstance(x, tuple)
        )
    ]
    return {"moe_tokens_per_expert": sum(counts)}


class CausalLMTask(PipelineTrainTask):
    """Next-token prediction with token-count loss weighting.

    Equivalent of the reference example's SFT task
    (example/qwen3_moe/pretrain.py): expects batches with ``input_ids``
    [B, T+1] (and optional ``loss_mask`` [B, T+1]); shifts internally.
    The model must be a CausalLM returning per-token loss given
    (tokens, positions, labels).
    """

    def prepare_batch(self, batch: PyTree) -> PyTree:
        input_ids = np.asarray(batch["input_ids"])
        tokens = input_ids[:, :-1]
        labels = input_ids[:, 1:].copy()
        if "loss_mask" in batch:
            labels = np.where(
                np.asarray(batch["loss_mask"])[:, 1:] != 0, labels, LM_IGNORE_INDEX
            )
        b, t = tokens.shape
        positions = np.broadcast_to(np.arange(t, dtype=np.int32), (b, t)).copy()
        return {"tokens": tokens, "labels": labels, "positions": positions}

    def loss_fn(
        self, module: nn.Module, params: PyTree, mb: PyTree, rng: Array
    ) -> tuple[Array, Array, dict[str, Array]]:
        per_token, updates = module.apply(
            params, mb["tokens"], mb["positions"], mb["labels"],
            mutable=["moe_stats"],
        )
        valid = (mb["labels"] != LM_IGNORE_INDEX).astype(jnp.float32)
        loss_sum = per_token.sum()
        weight = valid.sum()
        metrics = {"tokens": weight}
        metrics.update(_moe_load_metrics(updates))
        return loss_sum, weight, metrics

    def metrics_postprocess(self, metrics):
        counts = metrics.pop("task/moe_tokens_per_expert", None)
        if counts is not None:
            counts = np.asarray(counts, np.float64)
            # heaviest expert's share of routed assignments (layer-summed);
            # 1/num_experts = perfectly balanced routing
            metrics["task/moe_load_max_frac"] = float(
                counts.max() / max(counts.sum(), 1.0)
            )
        return metrics

    # -- pipeline surface (PipelineTrainTask) --------------------------
    # carry = token ids on stage 0, hidden states after; positions ride
    # kwargs (every stage's RoPE needs them); labels ride last-stage state.

    def sample_microbatch(self, microbatch_size: int, seq_len: int) -> PyTree:
        z = np.zeros((microbatch_size, seq_len), np.int32)
        return {"tokens": z, "labels": z, "positions": z}

    def split_microbatch(self, mb: PyTree) -> tuple[PyTree, PyTree, PyTree]:
        return (
            mb["tokens"],
            {"positions": mb["positions"]},
            {"labels": mb["labels"]},
        )

    def stage_forward(
        self, module: nn.Module, params: PyTree, carry: PyTree, kwargs: PyTree
    ) -> PyTree:
        return module.apply(params, carry, kwargs["positions"])

    def last_stage_loss(self, module, params, carry, kwargs, state):
        per_token = module.apply(
            params, carry, kwargs["positions"], state["labels"]
        )
        valid = (state["labels"] != LM_IGNORE_INDEX).astype(jnp.float32)
        return per_token.sum(), valid.sum(), {"tokens": valid.sum()}

    def stage_init(self, module, rng, carry, kwargs, state, is_last):
        if is_last:
            return module.init(
                rng, carry, kwargs["positions"], state["labels"]
            )
        return module.init(rng, carry, kwargs["positions"])


class SequenceClassificationTask(TrainTask):
    """Fine-tune a classification-head model (reference task surface,
    loop/control/task.py:180 + the ClassificationHead model family).

    Batches: ``input_ids`` [B, T] (+ optional ``attention_mask`` [B, T])
    and integer ``class_labels`` [B]. The model must map
    (tokens, positions, pooling_mask) → logits [B, C]. Per-class confusion
    counts are reduced on device inside the step; the ConfusionMatrixMetric
    aggregates them across the log window and processes.
    """

    def __init__(self, num_classes: int):
        self.num_classes = num_classes

    def prepare_batch(self, batch: PyTree) -> PyTree:
        tokens = np.asarray(batch["input_ids"])
        b, t = tokens.shape
        out = {
            "tokens": tokens,
            "labels": np.asarray(batch["class_labels"]).astype(np.int32),
            "positions": np.broadcast_to(
                np.arange(t, dtype=np.int32), (b, t)
            ).copy(),
        }
        if "attention_mask" in batch:
            out["pooling_mask"] = np.asarray(batch["attention_mask"])
        else:
            out["pooling_mask"] = np.ones((b, t), np.int32)
        return out

    def loss_fn(self, module, params, mb, rng):
        logits = module.apply(
            params, mb["tokens"], mb["positions"], mb["pooling_mask"]
        ).astype(jnp.float32)
        labels = mb["labels"]
        logp = jax.nn.log_softmax(logits, axis=-1)
        loss_sum = -jnp.take_along_axis(logp, labels[:, None], axis=-1).sum()
        b = labels.shape[0]
        pred = jnp.argmax(logits, axis=-1)
        pred_1h = jax.nn.one_hot(pred, self.num_classes, dtype=jnp.float32)
        true_1h = jax.nn.one_hot(labels, self.num_classes, dtype=jnp.float32)
        tp = (pred_1h * true_1h).sum(0)
        fp = (pred_1h * (1 - true_1h)).sum(0)
        fn = ((1 - pred_1h) * true_1h).sum(0)
        tn = ((1 - pred_1h) * (1 - true_1h)).sum(0)
        metrics = {
            "correct": (pred == labels).sum().astype(jnp.float32),
            "examples": jnp.asarray(b, jnp.float32),
            "confusion": jnp.stack([tp, fp, tn, fn]),  # [4, C]
        }
        return loss_sum, jnp.asarray(b, jnp.float32), metrics

    def metrics_postprocess(self, metrics):
        # per-step console view; the windowed truth rides the Metric objects
        if "task/correct" in metrics and "task/examples" in metrics:
            metrics["task/accuracy"] = metrics["task/correct"] / max(
                metrics["task/examples"], 1.0
            )
            metrics.pop("task/confusion", None)
        return metrics

    def metrics(self):
        from d9d_tpu.metric import ConfusionMatrixMetricBuilder

        return {
            "accuracy": (
                ConfusionMatrixMetricBuilder()
                .multiclass(self.num_classes)
                .with_accuracy()
                .micro()
                .build()
            ),
        }

    def update_metrics(self, metric_objs, stats):
        tp, fp, tn, fn = np.asarray(stats["confusion"])
        metric_objs["accuracy"].update_counts(tp=tp, fp=fp, tn=tn, fn=fn)


class EmbeddingContrastiveTask(TrainTask):
    """In-batch contrastive (InfoNCE) training for the embedding head
    (reference embedding task, loop/control/task.py:262 family).

    Batches: ``input_ids_a``/``input_ids_b`` [B, T] paired views. The
    model must map (tokens, positions, pooling_mask) → L2-normalized
    embeddings [B, D]. Loss is symmetric InfoNCE over the in-batch
    similarity matrix; retrieval@1 counts ride the metric window.
    """

    def __init__(self, temperature: float = 0.05):
        self.temperature = temperature

    def prepare_batch(self, batch: PyTree) -> PyTree:
        a = np.asarray(batch["input_ids_a"])
        b_ids = np.asarray(batch["input_ids_b"])
        bsz, t = a.shape
        positions = np.broadcast_to(np.arange(t, dtype=np.int32), (bsz, t))
        return {
            "tokens_a": a,
            "tokens_b": b_ids,
            "positions": positions.copy(),
            "pooling_mask": np.asarray(
                batch.get("attention_mask", np.ones((bsz, t), np.int32))
            ),
        }

    def loss_fn(self, module, params, mb, rng):
        emb_a = module.apply(
            params, mb["tokens_a"], mb["positions"], mb["pooling_mask"]
        ).astype(jnp.float32)
        emb_b = module.apply(
            params, mb["tokens_b"], mb["positions"], mb["pooling_mask"]
        ).astype(jnp.float32)
        sim = emb_a @ emb_b.T / self.temperature  # [B, B]
        bsz = sim.shape[0]
        targets = jnp.arange(bsz)
        logp_ab = jax.nn.log_softmax(sim, axis=-1)
        logp_ba = jax.nn.log_softmax(sim.T, axis=-1)
        diag = jnp.diag_indices(bsz)
        loss_sum = -(logp_ab[diag].sum() + logp_ba[diag].sum()) / 2.0
        hits = (jnp.argmax(sim, axis=-1) == targets).sum()
        metrics = {
            "retrieval_hits": hits.astype(jnp.float32),
            "examples": jnp.asarray(bsz, jnp.float32),
        }
        return loss_sum, jnp.asarray(bsz, jnp.float32), metrics

    def metrics(self):
        from d9d_tpu.metric import WeightedMeanMetric

        return {"retrieval_at_1": WeightedMeanMetric()}

    def update_metrics(self, metric_objs, stats):
        # WeightedMeanMetric computes Σ(value·weight)/Σweight, so feed the
        # per-window hit *rate* with the example count as its weight
        examples = np.asarray(stats["examples"], np.float32)
        hits = np.asarray(stats["retrieval_hits"], np.float32)
        metric_objs["retrieval_at_1"].update(
            values=hits / np.maximum(examples, 1.0),
            weights=examples,
        )
