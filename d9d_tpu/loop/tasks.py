"""Built-in task implementations."""

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from d9d_tpu.core.types import Array, PyTree
from d9d_tpu.loop.control.task import PipelineTrainTask
from d9d_tpu.ops import LM_IGNORE_INDEX


class CausalLMTask(PipelineTrainTask):
    """Next-token prediction with token-count loss weighting.

    Equivalent of the reference example's SFT task
    (example/qwen3_moe/pretrain.py): expects batches with ``input_ids``
    [B, T+1] (and optional ``loss_mask`` [B, T+1]); shifts internally.
    The model must be a CausalLM returning per-token loss given
    (tokens, positions, labels).
    """

    def prepare_batch(self, batch: PyTree) -> PyTree:
        input_ids = np.asarray(batch["input_ids"])
        tokens = input_ids[:, :-1]
        labels = input_ids[:, 1:].copy()
        if "loss_mask" in batch:
            labels = np.where(
                np.asarray(batch["loss_mask"])[:, 1:] != 0, labels, LM_IGNORE_INDEX
            )
        b, t = tokens.shape
        positions = np.broadcast_to(np.arange(t, dtype=np.int32), (b, t)).copy()
        return {"tokens": tokens, "labels": labels, "positions": positions}

    def loss_fn(
        self, module: nn.Module, params: PyTree, mb: PyTree, rng: Array
    ) -> tuple[Array, Array, dict[str, Array]]:
        per_token = module.apply(params, mb["tokens"], mb["positions"], mb["labels"])
        valid = (mb["labels"] != LM_IGNORE_INDEX).astype(jnp.float32)
        loss_sum = per_token.sum()
        weight = valid.sum()
        return loss_sum, weight, {"tokens": weight}

    # -- pipeline surface (PipelineTrainTask) --------------------------
    # carry = token ids on stage 0, hidden states after; positions ride
    # kwargs (every stage's RoPE needs them); labels ride last-stage state.

    def sample_microbatch(self, microbatch_size: int, seq_len: int) -> PyTree:
        z = np.zeros((microbatch_size, seq_len), np.int32)
        return {"tokens": z, "labels": z, "positions": z}

    def split_microbatch(self, mb: PyTree) -> tuple[PyTree, PyTree, PyTree]:
        return (
            mb["tokens"],
            {"positions": mb["positions"]},
            {"labels": mb["labels"]},
        )

    def stage_forward(
        self, module: nn.Module, params: PyTree, carry: PyTree, kwargs: PyTree
    ) -> PyTree:
        return module.apply(params, carry, kwargs["positions"])

    def last_stage_loss(self, module, params, carry, kwargs, state):
        per_token = module.apply(
            params, carry, kwargs["positions"], state["labels"]
        )
        valid = (state["labels"] != LM_IGNORE_INDEX).astype(jnp.float32)
        return per_token.sum(), valid.sum(), {"tokens": valid.sum()}

    def stage_init(self, module, rng, carry, kwargs, state, is_last):
        if is_last:
            return module.init(
                rng, carry, kwargs["positions"], state["labels"]
            )
        return module.init(rng, carry, kwargs["positions"])
