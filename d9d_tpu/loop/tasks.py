"""Built-in task implementations."""

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from d9d_tpu.core.types import Array, PyTree
from d9d_tpu.loop.control.task import TrainTask
from d9d_tpu.ops import LM_IGNORE_INDEX


class CausalLMTask(TrainTask):
    """Next-token prediction with token-count loss weighting.

    Equivalent of the reference example's SFT task
    (example/qwen3_moe/pretrain.py): expects batches with ``input_ids``
    [B, T+1] (and optional ``loss_mask`` [B, T+1]); shifts internally.
    The model must be a CausalLM returning per-token loss given
    (tokens, positions, labels).
    """

    def prepare_batch(self, batch: PyTree) -> PyTree:
        input_ids = np.asarray(batch["input_ids"])
        tokens = input_ids[:, :-1]
        labels = input_ids[:, 1:].copy()
        if "loss_mask" in batch:
            labels = np.where(
                np.asarray(batch["loss_mask"])[:, 1:] != 0, labels, LM_IGNORE_INDEX
            )
        b, t = tokens.shape
        positions = np.broadcast_to(np.arange(t, dtype=np.int32), (b, t)).copy()
        return {"tokens": tokens, "labels": labels, "positions": positions}

    def loss_fn(
        self, module: nn.Module, params: PyTree, mb: PyTree, rng: Array
    ) -> tuple[Array, Array, dict[str, Array]]:
        per_token = module.apply(params, mb["tokens"], mb["positions"], mb["labels"])
        valid = (mb["labels"] != LM_IGNORE_INDEX).astype(jnp.float32)
        loss_sum = per_token.sum()
        weight = valid.sum()
        return loss_sum, weight, {"tokens": weight}
