"""Typed event bus + train/inference event catalogue.

Reference: d9d/loop/event/core.py:25 (EventBus with ``bounded()`` pre/post
context manager) and event/catalogue/train.py. Components subscribe to
lifecycle events; user code can hook e.g. STEP_POST for custom logging
without touching the trainer. Events are plain frozen descriptors; the bus
is synchronous (handlers run inline, deterministic order).
"""

import contextlib
import dataclasses
from collections import defaultdict
from collections.abc import Callable
from typing import Any


@dataclasses.dataclass(frozen=True)
class Event:
    """A named lifecycle point. ``bounded`` events exist as .pre/.post."""

    name: str

    def __repr__(self):
        return f"Event({self.name})"


@dataclasses.dataclass(frozen=True)
class BoundedEvent:
    name: str

    @property
    def pre(self) -> Event:
        return Event(f"{self.name}.pre")

    @property
    def post(self) -> Event:
        return Event(f"{self.name}.post")


class EventBus:
    def __init__(self):
        self._handlers: dict[Event, list[Callable[..., None]]] = defaultdict(list)

    def subscribe(self, event: Event, handler: Callable[..., None]) -> None:
        self._handlers[event].append(handler)

    def unsubscribe(self, event: Event, handler: Callable[..., None]) -> None:
        self._handlers[event].remove(handler)

    def emit(self, event: Event, /, **payload: Any) -> None:
        for handler in list(self._handlers.get(event, ())):
            handler(**payload)

    @contextlib.contextmanager
    def bounded(self, event: BoundedEvent, /, **payload: Any):
        """Emit ``event.pre``, run the body, emit ``event.post`` (post fires
        only on success — an exception propagates without the post event,
        matching the reference's bounded() semantics)."""
        self.emit(event.pre, **payload)
        yield
        self.emit(event.post, **payload)


# -- catalogue (reference loop/event/catalogue/train.py) ----------------

EVENT_TRAIN_CONFIG_STARTED = Event("train.config_started")
EVENT_DATA_LOADER_READY = Event("train.data_loader_ready")
EVENT_MODEL_READY = Event("train.model_ready")
EVENT_OPTIMIZER_READY = Event("train.optimizer_ready")
EVENT_LR_SCHEDULER_READY = Event("train.lr_scheduler_ready")
EVENT_TRAIN_READY = Event("train.ready")
EVENT_TRAIN_FINISHED = Event("train.finished")

EVENT_STEP = BoundedEvent("train.step")
EVENT_FORWARD_BACKWARD = BoundedEvent("train.forward_backward")
EVENT_OPTIMIZER_STEP = BoundedEvent("train.optimizer_step")
EVENT_CHECKPOINT = BoundedEvent("train.checkpoint")
EVENT_SLEEP = BoundedEvent("train.sleep")
EVENT_WAKE = BoundedEvent("train.wake")

EVENT_INFER_CONFIG_STARTED = Event("infer.config_started")
EVENT_INFER_READY = Event("infer.ready")
EVENT_INFER_FINISHED = Event("infer.finished")
EVENT_INFER_BATCH = BoundedEvent("infer.batch")
