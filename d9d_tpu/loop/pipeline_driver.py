"""Pipeline-parallel training engine: builds per-stage models over the pp
axis and drives the schedule executor as the Trainer's step backend.

Reference: d9d/loop/run/train.py:251 (Trainer stepping through
``schedule.step``), d9d/loop/component/model_stage_factory.py:187
(per-stage module build) and d9d/pipelining/factory/factory.py:92
(schedule assembly). The TPU composition: each pipeline stage is an SPMD
program over its pp-rank's submesh (fsdp/tp/ep shardings apply per stage
unchanged), the executor moves carries between submeshes, and a
``PipelinedOptimizer`` steps the disjoint per-stage parameter groups.

Stage input shapes are inferred by chaining ``jax.eval_shape`` through the
task's ``stage_forward`` (the reference's meta-device
``infer_stage_inputs_from_pipeline_inputs`` protocol, module/model/*/model.py).
"""

import logging

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from d9d_tpu.core import compat
from d9d_tpu.core.mesh import MeshContext
from d9d_tpu.core.types import PyTree
from d9d_tpu.loop.components.batch_maths import BatchMaths
from d9d_tpu.loop.control.providers import ModelProvider
from d9d_tpu.loop.control.task import PipelineTrainTask
from d9d_tpu.loop.model_factory import init_sharded_from_fn
from d9d_tpu.pipelining import (
    FusedPipelineExecutor,
    PipelineScheduleExecutor,
    PipelineStageInfo,
    PipelineStageRuntime,
)
from d9d_tpu.pipelining.factory import (
    GPipeScheduleConfig,
    PipelineScheduleConfig,
    build_program_builder,
)
from d9d_tpu.pipelining.program import add_communication_ops
from d9d_tpu.pipelining.training import PipelinedOptimizer

logger = logging.getLogger("d9d_tpu.pipeline")


def _zeros_like_sdt(tree: PyTree) -> PyTree:
    return jax.tree.map(lambda s: np.zeros(s.shape, s.dtype), tree)


def _deep_merge(trees: list[PyTree]) -> PyTree:
    """Merge disjoint-leaved nested dicts (stage param trees → full model)."""
    out: dict = {}
    for tree in trees:
        stack = [(out, tree)]
        while stack:
            dst, src = stack.pop()
            for k, v in src.items():
                if isinstance(v, dict):
                    stack.append((dst.setdefault(k, {}), v))
                elif k in dst:
                    raise ValueError(f"stage param trees collide on key {k!r}")
                else:
                    dst[k] = v
    return out


def _key_to_host(key):
    """RNG key → (host ndarray, key impl | None). Keys must cross mesh
    boundaries as host data: a device-resident key carries its mesh in the
    sharding type, and cannot be fetched/closed over when that mesh spans
    processes. ``impl`` is None for old-style raw uint32 keys."""
    if jnp.issubdtype(key.dtype, jax.dtypes.prng_key):
        return np.asarray(jax.random.key_data(key)), jax.random.key_impl(key)
    return np.asarray(key), None


def _key_from_host(data, impl):
    """Inverse of ``_key_to_host`` (traceable — usable inside jit)."""
    arr = jnp.asarray(data)
    return jax.random.wrap_key_data(arr, impl=impl) if impl is not None else arr


def _put_key_replicated(key, submesh) -> jax.Array:
    """Commit an RNG key to a stage submesh, replicated — staged through
    the host (see ``_key_to_host``)."""
    sharding = NamedSharding(submesh, P())
    data, impl = _key_to_host(key)
    put = jax.device_put(data, sharding)
    return (
        jax.random.wrap_key_data(put, impl=impl) if impl is not None else put
    )


def build_pipeline_stages(
    *,
    ctx: MeshContext,
    builder,
    model_provider: ModelProvider,
    task,
    microbatch_size: int,
    seq_len: int,
    init_rng: jax.Array,
    grad_dtype=jnp.float32,
    residual_policy: str = "remat",
    stage_params: dict[int, PyTree] | None = None,
) -> dict[int, PipelineStageRuntime]:
    """Per-stage modules/params/runtimes over the pp submeshes (shared by
    the train and inference engines).

    ``stage_params`` supplies pre-built parameter trees (checkpoint
    scoring, trainer hand-off) — those stages skip the sharded random
    init entirely."""
    num_stages = builder.num_stages
    stage_owner = builder.stage_owner
    plan = model_provider.build_plan(ctx)
    sample_mb = task.sample_microbatch(microbatch_size, seq_len)
    carry, kwargs_s, state_s = task.split_microbatch(sample_mb)
    carry_sdt = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype),
        carry,
    )

    stages: dict[int, PipelineStageRuntime] = {}
    for s in range(num_stages):
        info = PipelineStageInfo(stage_index=s, num_stages=num_stages)
        module = model_provider.build_module(info)
        submesh = ctx.stage_mesh(stage_owner[s])
        # the stage's init key stays HOST data and is materialized inside
        # the jit: a device-resident key would (a) carry the ambient full
        # mesh in its sharding and poison the submesh-scoped init jit, and
        # (b) be un-fetchable as a closed-over constant when the submesh
        # spans multiple processes
        rng_host, rng_impl = _key_to_host(jax.random.fold_in(init_rng, s))
        carry_zero = _zeros_like_sdt(carry_sdt)

        def raw_init(
            module=module, rng_host=rng_host, rng_impl=rng_impl,
            carry=carry_zero, last=info.is_last,
        ):
            rng = _key_from_host(rng_host, rng_impl)
            return task.stage_init(module, rng, carry, kwargs_s, state_s, last)

        if stage_params is not None and s in stage_params:
            params = stage_params[s]
        else:
            with compat.set_mesh(submesh):
                params, _ = init_sharded_from_fn(raw_init, submesh, plan)

        data_spec = P(ctx.batch_axes, ctx.sequence_axes)
        stages[s] = PipelineStageRuntime(
            info=info,
            module=module,
            params=params,
            task=task,
            carry_sharding=NamedSharding(submesh, data_spec),
            kwargs_sharding=NamedSharding(submesh, data_spec),
            state_sharding=NamedSharding(submesh, data_spec),
            grad_dtype=grad_dtype,
            mesh=submesh,
            residual_policy=residual_policy,
        )

        if not info.is_last:
            # chain shapes: this stage's output is the next stage's carry
            carry_sdt = jax.eval_shape(
                lambda p, c, kw, module=module: task.stage_forward(
                    module, p, c, kw
                ),
                params,
                carry_sdt,
                kwargs_s,
            )
    return stages


class PipelineTrainEngine:
    """Owns stages, program, executor, and per-stage optimizer state."""

    def __init__(
        self,
        *,
        ctx: MeshContext,
        schedule: PipelineScheduleConfig | None,
        model_provider: ModelProvider,
        task: PipelineTrainTask,
        optimizer,
        batch_maths: BatchMaths,
        seq_len: int,
        init_rng: jax.Array,
        max_grad_norm: float | None = 1.0,
        grad_dtype=jnp.float32,
        peft_method=None,
        anomaly_policy: str | None = None,
        zero_sharding: bool = False,
        numerics: bool = False,
    ):
        if not isinstance(task, PipelineTrainTask):
            raise TypeError(
                "pipeline parallelism needs a PipelineTrainTask (the task "
                "defines the stage carry decomposition); got "
                f"{type(task).__name__}"
            )
        from d9d_tpu.resilience.anomaly import ANOMALY_POLICIES

        if anomaly_policy is not None and anomaly_policy not in ANOMALY_POLICIES:
            # same check as build_train_step: a typo must not silently
            # downgrade freeze protection to warn-only
            raise ValueError(
                f"anomaly_policy must be one of {ANOMALY_POLICIES} or "
                f"None, got {anomaly_policy!r}"
            )
        self.ctx = ctx
        self.task = task
        self.peft_method = peft_method
        self.num_microbatches = batch_maths.num_microbatches

        builder = build_program_builder(
            schedule if schedule is not None else GPipeScheduleConfig(),
            pp=ctx.pp_size,
        )
        self.num_stages = builder.num_stages
        self.stage_owner = builder.stage_owner
        self._style = builder.style

        self.stages = build_pipeline_stages(
            ctx=ctx,
            builder=builder,
            model_provider=model_provider,
            task=task,
            microbatch_size=batch_maths.microbatch_size,
            seq_len=seq_len,
            init_rng=init_rng,
            grad_dtype=grad_dtype,
            residual_policy=getattr(
                schedule, "residual_policy", "remat"
            )
            if schedule is not None
            else "remat",
        )

        if peft_method is not None:
            # per-stage reparameterization: rt.params becomes the stage's
            # adapter tree, the frozen base closes over the stage task
            # (reference trainable-predicate PEFT, model_stage_factory.py:25)
            from d9d_tpu.peft import PeftStageTask

            for s, rt in self.stages.items():
                submesh = ctx.stage_mesh(self.stage_owner[s])
                rng_s = _put_key_replicated(
                    jax.random.fold_in(init_rng, 10_000 + s), submesh
                )
                with compat.set_mesh(submesh):
                    base, adapters = peft_method.inject(rt.params, rng_s)
                rt.params = adapters
                rt.task = PeftStageTask(task, peft_method, base)

        program = add_communication_ops(
            builder.compose(self.num_microbatches),
            num_stages=self.num_stages,
            stage_owner=self.stage_owner,
        )
        # "fused" (default): the compiled-run executor — a handful of
        # device-resident programs per step. "legacy" keeps the
        # per-action interpreter as the bit-exact parity oracle for one
        # release (runtime/fused.py documents the contract).
        self._runtime = getattr(schedule, "runtime", "fused")
        if self._runtime == "fused":
            self.executor = FusedPipelineExecutor(
                stages=self.stages,
                program=program,
                stage_owner=self.stage_owner,
                num_microbatches=self.num_microbatches,
                train=True,
                numerics=numerics,
            )
        else:
            self.executor = PipelineScheduleExecutor(
                stages=self.stages,
                program=program,
                stage_owner=self.stage_owner,
                num_microbatches=self.num_microbatches,
                train=True,
            )
        self._eval_executor = None
        self.anomaly_policy = anomaly_policy
        from d9d_tpu.core.mesh import AXIS_DP_REPLICATE

        self.optimizer = PipelinedOptimizer(
            optimizer=optimizer,
            scalar_shardings={
                s: NamedSharding(ctx.stage_mesh(self.stage_owner[s]), P())
                for s in range(self.num_stages)
            },
            max_grad_norm=max_grad_norm,
            anomaly_freeze=anomaly_policy in ("skip_step", "rollback"),
            # ZeRO over dp_replicate: every stage submesh carries the
            # dp_r axis (stage meshes keep the full non-pp vocabulary)
            zero_axis=AXIS_DP_REPLICATE if zero_sharding else None,
        )
        self.opt_states = self.optimizer.init(
            {s: rt.params for s, rt in self.stages.items()}
        )
        # anomaly-guard device carry ([streak, total] on the last stage);
        # None when the guard is off
        self._guard_state = (
            self.optimizer.init_guard_state()
            if anomaly_policy is not None
            else None
        )
        # per-stage numerics plane (telemetry/numerics.py): specs name
        # each stage's rows for the trainer's host decode; the stats
        # executables live on the PipelinedOptimizer and only dispatch
        # on cadence steps (step(numerics=True))
        self.numerics = numerics
        self.numerics_specs: dict[int, Any] = {}
        if numerics:
            from d9d_tpu.telemetry.numerics import build_param_spec

            self.numerics_specs = {
                s: build_param_spec(rt.params)
                for s, rt in self.stages.items()
            }
        logger.info(
            "pipeline engine: %d stages over pp=%d (%s), %d microbatches",
            self.num_stages,
            ctx.pp_size,
            type(builder).__name__,
            self.num_microbatches,
        )

    # ------------------------------------------------------------------

    def eval_loss(self, microbatches: list[PyTree]):
        """Forward-only pass through an inference program → mean loss.

        Reference parity: loop/run/inference.py:55,176 drives the
        forward-only schedule from the loop; here the same stages are
        reused under a lazily-built ``InferenceProgramBuilder`` executor.
        """
        if self._eval_executor is None:
            from d9d_tpu.pipelining.program import InferenceProgramBuilder

            builder = InferenceProgramBuilder(
                self.ctx.pp_size,
                stages_per_rank=self.num_stages // self.ctx.pp_size,
            )
            # keep the training topology (loop vs V zig-zag) so stage→rank
            # ownership matches the already-built stages
            builder.style = self._style
            program = add_communication_ops(
                builder.compose(self.num_microbatches),
                num_stages=self.num_stages,
                stage_owner=self.stage_owner,
            )
            executor_cls = (
                FusedPipelineExecutor
                if self._runtime == "fused"
                else PipelineScheduleExecutor
            )
            self._eval_executor = executor_cls(
                stages=self.stages,
                program=program,
                stage_owner=self.stage_owner,
                num_microbatches=self.num_microbatches,
                train=False,
            )
        result = self._eval_executor.step(microbatches)
        with compat.set_mesh(self.ctx.stage_mesh(self.stage_owner[self.num_stages - 1])):
            return result.loss_sum / jnp.maximum(result.weight_sum, 1e-8)

    def step(
        self,
        microbatches: list[PyTree],
        *,
        numerics: bool = False,
        timeline: bool = False,
    ) -> dict:
        """One optimizer step over the microbatch list → device metrics.

        ``numerics=True`` (cadence steps only, trainer-driven) dispatches
        one per-stage stats executable BEFORE the optimizer update (the
        update donates params/grads/opt_state buffers) and folds the
        flat vectors into the metric dict as ``numerics/s{S}`` —
        off-cadence steps add zero dispatches to the controller loop.

        ``timeline=True`` (fused runtime only, trainer cadence
        ``pp_timeline_every_steps``) serializes the fused dispatch loop
        to attribute per-run wall and restore the ``pp/s{S}/*``
        busy/bubble gauges; the legacy interpreter already attributes on
        every step, so the flag is dropped there.
        """
        if self._runtime == "fused":
            kwargs: dict = {"timeline": timeline}
            if self.numerics:
                # the stats assembly is traced INTO each rank's last
                # fused program behind a cond flag, so the program
                # signature is fixed: the second-moment trees ride along
                # every step (a host-side tree selection, no dispatch),
                # and off-cadence steps compute a NaN fill instead of
                # the stats
                from d9d_tpu.telemetry.numerics import find_second_moments

                moments = {
                    s: find_second_moments(self.opt_states[s], rt.params)
                    for s, rt in self.stages.items()
                }
                kwargs.update(
                    numerics_on=numerics, numerics_moments=moments
                )
            result = self.executor.step(microbatches, **kwargs)
        else:
            result = self.executor.step(microbatches)
        params = {s: rt.params for s, rt in self.stages.items()}
        numerics_metrics = {}
        if numerics and self.numerics:
            if self._runtime == "fused":
                for s in sorted(result.numerics):
                    numerics_metrics[f"numerics/s{s}"] = result.numerics[s]
            else:
                for s in sorted(params):
                    numerics_metrics[f"numerics/s{s}"] = (
                        self.optimizer.stage_numerics(
                            s, params[s], result.grads[s], self.opt_states[s]
                        )
                    )
        guard_metrics = {}
        if self.anomaly_policy is not None:
            (new_params, self.opt_states, grad_norm, guard_metrics,
             self._guard_state) = self.optimizer.step_guarded(
                params, self.opt_states, result.grads, result.weight_sum,
                result.loss_sum, self._guard_state,
            )
        else:
            new_params, self.opt_states, grad_norm = self.optimizer.step(
                params, self.opt_states, result.grads, result.weight_sum
            )
        for s, rt in self.stages.items():
            rt.params = new_params[s]
        with compat.set_mesh(self.ctx.stage_mesh(self.stage_owner[self.num_stages - 1])):
            inv_w = 1.0 / jnp.maximum(result.weight_sum, 1e-8)
            loss = result.loss_sum * inv_w
        return {
            "loss": loss,
            "grad_norm": grad_norm,
            "loss_weight": result.weight_sum,
            **guard_metrics,
            **numerics_metrics,
            **{f"task/{k}": v for k, v in result.metrics.items()},
        }

    def reset_guard(self) -> None:
        """Zero the anomaly carry (trainer rollback path)."""
        if self.anomaly_policy is not None:
            self._guard_state = self.optimizer.init_guard_state()

    # -- state surface for checkpoint/export ---------------------------

    def job_arrays(self) -> PyTree:
        return {
            "params": {str(s): rt.params for s, rt in self.stages.items()},
            "opt_state": {str(s): v for s, v in self.opt_states.items()},
        }

    def load_job_arrays(self, arrays: PyTree) -> None:
        for s, rt in self.stages.items():
            rt.params = arrays["params"][str(s)]
        self.opt_states = {
            s: arrays["opt_state"][str(s)] for s in self.stages
        }

    def merged_params(self) -> PyTree:
        """Full model parameter tree (stage trees are key-disjoint by
        design: layers are named by global id). Under PEFT, adapters are
        folded into each stage's frozen base first."""
        if self.peft_method is None:
            return _deep_merge([rt.params for rt in self.stages.values()])
        merged = []
        for rt in self.stages.values():
            with compat.set_mesh(rt.mesh):
                merged.append(self.peft_method.merge(rt.task.base, rt.params))
        return _deep_merge(merged)


class PipelineInferenceEngine:
    """Forward-only pipeline runner for the Inference loop.

    Reference: d9d/loop/run/inference.py:55,176 +
    pipelining/factory/config.py:6-78's inference schedule — per-stage
    modules over the pp submeshes, an ``InferenceProgramBuilder`` program,
    and the executor's eval path returning per-microbatch last-stage
    outputs.
    """

    def __init__(
        self,
        *,
        ctx: MeshContext,
        model_provider: ModelProvider,
        task,
        num_microbatches: int,
        microbatch_size: int,
        seq_len: int,
        init_rng: jax.Array,
        stages_per_rank: int = 1,
        stage_params: dict[int, PyTree] | None = None,
        runtime: str = "fused",
    ):
        from d9d_tpu.pipelining.program import InferenceProgramBuilder

        self.ctx = ctx
        self.num_microbatches = num_microbatches
        builder = InferenceProgramBuilder(ctx.pp_size, stages_per_rank)
        self.num_stages = builder.num_stages
        self.stage_owner = builder.stage_owner
        self.stages = build_pipeline_stages(
            ctx=ctx,
            builder=builder,
            model_provider=model_provider,
            task=task,
            microbatch_size=microbatch_size,
            seq_len=seq_len,
            init_rng=init_rng,
            stage_params=stage_params,
        )
        program = add_communication_ops(
            builder.compose(num_microbatches),
            num_stages=self.num_stages,
            stage_owner=self.stage_owner,
        )
        executor_cls = (
            FusedPipelineExecutor
            if runtime == "fused"
            else PipelineScheduleExecutor
        )
        self.executor = executor_cls(
            stages=self.stages,
            program=program,
            stage_owner=self.stage_owner,
            num_microbatches=num_microbatches,
            train=False,
        )

    def forward(self, microbatches: list[PyTree]) -> list[PyTree]:
        """→ per-microbatch last-stage outputs (device arrays)."""
        return self.executor.step(microbatches).outputs
