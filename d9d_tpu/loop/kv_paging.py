"""Host-side bookkeeping for the paged KV cache + prefix cache
(docs/design/generation.md, "Paged KV cache").

The device side of paging is deliberately dumb: a pool of fixed-size
pages per cache leaf plus a static-shape ``[B, max_pages]`` int32 page
table that the jitted decode step indexes through
(``nn/attention.py`` write/gather, ``ops/attention/pallas_decode.py``
block-index gather). Everything with policy in it — allocation, free
lists, reference counting, content-hashed prefix reuse, LRU eviction —
lives HERE, on the host, and only ever runs at the serving loop's
existing chunk boundaries (admit/retire), so the one-dispatch /
one-readback-per-K-tokens contract is untouched.

Page identity contract: page 0 is the reserved GARBAGE page — never
allocated, never freed. Idle/dead device rows have their page-table
rows pinned to 0 in-device (``loop/serve.py`` ``_pin_page_table``), so
a row that dies mid-chunk scribbles into the garbage page instead of a
page the allocator may have handed to someone else (or, worse, a
shared prefix page).

Prefix cache contract: an entry maps a CONTENT HASH CHAIN over
page-size token blocks of a prompt to the page run holding their KV.
KV at slot ``s`` depends only on tokens ``0..s`` (causal), so a page
fully covered by prompt tokens is reusable by any prompt sharing that
exact token prefix. Entries hold one reference on their page; a hit
adds a per-row reference (copy-on-write: readers share, every writer
appends into its OWN pages past the shared run). An entry only becomes
hit-eligible (``ready``) once its filling row's prompt feed has been
fully DISPATCHED — device execution is in dispatch order, so a later
request's reads are guaranteed to see the writes. Eviction is LRU over
ready leaf entries (deepest-suffix first), and only at admission
boundaries when the free list runs short.
"""

import dataclasses
import hashlib
from typing import Optional

import numpy as np

__all__ = ["PageAllocation", "PagedKVAllocator"]


@dataclasses.dataclass
class PageAllocation:
    """One row's page run, handed back by :meth:`PagedKVAllocator.admit`.

    ``start_pos`` is the first token index the serving loop must still
    feed (``hit_tokens`` prompt tokens are served from shared pages and
    skipped); ``pages[:n_shared]`` are the prefix-cache pages (read
    only for this row), the rest are freshly allocated and owned.
    """

    row: int
    rid: int
    pages: list
    n_shared: int
    hit_tokens: int

    @property
    def start_pos(self) -> int:
        return self.hit_tokens


@dataclasses.dataclass
class _PrefixEntry:
    key: bytes
    parent: Optional[bytes]
    page: int
    depth: int              # page index within the prompt (0-based)
    last_use: int
    ready: bool
    owner_rid: Optional[int]
    children: set = dataclasses.field(default_factory=set)


class PagedKVAllocator:
    """Free-list page allocator + refcounts + content-hashed prefix
    cache + the host mirror of the device page table.

    Deterministic by construction (explicit free-list order, a logical
    clock for LRU) so chaos/parity tests can assert exact behavior.
    """

    def __init__(
        self,
        *,
        num_pages: int,
        page_size: int,
        rows: int,
        max_pages_per_row: int,
        enable_prefix_cache: bool = True,
    ):
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        if num_pages < 2:
            raise ValueError(
                f"num_pages must be >= 2 (page 0 is the reserved garbage "
                f"page), got {num_pages}"
            )
        self.page_size = int(page_size)
        self.num_pages = int(num_pages)
        self.max_pages_per_row = int(max_pages_per_row)
        self.prefix_cache_enabled = bool(enable_prefix_cache)
        # pop() yields ascending ids on a fresh allocator; freed pages
        # return LIFO — both deterministic
        self._free = list(range(num_pages - 1, 0, -1))
        self._refs = np.zeros(num_pages, np.int64)
        # host mirror of the device table (page 0 everywhere = garbage)
        self.table = np.zeros((rows, max_pages_per_row), np.int32)
        self._row_alloc: dict[int, PageAllocation] = {}
        # rows whose requests retired while chunks were still in flight:
        # their pages stay held (the device row may still be live and
        # writing) until flush_deferred() at a clean boundary
        self._deferred: dict[int, PageAllocation] = {}
        # prefix cache
        self._entries: dict[bytes, _PrefixEntry] = {}
        self._filling: dict[int, list[bytes]] = {}
        # rid → chain keys, memoized across admission ATTEMPTS: a
        # head-of-line request blocked on pages is retried every chunk
        # boundary, and its hash chain depends only on its prompt —
        # O(prompt) hashing must not repeat per boundary. Dropped on
        # successful admit / abort / forget.
        self._key_memo: dict[int, list[bytes]] = {}
        self._clock = 0
        # counters (the batcher mirrors these into telemetry)
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.prefix_hit_tokens = 0
        self.peak_pages_in_use = 0

    # -- accounting ----------------------------------------------------

    @property
    def pages_free(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return (self.num_pages - 1) - len(self._free)

    def pages_free_after_flush(self) -> int:
        """Free pages counting those :meth:`flush_deferred` will free at
        the next clean boundary (refcount simulation: a deferred page
        frees iff deferred references are ALL that hold it — pages a
        prefix entry or live row still shares correctly stay). The
        admission-capacity view for callers deciding between boundaries."""
        pend: dict[int, int] = {}
        for alloc in self._deferred.values():
            for p in alloc.pages:
                pend[p] = pend.get(p, 0) + 1
        extra = sum(1 for p, n in pend.items() if self._refs[p] == n)
        return len(self._free) + extra

    def pages_needed(self, total_tokens: int) -> int:
        return -(-int(total_tokens) // self.page_size)

    def fits_ever(self, total_tokens: int) -> bool:
        """Could a request of this token footprint EVER be admitted
        (every page free, nothing cached)? Submit-time validation."""
        return self.pages_needed(total_tokens) <= self.num_pages - 1

    # -- page primitives -----------------------------------------------

    def _alloc_page(self) -> int:
        page = self._free.pop()
        self._refs[page] = 1
        return page

    def _incref(self, page: int) -> None:
        self._refs[page] += 1

    def _decref(self, page: int) -> None:
        if page == 0:
            raise AssertionError("decref of the reserved garbage page")
        self._refs[page] -= 1
        if self._refs[page] < 0:
            raise AssertionError(f"negative refcount on page {page}")
        if self._refs[page] == 0:
            self._free.append(page)

    # -- prefix hashing ------------------------------------------------

    def _chain_keys(self, prompt, n_blocks: int) -> list[bytes]:
        """Content-hash chain over the first ``n_blocks`` page-size
        token blocks: ``key_i = H(key_{i-1} || tokens[i·ps:(i+1)·ps])``
        — a hit on block i implies the whole prefix matched."""
        ps = self.page_size
        keys = []
        digest = b""
        for i in range(n_blocks):
            block = np.asarray(prompt[i * ps:(i + 1) * ps], np.int64)
            digest = hashlib.sha1(digest + block.tobytes()).digest()
            keys.append(digest)
        return keys

    # -- admission -----------------------------------------------------

    def admit(
        self, row: int, rid: int, prompt, total_tokens: int
    ) -> Optional[PageAllocation]:
        """Map a request onto pages: walk the prefix cache over the
        prompt's full page-size blocks (capped so at least one prompt
        token is still fed — the request needs the last prompt
        position's logits), allocate the rest, register the request's
        own full-prompt pages as filling prefix entries. Returns None
        (leaving the caller's queue untouched) when even LRU eviction
        cannot free enough pages THIS boundary."""
        if row in self._row_alloc:
            raise AssertionError(f"row {row} already has an allocation")
        ps = self.page_size
        need = self.pages_needed(total_tokens)
        if need > self.max_pages_per_row:
            raise ValueError(
                f"request needs {need} pages > max_pages_per_row="
                f"{self.max_pages_per_row}"
            )
        full_blocks = len(prompt) // ps
        keys: list[bytes] = []
        if self.prefix_cache_enabled:
            keys = self._key_memo.get(rid, [])
            if len(keys) != full_blocks:
                keys = self._chain_keys(prompt, full_blocks)
                self._key_memo[rid] = keys
        # cap: at least one prompt token must remain to be fed
        max_hit_blocks = (len(prompt) - 1) // ps
        hits = 0
        for i in range(min(max_hit_blocks, len(keys))):
            e = self._entries.get(keys[i])
            if e is None or not e.ready:
                break
            hits += 1
        # claim the hit run BEFORE any eviction: with the extra
        # reference the hit entries can never be this same admission's
        # eviction victims (rolled back if admission still fails)
        self._clock += 1
        pages = []
        for i in range(hits):
            e = self._entries[keys[i]]
            e.last_use = self._clock
            self._incref(e.page)
            pages.append(e.page)
        own_needed = need - hits
        if own_needed > len(self._free):
            self._evict_lru(own_needed - len(self._free))
        if own_needed > len(self._free):
            for p in pages:
                self._decref(p)
            return None
        pages.extend(self._alloc_page() for _ in range(own_needed))
        # register this prompt's own full blocks as filling entries
        if self.prefix_cache_enabled:
            for i in range(hits, full_blocks):
                if keys[i] in self._entries:
                    continue  # cached already (capped hit / race): keep it
                parent = keys[i - 1] if i > 0 else None
                self._entries[keys[i]] = _PrefixEntry(
                    key=keys[i], parent=parent, page=pages[i], depth=i,
                    last_use=self._clock, ready=False, owner_rid=rid,
                )
                self._incref(pages[i])
                if parent is not None and parent in self._entries:
                    self._entries[parent].children.add(keys[i])
                self._filling.setdefault(rid, []).append(keys[i])
        self.table[row, :] = 0
        self.table[row, : len(pages)] = pages
        self._key_memo.pop(rid, None)  # admitted: the memo served its job
        alloc = PageAllocation(
            row=row, rid=rid, pages=pages, n_shared=hits,
            hit_tokens=hits * ps,
        )
        self._row_alloc[row] = alloc
        if self.prefix_cache_enabled:
            if hits:
                self.prefix_hits += 1
                self.prefix_hit_tokens += hits * ps
            else:
                self.prefix_misses += 1
        self.peak_pages_in_use = max(self.peak_pages_in_use,
                                     self.pages_in_use)
        return alloc

    def mark_filled(self, rid: int) -> None:
        """The row's prompt feed has been fully dispatched: its filling
        prefix entries become hit-eligible (later dispatches execute
        after the writes)."""
        for key in self._filling.pop(rid, []):
            e = self._entries.get(key)
            if e is not None and e.owner_rid == rid:
                e.ready = True
                e.owner_rid = None

    def forget(self, rid: int) -> None:
        """Drop any admission-attempt memo for a request leaving the
        queue without admitting here (fleet ejection/migration)."""
        self._key_memo.pop(rid, None)

    def abort_filling(self, rid: int) -> None:
        """The filling row failed before its prompt was fully dispatched
        (deadline mid-prompt): its never-ready entries are dropped so a
        half-written page can never be hit."""
        self._key_memo.pop(rid, None)
        for key in self._filling.pop(rid, []):
            e = self._entries.get(key)
            if e is None or e.owner_rid != rid or e.ready:
                continue
            self._drop_entry(e)

    # -- release -------------------------------------------------------

    def release(self, row: int) -> None:
        """Free a row's page references NOW (the device row is dead —
        finished in-device, or the caller is at a clean boundary and
        will push a zeroed table row before the next dispatch)."""
        alloc = self._row_alloc.pop(row, None)
        if alloc is None:
            return
        for page in alloc.pages:
            self._decref(page)
        self.table[row, :] = 0

    def defer_release(self, row: int) -> None:
        """Retire a row whose device twin may still be LIVE (host-side
        deadline eviction with chunks in flight): zero the mirror row
        but keep the page references until :meth:`flush_deferred` at a
        clean boundary — the zombie keeps writing into its own
        still-held pages, never into someone else's."""
        alloc = self._row_alloc.pop(row, None)
        if alloc is None:
            return
        self._deferred[row] = alloc
        self.table[row, :] = 0

    def flush_deferred(self) -> bool:
        """At a clean boundary (no chunks in flight, the zeroed table
        about to be pushed): drop deferred rows' page references. The
        push reroutes any still-live zombie's writes to the garbage
        page, so the pages are safe to reuse. Returns True if anything
        was freed."""
        if not self._deferred:
            return False
        for alloc in self._deferred.values():
            for page in alloc.pages:
                self._decref(page)
        self._deferred.clear()
        return True

    # -- cross-replica shipment (disaggregated serving) ----------------

    def export_pages(self, rid: int) -> list:
        """Ordered page ids of ``rid``'s live row allocation,
        refcount-neutral (the caller only reads payloads; nothing moves
        or changes hands). Raises ``KeyError`` when the request holds
        no live row here — the caller falls back to re-prefill."""
        for alloc in self._row_alloc.values():
            if alloc.rid == rid:
                return list(alloc.pages)
        raise KeyError(f"rid {rid} has no live row allocation")

    def export_prefix(self, tokens) -> list:
        """Page ids of the leading READY prefix-cache run over
        ``tokens``' full page-size blocks (refcount-neutral). Chains are
        contiguous from block 0 by construction (leaf-first eviction),
        so the run is directly shippable block-by-block."""
        if not self.prefix_cache_enabled:
            return []
        full_blocks = len(tokens) // self.page_size
        pages = []
        for key in self._chain_keys(tokens, full_blocks):
            e = self._entries.get(key)
            if e is None or not e.ready:
                break
            pages.append(e.page)
        return pages

    def import_pages(self, tokens, n_blocks: int) -> Optional[list]:
        """Install the first ``n_blocks`` full page-size blocks of
        ``tokens`` as READY prefix entries backed by freshly allocated
        pages — the receiving half of a cross-replica shipment. Leading
        blocks already cached here are skipped (their payload is
        already on-device); a mid-chain entry another row is still
        FILLING stops the import early (never alias a page being
        written). Returns ``[(block_idx, dest_page), ...]`` for the
        blocks whose payloads the caller must copy into the device
        pool BEFORE the next dispatch that could hit them, or None
        when even LRU eviction cannot free enough pages — in which
        case the allocator is left untouched (no partial import)."""
        if not self.prefix_cache_enabled:
            return None
        ps = self.page_size
        n_blocks = min(int(n_blocks), len(tokens) // ps)
        if n_blocks <= 0:
            return []
        keys = self._chain_keys(tokens, n_blocks)
        self._clock += 1
        skip = 0
        for key in keys:
            e = self._entries.get(key)
            if e is None:
                break
            if not e.ready:
                return []  # filling mid-chain: nothing importable past it
            e.last_use = self._clock
            skip += 1
        need = n_blocks - skip
        if need > len(self._free):
            self._evict_lru(need - len(self._free))
        if need > len(self._free):
            return None
        placed = []
        for i in range(skip, n_blocks):
            page = self._alloc_page()
            parent = keys[i - 1] if i > 0 else None
            self._entries[keys[i]] = _PrefixEntry(
                key=keys[i], parent=parent, page=page, depth=i,
                last_use=self._clock, ready=True, owner_rid=None,
            )
            if parent is not None and parent in self._entries:
                self._entries[parent].children.add(keys[i])
            placed.append((i, page))
        self.peak_pages_in_use = max(self.peak_pages_in_use,
                                     self.pages_in_use)
        return placed

    # -- invalidation --------------------------------------------------

    def invalidate_prefix_cache(self) -> int:
        """Drop EVERY prefix entry — cached KV is weights-dependent, so
        a live weight publish makes all of it stale (and a row mid-fill
        finishes its fill under the NEW weights, so its pending entries
        would be mixed-generation: those go too). Row page mappings are
        untouched: in-flight requests keep their pages and finish on
        the cache they built, exactly like contiguous rows complete on
        the weights their chunks were dispatched with. Returns the
        number of entries dropped."""
        n = len(self._entries)
        for e in list(self._entries.values()):
            self._drop_entry(e)
        self._filling.clear()
        self._key_memo.clear()
        return n

    # -- eviction ------------------------------------------------------

    def _drop_entry(self, e: _PrefixEntry) -> None:
        self._entries.pop(e.key, None)
        if e.parent is not None and e.parent in self._entries:
            self._entries[e.parent].children.discard(e.key)
        self._decref(e.page)

    def _evict_lru(self, shortfall: int) -> int:
        """Evict ready LEAF entries (no cached children — deeper
        suffixes go first, so a chain never dangles) in LRU order until
        ``shortfall`` pages came FREE or nothing evictable remains.
        Only entries that are the SOLE holder of their page qualify:
        evicting one whose page live rows still share would free
        nothing now and destroy a warm cache line for no benefit.

        Heap-ordered, one pass: popping a non-leaf discards it, but an
        evicted child re-pushes its parent, so the parent is
        reconsidered exactly when it may have become evictable —
        O((entries + evictions)·log entries) per blocked admission,
        not O(entries × shortfall)."""
        import heapq

        freed = 0
        heap = [
            (e.last_use, -e.depth, e.key)
            for e in self._entries.values() if e.ready
        ]
        heapq.heapify(heap)
        while freed < shortfall and heap:
            _, _, key = heapq.heappop(heap)
            e = self._entries.get(key)
            if e is None or not e.ready:
                continue  # stale (already evicted) or still filling
            if e.children & self._entries.keys():
                continue  # not a leaf now; a child's eviction re-pushes
            if self._refs[e.page] != 1:
                continue  # shared with live rows: evicting frees nothing
            parent_key = e.parent
            before = len(self._free)
            self._drop_entry(e)
            freed += len(self._free) - before
            if parent_key is not None:
                pe = self._entries.get(parent_key)
                if pe is not None and pe.ready:
                    heapq.heappush(
                        heap, (pe.last_use, -pe.depth, pe.key)
                    )
        return freed

    # -- invariants (tests) --------------------------------------------

    def check_invariants(self) -> None:
        refs = np.zeros(self.num_pages, np.int64)
        for alloc in self._row_alloc.values():
            for p in alloc.pages:
                refs[p] += 1
        for alloc in self._deferred.values():
            for p in alloc.pages:
                refs[p] += 1
        for e in self._entries.values():
            refs[e.page] += 1
        assert refs[0] == 0, "garbage page must never be referenced"
        if not np.array_equal(refs, self._refs):
            raise AssertionError(
                f"refcount drift: recomputed {refs.tolist()} != "
                f"tracked {self._refs.tolist()}"
            )
        free = set(self._free)
        assert len(free) == len(self._free), "duplicate pages in free list"
        for p in range(1, self.num_pages):
            held = self._refs[p] > 0
            assert held != (p in free), (
                f"page {p}: refs={self._refs[p]} free={p in free}"
            )
        for row, alloc in self._row_alloc.items():
            got = [int(x) for x in self.table[row] if x != 0]
            assert got == list(alloc.pages), (
                f"row {row} table/alloc mismatch: {got} != {alloc.pages}"
            )
