"""User-facing task protocol.

Reference: d9d/loop/control/task.py:180 (``TrainTask``) — the user supplies
(1) host-side batch preparation and (2) the per-microbatch loss. The TPU
redesign makes ``loss_fn`` a *pure* function (params in, loss out) so the
engine can jit/scan/pipeline it freely; the weighted-loss contract is the
reference's: return (loss_sum, weight), the engine divides by the global
Σweight after accumulation (loop/component/gradient_manager.py:16).
"""

import abc
from typing import Any

import flax.linen as nn

from d9d_tpu.core.types import Array, PyTree


class TrainTask(abc.ABC):
    """Defines what is being optimized, independent of how it is parallelized."""

    @abc.abstractmethod
    def prepare_batch(self, batch: PyTree) -> PyTree:
        """Host-side: raw loader batch → device-ready pytree of arrays.

        Runs outside jit (numpy ok). The result's leading dim is the global
        batch; the engine splits it into microbatches.
        """

    @abc.abstractmethod
    def loss_fn(
        self,
        module: nn.Module,
        params: PyTree,
        microbatch: PyTree,
        rng: Array,
    ) -> tuple[Array, Array, dict[str, Array]]:
        """Pure: → (loss_sum, weight, metrics). Runs under jit.

        ``loss_sum`` is the *sum* of per-example losses in this microbatch;
        ``weight`` its total weight (e.g. unmasked token count). The engine
        computes grads of Σ loss_sum and scales by 1/Σ weight — sum-then-
        scale, not mean-of-means.
        """

    def metrics_postprocess(self, metrics: dict[str, Any]) -> dict[str, Any]:
        """Optional host-side metric transformation before logging."""
        return metrics

    # -- structured metrics (reference loop/control/task.py metric surface,
    #    collected through loop/components/metric_collector.py) -----------

    def metrics(self) -> dict[str, Any]:
        """Metric objects (d9d_tpu.metric.Metric) this task maintains.

        Raw statistics returned by ``loss_fn``'s metric dict accumulate on
        device between log steps; ``update_metrics`` receives their
        window sums and feeds these objects.
        """
        return {}

    def update_metrics(
        self, metric_objs: dict[str, Any], stats: dict[str, Any]
    ) -> None:
        """Feed windowed host statistics into ``metrics()`` objects."""


class PipelineTrainTask(TrainTask):
    """A TrainTask that can also drive a pipeline-parallel schedule.

    Adds the per-stage decomposition the pipeline executor needs (the
    ``StageTask`` surface of d9d_tpu.pipelining.runtime.stage, mirroring
    the reference's TrainTask + LossComputer split, loop/control/task.py:180
    + component/pipeline_result_processing.py:18): what part of a
    microbatch flows stage-to-stage (the carry), what every stage needs
    (kwargs), what only the loss needs (state), and how the last stage
    turns activations into a weighted loss.
    """

    @abc.abstractmethod
    def sample_microbatch(self, microbatch_size: int, seq_len: int) -> PyTree:
        """Zero-filled microbatch matching ``prepare_batch``'s output
        structure — drives stage shape inference and parameter init."""

    @abc.abstractmethod
    def split_microbatch(
        self, microbatch: PyTree
    ) -> tuple[PyTree, PyTree, PyTree]:
        """→ (first_stage_carry, per_stage_kwargs, last_stage_state)."""

    @abc.abstractmethod
    def stage_forward(
        self, module: nn.Module, params: PyTree, carry: PyTree, kwargs: PyTree
    ) -> PyTree:
        """Non-last stage: carry in → carry out."""

    @abc.abstractmethod
    def last_stage_loss(
        self,
        module: nn.Module,
        params: PyTree,
        carry: PyTree,
        kwargs: PyTree,
        state: PyTree,
    ) -> tuple[Array, Array, dict[str, Array]]:
        """Last stage: → (loss_sum, weight, metrics)."""

    @abc.abstractmethod
    def stage_init(
        self,
        module: nn.Module,
        rng: Array,
        carry: PyTree,
        kwargs: PyTree,
        state: PyTree,
        is_last: bool,
    ) -> PyTree:
        """Initialize one stage's variables (must trace the same module
        call graph as ``stage_forward``/``last_stage_loss``)."""
