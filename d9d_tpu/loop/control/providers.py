"""Provider protocols wiring user code into the trainer.

Reference: d9d/loop/control/{model_provider.py:97, dataset_provider.py:41,
optimizer_provider} — providers keep the loop generic over model family,
data source, and optimizer.
"""

import abc
from collections.abc import Iterable

import flax.linen as nn
import optax

from d9d_tpu.core.mesh import MeshContext
from d9d_tpu.core.types import PyTree
from d9d_tpu.parallel.plan import ParallelPlan
from d9d_tpu.pipelining import PipelineStageInfo


class ModelProvider(abc.ABC):
    """Builds (stage-aware) model modules and their parallelism plan."""

    @abc.abstractmethod
    def build_module(self, stage: PipelineStageInfo) -> nn.Module: ...

    @abc.abstractmethod
    def build_plan(self, ctx: MeshContext) -> ParallelPlan: ...

    @abc.abstractmethod
    def sample_inputs(self, batch_size: int, seq_len: int) -> tuple:
        """Abstract sample inputs for shape/param initialization."""


class DatasetProvider(abc.ABC):
    @abc.abstractmethod
    def build(self) -> Iterable[PyTree]:
        """Yield raw (host) batches of the *global* batch size."""

    def __len__(self) -> int:
        raise NotImplementedError


class OptimizerProvider(abc.ABC):
    @abc.abstractmethod
    def build(
        self, learning_rate: optax.ScalarOrSchedule
    ) -> optax.GradientTransformation: ...


class AdamWProvider(OptimizerProvider):
    def __init__(
        self,
        b1: float = 0.9,
        b2: float = 0.95,
        eps: float = 1e-8,
        weight_decay: float = 0.0,
    ):
        self.b1, self.b2, self.eps, self.weight_decay = b1, b2, eps, weight_decay

    def build(self, learning_rate) -> optax.GradientTransformation:
        return optax.adamw(
            learning_rate,
            b1=self.b1,
            b2=self.b2,
            eps=self.eps,
            weight_decay=self.weight_decay,
        )
