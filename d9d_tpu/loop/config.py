"""Trainer configuration (reference: d9d/loop/config/config.py:169)."""

import pydantic


class TrainerConfig(pydantic.BaseModel):
    model_config = pydantic.ConfigDict(extra="forbid")

    global_batch_size: int
    microbatch_size: int
    seq_len: int
    total_steps: int
    learning_rate: float = 3e-4
    max_grad_norm: float | None = 1.0
    seed: int = 0
    log_every: int = 10


class InferenceConfig(pydantic.BaseModel):
    model_config = pydantic.ConfigDict(extra="forbid")

    batch_size: int
    seq_len: int
    seed: int = 0
