"""Trainer configuration (reference: d9d/loop/config/config.py:169).

Flat pydantic config; sub-knob groups (checkpoint/profile/watchdog/gc)
default to off so the minimum slice stays one-screen simple.
"""

from typing import Literal

import pydantic

from d9d_tpu.pipelining.factory import PipelineScheduleConfig


class TrainerConfig(pydantic.BaseModel):
    model_config = pydantic.ConfigDict(extra="forbid")

    global_batch_size: int
    microbatch_size: int
    seq_len: int
    total_steps: int

    # pipeline schedule; used when the mesh has pp > 1
    # (reference: d9d/pipelining/factory/config.py:70 discriminated union)
    pipeline: PipelineScheduleConfig | None = None
    learning_rate: float = 3e-4
    max_grad_norm: float | None = 1.0
    seed: int = 0
    log_every: int = 10
    run_name: str | None = None

    # checkpoint/resume (reference component/checkpointer.py:27)
    checkpoint_dir: str | None = None
    checkpoint_every_steps: int | None = None
    checkpoints_to_keep: int | None = 3
    # async save: orbax snapshots device arrays to host synchronously
    # (safe against the train step's donated buffers) and writes to disk
    # in the background, keeping checkpoint IO off the step path
    checkpoint_async: bool = True
    resume: bool = True

    # profiling (reference component/job_profiler.py:13)
    profile_dir: str | None = None
    profile_every_steps: int | None = None
    profile_active_steps: int = 3
    profile_wait_steps: int = 10

    # hang watchdog (reference component/timeout_manager.py:15); the exit
    # code distinguishes a watchdog kill from a crash for the scheduler
    # (docs/design/resilience.md exit-code contract)
    init_timeout_s: float | None = None
    step_timeout_s: float | None = None
    watchdog_exit_code: int = 42

    # resilience (docs/design/resilience.md): step anomaly guard.
    # None = guard compiled out entirely (seed behavior). "warn" flags
    # non-finite steps, "skip_step" additionally freezes params and
    # optimizer moments for anomalous steps in-device, "rollback"
    # restores the newest intact checkpoint after `anomaly_rollback_after`
    # consecutive anomalies (device streak or host loss-spike streak)
    anomaly_policy: Literal["warn", "skip_step", "rollback"] | None = None
    anomaly_rollback_after: int = pydantic.Field(default=3, ge=1)
    # host-side loss-spike detector: loss > factor x rolling-window
    # median counts as an anomaly; None disables spike detection
    anomaly_spike_factor: float | None = pydantic.Field(default=10.0, gt=1.0)
    anomaly_spike_window: int = pydantic.Field(default=32, ge=4)
    # consecutive rollbacks before giving up (a fault that survives the
    # restore is not transient; keep restarting forever helps nobody)
    anomaly_max_rollbacks: int = pydantic.Field(default=3, ge=1)

    # preemption-safe exit: SIGTERM/SIGINT → flag → step-boundary
    # emergency synchronous checkpoint → TrainingPreempted(exit_code)
    handle_preemption: bool = True
    preemption_exit_code: int = 83

    # manual GC (reference component/garbage_collector.py:13)
    gc_every_steps: int | None = 100

    # background input pipeline: batches prepared + device-staged this many
    # steps ahead on a producer thread (reference data_loader_factory.py:102
    # worker-backed StatefulDataLoader); 0 = fetch/stage on the step path
    prefetch_batches: int = 2

    # runtime telemetry (docs/design/observability.md): the process-local
    # registry is always on; these knobs attach sinks. telemetry_dir gets
    # one schema-versioned JSONL event file per process; the tracker
    # bridge + console summary flush on telemetry_every_steps (default:
    # the log cadence)
    telemetry_dir: str | None = None
    telemetry_every_steps: int | None = pydantic.Field(default=None, ge=1)
    telemetry_console: bool = True
    telemetry_console_interval_s: float = 30.0
    # live metrics endpoint (telemetry/export.py): serve /metrics
    # (Prometheus text), /healthz and /readyz from a background thread
    # for the duration of train() — 0 binds an ephemeral port (read it
    # back from trainer.metrics_server.port), None disables. /readyz
    # reports ready once the session is past introspect_warmup_steps
    # (every legitimate signature compiled — "compiling" never reads as
    # "serving traffic")
    metrics_port: int | None = pydantic.Field(default=None, ge=0)

    # training numerics plane (telemetry/numerics.py,
    # docs/design/observability.md "Training numerics plane"): per-layer
    # device-side tensor statistics (grad/activation RMS + absmax,
    # update:param ratio, optimizer second-moment health, per-leaf
    # finite masks) computed INSIDE the jitted step every this-many
    # steps — and additionally at every step whose metrics the loop
    # fetches anyway (log cadence / guard-forced checkpoint fetch), so
    # the window the host decodes is always the fetched step's own.
    # The stats ride the existing metric readback: off-cadence steps add
    # zero host dispatches and zero readbacks (bench-gated). None =
    # compiled out entirely (seed behavior). 1 = freshest provenance
    # (the anomaly guard names the first non-finite layer of the exact
    # anomalous step).
    numerics_every_steps: int | None = pydantic.Field(default=None, ge=1)
    # drift policies over training metrics (numerics.default_drift_
    # policies: grad-norm drift vs rolling baseline, update:param ratio
    # out of band, loss spike) evaluated at the log cadence, surfacing
    # train_slo/* gauges on /metrics. Active only with numerics enabled.
    numerics_drift: bool = True

    # pipeline timeline cadence (pipelining/runtime/fused.py,
    # docs/design/observability.md "Pipeline timeline & profiling"):
    # every this-many steps the fused PP executor blocks per fused run,
    # records each run's wall, and apportions it across the run's op
    # manifest by kind-weighted shares — restoring the legacy
    # interpreter's pp/s{S}/busy_s|bubble_s|bubble_frac gauges (plus the
    # pp/bubble_frac rollup and per-run pp/run/r{R}/k{K}/wall_s) under
    # runtime="fused". Cadence steps serialize the dispatch loop (the
    # per-run block IS the measurement), so keep this sparse; off-cadence
    # steps are structurally byte-identical (bench-gated: zero added
    # dispatches/readbacks). None = compiled out (seed behavior). No-op
    # under runtime="legacy", which always attributes.
    pp_timeline_every_steps: int | None = pydantic.Field(default=None, ge=1)

    # ZeRO-style optimizer-state sharding (parallel/zero.py,
    # docs/design/zero_sharding.md): partition fp32 masters + Adam
    # moments across the dp_replicate mesh axis — grads reduce-scattered
    # into the local 1/N shard, the update computed on the shard, new
    # params all-gathered back. A placement/annotation change only: the
    # update math is identical (CPU-exactness-tested), and checkpoints
    # keep global shapes so saves round-trip across different settings
    # of this knob (gather-on-load). No-op at dp_replicate == 1.
    zero_sharding: bool = False
    # elastic restore (docs/design/elasticity.md): when a checkpoint
    # written on a DIFFERENT mesh is restored (manifest v2 records the
    # saving topology), bound the transient per-array HBM footprint of
    # the reshard-on-load path to this budget — oversized leaves are
    # staged device-sharded and re-placed in <= budget chunks. None =
    # restore each leaf straight into its final placement (orbax's
    # shard-local reads, unbounded only for huge replicated leaves)
    reshard_hbm_budget_mb: float | None = pydantic.Field(default=None, gt=0)
    # observability split (tracked_jit): compile the optimizer phase as
    # its own `train_opt_update` executable so the introspection
    # inventory attributes the update's FLOPs/HBM separately from
    # hbm/train_step. Costs one extra dispatch per step and an HBM
    # round-trip of the clipped grads — leave off for recorded rows
    split_optimizer_update: bool = False

    # device-side introspection (telemetry/introspect.py): the recompile
    # guard arms after this many steps of the CURRENT train() session —
    # by then every legitimate signature (ragged last microbatch, both
    # fused-serve variants) has compiled, so any later compile is a
    # silent steady-state recompile worth a counter + warning
    introspect_warmup_steps: int = pydantic.Field(default=2, ge=1)
    # |model-FLOPs − XLA cost_analysis FLOPs| / model above this logs a
    # warning; the flops/model_vs_xla_divergence gauge is always set
    # when both sides are known
    flops_divergence_tolerance: float = pydantic.Field(default=0.25, gt=0)


class InferenceConfig(pydantic.BaseModel):
    model_config = pydantic.ConfigDict(extra="forbid")

    batch_size: int
    seq_len: int
    seed: int = 0
    log_every: int = 10
