"""Continuous batching: a slot-based serving loop over decode models.

Beyond-reference surface (the reference's ``Inference`` is forward-only
batch scoring; its serving story ends there). ``ContinuousBatcher``
keeps a fixed batch of ``batch_size`` slots decoding through ONE jitted
single-token step; requests are admitted into free slots as they
arrive and evicted on EOS/budget — rows never wait for each other
(the vLLM-style iteration-level scheduling loop, in its static-shape
TPU form).

Static shapes are the law under XLA, so admission is TOKEN-LEVEL: the
step always processes exactly one token per slot. A newly admitted
request spends its first ``len(prompt)`` steps consuming its prompt
(teacher-forced through the same decode step — cache contents and the
final-position logits are bit-identical to a one-shot prefill), then
flips to generation. The price is prompt consumption at one token per
step; long prompts can instead be pre-filled out-of-band with
``generate``'s chunked prefill and handed over — the primitives
compose, this loop stays shape-static.

Per-row cache state rides the decode modules unchanged: the serving
loop seeds the flax cache with a PER-ROW ``[B]`` ``cache_index``
(modules accept either rank — ``nn/attention.py``), the flash-decode
kernel takes per-row ``start`` offsets natively
(``ops/attention/pallas_decode.py``), and row admission resets just
that row's cache slice (every cache leaf leads with the batch dim).
GDN layers need nothing: their recurrent state is per-row already.

Parity contract: greedy serving of any admission schedule must emit,
per request, exactly the tokens ``generate(model, params, prompt)``
produces — ``tests/loop/test_serve.py`` drives staggered schedules
against that oracle.
"""

import collections
import dataclasses
import inspect
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from d9d_tpu.core.types import Array


@dataclasses.dataclass
class _Slot:
    rid: int = -1            # active request id, -1 = idle
    pending: list = dataclasses.field(default_factory=list)  # prompt left
    pos: int = 0             # next rope position for this row
    emitted: int = 0
    budget: int = 0          # max_new_tokens for the active request


@dataclasses.dataclass
class _Request:
    rid: int
    prompt: list
    max_new_tokens: int


def _zero_row(cache, row_mask: Array):
    """Zero every cache leaf's ``row_mask``-selected batch rows (all
    decode cache leaves — KV/latent caches, GDN state, conv tails,
    per-row cache_index — lead with the batch dim)."""
    def z(x):
        m = row_mask.reshape((-1,) + (1,) * (x.ndim - 1))
        return jnp.where(m, jnp.zeros_like(x), x)

    return jax.tree.map(z, cache)


class ContinuousBatcher:
    """Iteration-level scheduler over a KV-cache decode model.

    ``model`` must be built with ``decode_max_length`` ≥ the longest
    ``len(prompt) + max_new_tokens - 1`` it will serve. ``submit()``
    queues a request (admitted into the first free slot at the next
    ``step()``); each ``step()`` advances every active slot by one
    token and returns ``{rid: token}`` for tokens EMITTED this step
    (generation phase only). ``outputs[rid]`` accumulates; ``drain()``
    runs steps until every submitted request finishes.
    """

    def __init__(
        self,
        model,
        params,
        *,
        batch_size: int,
        eos_id: Optional[int] = None,
        temperature: float = 0.0,
        rng: Optional[jax.Array] = None,
    ):
        if temperature > 0.0 and rng is None:
            raise ValueError("temperature > 0 needs an rng key")
        self._model = model
        self._params = params
        self._b = batch_size
        self._eos = eos_id
        self._temp = temperature
        self._rng = rng if rng is not None else jax.random.PRNGKey(0)
        self._dml = int(getattr(model, "decode_max_length", 0))
        if self._dml <= 0:
            raise ValueError("model must be built with decode_max_length > 0")

        self._slots = [_Slot() for _ in range(batch_size)]
        self._queue: collections.deque[_Request] = collections.deque()
        self._next_rid = 0
        self._tokens = np.zeros((batch_size,), np.int32)  # next inputs
        self.outputs: dict[int, list[int]] = {}
        self.done: set[int] = set()

        method = getattr(model, "logits_last", None) or model.logits
        accepts_padding = (
            "padding_mask" in inspect.signature(method).parameters
        )
        step_pad = (
            jnp.ones((batch_size, 1), jnp.bool_) if accepts_padding else None
        )

        def step_fn(cache, tok, pos, key):
            kwargs = {"mask": None}
            if step_pad is not None:
                kwargs["padding_mask"] = step_pad
            logits, state = model.apply(
                {"params": params, "cache": cache},
                tok[:, None], pos[:, None],
                method=method, mutable=["cache"], **kwargs,
            )
            row_logits = logits[:, -1].astype(jnp.float32)
            if temperature == 0.0:
                nxt = jnp.argmax(row_logits, axis=-1).astype(jnp.int32)
            else:
                nxt = jax.random.categorical(
                    key, row_logits / temperature, axis=-1
                ).astype(jnp.int32)
            return state["cache"], nxt

        # donate the cache: XLA aliases input buffers to outputs, so the
        # per-step update is in place — no second cache residency or
        # full-cache memcpy per token
        self._step = jax.jit(step_fn, donate_argnums=0)
        self._reset = jax.jit(_zero_row, donate_argnums=0)
        self._cache = self._init_cache()

    def _init_cache(self):
        z = jnp.zeros((self._b, 1), jnp.int32)
        # eval_shape: cache SHAPES only — model.init would materialize
        # (and immediately discard) a full second copy of the parameters
        shapes = jax.eval_shape(
            self._model.init, jax.random.PRNGKey(0), z, z, z
        )
        cache = jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype), shapes["cache"]
        )
        # per-row write indices: seed [B] zeros in place of the scalar —
        # the decode modules accept either rank (nn/attention.py)
        from flax.traverse_util import flatten_dict, unflatten_dict

        flat = flatten_dict(cache)
        for path in list(flat):
            if path[-1] == "cache_index":
                flat[path] = jnp.zeros((self._b,), jnp.int32)
        return unflatten_dict(flat)

    # ------------------------------------------------------------------
    def submit(
        self, prompt: Sequence[int], *, max_new_tokens: int
    ) -> int:
        """Queue a request; returns its request id. Admission happens at
        the next step() with a free slot."""
        prompt = [int(x) for x in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}"
            )
        need = len(prompt) + max_new_tokens - 1
        if need > self._dml:
            raise ValueError(
                f"prompt {len(prompt)} + max_new_tokens {max_new_tokens}"
                f" - 1 = {need} exceeds decode_max_length={self._dml}"
            )
        rid = self._next_rid
        self._next_rid += 1
        self._queue.append(_Request(rid, prompt, max_new_tokens))
        self.outputs[rid] = []
        return rid

    @property
    def active(self) -> int:
        return sum(1 for s in self._slots if s.rid >= 0) + len(self._queue)

    def _admit(self):
        reset_mask = np.zeros((self._b,), bool)
        for i, slot in enumerate(self._slots):
            if slot.rid >= 0 or not self._queue:
                continue
            req = self._queue.popleft()
            self._slots[i] = _Slot(
                rid=req.rid,
                pending=list(req.prompt[1:]),
                pos=0,
                emitted=0,
                budget=req.max_new_tokens,
            )
            self._tokens[i] = req.prompt[0]
            reset_mask[i] = True
        if reset_mask.any():
            self._cache = self._reset(
                self._cache, jnp.asarray(reset_mask)
            )

    def step(self) -> dict[int, int]:
        """Admit waiting requests, advance every slot one token; returns
        ``{rid: token}`` for tokens emitted (generation phase) this step."""
        self._admit()
        if all(s.rid < 0 for s in self._slots):
            return {}
        pos = np.asarray([s.pos for s in self._slots], np.int32)
        self._rng, sub = jax.random.split(self._rng)
        self._cache, nxt = self._step(
            self._cache, jnp.asarray(self._tokens), jnp.asarray(pos), sub
        )
        nxt = np.asarray(nxt)

        emitted: dict[int, int] = {}
        evict_mask = np.zeros((self._b,), bool)
        for i, slot in enumerate(self._slots):
            if slot.rid < 0:
                continue
            slot.pos += 1
            if slot.pending:  # still consuming the prompt
                self._tokens[i] = slot.pending.pop(0)
                continue
            tok = int(nxt[i])  # sampled from the row's latest position
            emitted[slot.rid] = tok
            self.outputs[slot.rid].append(tok)
            slot.emitted += 1
            finished = slot.emitted >= slot.budget or (
                self._eos is not None and tok == self._eos
            )
            if finished:
                self.done.add(slot.rid)
                self._slots[i] = _Slot()
                self._tokens[i] = 0
                evict_mask[i] = True
            else:
                self._tokens[i] = tok
        if evict_mask.any():
            # reset at EVICTION, not just admission: an idle row still
            # runs through the jitted step, so its cache_index would
            # otherwise climb past capacity (spurious checkify overflow
            # under contract validation) and defeat the flash kernel's
            # whole-block skip (a huge start makes every block visible)
            self._cache = self._reset(
                self._cache, jnp.asarray(evict_mask)
            )
        return emitted

    def drain(self, max_steps: int = 100_000) -> dict[int, list[int]]:
        """Step until every submitted request has finished."""
        steps = 0
        while self.active:
            self.step()
            steps += 1
            if steps > max_steps:
                raise RuntimeError("drain exceeded max_steps")
        return self.outputs
