"""Continuous batching: a slot-based serving loop over decode models.

Beyond-reference surface (the reference's ``Inference`` is forward-only
batch scoring; its serving story ends there). ``ContinuousBatcher``
keeps a fixed batch of ``batch_size`` slots decoding through a jitted
decode loop; requests are admitted into free slots as they arrive and
evicted on EOS/budget — rows never wait for each other (the vLLM-style
iteration-level scheduling loop, in its static-shape TPU form).

Host-interaction contract (the perf-defining design decision): the
inner decode loop is FUSED — ``chunk_size`` (K) single-token steps run
as one jitted ``lax.scan`` that advances all slots, applies per-row
stop/length masks in-device, and accumulates emitted tokens into a
device-side ``[B, K]`` buffer. The host performs ONE dispatch and ONE
token readback per K generated tokens instead of per token; admission,
eviction and finished-row harvesting happen only at chunk boundaries.
Rows that finish mid-chunk (budget or EOS) are masked dead in-device —
their emissions stop and their ``cache_index`` pins to 0 the same step,
so the capacity contract holds without per-token host intervention —
and are harvested at the boundary. ``drain()`` additionally
double-buffers: while no admissions are waiting, chunk N+1 is
dispatched before chunk N's tokens are fetched (its plan is
deterministic — prompt feeding and positions advance device-side), so
the readback overlaps device compute via XLA async dispatch.

``chunk_size=None`` selects the legacy per-token stepping path (one
dispatch + one readback per token) — kept as the oracle for the fused
path's exactness tests and for latency-critical single-token serving.

Static shapes are the law under XLA, so admission is TOKEN-LEVEL: the
loop always processes exactly one token per slot per device step. A
newly admitted request spends its first ``len(prompt)`` steps consuming
its prompt (teacher-forced through the same decode step — cache
contents and the final-position logits are bit-identical to a one-shot
prefill), then flips to generation. The price is prompt consumption at
one token per step; long prompts can instead be pre-filled out-of-band
with ``generate``'s chunked prefill and handed over — the primitives
compose, this loop stays shape-static.

Per-row cache state rides the decode modules unchanged: the serving
loop seeds the flax cache with a PER-ROW ``[B]`` ``cache_index``
(modules accept either rank — ``nn/attention.py``), the flash-decode
kernel takes per-row ``start`` offsets natively
(``ops/attention/pallas_decode.py``), and row admission resets just
that row's cache slice (every cache leaf leads with the batch dim).
Idle and dead rows have their ``cache_index`` pinned to 0 inside the
jitted step, so a slot left idle for arbitrarily many steps can never
overflow the capacity contract or defeat the flash-decode block skip.
GDN layers need nothing: their recurrent state is per-row already.

Parity contract: greedy serving of any admission schedule must emit,
per request, exactly the tokens ``generate(model, params, prompt)``
produces — ``tests/loop/test_serve.py`` drives staggered schedules
against that oracle, for both the fused and the per-token path.
(With ``temperature > 0`` the two paths consume the RNG stream in
different orders — per chunk vs per token — so sampled outputs are
both valid draws but not bitwise-identical across modes.)

Telemetry (docs/design/observability.md): per-request TTFT / TPOT /
queue-wait and per-chunk slot-occupancy histograms are derived from the
host clock at the SAME boundaries the token readbacks already happen at
— the fused path's host-interaction contract (one dispatch + one
readback per chunk) is untouched; ``tests/telemetry`` pins
``stats.readbacks`` against it. Host dispatch/readback/admission
regions carry ``serve.*`` ``core/tracing.annotate`` labels inside
profiler capture windows (``tools/trace_summary.py`` groups them).
The monitoring plane rides the same boundaries: every request carries
a fleet-stable trace id (``request_trace`` JSONL milestones),
``replica_label`` namespaces the serve instruments per replica
(``serve/r{i}/...`` with base-name rollups), and ``metrics_port``
serves live Prometheus ``/metrics`` + ``/healthz`` + ``/readyz`` from
a background thread — all pure host work, zero added readbacks (gated
by ``tools/bench_compare.py``'s exporter leg).

Paged KV cache + prefix cache (docs/design/generation.md): with
``page_size`` set, the sequence caches become device-resident page
POOLS (``[num_pages, ..., page_size, ...]``) indexed through a
static-shape per-row ``[B, max_pages]`` page table — HBM per request
is proportional to its actual length instead of ``decode_max_length``,
admission is bounded by free pages rather than batch rows, and a
content-hashed prefix cache maps a shared prompt's pages
copy-on-write into later requests so it prefills once per replica.
All policy (free lists, refcounts, hashing, LRU eviction —
``loop/kv_paging.py``) runs on the host at the SAME chunk boundaries
admission already owns; the page table is a traced cache leaf like
``cache_index``, so the host-interaction contract above and the
``tracked_jit`` fingerprints are untouched (``tools/bench_compare.py``
gates the paged leg's dispatch/readback/compile counts against the
contiguous leg's). The flash-decode kernel generalizes its kv-block
index map to gather page ids (``ops/attention/pallas_decode.py``);
the eager path gathers a contiguous per-row view and remains the
bitwise exactness reference — greedy paged serving is token-identical
to the contiguous layout, prefix hit or cold.

Live weight publish (docs/design/elasticity.md): the jitted executables
take the parameter tree as a *traced argument* — never a trace-time
closure constant — so :meth:`ContinuousBatcher.install_weights` can
swap in a freshly published tree at a chunk boundary with an unchanged
``tracked_jit`` fingerprint (same shapes/dtypes/placements): no
restart, no steady-state recompile (``tools/bench_compare.py`` gates
this). Swaps are generation-stamped (``weights_version``); chunks
already dispatched complete on the weights they were dispatched with,
and ``defer_to_idle`` holds the swap until every in-flight request has
finished, so those requests complete wholly on the old generation.
"""

import _thread
import collections
import dataclasses
import inspect
import itertools
import os
import threading
import time
import weakref
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding

from d9d_tpu.core.tracing import annotate
from d9d_tpu.core.tree_sharding import replicate_uncommitted
from d9d_tpu.core.types import Array
from d9d_tpu.loop.quantize import dequantize_params, is_quantized_tree
from d9d_tpu.telemetry import get_telemetry, tracked_jit

# slot-occupancy fraction per chunk/step: 20 linear bins over [0, 1]
_UTIL_EDGES = tuple(i / 20 for i in range(21))

# tokens-per-completed-request distribution: 1 .. 4096 tokens, log bins.
# A generation-quality canary signal (docs/design/elasticity.md "SLO
# autopilot"): a bad weight publish that stops hitting EOS shows up as
# this distribution jumping to the budget ceiling on the canary replica
# long before any latency SLO moves.
_REQ_TOKENS_EDGES = tuple(
    1.0 * (4096.0 ** (i / 24)) for i in range(25)
)

# per-request trace ids (docs/design/observability.md): pid + a process
# counter — unique across a multi-process fleet without coordination,
# deterministic within one process (chaos tests assert exact sequences)
_TRACE_IDS = itertools.count()


def mint_trace_id() -> str:
    """A fleet-stable request trace id: minted once at the FIRST submit
    (fleet front door or direct batcher submit) and carried through
    queue → chunk dispatch → migration → kill-recovery continuation, so
    one id follows the request across every replica it touches."""
    return f"req-{os.getpid():x}-{next(_TRACE_IDS):x}"


class QueueFullError(RuntimeError):
    """``submit()`` rejected: the bounded admission queue is full.

    Degraded-mode backpressure (docs/design/resilience.md): an overload
    becomes an explicit, retryable rejection the caller can shed or
    redirect — not an unbounded host-memory queue that dies later.
    """


class ServeStalledError(RuntimeError):
    """``drain()`` aborted by the stall watchdog: no dispatch/readback
    progress within ``stall_timeout_s`` while work was outstanding —
    a wedged device/runtime surfaces as an error, not a silent hang."""


@dataclasses.dataclass
class _Slot:
    rid: int = -1            # active request id, -1 = idle
    # legacy (per-token) mode: prompt tokens after the one in _tokens
    pending: list = dataclasses.field(default_factory=list)
    pos: int = 0             # legacy mode: next rope position for this row
    # fused mode: prompt tokens not yet dispatched as step inputs
    feed: list = dataclasses.field(default_factory=list)
    emitted: int = 0         # committed (harvested) emissions
    budget: int = 0          # max_new_tokens for the active request
    deadline_t: float | None = None  # absolute perf_counter deadline


@dataclasses.dataclass
class _Request:
    rid: int
    prompt: list
    max_new_tokens: int
    deadline_t: float | None = None
    trace_id: str | None = None
    # admission tier (docs/design/elasticity.md "SLO autopilot"): higher
    # = more important. Admission itself stays FIFO (token-identity
    # contract); priority is what burn-driven shedding orders on —
    # lowest priority / longest deadline sheds first.
    priority: int = 0

    @property
    def total_tokens(self) -> int:
        """Cache slots this request writes over its lifetime: every
        prompt token plus every generated token except the final
        sample (emitted but never fed back). THE footprint every page
        computation keys on — submit validation, the queue-full
        capacity credit and allocation must never disagree by a page."""
        return len(self.prompt) + self.max_new_tokens - 1


@dataclasses.dataclass
class _ChunkPlan:
    """Host-side record of one dispatched fused chunk, consumed FIFO at
    harvest time: enough to replay the device's emission/stop logic on
    the readback without fetching any mask buffers."""

    k: int
    rids: list            # rid per slot at dispatch (-1 = idle)
    emit_from: list       # first step index (within the chunk) that emits
    version: int = 0      # weights generation this chunk dispatched with


# default per-transfer staging bound for KV page shipments: the same
# order as elastic-restore's redistribute budget — big enough that a
# whole tiny-model prefix ships in one chunk, small enough that a long
# production prefix never stages the full run on the host at once
_TRANSFER_BUDGET_BYTES = 64 << 20


@dataclasses.dataclass
class KVPageShipment:
    """One cross-replica KV prefix shipment (host-side, self-checking).

    ``payload`` maps each paged pool leaf path (values AND int8 scale
    siblings) to a ``[n_pages, ...]`` host array stacked in block
    order; ``checksums[i]`` is a crc32 over page ``i``'s bytes across
    every leaf in sorted-path order, verified by the importer BEFORE
    any allocator or pool mutation — a flipped byte or truncated
    payload is detected, and the request falls back to re-prefill.
    ``weights_version`` pins the generation the pages were computed
    under: cached KV is weights-dependent, so an importer on any other
    generation must reject (same invariant as ``install_weights``
    prefix invalidation)."""

    page_size: int
    tokens: list            # the full-block token prefix the pages cover
    n_pages: int
    weights_version: int
    kv_quant: Optional[str]
    payload: dict
    checksums: list
    chunks: int = 0         # transfer chunks the export staged through

    @property
    def nbytes(self) -> int:
        return sum(int(a.nbytes) for a in self.payload.values())


def _page_checksums(payload: dict) -> list:
    """Per-page crc32 across every payload leaf in sorted-path order."""
    import zlib

    if not payload:
        return []
    n = next(iter(payload.values())).shape[0]
    out = []
    for i in range(n):
        c = 0
        for name in sorted(payload):
            c = zlib.crc32(
                np.ascontiguousarray(payload[name][i]).tobytes(), c
            )
        out.append(c)
    return out


@dataclasses.dataclass
class RequestTelemetry:
    """Host-clock milestones for one request, harvested at the same
    boundaries the token readbacks already happen at (chunk boundaries
    on the fused path, per step on the legacy path) — deriving latency
    telemetry costs ZERO additional device readbacks.

    Granularity contract: on the fused path first-token and finish
    times are observed at chunk-boundary harvests, so TTFT/TPOT carry
    up-to-one-chunk quantization — exactly the latency a caller of
    ``step_chunk``/``drain`` experiences.
    """

    submit_t: float
    admit_t: float | None = None
    first_tok_t: float | None = None
    finish_t: float | None = None
    tokens: int = 0
    # weights generation of the chunk that FINISHED this request (the
    # publish-versioning audit trail: which params produced the tail)
    weights_version: int | None = None
    # fleet-stable per-request trace id (schema v3 request_trace events)
    trace_id: str | None = None

    @property
    def queue_wait_s(self) -> float | None:
        if self.admit_t is None:
            return None
        return self.admit_t - self.submit_t

    @property
    def ttft_s(self) -> float | None:
        """Submit → first emitted token visible on the host."""
        if self.first_tok_t is None:
            return None
        return self.first_tok_t - self.submit_t

    @property
    def tpot_s(self) -> float | None:
        """Mean per-output-token latency after the first token (the
        serving TPOT convention); None until finished or for
        single-token requests."""
        if self.finish_t is None or self.tokens < 2:
            return None
        return (self.finish_t - self.first_tok_t) / (self.tokens - 1)


@dataclasses.dataclass
class ServeStats:
    """Host-interaction and utilization counters (reset with ``reset()``).

    ``host_dispatches`` counts jitted-call dispatches (the quantity the
    fused loop divides by K); ``readbacks`` counts device→host token
    fetches; ``device_steps`` counts single-token decode steps executed
    on device; ``slot_steps_busy / slot_steps_total`` give slot
    occupancy (busy includes prompt-consumption steps).
    """

    host_dispatches: int = 0
    readbacks: int = 0
    chunks: int = 0
    device_steps: int = 0
    emitted_tokens: int = 0
    slot_steps_busy: int = 0
    slot_steps_total: int = 0
    # degraded-mode counters: submits rejected by the bounded queue,
    # requests expired by their deadline (queued or running), requests
    # shed by the autopilot's burn-driven admission tiering
    rejected: int = 0
    expired: int = 0
    shed: int = 0

    def reset(self) -> None:
        for f in dataclasses.fields(self):
            setattr(self, f.name, 0)

    @property
    def dispatches_per_1k_tokens(self) -> float:
        if self.emitted_tokens == 0:
            return float("inf")
        return 1000.0 * self.host_dispatches / self.emitted_tokens

    @property
    def slot_utilization(self) -> float:
        if self.slot_steps_total == 0:
            return 0.0
        return self.slot_steps_busy / self.slot_steps_total


def _zero_row(cache, row_mask: Array):
    """Zero every cache leaf's ``row_mask``-selected batch rows (all
    decode cache leaves — KV/latent caches, GDN state, conv tails,
    per-row cache_index — lead with the batch dim)."""
    def z(x):
        m = row_mask.reshape((-1,) + (1,) * (x.ndim - 1))
        return jnp.where(m, jnp.zeros_like(x), x)

    return jax.tree.map(z, cache)


def _normalize_params(params):
    """Pin uncommitted leaves of a handed-over param tree to the
    mesh-replicated placement of its committed leaves
    (``core/tree_sharding.replicate_uncommitted``); identity for trees
    with no committed NamedSharding to normalize against."""
    for leaf in jax.tree.leaves(params):
        sh = getattr(leaf, "sharding", None)
        if isinstance(sh, NamedSharding):
            return replicate_uncommitted(params, sh.mesh)
    return params


def _pin_cache_index(cache, live: Array):
    """Pin dead/idle rows' per-row write indices to 0: the jitted step
    advances every row's ``cache_index``, so without the pin a long-idle
    slot would climb past capacity (spurious checkify overflow under
    contract validation) and defeat the flash-decode whole-block skip
    (a huge start makes every block visible)."""
    from d9d_tpu.nn.decode_flags import map_cache_index

    return map_cache_index(cache, lambda idx: jnp.where(live, idx, 0))


def _pin_page_table(cache, live: Array):
    """Paged companion of :func:`_pin_cache_index`: pin dead/idle rows'
    page-table rows to the reserved garbage page (0). A row that dies
    mid-chunk keeps executing static-shape steps — with its write index
    pinned to 0 its writes land at logical slot 0, and WITHOUT this pin
    that is ``page_table[b, 0]``, which may be a freed page or (worse) a
    SHARED prefix page. With it, dead rows scribble harmlessly into the
    garbage page until the host reuses the slot."""
    from d9d_tpu.nn.decode_flags import map_page_table

    return map_page_table(
        cache, lambda pt: jnp.where(live[:, None], pt, 0)
    )


class ContinuousBatcher:
    """Iteration-level scheduler over a KV-cache decode model.

    ``model`` must be built with ``decode_max_length`` ≥ the longest
    ``len(prompt) + max_new_tokens - 1`` it will serve. ``submit()``
    queues a request (admitted into the first free slot at the next
    step/chunk boundary); ``step()`` advances every active slot by one
    token and returns ``{rid: token}`` for tokens EMITTED this step
    (generation phase only); ``step_chunk()`` advances by ``chunk_size``
    tokens in one dispatch and returns ``{rid: [tokens]}``.
    ``outputs[rid]`` accumulates; ``drain()`` runs (double-buffered)
    chunks until every submitted request finishes.

    ``chunk_size``: decode steps fused per dispatch (default 8).
    ``None`` selects the legacy per-token stepping path. ``overlap``
    (fused mode) lets ``drain()`` keep one chunk in flight while the
    previous chunk's tokens are fetched.
    """

    def __init__(
        self,
        model,
        params,
        *,
        batch_size: int,
        eos_id: Optional[int] = None,
        temperature: float = 0.0,
        rng: Optional[jax.Array] = None,
        chunk_size: Optional[int] = 8,
        overlap: bool = True,
        telemetry=None,
        max_queue: Optional[int] = None,
        stall_timeout_s: Optional[float] = None,
        replica_label: Optional[str] = None,
        metrics_port: Optional[int] = None,
        page_size: Optional[int] = None,
        num_pages: Optional[int] = None,
        prefix_cache: Optional[bool] = None,
        kv_quant: Optional[str] = None,
    ):
        """Degraded-mode knobs (docs/design/resilience.md): ``max_queue``
        bounds the admission queue — ``submit()`` past it raises
        :class:`QueueFullError` (explicit backpressure). Requests may
        carry per-request deadlines (``submit(..., deadline_s=...)``)
        that expire them cleanly whether queued or running.
        ``stall_timeout_s`` arms a drain watchdog: no host
        dispatch/readback progress for that long with work outstanding
        raises :class:`ServeStalledError` instead of hanging.

        Monitoring-plane knobs (docs/design/observability.md):
        ``replica_label`` (e.g. ``"r0"`` — ``ServingFleet.add_replica``
        assigns these) namespaces this batcher's serve instruments as
        ``serve/{label}/...`` so N same-process replicas stop blending
        into the shared ``serve/*`` names; counters and latency
        histograms additionally feed the base name as the fleet rollup.
        ``metrics_port`` (0 = ephemeral) starts a
        :class:`~d9d_tpu.telemetry.MetricsServer` for this batcher —
        ``/metrics`` in Prometheus text, ``/readyz`` not-ready until the
        first readback has round-tripped; call :meth:`close` (or use the
        fleet's endpoint instead) to shut it down.

        Paged KV knobs (docs/design/generation.md "Paged KV cache"):
        ``page_size`` switches the sequence caches to a device-resident
        page pool + per-row page tables — HBM per request becomes
        proportional to its ACTUAL length, admission is bounded by free
        pages (head-of-line waits, never rejects, when pages run
        short), and a content-hashed prefix cache lets a shared system
        prompt prefill once and be mapped copy-on-write into later
        requests. ``num_pages`` sizes the pool (default: enough for
        every slot at full ``decode_max_length`` + the reserved garbage
        page — no savings until you shrink it). ``prefix_cache`` —
        None (default) auto-enables when every sequence cache is
        pageable and disables for models with unpageable per-row
        recurrent state (GDN/conv tails: their state summarizes the
        whole prefix and cannot be restored from KV pages); True forces
        (raising if unsound), False disables. Greedy decoding is
        token-identical to the contiguous layout either way.

        ``kv_quant="int8"`` (paged mode only — the page is the
        quantization granule, docs/design/generation.md "Low-precision
        serving") stores the KV pools as int8 with f32
        per-(page, slot[, head]) scale pools riding next to them as
        sibling cache leaves. Writes quantize at the per-row scatter,
        reads dequantize in the decode-attention gather/kernel; the
        prefix cache and continuation handoff are unchanged (scale
        pages share the value pages' page table). Decoding is no longer
        bit-identical to bf16/f32 — it is drift-bounded, gated by the
        parity tests and the autopilot canary."""
        if temperature > 0.0 and rng is None:
            raise ValueError("temperature > 0 needs an rng key")
        if chunk_size is not None and chunk_size < 1:
            raise ValueError(f"chunk_size must be >= 1, got {chunk_size}")
        if max_queue is not None and max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        if stall_timeout_s is not None and stall_timeout_s <= 0:
            raise ValueError(
                f"stall_timeout_s must be > 0, got {stall_timeout_s}"
            )
        self._model = model
        # latent-placement fix (same class as the PR 5 resume bug): a
        # param tree handed over from a restored checkpoint can carry
        # uncommitted scalar leaves whose single-device placement
        # conflicts with the mesh-placed majority at the first dispatch
        self._params = _normalize_params(params)
        self._b = batch_size
        self._eos = eos_id
        self._temp = temperature
        self._rng = rng if rng is not None else jax.random.PRNGKey(0)
        self._k = chunk_size
        self._overlap = overlap and chunk_size is not None
        self._dml = int(getattr(model, "decode_max_length", 0))
        if self._dml <= 0:
            raise ValueError("model must be built with decode_max_length > 0")

        # paged KV mode (docs/design/generation.md): fixed-size page
        # pools + per-row page tables instead of contiguous per-row
        # cache leaves; allocation/refcounting/prefix caching is host
        # work at the existing chunk boundaries (loop/kv_paging.py)
        self._paged = page_size is not None
        self._kv = None
        if self._paged:
            if page_size < 1:
                raise ValueError(f"page_size must be >= 1, got {page_size}")
            self._page_size = int(page_size)
            self._pages_per_row = -(-self._dml // self._page_size)
            self._num_pages = (
                int(num_pages) if num_pages is not None
                # default: every slot can hold a full-length request
                # (+ the reserved garbage page) — paging then changes
                # accounting but strands nothing; shrink it to actually
                # overcommit HBM
                else batch_size * self._pages_per_row + 1
            )
        elif num_pages is not None or prefix_cache is not None:
            raise ValueError(
                "num_pages/prefix_cache need paged mode (set page_size)"
            )
        if kv_quant is not None and not self._paged:
            raise ValueError("kv_quant needs paged mode (set page_size)")
        if kv_quant not in (None, "int8"):
            raise ValueError(
                f"kv_quant must be None or 'int8', got {kv_quant!r}"
            )
        self._kv_quant = kv_quant

        self._slots = [_Slot() for _ in range(batch_size)]
        self._queue: collections.deque[_Request] = collections.deque()
        self._next_rid = 0
        self._tokens = np.zeros((batch_size,), np.int32)  # legacy inputs
        self.outputs: dict[int, list[int]] = {}
        self.done: set[int] = set()
        # degraded-mode state: rid → failure reason ("deadline") for
        # requests retired without completing; done includes them so
        # drain() terminates and harvests skip their rows
        self.failed: dict[int, str] = {}
        self._max_queue = max_queue
        self._stall_timeout_s = stall_timeout_s
        self._progress_t = time.perf_counter()
        self._stalled = False
        self.stats = ServeStats()
        # per-request latency telemetry (serve/* namespace): recorded into
        # the process hub unless an isolated hub is injected
        self._tele = telemetry if telemetry is not None else get_telemetry()
        # finished-request state (stats records, output token lists, done
        # flags) is retained bounded-FIFO (_MAX_FINISHED_STATS): a
        # long-lived server must not grow host memory linearly with total
        # requests served — read results within that retention horizon
        self.request_stats: dict[int, RequestTelemetry] = {}
        self._finished_rids: collections.deque[int] = collections.deque()
        # serve/tokens_per_s two-bucket rolling window, evaluated at
        # snapshot time via gauge_fn: a lifetime average would flatten
        # into a constant on a long-lived server, and a last-write-wins
        # gauge would freeze at the last healthy value through a stall —
        # this way an idle/stalled server's rate decays toward zero.
        # Registered through a weakref so the hub (whose gauge_fn
        # registrations are process-lifetime) never pins a discarded
        # batcher — and its device-resident cache — in memory.
        now = time.perf_counter()
        self._rate_win_t0 = now
        self._rate_win_tokens = 0
        self._rate_prev_t0 = now
        self._rate_prev_tokens = 0
        this = weakref.ref(self)
        self._rate_fn = (
            lambda: b._live_rate() if (b := this()) is not None
            else float("nan")
        )
        # label set BEFORE the first gauge_fn registration: a batcher
        # constructed with a label must never transiently claim (and on
        # labeling, delete) the base-name registration an earlier
        # unlabeled batcher may hold
        self._replica_label: Optional[str] = None
        if replica_label is not None:
            self._replica_label = self._validate_label(replica_label)
        self._tele.gauge_fn(self._rate_gauge_name(), self._rate_fn)
        # readiness (telemetry/export.py /readyz contract): a batcher is
        # ready once one readback has round-tripped — the executables
        # are compiled and the device answered. Deliberately NOT reset
        # by reset_measurement: warmth survives a bench window reset.
        self._first_readback_t: Optional[float] = None

        method = getattr(model, "logits_last", None) or model.logits
        self._method = method
        accepts_padding = (
            "padding_mask" in inspect.signature(method).parameters
        )
        self._step_pad = (
            jnp.ones((batch_size, 1), jnp.bool_) if accepts_padding else None
        )

        # jitted executables are built lazily: the per-token step only
        # compiles if the legacy path (or a mode mix) is actually used,
        # and each distinct fused K compiles its own scan
        self._step = None
        self._fused: dict[tuple[int, bool], object] = {}  # (k, with_admit)
        if self._paged:
            from d9d_tpu.nn.decode_flags import (
                map_cache_index,
                zero_rows_skip_paged,
            )

            def _reset_rows_paged(cache, row_mask, admit_pos):
                # page pools are shared (never row-zeroed — stale page
                # bytes are unreachable behind the slot mask) and table
                # rows come from the host mirror; per-row leaves reset,
                # write indices jump to the first un-cached position
                cache = zero_rows_skip_paged(cache, row_mask)
                return map_cache_index(
                    cache,
                    lambda idx: jnp.where(row_mask, admit_pos, idx),
                )

            self._reset = tracked_jit(
                _reset_rows_paged, name="serve/reset_row_paged",
                donate_argnums=0,
            )
        else:
            self._reset = tracked_jit(
                _zero_row, name="serve/reset_row", donate_argnums=0
            )
        self._cache = self._init_cache()
        if self._paged:
            # static per-batcher fact, but exported so dashboards (and
            # the bench accounting) can tell quantized pools apart
            # without reverse-engineering bytes-per-page
            self._gauge_set(
                "serve/kv_quant_enabled", 0.0 if kv_quant is None else 1.0
            )
        # KV residency accounting (serve/kv_* gauges + the bench's
        # hbm_bytes_per_request): peaks over the measurement window
        self._peak_running = 0
        if self._paged:
            from d9d_tpu.loop.kv_paging import PagedKVAllocator

            if prefix_cache and self._unpageable_leaves:
                raise ValueError(
                    "prefix_cache=True is unsound for this model: cache "
                    f"leaves {self._unpageable_leaves} hold per-row "
                    "recurrent state that summarizes the whole prefix "
                    "and cannot be restored from KV pages"
                )
            self._kv = PagedKVAllocator(
                num_pages=self._num_pages,
                page_size=self._page_size,
                rows=batch_size,
                max_pages_per_row=self._pages_per_row,
                enable_prefix_cache=(
                    prefix_cache if prefix_cache is not None
                    else not self._unpageable_leaves
                ),
            )
            self._kv_table_dirty = False  # seeded leaves match the mirror

        # live weight publish (docs/design/elasticity.md): staged tree
        # swapped in at the next dispatch boundary, generation-stamped
        self.weights_version = 0
        self._pending_weights: tuple | None = None

        # fused-mode device carries (one buffer each, donated through)
        self._tok_d = jnp.zeros((batch_size,), jnp.int32)
        self._pos_d = jnp.zeros((batch_size,), jnp.int32)
        self._live_d = jnp.zeros((batch_size,), jnp.bool_)
        self._rem_d = jnp.zeros((batch_size,), jnp.int32)
        # dispatched-but-unharvested fused chunks, FIFO
        self._pending: collections.deque[tuple] = collections.deque()

        # opt-in live metrics endpoint (telemetry/export.py); weakrefs so
        # the endpoint can never pin a discarded batcher's device cache
        self.metrics_server = None
        if metrics_port is not None:
            from d9d_tpu.telemetry import MetricsServer

            ref = weakref.ref(self)
            self.metrics_server = MetricsServer(
                self._tele,
                port=metrics_port,
                readiness=lambda: (
                    (b.ready, {"replica": b._replica_label})
                    if (b := ref()) is not None else (False, {})
                ),
                health=lambda: (
                    {
                        "replica": b._replica_label,
                        "active": b.active,
                        "ready": b.ready,
                        "stalled": b._stalled,
                    }
                    if (b := ref()) is not None else {"gone": True}
                ),
            ).start()

    @property
    def ready(self) -> bool:
        """Past the first readback round-trip (compiled + device alive)
        — the /readyz contract for this batcher."""
        return self._first_readback_t is not None

    def close(self) -> None:
        """Release host-side attachments (the metrics endpoint and this
        batcher's gauge registrations); the batcher itself stays usable
        except for scraping."""
        if self.metrics_server is not None:
            self.metrics_server.close()
            self.metrics_server = None
        self._tele.registry.unregister_gauge_fn(
            self._rate_gauge_name(), self._rate_fn
        )

    # -- instrument naming (replica namespacing, ISSUE satellite) ------

    def _rate_gauge_name(self) -> str:
        return (
            f"serve/{self._replica_label}/tokens_per_s"
            if self._replica_label else "serve/tokens_per_s"
        )

    @staticmethod
    def _validate_label(label: str) -> str:
        if not label or "/" in label:
            raise ValueError(f"replica_label must be path-free, got {label!r}")
        return str(label)

    def set_replica_label(self, label: str) -> None:
        """Namespace this batcher's serve instruments as
        ``serve/{label}/...`` (the fleet assigns ``r{i}``). Re-homes the
        live-rate callback gauge; subsequent records use the new name.
        Counters/histograms keep feeding the base ``serve/*`` name too —
        the fleet rollup the unlabeled world saw stays intact. (Prefer
        ``replica_label=`` at construction: an unlabeled batcher holds
        the base-name rate gauge until this call, with the pre-existing
        last-registration-wins semantics across unlabeled batchers.)"""
        label = self._validate_label(label)
        # fn-guarded: only tears down THIS batcher's registration
        self._tele.registry.unregister_gauge_fn(
            self._rate_gauge_name(), self._rate_fn
        )
        self._replica_label = label
        self._tele.gauge_fn(self._rate_gauge_name(), self._rate_fn)

    def _mname(self, name: str) -> str:
        # name always carries the "serve/" prefix at call sites
        return f"serve/{self._replica_label}/{name[6:]}"

    def _count(self, name: str, n: float = 1.0) -> None:
        self._tele.counter(name).add(n)
        if self._replica_label:
            self._tele.counter(self._mname(name)).add(n)

    def _observe(self, name: str, v: float, edges=None) -> None:
        # base name first: SLO digests key on the fleet-level metric
        self._tele.observe(name, v, edges)
        if self._replica_label:
            self._tele.observe(self._mname(name), v, edges)

    def _gauge_set(self, name: str, v: float) -> None:
        # gauges are last-write-wins: a shared base name would blend N
        # replicas (the conflation bug this satellite fixes), so labeled
        # batchers write ONLY their namespaced gauge; fleet-level gauges
        # are computed by ServingFleet as explicit rollups
        self._tele.gauge(
            self._mname(name) if self._replica_label else name
        ).set(v)

    # -- per-request trace events (schema v3, docs/design/observability.md)

    def _trace(
        self,
        trace_id: Optional[str],
        event: str,
        t: float,
        *,
        rid: Optional[int] = None,
        **meta,
    ) -> None:
        if trace_id is None:
            return
        rec: dict = {"trace_id": trace_id, "event": event, "t": t}
        if self._replica_label is not None:
            rec["replica"] = self._replica_label
        if rid is not None:
            rec["rid"] = rid
        if meta:
            rec["meta"] = meta
        self._tele.record_request_trace(rec)

    def _init_cache(self):
        import math

        from flax.traverse_util import flatten_dict, unflatten_dict

        from d9d_tpu.nn.decode_flags import (
            PAGE_TABLE_LEAF,
            PAGED_CACHE_LEAVES,
            PAGED_SCALE_SUFFIX,
        )

        z = jnp.zeros((self._b, 1), jnp.int32)
        # eval_shape: cache SHAPES only — model.init would materialize
        # (and immediately discard) a full second copy of the parameters
        shapes = jax.eval_shape(
            self._model.init, jax.random.PRNGKey(0), z, z, z
        )
        flat = flatten_dict(shapes["cache"])
        # dense-layout byte total of the sequence caches: the paged
        # mode's savings denominator, and the contiguous mode's (static)
        # KV residency for the hbm-bytes-per-request accounting
        self._kv_bytes_static = sum(
            math.prod(s.shape) * jnp.dtype(s.dtype).itemsize
            for p, s in flat.items() if p[-1] in PAGED_CACHE_LEAVES
        )
        # per-row cache leaves that are NOT pageable (GDN recurrent
        # state, conv tails, toy memories): paging leaves them per-row;
        # their presence auto-disables the prefix cache (their state
        # can't be rebuilt from shared KV pages)
        self._unpageable_leaves = sorted({
            p[-1] for p in flat
            if p[-1] not in PAGED_CACHE_LEAVES and p[-1] != "cache_index"
        })
        self._page_bytes = 0
        out = {}
        for p, s in flat.items():
            if p[-1] == "cache_index":
                # per-row write indices: seed [B] zeros in place of the
                # scalar — the decode modules accept either rank
                out[p] = jnp.zeros((self._b,), jnp.int32)
            elif self._paged and p[-1] in PAGED_CACHE_LEAVES:
                axis = PAGED_CACHE_LEAVES[p[-1]]
                if s.shape[axis] != self._dml:
                    raise ValueError(
                        f"cache leaf {'/'.join(p)} slot axis {axis} is "
                        f"{s.shape[axis]}, expected decode_max_length="
                        f"{self._dml}"
                    )
                pool_shape = (
                    (self._num_pages,) + s.shape[1:axis]
                    + (self._page_size,) + s.shape[axis + 1:]
                )
                if self._kv_quant is not None:
                    # int8 pool + f32 per-(page, slot[, head]) scale
                    # pool: the scale leaf drops only the trailing
                    # feature dim, so one scale covers one slot's
                    # feature vector (the finest granule the one-token
                    # scatter can maintain) and the scale pool indexes
                    # through the SAME page table as its value pool
                    pool = jnp.zeros(pool_shape, jnp.int8)
                    scale = jnp.zeros(pool_shape[:-1], jnp.float32)
                    out[p[:-1] + (p[-1] + PAGED_SCALE_SUFFIX,)] = scale
                    self._page_bytes += scale.nbytes // self._num_pages
                else:
                    pool = jnp.zeros(pool_shape, s.dtype)
                out[p] = pool
                # one table per module scope (identical contents; a few
                # ints per layer) so the module reads its own sibling
                out[p[:-1] + (PAGE_TABLE_LEAF,)] = jnp.zeros(
                    (self._b, self._pages_per_row), jnp.int32
                )
                self._page_bytes += pool.nbytes // self._num_pages
            else:
                out[p] = jnp.zeros(s.shape, s.dtype)
        return unflatten_dict(out)

    # ------------------------------------------------------------------
    # jitted executables

    def _model_step(self, params, cache, tok, pos):
        """One single-token decode call (trace-time helper shared by the
        per-token and fused executables). ``params`` is a TRACED
        argument, never a closure constant: that is what lets
        :meth:`install_weights` swap trees without retracing — the
        executable's signature (shapes/dtypes/placements) is identical
        across publishes, so ``tracked_jit`` sees the same fingerprint.

        A quantized tree (``loop/quantize.py``: int8 ``qvalue`` +
        per-channel ``scale`` sub-leaves) dequantizes HERE, inside the
        traced program: XLA streams the int8 bytes from HBM and widens
        per-tile at the matmul, which is the whole point — the weight
        stream halves while the compiled signature stays a pure
        function of the (quantized) tree's shapes/dtypes. On an
        unquantized tree this is a structural no-op."""
        params = dequantize_params(params)
        kwargs = {"mask": None}
        if self._step_pad is not None:
            kwargs["padding_mask"] = self._step_pad
        logits, state = self._model.apply(
            {"params": params, "cache": cache},
            tok[:, None], pos[:, None],
            method=self._method, mutable=["cache"], **kwargs,
        )
        return state["cache"], logits[:, -1].astype(jnp.float32)

    def _sample(self, row_logits, key):
        if self._temp == 0.0:
            return jnp.argmax(row_logits, axis=-1).astype(jnp.int32)
        return jax.random.categorical(
            key, row_logits / self._temp, axis=-1
        ).astype(jnp.int32)

    def _build_step(self):
        paged = self._paged

        def step_fn(params, cache, tok, pos, key, live):
            cache, row_logits = self._model_step(params, cache, tok, pos)
            nxt = self._sample(row_logits, key)
            # idle rows ride through the static-shape step; pin their
            # write index so an arbitrarily long idle stretch can't
            # overflow capacity or defeat the flash block skip
            cache = _pin_cache_index(cache, live)
            if paged:
                cache = _pin_page_table(cache, live)
            return cache, nxt

        # donate the cache: XLA aliases input buffers to outputs, so the
        # per-step update is in place — no second cache residency or
        # full-cache memcpy per token. Params are NOT donated: the same
        # tree serves every following dispatch.
        return tracked_jit(step_fn, name="serve/step", donate_argnums=1)

    def _build_fused(self, k: int, with_admit: bool):
        """Compile one fused K-step executable. ``with_admit`` variants
        open with the admitted rows' cache zeroing + carry resets fused
        into the same dispatch; the no-admit variant (the steady state:
        every follow-up chunk, all speculative chunks) skips them — the
        masked zero is a full-capacity read+write of every cache leaf,
        exactly the O(s_max) traffic class the fused loop exists to
        avoid paying per chunk.

        Paged mode differences, same dispatch structure: admitted rows
        reset only their PER-ROW leaves (pools are shared; stale page
        bytes sit behind the slot mask) and jump their write index /
        position to ``admit_pos`` — the first token past their prefix-
        cache hit; each step additionally pins dead/idle rows' page
        tables to the garbage page (see :func:`_pin_page_table`)."""
        eos = self._eos
        paged = self._paged
        if paged:
            from d9d_tpu.nn.decode_flags import (
                map_cache_index,
                zero_rows_skip_paged,
            )

        def fused_fn(params, cache, tok, pos, live, rem, key,
                     forced_t, n_forced, emit_from,
                     admit_mask=None, admit_budget=None, admit_pos=None):
            if with_admit:
                # boundary work, fused into the same dispatch: zero
                # admitted rows' cache and reset their carries
                if paged:
                    cache = zero_rows_skip_paged(cache, admit_mask)
                    cache = map_cache_index(
                        cache,
                        lambda idx: jnp.where(admit_mask, admit_pos, idx),
                    )
                    pos = jnp.where(admit_mask, admit_pos, pos)
                else:
                    cache = _zero_row(cache, admit_mask)
                    pos = jnp.where(admit_mask, 0, pos)
                live = jnp.where(admit_mask, True, live)
                rem = jnp.where(admit_mask, admit_budget, rem)
            keys = jax.random.split(key, k)

            def body(carry, xs):
                cache, tok, pos, live, rem = carry
                j, kj, fj = xs
                # input: host-forced prompt token while any remain for
                # this row, else the previous step's sampled token
                inp = jnp.where((j < n_forced) & live, fj, tok)
                inp = jnp.where(live, inp, 0)
                pos_in = jnp.where(live, pos, 0)
                cache, row_logits = self._model_step(
                    params, cache, inp, pos_in
                )
                nxt = self._sample(row_logits, kj)
                emit = live & (j >= emit_from)
                out = jnp.where(emit, nxt, -1)
                # per-row stop masks, applied in-device: the finishing
                # emission itself goes out, then the row is dead for the
                # rest of the chunk (harvested at the boundary)
                rem = rem - emit.astype(jnp.int32)
                died = emit & (rem <= 0)
                if eos is not None:
                    died = died | (emit & (nxt == eos))
                live = live & jnp.logical_not(died)
                tok = jnp.where(live, nxt, tok)
                pos = jnp.where(live, pos + 1, pos)
                cache = _pin_cache_index(cache, live)
                if paged:
                    cache = _pin_page_table(cache, live)
                return (cache, tok, pos, live, rem), out

            (cache, tok, pos, live, rem), toks = jax.lax.scan(
                body, (cache, tok, pos, live, rem),
                (jnp.arange(k, dtype=jnp.int32), keys, forced_t),
            )
            # toks [K, B] → the [B, K] device-side emission buffer the
            # host fetches in ONE readback per chunk
            return cache, tok, pos, live, rem, jnp.moveaxis(toks, 0, 1)

        return tracked_jit(
            fused_fn,
            name=(
                f"serve/fused_k{k}" + ("_paged" if paged else "")
                + ("_admit" if with_admit else "")
            ),
            donate_argnums=(1, 2, 3, 4, 5),
        )

    # ------------------------------------------------------------------
    def submit(
        self,
        prompt: Sequence[int],
        *,
        max_new_tokens: int,
        deadline_s: Optional[float] = None,
        trace_id: Optional[str] = None,
        priority: int = 0,
    ) -> int:
        """Queue a request; returns its request id. Admission happens at
        the next step/chunk boundary with a free slot.

        ``deadline_s`` (relative, host clock) expires the request at the
        next boundary after the deadline passes — whether it is still
        queued or already decoding (partial output is kept, the request
        lands in ``failed[rid] == "deadline"``). With ``max_queue``
        configured, a full queue rejects with :class:`QueueFullError`
        before a rid is allocated.

        ``priority`` is the admission tier (higher = more important).
        It does NOT reorder admission (FIFO — the token-identity
        contract); it orders burn-driven shedding: while an SLO policy
        burns, the fleet autopilot retires the lowest-priority /
        longest-deadline queued requests first (:meth:`cancel_queued`,
        ``failed[rid] == "shed"``) instead of failing traffic uniformly
        at the front door.

        ``trace_id`` carries an existing per-request trace id (the fleet
        mints one at ITS front door and re-submits with it across
        migrations); a direct submit mints a fresh one. Milestones ride
        schema-v3 ``request_trace`` events and the id is readable as
        ``request_stats[rid].trace_id``.
        """
        prompt = [int(x) for x in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}"
            )
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
        need = len(prompt) + max_new_tokens - 1
        if need > self._dml:
            raise ValueError(
                f"prompt {len(prompt)} + max_new_tokens {max_new_tokens}"
                f" - 1 = {need} exceeds decode_max_length={self._dml}"
            )
        if self._paged and not self._kv.fits_ever(need):
            raise ValueError(
                f"request needs {self._kv.pages_needed(need)} pages but "
                f"the pool holds {self._num_pages - 1} allocatable "
                f"(num_pages={self._num_pages}, page_size="
                f"{self._page_size}); it could never be admitted"
            )
        now = time.perf_counter()
        minted_here = trace_id is None
        if minted_here:
            trace_id = mint_trace_id()
        if self._max_queue is not None:
            # count only live waiters: requests whose deadline already
            # passed must not hold queue capacity against new traffic
            self._expire_queued(now)
            if len(self._queue) >= self._max_queue:
                # running-side mirror of the PR 5 queued-side fix: a
                # deadline-expired RUNNING row frees a slot this
                # boundary, which the queue head is guaranteed to admit
                # into — count those frees as capacity before rejecting
                freed = int(self._expire_running(now).sum())
                if freed and self._paged:
                    # paged admission is PAGE-bounded, not slot-bounded:
                    # the freed slot is only real capacity if the queue
                    # head can map onto pages by the next admit boundary
                    # — which flushes deferred frees first, so count
                    # those too (conservative beyond that: prefix hits
                    # and LRU eviction could only help)
                    head = self._queue[0]
                    if (
                        self._kv.pages_needed(head.total_tokens)
                        > self._kv.pages_free_after_flush()
                    ):
                        freed = 0
                if len(self._queue) - freed >= self._max_queue:
                    self.stats.rejected += 1
                    self._count("serve/rejected")
                    if minted_here:
                        # terminal only for a front-door submit: a fleet
                        # placement attempt (external trace id) that
                        # this replica rejects may still land on a
                        # survivor — the fleet emits the terminal event
                        # if ALL reject
                        self._trace(trace_id, "rejected", now,
                                    queued=len(self._queue))
                    raise QueueFullError(
                        f"admission queue full ({len(self._queue)} >= "
                        f"max_queue={self._max_queue}); retry after drain"
                    )
        rid = self._next_rid
        self._next_rid += 1
        self._queue.append(_Request(
            rid, prompt, max_new_tokens,
            deadline_t=now + deadline_s if deadline_s is not None else None,
            trace_id=trace_id,
            priority=int(priority),
        ))
        self.outputs[rid] = []
        self.request_stats[rid] = RequestTelemetry(
            submit_t=now, trace_id=trace_id
        )
        self._gauge_set("serve/queued", len(self._queue))
        self._trace(
            trace_id, "submit", now, rid=rid,
            prompt_len=len(prompt), max_new_tokens=max_new_tokens,
        )
        return rid

    @property
    def active(self) -> int:
        return sum(1 for s in self._slots if s.rid >= 0) + len(self._queue)

    def _busy(self) -> bool:
        return any(s.rid >= 0 for s in self._slots)

    def reset_measurement(self) -> None:
        """Zero the counters, per-request records, accumulated outputs and
        the throughput-rate window. Bench harnesses call this after a
        warmup/compile request so recorded stats (and the
        ``serve/tokens_per_s`` gauge's window) cover only the timed
        window. Only valid while idle — live requests still need their
        ``request_stats`` records."""
        if self.active:
            raise RuntimeError(
                "reset_measurement() with requests queued or in flight"
            )
        self.stats.reset()
        self.request_stats.clear()
        self._finished_rids.clear()
        self.outputs.clear()
        self.done.clear()
        self.failed.clear()
        # KV residency window accounting; the prefix cache itself stays
        # warm deliberately (like compile warmth / _first_readback_t)
        self._peak_running = 0
        if self._paged:
            self._kv.peak_pages_in_use = self._kv.pages_in_use
            self._kv.prefix_hits = 0
            self._kv.prefix_misses = 0
            self._kv.prefix_hit_tokens = 0
        now = time.perf_counter()
        self._rate_win_t0 = now
        self._rate_win_tokens = 0
        self._rate_prev_t0 = now
        self._rate_prev_tokens = 0

    # ------------------------------------------------------------------
    # live weight publish (docs/design/elasticity.md)

    def install_weights(
        self,
        params,
        *,
        version: Optional[int] = None,
        defer_to_idle: bool = False,
    ) -> int:
        """Stage a published parameter tree; the swap happens at the
        next dispatch boundary (chunk boundary in fused mode, step
        boundary in legacy mode) — never mid-chunk, so chunks already
        in flight complete on the weights they were dispatched with.

        The tree must match the serving model's structure, shapes and
        placement (it is the same model, freshly trained): the jitted
        executables then keep their compiled signature and NO
        steady-state recompile happens. ``defer_to_idle`` holds the
        swap until no slot is busy, so requests in flight at install
        time finish wholly on the old generation (note: under sustained
        load this can defer indefinitely — it is a drain-style publish
        for low-traffic windows and deterministic tests). Returns the
        generation number the install will carry.
        """
        # generations are strictly monotonic PER BATCHER: two installs
        # before a boundary get distinct versions, and an external
        # version (a publisher whose own counter lags this batcher's)
        # is floored up rather than allowed to regress — otherwise two
        # different trees could share a stamp and the audit trail
        # couldn't tell which produced a request's tail
        staged = (
            self._pending_weights[1] if self._pending_weights is not None
            else self.weights_version
        )
        floor = max(self.weights_version, staged) + 1
        version = floor if version is None else max(int(version), floor)
        self._pending_weights = (
            _normalize_params(params), int(version), time.perf_counter(),
            bool(defer_to_idle),
        )
        return int(version)

    def _apply_pending_weights(self) -> None:
        """Swap a staged publish in at a dispatch boundary. The old
        tree's device buffers stay alive exactly as long as an
        in-flight chunk references them (XLA holds the arguments), then
        free — device-side donation of nothing: the swap itself moves
        no data and dispatches nothing."""
        if self._pending_weights is None:
            return
        params, version, t0, defer = self._pending_weights
        if defer and self._busy():
            return  # in-flight requests finish on the old weights
        self._pending_weights = None
        self._params = params
        self.weights_version = int(version)
        if self._paged and self._kv.prefix_cache_enabled:
            # cached prefix KV was computed under the OLD weights: a
            # post-publish hit would silently attend stale pages and
            # break the token-identity contract — drop every entry (the
            # next cold fill re-caches under the new generation).
            # In-flight rows are untouched; like the contiguous path,
            # they finish on the cache they built.
            dropped = self._kv.invalidate_prefix_cache()
            if dropped:
                self._count("serve/prefix_cache_invalidated", dropped)
            # stamp the invalidation with the weights generation that
            # caused it: a canary rollback's re-invalidation is then
            # distinguishable from the publish invalidation it undoes
            # (both drop entries; only the stamp tells them apart)
            self._gauge_set("serve/prefix_cache_invalidated_version", version)
            self._note_pages()
        self._count("serve/weight_publish")
        self._observe(
            "serve/weight_publish_s", time.perf_counter() - t0
        )
        self._gauge_set("serve/weights_version", version)
        if is_quantized_tree(params):
            # generation stamp of the last QUANTIZED tree installed (a
            # rollback to full precision leaves it at the rolled-back
            # generation — the gauge answers "which quantizer output is
            # live / was last live", not "is the live tree quantized")
            self._gauge_set("serve/weight_quant_version", version)

    # ------------------------------------------------------------------
    # fleet support (resilience/elastic.ServingFleet)

    def eject_queued(self) -> list[tuple[int, list, int, Optional[float]]]:
        """Remove every queued (never-admitted) request from the
        admission queue; returns ``[(rid, prompt, max_new_tokens,
        deadline_t)]``. The rids' outputs/stats records are left in
        place: the caller (``ServingFleet.shrink``) decides per request
        whether to migrate it (and drop this replica's records) or to
        retire it as an explicit failure — ejection must never make a
        request silently unobservable."""
        out = []
        while self._queue:
            req = self._queue.popleft()
            if self._paged:
                self._kv.forget(req.rid)  # drop any admission memo
            out.append(
                (req.rid, list(req.prompt), req.max_new_tokens,
                 req.deadline_t)
            )
        if out:
            self._gauge_set("serve/queued", 0)
        return out

    def fail_request(self, rid: int, reason: str) -> None:
        """Retire a not-yet-finished request as an explicit failure
        (``failed[rid] = reason``, partial output kept) — the fleet's
        surface for requests it cannot migrate."""
        if rid in self.done:
            return
        self._fail(rid, reason, time.perf_counter())

    def cancel_queued(self, rid: int, reason: str = "shed") -> bool:
        """Remove a still-QUEUED (never-admitted) request and retire it
        as an explicit failure (``failed[rid] = reason``, observable
        empty output) — the autopilot's shed surface. Returns False
        when ``rid`` is not in the queue (already admitted, finished,
        or unknown): an in-flight request is never yanked mid-decode;
        the caller decides what to do instead."""
        for req in self._queue:
            if req.rid == rid:
                self._queue.remove(req)
                if self._paged:
                    self._kv.forget(rid)  # drop any admission memo
                self._fail(rid, reason, time.perf_counter())
                self._gauge_set("serve/queued", len(self._queue))
                return True
        return False

    # ------------------------------------------------------------------
    # cross-replica KV page shipment (docs/design/elasticity.md
    # "Disaggregated serving"): a prefill replica exports the READY
    # prefix pages covering a prompt; a decode replica imports them as
    # ready prefix entries and copies the payloads into its own pool.
    # Pure transfers at clean chunk boundaries — page pulls/pushes are
    # untracked device array ops, never tracked_jit dispatches, so the
    # steady-state executable census and the dispatch counts the bench
    # gates are untouched. EVERY failure (dirty boundary, version skew,
    # checksum mismatch, allocation shortfall) returns None/False and
    # the caller falls back to plain continuation re-prefill — fallback,
    # not failure, is the contract.

    def _pool_leaves(self) -> dict:
        """Paged pool leaves (values + int8 scale siblings) by path."""
        from flax.traverse_util import flatten_dict

        from d9d_tpu.nn.decode_flags import (
            PAGED_CACHE_LEAVES,
            PAGED_SCALE_SUFFIX,
        )

        return {
            "/".join(p): leaf
            for p, leaf in flatten_dict(self._cache).items()
            if p[-1] in PAGED_CACHE_LEAVES
            or p[-1].endswith(PAGED_SCALE_SUFFIX)
        }

    def export_kv_pages(
        self,
        tokens: Sequence[int],
        *,
        transfer_budget_bytes: int = _TRANSFER_BUDGET_BYTES,
    ) -> Optional["KVPageShipment"]:
        """Pull the READY prefix pages covering ``tokens``' leading
        full blocks off the device pool, chunk-by-chunk under
        ``transfer_budget_bytes`` (the ``_chunked_place`` discipline
        from ``resilience/elastic.py`` — bounded host staging however
        large the run). Returns None when not paged, mid-chunk (only a
        clean boundary has an exact pool view), or nothing is cached —
        the caller re-prefills instead."""
        if not self._paged or self._pending:
            return None
        # same boundary discipline as import: a staged publish means the
        # cache below is the OLD generation — apply it (invalidating the
        # stale entries) rather than stamping dead pages with a version
        # the importer would refuse anyway
        self._apply_pending_weights()
        tokens = [int(x) for x in tokens]
        pages = self._kv.export_prefix(tokens)
        if not pages:
            return None
        leaves = self._pool_leaves()
        chunk_len = max(
            1, int(transfer_budget_bytes) // max(1, self._page_bytes)
        )
        parts: dict[str, list] = {name: [] for name in leaves}
        chunks = 0
        for a in range(0, len(pages), chunk_len):
            idx = jnp.asarray(np.asarray(pages[a:a + chunk_len], np.int32))
            for name, pool in leaves.items():
                # d9d-lint: disable=D9D003 — bounded page-payload pull at
                # a clean boundary (a transfer, not a decode readback)
                parts[name].append(np.asarray(pool[idx]))
            chunks += 1
        payload = {
            name: np.concatenate(arrs, axis=0)
            for name, arrs in parts.items()
        }
        ship = KVPageShipment(
            page_size=self._page_size,
            tokens=tokens[: len(pages) * self._page_size],
            n_pages=len(pages),
            weights_version=self.weights_version,
            kv_quant=self._kv_quant,
            payload=payload,
            checksums=_page_checksums(payload),
            chunks=chunks,
        )
        self._count("serve/handoff_exports")
        self._count("serve/handoff_pages", len(pages))
        self._count("serve/handoff_bytes", ship.nbytes)
        self._count("serve/handoff_chunks", chunks)
        return ship

    def import_kv_pages(
        self,
        ship: "KVPageShipment",
        *,
        transfer_budget_bytes: int = _TRANSFER_BUDGET_BYTES,
    ) -> bool:
        """Install a shipment's pages as READY prefix entries and copy
        the payloads into this replica's pool (chunked under the same
        transfer budget). Checksums are verified BEFORE any allocator
        or pool mutation — a corrupt/truncated shipment is detected and
        rejected whole, never half-imported. A weights-generation
        mismatch (or a publish staged here) rejects too: cached KV is
        weights-dependent, the same invariant as ``install_weights``
        prefix invalidation. Returns False on any rejection — the
        caller falls back to continuation re-prefill."""
        if not self._paged or self._pending:
            return False
        # an import IS a dispatch-boundary mutation: swap a staged
        # publish in first, exactly as the next _dispatch_chunk would —
        # otherwise a freshly-grown (idle) replica still reports the
        # pre-publish generation and refuses every current-gen shipment
        self._apply_pending_weights()
        if (
            ship.page_size != self._page_size
            or ship.kv_quant != self._kv_quant
            or not self._kv.prefix_cache_enabled
        ):
            return False
        if (
            ship.weights_version != self.weights_version
            or self._pending_weights is not None
        ):
            self._count("serve/handoff_version_mismatch")
            return False
        leaves = self._pool_leaves()
        if set(ship.payload) != set(leaves) or any(
            ship.payload[n].shape[0] != ship.n_pages for n in ship.payload
        ):
            self._count("serve/handoff_checksum_failures")
            return False
        if _page_checksums(ship.payload) != list(ship.checksums):
            self._count("serve/handoff_checksum_failures")
            return False
        placed = self._kv.import_pages(ship.tokens, ship.n_pages)
        if placed is None:
            return False
        chunk_len = max(
            1, int(transfer_budget_bytes) // max(1, self._page_bytes)
        )
        flat = None
        for a in range(0, len(placed), chunk_len):
            part = placed[a:a + chunk_len]
            src = np.asarray([b for b, _ in part], np.int32)
            dest = jnp.asarray(np.asarray([p for _, p in part], np.int32))
            if flat is None:
                from flax.traverse_util import flatten_dict

                flat = flatten_dict(self._cache)
            for name in leaves:
                path = tuple(name.split("/"))
                flat[path] = flat[path].at[dest].set(
                    jnp.asarray(ship.payload[name][src])
                )
        if flat is not None:
            from flax.traverse_util import unflatten_dict

            self._cache = unflatten_dict(flat)
        self._count("serve/handoff_imports")
        self._count("serve/handoff_pages", len(placed))
        self._note_pages()
        return True

    # ------------------------------------------------------------------
    # paged KV bookkeeping (loop/kv_paging.py): all host work, all at
    # the existing chunk boundaries — the dispatch/readback contract and
    # the tracked_jit fingerprints are untouched

    def _try_alloc(self, row: int, req: _Request):
        """Map the queue head onto pages (prefix-cache walk + free-list
        allocation); None leaves it queued — admission is bounded by
        free pages, not rows."""
        alloc = self._kv.admit(row, req.rid, req.prompt, req.total_tokens)
        if alloc is None:
            return None
        self._kv_table_dirty = True
        if self._kv.prefix_cache_enabled:
            if alloc.hit_tokens:
                self._count("serve/prefix_cache_hits")
                self._count(
                    "serve/prefix_cache_hit_tokens", alloc.hit_tokens
                )
            else:
                self._count("serve/prefix_cache_misses")
        return alloc

    def _push_page_table(self) -> None:
        """Sync the device page tables from the host mirror (a tiny
        host→device transfer between dispatches — NOT a tracked
        dispatch). Only ever called at clean boundaries (no chunks in
        flight), so a zeroed row reroutes any still-live zombie row's
        writes to the garbage page before its next chunk."""
        if not self._kv_table_dirty:
            return
        self._kv_table_dirty = False
        from d9d_tpu.nn.decode_flags import map_page_table

        table = self._kv.table
        # one fresh buffer PER leaf: the cache is donated into the
        # fused dispatch, and donating one shared buffer through N
        # layer scopes trips XLA's double-donation check
        self._cache = map_page_table(
            self._cache, lambda _pt: jnp.asarray(table)
        )

    def _release_row_pages(self, row: int, *, device_dead: bool) -> None:
        """Drop a retired row's page references. ``device_dead`` rows
        (finished in-device: their writes are already pinned to the
        garbage page) free immediately; host-side kills with chunks in
        flight DEFER — the device twin may still be live and writing
        into these pages, so they stay held until the zeroed table row
        has been pushed at a clean boundary (``flush_deferred``)."""
        if device_dead or not self._pending:
            self._kv.release(row)
        else:
            self._kv.defer_release(row)
        self._kv_table_dirty = True
        self._note_pages()

    def _note_pages(self) -> None:
        """Refresh the page-pool gauges (and the peak-concurrency
        accounting both modes share) — pure host arithmetic."""
        running = sum(1 for s in self._slots if s.rid >= 0)
        self._peak_running = max(self._peak_running, running)
        if not self._paged:
            return
        in_use = self._kv.pages_in_use
        self._gauge_set("serve/kv_pages_in_use", in_use)
        self._gauge_set("serve/kv_pages_free", self._kv.pages_free)
        self._gauge_set(
            "serve/kv_hbm_bytes_per_request",
            in_use * self._page_bytes / max(1, running),
        )

    def hbm_bytes_per_request(self) -> float:
        """Peak resident KV bytes over peak concurrent running requests
        for the current measurement window — deterministic given the
        schedule, so the bench gate can pin it exactly. Contiguous mode
        charges the full static allocation (every row's
        decode_max_length is resident whether used or not); paged mode
        charges pages actually mapped."""
        if self._paged:
            resident = self._kv.peak_pages_in_use * self._page_bytes
        else:
            resident = self._kv_bytes_static
        return resident / max(1, self._peak_running)

    def prefix_hit_rate(self) -> float:
        """Admissions served (partly) from the prefix cache over all
        admissions in the window; 0.0 when disabled or idle."""
        if self._kv is None:
            return 0.0
        total = self._kv.prefix_hits + self._kv.prefix_misses
        return self._kv.prefix_hits / total if total else 0.0

    # ------------------------------------------------------------------
    # request latency telemetry (host clock only; see RequestTelemetry)

    def _note_admit(self, rid: int) -> None:
        rec = self.request_stats[rid]
        rec.admit_t = time.perf_counter()
        self._observe("serve/queue_wait_s", rec.queue_wait_s)
        self._gauge_set("serve/queued", len(self._queue))
        self._trace(rec.trace_id, "admit", rec.admit_t, rid=rid)

    def _note_tokens(self, rid: int, n: int, now: float) -> None:
        rec = self.request_stats[rid]
        if rec.first_tok_t is None:
            rec.first_tok_t = now
            self._observe("serve/ttft_s", rec.ttft_s)
            self._trace(rec.trace_id, "first_token", now, rid=rid)
        rec.tokens += n

    def _note_finish(
        self, rid: int, now: float, version: Optional[int] = None
    ) -> None:
        rec = self.request_stats[rid]
        rec.finish_t = now
        rec.weights_version = (
            version if version is not None else self.weights_version
        )
        tpot = rec.tpot_s
        if tpot is not None:
            self._observe("serve/tpot_s", tpot)
        self._observe(
            "serve/request_tokens", float(rec.tokens), _REQ_TOKENS_EDGES
        )
        self._count("serve/requests_finished")
        self._trace(
            rec.trace_id, "finish", now, rid=rid,
            tokens=rec.tokens, weights_version=rec.weights_version,
        )
        self._retire(rid)

    def _retire(self, rid: int) -> None:
        # bound the finished/failed-request retention (FIFO) — stats
        # record, output token list, done and failed flags together, so
        # host memory stays flat however many requests a long-lived
        # server processes; the aggregate histograms already captured
        # the latencies
        self._finished_rids.append(rid)
        while len(self._finished_rids) > self._MAX_FINISHED_STATS:
            old = self._finished_rids.popleft()
            self.request_stats.pop(old, None)
            self.outputs.pop(old, None)
            self.done.discard(old)
            self.failed.pop(old, None)

    # -- degraded mode: deadlines (docs/design/resilience.md) ----------

    def _fail(self, rid: int, reason: str, now: float) -> None:
        self.failed[rid] = reason
        self.done.add(rid)
        if self._paged:
            # a request that failed mid-prompt-fill must not leave its
            # half-written pages hit-eligible in the prefix cache
            self._kv.abort_filling(rid)
        # accounting keyed on the reason: "expired" means deadline
        # expiry and nothing else (the degraded-mode signal operators
        # alert on); "shed" is the autopilot's deliberate load-shedding
        # (its own alertable signal — shed traffic is policy, not a
        # fault); other retirements (fleet shrink) count serve/failed
        if reason == "deadline":
            self.stats.expired += 1
            self._count("serve/expired")
        elif reason == "shed":
            self.stats.shed += 1
            self._count("serve/shed")
        else:
            self._count("serve/failed")
        rec = self.request_stats.get(rid)
        if rec is not None and rec.finish_t is None:
            rec.finish_t = now
        if rec is not None:
            self._trace(
                rec.trace_id,
                "expired" if reason == "deadline" else "failed",
                now, rid=rid, reason=reason, tokens=rec.tokens,
            )
        self._retire(rid)

    def _expire_queued(self, now: float) -> None:
        """Drop queued requests whose deadline passed — an explicit
        failure the caller can observe, not a silent never-ran."""
        if not self._queue:
            return
        live = collections.deque()
        for req in self._queue:
            if req.deadline_t is not None and now >= req.deadline_t:
                self._fail(req.rid, "deadline", now)
            else:
                live.append(req)
        if len(live) != len(self._queue):
            self._queue = live
            self._gauge_set("serve/queued", len(self._queue))

    def _expire_running(self, now: float) -> np.ndarray:
        """Evict running rows past their deadline at a boundary; returns
        the evicted-row mask (legacy mode resets those cache rows; fused
        mode leaves the device row decoding into the void until the slot
        is reused — emissions for a done rid are dropped at harvest)."""
        evict = np.zeros((self._b,), bool)
        for i, slot in enumerate(self._slots):
            if (
                slot.rid < 0
                or slot.deadline_t is None
                or now < slot.deadline_t
                or slot.rid in self.done
            ):
                continue
            self._fail(slot.rid, "deadline", now)
            self._slots[i] = _Slot()
            self._tokens[i] = 0
            evict[i] = True
            if self._paged:
                # the device twin may still be live: defer the free
                # when chunks are in flight (see _release_row_pages)
                self._release_row_pages(i, device_dead=False)
        return evict

    # rolling-window span for the live throughput gauge: long enough to
    # average over scheduling noise, short enough that a collapse shows
    # within seconds on an operator's console/dashboard
    _RATE_WINDOW_S = 10.0
    # finished RequestTelemetry records retained for the host stats API
    _MAX_FINISHED_STATS = 50_000

    def _live_rate(self) -> float:
        """Tokens over the current + previous window, against the age of
        the older one — evaluated at flush/snapshot time, so it reflects
        'now' even when no harvest has run since the last flush."""
        dt = time.perf_counter() - self._rate_prev_t0
        if dt <= 0:
            return float("nan")
        return (self._rate_win_tokens + self._rate_prev_tokens) / dt

    def _note_throughput(self, new_tokens: int, now: float) -> None:
        self._count("serve/tokens", new_tokens)
        self._gauge_set(
            "serve/slot_utilization", self.stats.slot_utilization
        )
        self._rate_win_tokens += new_tokens
        if now - self._rate_win_t0 >= self._RATE_WINDOW_S:
            self._rate_prev_t0 = self._rate_win_t0
            self._rate_prev_tokens = self._rate_win_tokens
            self._rate_win_t0 = now
            self._rate_win_tokens = 0

    # ------------------------------------------------------------------
    # legacy per-token path (chunk_size=None): the exactness oracle for
    # the fused path and the latency-critical single-token mode

    def _admit_legacy(self):
        with annotate("serve.admit"):
            now = time.perf_counter()
            self._expire_queued(now)
            reset_mask = self._expire_running(now)
            admit_pos = np.zeros((self._b,), np.int32)
            if self._paged and self._kv.flush_deferred():
                self._kv_table_dirty = True  # legacy: always clean
            for i, slot in enumerate(self._slots):
                if slot.rid >= 0 or not self._queue:
                    continue
                req = self._queue[0]
                start_pos = 0
                if self._paged:
                    alloc = self._try_alloc(i, req)
                    if alloc is None:
                        break  # head-of-line waits for pages to free
                    start_pos = alloc.start_pos
                self._queue.popleft()
                self._slots[i] = _Slot(
                    rid=req.rid,
                    pending=list(req.prompt[start_pos + 1:]),
                    pos=start_pos,
                    emitted=0,
                    budget=req.max_new_tokens,
                    deadline_t=req.deadline_t,
                )
                self._tokens[i] = req.prompt[start_pos]
                reset_mask[i] = True
                admit_pos[i] = start_pos
                self._note_admit(req.rid)
            if reset_mask.any():
                if self._paged:
                    self._cache = self._reset(
                        self._cache, jnp.asarray(reset_mask),
                        jnp.asarray(admit_pos),
                    )
                else:
                    self._cache = self._reset(
                        self._cache, jnp.asarray(reset_mask)
                    )
                self.stats.host_dispatches += 1
            if self._paged:
                self._push_page_table()
            self._note_pages()

    def _step_legacy(self) -> dict[int, int]:
        self._apply_pending_weights()
        self._admit_legacy()
        if not self._busy():
            return {}
        if self._step is None:
            self._step = self._build_step()
        pos = np.asarray([s.pos for s in self._slots], np.int32)
        live = np.asarray([s.rid >= 0 for s in self._slots], bool)
        self._rng, sub = jax.random.split(self._rng)
        with annotate("serve.dispatch"):
            self._cache, nxt = self._step(
                self._params, self._cache, jnp.asarray(self._tokens),
                jnp.asarray(pos), sub, jnp.asarray(live),
            )
        with annotate("serve.readback"):
            # d9d-lint: disable=D9D003 — the one [B] readback per legacy token step
            nxt = np.asarray(nxt)
        now = time.perf_counter()
        self._progress_t = now
        if self._first_readback_t is None:
            self._first_readback_t = now
        self.stats.host_dispatches += 1
        self.stats.readbacks += 1
        self.stats.device_steps += 1
        self.stats.slot_steps_total += self._b
        self.stats.slot_steps_busy += int(live.sum())
        self._observe("serve/slot_util", live.sum() / self._b, _UTIL_EDGES)

        emitted: dict[int, int] = {}
        evict_mask = np.zeros((self._b,), bool)
        for i, slot in enumerate(self._slots):
            if slot.rid < 0:
                continue
            slot.pos += 1
            if self._paged and not slot.pending:
                # the whole prompt has been dispatched: this rid's
                # prefix-cache entries become hit-eligible (idempotent)
                self._kv.mark_filled(slot.rid)
            if slot.pending:  # still consuming the prompt
                self._tokens[i] = slot.pending.pop(0)
                continue
            tok = int(nxt[i])  # sampled from the row's latest position
            emitted[slot.rid] = tok
            self.outputs[slot.rid].append(tok)
            slot.emitted += 1
            self.stats.emitted_tokens += 1
            self._note_tokens(slot.rid, 1, now)
            finished = slot.emitted >= slot.budget or (
                self._eos is not None and tok == self._eos
            )
            if finished:
                self._note_finish(slot.rid, now)
                self.done.add(slot.rid)
                self._slots[i] = _Slot()
                self._tokens[i] = 0
                evict_mask[i] = True
                if self._paged:
                    # legacy rows only step under a host live mask, so
                    # a cleared slot can never write again: free now
                    self._release_row_pages(i, device_dead=True)
            else:
                self._tokens[i] = tok
        self._note_throughput(len(emitted), now)
        if evict_mask.any():
            # reset at EVICTION, not just admission, so the freed row's
            # cache contents can't leak into a same-rid-free diagnostic
            # view; the overflow/block-skip concern itself is handled by
            # the in-step cache_index pin
            if self._paged:
                self._cache = self._reset(
                    self._cache, jnp.asarray(evict_mask),
                    jnp.zeros((self._b,), jnp.int32),
                )
            else:
                self._cache = self._reset(
                    self._cache, jnp.asarray(evict_mask)
                )
            self.stats.host_dispatches += 1
        return emitted

    # ------------------------------------------------------------------
    # fused path: one dispatch + one readback per K-step chunk

    def _dispatch_chunk(self, k: int, admit: bool) -> None:
        """Build the host plan for one fused chunk and dispatch it.

        ``admit`` must only be True when no chunk is in flight (the
        host's slot view is then exact); speculative follow-up chunks
        dispatch with ``admit=False`` and a plan that is deterministic
        given the previous dispatch (prompt feeding advances host-side,
        everything else is a device carry).
        """
        self._apply_pending_weights()
        admit_mask = np.zeros((self._b,), bool)
        admit_budget = np.zeros((self._b,), np.int32)
        admit_pos = np.zeros((self._b,), np.int32)
        if admit:
            with annotate("serve.admit"):
                now = time.perf_counter()
                self._expire_queued(now)
                self._expire_running(now)
                if self._paged and self._kv.flush_deferred():
                    # admit=True ⇒ no chunks in flight: deferred zombie
                    # pages free now; the zeroed table rows push below,
                    # BEFORE this dispatch
                    self._kv_table_dirty = True
                for i, slot in enumerate(self._slots):
                    if slot.rid >= 0 or not self._queue:
                        continue
                    req = self._queue[0]
                    start_pos = 0
                    if self._paged:
                        alloc = self._try_alloc(i, req)
                        if alloc is None:
                            break  # head-of-line waits for pages
                        start_pos = alloc.start_pos
                    self._queue.popleft()
                    self._slots[i] = _Slot(
                        rid=req.rid,
                        # a prefix-cache hit skips the cached tokens:
                        # feeding resumes at the first un-cached one
                        feed=list(req.prompt[start_pos:]),
                        emitted=0,
                        budget=req.max_new_tokens,
                        deadline_t=req.deadline_t,
                    )
                    admit_mask[i] = True
                    admit_budget[i] = req.max_new_tokens
                    admit_pos[i] = start_pos
                    self._note_admit(req.rid)
                if self._paged:
                    self._push_page_table()
                self._note_pages()

        forced = np.zeros((self._b, k), np.int32)
        n_forced = np.zeros((self._b,), np.int32)
        emit_from = np.full((self._b,), k, np.int32)
        rids = []
        for i, slot in enumerate(self._slots):
            rids.append(slot.rid)
            if slot.rid < 0:
                continue
            m = len(slot.feed)
            nf = min(m, k)
            if nf:
                forced[i, :nf] = slot.feed[:nf]
            n_forced[i] = nf
            emit_from[i] = max(m - 1, 0)
            slot.feed = slot.feed[k:]

        self._rng, sub = jax.random.split(self._rng)
        with_admit = bool(admit_mask.any())
        fused = self._fused.get((k, with_admit))
        if fused is None:
            fused = self._fused[(k, with_admit)] = self._build_fused(
                k, with_admit
            )
        admit_args = ()
        if with_admit:
            admit_args = (jnp.asarray(admit_mask), jnp.asarray(admit_budget))
            if self._paged:
                admit_args += (jnp.asarray(admit_pos),)
        with annotate("serve.dispatch"):
            (self._cache, self._tok_d, self._pos_d, self._live_d,
             self._rem_d, toks) = fused(
                self._params, self._cache, self._tok_d, self._pos_d,
                self._live_d, self._rem_d, sub,
                # forced_t: scan xs layout [K, B]
                jnp.asarray(forced.T), jnp.asarray(n_forced),
                jnp.asarray(emit_from),
                *admit_args,
            )
        if self._paged:
            for slot in self._slots:
                if slot.rid >= 0 and not slot.feed:
                    # the whole prompt is now DISPATCHED: this rid's
                    # prefix-cache entries become hit-eligible — later
                    # admits dispatch after, so their reads see the
                    # writes (idempotent across chunks)
                    self._kv.mark_filled(slot.rid)
        self._pending.append(
            (toks,
             _ChunkPlan(k=k, rids=rids, emit_from=emit_from.tolist(),
                        version=self.weights_version))
        )
        self.stats.host_dispatches += 1
        self.stats.chunks += 1
        self.stats.device_steps += k
        self._progress_t = time.perf_counter()

    def _harvest_one(self) -> dict[int, list[int]]:
        """Fetch the oldest in-flight chunk (ONE readback) and replay the
        device's emission/stop logic on it to commit host state."""
        toks_d, plan = self._pending.popleft()
        with annotate("serve.readback"):
            # d9d-lint: disable=D9D003 — the single [B, K] readback per chunk
            toks = np.asarray(toks_d)
        now = time.perf_counter()
        self._progress_t = now
        if self._first_readback_t is None:
            self._first_readback_t = now
        self.stats.readbacks += 1
        self.stats.slot_steps_total += self._b * plan.k
        chunk_busy = 0
        chunk_tokens = 0
        emitted: dict[int, list[int]] = {}
        for i, rid in enumerate(plan.rids):
            if rid < 0 or rid in self.done:
                # idle at dispatch, or finished in an earlier chunk that
                # was harvested after this one was (speculatively)
                # dispatched — the device masked it dead already
                continue
            slot = self._slots[i]
            # exact occupancy, replayed like the device's stop masks: a
            # row is busy through the step it dies on, idle after
            busy_steps = plan.k
            for j in range(min(plan.emit_from[i], plan.k), plan.k):
                tok = int(toks[i, j])
                emitted.setdefault(rid, []).append(tok)
                self.outputs[rid].append(tok)
                slot.emitted += 1
                self.stats.emitted_tokens += 1
                chunk_tokens += 1
                if slot.emitted >= slot.budget or (
                    self._eos is not None and tok == self._eos
                ):
                    self.done.add(rid)
                    self._slots[i] = _Slot()
                    busy_steps = j + 1
                    if self._paged:
                        # the device row died IN-DEVICE at this same
                        # step (its later writes are pinned to the
                        # garbage page), so the pages free immediately;
                        # reuse waits for the next admit boundary,
                        # which pushes the zeroed table row first
                        self._release_row_pages(i, device_dead=True)
                    break
            self.stats.slot_steps_busy += busy_steps
            chunk_busy += busy_steps
            if rid in emitted:
                self._note_tokens(rid, len(emitted[rid]), now)
                if rid in self.done:
                    self._note_finish(rid, now, version=plan.version)
        self._observe(
            "serve/slot_util", chunk_busy / (self._b * plan.k), _UTIL_EDGES
        )
        self._note_throughput(chunk_tokens, now)
        return emitted

    def _sync(self) -> dict[int, list[int]]:
        out: dict[int, list[int]] = {}
        while self._pending:
            for rid, toks in self._harvest_one().items():
                out.setdefault(rid, []).extend(toks)
        return out

    def _may_outlive_pending(self) -> bool:
        """Could any busy row still be live after the in-flight chunks?

        With no EOS, stopping is budget-only and fully host-predictable,
        so a speculative chunk that could only serve dead rows is never
        dispatched. With an EOS id any emission may stop a row — the
        host can't know until readback, so speculation proceeds (worst
        case: one wasted chunk at the tail of a drain).
        """
        if self._eos is not None:
            return True
        proj = {
            i: s.emitted for i, s in enumerate(self._slots) if s.rid >= 0
        }
        for _toks, plan in self._pending:
            for i in proj:
                if plan.rids[i] == self._slots[i].rid:
                    proj[i] += max(0, plan.k - plan.emit_from[i])
        return any(
            proj[i] < self._slots[i].budget for i in proj
        )

    def step_chunk(self) -> dict[int, list[int]]:
        """Admit waiting requests, advance every slot ``chunk_size``
        tokens in ONE dispatch; returns ``{rid: [tokens]}`` emitted
        (generation phase) during the chunk. Fused mode only."""
        if self._k is None:
            raise RuntimeError(
                "step_chunk() needs a fused batcher (chunk_size not None)"
            )
        self._sync()
        if not self._busy() and not self._queue:
            return {}
        self._dispatch_chunk(self._k, admit=True)
        return self._sync()

    def step(self) -> dict[int, int]:
        """Admit waiting requests, advance every slot one token; returns
        ``{rid: token}`` for tokens emitted (generation phase) this step.

        In fused mode this runs a K=1 chunk (same one-dispatch boundary
        semantics); with ``chunk_size=None`` it is the legacy per-token
        path.
        """
        if self._k is None:
            return self._step_legacy()
        self._sync()
        if not self._busy() and not self._queue:
            return {}
        self._dispatch_chunk(1, admit=True)
        return {
            rid: toks[0] for rid, toks in self._sync().items() if toks
        }

    def drain(self, max_steps: int = 100_000) -> dict[int, list[int]]:
        """Run until every submitted request has finished.

        Fused mode pipelines chunks double-buffered: while no admissions
        are waiting, the next chunk is dispatched BEFORE the previous
        chunk's tokens are fetched, overlapping the host readback with
        device compute (XLA async dispatch). Admission needs an exact
        slot view, so a non-empty queue forces a synchronous boundary.

        With ``stall_timeout_s`` set, a watchdog thread monitors
        dispatch/readback progress and converts a wedge into
        :class:`ServeStalledError`. (The interrupt lands between Python
        bytecodes: it catches host-visible stalls — a retry loop, a
        deadlocked lock, a sleeping fake — immediately; a readback
        hard-wedged inside the runtime's C++ is additionally covered by
        the process-level ``TimeoutManager`` watchdog.)
        """
        if self._stall_timeout_s is None:
            return self._drain_impl(max_steps)
        if threading.current_thread() is not threading.main_thread():
            # the watchdog interrupts via a signal to the MAIN thread; a
            # drain on a worker thread cannot be safely interrupted that
            # way (the exception would land in an unrelated thread)
            import warnings

            warnings.warn(
                "serve stall watchdog disabled: drain() is not on the "
                "main thread", stacklevel=2,
            )
            return self._drain_impl(max_steps)
        self._stalled = False
        self._progress_t = time.perf_counter()
        stop = threading.Event()

        main_ident = threading.main_thread().ident

        def watch():
            tick = min(0.05, self._stall_timeout_s / 4)
            fired = 0
            while not stop.wait(tick):
                if self.stats.readbacks == 0:
                    # nothing has ever round-tripped: the gap is almost
                    # certainly first-call XLA compilation, which can
                    # legitimately run minutes — interrupting it would
                    # fail a healthy cold start (and land the signal
                    # inside the compiler). A wedge this early is the
                    # process-level TimeoutManager's job.
                    continue
                if (
                    time.perf_counter() - self._progress_t
                    > self._stall_timeout_s * (1 + fired)
                ):
                    if stop.is_set():  # drain just finished: stand down
                        return
                    self._stalled = True
                    if fired == 0:
                        self._count("serve/stalls")
                    fired += 1
                    try:
                        # a real signal: wakes blocking C calls (sleeps,
                        # waits) via EINTR, unlike interrupt_main's
                        # between-bytecodes flag. Keep re-firing on a
                        # backoff rather than one-shot: an embedder's
                        # own SIGINT handler (graceful-shutdown servers,
                        # PreemptionGuard) swallows the first delivery
                        # without raising KeyboardInterrupt.
                        import signal

                        signal.pthread_kill(main_ident, signal.SIGINT)
                    except (OSError, AttributeError, ValueError):
                        _thread.interrupt_main()

        watchdog = threading.Thread(
            target=watch, name="d9d-serve-stall-watchdog", daemon=True
        )
        watchdog.start()
        try:
            return self._drain_impl(max_steps)
        except KeyboardInterrupt:
            if self._stalled:
                # black-box dump before surfacing the wedge: the recent
                # metric windows + span tail at the moment of the stall
                # (no-op unless a flight recorder is configured)
                self._tele.dump_flight_record(
                    "serve_stall",
                    extra={
                        "replica": self._replica_label,
                        "active": self.active,
                        "stall_timeout_s": self._stall_timeout_s,
                    },
                )
                raise ServeStalledError(
                    f"serving drain made no dispatch/readback progress "
                    f"for {self._stall_timeout_s}s with "
                    f"{self.active} request(s) outstanding"
                ) from None
            raise
        finally:
            stop.set()
            watchdog.join(timeout=1.0)

    def _drain_impl(self, max_steps: int) -> dict[int, list[int]]:
        if self._k is None:
            steps = 0
            while self.active:
                self._step_legacy()
                steps += 1
                if steps > max_steps:
                    raise RuntimeError("drain exceeded max_steps")
            return self.outputs

        steps = 0
        while self.active or self._pending:
            # admissions are waiting: sync so freed slots refill promptly
            # (and so the admit plan sees exact state)
            while self._pending and self._queue:
                self._harvest_one()
            if self._queue or (self._busy() and self._may_outlive_pending()):
                self._dispatch_chunk(self._k, admit=not self._pending)
                steps += self._k
                if steps > max_steps:
                    self._sync()
                    raise RuntimeError("drain exceeded max_steps")
                # keep at most one chunk in flight beyond the newest: the
                # harvest of chunk N overlaps chunk N+1's device compute
                while len(self._pending) > (1 if self._overlap else 0):
                    self._harvest_one()
            elif self._pending:
                self._harvest_one()
        return self.outputs
