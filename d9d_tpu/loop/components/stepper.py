"""Step counting + periodic-action predicates.

Reference: d9d/loop/component/stepper.py:8 (``Stepper``, ``StepActionPeriod``).
"""

import dataclasses


@dataclasses.dataclass
class StepActionPeriod:
    """Fire every ``period`` steps (and optionally on the final step)."""

    period: int
    on_last: bool = True

    def should_fire(self, step: int, total_steps: int | None = None) -> bool:
        if self.period > 0 and (step + 1) % self.period == 0:
            return True
        if self.on_last and total_steps is not None and step + 1 == total_steps:
            return True
        return False


@dataclasses.dataclass
class Stepper:
    total_steps: int | None = None
    step: int = 0

    def advance(self) -> int:
        self.step += 1
        return self.step

    @property
    def finished(self) -> bool:
        return self.total_steps is not None and self.step >= self.total_steps

    def state_dict(self) -> dict:
        return {"step": self.step}

    def load_state_dict(self, state: dict) -> None:
        self.step = int(state["step"])
