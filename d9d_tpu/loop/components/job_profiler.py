"""Periodic jax.profiler tracing.

Reference: d9d/internals/profiling/profile.py:11 + loop/component/
job_profiler.py:13 — torch.profiler with a wait/warmup/active periodic
schedule, per-rank chrome traces. TPU equivalent: ``jax.profiler`` traces
(viewable in XProf/TensorBoard, incl. device HLO timelines); one trace dir
per cycle, named by step and process index.
"""

import logging
from pathlib import Path

import jax

from d9d_tpu.core.tracing import set_trace_annotations

logger = logging.getLogger("d9d_tpu.profiler")


class JobProfiler:
    """Trace ``active_steps`` steps every ``every_steps`` (first cycle after
    ``wait_steps``). No-op when ``every_steps`` is None."""

    def __init__(
        self,
        trace_dir: str | Path | None = None,
        *,
        every_steps: int | None = None,
        active_steps: int = 3,
        wait_steps: int = 10,
    ):
        self.trace_dir = Path(trace_dir) if trace_dir else None
        self.every_steps = every_steps
        self.active_steps = active_steps
        self.wait_steps = wait_steps
        self._tracing_until: int | None = None

    def _should_start(self, step: int) -> bool:
        if self.every_steps is None or self.trace_dir is None:
            return False
        if step < self.wait_steps:
            return False
        return (step - self.wait_steps) % self.every_steps == 0

    def step_begin(self, step: int) -> None:
        if self._tracing_until is None and self._should_start(step):
            out = self.trace_dir / f"step_{step}_proc_{jax.process_index()}"
            out.mkdir(parents=True, exist_ok=True)
            logger.info("profiler: tracing steps %d..%d -> %s",
                        step, step + self.active_steps - 1, out)
            # host-side action/staging annotations only exist inside
            # capture windows — zero cost on unprofiled steps
            set_trace_annotations(True)
            jax.profiler.start_trace(str(out))
            self._tracing_until = step + self.active_steps

    def step_end(self, step: int) -> None:
        if self._tracing_until is not None and step + 1 >= self._tracing_until:
            jax.profiler.stop_trace()
            set_trace_annotations(False)
            self._tracing_until = None

    def close(self) -> None:
        if self._tracing_until is not None:
            jax.profiler.stop_trace()
            set_trace_annotations(False)
            self._tracing_until = None
