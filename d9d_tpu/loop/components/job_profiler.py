"""Periodic + on-demand jax.profiler tracing.

Reference: d9d/internals/profiling/profile.py:11 + loop/component/
job_profiler.py:13 — torch.profiler with a wait/warmup/active periodic
schedule, per-rank chrome traces. TPU equivalent: ``jax.profiler`` traces
(viewable in XProf/TensorBoard, incl. device HLO timelines); one trace dir
per cycle, named by step and process index.

Two capture modes share one profiler (jax allows exactly one live trace
per process, so they are mutually exclusive and lock-guarded):

- the original step-cadence schedule (``every_steps``/``active_steps``);
- :meth:`capture` — a wall-clock one-shot for operator-driven captures
  (``MetricsServer`` ``/debug/profile``) and the ``FlightRecorder``
  capture hook: start a trace now, stop it on a timer thread after
  ``duration_s``. Both modes run the ``HostSampler``
  (telemetry/host_sampler.py) over the controller thread for the window
  and emit its folded stacks as a schema-v5 ``host_stacks`` event, so
  every device trace ships with matching host attribution.
"""

import logging
import threading
import time
from pathlib import Path

import jax

from d9d_tpu.core.tracing import set_trace_annotations
from d9d_tpu.telemetry import get_telemetry
from d9d_tpu.telemetry.host_sampler import HostSampler

logger = logging.getLogger("d9d_tpu.profiler")


class JobProfiler:
    """Trace ``active_steps`` steps every ``every_steps`` (first cycle after
    ``wait_steps``). No-op when ``every_steps`` is None. ``capture()``
    works regardless of the cadence config (it needs no trace_dir — the
    caller supplies the output directory)."""

    def __init__(
        self,
        trace_dir: str | Path | None = None,
        *,
        every_steps: int | None = None,
        active_steps: int = 3,
        wait_steps: int = 10,
    ):
        self.trace_dir = Path(trace_dir) if trace_dir else None
        self.every_steps = every_steps
        self.active_steps = active_steps
        self.wait_steps = wait_steps
        self._tracing_until: int | None = None
        self._lock = threading.Lock()
        self._capture_dir: Path | None = None
        self._capture_timer: threading.Timer | None = None
        self._sampler: HostSampler | None = None

    @property
    def capture_active(self) -> bool:
        """A one-shot :meth:`capture` is currently live (step-cadence
        windows don't count — callers gate new captures on this)."""
        with self._lock:
            return self._capture_dir is not None

    def _should_start(self, step: int) -> bool:
        if self.every_steps is None or self.trace_dir is None:
            return False
        if step < self.wait_steps:
            return False
        return (step - self.wait_steps) % self.every_steps == 0

    def _start_sampler(self) -> None:
        self._sampler = HostSampler()
        self._sampler.start()

    def _stop_sampler(self) -> None:
        if self._sampler is None:
            return
        record = self._sampler.stop()
        self._sampler = None
        try:
            get_telemetry().record_host_stacks(record)
        except Exception:  # noqa: BLE001 — a sink failure must not
            # take down the trace stop path
            logger.warning("host-stacks emission failed", exc_info=True)

    def step_begin(self, step: int) -> None:
        if self._tracing_until is None and self._should_start(step):
            with self._lock:
                if self._capture_dir is not None:
                    return  # a one-shot capture owns the profiler
                out = (
                    self.trace_dir
                    / f"step_{step}_proc_{jax.process_index()}"
                )
                out.mkdir(parents=True, exist_ok=True)
                logger.info("profiler: tracing steps %d..%d -> %s",
                            step, step + self.active_steps - 1, out)
                # host-side action/staging annotations only exist inside
                # capture windows — zero cost on unprofiled steps
                set_trace_annotations(True)
                jax.profiler.start_trace(str(out))
                # sampler last: start_trace's first-use initialization can
                # take seconds and must not pollute the host-stacks window
                # (mirror of the stop ordering in step_end)
                self._start_sampler()
                self._tracing_until = step + self.active_steps

    def step_end(self, step: int) -> None:
        if self._tracing_until is not None and step + 1 >= self._tracing_until:
            with self._lock:
                # sampler first: stop_trace serializes the xplane (can
                # take seconds) and that teardown must not pollute the
                # host-stacks window
                self._stop_sampler()
                jax.profiler.stop_trace()
                set_trace_annotations(False)
                self._tracing_until = None

    # -- on-demand one-shot capture ------------------------------------

    def capture(
        self, duration_s: float, out_dir: str | Path
    ) -> Path | None:
        """Start a wall-clock one-shot capture into ``out_dir`` and
        return the capture directory immediately (the trace stops on a
        timer thread after ``duration_s``). Returns ``None`` — never
        raises to its caller's caller — when the profiler is already
        busy (a cadence window or another capture is live)."""
        with self._lock:
            if self._capture_dir is not None or self._tracing_until is not None:
                return None
            stamp = time.strftime("%Y%m%d_%H%M%S")
            out = (
                Path(out_dir)
                / f"ondemand_{stamp}_proc{jax.process_index()}"
            )
            out.mkdir(parents=True, exist_ok=True)
            logger.info(
                "profiler: on-demand capture (%.1fs) -> %s",
                duration_s, out,
            )
            set_trace_annotations(True)
            try:
                jax.profiler.start_trace(str(out))
            except Exception:
                set_trace_annotations(False)
                raise
            # sampler after start_trace: first-use profiler init can take
            # seconds and must not pollute the host-stacks window (the
            # stop side mirrors this — sampler stops before stop_trace)
            self._start_sampler()
            self._capture_dir = out
            tele = get_telemetry()
            tele.counter("profile/captures").add(1)
            tele.gauge("profile/last_duration_s").set(duration_s)
            timer = threading.Timer(
                max(duration_s, 0.05), self._finish_capture
            )
            timer.daemon = True
            self._capture_timer = timer
            timer.start()
            return out

    def _finish_capture(self) -> None:
        with self._lock:
            if self._capture_dir is None:
                return
            self._stop_sampler()  # before stop_trace: see step_end
            try:
                jax.profiler.stop_trace()
            except Exception:  # noqa: BLE001 — a stop race (close()
                # already stopped it) must not kill the timer thread
                logger.warning("capture stop failed", exc_info=True)
            set_trace_annotations(False)
            logger.info(
                "profiler: on-demand capture done -> %s", self._capture_dir
            )
            self._capture_dir = None
            self._capture_timer = None

    def close(self) -> None:
        timer = self._capture_timer
        if timer is not None:
            timer.cancel()
        self._finish_capture()  # no-op when no capture is live
        with self._lock:
            if self._tracing_until is not None:
                self._stop_sampler()
                jax.profiler.stop_trace()
                set_trace_annotations(False)
                self._tracing_until = None
