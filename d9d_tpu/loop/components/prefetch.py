"""Background batch prefetch: overlap host input work with device steps.

Reference: d9d/loop/component/data_loader_factory.py:102 — torchdata's
worker-backed ``StatefulDataLoader`` keeps batch N+1's host work off the
step path. TPU equivalent (VERDICT r3 item 4): a producer thread runs the
host input pipeline — raw fetch from the loader and task
``prepare_batch`` (numpy), plus device staging whenever that is
collective-free — ``depth`` batches ahead of the consuming train loop,
so step N's compute overlaps step N+1's input processing. Single-process
runs stage in the producer too (async ``device_put``); multi-process
runs MUST stage on the consumer thread via ``finish_fn`` — ``device_put``
onto a multi-process sharding performs a cross-process consistency
collective, and producer-thread collectives interleave differently per
process against the main thread's step collectives (observed deadlock on
the 2-process rig).

Exact resume stays exact: the producer snapshots the loader's *position*
right after each fetch (the loader advances before yielding, so the
snapshot IS the resume point after consuming that batch), and the
consumer records the snapshot of every batch it hands out. Checkpoints
then serialize the loader state *as of the consumed batch* via
``StatefulDataLoader.state_dict_at`` — never the producer's run-ahead
position.
"""

import queue
import threading
from collections.abc import Callable, Iterator
from typing import Any

from d9d_tpu.core.tracing import annotate
from d9d_tpu.core.types import PyTree
from d9d_tpu.telemetry import get_telemetry

__all__ = ["BatchPrefetcher"]

_DONE = object()


class BatchPrefetcher:
    """Iterator of staged batches produced ``depth`` ahead on a thread.

    ``stage_fn`` runs in the producer thread (prepare + device staging);
    ``position_fn`` (optional) snapshots the underlying loader position
    after each raw fetch — :attr:`consumed_position` then tracks the
    resume point of the last batch handed to the consumer.
    """

    def __init__(
        self,
        data_iter: Iterator[PyTree],
        stage_fn: Callable[[PyTree], PyTree],
        *,
        depth: int = 2,
        position_fn: Callable[[], Any] | None = None,
        finish_fn: Callable[[PyTree], PyTree] | None = None,
    ):
        """``stage_fn`` runs in the producer thread; ``finish_fn`` (if
        given) runs on the CONSUMER thread at ``__next__``. Multi-process
        trainers must keep ``device_put`` onto multi-process shardings in
        ``finish_fn``: jax turns it into a cross-process consistency
        collective (``multihost_utils.assert_equal``), and collectives
        issued from a producer thread interleave differently per process
        against the main thread's step collectives — a deadlock observed
        on the 2-process e2e rig. Host-only work (tokenize/pack/reshape)
        stays safely in the producer."""
        if depth < 1:
            raise ValueError("prefetch depth must be >= 1")
        self._iter = data_iter
        self._stage_fn = stage_fn
        self._finish_fn = finish_fn
        self._position_fn = position_fn
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self.consumed_position: Any | None = None
        self._thread = threading.Thread(
            target=self._produce, name="d9d-batch-prefetch", daemon=True
        )
        self._thread.start()

    # -- producer ------------------------------------------------------

    def _put(self, item) -> bool:
        """Bounded put that aborts promptly when the consumer closed."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _produce(self) -> None:
        try:
            while not self._stop.is_set():
                try:
                    raw = next(self._iter)
                except StopIteration:
                    self._put(_DONE)
                    return
                pos = self._position_fn() if self._position_fn else None
                with annotate("loop.prefetch_stage"):
                    staged = self._stage_fn(raw)
                if not self._put(("batch", staged, pos)):
                    return
        except BaseException as e:  # noqa: BLE001 — reraised in consumer
            get_telemetry().counter("io/prefetch_errors").add(1)
            self._put(("error", e, None))

    # -- consumer ------------------------------------------------------

    def __iter__(self):
        return self

    def __next__(self) -> PyTree:
        # bounded waits + liveness checks: a producer thread that dies
        # without delivering its sentinel (injected crash, interpreter
        # teardown racing a worker) must surface as an immediate,
        # explanatory error — not an unbounded q.get() hang
        while True:
            try:
                item = self._q.get(timeout=1.0)
                break
            except queue.Empty:
                if not self._thread.is_alive():
                    # the producer may have enqueued its final item (a
                    # real error, end-of-data) and exited between our
                    # timeout and this liveness check — drain once more
                    # before declaring a silent death, or the generic
                    # message would shadow the real diagnostic
                    try:
                        item = self._q.get_nowait()
                        break
                    except queue.Empty:
                        pass
                    raise RuntimeError(
                        "prefetch producer thread died without delivering "
                        "a batch, an error, or end-of-data (last consumed "
                        f"position: {self.consumed_position})"
                    ) from None
        if item is _DONE:
            raise StopIteration
        kind, payload, pos = item
        if kind == "error":
            # the producer's exception travels intact (DataFetchError
            # carries the failing epoch/batch position in its message)
            raise payload
        if self._finish_fn is not None:
            payload = self._finish_fn(payload)
        # only after the batch is fully materialized for the consumer —
        # a finish_fn failure must not mark the batch consumed (exact
        # resume would skip it)
        self.consumed_position = pos
        return payload

    def close(self) -> None:
        """Stop the producer and release its queue slot."""
        self._stop.set()
        try:  # unblock a producer waiting on a full queue
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5.0)
