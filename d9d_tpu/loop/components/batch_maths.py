"""Global-batch ↔ microbatch arithmetic.

Reference: d9d/loop/component/batch_maths.py:5. One place owns the
divisibility rules between global batch size, microbatch size, and the
data-parallel world so every component agrees on counts.
"""

import dataclasses

from d9d_tpu.core.mesh import MeshContext


@dataclasses.dataclass(frozen=True)
class BatchMaths:
    global_batch_size: int
    microbatch_size: int
    dp_size: int

    def __post_init__(self) -> None:
        if self.global_batch_size % self.microbatch_size != 0:
            raise ValueError(
                f"global_batch_size {self.global_batch_size} not divisible by "
                f"microbatch_size {self.microbatch_size}"
            )
        if self.microbatch_size % self.dp_size != 0:
            raise ValueError(
                f"microbatch_size {self.microbatch_size} (global across DP) not "
                f"divisible by dp_size {self.dp_size}"
            )

    @staticmethod
    def from_context(
        ctx: MeshContext, global_batch_size: int, microbatch_size: int
    ) -> "BatchMaths":
        return BatchMaths(
            global_batch_size=global_batch_size,
            microbatch_size=microbatch_size,
            dp_size=ctx.axis_size(*ctx.batch_axes),
        )

    @property
    def num_microbatches(self) -> int:
        """Gradient-accumulation steps per optimizer step."""
        return self.global_batch_size // self.microbatch_size

    @property
    def microbatch_size_per_dp_rank(self) -> int:
        return self.microbatch_size // self.dp_size
