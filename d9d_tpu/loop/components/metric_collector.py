"""Device-buffered metric collection for the training loop.

Reference: d9d/internals/metric_collector/collector.py:10
(AsyncMetricCollector runs metric sync on a side CUDA stream) and
d9d/loop/component/job_logger.py:44 (flush cadence into the tracker).

TPU redesign: there is no side stream to manage — XLA's async dispatch
*is* the side stream. Raw task statistics accumulate as device arrays
(`carry + step_stats`, enqueued without blocking); only ``flush`` on the
log cadence materializes them to host, feeds the task's Metric objects,
runs their cross-process ``sync()``, computes, and pushes results to the
tracker. Between flushes the host never waits on a metric.
"""

import jax
import numpy as np

from d9d_tpu.core.types import PyTree
from d9d_tpu.loop.control.task import TrainTask
from d9d_tpu.telemetry import tracked_jit

TASK_STAT_PREFIX = "task/"


def _flatten_result(name: str, value) -> dict[str, float]:
    """Metric compute() results → flat scalar dict for the tracker."""
    out: dict[str, float] = {}
    if isinstance(value, dict):
        for k, v in value.items():
            out.update(_flatten_result(f"{name}/{k}", v))
        return out
    arr = np.asarray(value)
    if arr.ndim == 0:
        out[name] = float(arr)
    else:
        for i, v in enumerate(arr.reshape(-1)):
            out[f"{name}/{i}"] = float(v)
    return out


class MetricCollector:
    def __init__(self, task: TrainTask):
        self.task = task
        self.metrics = task.metrics()
        self._carry: PyTree | None = None
        # runs every step (device-side accumulate): tracked so its
        # compile/recompiles are visible like the rest of the step path
        self._add = tracked_jit(
            lambda a, b: jax.tree.map(lambda x, y: x + y, a, b),
            name="metric/accumulate",
        )

    def collect(self, step_metrics: dict) -> None:
        """Accumulate this step's raw task statistics on device (async)."""
        if not self.metrics:
            return
        stats = {
            k[len(TASK_STAT_PREFIX):]: v
            for k, v in step_metrics.items()
            if k.startswith(TASK_STAT_PREFIX)
        }
        if not stats:
            return
        self._carry = (
            stats if self._carry is None else self._add(self._carry, stats)
        )

    def flush(self, run, step: int) -> dict[str, float]:
        """Materialize the window's statistics, update/sync/compute every
        task metric, push to the tracker, reset for the next window."""
        if not self.metrics or self._carry is None:
            return {}
        host_stats = jax.tree.map(np.asarray, jax.device_get(self._carry))
        self._carry = None
        self.task.update_metrics(self.metrics, host_stats)
        results: dict[str, float] = {}
        for name, metric in self.metrics.items():
            metric.sync()
            results.update(_flatten_result(name, metric.compute()))
            metric.reset()
        if run is not None:
            for k, v in results.items():
                run.track_scalar(
                    f"metric/{k}", v, step=step, context={"subset": "train"}
                )
        return results
