"""Stateful data loading.

Reference: d9d/loop/component/data_loader_factory.py:102
(``StatefulDataLoaderDataParallelAware``) — a loader whose position
(epoch, batch index, shuffle RNG) is part of the job checkpoint, with
state namespaced per data-parallel feeder so resume is exact. Under
single-controller JAX the feeder unit is the *process* (each host stages
its slice of the global batch), so state keys are ``process_{i}``.
"""

import time
from collections.abc import Callable, Sequence
from typing import Any

import jax
import numpy as np

from d9d_tpu.core.types import PyTree
from d9d_tpu.telemetry import get_telemetry


def default_collate(items: Sequence[PyTree]) -> PyTree:
    """Stack a list of same-structure pytrees of arrays along a new batch
    leading dim (numpy, host-side)."""
    return jax.tree.map(lambda *xs: np.stack([np.asarray(x) for x in xs]), *items)


class DataFetchError(RuntimeError):
    """A batch fetch failed after exhausting its retry budget.

    Carries the failing position so the consumer-side re-raise (possibly
    on the other end of a prefetch queue) names exactly which batch died
    instead of surfacing a bare timeout.
    """

    def __init__(self, message: str, *, epoch: int, batch_index: int):
        super().__init__(message)
        self.epoch = epoch
        self.batch_index = batch_index


class StatefulDataLoader:
    """Map-style dataset → batch iterator with exact-resume state.

    Shuffling draws a fresh permutation per epoch from ``seed + epoch`` so
    resume mid-epoch reproduces the same order without storing the
    permutation itself.
    """

    def __init__(
        self,
        dataset,
        batch_size: int,
        *,
        collate_fn: Callable[[Sequence[Any]], PyTree] = default_collate,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = True,
        num_epochs: int | None = 1,
        retry_attempts: int = 0,
        retry_backoff_s: float = 0.05,
        retry_max_backoff_s: float = 5.0,
    ):
        if batch_size <= 0:
            raise ValueError("batch_size must be positive")
        if retry_attempts < 0:
            raise ValueError("retry_attempts must be >= 0")
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.num_epochs = num_epochs
        # transient-fetch resilience (docs/design/resilience.md): each
        # batch fetch retries up to retry_attempts times with capped
        # exponential backoff before failing the run with a
        # DataFetchError naming the epoch/batch position
        self.retry_attempts = retry_attempts
        self.retry_backoff_s = retry_backoff_s
        self.retry_max_backoff_s = retry_max_backoff_s
        self._epoch = 0
        self._batch_index = 0

    def _fetch_batch(self, idxs: np.ndarray, b: int) -> PyTree:
        """One batch fetch + collate with capped-exponential retry.
        Retries restart the whole batch (a flaky source may fail partway
        through the item list) and count into ``io/data_retries``."""
        attempt = 0
        while True:
            try:
                items = [self.dataset[int(i)] for i in idxs]
                return self.collate_fn(items)
            except Exception as e:  # noqa: BLE001 — classified below
                if attempt >= self.retry_attempts:
                    raise DataFetchError(
                        f"batch fetch failed at epoch {self._epoch} batch "
                        f"{b} after {attempt + 1} attempt(s): "
                        f"{type(e).__name__}: {e}",
                        epoch=self._epoch,
                        batch_index=b,
                    ) from e
                delay = min(
                    self.retry_backoff_s * (2.0 ** attempt),
                    self.retry_max_backoff_s,
                )
                get_telemetry().counter("io/data_retries").add(1)
                attempt += 1
                time.sleep(delay)

    def __len__(self) -> int:
        n = len(self.dataset)
        per_epoch = (
            n // self.batch_size if self.drop_last else -(-n // self.batch_size)
        )
        return per_epoch if self.num_epochs is None else per_epoch * self.num_epochs

    def _epoch_order(self, epoch: int) -> np.ndarray:
        n = len(self.dataset)
        if not self.shuffle:
            return np.arange(n)
        return np.random.default_rng(self.seed + epoch).permutation(n)

    def __iter__(self):
        while self.num_epochs is None or self._epoch < self.num_epochs:
            order = self._epoch_order(self._epoch)
            n_batches = len(order) // self.batch_size
            if not self.drop_last and len(order) % self.batch_size:
                n_batches += 1
            while self._batch_index < n_batches:
                b = self._batch_index
                t_fetch = time.perf_counter()
                idxs = order[b * self.batch_size : (b + 1) * self.batch_size]
                batch = self._fetch_batch(idxs, b)
                # io/* telemetry: the producer-side fetch+collate cost —
                # distinct from the trainer's train/phase/data_wait, which
                # only sees this when prefetch is off or falls behind
                get_telemetry().histogram("io/data_fetch_s").record(
                    time.perf_counter() - t_fetch
                )
                # yield BEFORE advancing: a checkpoint taken after the step
                # that consumed batch b must record position b+1
                self._batch_index = b + 1
                yield batch
            self._epoch += 1
            self._batch_index = 0

    # -- state ---------------------------------------------------------

    def position(self) -> dict[str, Any]:
        """Local position snapshot (no collectives — safe to call from a
        prefetch producer thread after each fetch). Because ``__iter__``
        advances ``batch_index`` before yielding, the snapshot taken after
        fetching batch ``b`` is exactly the resume point for a job that
        consumed ``b``."""
        my = {"epoch": self._epoch, "batch_index": self._batch_index}
        if hasattr(self.dataset, "state_dict"):
            my["dataset"] = self.dataset.state_dict()
        return my

    def _merged_state(self, my: dict[str, Any]) -> dict[str, Any]:
        if jax.process_count() == 1:
            return {"process_0": my}
        # every feeder's position must land in the (primary-written) job
        # meta, so gather all processes' states and return the merged dict
        from d9d_tpu.core.collectives import host_allgather_object

        return {
            f"process_{i}": s
            for i, s in enumerate(host_allgather_object(my))
        }

    def state_dict(self) -> dict[str, Any]:
        return self._merged_state(self.position())

    def state_dict_at(self, position: dict[str, Any]) -> dict[str, Any]:
        """State dict for an explicit :meth:`position` snapshot — how a
        prefetching trainer checkpoints the *consumed* position while the
        producer thread runs ahead (collective; call from the main thread
        on every process together)."""
        return self._merged_state(position)

    def load_state_dict(self, state: dict[str, Any]) -> None:
        key = f"process_{jax.process_index()}"
        if key not in state:
            raise KeyError(
                f"loader state has no entry for {key} (keys: {list(state)})"
            )
        my = state[key]
        self._epoch = my["epoch"]
        self._batch_index = my["batch_index"]
        if "dataset" in my and hasattr(self.dataset, "load_state_dict"):
            self.dataset.load_state_dict(my["dataset"])
