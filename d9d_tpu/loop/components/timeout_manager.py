"""Hang detection via a watchdog thread.

Reference: d9d/loop/component/timeout_manager.py:15 — two-phase NCCL
timeouts (generous at init, tight per-step) so a hung collective kills the
job fast instead of burning a pod for hours. JAX has no per-collective
timeout knob, so the TPU equivalent is a host watchdog: the trainer pets it
at every step boundary; if no heartbeat arrives within the active window
the watchdog dumps all Python stacks and hard-exits, letting the job
scheduler restart-and-resume (the reference's recovery model).
"""

import faulthandler
import logging
import os
import sys
import threading
import time

logger = logging.getLogger("d9d_tpu.timeout")


class TimeoutManager:
    def __init__(
        self,
        *,
        init_timeout_s: float | None = None,
        step_timeout_s: float | None = None,
    ):
        self.init_timeout_s = init_timeout_s
        self.step_timeout_s = step_timeout_s
        self._deadline: float | None = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def _arm(self, timeout_s: float | None) -> None:
        with self._lock:
            self._deadline = (
                time.monotonic() + timeout_s if timeout_s is not None else None
            )

    def set_init(self) -> None:
        self._arm(self.init_timeout_s)

    def set_periodic(self) -> None:
        """Heartbeat: call at every step boundary."""
        self._arm(self.step_timeout_s)

    def disarm(self) -> None:
        self._arm(None)

    def _watch(self) -> None:
        while not self._stop.wait(1.0):
            with self._lock:
                deadline = self._deadline
            if deadline is not None and time.monotonic() > deadline:
                logger.critical(
                    "watchdog timeout: no step heartbeat — dumping stacks and exiting"
                )
                faulthandler.dump_traceback(file=sys.stderr)
                os._exit(42)

    def __enter__(self):
        if self.init_timeout_s is not None or self.step_timeout_s is not None:
            self._stop = threading.Event()  # fresh per entry: reusable
            self._thread = threading.Thread(
                target=self._watch, name="d9d-watchdog", daemon=True
            )
            self._thread.start()
            self.set_init()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self.disarm()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        return False
