"""Hang detection via a watchdog thread.

Reference: d9d/loop/component/timeout_manager.py:15 — two-phase NCCL
timeouts (generous at init, tight per-step) so a hung collective kills the
job fast instead of burning a pod for hours. JAX has no per-collective
timeout knob, so the TPU equivalent is a host watchdog: the trainer pets it
at every step boundary; if no heartbeat arrives within the active window
the watchdog flushes the telemetry sinks (with a final
``resilience/watchdog_timeout`` event, so the JSONL log explains the
death), dumps all Python stacks and hard-exits with a configurable,
documented exit code (docs/design/resilience.md exit-code contract),
letting the job scheduler restart-and-resume (the reference's recovery
model).
"""

import faulthandler
import logging
import os
import sys
import threading
import time

from d9d_tpu.telemetry import get_telemetry

logger = logging.getLogger("d9d_tpu.timeout")


class TimeoutManager:
    def __init__(
        self,
        *,
        init_timeout_s: float | None = None,
        step_timeout_s: float | None = None,
        exit_code: int = 42,
    ):
        self.init_timeout_s = init_timeout_s
        self.step_timeout_s = step_timeout_s
        self.exit_code = exit_code
        self._deadline: float | None = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def _arm(self, timeout_s: float | None) -> None:
        with self._lock:
            self._deadline = (
                time.monotonic() + timeout_s if timeout_s is not None else None
            )

    def set_init(self) -> None:
        self._arm(self.init_timeout_s)

    def set_periodic(self) -> None:
        """Heartbeat: call at every step boundary."""
        self._arm(self.step_timeout_s)

    def disarm(self) -> None:
        self._arm(None)

    def _flush_telemetry(self) -> None:
        """Best-effort: a final watchdog_timeout event + sink flush so
        the on-disk JSONL records *why* the process died. The main
        thread is wedged (that is why we are here), so only the host-
        side registry/sinks are touched — never the device."""
        try:
            tele = get_telemetry()
            tele.counter("resilience/watchdog_timeout").add(1)
            # spans stream to the JSONL sink as they complete: this is
            # the "final event" an operator greps for post-mortem
            tele.registry.record_span(
                "resilience/watchdog_timeout",
                time.perf_counter(),
                0.0,
                meta={"exit_code": self.exit_code},
            )
            tele.flush(tele.registry.current_step)
        except Exception:  # noqa: BLE001 — never block the hard exit
            logger.exception("telemetry flush failed during watchdog exit")

    def _watch(self) -> None:
        while not self._stop.wait(1.0):
            with self._lock:
                deadline = self._deadline
            if deadline is not None and time.monotonic() > deadline:
                logger.critical(
                    "watchdog timeout: no step heartbeat — dumping stacks "
                    "and exiting with code %d", self.exit_code,
                )
                # the flush itself may block (a hung storage mount is a
                # classic cause of the missed heartbeat): bound it with a
                # helper thread so the guaranteed-exit contract holds
                flusher = threading.Thread(
                    target=self._flush_telemetry, daemon=True
                )
                flusher.start()
                flusher.join(timeout=5.0)
                faulthandler.dump_traceback(file=sys.stderr)
                os._exit(self.exit_code)

    def __enter__(self):
        if self.init_timeout_s is not None or self.step_timeout_s is not None:
            self._stop = threading.Event()  # fresh per entry: reusable
            self._thread = threading.Thread(
                target=self._watch, name="d9d-watchdog", daemon=True
            )
            self._thread.start()
            self.set_init()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self.disarm()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        return False
