"""Host batch → device staging, shared by Trainer and Inference.

Reshapes the prepared batch to [num_microbatches, microbatch, ...] and
places it with dp sharding on the batch dim; on context-parallel meshes
the sequence dim additionally shards over cp_s — but only for leaves whose
dim 2 both equals the configured sequence length AND divides evenly by the
cp size (a [B, T+1] raw-ids leaf or ragged feature leaf falls back to
batch-only sharding rather than failing device_put).
"""

from collections.abc import Callable

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from d9d_tpu.core.mesh import MeshContext
from d9d_tpu.core.types import PyTree


def make_batch_stager(
    ctx: MeshContext,
    *,
    num_microbatches: int,
    microbatch_size: int,
    seq_len: int,
) -> Callable[[PyTree], PyTree]:
    seq_sharding = NamedSharding(
        ctx.mesh, P(None, ctx.batch_axes, ctx.sequence_axes)
    )
    flat_sharding = NamedSharding(ctx.mesh, P(None, ctx.batch_axes))
    cp_size = ctx.axis_size(*ctx.sequence_axes)

    def stage(batch: PyTree) -> PyTree:
        def reshape(x):
            x = np.asarray(x)
            if x.shape[0] != num_microbatches * microbatch_size:
                raise ValueError(
                    f"batch leading dim {x.shape[0]} != global batch "
                    f"{num_microbatches * microbatch_size}"
                )
            return x.reshape(
                num_microbatches, microbatch_size, *x.shape[1:]
            )

        def pick(x):
            if x.ndim >= 3 and x.shape[2] == seq_len and seq_len % cp_size == 0:
                return seq_sharding
            return flat_sharding

        batch_r = jax.tree.map(reshape, batch)
        return jax.device_put(batch_r, jax.tree.map(pick, batch_r))

    return stage
