"""Host batch → device staging, shared by Trainer and Inference.

Reshapes the prepared batch to [num_microbatches, microbatch, ...] and
places it with dp sharding on the batch dim; on context-parallel meshes
the sequence dim additionally shards over cp_s — but only for leaves whose
dim 2 both equals the configured sequence length AND divides evenly by the
cp size (a [B, T+1] raw-ids leaf or ragged feature leaf falls back to
batch-only sharding rather than failing device_put).
"""

import warnings
from collections.abc import Callable

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from d9d_tpu.core.mesh import MeshContext
from d9d_tpu.core.tracing import annotate
from d9d_tpu.core.types import PyTree


def split_microbatches(
    prepared: PyTree, *, num_microbatches: int, microbatch_size: int
) -> list[PyTree]:
    """Host-side: cut a prepared global batch into a microbatch list (the
    pipeline executor places each carry/kwargs/state on its stage's
    submesh, so no device_put happens here)."""
    n, m = num_microbatches, microbatch_size

    def cut(x):
        x = np.asarray(x)
        if x.shape[0] != n * m:
            raise ValueError(
                f"batch leading dim {x.shape[0]} != global batch {n * m}"
            )
        return x.reshape(n, m, *x.shape[1:])

    stacked = jax.tree.map(cut, prepared)
    return [jax.tree.map(lambda x: x[i], stacked) for i in range(n)]


def make_batch_stager(
    ctx: MeshContext,
    *,
    num_microbatches: int,
    microbatch_size: int,
    seq_len: int,
) -> Callable[[PyTree], PyTree]:
    seq_sharding = NamedSharding(
        ctx.mesh, P(None, ctx.batch_axes, ctx.sequence_axes)
    )
    flat_sharding = NamedSharding(ctx.mesh, P(None, ctx.batch_axes))
    cp_size = ctx.axis_size(*ctx.sequence_axes)
    if cp_size > 1 and seq_len % cp_size != 0:
        # an off-by-one here used to silently un-shard every sequence leaf,
        # changing memory/perf without failing (VERDICT r1 Weak #7)
        raise ValueError(
            f"seq_len {seq_len} not divisible by the context-parallel axis "
            f"size {cp_size}; no leaf could ever be sequence-sharded"
        )
    warned_shapes: set[tuple[int, ...]] = set()

    def stage(batch: PyTree) -> PyTree:
        def reshape(x):
            x = np.asarray(x)
            if x.shape[0] != num_microbatches * microbatch_size:
                raise ValueError(
                    f"batch leading dim {x.shape[0]} != global batch "
                    f"{num_microbatches * microbatch_size}"
                )
            return x.reshape(
                num_microbatches, microbatch_size, *x.shape[1:]
            )

        def pick(x):
            if x.ndim >= 3 and x.shape[2] == seq_len:
                return seq_sharding
            if cp_size > 1 and x.ndim >= 3 and x.shape[2] != seq_len:
                if x.shape not in warned_shapes:
                    warned_shapes.add(x.shape)
                    warnings.warn(
                        f"batch leaf with shape {x.shape} has a dim-2 of "
                        f"{x.shape[2]} != seq_len {seq_len}; it will be "
                        "batch-sharded only and bypass context-parallel "
                        "sequence sharding",
                        stacklevel=2,
                    )
            return flat_sharding

        with annotate("loop.batch_staging"):
            batch_r = jax.tree.map(reshape, batch)
            return jax.device_put(batch_r, jax.tree.map(pick, batch_r))

    return stage
