"""Manual garbage collection.

Reference: d9d/loop/component/garbage_collector.py:13 — automatic GC causes
jittery step times on the hot loop (host must keep up with async dispatch
on TPU just as with CUDA streams); disable it and collect deterministically
every N steps instead.
"""

import gc


class ManualGarbageCollector:
    def __init__(self, every_steps: int | None = 100):
        self.every_steps = every_steps
        self._was_enabled = False

    def __enter__(self):
        if self.every_steps is not None:
            self._was_enabled = gc.isenabled()
            gc.disable()
            gc.collect()
        return self

    def __exit__(self, *exc):
        if self.every_steps is not None and self._was_enabled:
            gc.enable()
        return False

    def step(self, step: int) -> None:
        if self.every_steps is not None and step % self.every_steps == 0:
            gc.collect()
