"""Job-state checkpointing: save-{step} dirs, rotation, resume-latest.

Reference: d9d/loop/component/checkpointer.py:27 (StateCheckpointer over
torch DCP). The TPU equivalent rides orbax: arrays (params, optimizer
state, rng) go through ``StandardSave`` (sharded, parallel-IO), host-side
scalars (stepper, dataloader position, tracker run hash, task state) ride
a JSON item. Directory layout mirrors the reference contract (orbax
spelling): ``{dir}/save_{step}/`` with ``num_to_keep`` rotation and
resume = latest.

Integrity (docs/design/resilience.md): every finalized step directory
gets a ``d9d_manifest.json`` — content checksums over the meta item and
small index files plus a size inventory of the array files — written
*after* the step's data is durable (at the next async barrier).
``restore()`` validates the newest step against its manifest and walks
back through the rotation history to the newest step that both
validates and restores, instead of crashing on a truncated directory —
covering the machine-died-mid-async-save case the finalize rename alone
cannot.

Elastic topology (docs/design/elasticity.md): ``save()`` records the
saving mesh in the manifest (schema v2 ``mesh`` block); ``restore()``
compares it against the restore target's mesh and, on a mismatch,
reshard-on-loads — orbax reads shard-local byte ranges into the new
placement, counted and timed under ``resilience/reshard_restore``.
With ``reshard_hbm_budget_bytes`` set, leaves whose per-device
materialization would exceed the budget restore through a
device-sharded staging layout and are then re-placed chunk by chunk
(``resilience/elastic.redistribute_tree``), bounding the transient
footprint of any single array.
"""

import logging
import time
from pathlib import Path
from typing import Any

import jax
import orbax.checkpoint as ocp

from d9d_tpu.core.types import PyTree
from d9d_tpu.resilience.manifest import (
    CheckpointIntegrityError,
    manifest_mesh,
    validate_checkpoint_dir,
    write_manifest,
)
from d9d_tpu.telemetry import get_telemetry

logger = logging.getLogger("d9d_tpu.checkpointer")

_ARRAYS = "arrays"
_META = "meta"

# rate limit for the unverified-restore warning: the counter records
# every occurrence, the log line shows up once per interval per process
_UNVERIFIED_WARN_INTERVAL_S = 300.0
_last_unverified_warn = -float("inf")


def _note_unverified_restore(step: int) -> None:
    """An operator-visible trace of a manifest-less restore attempt:
    ``resilience/unverified_restore`` counts every one; the warning is
    rate-limited so a tight resume loop cannot flood the log."""
    global _last_unverified_warn
    get_telemetry().counter("resilience/unverified_restore").add(1)
    now = time.monotonic()
    if now - _last_unverified_warn >= _UNVERIFIED_WARN_INTERVAL_S:
        _last_unverified_warn = now
        logger.warning(
            "checkpoint step %d has no integrity manifest; attempting "
            "unverified restore (further occurrences counted in "
            "resilience/unverified_restore without this warning)", step,
        )


class StateCheckpointer:
    def __init__(
        self,
        directory: str | Path,
        *,
        save_every_steps: int | None = None,
        num_to_keep: int | None = 3,
        async_save: bool = True,
    ):
        self.directory = Path(directory).absolute()
        self.save_every_steps = save_every_steps
        self.async_save = async_save
        # steps saved but whose manifest is not yet written (async saves
        # may still be writing array files in the background); and the
        # most recent step handed to save() — lets the trainer's
        # emergency/final save skip a duplicate same-step save
        self._manifest_pending: set[int] = set()
        # per-step saving-mesh blocks awaiting their manifest write
        self._mesh_specs: dict[int, dict[str, Any]] = {}
        self.last_saved_step: int | None = None
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=num_to_keep,
                step_prefix="save",
                create=True,
                enable_async_checkpointing=async_save,
            ),
            item_names=(_ARRAYS, _META),
        )

    def _step_dir(self, step: int) -> Path:
        return self.directory / f"save_{step}"

    def _finalize_manifests(self) -> None:
        """Write manifests for every pending step whose directory has
        been finalized (tmp → rename done). Call only behind a barrier
        or where orbax guarantees prior saves completed.

        Multi-host: the checkpoint directory is shared storage, so only
        the primary process writes manifests (concurrent writers would
        race the identical tmp path and install a torn file — which
        validation would then reject as corruption on an intact step).
        """
        if jax.process_index() != 0:
            for step in self._manifest_pending:
                self._mesh_specs.pop(step, None)
            self._manifest_pending.clear()
            return
        for step in sorted(self._manifest_pending):
            step_dir = self._step_dir(step)
            if not step_dir.is_dir():
                # rotated away before its manifest barrier, or the save
                # never finalized — either way nothing to describe
                self._manifest_pending.discard(step)
                self._mesh_specs.pop(step, None)
                continue
            try:
                write_manifest(
                    step_dir, step=step, mesh=self._mesh_specs.pop(step, None)
                )
            except OSError as e:
                # racing the rotation delete of an old step: the step is
                # gone (or going); an unmanifested step still restores
                # through the unverified path
                logger.warning(
                    "could not write manifest for step %d: %s", step, e
                )
            self._manifest_pending.discard(step)

    # -- save ----------------------------------------------------------

    def should_checkpoint(self, step: int, *, last: bool = False) -> bool:
        if last:
            return True
        return (
            self.save_every_steps is not None
            and step > 0
            and step % self.save_every_steps == 0
        )

    def save(
        self,
        step: int,
        arrays: PyTree,
        meta: dict[str, Any],
        *,
        mesh_spec: dict[str, Any] | None = None,
    ) -> None:
        """Save one step. ``mesh_spec`` (resilience/elastic.job_mesh_spec)
        is recorded in the step's integrity manifest so a later restore
        on a different topology can detect the mismatch before loading."""
        logger.info("checkpointing step %d -> %s", step, self.directory)
        if mesh_spec is not None:
            self._mesh_specs[step] = mesh_spec
        # the span covers the synchronous part only: under async save
        # that is the device→host snapshot; the background disk write is
        # timed by the io/checkpoint_wait span that eventually joins it
        with get_telemetry().span("io/checkpoint_save", step=step):
            self._mgr.save(
                step,
                args=ocp.args.Composite(
                    **{
                        _ARRAYS: ocp.args.StandardSave(arrays),
                        _META: ocp.args.JsonSave(meta),
                    }
                ),
            )
            self.last_saved_step = step
            self._manifest_pending.add(step)
            # async mode: orbax has already snapshotted the device arrays to
            # host (so the train step's donated buffers can't race the save);
            # the disk write continues in the background and the next save /
            # restore / close waits on it internally. Sync mode keeps the old
            # barrier for callers that need the files on disk on return.
            if not self.async_save:
                self._mgr.wait_until_finished()
                self._finalize_manifests()
            else:
                # entering save() means orbax just waited for any PRIOR
                # in-flight save — earlier steps are finalized and may
                # take their manifests now (this step's stays pending)
                self._manifest_pending.discard(step)
                self._finalize_manifests()
                self._manifest_pending.add(step)

    def wait_until_finished(self) -> None:
        """Block until any in-flight background save hits disk."""
        with get_telemetry().span("io/checkpoint_wait"):
            self._mgr.wait_until_finished()
        self._finalize_manifests()

    # -- load ----------------------------------------------------------

    def latest_step(self) -> int | None:
        # orbax registers a step the moment its async save DISPATCHES, and
        # neither latest_step nor restore waits on the background write
        # (verified against orbax 0.11 source) — barrier here so callers
        # never see (or race) a step whose directory is still a tmp path
        if self.async_save:
            self.wait_until_finished()
        return self._mgr.latest_step()

    def _restore_one(
        self,
        step: int,
        abstract_arrays: PyTree,
        *,
        reshard: bool = False,
        reshard_hbm_budget_bytes: int | None = None,
    ) -> tuple[int, PyTree, dict[str, Any]]:
        with get_telemetry().span("io/checkpoint_restore", step=step):
            abstract = jax.tree.map(
                ocp.utils.to_shape_dtype_struct, abstract_arrays
            )
            if reshard:
                return self._restore_resharded(
                    step, abstract, reshard_hbm_budget_bytes
                )
            restored = self._mgr.restore(
                step,
                args=ocp.args.Composite(
                    **{
                        _ARRAYS: ocp.args.StandardRestore(abstract),
                        _META: ocp.args.JsonRestore(),
                    }
                ),
            )
        return step, restored[_ARRAYS], restored[_META]

    def _restore_resharded(
        self,
        step: int,
        abstract: PyTree,
        hbm_budget_bytes: int | None,
    ) -> tuple[int, PyTree, dict[str, Any]]:
        """Cross-topology restore: the checkpoint was written by a
        different mesh than the one ``abstract`` targets. Orbax itself
        reads shard-local byte ranges into the new placement; under an
        HBM budget, leaves whose per-device materialization would
        exceed it restore into a flat device-sharded staging layout and
        are re-placed chunk by chunk (elastic.redistribute_tree), so no
        array's transient footprint ever exceeds target-shard + budget.
        Timed and counted under ``resilience/reshard_restore``; the
        ``resilience/reshard_bytes`` gauge records the total payload
        landed on the new topology (every leaf changes device placement
        in a cross-mesh restore; the chunked re-place traffic
        specifically is ``resilience/reshard_bytes_total``)."""
        from d9d_tpu.resilience.elastic import (
            bounded_restore_shardings,
            redistribute_tree,
        )

        tele = get_telemetry()
        tele.counter("resilience/reshard_restores").add(1)
        with tele.span("resilience/reshard_restore", step=step):
            staging = bounded_restore_shardings(
                abstract, hbm_budget_bytes=hbm_budget_bytes
            )
            load_target = jax.tree.map(
                lambda stage, a: (
                    a if stage is None
                    else jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=stage)
                ),
                staging,
                abstract,
                is_leaf=lambda x: x is None,
            )
            restored = self._mgr.restore(
                step,
                args=ocp.args.Composite(
                    **{
                        _ARRAYS: ocp.args.StandardRestore(load_target),
                        _META: ocp.args.JsonRestore(),
                    }
                ),
            )
            arrays = restored[_ARRAYS]
            # re-place only the staged leaves; the rest already landed
            # on their final shardings via orbax's shard-local reads
            final = jax.tree.map(
                lambda stage, a: (
                    None if stage is None
                    else getattr(a, "sharding", None)
                ),
                staging,
                abstract,
                is_leaf=lambda x: x is None,
            )
            arrays = redistribute_tree(
                arrays, final, hbm_budget_bytes=hbm_budget_bytes,
                telemetry=tele,
            )
            tele.gauge("resilience/reshard_bytes").set(
                sum(
                    getattr(leaf, "nbytes", 0)
                    for leaf in jax.tree.leaves(arrays)
                )
            )
        return step, arrays, restored[_META]

    def _detect_topology_mismatch(
        self, step: int, abstract_arrays: PyTree
    ) -> bool:
        """True when the step's manifest records a saving mesh that
        differs from the restore target's (device count or axis sizes)
        — the signal that routes restore through the resharding path.
        Best-effort: pre-v2 manifests and unplaced targets read as
        "same topology" (the plain path is always value-correct)."""
        from d9d_tpu.resilience.elastic import (
            topology_mismatch,
            tree_mesh_summary,
        )

        saved = manifest_mesh(self._step_dir(step))
        target = tree_mesh_summary(abstract_arrays)
        if not topology_mismatch(saved, target):
            return False
        logger.warning(
            "checkpoint step %d was saved on a different topology "
            "(saved %s -> restoring onto %s); resharding on load",
            step,
            {k: saved.get(k) for k in ("device_count", "axes",
                                       "zero_sharding")},
            target,
        )
        return True

    def restore(
        self,
        abstract_arrays: PyTree,
        step: int | None = None,
        *,
        reshard_hbm_budget_bytes: int | None = None,
    ) -> tuple[int, PyTree, dict[str, Any]] | None:
        """Restore (step, arrays, meta); arrays land with the shardings of
        ``abstract_arrays`` (pass the live state — jax.eval_shape-style
        ShapeDtypeStructs with shardings also work).

        With ``step=None`` (resume-latest), candidate steps are tried
        newest-first: each must pass manifest validation (steps without
        a manifest are attempted unverified — counted in
        ``resilience/unverified_restore`` with a rate-limited warning)
        and actually restore; corrupt or truncated steps are logged,
        counted in ``resilience/checkpoint_fallback`` telemetry, and
        skipped — manifest-CONFIRMED corrupt steps newer than the
        restored one are then pruned from the rotation. Returns None
        only when no steps exist at all; raises when checkpoints exist
        but none restores (silently training from scratch would be
        quiet data loss). An explicit ``step`` keeps strict semantics:
        validation/restore errors raise.

        A step saved on a different mesh (manifest v2 records it) is
        resharded on load; ``reshard_hbm_budget_bytes`` bounds the
        transient per-array footprint of that path (see
        docs/design/elasticity.md).
        """
        if self.async_save:
            self._mgr.wait_until_finished()
        self._finalize_manifests()
        if step is not None:
            if not validate_checkpoint_dir(self._step_dir(step)):
                _note_unverified_restore(step)
            result = self._restore_one(
                step, abstract_arrays,
                reshard=self._detect_topology_mismatch(step, abstract_arrays),
                reshard_hbm_budget_bytes=reshard_hbm_budget_bytes,
            )
            self.last_saved_step = None  # the save timeline restarts here
            return result

        candidates = sorted(self._mgr.all_steps(), reverse=True)
        confirmed_corrupt: list[int] = []
        last_error: Exception | None = None
        for s in candidates:
            try:
                verified = validate_checkpoint_dir(self._step_dir(s))
                if not verified:
                    _note_unverified_restore(s)
                result = self._restore_one(
                    s, abstract_arrays,
                    reshard=self._detect_topology_mismatch(
                        s, abstract_arrays
                    ),
                    reshard_hbm_budget_bytes=reshard_hbm_budget_bytes,
                )
            except Exception as e:  # noqa: BLE001 — classified below
                get_telemetry().counter(
                    "resilience/checkpoint_fallback"
                ).add(1)
                logger.error(
                    "checkpoint step %d is not restorable (%s: %s); "
                    "falling back to the previous rotation entry",
                    s, type(e).__name__, e,
                )
                # only a manifest-confirmed corruption may be pruned
                # later; a transient restore failure (storage blip,
                # momentary OOM) must never cost an intact checkpoint
                if isinstance(e, CheckpointIntegrityError):
                    confirmed_corrupt.append(s)
                last_error = e
                continue
            # restored by walking back: drop the CONFIRMED-corrupt newer
            # steps so (a) training replayed past them can re-save at
            # the same step numbers and (b) they can never shadow this
            # intact step as rotation's "latest" again; and forget the
            # same-step save guard — it described the abandoned timeline
            # primary-only on shared storage: concurrent deleters (or a
            # deleter racing another process's validation pass) must not
            # turn a coordinated walk-back into divergent restores
            if jax.process_index() == 0:
                for dead in confirmed_corrupt:
                    try:
                        self._mgr.delete(dead)
                    except Exception as e:  # noqa: BLE001 — best effort
                        logger.warning(
                            "could not prune corrupt checkpoint step "
                            "%d: %s", dead, e,
                        )
            self.last_saved_step = None
            return result
        if candidates:
            # checkpoints exist but none restored: silently training
            # from scratch (and eventually rotating the old run's data
            # away) would be quiet data loss — fail for the operator
            raise RuntimeError(
                f"none of the checkpoint steps {candidates} could be "
                "restored; refusing to silently start from scratch"
            ) from last_error
        return None

    def close(self) -> None:
        # flush any in-flight save AND its pending integrity manifest —
        # a direct save()+close() user must not leave the newest step
        # permanently unverified
        self.wait_until_finished()
        self._mgr.close()
