"""Job-state checkpointing: save-{step} dirs, rotation, resume-latest.

Reference: d9d/loop/component/checkpointer.py:27 (StateCheckpointer over
torch DCP). The TPU equivalent rides orbax: arrays (params, optimizer
state, rng) go through ``StandardSave`` (sharded, parallel-IO), host-side
scalars (stepper, dataloader position, tracker run hash, task state) ride
a JSON item. Directory layout mirrors the reference contract (orbax
spelling): ``{dir}/save_{step}/`` with ``num_to_keep`` rotation and
resume = latest.
"""

import logging
from pathlib import Path
from typing import Any

import jax
import orbax.checkpoint as ocp

from d9d_tpu.core.types import PyTree
from d9d_tpu.telemetry import get_telemetry

logger = logging.getLogger("d9d_tpu.checkpointer")

_ARRAYS = "arrays"
_META = "meta"


class StateCheckpointer:
    def __init__(
        self,
        directory: str | Path,
        *,
        save_every_steps: int | None = None,
        num_to_keep: int | None = 3,
        async_save: bool = True,
    ):
        self.directory = Path(directory).absolute()
        self.save_every_steps = save_every_steps
        self.async_save = async_save
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=num_to_keep,
                step_prefix="save",
                create=True,
                enable_async_checkpointing=async_save,
            ),
            item_names=(_ARRAYS, _META),
        )

    # -- save ----------------------------------------------------------

    def should_checkpoint(self, step: int, *, last: bool = False) -> bool:
        if last:
            return True
        return (
            self.save_every_steps is not None
            and step > 0
            and step % self.save_every_steps == 0
        )

    def save(self, step: int, arrays: PyTree, meta: dict[str, Any]) -> None:
        logger.info("checkpointing step %d -> %s", step, self.directory)
        # the span covers the synchronous part only: under async save
        # that is the device→host snapshot; the background disk write is
        # timed by the io/checkpoint_wait span that eventually joins it
        with get_telemetry().span("io/checkpoint_save", step=step):
            self._mgr.save(
                step,
                args=ocp.args.Composite(
                    **{
                        _ARRAYS: ocp.args.StandardSave(arrays),
                        _META: ocp.args.JsonSave(meta),
                    }
                ),
            )
            # async mode: orbax has already snapshotted the device arrays to
            # host (so the train step's donated buffers can't race the save);
            # the disk write continues in the background and the next save /
            # restore / close waits on it internally. Sync mode keeps the old
            # barrier for callers that need the files on disk on return.
            if not self.async_save:
                self._mgr.wait_until_finished()

    def wait_until_finished(self) -> None:
        """Block until any in-flight background save hits disk."""
        with get_telemetry().span("io/checkpoint_wait"):
            self._mgr.wait_until_finished()

    # -- load ----------------------------------------------------------

    def latest_step(self) -> int | None:
        # orbax registers a step the moment its async save DISPATCHES, and
        # neither latest_step nor restore waits on the background write
        # (verified against orbax 0.11 source) — barrier here so callers
        # never see (or race) a step whose directory is still a tmp path
        if self.async_save:
            self._mgr.wait_until_finished()
        return self._mgr.latest_step()

    def restore(
        self, abstract_arrays: PyTree, step: int | None = None
    ) -> tuple[int, PyTree, dict[str, Any]] | None:
        """Restore (step, arrays, meta); arrays land with the shardings of
        ``abstract_arrays`` (pass the live state — jax.eval_shape-style
        ShapeDtypeStructs with shardings also work)."""
        if self.async_save:
            self._mgr.wait_until_finished()  # see latest_step
        step = step if step is not None else self.latest_step()
        if step is None:
            return None
        with get_telemetry().span("io/checkpoint_restore", step=step):
            abstract = jax.tree.map(
                ocp.utils.to_shape_dtype_struct, abstract_arrays
            )
            restored = self._mgr.restore(
                step,
                args=ocp.args.Composite(
                    **{
                        _ARRAYS: ocp.args.StandardRestore(abstract),
                        _META: ocp.args.JsonRestore(),
                    }
                ),
            )
        return step, restored[_ARRAYS], restored[_META]

    def close(self) -> None:
        self._mgr.close()
