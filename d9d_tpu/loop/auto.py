"""Config-driven optimizer / LR-scheduler factories.

Reference: d9d/loop/auto/{auto_optimizer.py, auto_lr_scheduler.py} —
pydantic discriminated unions so the whole optimization setup rides the
job's single JSON config. The LR side reuses the piecewise scheduler
config (d9d_tpu/lr_scheduler/config.py); optimizers cover optax AdamW and
the bf16 StochasticAdamW.
"""

from typing import Annotated, Literal, Union

import optax
import pydantic

from d9d_tpu.lr_scheduler.config import (
    PiecewiseSchedulerConfig,
    piecewise_scheduler_from_config,
)


class AdamWConfig(pydantic.BaseModel):
    type: Literal["adamw"] = "adamw"
    b1: float = 0.9
    b2: float = 0.95
    eps: float = 1e-8
    weight_decay: float = 0.0


class StochasticAdamWConfig(pydantic.BaseModel):
    type: Literal["stochastic_adamw"] = "stochastic_adamw"
    b1: float = 0.9
    b2: float = 0.95
    eps: float = 1e-8
    weight_decay: float = 0.0
    moment_dtype: Literal["float32", "bfloat16"] = "float32"
    seed: int = 0


OptimizerConfig = Annotated[
    Union[AdamWConfig, StochasticAdamWConfig],
    pydantic.Field(discriminator="type"),
]


def build_optimizer(config: OptimizerConfig, learning_rate):
    """learning_rate: float or optax schedule."""
    if isinstance(config, AdamWConfig):
        return optax.adamw(
            learning_rate,
            b1=config.b1,
            b2=config.b2,
            eps=config.eps,
            weight_decay=config.weight_decay,
        )
    if isinstance(config, StochasticAdamWConfig):
        import jax.numpy as jnp

        from d9d_tpu.optim import StochasticAdamW

        return StochasticAdamW(
            learning_rate,
            b1=config.b1,
            b2=config.b2,
            eps=config.eps,
            weight_decay=config.weight_decay,
            moment_dtype=jnp.bfloat16
            if config.moment_dtype == "bfloat16"
            else jnp.float32,
            seed=config.seed,
        )
    raise TypeError(f"unknown optimizer config: {config!r}")


class ConstantLRConfig(pydantic.BaseModel):
    type: Literal["constant"] = "constant"
    value: float


class PiecewiseLRConfig(pydantic.BaseModel):
    type: Literal["piecewise"] = "piecewise"
    base_lr: float
    schedule: PiecewiseSchedulerConfig


LRSchedulerConfig = Annotated[
    Union[ConstantLRConfig, PiecewiseLRConfig],
    pydantic.Field(discriminator="type"),
]


def build_lr_schedule(config: LRSchedulerConfig, total_steps: int | None = None):
    """Returns an optax-compatible schedule (step -> lr) or a float."""
    if isinstance(config, ConstantLRConfig):
        return config.value
    if isinstance(config, PiecewiseLRConfig):
        schedule = piecewise_scheduler_from_config(
            config.schedule, total_steps=total_steps
        )
        return lambda step: config.base_lr * schedule(step)
    raise TypeError(f"unknown lr config: {config!r}")
