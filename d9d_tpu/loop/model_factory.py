"""Sharded model initialization.

The TPU equivalent of the reference's meta-device init → parallelize →
``to_empty`` → ``reset_parameters`` flow
(d9d/loop/component/model_stage_factory.py:215-255): shapes are inferred
abstractly with ``jax.eval_shape`` (free "meta device"), the parallel plan
maps flax logical-axis metadata to NamedShardings, and a jitted init
materializes every parameter directly into its shard — no full-model
host copy ever exists.
"""

import functools

import flax.linen as nn
import jax

from d9d_tpu.core.mesh import MeshContext
from d9d_tpu.core.types import PyTree
from d9d_tpu.parallel.plan import ParallelPlan, logical_to_mesh_sharding


def init_sharded_from_fn(
    raw_init,
    mesh,
    plan: ParallelPlan,
) -> tuple[PyTree, PyTree]:
    """Materialize ``raw_init()``'s variables directly into their shards on
    ``mesh`` according to ``plan``; returns (params, shardings)."""

    def init_fn():
        variables = raw_init()
        # drop transient sown stats (e.g. MoE tokens_per_expert): they are
        # re-collected per step via mutable apply, not trained state
        return {k: v for k, v in variables.items() if k != "moe_stats"}

    abstract = jax.eval_shape(init_fn)
    logical_spec = nn.get_partition_spec(abstract)
    shardings = logical_to_mesh_sharding(logical_spec, mesh, plan.rules)
    # d9d-lint: disable=D9D001 — one-shot sharded init at model build
    boxed = jax.jit(init_fn, out_shardings=shardings)()
    params = nn.unbox(boxed)
    return params, jax.tree.map(lambda x: x.sharding, params)


def init_sharded_params(
    module: nn.Module,
    sample_inputs: tuple,
    rng: jax.Array,
    ctx: MeshContext,
    plan: ParallelPlan,
) -> tuple[PyTree, PyTree]:
    """Returns (params, shardings); params are unboxed jax.Arrays already
    placed according to ``plan``."""
    return init_sharded_from_fn(
        functools.partial(module.init, rng, *sample_inputs), ctx.mesh, plan
    )


def abstract_param_shapes(module: nn.Module, sample_inputs: tuple, rng: jax.Array) -> PyTree:
    return jax.eval_shape(functools.partial(module.init, rng, *sample_inputs))
