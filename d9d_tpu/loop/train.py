"""Trainer: wires providers into the compiled step and runs the loop.

Reference: d9d/loop/run/train.py:71,251 (TrainingConfigurator/Trainer).
The configure step builds mesh→model→optimizer→step-fn; ``train()`` is a
thin host loop around the jitted step — data staging and metric readback
are the only per-step host work (hot path is one XLA program). Around it
sit the reference's loop components: event bus, tracker-backed logger,
orbax job-state checkpointer with resume, jax.profiler cycles, manual GC,
hang watchdog, and sleep/wake host offload.
"""

import logging
import time
import warnings

import jax
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from d9d_tpu.core.mesh import MeshContext
from d9d_tpu.core.offload import SleepTag, offload_tree, onload_tree
from d9d_tpu.core.tree_sharding import replicate_uncommitted
from d9d_tpu.core.types import PyTree
from d9d_tpu.loop import event as ev
from d9d_tpu.loop.components.batch_maths import BatchMaths
from d9d_tpu.loop.components.batch_staging import (
    make_batch_stager,
    split_microbatches,
)
from d9d_tpu.loop.components.checkpointer import StateCheckpointer
from d9d_tpu.loop.components.garbage_collector import ManualGarbageCollector
from d9d_tpu.loop.components.job_profiler import JobProfiler
from d9d_tpu.loop.components.metric_collector import MetricCollector
from d9d_tpu.loop.components.prefetch import BatchPrefetcher
from d9d_tpu.loop.components.stepper import Stepper
from d9d_tpu.loop.components.timeout_manager import TimeoutManager
from d9d_tpu.loop.config import TrainerConfig
from d9d_tpu.loop.control.providers import (
    DatasetProvider,
    ModelProvider,
    OptimizerProvider,
)
from d9d_tpu.loop.control.task import TrainTask
from d9d_tpu.loop.event import EventBus
from d9d_tpu.loop.model_factory import init_sharded_params
from d9d_tpu.loop.train_step import build_eval_step, build_train_step
from d9d_tpu.pipelining import PipelineStageInfo
from d9d_tpu.resilience import (
    HostAnomalyGuard,
    PreemptionGuard,
    TrainingPreempted,
)
from d9d_tpu.telemetry import (
    ConsoleSink,
    JsonlSink,
    TrackerBridge,
    get_telemetry,
    recompile_guard,
)
from d9d_tpu.telemetry.numerics import (
    NumericsMonitor,
    TrainDriftMonitor,
    default_drift_policies,
)
from d9d_tpu.telemetry.introspect import executable_flops
from d9d_tpu.telemetry.flops import (
    active_param_count,
    device_peak_flops,
    model_flops_per_token,
)
from d9d_tpu.tracker import NullTracker, Tracker

logger = logging.getLogger("d9d_tpu.trainer")


class Trainer:
    def __init__(
        self,
        *,
        ctx: MeshContext,
        config: TrainerConfig,
        model_provider: ModelProvider,
        dataset_provider: DatasetProvider,
        task: TrainTask,
        optimizer_provider: OptimizerProvider,
        learning_rate: optax.ScalarOrSchedule | None = None,
        peft_method=None,
        tracker: Tracker | None = None,
        event_bus: EventBus | None = None,
    ):
        self.ctx = ctx
        self.config = config
        self.task = task
        self.events = event_bus if event_bus is not None else EventBus()
        self.tracker = tracker if tracker is not None else NullTracker()
        self.events.emit(ev.EVENT_TRAIN_CONFIG_STARTED, trainer=self)

        self.batch_maths = BatchMaths.from_context(
            ctx, config.global_batch_size, config.microbatch_size
        )
        self.stepper = Stepper(total_steps=config.total_steps)

        rng = jax.random.PRNGKey(config.seed)
        self.init_rng, self.step_rng = jax.random.split(rng)
        self.peft_method = peft_method
        self.base_params = None
        self.pp_engine = None
        self.module = None
        self.params = self.param_shardings = None
        self.opt_state = None
        self.step_fn = None

        self.optimizer = optimizer_provider.build(
            learning_rate if learning_rate is not None else config.learning_rate
        )

        if ctx.pp_size > 1:
            from d9d_tpu.loop.pipeline_driver import PipelineTrainEngine

            self.zero = None  # PP: the per-stage optimizer owns the tables
            self.pp_engine = PipelineTrainEngine(
                ctx=ctx,
                schedule=config.pipeline,
                model_provider=model_provider,
                task=task,
                optimizer=self.optimizer,
                batch_maths=self.batch_maths,
                seq_len=config.seq_len,
                init_rng=self.init_rng,
                max_grad_norm=config.max_grad_norm,
                peft_method=peft_method,
                anomaly_policy=config.anomaly_policy,
                zero_sharding=config.zero_sharding,
                numerics=config.numerics_every_steps is not None,
            )
            self.events.emit(ev.EVENT_MODEL_READY, trainer=self)
            self.events.emit(ev.EVENT_OPTIMIZER_READY, trainer=self)
        else:
            self.module = model_provider.build_module(PipelineStageInfo())
            plan = model_provider.build_plan(ctx)
            sample = model_provider.sample_inputs(
                self.batch_maths.microbatch_size, config.seq_len
            )
            self.params, self.param_shardings = init_sharded_params(
                self.module, sample, self.init_rng, ctx, plan
            )

            if peft_method is not None:
                # engine "params" become the adapter tree; base stays frozen
                from d9d_tpu.peft import PeftTask

                inject_rng = jax.random.fold_in(self.init_rng, 1)
                self.base_params, adapters = peft_method.inject(
                    self.params, inject_rng
                )
                self.params = adapters
                self.task = task = PeftTask(task, peft_method, self.base_params)
            self.events.emit(ev.EVENT_MODEL_READY, trainer=self)

            # normalize placement: a fresh jit(init) leaves constraint-free
            # scalars (step counters) uncommitted on one device, which
            # round-trips through a checkpoint as a committed placement
            # that conflicts with the mesh-placed params at the first
            # post-restore step (core/tree_sharding.replicate_uncommitted)
            self.opt_state = replicate_uncommitted(
                # d9d-lint: disable=D9D001 — one-shot optimizer-state init
                jax.jit(self.optimizer.init)(self.params), ctx.mesh
            )
            self.zero = None
            if config.zero_sharding:
                # ZeRO optimizer-state sharding (parallel/zero.py): move
                # the live state onto its 1/N-per-chip layout and wrap
                # the optimizer with the reduce-scatter/all-gather
                # annotations around the update seam
                from d9d_tpu.parallel.zero import (
                    ZeroShardedOptimizer,
                    build_zero_sharding,
                    place_tree,
                )

                self.zero = build_zero_sharding(
                    params=self.params,
                    opt_state=self.opt_state,
                    mesh=ctx.mesh,
                )
                self.opt_state = place_tree(
                    self.opt_state, self.zero.state_shardings
                )
                self.optimizer = ZeroShardedOptimizer(
                    self.optimizer, self.zero
                )
            self.events.emit(ev.EVENT_OPTIMIZER_READY, trainer=self)

            self.step_fn = build_train_step(
                module=self.module,
                task=self.task,
                optimizer=self.optimizer,
                num_microbatches=self.batch_maths.num_microbatches,
                max_grad_norm=config.max_grad_norm,
                anomaly_policy=config.anomaly_policy,
                zero=self.zero,
                split_update=config.split_optimizer_update,
                numerics=config.numerics_every_steps is not None,
            )

        self.dataset_provider = dataset_provider
        self.data_loader = None  # built fresh per train() call

        self.checkpointer = (
            StateCheckpointer(
                config.checkpoint_dir,
                save_every_steps=config.checkpoint_every_steps,
                num_to_keep=config.checkpoints_to_keep,
                async_save=config.checkpoint_async,
            )
            if config.checkpoint_dir is not None
            else None
        )
        self.profiler = JobProfiler(
            config.profile_dir,
            every_steps=config.profile_every_steps,
            active_steps=config.profile_active_steps,
            wait_steps=config.profile_wait_steps,
        )
        self.timeout = TimeoutManager(
            init_timeout_s=config.init_timeout_s,
            step_timeout_s=config.step_timeout_s,
            exit_code=config.watchdog_exit_code,
        )
        # resilience (docs/design/resilience.md): host half of the step
        # anomaly guard + the preemption signal flag; both no-ops unless
        # their config knobs enable them
        self.anomaly_guard = (
            HostAnomalyGuard(
                policy=config.anomaly_policy,
                rollback_after=config.anomaly_rollback_after,
                spike_factor=config.anomaly_spike_factor,
                spike_window=config.anomaly_spike_window,
            )
            if config.anomaly_policy is not None
            else None
        )
        self.preemption = PreemptionGuard(enabled=config.handle_preemption)
        # training numerics plane (telemetry/numerics.py): host half —
        # decodes the cadence windows the metric fetch already carried,
        # names the first non-finite layer for the anomaly guard, feeds
        # numerics/* gauges + the schema-v4 JSONL event; drift policies
        # gauge train_slo/* over the same host metric dicts
        self.numerics_monitor = (
            NumericsMonitor(telemetry=get_telemetry())
            if config.numerics_every_steps is not None
            else None
        )
        self.drift_monitor = (
            TrainDriftMonitor(
                default_drift_policies(), telemetry=get_telemetry()
            )
            if config.numerics_every_steps is not None and config.numerics_drift
            else None
        )
        self.gc = ManualGarbageCollector(config.gc_every_steps)
        self.metric_collector = MetricCollector(self.task)
        self.run = None  # tracker run, opened in train()
        self._sleep_store: dict[SleepTag, tuple[PyTree, PyTree]] = {}
        self._prefetcher = None  # BatchPrefetcher, live only inside train()

        self._stage = make_batch_stager(
            ctx,
            num_microbatches=self.batch_maths.num_microbatches,
            microbatch_size=self.batch_maths.microbatch_size,
            seq_len=config.seq_len,
        )
        self._eval_fn = None
        self._merge_fn = None

        # always-on runtime telemetry (docs/design/observability.md):
        # recording happens regardless; config knobs only attach sinks
        # (JSONL event log / tracker bridge / console) inside train()
        self.telemetry = get_telemetry()
        self._tokens_per_step = config.global_batch_size * config.seq_len
        self._flops_per_token = model_flops_per_token(
            self._active_param_count(), seq_len=config.seq_len,
            config=self._model_config(),
        )
        # tok_s is whole-mesh throughput, so MFU normalizes by the whole
        # mesh's peak (per-chip peak x mesh size), matching bench.py's
        # single-chip convention at mesh size 1
        self._peak_flops = device_peak_flops() * int(ctx.mesh.devices.size)
        # per-chip optimizer-state footprint (docs/design/zero_sharding.md):
        # under ZeRO this reads ~1/dp_replicate of the replicated value —
        # the executable claim the bench column mirrors
        self.telemetry.gauge("opt/state_bytes_per_chip").set(
            self.opt_state_bytes_per_chip()
        )
        # once-per-process flag for the model-vs-XLA FLOPs cross-check
        # (telemetry/introspect.py inventory vs the roofline convention)
        self._flops_divergence_checked = False
        # monitoring plane (docs/design/observability.md): steps run by
        # the CURRENT train() session — the /readyz warmup contract;
        # the metrics endpoint itself is started/stopped inside train()
        self._session_steps = 0
        self.metrics_server = None
        # anomaly flight recorder: with a telemetry dir configured, the
        # guard/watchdog failure paths dump flight_recorder_{event}.json
        # NEXT TO that dir (its parent) — one black box per job dir
        if config.telemetry_dir is not None:
            from pathlib import Path

            self.telemetry.configure_flight_recorder(
                Path(config.telemetry_dir).parent
            )
            # flight-recorder capture hook: an SLO-burn/anomaly dump
            # kicks off a 1s on-demand profile (device trace + folded
            # host stacks) into the telemetry dir's captures/ next to
            # the metric windows — JobProfiler.capture degrades to None
            # when the profiler is busy, and the recorder treats that
            # as "no capture", never a failed dump
            captures_dir = Path(config.telemetry_dir) / "captures"
            recorder = self.telemetry.flight_recorder
            if recorder is not None:
                recorder.capture_hook = (
                    lambda event: self.profiler.capture(1.0, captures_dir)
                )
        # saving-mesh block for checkpoint manifests (elastic restore);
        # built lazily at the first save — placement is stable by then
        self._mesh_spec = None
        self.events.emit(ev.EVENT_TRAIN_READY, trainer=self)

    # -- live-MFU inputs (telemetry/flops.py roofline convention) ------

    def _active_param_count(self) -> float:
        """Params that compute per token, via the shared accounting in
        telemetry/flops.py (MoE experts scaled by top_k/num_experts) —
        so the live MFU gauge and the bench-reported MFU agree."""
        if self.pp_engine is not None:
            trees = [rt.params for rt in self.pp_engine.stages.values()]
        else:
            trees = [self.params]
        if self.base_params is not None:  # PEFT: frozen base still computes
            trees.append(self.base_params)
        return active_param_count(trees, self._model_config())

    def opt_state_bytes_per_chip(self) -> int:
        """Per-chip bytes of the live optimizer state (shard-aware).

        Under PP each chip belongs to exactly one stage, so the honest
        per-chip number is the worst stage's footprint, not the sum.
        """
        from d9d_tpu.parallel.zero import tree_bytes_per_device

        if self.pp_engine is not None:
            per_rank: dict[int, int] = {}
            for s, state in self.pp_engine.opt_states.items():
                rank = self.pp_engine.stage_owner[s]
                per_rank[rank] = per_rank.get(rank, 0) + tree_bytes_per_device(
                    state
                )
            return max(per_rank.values(), default=0)
        return tree_bytes_per_device(self.opt_state)

    def _model_config(self):
        if self.pp_engine is not None:
            rt = self.pp_engine.stages.get(0)
            return getattr(rt.module, "config", None) if rt else None
        return getattr(self.module, "config", None)

    def _note_flops_divergence(self) -> None:
        """Cross-check the roofline FLOPs inventory (telemetry/flops.py,
        the live-MFU convention) against XLA's own cost analysis of the
        compiled train step. A large gap means the MFU gauge is lying —
        the model inventory drifted from what actually runs (missed
        attention term, uncounted recompute) — so it gets a gauge
        (``flops/model_vs_xla_divergence``, signed, relative) and a
        warning past the configured tolerance. Non-PP only: under PP
        the step is many per-action executables, not one program."""
        if self._flops_divergence_checked or self.pp_engine is not None:
            return
        xla = executable_flops("train_step")
        if xla is None or xla <= 0:
            return  # backend declined cost analysis, or tracked_jit degraded
        # Two normalizations to compare like with like: cost_analysis
        # describes the PER-DEVICE SPMD program (the model inventory
        # counts the whole mesh), and XLA's static analysis counts the
        # microbatch lax.scan body ONCE, not x trip-count — so the
        # comparable model term is per-device, per-microbatch.
        model = (
            self._flops_per_token * self._tokens_per_step
            / max(int(self.ctx.mesh.devices.size), 1)
            / max(self.batch_maths.num_microbatches, 1)
        )
        if model <= 0:
            return
        divergence = (xla - model) / model
        self.telemetry.gauge("flops/model_vs_xla_divergence").set(divergence)
        self._flops_divergence_checked = True
        if abs(divergence) > self.config.flops_divergence_tolerance:
            logger.warning(
                "model-FLOPs inventory diverges from XLA cost analysis by "
                "%+.1f%% (model %.3e vs XLA %.3e FLOPs/step): the MFU "
                "gauge inherits this error — check telemetry/flops.py's "
                "inventory against the model geometry",
                100 * divergence, model, xla,
            )

    # ------------------------------------------------------------------

    def _stage_batch(self, raw_batch: PyTree) -> PyTree:
        """prepare → microbatch-reshape → device_put (dp + cp sharding).

        Pipeline mode returns the host microbatch *list* instead — the
        executor places each carry/kwargs/state on its stage's submesh.
        """
        prepared = self.task.prepare_batch(raw_batch)
        if self.pp_engine is not None:
            return self._split_microbatches(prepared)
        return self._stage(prepared)

    def _split_microbatches(self, prepared: PyTree) -> list[PyTree]:
        return split_microbatches(
            prepared,
            num_microbatches=self.batch_maths.num_microbatches,
            microbatch_size=self.batch_maths.microbatch_size,
        )

    def run_step(self, raw_batch: PyTree) -> dict:
        """Public single-step API: stage ``raw_batch``, run one optimizer
        step and advance the step counter.

        Returns the step's metric dict with values still on device (call
        ``jax.block_until_ready`` to synchronize). This is the stable hook
        for benchmarks and external drivers; ``train()`` runs through the
        same staging/step internals.
        """
        metrics = self._optimizer_step(self._stage_batch(raw_batch))
        self.stepper.advance()
        return metrics

    def _fetches_metrics(self, step: int) -> bool:
        """Will the loop fetch ``step``'s metrics? Log cadence, final
        step, or a guard-forced checkpoint fetch (a checkpoint step only
        forces a fetch when the anomaly guard must examine the state
        being saved). THE predicate — shared by the loop's fetch site
        and :meth:`_numerics_on`, so a computed numerics window is
        always one the host actually decodes and vice versa."""
        return (
            step % self.config.log_every == 0
            or step >= self.config.total_steps
            or (
                self.anomaly_guard is not None
                and self.checkpointer is not None
                and self.checkpointer.should_checkpoint(step)
            )
        )

    def _numerics_on(self) -> bool:
        """Whether THIS step computes its numerics window: the config
        cadence, plus every step whose metrics the loop will fetch
        anyway (:meth:`_fetches_metrics`) — the window the host decodes
        is always the fetched step's own, at zero extra fetches."""
        k = self.config.numerics_every_steps
        if k is None:
            return False
        nxt = self.stepper.step + 1
        return nxt % k == 0 or self._fetches_metrics(nxt)

    def _pp_timeline_on(self) -> bool:
        """Whether THIS step runs the fused pipeline timeline cadence
        (``pp_timeline_every_steps``). Strictly the config cadence — NOT
        folded with :meth:`_fetches_metrics` like numerics, because a
        timeline step serializes the fused dispatch loop and that cost
        should land only where the user asked for it."""
        k = self.config.pp_timeline_every_steps
        if k is None or self.pp_engine is None:
            return False
        return (self.stepper.step + 1) % k == 0

    def _optimizer_step(self, batch: PyTree) -> dict:
        if self.pp_engine is not None:
            return self.pp_engine.step(
                batch,
                numerics=self._numerics_on(),
                timeline=self._pp_timeline_on(),
            )
        rng = jax.random.fold_in(self.step_rng, self.stepper.step)
        self.step_fn.numerics_next = self._numerics_on()
        self.params, self.opt_state, metrics = self.step_fn(
            self.params, self.opt_state, batch, rng
        )
        return metrics

    # -- checkpoint/resume ---------------------------------------------

    def _job_arrays(self) -> PyTree:
        if self.pp_engine is not None:
            return self.pp_engine.job_arrays()
        return {"params": self.params, "opt_state": self.opt_state}

    def _job_mesh_spec(self) -> dict:
        """The saving-topology record for checkpoint manifests
        (docs/design/elasticity.md): MeshParameters axes incl.
        dp_replicate, the zero_sharding setting, per-leaf shardings."""
        if self._mesh_spec is None:
            from d9d_tpu.resilience.elastic import job_mesh_spec

            self._mesh_spec = job_mesh_spec(
                ctx=self.ctx,
                zero_sharding=self.config.zero_sharding,
                arrays=self._job_arrays(),
            )
        return self._mesh_spec

    def _job_meta(self) -> dict:
        meta = {"step": self.stepper.step}
        if self.data_loader is not None:
            # under prefetch the loader runs ahead of the trainer; the
            # checkpoint must record the position of the last CONSUMED
            # batch, not the producer's run-ahead position
            if (
                self._prefetcher is not None
                and self._prefetcher.consumed_position is not None
                and hasattr(self.data_loader, "state_dict_at")
            ):
                meta["data_loader"] = self.data_loader.state_dict_at(
                    self._prefetcher.consumed_position
                )
            elif hasattr(self.data_loader, "state_dict"):
                meta["data_loader"] = self.data_loader.state_dict()
        if self.run is not None:
            meta["tracker"] = self.run.state_dict()
        return meta

    def _save_checkpoint(self, *, last: bool = False) -> None:
        if self.checkpointer is None:
            return
        step = self.stepper.step
        if not self.checkpointer.should_checkpoint(step, last=last):
            return
        with self.events.bounded(ev.EVENT_CHECKPOINT, trainer=self, step=step):
            if self.checkpointer.last_saved_step != step:
                self.checkpointer.save(
                    step, self._job_arrays(), self._job_meta(),
                    mesh_spec=self._job_mesh_spec(),
                )
            if last:
                # intermediate saves overlap training (async write-back);
                # the FINAL one must be durable when train() returns — the
                # process may exit right after, and auto-resume contracts
                # on the last step's checkpoint existing
                self.checkpointer.wait_until_finished()

    def _restore_state(self) -> int | None:
        """Restore the newest intact checkpoint into the live job state
        (arrays, stepper, loader position, tracker run); returns the
        restored step or None. Shared by resume and anomaly rollback."""
        if self.checkpointer is None:
            return None
        budget_mb = self.config.reshard_hbm_budget_mb
        restored = self.checkpointer.restore(
            self._job_arrays(),
            reshard_hbm_budget_bytes=(
                int(budget_mb * 2**20) if budget_mb is not None else None
            ),
        )
        if restored is None:
            return None
        step, arrays, meta = restored
        if self.pp_engine is not None:
            self.pp_engine.load_job_arrays(arrays)
        else:
            self.params = arrays["params"]
            self.opt_state = arrays["opt_state"]
        self.stepper.load_state_dict({"step": meta["step"]})
        if (
            "data_loader" in meta
            and self.data_loader is not None
            and hasattr(self.data_loader, "load_state_dict")
        ):
            self.data_loader.load_state_dict(meta["data_loader"])
        if "tracker" in meta and self.run is not None:
            self.run.load_state_dict(meta["tracker"])
        return step

    def _try_resume(self) -> None:
        if self.checkpointer is None or not self.config.resume:
            return
        step = self._restore_state()
        if step is not None:
            logger.info("resumed from checkpoint at step %d", step)

    def _reset_guard_state(self) -> None:
        """Zero both halves of the anomaly guard (post-rollback), plus
        the numerics/drift windows the restored state invalidates."""
        if self.anomaly_guard is not None:
            self.anomaly_guard.reset()
        if self.pp_engine is not None:
            self.pp_engine.reset_guard()
        elif self.step_fn is not None:
            self.step_fn.reset_guard()
        if self.numerics_monitor is not None:
            self.numerics_monitor.reset()
        if self.drift_monitor is not None:
            self.drift_monitor.reset()

    def _numerics_windows(self, vecs: dict) -> list:
        """(prefix, spec, host vector) windows for the monitor: the
        single-program step's ``numerics/stats``, or one ``pp/s{S}/``-
        prefixed window per stage under PP."""
        windows = []
        if self.pp_engine is not None:
            for s, spec in sorted(self.pp_engine.numerics_specs.items()):
                vec = vecs.get(f"numerics/s{s}")
                if vec is not None:
                    windows.append((f"pp/s{s}/", spec, vec))
            return windows
        spec = self.step_fn.numerics_spec
        vec = vecs.get("numerics/stats")
        if spec is not None and vec is not None:
            windows.append(("", spec, vec))
        return windows

    # -- the loop ------------------------------------------------------

    def train(self) -> list[dict]:
        """Run until total_steps or data exhaustion; returns metric history."""
        history: list[dict] = []
        self.run = None
        tele = self.telemetry
        tele_sinks = []
        if self.config.telemetry_dir:
            tele_sinks.append(tele.add_sink(JsonlSink(
                self.config.telemetry_dir,
                run_name=self.config.run_name or "train",
                process_index=jax.process_index(),
            )))
        if self.config.telemetry_console:
            tele_sinks.append(tele.add_sink(ConsoleSink(
                min_interval_s=self.config.telemetry_console_interval_s,
            )))
        flush_every = (
            self.config.telemetry_every_steps
            if self.config.telemetry_every_steps is not None
            else self.config.log_every
        )
        last_tele_flush = None  # step of the loop's most recent flush
        self._session_steps = 0
        # silent-recompile guard: re-arm for this session — every
        # legitimate signature compiles within the warmup steps, after
        # which any compile is a flagged steady-state recompile
        guard = recompile_guard()
        guard.configure(self.config.introspect_warmup_steps)
        try:
            # live metrics endpoint for the duration of this train()
            # session (telemetry/export.py): ready once past the
            # introspection warmup. Started INSIDE the try: a bind
            # failure (port taken) must still run the finally that
            # detaches the sinks attached above
            if self.config.metrics_port is not None:
                from pathlib import Path

                from d9d_tpu.telemetry import MetricsServer

                # /debug/profile backend: one-shot captures land in the
                # telemetry dir's captures/ (falling back to the profile
                # dir when no telemetry dir is configured); None when
                # neither exists — the endpoint then answers 404
                cap_base = (
                    self.config.telemetry_dir or self.config.profile_dir
                )
                profile_backend = (
                    (lambda d: self.profiler.capture(
                        d, Path(cap_base) / "captures"
                    ))
                    if cap_base is not None
                    else None
                )
                self.metrics_server = MetricsServer(
                    tele,
                    port=self.config.metrics_port,
                    readiness=lambda: (
                        self._session_steps
                        >= self.config.introspect_warmup_steps,
                        {"session_steps": self._session_steps},
                    ),
                    health=lambda: {"step": self.stepper.step},
                    profile=profile_backend,
                ).start()
            self.data_loader = self.dataset_provider.build()
            self.events.emit(ev.EVENT_DATA_LOADER_READY, trainer=self)
            self.run = self.tracker.new_run(self.config.run_name)
            # resume BEFORE hparams: restoring the tracker run hash re-points
            # output at the original run
            self._try_resume()
            self.run.track_hparams(self.config.model_dump())
            tele_sinks.append(tele.add_sink(TrackerBridge(self.run)))
            t0 = time.perf_counter()
            session_steps = 0  # steps run by THIS call (excludes resume)
            tele_sync_t0 = t0  # last host/device sync point (log cadence)
            steps_since_sync = 0
            data_iter = iter(self.data_loader)
            use_prefetch = self.config.prefetch_batches > 0
            if (
                use_prefetch
                and hasattr(self.data_loader, "state_dict")
                and not hasattr(self.data_loader, "position")
            ):
                # a stateful loader we cannot snapshot per-fetch would get
                # checkpointed at the producer's run-ahead position — keep
                # resume exact by staying on the step path instead
                warnings.warn(
                    "data loader has state_dict() but no position(); "
                    "disabling batch prefetch to keep checkpoint resume "
                    "exact (add position()/state_dict_at() to re-enable)",
                    stacklevel=2,
                )
                use_prefetch = False
            def spawn_prefetcher(batch_iter):
                # producer thread runs fetch + prepare (+ device staging
                # when that is collective-free) prefetch_batches ahead;
                # must start AFTER _try_resume (and restart after an
                # anomaly rollback) so it iterates from the restored
                # loader position. Multi-process non-PP staging
                # device_puts onto multi-process shardings — a hidden
                # collective — so it moves to the consumer thread
                # (finish_fn); PP staging is host-only and stays in the
                # producer either way.
                if self.pp_engine is None and jax.process_count() > 1:
                    produce, finish = self.task.prepare_batch, self._stage
                else:
                    produce, finish = self._stage_batch, None
                self._prefetcher = BatchPrefetcher(
                    batch_iter,
                    produce,
                    depth=self.config.prefetch_batches,
                    position_fn=getattr(self.data_loader, "position", None),
                    finish_fn=finish,
                )

            if use_prefetch:
                spawn_prefetcher(data_iter)
            rollbacks = 0
            last_rollback_trigger: int | None = None
            with self.timeout, self.gc, self.preemption:
                while not self.stepper.finished:
                    step = self.stepper.step
                    tele.set_step(step)
                    # contiguous phase timeline: data_wait / host_dispatch /
                    # device_block / metric_flush / checkpoint / other
                    # partition the step's wall time gap-free (the JSONL
                    # timeline accounts for the whole step by construction)
                    clock = tele.phases("train", step=step)
                    try:
                        if self._prefetcher is not None:
                            raw, batch = None, next(self._prefetcher)
                        else:
                            raw = next(data_iter)
                    except StopIteration:
                        # no step ran — discard the timeline rather than
                        # emit a phantom train/step span for this step
                        clock.cancel()
                        break
                    clock.mark("data_wait")
                    self.profiler.step_begin(step)
                    with self.events.bounded(ev.EVENT_STEP, trainer=self, step=step):
                        if raw is not None:
                            batch = self._stage_batch(raw)
                        with self.events.bounded(
                            ev.EVENT_FORWARD_BACKWARD, trainer=self, step=step
                        ):
                            metrics = self._optimizer_step(batch)
                        self.metric_collector.collect(metrics)
                    step = self.stepper.advance()
                    session_steps += 1
                    self._session_steps = session_steps
                    steps_since_sync += 1
                    guard.note_step(session_steps)
                    self.profiler.step_end(step - 1)
                    self.gc.step(step)
                    clock.mark("host_dispatch")
                    if self.timeout.step_timeout_s is not None:
                        # async dispatch lets the host run ahead of the device;
                        # a heartbeat only counts once this step really finished,
                        # so a hung collective trips the watchdog within one step
                        jax.block_until_ready(metrics)
                    clock.mark("device_block")
                    self.timeout.set_periodic()
                    guard_action = "ok"
                    # _fetches_metrics: log cadence, final step, or a
                    # guard-forced checkpoint fetch (anomalous state
                    # must never be persisted unexamined; the fetch
                    # costs nothing extra — the save itself snapshots
                    # device state anyway). The SAME predicate gates the
                    # step's numerics window (_numerics_on), so every
                    # fetched step decodes its own fresh window.
                    if self._fetches_metrics(step):
                        # postprocess sees everything (it may derive scalars
                        # from vector stats, e.g. expert-load counts); only
                        # scalars survive into history/tracker — remaining
                        # vectors (e.g. per-class confusion counts) are
                        # metric-collector fodder
                        host_metrics = {
                            k: float(arr) if (arr := np.asarray(v)).ndim == 0
                            else arr
                            for k, v in metrics.items()
                        }
                        # numerics windows ride the same fetch (the
                        # np.asarray above IS their readback); peel them
                        # off before task postprocess sees the dict
                        numerics_vecs = {
                            k: host_metrics.pop(k)
                            for k in [
                                k for k in host_metrics
                                if k.startswith("numerics/")
                            ]
                        }
                        host_metrics = self.task.metrics_postprocess(host_metrics)
                        host_metrics = {
                            k: float(v)
                            for k, v in host_metrics.items()
                            if np.ndim(v) == 0
                        }
                        host_metrics.update(
                            self.metric_collector.flush(self.run, step)
                        )
                        host_metrics["step"] = step
                        if self.numerics_monitor is not None and numerics_vecs:
                            report = self.numerics_monitor.ingest(
                                step, self._numerics_windows(numerics_vecs)
                            )
                            if report is not None:
                                host_metrics.update(report.scalars())
                        # drift policies gauge BEFORE the guard acts: a
                        # rollback this cadence must still record what
                        # was drifting when it fired
                        if self.drift_monitor is not None:
                            self.drift_monitor.observe(step, host_metrics)
                        # anomaly guard, host half: the metrics are on
                        # host anyway at this cadence, so inspecting the
                        # device guard's flags (and the loss for spikes)
                        # costs no extra sync (docs/design/resilience.md)
                        if self.anomaly_guard is not None:
                            guard_action = self.anomaly_guard.observe(
                                step, host_metrics,
                                context=(
                                    self.numerics_monitor.guard_context()
                                    if self.numerics_monitor is not None
                                    else None
                                ),
                            )
                        host_metrics["wall_s"] = time.perf_counter() - t0
                        # throughput from the batch-maths token count — live
                        # even before any telemetry sink is attached
                        host_metrics["tokens_per_s"] = (
                            session_steps * self._tokens_per_step
                            / max(host_metrics["wall_s"], 1e-9)
                        )
                        history.append(host_metrics)
                        for k, v in host_metrics.items():
                            if k != "step":
                                self.run.track_scalar(
                                    f"train/{k}", v, step=step,
                                    context={"subset": "train"},
                                )
                        logger.info("step %d: %s", step, host_metrics)
                        # live throughput + MFU gauges (roofline FLOPs
                        # inventory, telemetry/flops.py), averaged since
                        # the previous sync point: the metric fetch above
                        # just drained the device, so the window is an
                        # honest device-time average — a single step's
                        # host wall under async dispatch is not
                        now = time.perf_counter()
                        window = now - tele_sync_t0
                        if window > 0 and steps_since_sync:
                            tok_s = (
                                steps_since_sync * self._tokens_per_step
                                / window
                            )
                            tele.gauge("train/tokens_per_s").set(tok_s)
                            tele.gauge("train/mfu").set(
                                tok_s * self._flops_per_token
                                / self._peak_flops
                            )
                        tele_sync_t0 = now
                        steps_since_sync = 0
                        self._note_flops_divergence()
                    clock.mark("metric_flush")
                    if guard_action == "ok":
                        # never persist state the guard flagged: under a
                        # spike streak the params keep updating (finite
                        # losses never trip the device freeze), so a
                        # cadence save during "warn" steps would hand a
                        # later rollback the exploded checkpoint it was
                        # meant to discard
                        self._save_checkpoint()
                    clock.mark("checkpoint")
                    clock.close()
                    tele.counter("train/tokens").add(self._tokens_per_step)
                    tele.counter("train/steps").add(1)
                    if step % flush_every == 0 or self.stepper.finished:
                        tele.flush(step)
                        last_tele_flush = step
                    if guard_action == "rollback":
                        # "consecutive" semantics: progressing PAST the
                        # previous rollback's trigger step means that
                        # fault was cleared — a later, independent fault
                        # starts a fresh streak instead of inheriting a
                        # month of unrelated history
                        if (
                            last_rollback_trigger is not None
                            and step > last_rollback_trigger
                        ):
                            rollbacks = 0
                        last_rollback_trigger = step
                        rollbacks += 1
                        tele.counter("resilience/rollbacks").add(1)
                        if rollbacks > self.config.anomaly_max_rollbacks:
                            raise RuntimeError(
                                "anomaly guard: rollback triggered "
                                f"{rollbacks} times (anomaly_max_rollbacks="
                                f"{self.config.anomaly_max_rollbacks}); the "
                                "fault survives restores — failing fast"
                            )
                        # the producer thread must not race the restore's
                        # loader-state mutation; rewinding makes its
                        # run-ahead batches moot anyway
                        if self._prefetcher is not None:
                            self._prefetcher.close()
                            self._prefetcher = None
                        # a large restore can take longer than the tight
                        # per-step watchdog window — recovery must not be
                        # hard-killed as a hang
                        self.timeout.disarm()
                        restored_step = self._restore_state()
                        self.timeout.set_periodic()
                        self._reset_guard_state()
                        if restored_step is None:
                            logger.error(
                                "anomaly rollback requested at step %d but "
                                "no restorable checkpoint exists; continuing "
                                "under skip/warn semantics (prefetched "
                                "batches in flight were dropped)", step,
                            )
                        else:
                            logger.warning(
                                "anomaly rollback: restored step %d state "
                                "(anomalies began before step %d)",
                                restored_step, step,
                            )
                            data_iter = iter(self.data_loader)
                        if use_prefetch:
                            spawn_prefetcher(data_iter)
                        continue
                    if self.preemption.triggered:
                        # step boundary reached with the flag set: write
                        # the emergency checkpoint (synchronous — durable
                        # before the raise) and exit with the documented
                        # code; resume picks this checkpoint up unchanged
                        logger.warning(
                            "preemption: emergency checkpoint at step %d, "
                            "exiting with code %d",
                            step, self.config.preemption_exit_code,
                        )
                        tele.counter("resilience/preemptions").add(1)
                        # the emergency save's durability barrier can
                        # outlast the per-step watchdog window; exiting
                        # with the watchdog code mid-save would waste the
                        # preemption grace period
                        self.timeout.disarm()
                        self._save_checkpoint(last=True)
                        raise TrainingPreempted(
                            self.config.preemption_exit_code, step=step
                        )
                self.timeout.disarm()  # final durable save, same reason
                self._save_checkpoint(last=True)
            self.events.emit(ev.EVENT_TRAIN_FINISHED, trainer=self)
        finally:
            # release the profiler trace and flush/close the tracker run even
            # when a step raises (a dangling trace breaks the next train())
            if self._prefetcher is not None:
                self._prefetcher.close()
                self._prefetcher = None
            if self.metrics_server is not None:
                # the endpoint serves THIS session; a crashed step must
                # not leave the port bound (the next train() rebinds it)
                self.metrics_server.close()
                self.metrics_server = None
            self.profiler.close()
            # final telemetry flush (short runs still get one flush event,
            # and early exits flush the tail steps) unless the loop already
            # flushed at this exact step; then detach this run's sinks —
            # the registry itself stays live
            try:
                if last_tele_flush != self.stepper.step:
                    tele.flush(self.stepper.step)
            finally:
                for sink in tele_sinks:
                    tele.remove_sink(sink)
                tele.set_step(None)
            if self.run is not None:
                self.run.close()
            if self.checkpointer is not None:
                # a step raising must not strand an in-flight async save as
                # an unfinalized tmp dir — the crashed job's restart resumes
                # from this checkpoint (the old sync default was durable at
                # every save; keep that property on the exception path)
                self.checkpointer.wait_until_finished()
        return history

    def close(self) -> None:
        """Release held resources (checkpoint manager IO threads)."""
        if self.checkpointer is not None:
            self.checkpointer.close()

    # -- sleep/wake (reference component/train_sleeper.py:22) ----------

    def sleep(self, tags: set[SleepTag] = frozenset(SleepTag)) -> None:
        """Offload model/optimizer state to host, freeing device HBM."""
        with self.events.bounded(ev.EVENT_SLEEP, trainer=self):
            if SleepTag.MODEL in tags and SleepTag.MODEL not in self._sleep_store:
                if self.pp_engine is not None:
                    store = {}
                    for s, rt in self.pp_engine.stages.items():
                        store[s] = offload_tree(rt.params)
                        rt.params = None
                    self._sleep_store[SleepTag.MODEL] = store
                else:
                    self._sleep_store[SleepTag.MODEL] = offload_tree(self.params)
                    self.params = None
            if (
                SleepTag.OPTIMIZER in tags
                and SleepTag.OPTIMIZER not in self._sleep_store
            ):
                if self.pp_engine is not None:
                    store = {
                        s: offload_tree(v)
                        for s, v in self.pp_engine.opt_states.items()
                    }
                    self.pp_engine.opt_states = None
                    self._sleep_store[SleepTag.OPTIMIZER] = store
                else:
                    self._sleep_store[SleepTag.OPTIMIZER] = offload_tree(
                        self.opt_state
                    )
                    self.opt_state = None

    def wake(self) -> None:
        """Restore everything offloaded by :meth:`sleep`."""
        with self.events.bounded(ev.EVENT_WAKE, trainer=self):
            if SleepTag.MODEL in self._sleep_store:
                stored = self._sleep_store.pop(SleepTag.MODEL)
                if self.pp_engine is not None:
                    for s, (host, sh) in stored.items():
                        self.pp_engine.stages[s].params = onload_tree(host, sh)
                else:
                    self.params = onload_tree(*stored)
            if SleepTag.OPTIMIZER in self._sleep_store:
                stored = self._sleep_store.pop(SleepTag.OPTIMIZER)
                if self.pp_engine is not None:
                    self.pp_engine.opt_states = {
                        s: onload_tree(host, sh)
                        for s, (host, sh) in stored.items()
                    }
                else:
                    self.opt_state = onload_tree(*stored)

    # -- export (reference component/model_stage_exporter.py:11) -------

    def export(self, out_dir, mapper=None, shard_size_gb: float = 4.0) -> None:
        """Write the (merged) model weights as sharded safetensors via the
        model_state mapper system."""
        from d9d_tpu.model_state.io.module import save_params

        save_params(
            out_dir, self.merged_params(), mapper=mapper,
            shard_size_gb=shard_size_gb,
        )

    def merged_params(self) -> PyTree:
        """Full parameter tree for export: identity without PEFT, adapters
        folded into the frozen base with it; stage trees merged under PP."""
        if self.pp_engine is not None:
            return self.pp_engine.merged_params()
        if self.peft_method is None:
            return self.params
        if self._merge_fn is None:
            # d9d-lint: disable=D9D001 — one-shot export-time PEFT merge
            self._merge_fn = jax.jit(self.peft_method.merge)
        return self._merge_fn(self.base_params, self.params)

    # convenience for tests / evaluation -------------------------------

    def loss_on_batch(self, raw_batch: PyTree) -> float:
        if self.pp_engine is not None:
            # forward-only pipeline program over the same stages
            return float(self.pp_engine.eval_loss(self._stage_batch(raw_batch)))
        if self._eval_fn is None:
            self._eval_fn = build_eval_step(
                module=self.module,
                task=self.task,
                num_microbatches=self.batch_maths.num_microbatches,
            )
        batch = self._stage_batch(raw_batch)
        rng = jax.random.fold_in(self.step_rng, 10**9)
        return float(self._eval_fn(self.params, batch, rng))
