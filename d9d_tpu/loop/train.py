"""Trainer: wires providers into the compiled step and runs the loop.

Reference: d9d/loop/run/train.py:71,251 (TrainingConfigurator/Trainer).
The configure step builds mesh→model→optimizer→step-fn; ``train()`` is a
thin host loop around the jitted step — data staging and metric readback
are the only per-step host work (hot path is one XLA program).
"""

import logging
import time

import jax
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from d9d_tpu.core.mesh import MeshContext
from d9d_tpu.core.types import PyTree
from d9d_tpu.loop.components.batch_maths import BatchMaths
from d9d_tpu.loop.components.stepper import Stepper
from d9d_tpu.loop.config import TrainerConfig
from d9d_tpu.loop.control.providers import (
    DatasetProvider,
    ModelProvider,
    OptimizerProvider,
)
from d9d_tpu.loop.control.task import TrainTask
from d9d_tpu.loop.model_factory import init_sharded_params
from d9d_tpu.loop.train_step import build_eval_step, build_train_step
from d9d_tpu.pipelining import PipelineStageInfo

logger = logging.getLogger("d9d_tpu.trainer")


class Trainer:
    def __init__(
        self,
        *,
        ctx: MeshContext,
        config: TrainerConfig,
        model_provider: ModelProvider,
        dataset_provider: DatasetProvider,
        task: TrainTask,
        optimizer_provider: OptimizerProvider,
        learning_rate: optax.ScalarOrSchedule | None = None,
        peft_method=None,
    ):
        self.ctx = ctx
        self.config = config
        self.task = task
        self.batch_maths = BatchMaths.from_context(
            ctx, config.global_batch_size, config.microbatch_size
        )
        self.stepper = Stepper(total_steps=config.total_steps)

        self.module = model_provider.build_module(PipelineStageInfo())
        plan = model_provider.build_plan(ctx)
        rng = jax.random.PRNGKey(config.seed)
        self.init_rng, self.step_rng = jax.random.split(rng)
        sample = model_provider.sample_inputs(
            self.batch_maths.microbatch_size, config.seq_len
        )
        self.params, self.param_shardings = init_sharded_params(
            self.module, sample, self.init_rng, ctx, plan
        )

        self.peft_method = peft_method
        self.base_params = None
        if peft_method is not None:
            # engine "params" become the adapter tree; base stays frozen
            from d9d_tpu.peft import PeftTask

            inject_rng = jax.random.fold_in(self.init_rng, 1)
            self.base_params, adapters = peft_method.inject(
                self.params, inject_rng
            )
            self.params = adapters
            self.task = task = PeftTask(task, peft_method, self.base_params)

        self.optimizer = optimizer_provider.build(
            learning_rate if learning_rate is not None else config.learning_rate
        )
        self.opt_state = jax.jit(self.optimizer.init)(self.params)

        self.step_fn = build_train_step(
            module=self.module,
            task=self.task,
            optimizer=self.optimizer,
            num_microbatches=self.batch_maths.num_microbatches,
            max_grad_norm=config.max_grad_norm,
        )
        self.dataset = dataset_provider
        self._batch_sharding = NamedSharding(ctx.mesh, P(None, ctx.batch_axes))
        self._eval_fn = None
        self._merge_fn = None

    # ------------------------------------------------------------------

    def _stage_batch(self, raw_batch: PyTree) -> PyTree:
        """prepare → microbatch-reshape → device_put with dp sharding."""
        batch = self.task.prepare_batch(raw_batch)
        n_mb = self.batch_maths.num_microbatches
        mb = self.batch_maths.microbatch_size

        def reshape(x):
            x = np.asarray(x)
            if x.shape[0] != n_mb * mb:
                raise ValueError(
                    f"batch leading dim {x.shape[0]} != global batch {n_mb * mb}"
                )
            return x.reshape(n_mb, mb, *x.shape[1:])

        batch = jax.tree.map(reshape, batch)
        return jax.device_put(batch, self._batch_sharding)

    def train(self) -> list[dict]:
        """Run until total_steps or data exhaustion; returns metric history."""
        history: list[dict] = []
        t0 = time.perf_counter()
        data_iter = iter(self.dataset.build())
        while not self.stepper.finished:
            try:
                raw = next(data_iter)
            except StopIteration:
                break
            batch = self._stage_batch(raw)
            rng = jax.random.fold_in(self.step_rng, self.stepper.step)
            self.params, self.opt_state, metrics = self.step_fn(
                self.params, self.opt_state, batch, rng
            )
            step = self.stepper.advance()
            if step % self.config.log_every == 0 or self.stepper.finished:
                host_metrics = {
                    k: float(np.asarray(v)) for k, v in metrics.items()
                }
                host_metrics = self.task.metrics_postprocess(host_metrics)
                host_metrics["step"] = step
                host_metrics["wall_s"] = time.perf_counter() - t0
                history.append(host_metrics)
                logger.info("step %d: %s", step, host_metrics)
        return history

    def merged_params(self) -> PyTree:
        """Full parameter tree for export: identity without PEFT, adapters
        folded into the frozen base with it."""
        if self.peft_method is None:
            return self.params
        if self._merge_fn is None:
            self._merge_fn = jax.jit(self.peft_method.merge)
        return self._merge_fn(self.base_params, self.params)

    # convenience for tests / evaluation -------------------------------

    def loss_on_batch(self, raw_batch: PyTree) -> float:
        if self._eval_fn is None:
            self._eval_fn = build_eval_step(
                module=self.module,
                task=self.task,
                num_microbatches=self.batch_maths.num_microbatches,
            )
        batch = self._stage_batch(raw_batch)
        rng = jax.random.fold_in(self.step_rng, 10**9)
        return float(self._eval_fn(self.params, batch, rng))
