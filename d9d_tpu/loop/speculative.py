"""Speculative decoding: draft proposes, target verifies in one call.

Serving extension over the decode stack (docs/design/generation.md):
a small DRAFT model decodes ``k`` tokens autoregressively, then the
TARGET model scores all of them in ONE multi-token continuation call —
``1 + j`` committed tokens per target call instead of 1, where ``j`` is
the accepted prefix length. The whole round — index rewind, the ``k``
draft steps (a ``lax.scan``), the extra key write, and the verify call
— is ONE jitted program, so the host pays a single dispatch and a
single readback per round rather than re-entering Python per draft
token (the same chunked host-interaction contract as the fused
``ContinuousBatcher`` decode loop). Greedy acceptance (argmax-match) makes the
output BIT-IDENTICAL to target-only greedy decoding — speculation is a
latency optimization, never an approximation; the tests pin
``speculative_generate == generate`` exactly.

Cache mechanics (why this needs no new module support):

- The verify call is an ordinary continuation chunk
  (``d9d_tpu.nn.decode_flags.continuation_chunk``): ``t = 1 + k``
  tokens against the warm slot cache, per-row ``start`` — machinery
  chunked prefill and continuous batching already built.
- REJECTION IS AN INDEX REWIND. Attention decode caches are slot-causal
  (``_decode_slot_mask`` / the flash-decode kernel mask by the write
  index), so keys written for rejected proposals become invisible the
  moment ``cache_index`` rewinds — no buffer surgery. Rows rewind
  independently (per-row ``[B]`` indices).
- GatedDeltaNet layers are REJECTED by contract
  (``NotImplementedError``): their recurrent state advances
  irreversibly through every token, so rejected proposals would need
  per-position state checkpoints the layer does not keep. Speculate
  with attention-family models (dense GQA, Llama, MLA); hybrids decode
  through ``generate``/``ContinuousBatcher``.
"""

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from d9d_tpu.core.types import Array
from d9d_tpu.nn.decode_flags import continuation_chunk
from d9d_tpu.telemetry import tracked_jit


def _assert_rewindable(cache) -> None:
    from flax.traverse_util import flatten_dict

    for path in flatten_dict(cache):
        if path[-1] in ("delta_state", "conv_tail"):
            raise NotImplementedError(
                "speculative decoding requires rewindable decode state; "
                "GatedDeltaNet layers advance a recurrent state that "
                "cannot roll back past rejected proposals "
                f"(cache leaf {'/'.join(path)}). Use generate() or "
                "ContinuousBatcher for hybrid models."
            )


def _set_indices(cache, new_index: Array):
    """Rewind every cache_index leaf to per-row ``new_index [B]``."""
    from d9d_tpu.nn.decode_flags import map_cache_index

    return map_cache_index(cache, lambda _idx: new_index)


def speculative_generate(
    model,
    params: Any,
    draft_model,
    draft_params: Any,
    prompt_ids: Array,
    *,
    max_new_tokens: int,
    speculate_k: int = 4,
    eos_id: Optional[int] = None,
) -> Array:
    """``prompt_ids [B, P]`` → ``[B, max_new_tokens]``, bit-identical to
    ``generate(model, params, prompt_ids, max_new_tokens=...)`` (greedy).

    Both models need ``decode_max_length >= P + max_new_tokens - 1``
    (the draft additionally writes up to ``speculate_k`` speculative
    slots, which rewind — capacity must cover
    ``P + max_new_tokens - 1 + speculate_k`` on both). Each round runs
    as ONE jitted dispatch (rewind + ``speculate_k`` draft steps as a
    ``lax.scan`` + the single verify call) and one host readback; the
    host only runs the accept/commit bookkeeping between rounds —
    Python is re-entered once per round, not once per draft token.
    """
    b, p = prompt_ids.shape
    k = int(speculate_k)
    if k < 1:
        raise ValueError(f"speculate_k must be >= 1, got {k}")
    if max_new_tokens < 1:
        raise ValueError(
            f"max_new_tokens must be >= 1, got {max_new_tokens}"
        )
    for name, m in (("model", model), ("draft_model", draft_model)):
        dml = int(getattr(m, "decode_max_length", 0))
        need = p + max_new_tokens - 1 + k
        if dml < need:
            raise ValueError(
                f"{name}.decode_max_length={dml} < prompt {p} + "
                f"max_new_tokens {max_new_tokens} - 1 + speculate_k {k} "
                f"= {need} (speculative slots rewind but must fit)"
            )

    def prefill(m, prm):
        z_pos = jnp.broadcast_to(jnp.arange(p, dtype=jnp.int32), (b, p))
        logits, state = m.apply(
            {"params": prm}, prompt_ids.astype(jnp.int32), z_pos,
            method=m.logits_last, mutable=["cache"],
        )
        return logits[:, -1], state["cache"]

    # contract check BEFORE any forward pass: eval_shape exposes the
    # cache tree (leaf names included) without compiling or running
    z1 = jnp.zeros((b, 1), jnp.int32)
    for m, prm in ((model, params), (draft_model, draft_params)):
        _assert_rewindable(
            jax.eval_shape(m.init, jax.random.PRNGKey(0), z1, z1, z1)[
                "cache"
            ]
        )

    t_logits, t_cache = prefill(model, params)
    d_logits, d_cache = prefill(draft_model, draft_params)
    # per-row committed length (rows accept different prefix lengths);
    # the caches' write indices are NOT touched here — every round's
    # spec_round opens by rewinding both to the committed length, which
    # covers the first round too (nothing reads them in between)
    n = np.full((b,), p, np.int32)

    def draft_step(prm, cache, tok, pos):
        logits, state = draft_model.apply(
            {"params": prm, "cache": cache},
            tok[:, None], pos[:, None],
            method=draft_model.logits_last, mutable=["cache"],
        )
        return (
            state["cache"],
            jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32),
        )

    def round_fn(t_cache, d_cache, pending, n_eff, t_params, d_params):
        """One full speculation round as a single XLA program: rewind both
        caches to the committed length, draft ``k`` greedy tokens with a
        ``lax.scan`` (plus the extra key-write for the fully-accepted
        case), then verify ``pending + proposals`` in one target call —
        the host dispatches ONCE and reads back once per round instead of
        re-entering Python for every draft token.

        Both param trees are TRACED ARGUMENTS, never closure captures: a
        captured tree is baked into the executable as a constant (the
        install_weights publish-recompile class — D9D002)."""
        t_cache = _set_indices(t_cache, n_eff)
        d_cache = _set_indices(d_cache, n_eff)

        def body(carry, i):
            cache, tok = carry
            cache, nxt = draft_step(d_params, cache, tok, n_eff + i)
            return (cache, nxt), nxt

        (d_cache, last), props = jax.lax.scan(
            body, (d_cache, pending), jnp.arange(k, dtype=jnp.int32)
        )
        proposals = jnp.moveaxis(props, 0, 1)  # [B, k]
        # one extra draft step writes proposals[k-1]'s KEY (its output is
        # discarded): on a fully-accepted round the committed text
        # includes proposals[k-1], and without this write the draft
        # cache would carry a permanently visible unwritten slot —
        # silently degrading every later proposal's conditioning (and
        # with it the acceptance rate)
        d_cache, _ = draft_step(d_params, d_cache, last, n_eff + k)
        toks = jnp.concatenate([pending[:, None], proposals], axis=1)
        pos = n_eff[:, None] + jnp.arange(1 + k, dtype=jnp.int32)[None]
        # trace-time flag: the verify chunk attends the warm slot cache
        # (valid at any index), not the empty-cache prefill fast path
        with continuation_chunk():
            logits, state = model.apply(
                {"params": t_params, "cache": t_cache},
                toks, pos, method=model.logits, mutable=["cache"],
            )
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, 1+k]
        return state["cache"], d_cache, proposals, greedy

    spec_round = tracked_jit(
        round_fn, name="serve/spec_round", donate_argnums=(0, 1)
    )

    # first committed token: target's own greedy continuation of the
    # prompt (not yet fed to either cache)
    pending = np.asarray(jnp.argmax(t_logits, axis=-1), np.int32)
    out = np.zeros((b, max_new_tokens), np.int32)
    out[:, 0] = pending
    emitted = np.ones((b,), np.int32)
    done = (
        (pending == eos_id) if eos_id is not None
        else np.zeros((b,), bool)
    )

    while int((emitted < max_new_tokens).sum()) and not bool(done.all()):
        # done rows still flow through the static-shape step; park their
        # writes at slot 0 (their cache is dead) so a finished row near
        # capacity can never violate the overflow contract
        n_eff = np.where(done, 0, n).astype(np.int32)
        # ONE dispatch per round: rewind-to-committed + k draft steps +
        # the extra key write + the verify call, all inside spec_round;
        # ONE readback fetches proposals and the target's greedy tokens
        t_cache, d_cache, proposals_d, greedy_d = spec_round(
            t_cache, d_cache, jnp.asarray(pending), jnp.asarray(n_eff),
            params, draft_params,
        )
        # d9d-lint: disable=D9D003 — the one accounted readback per round
        proposals, greedy = jax.device_get((proposals_d, greedy_d))
        # greedy[:, i] = target tok after toks[:, :i+1]

        # --- accept the matching prefix, commit the bonus token -------
        new_tokens = np.zeros((b,), np.int32)
        for r in range(b):
            if done[r]:
                new_tokens[r] = 0
                continue
            j = 0
            while j < k and proposals[r, j] == greedy[r, j]:
                j += 1
            # committed this round: proposals[:j] plus target's token at
            # the first mismatch (or after all k accepted) — all of them
            # target-greedy by construction
            committed = list(proposals[r, :j]) + [greedy[r, j]]
            for c in committed:
                if emitted[r] >= max_new_tokens or done[r]:
                    break
                out[r, emitted[r]] = c
                emitted[r] += 1
                if eos_id is not None and c == eos_id:
                    done[r] = True
            # pending token fed next round = last committed token;
            # its KEY is not yet in either cache (position n + j + ...)
            n[r] += 1 + j  # pending + accepted proposals are now cached
            new_tokens[r] = committed[-1] if committed else 0
        pending = new_tokens
        # no explicit rewind dispatch here: the NEXT round's spec_round
        # opens by setting both caches' write indices to the committed
        # length (done rows parked at 0) — rejected proposals' keys
        # become invisible the moment the index rewinds (slot-causal
        # masks), so the correction rides the next dispatch for free
        if eos_id is not None:
            done |= emitted >= max_new_tokens
        else:
            done = emitted >= max_new_tokens

    if eos_id is not None:
        # frozen rows keep emitting eos (generate()'s static-shape rule)
        for r in range(b):
            if emitted[r] < max_new_tokens and done[r]:
                out[r, emitted[r]:] = eos_id
    return jnp.asarray(out)
