"""The jitted training step: microbatch scan, weighted-loss grad
accumulation, distributed-correct clipping, optimizer update.

This one function replaces several reference subsystems, because XLA SPMD
owns what the reference implements imperatively:

- grad bucketing/allreduce (d9d/internals/grad_sync) → reduce happens inside
  the jitted grad computation, overlapped by the XLA scheduler;
- weighted-loss accumulation + sum-then-scale-by-Σweight
  (loop/component/gradient_manager.py:16) → explicit lax.scan carry here;
- ND-correct grad-norm clipping (internals/grad_norm/norm.py:99) → a plain
  global norm: params are jax.Arrays with global semantics, so no
  placement bookkeeping is needed to avoid double counting;
- the grad-accumulation microbatch loop (loop/run/train.py:312) →
  ``lax.scan`` over a microbatch-leading batch.

Everything compiles to a single XLA program per (shapes, mesh) — no
per-step Python dispatch on the hot path.
"""

import dataclasses
import functools
from collections.abc import Callable
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax import lax

from d9d_tpu.core.protocol import OptimizerProtocol
from d9d_tpu.core.types import Array, PyTree
from d9d_tpu.loop.control.task import TrainTask
from d9d_tpu.parallel.zero import ZeroSharding, constrain_tree
from d9d_tpu.resilience.anomaly import ANOMALY_POLICIES
from d9d_tpu.telemetry import numerics as numerics_mod
from d9d_tpu.telemetry import tracked_jit


@dataclasses.dataclass
class TrainStepFn:
    """A compiled train step plus its metadata.

    With the anomaly guard compiled in (``guarded``), the jitted function
    additionally threads a tiny device-resident ``[streak, total]``
    anomaly carry through every call — held here so callers keep the
    4-argument step signature. The carry never visits the host: its
    values surface through the step's metric dict, which the trainer
    fetches on its ordinary log cadence.

    With the numerics plane compiled in (``numerics``), the step takes
    one more traced operand: a device-resident boolean cadence flag
    (two cached scalars, so toggling it never re-transfers or
    recompiles). The trainer sets ``numerics_next`` before each call;
    the spec naming the stats rows materializes at first trace
    (``numerics_spec``).
    """

    fn: Callable[..., tuple[PyTree, PyTree, dict[str, Any]]]
    guarded: bool = False
    guard_state: Any = None  # device int32[2]: [anomaly streak, total]
    numerics: bool = False
    numerics_next: bool = False  # trainer-set cadence flag for the NEXT call
    _numerics_holder: dict | None = None  # {"spec": NumericsSpec} at trace
    _flags: Any = None  # cached (off, on) device bool scalars

    @property
    def numerics_spec(self):
        """Row spec of ``numerics/stats`` (None until the first trace)."""
        if self._numerics_holder is None:
            return None
        return self._numerics_holder.get("spec")

    def _numerics_flag(self):
        if self._flags is None:
            self._flags = (jnp.asarray(False), jnp.asarray(True))
        return self._flags[1] if self.numerics_next else self._flags[0]

    def __call__(self, params, opt_state, batch, rng):
        args = [params, opt_state, batch, rng]
        if self.guarded:
            if self.guard_state is None:
                self.guard_state = jnp.zeros((2,), jnp.int32)
            args.append(self.guard_state)
        if self.numerics:
            args.append(self._numerics_flag())
        if not self.guarded:
            return self.fn(*args)
        params, opt_state, metrics, self.guard_state = self.fn(*args)
        return params, opt_state, metrics

    def reset_guard(self) -> None:
        """Zero the anomaly carry (after a rollback restored state the
        pre-rollback streak no longer describes)."""
        self.guard_state = None


def global_grad_norm(grads: PyTree) -> Array:
    return optax.global_norm(grads)


def build_train_step(
    *,
    module: nn.Module,
    task: TrainTask,
    optimizer: "optax.GradientTransformation | OptimizerProtocol",
    num_microbatches: int,
    max_grad_norm: float | None = 1.0,
    grad_dtype: jnp.dtype | None = jnp.float32,
    donate: bool = True,
    anomaly_policy: str | None = None,
    zero: ZeroSharding | None = None,
    split_update: bool = False,
    numerics: bool = False,
) -> TrainStepFn:
    """Build the jitted step.

    The incoming ``batch`` pytree must have leading dims
    ``[num_microbatches, microbatch_size, ...]`` (the trainer reshapes).
    ``grad_dtype`` overrides the accumulation dtype (reference
    GradientManager's grad-dtype override, gradient_manager.py:16).

    ``anomaly_policy`` compiles the step anomaly guard into the same XLA
    program (docs/design/resilience.md): non-finite loss/grad-norm is
    detected from the already-computed values — zero extra dispatches or
    readbacks — and under ``skip_step``/``rollback`` the parameter and
    optimizer-moment update is frozen for that step via an in-device
    select (``warn`` applies the update and only flags). The metric dict
    gains ``resilience/anomaly`` / ``anomaly_streak`` / ``anomaly_total``.

    ``zero`` (parallel/zero.py, docs/design/zero_sharding.md) annotates
    the grad-accumulation scan carry with the dp_replicate-sharded
    layout, so XLA reduce-scatters each microbatch's gradient into the
    local 1/N shard (the fp32 accumulator itself shrinks to 1/N per
    chip) and the optimizer — which the trainer wraps in
    ``ZeroShardedOptimizer`` — updates only the local shard before the
    all-gather back. The caller passes the *wrapped* optimizer here;
    ``zero`` only drives the accumulator annotation.

    ``split_update`` compiles the optimizer phase as its OWN
    ``tracked_jit`` executable (``train_opt_update``) instead of fusing
    it into the step program: two dispatches per step and the clipped
    grads materialize in HBM between them, but the introspection
    inventory then splits the update's FLOPs/HBM claim out of
    ``hbm/train_step`` — the observability mode for attributing the
    optimizer stream (and watching ZeRO's 1/N argument-bytes drop).

    ``numerics`` compiles the per-layer numerics plane
    (``telemetry/numerics.py``) into the SAME program: activation taps
    collect through the loss, per-leaf grad/param/update/moment stats
    assemble under a ``lax.cond`` on a traced cadence flag, and the
    flat f32 stats vector rides the metric dict as
    ``numerics/stats`` — zero extra dispatches, zero extra readbacks
    (off-cadence the cond skips the stats branch and the vector stays
    NaN). Not composable with ``split_update`` (the update:param ratio
    needs old and new params in one program).
    """
    if numerics and split_update:
        raise ValueError(
            "numerics is not supported with split_optimizer_update: the "
            "update:param ratio needs the pre- and post-update params "
            "inside one program"
        )
    if anomaly_policy is not None and anomaly_policy not in ANOMALY_POLICIES:
        raise ValueError(
            f"anomaly_policy must be one of {ANOMALY_POLICIES} or None, "
            f"got {anomaly_policy!r}"
        )
    freeze_on_anomaly = anomaly_policy in ("skip_step", "rollback")
    grad_shardings = (
        zero.grad_shardings if zero is not None and zero.active else None
    )

    numerics_holder: dict | None = {"spec": None} if numerics else None
    tap_order: dict[str, int] = {}  # tap name → forward rank (probe-time)

    def microbatch_grads(params, mb, rng):
        def scalar_loss(p):
            if numerics:
                # activation taps (telemetry/numerics.py): models tap
                # their residual stream; collection is active only here,
                # so every other trace of the same modules is unchanged
                with numerics_mod.collect_taps() as col:
                    loss_sum, weight, metrics = task.loss_fn(
                        module, p, mb, rng
                    )
                return loss_sum, (weight, metrics, dict(col.stats))
            loss_sum, weight, metrics = task.loss_fn(module, p, mb, rng)
            return loss_sum, (weight, metrics, {})

        with jax.named_scope("train/microbatch_grad"):
            (loss_sum, (weight, metrics, acts)), grads = jax.value_and_grad(
                scalar_loss, has_aux=True
            )(params)
        return loss_sum, weight, metrics, acts, grads

    def accumulate_grads(params, batch, rng):
        """Microbatch scan + sum-then-scale + clip → (grads, metrics)."""
        zero_grads = jax.tree.map(
            lambda p: jnp.zeros(p.shape, grad_dtype or p.dtype), params
        )
        if grad_shardings is not None:
            # ZeRO: the carry is pinned to the dp_r-sharded layout — the
            # fp32 accumulator holds 1/N per chip across the whole scan,
            # and XLA's reduce-scatter rewrite can fold the backward's
            # dp_r reduction straight into the shard
            zero_grads = constrain_tree(zero_grads, grad_shardings)

        def scan_body(carry, mb_and_idx):
            grads_acc, loss_acc, weight_acc, metrics_acc, acts_acc = carry
            mb, idx = mb_and_idx
            mb_rng = jax.random.fold_in(rng, idx)
            loss_sum, weight, metrics, acts, grads = microbatch_grads(
                params, mb, mb_rng
            )
            if grad_shardings is not None:
                # pin the per-microbatch grads to the baseline (replicated)
                # layout FIRST: the backward partitions exactly as the
                # unsharded path, and the accumulate below is then a
                # shard-local elementwise add — bitwise-identical values,
                # 1/N accumulator (see ZeroSharding.grad_pin_shardings)
                grads = constrain_tree(grads, zero.grad_pin_shardings)
            grads_acc = jax.tree.map(
                lambda a, g: a + g.astype(a.dtype), grads_acc, grads
            )
            if grad_shardings is not None:
                grads_acc = constrain_tree(grads_acc, grad_shardings)
            metrics_acc = jax.tree.map(lambda a, m: a + m, metrics_acc, metrics)
            if numerics:
                acts_acc = numerics_mod.merge_tap_stats(acts_acc, acts)
            return (
                grads_acc,
                loss_acc + loss_sum,
                weight_acc + weight,
                metrics_acc,
                acts_acc,
            ), None

        # probe metric (and tap) structure with zeros so the scan carry
        # is well-typed; the probe runs under a collector so the tap set
        # — and therefore the numerics row spec — is discovered here.
        # The collector's insertion order IS forward tap order; record
        # it before jax's dict canonicalization sorts the keys
        # ("layers_10" < "layers_2"), so NaN provenance can walk acts in
        # production order
        def _probe():
            mb0 = jax.tree.map(lambda x: x[0], batch)
            if numerics:
                with numerics_mod.collect_taps() as col:
                    m = task.loss_fn(module, params, mb0, rng)[2]
                tap_order.update(
                    (n, i) for i, n in enumerate(col.stats)
                )
                return m, dict(col.stats)
            return task.loss_fn(module, params, mb0, rng)[2], {}

        init_metrics, init_acts_shape = jax.eval_shape(_probe)
        init_metrics = jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype), init_metrics
        )
        init_acts = numerics_mod.init_tap_stats(init_acts_shape)

        idxs = jnp.arange(num_microbatches)
        (grads, loss_sum, weight_sum, metrics, act_stats), _ = lax.scan(
            scan_body,
            (zero_grads, jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32), init_metrics, init_acts),
            (batch, idxs),
        )

        # sum-then-scale: grads of Σ loss_sum scaled by 1 / Σ weight
        with jax.named_scope("train/grad_scale_clip"):
            inv_w = 1.0 / jnp.maximum(weight_sum, 1e-8)
            grads = jax.tree.map(lambda g: g * inv_w, grads)
            loss = loss_sum * inv_w

            grad_norm = global_grad_norm(grads)
            if max_grad_norm is not None:
                clip = jnp.minimum(
                    1.0, max_grad_norm / jnp.maximum(grad_norm, 1e-12)
                )
                grads = jax.tree.map(lambda g: g * clip, grads)

        out_metrics = {
            "loss": loss,
            "grad_norm": grad_norm,
            "loss_weight": weight_sum,
            **{f"task/{k}": v for k, v in metrics.items()},
        }
        if numerics:
            return grads, out_metrics, act_stats
        return grads, out_metrics

    def apply_update(params, opt_state, grads, out_metrics, guard_state):
        # OptimizerOwnsApply capabilities (core/protocol.py): fp32 grads
        # pass-through + optimizer-owned parameter write. Under ZeRO the
        # optimizer is the ZeroShardedOptimizer wrapper: update runs on
        # the 1/N shard, apply_updates all-gathers the new params.
        with jax.named_scope("train/optimizer"):
            if not getattr(optimizer, "accepts_fp32_grads", False):
                grads = jax.tree.map(
                    lambda g, p: g.astype(p.dtype), grads, params
                )
            updates, new_opt_state = optimizer.update(
                grads, opt_state, params
            )
            apply = getattr(optimizer, "apply_updates", optax.apply_updates)
            new_params = apply(params, updates)

        if anomaly_policy is None:
            return new_params, new_opt_state, out_metrics

        # step anomaly guard (device half): both operands were already
        # computed for the metric dict / clipping, so detection is free.
        # A NaN/inf anywhere in the grads reaches grad_norm by
        # construction (the global norm sums every leaf).
        with jax.named_scope("train/anomaly_guard"):
            ok = jnp.isfinite(out_metrics["loss"]) & jnp.isfinite(
                out_metrics["grad_norm"]
            )
            if freeze_on_anomaly:
                # freeze params AND optimizer moments for the step: a
                # NaN that reached Adam's second moment would poison
                # every later step despite finite grads. Elementwise
                # select — sharded ZeRO moments freeze shard-local.
                new_params = jax.tree.map(
                    lambda new, old: jnp.where(ok, new, old),
                    new_params, params,
                )
                new_opt_state = jax.tree.map(
                    lambda new, old: jnp.where(ok, new, old),
                    new_opt_state, opt_state,
                )
            anomaly = jnp.logical_not(ok).astype(jnp.int32)
            streak = jnp.where(ok, 0, guard_state[0] + 1)
            total = guard_state[1] + anomaly
            out_metrics = dict(out_metrics)
            out_metrics["resilience/anomaly"] = anomaly.astype(jnp.float32)
            out_metrics["resilience/anomaly_streak"] = streak.astype(
                jnp.float32
            )
            out_metrics["resilience/anomaly_total"] = total.astype(
                jnp.float32
            )
        return new_params, new_opt_state, out_metrics, jnp.stack(
            [streak, total]
        )

    def numerics_vector(
        act_stats, out_metrics, grads, params, new_params, new_opt_state,
        numerics_flag,
    ):
        """The flat stats array (telemetry/numerics.py): assembled under
        ``lax.cond`` on the traced cadence flag, so off-cadence steps run
        the identical single-dispatch program with the stats branch
        skipped and the vector left all-NaN. The spec naming the rows is
        captured at trace time — it can never drift from the layout."""
        with jax.named_scope("train/numerics"):
            nu = numerics_mod.find_second_moments(new_opt_state, params)
            spec = numerics_mod.build_spec(
                list(act_stats), numerics_mod.param_leaf_names(grads),
                act_rank=dict(tap_order),
            )
            numerics_holder["spec"] = spec

            def compute(ops):
                acts, loss, g, p_old, p_new, nu_t = ops
                parts = []
                if acts:
                    parts.append(
                        numerics_mod.act_rows(acts, num_microbatches)
                    )
                parts.append(numerics_mod.loss_row(loss))
                parts.append(
                    numerics_mod.stacked_param_rows(g, p_old, p_new, nu_t)
                )
                return jnp.concatenate(parts, axis=0).reshape(-1)

            return lax.cond(
                numerics_flag,
                compute,
                lambda ops: jnp.full(
                    (spec.flat_size,), jnp.nan, jnp.float32
                ),
                (act_stats, out_metrics["loss"], grads, params,
                 new_params, nu),
            )

    def step_impl(params, opt_state, batch, rng, guard_state, numerics_flag):
        if numerics:
            grads, out_metrics, act_stats = accumulate_grads(
                params, batch, rng
            )
        else:
            grads, out_metrics = accumulate_grads(params, batch, rng)
        result = apply_update(params, opt_state, grads, out_metrics, guard_state)
        if not numerics:
            return result
        new_params, new_opt_state, out_metrics = result[:3]
        out_metrics = dict(out_metrics)
        out_metrics["numerics/stats"] = numerics_vector(
            act_stats, out_metrics, grads, params, new_params,
            new_opt_state, numerics_flag,
        )
        if anomaly_policy is not None:
            return new_params, new_opt_state, out_metrics, result[3]
        return new_params, new_opt_state, out_metrics

    # fixed-arity adapters: tracked_jit sees exactly the operands this
    # build threads (guard carry at 4; the never-donated numerics flag
    # last), so signatures stay stable call to call
    if anomaly_policy is not None and numerics:
        def step(params, opt_state, batch, rng, guard_state, numerics_flag):
            return step_impl(
                params, opt_state, batch, rng, guard_state, numerics_flag
            )
    elif anomaly_policy is not None:
        def step(params, opt_state, batch, rng, guard_state):
            return step_impl(params, opt_state, batch, rng, guard_state, None)
    elif numerics:
        def step(params, opt_state, batch, rng, numerics_flag):
            return step_impl(params, opt_state, batch, rng, None, numerics_flag)
    else:
        def step(params, opt_state, batch, rng):
            return step_impl(params, opt_state, batch, rng, None, None)

    guard_ix = (4,) if anomaly_policy is not None else ()

    if split_update:
        # two tracked executables: grads (reuses the train_step name so
        # the MFU cross-check and dashboards keep working) + the
        # optimizer update under its own inventory row. grads/opt_state
        # (and the guard carry) are donated to the update program;
        # params are donated there too — the grads program has already
        # consumed them by the time the update dispatches.
        # d9d-lint: disable=D9D007 — split_update's grads program deliberately reuses the fused step's name so the MFU cross-check and dashboards keep working; build_train_step constructs exactly one of the two per call
        grads_jit = tracked_jit(accumulate_grads, name="train_step")
        update_jit = tracked_jit(
            apply_update, name="train_opt_update",
            donate_argnums=(0, 1, 2) + guard_ix if donate else (),
        )

        def split_fn(params, opt_state, batch, rng, guard_state=None):
            grads, out_metrics = grads_jit(params, batch, rng)
            return update_jit(params, opt_state, grads, out_metrics, guard_state)

        return TrainStepFn(
            fn=split_fn, guarded=anomaly_policy is not None
        )

    # tracked_jit (telemetry/introspect.py): same single dispatch per
    # call, plus compile/train_step spans, the steady-state recompile
    # guard, and the per-executable FLOPs/HBM inventory the MFU
    # cross-check reads
    jitted = tracked_jit(  # d9d-lint: disable=D9D007 — shares "train_step" with split_update's grads program by design; the two sites are mutually exclusive per TrainStepFn
        step, name="train_step",
        donate_argnums=(0, 1) + guard_ix if donate else (),
    )
    return TrainStepFn(
        fn=jitted, guarded=anomaly_policy is not None,
        numerics=numerics, _numerics_holder=numerics_holder,
    )


def build_eval_step(
    *,
    module: nn.Module,
    task: TrainTask,
    num_microbatches: int,
) -> Callable:
    """Forward-only step returning (loss, metrics) with the same weighting."""

    def step(params, batch, rng):
        def scan_body(carry, mb_and_idx):
            loss_acc, weight_acc = carry
            mb, idx = mb_and_idx
            loss_sum, weight, _ = task.loss_fn(
                module, params, mb, jax.random.fold_in(rng, idx)
            )
            return (loss_acc + loss_sum, weight_acc + weight), None

        idxs = jnp.arange(num_microbatches)
        (loss_sum, weight_sum), _ = lax.scan(
            scan_body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)), (batch, idxs)
        )
        return loss_sum / jnp.maximum(weight_sum, 1e-8)

    return tracked_jit(step, name="eval_step")
