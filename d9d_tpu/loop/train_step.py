"""The jitted training step: microbatch scan, weighted-loss grad
accumulation, distributed-correct clipping, optimizer update.

This one function replaces several reference subsystems, because XLA SPMD
owns what the reference implements imperatively:

- grad bucketing/allreduce (d9d/internals/grad_sync) → reduce happens inside
  the jitted grad computation, overlapped by the XLA scheduler;
- weighted-loss accumulation + sum-then-scale-by-Σweight
  (loop/component/gradient_manager.py:16) → explicit lax.scan carry here;
- ND-correct grad-norm clipping (internals/grad_norm/norm.py:99) → a plain
  global norm: params are jax.Arrays with global semantics, so no
  placement bookkeeping is needed to avoid double counting;
- the grad-accumulation microbatch loop (loop/run/train.py:312) →
  ``lax.scan`` over a microbatch-leading batch.

Everything compiles to a single XLA program per (shapes, mesh) — no
per-step Python dispatch on the hot path.
"""

import dataclasses
import functools
from collections.abc import Callable
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax import lax

from d9d_tpu.core.protocol import OptimizerProtocol
from d9d_tpu.core.types import Array, PyTree
from d9d_tpu.loop.control.task import TrainTask


@dataclasses.dataclass
class TrainStepFn:
    """A compiled train step plus its metadata."""

    fn: Callable[..., tuple[PyTree, PyTree, dict[str, Any]]]

    def __call__(self, params, opt_state, batch, rng):
        return self.fn(params, opt_state, batch, rng)


def global_grad_norm(grads: PyTree) -> Array:
    return optax.global_norm(grads)


def build_train_step(
    *,
    module: nn.Module,
    task: TrainTask,
    optimizer: "optax.GradientTransformation | OptimizerProtocol",
    num_microbatches: int,
    max_grad_norm: float | None = 1.0,
    grad_dtype: jnp.dtype | None = jnp.float32,
    donate: bool = True,
) -> TrainStepFn:
    """Build the jitted step.

    The incoming ``batch`` pytree must have leading dims
    ``[num_microbatches, microbatch_size, ...]`` (the trainer reshapes).
    ``grad_dtype`` overrides the accumulation dtype (reference
    GradientManager's grad-dtype override, gradient_manager.py:16).
    """

    def microbatch_grads(params, mb, rng):
        def scalar_loss(p):
            loss_sum, weight, metrics = task.loss_fn(module, p, mb, rng)
            return loss_sum, (weight, metrics)

        with jax.named_scope("train/microbatch_grad"):
            (loss_sum, (weight, metrics)), grads = jax.value_and_grad(
                scalar_loss, has_aux=True
            )(params)
        return loss_sum, weight, metrics, grads

    def step(params, opt_state, batch, rng):
        zero_grads = jax.tree.map(
            lambda p: jnp.zeros(p.shape, grad_dtype or p.dtype), params
        )

        def scan_body(carry, mb_and_idx):
            grads_acc, loss_acc, weight_acc, metrics_acc = carry
            mb, idx = mb_and_idx
            mb_rng = jax.random.fold_in(rng, idx)
            loss_sum, weight, metrics, grads = microbatch_grads(params, mb, mb_rng)
            grads_acc = jax.tree.map(
                lambda a, g: a + g.astype(a.dtype), grads_acc, grads
            )
            metrics_acc = jax.tree.map(lambda a, m: a + m, metrics_acc, metrics)
            return (
                grads_acc,
                loss_acc + loss_sum,
                weight_acc + weight,
                metrics_acc,
            ), None

        # probe metric structure with zeros so the scan carry is well-typed
        init_metrics = jax.eval_shape(
            lambda: task.loss_fn(
                module, params, jax.tree.map(lambda x: x[0], batch), rng
            )[2]
        )
        init_metrics = jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype), init_metrics
        )

        idxs = jnp.arange(num_microbatches)
        (grads, loss_sum, weight_sum, metrics), _ = lax.scan(
            scan_body,
            (zero_grads, jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32), init_metrics),
            (batch, idxs),
        )

        # sum-then-scale: grads of Σ loss_sum scaled by 1 / Σ weight
        with jax.named_scope("train/grad_scale_clip"):
            inv_w = 1.0 / jnp.maximum(weight_sum, 1e-8)
            grads = jax.tree.map(lambda g: g * inv_w, grads)
            loss = loss_sum * inv_w

            grad_norm = global_grad_norm(grads)
            if max_grad_norm is not None:
                clip = jnp.minimum(
                    1.0, max_grad_norm / jnp.maximum(grad_norm, 1e-12)
                )
                grads = jax.tree.map(lambda g: g * clip, grads)

        # OptimizerOwnsApply capabilities (core/protocol.py): fp32 grads
        # pass-through + optimizer-owned parameter write
        with jax.named_scope("train/optimizer"):
            if not getattr(optimizer, "accepts_fp32_grads", False):
                grads = jax.tree.map(
                    lambda g, p: g.astype(p.dtype), grads, params
                )
            updates, opt_state = optimizer.update(grads, opt_state, params)
            apply = getattr(optimizer, "apply_updates", optax.apply_updates)
            params = apply(params, updates)

        out_metrics = {
            "loss": loss,
            "grad_norm": grad_norm,
            "loss_weight": weight_sum,
            **{f"task/{k}": v for k, v in metrics.items()},
        }
        return params, opt_state, out_metrics

    jitted = jax.jit(step, donate_argnums=(0, 1) if donate else ())
    return TrainStepFn(fn=jitted)


def build_eval_step(
    *,
    module: nn.Module,
    task: TrainTask,
    num_microbatches: int,
) -> Callable:
    """Forward-only step returning (loss, metrics) with the same weighting."""

    def step(params, batch, rng):
        def scan_body(carry, mb_and_idx):
            loss_acc, weight_acc = carry
            mb, idx = mb_and_idx
            loss_sum, weight, _ = task.loss_fn(
                module, params, mb, jax.random.fold_in(rng, idx)
            )
            return (loss_acc + loss_sum, weight_acc + weight), None

        idxs = jnp.arange(num_microbatches)
        (loss_sum, weight_sum), _ = lax.scan(
            scan_body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)), (batch, idxs)
        )
        return loss_sum / jnp.maximum(weight_sum, 1e-8)

    return jax.jit(step)
