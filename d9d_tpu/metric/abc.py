"""Metric abstraction.

Parity: reference d9d/metric/abc.py:13 (Metric with
update/sync/compute/reset + Stateful persistence). Differences for TPU:
state lives in host numpy (metrics are host-side bookkeeping; the hot path
returns raw statistics from the jitted step), and ``sync`` reduces across
JAX *processes* — device-level reduction already happened inside jit.
"""

import abc
from typing import Any, Generic, TypeVar

TComputeResult = TypeVar("TComputeResult")


class Metric(abc.ABC, Generic[TComputeResult]):
    @abc.abstractmethod
    def update(self, *args: Any, **kwargs: Any) -> None:
        """Accumulate a new batch of statistics into local state."""

    @abc.abstractmethod
    def sync(self) -> None:
        """All-reduce local state across processes into synchronized state."""

    @abc.abstractmethod
    def compute(self) -> TComputeResult:
        """Compute the metric from (synchronized, else local) state."""

    @abc.abstractmethod
    def reset(self) -> None:
        """Reset local state to initial values."""

    @abc.abstractmethod
    def state_dict(self) -> dict[str, Any]:
        ...

    @abc.abstractmethod
    def load_state_dict(self, state_dict: dict[str, Any]) -> None:
        ...
