from d9d_tpu.metric.abc import Metric
from d9d_tpu.metric.accumulator import MetricAccumulator
from d9d_tpu.metric.aggregation import SumMetric, WeightedMeanMetric
from d9d_tpu.metric.auroc import BinaryAUROCMetric
from d9d_tpu.metric.classification import (
    AggregationMethod,
    ConfusionMatrix,
    ConfusionMatrixAccumulator,
    ConfusionMatrixMetric,
    ConfusionMatrixMetricBuilder,
)
from d9d_tpu.metric.container import ComposeMetric

__all__ = [
    "AggregationMethod",
    "BinaryAUROCMetric",
    "ComposeMetric",
    "ConfusionMatrix",
    "ConfusionMatrixAccumulator",
    "ConfusionMatrixMetric",
    "ConfusionMatrixMetricBuilder",
    "Metric",
    "MetricAccumulator",
    "SumMetric",
    "WeightedMeanMetric",
]
