"""Confusion-matrix classification metrics.

Parity: reference d9d/metric/impl/classification/confusion_matrix.py:23,105
plus its component stack (d9d/metric/component/classification/*): prediction
processors (threshold / one-hot argmax / top-k), per-class TP/FP/TN/FN
accumulation, statistics (accuracy/precision/recall/F-beta) and
micro/macro/weighted/none aggregation, composed by a fluent builder.
"""

import dataclasses
from enum import Enum
from typing import Any, Callable, Protocol

import numpy as np

from d9d_tpu.metric.abc import Metric
from d9d_tpu.metric.accumulator import MetricAccumulator


# --- processors -----------------------------------------------------------


class ClassificationPredictionsProcessor(Protocol):
    def __call__(
        self, preds: np.ndarray, targets: np.ndarray
    ) -> tuple[np.ndarray, np.ndarray]:
        ...


class ThresholdProcessor:
    """Binarize probabilistic predictions at a threshold (binary/multilabel)."""

    def __init__(self, threshold: float):
        self._threshold = threshold

    def __call__(self, preds, targets):
        preds = np.asarray(preds)
        targets = np.asarray(targets)
        if preds.ndim == 1:
            preds = preds[:, None]
        if targets.ndim == 1:
            targets = targets[:, None]
        return (preds > self._threshold).astype(np.float32), targets.astype(
            np.float32
        )


class OneHotProcessor:
    """Argmax predictions and one-hot both sides (multiclass)."""

    def __init__(self, num_classes: int):
        self._num_classes = num_classes

    def __call__(self, preds, targets):
        preds = np.asarray(preds)
        targets = np.asarray(targets)
        if preds.shape[-1] != self._num_classes:
            raise ValueError(
                f"Expected last dim of preds to equal num_classes="
                f"{self._num_classes}, got {preds.shape[-1]}"
            )
        classes = np.arange(self._num_classes)

        def one_hot(idx):
            if idx.size and (idx.min() < 0 or idx.max() >= self._num_classes):
                raise ValueError(
                    f"targets out of range [0, {self._num_classes}): "
                    f"min={idx.min()}, max={idx.max()}"
                )
            return (idx[..., None] == classes).astype(np.int64)

        preds_one_hot = one_hot(np.argmax(preds, axis=-1))
        if targets.shape == preds.shape:
            targets_one_hot = targets.astype(np.int64)
        elif targets.shape == preds.shape[:-1]:
            targets_one_hot = one_hot(targets.astype(np.int64))
        elif targets.shape == (*preds.shape[:-1], 1):
            targets_one_hot = one_hot(targets[..., 0].astype(np.int64))
        else:
            raise ValueError(
                f"Targets shape {targets.shape} is incompatible with "
                f"predictions shape {preds.shape}"
            )
        return preds_one_hot, targets_one_hot


class TopKProcessor:
    """Hit/miss of target within top-k predictions (multiclass top-k)."""

    def __init__(self, k: int):
        self._k = k

    def __call__(self, preds, targets):
        preds = np.asarray(preds)
        targets = np.asarray(targets)
        topk_idx = np.argpartition(-preds, self._k - 1, axis=-1)[
            ..., : self._k
        ]
        is_hit = (topk_idx == targets[..., None]).any(
            axis=-1, keepdims=True
        ).astype(np.int64)
        return is_hit, np.ones_like(is_hit)


# --- confusion matrix state ----------------------------------------------


@dataclasses.dataclass(frozen=True)
class ConfusionMatrix:
    """Per-class counts, each of shape [C]."""

    tp: np.ndarray
    fp: np.ndarray
    tn: np.ndarray
    fn: np.ndarray


class ConfusionMatrixAccumulator:
    def __init__(self, num_outputs: int):
        self._num_outputs = num_outputs
        zeros = np.zeros(num_outputs, dtype=np.int64)
        self._tp = MetricAccumulator(zeros)
        self._fp = MetricAccumulator(zeros)
        self._tn = MetricAccumulator(zeros)
        self._fn = MetricAccumulator(zeros)

    @property
    def state(self) -> ConfusionMatrix:
        return ConfusionMatrix(
            tp=self._tp.value,
            fp=self._fp.value,
            tn=self._tn.value,
            fn=self._fn.value,
        )

    def update(self, preds: np.ndarray, targets: np.ndarray) -> None:
        preds = np.asarray(preds).reshape(-1, self._num_outputs)
        targets = np.asarray(targets).reshape(-1, self._num_outputs)
        p = preds.astype(bool)
        t = targets.astype(bool)
        self.update_counts(
            tp=(p & t).sum(axis=0),
            fp=(p & ~t).sum(axis=0),
            tn=(~p & ~t).sum(axis=0),
            fn=(~p & t).sum(axis=0),
        )

    def update_counts(self, *, tp, fp, tn, fn) -> None:
        """Accumulate pre-reduced per-class counts — the entry point for
        statistics that were already summed on device inside the jitted
        step (the TPU-native replacement for row-level update)."""
        self._tp.update(tp)
        self._fp.update(fp)
        self._tn.update(tn)
        self._fn.update(fn)

    def sync(self) -> None:
        for acc in (self._tp, self._fp, self._tn, self._fn):
            acc.sync()

    def reset(self) -> None:
        for acc in (self._tp, self._fp, self._tn, self._fn):
            acc.reset()

    def state_dict(self) -> dict[str, Any]:
        return {
            "tp": self._tp.state_dict(),
            "fp": self._fp.state_dict(),
            "tn": self._tn.state_dict(),
            "fn": self._fn.state_dict(),
        }

    def load_state_dict(self, state_dict: dict[str, Any]) -> None:
        self._tp.load_state_dict(state_dict["tp"])
        self._fp.load_state_dict(state_dict["fp"])
        self._tn.load_state_dict(state_dict["tn"])
        self._fn.load_state_dict(state_dict["fn"])


# --- statistics + aggregation --------------------------------------------

ConfusionMatrixStatistic = Callable[[ConfusionMatrix], np.ndarray]


def accuracy_statistic(m: ConfusionMatrix) -> np.ndarray:
    return (m.tp + m.tn) / (m.tp + m.tn + m.fp + m.fn)


def precision_statistic(m: ConfusionMatrix) -> np.ndarray:
    return m.tp / (m.tp + m.fp)


def recall_statistic(m: ConfusionMatrix) -> np.ndarray:
    return m.tp / (m.tp + m.fn)


def fbeta_statistic(beta: float) -> ConfusionMatrixStatistic:
    beta_sq = beta**2

    def stat(m: ConfusionMatrix) -> np.ndarray:
        num = (1 + beta_sq) * m.tp
        den = (1 + beta_sq) * m.tp + beta_sq * m.fn + m.fp
        return num / den

    return stat


class AggregationMethod(str, Enum):
    MICRO = "micro"
    MACRO = "macro"
    WEIGHTED = "weighted"
    NONE = "none"


def aggregate(
    method: AggregationMethod,
    statistic: ConfusionMatrixStatistic,
    matrix: ConfusionMatrix,
) -> np.ndarray:
    # 0/0 per-class scores (zero support/predictions) count as 0.0, matching
    # sklearn's zero_division=0 default — otherwise one absent class would
    # NaN-poison every macro/weighted aggregate.
    with np.errstate(divide="ignore", invalid="ignore"):
        match method:
            case AggregationMethod.MICRO:
                return np.nan_to_num(
                    statistic(
                        ConfusionMatrix(
                            tp=matrix.tp.sum(),
                            fp=matrix.fp.sum(),
                            tn=matrix.tn.sum(),
                            fn=matrix.fn.sum(),
                        )
                    )
                )
            case AggregationMethod.MACRO:
                return np.nan_to_num(statistic(matrix)).mean()
            case AggregationMethod.WEIGHTED:
                scores = np.nan_to_num(statistic(matrix))
                supports = matrix.tp + matrix.fn
                return np.nan_to_num(
                    (scores * supports).sum() / supports.sum()
                )
            case AggregationMethod.NONE:
                return np.nan_to_num(statistic(matrix))
    raise ValueError(f"Unknown aggregation method: {method}")


# --- the metric + builder -------------------------------------------------


class ConfusionMatrixMetric(Metric[np.ndarray]):
    def __init__(
        self,
        processor: ClassificationPredictionsProcessor,
        accumulator: ConfusionMatrixAccumulator,
        method: AggregationMethod,
        statistic: ConfusionMatrixStatistic,
    ):
        self._processor = processor
        self._accumulator = accumulator
        self._method = method
        self._statistic = statistic

    def update(self, preds, targets) -> None:
        p, t = self._processor(preds, targets)
        self._accumulator.update(p, t)

    def update_counts(self, *, tp, fp, tn, fn) -> None:
        """Feed device-pre-reduced per-class counts straight to the
        accumulator (bypasses the prediction processor)."""
        self._accumulator.update_counts(tp=tp, fp=fp, tn=tn, fn=fn)

    def sync(self) -> None:
        self._accumulator.sync()

    def compute(self) -> np.ndarray:
        return aggregate(self._method, self._statistic, self._accumulator.state)

    def reset(self) -> None:
        self._accumulator.reset()

    def state_dict(self) -> dict[str, Any]:
        return self._accumulator.state_dict()

    def load_state_dict(self, state_dict: dict[str, Any]) -> None:
        self._accumulator.load_state_dict(state_dict)


class ConfusionMatrixMetricBuilder:
    """Fluent pipeline: problem type → statistic → aggregation → build().

    Parity: reference ConfusionMatrixMetricBuilder
    (impl/classification/confusion_matrix.py:105).
    """

    def __init__(self):
        self._num_outputs: int | None = None
        self._processor: ClassificationPredictionsProcessor | None = None
        self._statistic: ConfusionMatrixStatistic | None = None
        self._method: AggregationMethod | None = None

    def _ensure_no_problem(self):
        if self._processor is not None:
            raise ValueError("A problem type has already been configured.")

    def _ensure_no_statistic(self):
        if self._statistic is not None:
            raise ValueError("A target statistic has already been configured.")

    def _ensure_no_aggregation(self):
        if self._method is not None:
            raise ValueError("An aggregation methodology has already been selected.")

    def binary(self, threshold: float = 0.5) -> "ConfusionMatrixMetricBuilder":
        self._ensure_no_problem()
        self._processor = ThresholdProcessor(threshold)
        self._num_outputs = 1
        self._method = AggregationMethod.MICRO
        return self

    def multiclass(
        self, num_classes: int, top_k: int | None = None
    ) -> "ConfusionMatrixMetricBuilder":
        self._ensure_no_problem()
        if top_k is not None:
            self._processor = TopKProcessor(top_k)
            self._num_outputs = 1
            self._method = AggregationMethod.MICRO
        else:
            self._processor = OneHotProcessor(num_classes)
            self._num_outputs = num_classes
        return self

    def multilabel(
        self, num_classes: int, threshold: float = 0.5
    ) -> "ConfusionMatrixMetricBuilder":
        self._ensure_no_problem()
        self._processor = ThresholdProcessor(threshold)
        self._num_outputs = num_classes
        return self

    def with_accuracy(self) -> "ConfusionMatrixMetricBuilder":
        self._ensure_no_statistic()
        self._statistic = accuracy_statistic
        return self

    def with_precision(self) -> "ConfusionMatrixMetricBuilder":
        self._ensure_no_statistic()
        self._statistic = precision_statistic
        return self

    def with_recall(self) -> "ConfusionMatrixMetricBuilder":
        self._ensure_no_statistic()
        self._statistic = recall_statistic
        return self

    def with_f1(self) -> "ConfusionMatrixMetricBuilder":
        return self.with_fbeta(1.0)

    def with_fbeta(self, beta: float) -> "ConfusionMatrixMetricBuilder":
        self._ensure_no_statistic()
        self._statistic = fbeta_statistic(beta)
        return self

    def micro(self) -> "ConfusionMatrixMetricBuilder":
        self._ensure_no_aggregation()
        self._method = AggregationMethod.MICRO
        return self

    def macro(self) -> "ConfusionMatrixMetricBuilder":
        self._ensure_no_aggregation()
        self._method = AggregationMethod.MACRO
        return self

    def weighted(self) -> "ConfusionMatrixMetricBuilder":
        self._ensure_no_aggregation()
        self._method = AggregationMethod.WEIGHTED
        return self

    def per_class(self) -> "ConfusionMatrixMetricBuilder":
        self._ensure_no_aggregation()
        self._method = AggregationMethod.NONE
        return self

    def build(self) -> ConfusionMatrixMetric:
        if self._processor is None or self._num_outputs is None:
            raise ValueError(
                "Problem type not configured (binary/multiclass/multilabel)."
            )
        if self._statistic is None:
            raise ValueError("Statistic not configured.")
        method = self._method or AggregationMethod.MACRO
        return ConfusionMatrixMetric(
            processor=self._processor,
            accumulator=ConfusionMatrixAccumulator(self._num_outputs),
            method=method,
            statistic=self._statistic,
        )
