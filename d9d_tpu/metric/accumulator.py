"""Dual-state metric accumulator.

Parity: reference d9d/metric/component/accumulator.py:42 (MetricAccumulator
with a 'local' copy updated per step and a 'synchronized' copy populated by
all-reduce; 'avg' deliberately unsupported). State is host numpy; sync uses
the process-level collectives in d9d_tpu/core/collectives.py.
"""

from typing import Any

import numpy as np

from d9d_tpu.core.collectives import ReduceOp, host_allreduce


def _accumulate(op: ReduceOp, acc: np.ndarray, value) -> np.ndarray:
    value = np.asarray(value, dtype=acc.dtype)
    match op:
        case ReduceOp.sum:
            return acc + value
        case ReduceOp.max:
            return np.maximum(acc, value)
        case ReduceOp.min:
            return np.minimum(acc, value)
    raise ValueError(f"Unknown reduce op {op}")


class MetricAccumulator:
    def __init__(
        self,
        initial_value: np.ndarray | float,
        reduce_op: ReduceOp = ReduceOp.sum,
    ):
        self._initial = np.array(initial_value, copy=True)
        self._local = self._initial.copy()
        self._synchronized = self._initial.copy()
        self._reduce_op = reduce_op
        self._is_synchronized = False

    def update(self, value) -> None:
        self._local = _accumulate(self._reduce_op, self._local, value)
        self._is_synchronized = False

    def sync(self) -> None:
        self._synchronized = host_allreduce(self._local, self._reduce_op)
        self._is_synchronized = True

    @property
    def value(self) -> np.ndarray:
        """Synchronized value if sync() ran since the last update, else local."""
        return self._synchronized if self._is_synchronized else self._local

    def reset(self) -> None:
        self._local = self._initial.copy()
        self._is_synchronized = False

    def state_dict(self) -> dict[str, Any]:
        return {
            "local": self._local,
            "synchronized": self._synchronized,
            "is_synchronized": self._is_synchronized,
        }

    def load_state_dict(self, state_dict: dict[str, Any]) -> None:
        self._local = np.asarray(state_dict["local"])
        self._synchronized = np.asarray(state_dict["synchronized"])
        self._is_synchronized = bool(state_dict["is_synchronized"])
