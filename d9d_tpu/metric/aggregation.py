"""Aggregation metrics: weighted mean and sum.

Parity: reference d9d/metric/impl/aggregation/{mean,sum}.py:10.
"""

from typing import Any

import numpy as np

from d9d_tpu.metric.abc import Metric
from d9d_tpu.metric.accumulator import MetricAccumulator


class WeightedMeanMetric(Metric[np.ndarray]):
    """Tracks Σ(value·weight) and Σweight; computes their ratio."""

    def __init__(self):
        self._value = MetricAccumulator(np.float32(0))
        self._weight = MetricAccumulator(np.float32(0))

    def update(self, values, weights) -> None:
        values = np.asarray(values, np.float32)
        weights = np.asarray(weights, np.float32)
        self._value.update((values * weights).sum())
        self._weight.update(weights.sum())

    def sync(self) -> None:
        self._value.sync()
        self._weight.sync()

    def compute(self) -> np.ndarray:
        return self._value.value / self._weight.value

    def reset(self) -> None:
        self._value.reset()
        self._weight.reset()

    @property
    def accumulated_weight(self) -> np.ndarray:
        return self._weight.value

    def state_dict(self) -> dict[str, Any]:
        return {
            "value": self._value.state_dict(),
            "weight": self._weight.state_dict(),
        }

    def load_state_dict(self, state_dict: dict[str, Any]) -> None:
        self._value.load_state_dict(state_dict["value"])
        self._weight.load_state_dict(state_dict["weight"])


class SumMetric(Metric[np.ndarray]):
    def __init__(self):
        self._accumulator = MetricAccumulator(np.float32(0))

    def update(self, value) -> None:
        self._accumulator.update(np.asarray(value, np.float32).sum())

    def sync(self) -> None:
        self._accumulator.sync()

    def compute(self) -> np.ndarray:
        return self._accumulator.value

    def reset(self) -> None:
        self._accumulator.reset()

    def state_dict(self) -> dict[str, Any]:
        return {"accumulator": self._accumulator.state_dict()}

    def load_state_dict(self, state_dict: dict[str, Any]) -> None:
        self._accumulator.load_state_dict(state_dict["accumulator"])
