"""Metric container.

Parity: reference d9d/metric/impl/container/compose.py:10 (ComposeMetric —
updates go to named children; sync/compute/reset fan out).
"""

from collections.abc import Mapping
from typing import Any

from d9d_tpu.metric.abc import Metric


class ComposeMetric(Metric[dict[str, Any]]):
    def __init__(self, children: Mapping[str, Metric]):
        self._children = dict(children)

    def update(self, *args: Any, **kwargs: Any) -> None:
        raise ValueError(
            "Cannot update ComposeMetric directly - update its children"
        )

    def __getitem__(self, item: str) -> Metric:
        return self._children[item]

    @property
    def children(self) -> Mapping[str, Metric]:
        return self._children

    def sync(self) -> None:
        for metric in self._children.values():
            metric.sync()

    def compute(self) -> dict[str, Any]:
        return {name: m.compute() for name, m in self._children.items()}

    def reset(self) -> None:
        for metric in self._children.values():
            metric.reset()

    def state_dict(self) -> dict[str, Any]:
        return {name: m.state_dict() for name, m in self._children.items()}

    def load_state_dict(self, state_dict: dict[str, Any]) -> None:
        for name, metric in self._children.items():
            metric.load_state_dict(state_dict[name])
