"""Histogram-sketch binary AUROC.

Parity: reference d9d/metric/impl/classification/auroc.py:48
(BinaryAUROCMetric): probabilities are bucketed into fixed histograms for
positives and negatives; AUROC ≈ P(X>Y) + 0.5·P(X=Y) via the trapezoidal
rule over the histograms — O(bins) memory instead of storing predictions.
"""

from typing import Any

import numpy as np

from d9d_tpu.metric.abc import Metric
from d9d_tpu.metric.accumulator import MetricAccumulator


def _compute_histogram_auroc(
    pos_hist: np.ndarray, neg_hist: np.ndarray
) -> np.ndarray:
    total_pos = pos_hist.sum()
    total_neg = neg_hist.sum()
    if total_pos <= 0 or total_neg <= 0:
        return np.float32(0.5)
    cum_pos = np.cumsum(pos_hist)
    acc_pos = total_pos - cum_pos
    area = ((0.5 * neg_hist * pos_hist) + (neg_hist * acc_pos)).sum()
    return np.float32(area / (total_pos * total_neg))


class BinaryAUROCMetric(Metric[np.ndarray]):
    def __init__(self, num_bins: int = 10000):
        self._num_bins = num_bins
        zeros = np.zeros((num_bins,), np.float32)
        self._pos_hist = MetricAccumulator(zeros)
        self._neg_hist = MetricAccumulator(zeros)

    def update(self, probs, labels) -> None:
        probs = np.asarray(probs).reshape(-1)
        labels = np.asarray(labels).reshape(-1).astype(np.float32)
        if probs.size != labels.size:
            raise ValueError(
                "Predictions and labels should have the same number of elements"
            )
        bins = np.clip(
            (probs * self._num_bins).astype(np.int64), 0, self._num_bins - 1
        )
        pos_batch = np.bincount(
            bins, weights=labels, minlength=self._num_bins
        ).astype(np.float32)
        neg_batch = np.bincount(
            bins, weights=1.0 - labels, minlength=self._num_bins
        ).astype(np.float32)
        self._pos_hist.update(pos_batch)
        self._neg_hist.update(neg_batch)

    def sync(self) -> None:
        self._pos_hist.sync()
        self._neg_hist.sync()

    def compute(self) -> np.ndarray:
        return _compute_histogram_auroc(
            self._pos_hist.value, self._neg_hist.value
        )

    def reset(self) -> None:
        self._pos_hist.reset()
        self._neg_hist.reset()

    def state_dict(self) -> dict[str, Any]:
        return {
            "pos": self._pos_hist.state_dict(),
            "neg": self._neg_hist.state_dict(),
        }

    def load_state_dict(self, state_dict: dict[str, Any]) -> None:
        self._pos_hist.load_state_dict(state_dict["pos"])
        self._neg_hist.load_state_dict(state_dict["neg"])
